"""Metrics registry: counters/gauges/histograms + Prometheus exposition.

The runtime-observability counterpart of training/logging_writer.py (which
streams scalars to tensorboard/wandb for AFTER-the-run analysis): these
collectors are cheap enough to update on every engine tick / train step
and are scraped LIVE over HTTP (`/metrics` on the serving server,
`--metrics_port` sidecar on the train loop) in the Prometheus text format
(https://prometheus.io/docs/instrumenting/exposition_formats/ 0.0.4 —
no client_prometheus dependency, the format is 40 lines of code).

Design points:

  * get-or-create registration: two subsystems asking for the same metric
    name share the collector (the serving engine and the HTTP layer both
    run against the process-default registry; re-registering must not
    raise, but a name re-registered with a different type/label schema is
    a bug and does).
  * labels are per-call kwargs, not child objects: `c.inc(1, status="200")`
    — one collector owns all its label combinations, which keeps the
    exposition grouped under one # TYPE header as the format requires.
  * histograms are cumulative-bucket, like Prometheus': le-bucket counts,
    _sum and _count, so rate() / histogram_quantile() work server-side.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Iterable, Optional, Sequence, Tuple

# default latency-ish buckets (seconds): spans 1ms..60s, the range of a
# decode tick at one end and a checkpoint stall at the other
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


def _escape_label_value(v: str) -> str:
    return (str(v).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _escape_help(s: str) -> str:
    """HELP text escaping per the text format: backslash and newline
    only (quotes stay literal in HELP, unlike label values). Symmetric
    with fleet/scrape.py parse_prom_metadata."""
    return s.replace("\\", r"\\").replace("\n", r"\n")


def _format_labels(labels: Tuple[Tuple[str, str], ...],
                   extra: Tuple[Tuple[str, str], ...] = ()) -> str:
    items = labels + extra
    if not items:
        return ""
    body = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in items)
    return "{" + body + "}"


def _format_value(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class _Collector:
    kind = "untyped"

    def __init__(self, name: str, help: str, label_names: Sequence[str]):
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()

    def _key(self, labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"metric {self.name} expects labels {self.label_names}, "
                f"got {tuple(sorted(labels))}")
        return tuple((k, str(labels[k])) for k in self.label_names)

    def samples(self) -> Iterable[str]:  # pragma: no cover - abstract
        raise NotImplementedError

    def expose(self) -> str:
        # every family gets a HELP and a TYPE line (strict scrapers —
        # fleet/scrape.py parse_prom_text(strict=True) — reject samples
        # of undeclared families); empty help falls back to the name so
        # the HELP line is never blank, and the text is escaped so a
        # newline in a help string can't inject a bogus sample line
        lines = [f"# HELP {self.name} {_escape_help(self.help or self.name)}",
                 f"# TYPE {self.name} {self.kind}"]
        lines.extend(self.samples())
        return "\n".join(lines)


class Counter(_Collector):
    """Monotonically increasing count."""

    kind = "counter"

    def __init__(self, name, help, label_names=()):
        super().__init__(name, help, label_names)
        self._values: Dict[Tuple, float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def samples(self):
        with self._lock:
            items = sorted(self._values.items())
        if not items and not self.label_names:
            items = [((), 0.0)]
        for key, v in items:
            yield f"{self.name}{_format_labels(key)} {_format_value(v)}"


class Gauge(_Collector):
    """Set-to-current-value metric (slot occupancy, queue depth, ...)."""

    kind = "gauge"

    def __init__(self, name, help, label_names=()):
        super().__init__(name, help, label_names)
        self._values: Dict[Tuple, float] = {}

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[self._key(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def samples(self):
        with self._lock:
            items = sorted(self._values.items())
        if not items and not self.label_names:
            items = [((), 0.0)]
        for key, v in items:
            yield f"{self.name}{_format_labels(key)} {_format_value(v)}"


class Histogram(_Collector):
    """Cumulative-bucket histogram (le buckets + _sum + _count)."""

    kind = "histogram"

    def __init__(self, name, help, label_names=(), buckets=DEFAULT_BUCKETS):
        super().__init__(name, help, label_names)
        b = tuple(sorted(float(x) for x in buckets))
        if not b:
            raise ValueError("histogram needs at least one bucket bound")
        self.buckets = b
        self._counts: Dict[Tuple, list] = {}
        self._sum: Dict[Tuple, float] = {}
        self._total: Dict[Tuple, int] = {}

    def observe(self, value: float, **labels) -> None:
        key = self._key(labels)
        v = float(value)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            for i, bound in enumerate(self.buckets):
                if v <= bound:
                    counts[i] += 1
            self._sum[key] = self._sum.get(key, 0.0) + v
            self._total[key] = self._total.get(key, 0) + 1

    def count(self, **labels) -> int:
        with self._lock:
            return self._total.get(self._key(labels), 0)

    def percentile(self, q: float, **labels) -> float:
        """Approximate q-quantile from the bucket counts (upper bound of
        the bucket the quantile falls in; +Inf bucket reports the largest
        finite bound). For dashboards/tests, not precision statistics."""
        key = self._key(labels)
        with self._lock:
            counts = list(self._counts.get(key, ()))
            total = self._total.get(key, 0)
        if not total:
            return float("nan")
        rank = q * total
        # observe() increments every bucket whose bound >= v, so counts[i]
        # is already the cumulative count at bound i (Prometheus-style)
        for i, bound in enumerate(self.buckets):
            if counts[i] >= rank:
                return bound
        return self.buckets[-1]

    def samples(self):
        with self._lock:
            keys = set(self._counts)
            if not self.label_names:
                keys.add(())  # unlabeled histogram exposes an empty series
            keys = sorted(keys)
        for key in keys:
            with self._lock:
                counts = list(self._counts.get(key, [0] * len(self.buckets)))
                total = self._total.get(key, 0)
                s = self._sum.get(key, 0.0)
            for bound, c in zip(self.buckets, counts):
                yield (f"{self.name}_bucket"
                       f"{_format_labels(key, (('le', _format_value(bound)),))}"
                       f" {c}")
            yield (f"{self.name}_bucket{_format_labels(key, (('le', '+Inf'),))}"
                   f" {total}")
            yield f"{self.name}_sum{_format_labels(key)} {_format_value(s)}"
            yield f"{self.name}_count{_format_labels(key)} {total}"


class MetricsRegistry:
    """Named collectors + one-call Prometheus text exposition."""

    def __init__(self):
        self._lock = threading.Lock()
        self._collectors: Dict[str, _Collector] = {}

    def _get_or_create(self, cls, name, help, label_names, **kw):
        with self._lock:
            existing = self._collectors.get(name)
            if existing is not None:
                if (type(existing) is not cls
                        or existing.label_names != tuple(label_names)):
                    raise ValueError(
                        f"metric {name} already registered as "
                        f"{type(existing).__name__} with labels "
                        f"{existing.label_names}")
                return existing
            c = cls(name, help, label_names, **kw)
            self._collectors[name] = c
            return c

    def counter(self, name: str, help: str = "", label_names=()) -> Counter:
        return self._get_or_create(Counter, name, help, label_names)

    def gauge(self, name: str, help: str = "", label_names=()) -> Gauge:
        return self._get_or_create(Gauge, name, help, label_names)

    def histogram(self, name: str, help: str = "", label_names=(),
                  buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, label_names,
                                   buckets=buckets)

    def get(self, name: str) -> Optional[_Collector]:
        with self._lock:
            return self._collectors.get(name)

    def render(self) -> str:
        """Prometheus text exposition (format 0.0.4) of every collector."""
        with self._lock:
            collectors = [self._collectors[n]
                          for n in sorted(self._collectors)]
        out = [c.expose() for c in collectors]
        return "\n".join(out) + ("\n" if out else "")


class _BoundCollector:
    """A collector with a constant label set pre-applied (the lane tag
    of a CP x DP engine lane). Observation methods proxy through with
    the constant labels merged in; reads do the same."""

    def __init__(self, collector: _Collector,
                 constant: Dict[str, str]):
        self._c = collector
        self._constant = dict(constant)

    def _merge(self, labels: Dict) -> Dict:
        overlap = set(labels) & set(self._constant)
        if overlap:
            raise ValueError(
                f"metric {self._c.name}: label(s) {sorted(overlap)} are "
                "pinned by the registry view and cannot be passed "
                "per-call")
        out = dict(self._constant)
        out.update(labels)
        return out

    def inc(self, amount: float = 1.0, **labels) -> None:
        self._c.inc(amount, **self._merge(labels))

    def set(self, value: float, **labels) -> None:
        self._c.set(value, **self._merge(labels))

    def observe(self, value: float, **labels) -> None:
        self._c.observe(value, **self._merge(labels))

    def value(self, **labels) -> float:
        return self._c.value(**self._merge(labels))

    def count(self, **labels) -> int:
        return self._c.count(**self._merge(labels))


class LabeledRegistryView:
    """A registry facade that stamps constant labels onto every
    collector it hands out — how the CP x DP engine lanes share one
    host registry while keeping per-lane series: every lane asks for
    the same metric names, the real collectors carry an extra "lane"
    label dimension, and the exposition (and the fleet router's load
    scrape, which SUMS across label sets) sees each lane separately."""

    def __init__(self, registry: "MetricsRegistry", **constant_labels):
        if not constant_labels:
            raise ValueError("LabeledRegistryView needs at least one "
                             "constant label")
        self._reg = registry
        self._constant = {k: str(v) for k, v in constant_labels.items()}
        self._extra = tuple(sorted(self._constant))

    def _names(self, label_names) -> tuple:
        return tuple(label_names) + self._extra

    def counter(self, name: str, help: str = "",
                label_names=()) -> _BoundCollector:
        return _BoundCollector(
            self._reg.counter(name, help, self._names(label_names)),
            self._constant)

    def gauge(self, name: str, help: str = "",
              label_names=()) -> _BoundCollector:
        return _BoundCollector(
            self._reg.gauge(name, help, self._names(label_names)),
            self._constant)

    def histogram(self, name: str, help: str = "", label_names=(),
                  buckets=DEFAULT_BUCKETS) -> _BoundCollector:
        return _BoundCollector(
            self._reg.histogram(name, help, self._names(label_names),
                                buckets=buckets),
            self._constant)

    def get(self, name: str) -> Optional[_Collector]:
        return self._reg.get(name)

    def render(self) -> str:
        return self._reg.render()


_default: Optional[MetricsRegistry] = None
_default_lock = threading.Lock()


def default_registry() -> MetricsRegistry:
    """Process-wide registry: the serving engine, HTTP server, and train
    loop all publish here unless handed an explicit registry (tests)."""
    global _default
    with _default_lock:
        if _default is None:
            _default = MetricsRegistry()
        return _default

"""Journal -> Chrome trace-event JSON: the whole run as one timeline.

``tools/telemetry_report.py --perfetto out.json`` renders the JSONL
event journals (one per host of a multi-host run) as a trace-event file
loadable in Perfetto (https://ui.perfetto.dev) or chrome://tracing —
steps, data waits, checkpoint stage->commit, eval/rollback stalls,
per-request serve spans, profiler windows, and the incident instants
(preemption, hang, SDC, peer abort, divergence) across every host of
the cluster in one scrollable view.

Format: the "JSON Array Format" of the Trace Event spec — an object
with ``traceEvents`` (list of events with ``ph``/``ts``/``pid``/
``tid``; ``ts`` in MICROseconds), ``displayTimeUnit``, and free
``metadata``. Each journal becomes one process (pid = host id when the
journal records one, else its index); lanes within it are threads with
``thread_name`` metadata. Durations the journal only records at
completion (step_ms, wall_s, seconds) become complete ("X") events
drawn backwards from their end timestamp; point incidents become
instant ("i") events.

No jax import — like the rest of the report tooling this runs on
journals scp'd off a pod.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

#: lane (tid) layout per host process — stable ordering in the UI
LANES = (
    (1, "train steps"),
    (2, "data wait / prefetch"),
    (3, "checkpoint"),
    (4, "eval + rollback + stalls"),
    (5, "serve requests"),
    (6, "profiler"),
    (7, "events"),
)
_TID = {name: tid for tid, name in LANES}

#: point events rendered as instants on the "events" lane
INSTANT_KINDS = (
    "run_start", "run_end", "preemption", "preemption_timeout",
    "hang_detected", "sdc_detected", "peer_abort", "commit_abort",
    "divergence", "elastic_resume", "fault_injection", "cadence_retune",
    "step_skipped", "serve_route",
    "serve_drain_begin", "serve_drain_done", "serve_readmit",
    "serve_weight_reload", "weight_reload", "replica_breaker_open",
    "replica_readmitted",
)


def _x(name: str, pid: int, tid: int, start_s: float, dur_s: float,
       t0: float, args: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    ev = {"ph": "X", "name": name, "pid": pid, "tid": tid,
          "ts": round((start_s - t0) * 1e6, 3),
          "dur": round(max(dur_s, 0.0) * 1e6, 3), "cat": "journal"}
    if args:
        ev["args"] = args
    return ev


def _instant(name: str, pid: int, tid: int, ts_s: float, t0: float,
             args: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    ev = {"ph": "i", "name": name, "pid": pid, "tid": tid,
          "ts": round((ts_s - t0) * 1e6, 3), "s": "p", "cat": "journal"}
    if args:
        ev["args"] = args
    return ev


def _meta(name: str, pid: int, value: str,
          tid: Optional[int] = None) -> Dict[str, Any]:
    ev = {"ph": "M", "name": name, "pid": pid, "ts": 0,
          "args": {"name": value}}
    if tid is not None:
        ev["tid"] = tid
    return ev


def _clean_args(e: Dict[str, Any]) -> Dict[str, Any]:
    return {k: v for k, v in e.items() if k not in ("ts", "kind")}


def _host_pid(events: List[Dict[str, Any]], index: int) -> Tuple[int, str]:
    """(pid, label) for one journal: the coordination host id when the
    run_start recorded one (multi-host runs), else the journal's
    position on the command line."""
    for e in events:
        if e.get("kind") == "run_start" and e.get("host") is not None:
            try:
                return int(e["host"]), f"host {e['host']}"
            except (TypeError, ValueError):
                break
    return index, f"journal {index}"


def journals_to_trace_events(
        journals: Sequence[Tuple[str, List[Dict[str, Any]]]]
) -> Dict[str, Any]:
    """(label, events) per journal -> the trace-event JSON object."""
    all_ts = [e["ts"] for _, events in journals for e in events
              if isinstance(e.get("ts"), (int, float))]
    t0 = min(all_ts) if all_ts else 0.0
    out: List[Dict[str, Any]] = []
    used_pids: Dict[int, int] = {}
    for index, (label, events) in enumerate(journals):
        pid, host_label = _host_pid(events, index)
        if pid in used_pids:  # two journals claiming one host id
            pid = max(used_pids) + 1
        used_pids[pid] = 1
        out.append(_meta("process_name", pid, f"{host_label} ({label})"))
        for tid, name in LANES:
            out.append(_meta("thread_name", pid, name, tid=tid))
        out.extend(_journal_events(events, pid, t0))
    return {
        "traceEvents": out,
        "displayTimeUnit": "ms",
        "metadata": {"tool": "megatron_tpu tools/telemetry_report.py",
                     "journals": [label for label, _ in journals],
                     "t0_unix_s": t0},
    }


def _journal_events(events: List[Dict[str, Any]], pid: int, t0: float
                    ) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    profile_open: Optional[Dict[str, Any]] = None
    ckpt_begin: Dict[Any, float] = {}
    for e in events:
        kind = e.get("kind")
        ts = e.get("ts")
        if not isinstance(ts, (int, float)):
            continue
        if kind == "step":
            dur = float(e.get("step_ms", 0.0)) / 1e3
            start = ts - dur
            out.append(_x(f"step {e.get('iteration')}", pid,
                          _TID["train steps"], start, dur, t0,
                          _clean_args(e)))
            wait = float(e.get("data_wait_ms", 0.0)) / 1e3
            if wait > 0:
                # the queue-pop wait precedes the step's processing span
                out.append(_x("data_wait", pid,
                              _TID["data wait / prefetch"],
                              start - wait, wait, t0,
                              {"iteration": e.get("iteration")}))
        elif kind == "checkpoint_begin":
            ckpt_begin[e.get("iteration")] = ts
        elif kind == "checkpoint_commit":
            begin = ckpt_begin.pop(e.get("iteration"), None)
            dur = (ts - begin if begin is not None
                   else float(e.get("seconds", 0.0)))
            out.append(_x(f"checkpoint {e.get('iteration')}", pid,
                          _TID["checkpoint"], ts - dur, dur, t0,
                          _clean_args(e)))
        elif kind == "checkpoint_stall":
            dur = float(e.get("seconds", 0.0))
            out.append(_x("checkpoint_stall", pid, _TID["checkpoint"],
                          ts - dur, dur, t0, _clean_args(e)))
        elif kind in ("eval", "rollback_replay", "data_wait"):
            dur = float(e.get("seconds", 0.0))
            out.append(_x(kind, pid, _TID["eval + rollback + stalls"],
                          ts - dur, dur, t0, _clean_args(e)))
        elif kind in ("serve_request", "serve_warmup"):
            dur = float(e.get("wall_s", 0.0))
            name = (f"req {e.get('status')}" if kind == "serve_request"
                    else kind)
            out.append(_x(name, pid, _TID["serve requests"],
                          ts - dur, dur, t0, _clean_args(e)))
        elif kind == "profile_begin":
            profile_open = e
        elif kind == "profile_end" and profile_open is not None:
            start = profile_open["ts"]
            out.append(_x("profile window", pid, _TID["profiler"],
                          start, ts - start, t0,
                          _clean_args(profile_open)))
            profile_open = None
        elif kind == "profile_aborted":
            # an abort CLOSES any open window (preemption/hang flush, or
            # a busy-rejected /admin/profile) so the next begin/end pair
            # isn't mis-paired across it; the instant keeps the reason
            out.append(_instant(kind, pid, _TID["profiler"], ts, t0,
                                _clean_args(e)))
            if profile_open is not None:
                start = profile_open["ts"]
                out.append(_x("profile window (aborted)", pid,
                              _TID["profiler"], start, ts - start, t0,
                              _clean_args(profile_open)))
                profile_open = None
        elif kind in INSTANT_KINDS:
            out.append(_instant(kind, pid, _TID["events"], ts, t0,
                                _clean_args(e)))
    if profile_open is not None:
        # window never closed (abort path): render what we know
        out.append(_instant("profile window (unclosed)", pid,
                            _TID["profiler"], profile_open["ts"], t0,
                            _clean_args(profile_open)))
    return out

"""Goodput accounting: where did the wall-clock go, and was it training?

Goodput = productive step seconds / total wall seconds. Everything else
is attributed to a named stall category so regressions are diagnosable
("goodput dropped 4 points" is useless; "checkpoint_stall grew 4 points
after the save interval changed" is a fix):

  productive        forward+backward+optimizer device time actually
                    advancing the model (compile time subtracted)
  compile           jit tracing + XLA backend compiles (RecompileTracker)
  data_wait         blocked on the input pipeline
  checkpoint_stall  train-loop stall of a save (async: barrier + host copy)
  rollback_replay   divergence rollback + fast-forward through the poison
                    window (post-crash replay is the same bucket)
  eval              validation loops
  other             unattributed remainder (loop overhead, logging, ...)

The recompile side doubles as a runtime invariant: the serving engine's
"zero recompiles after warmup" (PR 1) stops being a bench footnote and
becomes an assertable counter (tests/test_telemetry.py).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

#: canonical category names (journal `goodput` events and the report tool
#: rely on these strings)
CATEGORIES = ("productive", "compile", "data_wait", "checkpoint_stall",
              "rollback_replay", "eval", "other")


class GoodputTracker:
    """Wall-clock ledger over the categories above."""

    def __init__(self, clock=time.perf_counter):
        self._clock = clock
        self._lock = threading.Lock()
        self._t0 = clock()
        self._seconds: Dict[str, float] = {c: 0.0 for c in CATEGORIES}

    def attribute(self, category: str, seconds: float) -> None:
        if category not in self._seconds:
            raise ValueError(
                f"unknown goodput category {category!r}; one of {CATEGORIES}")
        if seconds < 0:
            return
        with self._lock:
            self._seconds[category] += seconds

    class _Span:
        def __init__(self, tracker, category):
            self._tracker, self._category = tracker, category

        def __enter__(self):
            self._start = self._tracker._clock()
            return self

        def __exit__(self, *exc):
            self._tracker.attribute(
                self._category, self._tracker._clock() - self._start)
            return False

    def track(self, category: str) -> "GoodputTracker._Span":
        """with tracker.track("eval"): ..."""
        if category not in self._seconds:
            raise ValueError(
                f"unknown goodput category {category!r}; one of {CATEGORIES}")
        return self._Span(self, category)

    def report(self) -> Dict[str, float]:
        """{"wall_s", "goodput", per-category seconds, "other_s"} — other
        absorbs the unattributed remainder so the split always sums to
        wall (concurrent attributions, e.g. an async-checkpoint commit
        overlapping compute, can push the sum past wall; other floors at
        0 and goodput stays productive/wall either way)."""
        with self._lock:
            wall = max(self._clock() - self._t0, 1e-9)
            seconds = dict(self._seconds)
        attributed = sum(v for k, v in seconds.items() if k != "other")
        seconds["other"] += max(wall - attributed - seconds["other"], 0.0)
        out = {"wall_s": round(wall, 4),
               "goodput": round(seconds["productive"] / wall, 4)}
        for c in CATEGORIES:
            out[f"{c}_s"] = round(seconds[c], 4)
        return out


# -- recompile tracking -------------------------------------------------------
#
# jax.monitoring emits '/jax/core/compile/backend_compile_duration' once per
# XLA backend compile (and the jaxpr-trace / mlir-lowering phases under
# sibling names). Listeners cannot be unregistered individually on this jax
# (clear_event_listeners would nuke everyone's), so the tracker is a
# process-global install-once singleton and consumers diff snapshots.

_BACKEND_COMPILE = "/jax/core/compile/backend_compile_duration"
_TRACE = "/jax/core/compile/jaxpr_trace_duration"
_LOWER = "/jax/core/compile/jaxpr_to_mlir_module_duration"
# plain (duration-less) events fired by jax's persistent compilation cache
# on every backend-compile request when jax_compilation_cache_dir is set: a
# hit skips the XLA compile entirely (no _BACKEND_COMPILE duration fires),
# a miss compiles then writes the entry. Counting both makes the warm-start
# story assertable: a resumed process with a warm cache shows hits > 0 and
# a collapsed goodput `compile` bucket (tests/test_prefetch.py).
_CACHE_HIT = "/jax/compilation_cache/cache_hits"
_CACHE_MISS = "/jax/compilation_cache/cache_misses"


class RecompileTracker:
    """Counts XLA backend compiles (jit cache misses reaching the
    compiler) and their total seconds, plus persistent-compilation-cache
    hits/misses, via jax.monitoring.

    Caveat on this jax (0.4.37): the backend_compile duration event wraps
    compile_or_get_cached, so a persistent-cache HIT still increments
    `compiles` — with a near-zero duration. Warm-start assertions should
    therefore read cache_hits and compile_seconds, not the compile count
    (tests/test_prefetch.py)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.compiles = 0
        self.compile_seconds = 0.0
        self.trace_seconds = 0.0
        self.cache_hits = 0
        self.cache_misses = 0

    def _on_duration(self, name: str, secs: float, **kw) -> None:
        with self._lock:
            if name == _BACKEND_COMPILE:
                self.compiles += 1
                self.compile_seconds += secs
            elif name in (_TRACE, _LOWER):
                self.trace_seconds += secs

    def _on_event(self, name: str, **kw) -> None:
        with self._lock:
            if name == _CACHE_HIT:
                self.cache_hits += 1
            elif name == _CACHE_MISS:
                self.cache_misses += 1

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {"compiles": self.compiles,
                    "compile_seconds": self.compile_seconds,
                    "trace_seconds": self.trace_seconds,
                    "cache_hits": self.cache_hits,
                    "cache_misses": self.cache_misses}

    def delta(self, since: Dict[str, float]) -> Dict[str, float]:
        now = self.snapshot()
        return {k: now[k] - since[k] for k in now}


_tracker: Optional[RecompileTracker] = None
_tracker_lock = threading.Lock()


def recompile_tracker() -> RecompileTracker:
    """The process-wide tracker, installing the jax.monitoring listener on
    first use. Importing jax here (not module top) keeps the telemetry
    package importable for tools that only read journals."""
    global _tracker
    with _tracker_lock:
        if _tracker is None:
            t = RecompileTracker()
            try:
                from jax import monitoring

                monitoring.register_event_duration_secs_listener(
                    t._on_duration)
                monitoring.register_event_listener(t._on_event)
            except Exception as e:  # noqa: BLE001 - count stays 0; the
                # zero-recompile assertion degrades to vacuous rather than
                # taking serving down over a jax-internals change
                import sys

                print(f"telemetry: jax.monitoring unavailable ({e}); "
                      "recompile tracking disabled", file=sys.stderr)
            _tracker = t
        return _tracker

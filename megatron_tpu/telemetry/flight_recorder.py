"""Flight recorder: a heartbeat watchdog that turns a silent hang into a
diagnosable bundle.

PR 2 made crashes safe (atomic checkpoints, auto-fallback resume) but a
HANG — a wedged collective, a deadlocked host callback, an engine step
loop stuck on a device transfer — leaves nothing: the process sits there
until the scheduler kills it, and the kill destroys the evidence. The
flight recorder closes that gap:

  * the owning loop arms it and calls heartbeat() once per train step /
    engine tick;
  * a daemon watchdog thread checks the heartbeat age; past `deadline_s`
    it writes a bundle directory:
      - meta.json        last heartbeat (age, note, count), deadline, pid
      - stacks.txt       every thread's Python stack (sys._current_frames)
      - events.jsonl     the last N journal events (the steps leading in)
  * then either keeps watching (default) or SIGABRTs the process
    (`abort=True`) so a supervisor restarts it with the bundle on disk —
    the moral equivalent of a kernel crash dump.

The watchdog never fires while stopped/disarmed (checkpointed exits,
engine shutdown) and fires at most once per stall (re-arms only after a
fresh heartbeat), so a long diagnosed stall produces one bundle, not one
per poll interval.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
import traceback
from typing import Optional

from megatron_tpu.telemetry.journal import EventJournal


def dump_all_stacks() -> str:
    """Every live thread's Python stack, main thread first."""
    frames = sys._current_frames()
    by_ident = {t.ident: t for t in threading.enumerate()}
    chunks = []
    order = sorted(frames, key=lambda i: (by_ident.get(i) is None,
                                          by_ident.get(i) is not threading.main_thread()))
    for ident in order:
        t = by_ident.get(ident)
        name = t.name if t is not None else f"unknown-{ident}"
        daemon = " daemon" if (t is not None and t.daemon) else ""
        chunks.append(f"--- thread {name} (ident {ident}{daemon}) ---")
        chunks.append("".join(traceback.format_stack(frames[ident])).rstrip())
    return "\n".join(chunks) + "\n"


class FlightRecorder:
    """Stall watchdog with heartbeat + bundle dump."""

    def __init__(self, out_dir: str, deadline_s: float,
                 journal: Optional[EventJournal] = None,
                 tail_events: int = 200, abort: bool = False,
                 poll_s: Optional[float] = None, log=print):
        if deadline_s <= 0:
            raise ValueError("deadline_s must be > 0")
        self.out_dir = os.path.abspath(out_dir)
        self.deadline_s = float(deadline_s)
        self.journal = journal
        self.tail_events = int(tail_events)
        self.abort = bool(abort)
        # poll fast enough that a stall is detected within ~1.25x deadline
        self.poll_s = float(poll_s) if poll_s else max(deadline_s / 4, 0.05)
        self.log = log
        self._lock = threading.Lock()
        self._last_beat = time.monotonic()
        self._beat_count = 0
        self._note = "armed (watchdog live from the first heartbeat)"
        self._fired_for_beat = -1  # at most one bundle per stall
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.bundles = []  # paths of written bundles (tests, reporting)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "FlightRecorder":
        """Spawn the watchdog. The deadline clock only starts at the
        FIRST heartbeat: the window between arming and the first step —
        which contains the multi-minute initial XLA compile — must not
        be judged against a deadline sized for steady-state steps (a
        false fire there with abort=True would crash-loop a healthy
        process through recompile after recompile)."""
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._watch, daemon=True,
                                        name="flight-recorder")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=self.poll_s * 4 + 5)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- heartbeat ----------------------------------------------------------

    def heartbeat(self, note: str = "") -> None:
        """Record liveness; called once per step/tick by the owning loop."""
        with self._lock:
            self._last_beat = time.monotonic()
            self._beat_count += 1
            if note:
                self._note = note

    # -- watchdog -----------------------------------------------------------

    def _watch(self):
        while not self._stop.wait(self.poll_s):
            with self._lock:
                age = time.monotonic() - self._last_beat
                beat = self._beat_count
                fired = self._fired_for_beat
            if beat == 0:  # not live until the first heartbeat (start())
                continue
            if age < self.deadline_s or beat == fired:
                continue
            try:
                path = self.dump(reason=f"no heartbeat for {age:.1f}s "
                                        f"(deadline {self.deadline_s:.1f}s)")
                self.log(f"flight recorder: stall detected — bundle written "
                         f"to {path}")
            except Exception as e:  # noqa: BLE001 - the watchdog must
                # survive a full disk; a dead watchdog is a silent hang
                self.log(f"flight recorder: bundle dump FAILED: {e}")
            with self._lock:
                self._fired_for_beat = beat
            if self.abort:
                self.log("flight recorder: aborting (SIGABRT) so the "
                         "supervisor restarts this process with the bundle "
                         "on disk")
                # flush whatever the journal buffered before dying
                if self.journal is not None:
                    try:
                        self.journal.flush()
                    except OSError:
                        pass
                os.kill(os.getpid(), signal.SIGABRT)

    def dump(self, reason: str = "manual") -> str:
        """Write one bundle dir; returns its path. Public so crash paths
        (signal handlers, except blocks) can force a dump."""
        ts = time.strftime("%Y%m%d-%H%M%S")
        path = os.path.join(self.out_dir, f"flight-{ts}-pid{os.getpid()}")
        # a second stall in the same second must not clobber the first
        suffix = 0
        final = path
        while os.path.exists(final):
            suffix += 1
            final = f"{path}.{suffix}"
        os.makedirs(final, exist_ok=True)
        with self._lock:
            meta = {
                "reason": reason,
                "pid": os.getpid(),
                "deadline_s": self.deadline_s,
                "heartbeat_age_s": round(
                    time.monotonic() - self._last_beat, 3),
                "heartbeat_count": self._beat_count,
                "last_note": self._note,
                "abort": self.abort,
                "ts": time.time(),
            }
        with open(os.path.join(final, "meta.json"), "w") as f:
            json.dump(meta, f, indent=1)
        with open(os.path.join(final, "stacks.txt"), "w") as f:
            f.write(dump_all_stacks())
        if self.journal is not None:
            events = self.journal.tail(self.tail_events)
            with open(os.path.join(final, "events.jsonl"), "w") as f:
                for ev in events:
                    f.write(json.dumps(ev, separators=(",", ":")) + "\n")
        self.bundles.append(final)
        return final

"""Unified telemetry: event journal, metrics, goodput, flight recorder.

One subsystem shared by training and serving (ISSUE 4):

  * journal      crash-safe append-only JSONL of structured run events
                 (per-step records, checkpoint/rollback/fault events) —
                 the ground truth tools/telemetry_report.py summarizes
  * metrics      Prometheus-expositable counters/gauges/histograms,
                 scraped via /metrics on the serving server or the
                 --metrics_port sidecar on the train loop
  * goodput      wall-clock split into productive vs. stall categories +
                 the jit recompile tracker (zero-after-warmup invariant)
  * flight       heartbeat watchdog that dumps all-thread stacks + the
    recorder     journal tail to a bundle when a step/tick stalls

docs/observability.md documents the journal schema, metric names, and
goodput definitions.
"""

from megatron_tpu.telemetry.flight_recorder import (  # noqa: F401
    FlightRecorder, dump_all_stacks,
)
from megatron_tpu.telemetry.goodput import (  # noqa: F401
    CATEGORIES, GoodputTracker, RecompileTracker, recompile_tracker,
)
from megatron_tpu.telemetry.http import (  # noqa: F401
    MetricsServer, start_metrics_server,
)
from megatron_tpu.telemetry.journal import (  # noqa: F401
    EventJournal, get_global_journal, read_events, set_global_journal,
)
from megatron_tpu.telemetry.metrics import (  # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry, default_registry,
)
from megatron_tpu.telemetry.run import (  # noqa: F401
    RunTelemetry, for_training,
)

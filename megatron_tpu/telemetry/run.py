"""Per-run telemetry bundle for the train loop (and batch tools).

One object owning the enabled subset of {journal, goodput ledger,
recompile tracker, metrics collectors, sidecar /metrics server, flight
recorder}, so megatron_tpu/training/pretrain.py wires telemetry with a
handful of calls instead of six objects' lifecycles. Construction is
driven by TrainingConfig's telemetry fields; everything is optional and
for_training() returns None when nothing is enabled (zero overhead for
runs that don't ask).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

from megatron_tpu.telemetry.flight_recorder import FlightRecorder
from megatron_tpu.telemetry.goodput import GoodputTracker, recompile_tracker
from megatron_tpu.telemetry.http import MetricsServer, start_metrics_server
from megatron_tpu.telemetry.journal import (
    JOURNAL_NAME, EventJournal, set_global_journal,
)
from megatron_tpu.telemetry.metrics import MetricsRegistry, default_registry


class RunTelemetry:
    """The enabled telemetry components of one training run."""

    def __init__(self, journal: Optional[EventJournal],
                 goodput: GoodputTracker,
                 metrics: MetricsRegistry,
                 server: Optional[MetricsServer],
                 flight: Optional[FlightRecorder]):
        self.journal = journal
        self.goodput = goodput
        self.recompiles = recompile_tracker()
        self.metrics = metrics
        self.server = server
        self.flight = flight
        # train-side collectors (get-or-create: stable across restarts in
        # one process, shared with anything else publishing to `metrics`)
        self.steps_total = metrics.counter(
            "train_steps_total", "optimizer steps completed")
        self.tokens_total = metrics.counter(
            "train_tokens_total", "tokens consumed by completed steps")
        self.recompiles_total = metrics.gauge(
            "jit_backend_compiles_total",
            "XLA backend compiles in this process (jit cache misses)")
        self.loss_gauge = metrics.gauge(
            "train_loss", "last completed step's loss")
        self.goodput_gauge = metrics.gauge(
            "train_goodput", "productive fraction of wall-clock so far")
        self.step_seconds = metrics.histogram(
            "train_step_seconds", "per-step wall time")
        self.stall_seconds = metrics.counter(
            "train_stall_seconds_total",
            "non-productive wall seconds by category",
            label_names=("category",))
        self.compile_cache_hits = metrics.gauge(
            "jit_compile_cache_hits_total",
            "persistent compilation cache hits in this process")
        # the async loop's sync-freedom invariant as a live number: in
        # steady state this advances by exactly 1 per step (the lagged
        # metrics fetch); growth beyond that is a hidden host sync on the
        # hot path (tests/test_prefetch.py regression-gates it)
        self.host_syncs = metrics.counter(
            "train_host_syncs_total",
            "blocking device->host transfers issued by the train loop")
        # resilience event counters (ROADMAP item 5: today's journal-only
        # events surfaced on /metrics so alerting needs no log scraping):
        # keyed by the journal kind, incremented transparently by emit()
        self.event_counters = {
            kind: metrics.counter(name, help_)
            for kind, name, help_ in (
                ("preemption", "train_preemptions_total",
                 "SIGTERM preemption notices that completed the expedited "
                 "checkpoint-and-exit path"),
                ("preemption_timeout", "train_preemption_timeouts_total",
                 "preemption checkpoints that missed --preempt_save_timeout"
                 " (forced exit 75)"),
                ("hang_detected", "train_hangs_total",
                 "step-watchdog hang verdicts (--step_timeout_s, exit 70)"),
                ("sdc_detected", "train_sdc_total",
                 "silent-data-corruption verdicts from the "
                 "--replay_check_interval bitwise replay"),
                ("elastic_resume", "train_elastic_resumes_total",
                 "resumes that re-derived the topology (dp/micro-batch/"
                 "tp/pp/host-count change)"),
                ("peer_abort", "train_peer_aborts_total",
                 "exits taken because a PEER host died or published a "
                 "poison record (exit 76)"),
                ("commit_abort", "train_commit_aborts_total",
                 "two-phase checkpoint commits aborted because the "
                 "cluster could not agree"),
                ("cadence_retune", "train_cadence_retunes_total",
                 "--save_interval auto interval changes"),
            )
        }

    # -- event plumbing -----------------------------------------------------

    def emit(self, kind: str, **fields: Any) -> None:
        c = self.event_counters.get(kind)
        if c is not None:
            c.inc()
        if self.journal is not None:
            self.journal.emit(kind, **fields)

    def journal_sink(self) -> "_CountingJournal":
        """Journal-shaped object (emit/flush) that ALSO feeds the event
        counters — for components that hold a journal handle rather than
        the RunTelemetry (AsyncCheckpointSaver: its commit_abort events
        must reach train_commit_aborts_total on /metrics)."""
        return _CountingJournal(self)

    def heartbeat(self, note: str = "") -> None:
        if self.flight is not None:
            self.flight.heartbeat(note)

    def compile_snapshot(self) -> Dict[str, float]:
        return self.recompiles.snapshot()

    def step(self, iteration: int, step_s: float, ntokens: int,
             compile_delta: Dict[str, float], **fields: Any) -> None:
        """One completed optimizer step: journal record + metrics +
        goodput attribution (compile seconds carved out of the span)."""
        compile_s = (compile_delta.get("compile_seconds", 0.0)
                     + compile_delta.get("trace_seconds", 0.0))
        compile_s = min(max(compile_s, 0.0), step_s)
        self.goodput.attribute("compile", compile_s)
        self.goodput.attribute("productive", step_s - compile_s)
        self.steps_total.inc()
        self.tokens_total.inc(ntokens)
        self.step_seconds.observe(step_s)
        snap = self.recompiles.snapshot()
        self.recompiles_total.set(snap["compiles"])
        self.compile_cache_hits.set(snap.get("cache_hits", 0))
        if "loss" in fields and fields["loss"] is not None:
            self.loss_gauge.set(fields["loss"])
        rec = dict(fields)
        rec.update(iteration=iteration, step_ms=round(step_s * 1e3, 3),
                   ntokens=int(ntokens))
        if compile_s > 0:
            rec["compile_ms"] = round(compile_s * 1e3, 3)
            rec["compiles"] = int(compile_delta.get("compiles", 0))
        # persistent-cache traffic for this step (a warm resume shows
        # cache_hits with compiles == 0: the trace ran, XLA did not)
        hits = int(compile_delta.get("cache_hits", 0))
        if hits:
            rec["cache_hits"] = hits
        self.emit("step", **rec)

    def stall(self, category: str, seconds: float, **fields: Any) -> None:
        """Attribute a named non-productive span + journal it."""
        self.goodput.attribute(category, seconds)
        self.stall_seconds.inc(max(seconds, 0.0), category=category)
        self.emit(category, seconds=round(seconds, 4), **fields)

    def goodput_report(self) -> Dict[str, float]:
        rep = self.goodput.report()
        self.goodput_gauge.set(rep["goodput"])
        return rep

    def close(self, **fields: Any) -> None:
        """Final goodput event, then tear down server/recorder/journal.

        fields land on the run_end record — the train loop passes
        received_signal so a post-mortem can tell a cluster preemption
        (SIGTERM) from an operator interrupt (SIGINT) without scraping
        stderr."""
        try:
            self.emit("goodput", final=True, **self.goodput_report())
            self.emit("run_end", **fields)
        finally:
            if self.flight is not None:
                self.flight.stop()
            if self.server is not None:
                self.server.close()
            if self.journal is not None:
                set_global_journal(None)
                self.journal.flush()
                self.journal.close()


class _CountingJournal:
    """EventJournal facade over a RunTelemetry: emit() routes through
    RunTelemetry.emit (journal + event counters), flush() reaches the
    underlying journal when one exists. Safe when the run has metrics but
    no journal (the counters still move; nothing is written)."""

    def __init__(self, rt: RunTelemetry):
        self._rt = rt

    def emit(self, kind: str, **fields: Any) -> None:
        self._rt.emit(kind, **fields)

    def flush(self) -> None:
        if self._rt.journal is not None:
            self._rt.journal.flush()


def for_training(tcfg, log=print, registry: Optional[MetricsRegistry] = None
                 ) -> Optional[RunTelemetry]:
    """Build the RunTelemetry a TrainingConfig asks for, or None.

    telemetry_dir enables the journal (and gives the flight recorder its
    bundle dir); metrics_port enables the sidecar /metrics listener (None
    disables; 0 binds a free port — tests read it back off server.port);
    flight_recorder arms the watchdog.
    """
    journal_on = bool(tcfg.telemetry_dir)
    server_on = tcfg.metrics_port is not None
    flight_on = bool(tcfg.flight_recorder)
    if not (journal_on or server_on or flight_on):
        return None
    metrics = registry if registry is not None else default_registry()
    journal = None
    if journal_on:
        # join the canonical name explicitly: telemetry_dir may not exist
        # yet, which would defeat EventJournal's dir-vs-file sniffing
        journal = EventJournal(
            os.path.join(tcfg.telemetry_dir, JOURNAL_NAME),
            max_bytes=int(tcfg.journal_max_mb * (1 << 20)))
        set_global_journal(journal)
    server = None
    if server_on:
        server = start_metrics_server(metrics, int(tcfg.metrics_port))
        log(f"telemetry: /metrics listening on port {server.port}")
    flight = None
    if flight_on:
        base = tcfg.telemetry_dir or tcfg.save
        out = (os.path.join(base, "flight_bundles") if base
               else "flight_bundles")
        flight = FlightRecorder(
            out_dir=out,
            deadline_s=tcfg.flight_recorder_deadline_s,
            journal=journal,
            abort=tcfg.flight_recorder_abort,
            log=log).start()
        log(f"telemetry: flight recorder armed "
            f"(deadline {tcfg.flight_recorder_deadline_s:.0f}s, "
            f"abort={tcfg.flight_recorder_abort})")
    return RunTelemetry(journal, GoodputTracker(), metrics, server, flight)

"""Per-run telemetry bundle for the train loop (and batch tools).

One object owning the enabled subset of {journal, goodput ledger,
recompile tracker, metrics collectors, sidecar /metrics server, flight
recorder}, so megatron_tpu/training/pretrain.py wires telemetry with a
handful of calls instead of six objects' lifecycles. Construction is
driven by TrainingConfig's telemetry fields; everything is optional and
for_training() returns None when nothing is enabled (zero overhead for
runs that don't ask).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

from megatron_tpu.telemetry.flight_recorder import FlightRecorder
from megatron_tpu.telemetry.goodput import GoodputTracker, recompile_tracker
from megatron_tpu.telemetry.http import MetricsServer, start_metrics_server
from megatron_tpu.telemetry.journal import (
    JOURNAL_NAME, EventJournal, set_global_journal,
)
from megatron_tpu.telemetry.metrics import MetricsRegistry, default_registry


class RunTelemetry:
    """The enabled telemetry components of one training run."""

    def __init__(self, journal: Optional[EventJournal],
                 goodput: GoodputTracker,
                 metrics: MetricsRegistry,
                 server: Optional[MetricsServer],
                 flight: Optional[FlightRecorder]):
        self.journal = journal
        self.goodput = goodput
        self.recompiles = recompile_tracker()
        self.metrics = metrics
        self.server = server
        self.flight = flight
        # train-side collectors (get-or-create: stable across restarts in
        # one process, shared with anything else publishing to `metrics`)
        self.steps_total = metrics.counter(
            "train_steps_total", "optimizer steps completed")
        self.tokens_total = metrics.counter(
            "train_tokens_total", "tokens consumed by completed steps")
        self.recompiles_total = metrics.gauge(
            "jit_backend_compiles_total",
            "XLA backend compiles in this process (jit cache misses)")
        self.loss_gauge = metrics.gauge(
            "train_loss", "last completed step's loss")
        self.goodput_gauge = metrics.gauge(
            "train_goodput", "productive fraction of wall-clock so far")
        self.step_seconds = metrics.histogram(
            "train_step_seconds", "per-step wall time")
        self.stall_seconds = metrics.counter(
            "train_stall_seconds_total",
            "non-productive wall seconds by category",
            label_names=("category",))
        self.compile_cache_hits = metrics.gauge(
            "jit_compile_cache_hits_total",
            "persistent compilation cache hits in this process")
        # the async loop's sync-freedom invariant as a live number: in
        # steady state this advances by exactly 1 per step (the lagged
        # metrics fetch); growth beyond that is a hidden host sync on the
        # hot path (tests/test_prefetch.py regression-gates it)
        self.host_syncs = metrics.counter(
            "train_host_syncs_total",
            "blocking device->host transfers issued by the train loop")

    # -- event plumbing -----------------------------------------------------

    def emit(self, kind: str, **fields: Any) -> None:
        if self.journal is not None:
            self.journal.emit(kind, **fields)

    def heartbeat(self, note: str = "") -> None:
        if self.flight is not None:
            self.flight.heartbeat(note)

    def compile_snapshot(self) -> Dict[str, float]:
        return self.recompiles.snapshot()

    def step(self, iteration: int, step_s: float, ntokens: int,
             compile_delta: Dict[str, float], **fields: Any) -> None:
        """One completed optimizer step: journal record + metrics +
        goodput attribution (compile seconds carved out of the span)."""
        compile_s = (compile_delta.get("compile_seconds", 0.0)
                     + compile_delta.get("trace_seconds", 0.0))
        compile_s = min(max(compile_s, 0.0), step_s)
        self.goodput.attribute("compile", compile_s)
        self.goodput.attribute("productive", step_s - compile_s)
        self.steps_total.inc()
        self.tokens_total.inc(ntokens)
        self.step_seconds.observe(step_s)
        snap = self.recompiles.snapshot()
        self.recompiles_total.set(snap["compiles"])
        self.compile_cache_hits.set(snap.get("cache_hits", 0))
        if "loss" in fields and fields["loss"] is not None:
            self.loss_gauge.set(fields["loss"])
        rec = dict(fields)
        rec.update(iteration=iteration, step_ms=round(step_s * 1e3, 3),
                   ntokens=int(ntokens))
        if compile_s > 0:
            rec["compile_ms"] = round(compile_s * 1e3, 3)
            rec["compiles"] = int(compile_delta.get("compiles", 0))
        # persistent-cache traffic for this step (a warm resume shows
        # cache_hits with compiles == 0: the trace ran, XLA did not)
        hits = int(compile_delta.get("cache_hits", 0))
        if hits:
            rec["cache_hits"] = hits
        self.emit("step", **rec)

    def stall(self, category: str, seconds: float, **fields: Any) -> None:
        """Attribute a named non-productive span + journal it."""
        self.goodput.attribute(category, seconds)
        self.stall_seconds.inc(max(seconds, 0.0), category=category)
        self.emit(category, seconds=round(seconds, 4), **fields)

    def goodput_report(self) -> Dict[str, float]:
        rep = self.goodput.report()
        self.goodput_gauge.set(rep["goodput"])
        return rep

    def close(self, **fields: Any) -> None:
        """Final goodput event, then tear down server/recorder/journal.

        fields land on the run_end record — the train loop passes
        received_signal so a post-mortem can tell a cluster preemption
        (SIGTERM) from an operator interrupt (SIGINT) without scraping
        stderr."""
        try:
            self.emit("goodput", final=True, **self.goodput_report())
            self.emit("run_end", **fields)
        finally:
            if self.flight is not None:
                self.flight.stop()
            if self.server is not None:
                self.server.close()
            if self.journal is not None:
                set_global_journal(None)
                self.journal.flush()
                self.journal.close()


def for_training(tcfg, log=print, registry: Optional[MetricsRegistry] = None
                 ) -> Optional[RunTelemetry]:
    """Build the RunTelemetry a TrainingConfig asks for, or None.

    telemetry_dir enables the journal (and gives the flight recorder its
    bundle dir); metrics_port enables the sidecar /metrics listener (None
    disables; 0 binds a free port — tests read it back off server.port);
    flight_recorder arms the watchdog.
    """
    journal_on = bool(tcfg.telemetry_dir)
    server_on = tcfg.metrics_port is not None
    flight_on = bool(tcfg.flight_recorder)
    if not (journal_on or server_on or flight_on):
        return None
    metrics = registry if registry is not None else default_registry()
    journal = None
    if journal_on:
        # join the canonical name explicitly: telemetry_dir may not exist
        # yet, which would defeat EventJournal's dir-vs-file sniffing
        journal = EventJournal(
            os.path.join(tcfg.telemetry_dir, JOURNAL_NAME),
            max_bytes=int(tcfg.journal_max_mb * (1 << 20)))
        set_global_journal(journal)
    server = None
    if server_on:
        server = start_metrics_server(metrics, int(tcfg.metrics_port))
        log(f"telemetry: /metrics listening on port {server.port}")
    flight = None
    if flight_on:
        base = tcfg.telemetry_dir or tcfg.save
        out = (os.path.join(base, "flight_bundles") if base
               else "flight_bundles")
        flight = FlightRecorder(
            out_dir=out,
            deadline_s=tcfg.flight_recorder_deadline_s,
            journal=journal,
            abort=tcfg.flight_recorder_abort,
            log=log).start()
        log(f"telemetry: flight recorder armed "
            f"(deadline {tcfg.flight_recorder_deadline_s:.0f}s, "
            f"abort={tcfg.flight_recorder_abort})")
    return RunTelemetry(journal, GoodputTracker(), metrics, server, flight)

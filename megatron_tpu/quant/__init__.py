"""Quantized-communication subsystem (Flash Communication, 2412.04964).

Low-bit (int8 / fp8) tensor-parallel collectives for serving:
quantize/dequantize primitives with unit-tested worst-case error bounds
(primitives.py), compressed psum / all-gather wrappers + the model-facing
shard_map seams (collectives.py), and the exposure-driven per-site
enable policy (policy.py — stdlib-only, loadable without jax).

Wired into serving via ``--serve_compress_collectives {none,int8,fp8}``
and ``--serve_comm_policy`` (docs/serving.md); byte reduction is pinned
by the decode_tp2_* golden comm manifests (docs/performance.md
"Compressed collectives").
"""

from megatron_tpu.quant.collectives import (  # noqa: F401
    MODES, TpComm, compressed_all_gather, compressed_psum,
    forward_comm_bytes, make_tp_comm, row_parallel_matmul,
    vocab_parallel_logits,
)
from megatron_tpu.quant.policy import (  # noqa: F401
    CommPolicy, DEFAULT_SITES, SITE_COLLECTIVES, default_policy,
    load_policy, policy_from_exposure, resolve_policy,
)
from megatron_tpu.quant.primitives import (  # noqa: F401
    dequantize_chunked, effective_chunk, fp8_supported, quantize_chunked,
    quantization_error_bound,
)

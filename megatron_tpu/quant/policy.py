"""Per-collective compression policy (stdlib-only).

Which TP collective SITES run compressed is a measurement-driven choice:
compressing a collective whose time is hidden under compute buys nothing
and costs quantization error. The runtime trace pipeline
(tools/trace_report.py, PR 12) measures each collective kind's EXPOSED
fraction — the Flash Communication number — and
``policy_from_exposure`` turns those fractions into a site policy:

  * ``attn_out`` / ``mlp_out`` — the row-parallel output reductions
    (all-reduce at runtime): compressed when the measured all-reduce
    exposed fraction clears the threshold.
  * ``logits``  — the vocab-parallel logits gather (all-gather at
    runtime): compressed when the all-gather exposed fraction clears it.
  * ``cp_ring`` — the context-parallel ring-attention hop
    (collective-permute at runtime, inference/context_parallel/):
    compressed when the collective-permute exposed fraction clears it.
  * ``cp_a2a`` — the 2D CP geometry's intra-subgroup head
    scatter/gather legs (all-to-all at runtime, ring_kv._merge_2d):
    compressed when the all-to-all exposed fraction clears it —
    measured SEPARATELY from the ring's collective-permute, because the
    two run on different fabric tiers (node-local vs cross-node).

``tools/trace_report.py --emit-comm-policy OUT.json`` writes the derived
policy; serving loads it back with ``--serve_comm_policy OUT.json``.
With no policy file every site compresses (the static worst case — the
trace refines it per deployment).

NO jax import: trace_report loads this module by file path on machines
holding nothing but the trace (same contract as analysis/taxonomy.py).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Mapping, Optional

#: the compressible collective sites in the serving forward
#: (models/transformer.py attention_block + mlp_block, models/
#: language_model.py lm_logits, inference/context_parallel/ring_kv.py)
#: and the HLO collective kind each one runs as — the join key between
#: trace exposure and site policy.
SITE_COLLECTIVES: Dict[str, str] = {
    "attn_out": "all-reduce",
    "mlp_out": "all-reduce",
    "logits": "all-gather",
    "cp_ring": "collective-permute",
    "cp_a2a": "all-to-all",
}

#: the subset of sites living inside the TENSOR-parallel comm plan
#: (TpComm): "cp_ring" / "cp_a2a" belong to the context-parallel
#: transport (CpComm) and must never reach TpComm's width validation.
TP_SITES = ("attn_out", "mlp_out", "logits")

#: the context-parallel transport's sites (CpComm): the ring hop and,
#: under the 2d geometry, the intra-subgroup head all-to-all legs.
CP_SITES = ("cp_ring", "cp_a2a")

#: no-measurement default: compress everything (the static Flash-
#: Communication stance; a trace-derived policy prunes hidden ones)
DEFAULT_SITES: Dict[str, bool] = {s: True for s in SITE_COLLECTIVES}


@dataclasses.dataclass(frozen=True)
class CommPolicy:
    """site name -> compress? plus where the decision came from."""

    sites: Mapping[str, bool]
    source: str = "default"
    threshold: Optional[float] = None

    def enabled(self, site: str) -> bool:
        return bool(self.sites.get(site, False))

    def enabled_sites(self) -> tuple:
        return tuple(s for s in SITE_COLLECTIVES if self.enabled(s))

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"sites": dict(self.sites),
                               "source": self.source}
        if self.threshold is not None:
            out["threshold"] = self.threshold
        return out


def default_policy() -> CommPolicy:
    return CommPolicy(sites=dict(DEFAULT_SITES))


def _validate_sites(sites: Mapping[str, Any], where: str) -> Dict[str, bool]:
    unknown = sorted(set(sites) - set(SITE_COLLECTIVES))
    if unknown:
        raise ValueError(
            f"{where}: unknown collective site(s) {unknown} "
            f"(known: {sorted(SITE_COLLECTIVES)})")
    out = dict(DEFAULT_SITES)
    for k, v in sites.items():
        if not isinstance(v, bool):
            raise ValueError(f"{where}: site {k!r} must map to a JSON "
                             f"boolean, got {v!r}")
        out[k] = v
    return out


def policy_from_exposure(exposed_frac_by_op: Mapping[str, float],
                         threshold: float = 0.25,
                         source: str = "trace") -> CommPolicy:
    """Derive the site policy from measured per-collective exposed
    fractions (trace_report's per-op ``exposed_frac``): a site compresses
    when its collective kind's exposed fraction >= threshold — i.e. the
    collective actually costs wall time that compute does not hide. An
    op kind absent from the trace (it never ran, or was fully hidden at
    0 exposure) maps to not-compressed."""
    sites = {site: float(exposed_frac_by_op.get(op, 0.0)) >= threshold
             for site, op in SITE_COLLECTIVES.items()}
    return CommPolicy(sites=sites, source=source, threshold=threshold)


def load_policy(path: str) -> CommPolicy:
    """Read a policy JSON ({"sites": {...}}, as --emit-comm-policy
    writes). Unknown sites are a loud error — a typo'd site name must
    not silently leave the real one at its default."""
    with open(path, encoding="utf-8") as f:
        raw = json.load(f)
    if not isinstance(raw, dict) or "sites" not in raw:
        raise ValueError(f"{path}: expected a JSON object with a "
                         "'sites' mapping")
    return CommPolicy(
        sites=_validate_sites(raw["sites"], path),
        source=str(raw.get("source", f"file:{path}")),
        threshold=raw.get("threshold"))


def resolve_policy(policy) -> CommPolicy:
    """Normalize the engine-facing knob: None (defaults), a CommPolicy,
    a {site: bool} dict, or a path to a policy JSON."""
    if policy is None:
        return default_policy()
    if isinstance(policy, CommPolicy):
        return policy
    if isinstance(policy, dict):
        return CommPolicy(sites=_validate_sites(policy, "comm_policy"),
                          source="dict")
    if isinstance(policy, str):
        return load_policy(policy)
    raise TypeError(f"comm_policy: expected None, CommPolicy, dict, or "
                    f"path, got {type(policy).__name__}")

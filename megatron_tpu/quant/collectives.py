"""Compressed tensor-parallel collectives (Flash Communication, TPU form).

Serving latency at tensor parallelism is dominated by the per-layer
output reductions (the row-parallel all-reduce after attention-out and
mlp-out) and the vocab-parallel logits gather — collectives whose
EXPOSED time the trace pipeline measures (ROADMAP item 2). This module
replaces them, inside the existing jitted decode/prefill bodies, with
low-bit versions (arXiv 2412.04964):

  * ``compressed_psum`` — the Flash-AllReduce shape: per-chunk quantize
    the partial sums, all-to-all the low-bit payload (+ scales riding
    alongside), dequantize + reduce the local shard at full precision,
    re-quantize, all-gather, dequantize. BOTH wire phases move int8/fp8
    bytes; the reduction itself stays exact fp32.
  * ``compressed_all_gather`` — quantize locally, gather payload +
    scales, dequantize.

Each wrapper is usable inside any shard_map body and falls back to the
dense op when the mesh axis is trivial (tp == 1). ``row_parallel_matmul``
and ``vocab_parallel_logits`` are the model-facing seams
(models/transformer.py / language_model.py): GSPMD-compatible shard_map
islands over the "tensor" axis that pick dense psum / compressed
transport per site according to a :class:`TpComm` (mode + policy).

Numerics: two quantization stages per psum — each bounded by
quant/primitives.quantization_error_bound — so per-site output error is
<= sum of both stages' chunk bounds; the engine-level gates
(tests/test_quant_comm.py) hold the resulting greedy decode to >= 99%
token match and a bounded max logit error against the dense engine.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, FrozenSet, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from megatron_tpu.analysis.taxonomy import wire_bytes_per_call
from megatron_tpu.parallel.mesh import AXIS_CONTEXT, AXIS_TENSOR
from megatron_tpu.quant.policy import (
    CommPolicy, SITE_COLLECTIVES, TP_SITES, resolve_policy,
)
from megatron_tpu.quant.primitives import (
    dequantize_chunked, effective_chunk, fp8_supported, quantize_chunked,
)

#: the modes --serve_compress_collectives exposes plus the explicit
#: "dense" baseline (same shard_map decomposition, full-precision psum /
#: all_gather — the contract manifest the compressed ones diff against)
MODES = ("none", "dense", "int8", "fp8")


@dataclasses.dataclass(frozen=True)
class TpComm:
    """One engine's tensor-parallel communication plan: which mesh axis,
    what transport precision, which sites route through the explicit
    collectives. Static at engine build => compiled into the decode
    step, zero traced args, zero recompiles."""

    mesh: object                 # jax.sharding.Mesh
    tp: int
    mode: str                    # "dense" | "int8" | "fp8"
    chunk: int = 32
    axis: str = AXIS_TENSOR
    sites: FrozenSet[str] = frozenset(TP_SITES)

    def compresses(self) -> bool:
        return self.mode in ("int8", "fp8")


def make_tp_comm(mesh, mode: str, cfg=None, policy=None,
                 chunk: int = 32) -> Optional[TpComm]:
    """Build the engine's TpComm, or None when the configuration is a
    no-op (mode "none", no mesh, or a trivial tensor axis — the dense
    GSPMD path then serves unchanged).

    policy: None (compress every site), a CommPolicy / {site: bool}
    dict / policy-JSON path (quant/policy.py). Under mode "dense" the
    policy still selects which sites take the EXPLICIT path (the
    contract baseline routes all of them).
    """
    if mode not in MODES:
        raise ValueError(f"compress_collectives must be one of {MODES}, "
                         f"got {mode!r}")
    if mode == "none" or mesh is None:
        return None
    tp = dict(mesh.shape).get(AXIS_TENSOR, 1)
    if tp <= 1:
        import warnings

        warnings.warn(
            f"compress_collectives={mode!r} requested but the mesh has a "
            "trivial tensor axis — serving the dense path unchanged",
            stacklevel=2)
        return None
    if mode == "fp8" and not fp8_supported():
        raise ValueError(
            "compress_collectives='fp8': this toolchain has no fp8 "
            "dtype; use 'int8'")
    if chunk < 1:
        raise ValueError(f"comm chunk must be >= 1, got {chunk}")
    pol = resolve_policy(policy)
    # only the TENSOR-axis sites belong to this plan; "cp_ring" is the
    # context-parallel ring transport's decision (make_cp_comm)
    sites = frozenset(s for s in pol.enabled_sites() if s in TP_SITES)
    if cfg is not None:
        _validate_cfg(cfg, tp, sites)
    return TpComm(mesh=mesh, tp=tp, mode=mode, chunk=int(chunk),
                  sites=sites)


#: the CP attention geometries --serve_cp_geometry exposes: "ring" is
#: the flat 1D sequence ring (cp-1 hops); "2d" factors the context axis
#: into cp_seq x cp_head (ATTENTION2D): ulysses-style head all-to-all
#: inside a `subgroup`-sized group, ring hops only ACROSS subgroups —
#: TASP's topology-aware placement (the expensive ring traverses the
#: slow fabric tier once, at 1/subgroup the payload).
CP_GEOMETRIES = ("ring", "2d")


@dataclasses.dataclass(frozen=True)
class CpComm:
    """One engine's context-parallel communication plan: the mesh axis
    the KV pages are striped over, the transport precision of the
    ring-attention hop (site "cp_ring") and of the 2d geometry's head
    all-to-all legs (site "cp_a2a"), the attention geometry, and the
    ring schedule (overlapped vs serial). Static at engine build, like
    TpComm — compiled into the decode/chunk steps."""

    mesh: object                 # jax.sharding.Mesh
    cp: int
    mode: str                    # "dense" | "int8" | "fp8"
    chunk: int = 32
    axis: str = AXIS_CONTEXT
    compress_ring: bool = True   # the policy's "cp_ring" decision
    geometry: str = "ring"       # "ring" | "2d"
    subgroup: int = 1            # cp_head under "2d" (1 under "ring")
    overlap: bool = True         # hop l+1 issued before hop l's merge
    compress_a2a: bool = True    # the policy's "cp_a2a" decision

    def compresses(self) -> bool:
        return self.compress_ring and self.mode in ("int8", "fp8")

    def wire_mode(self) -> str:
        """The mode ring_permute actually runs with: the requested
        low-bit mode only when the policy enabled the cp_ring site."""
        return self.mode if self.compresses() else "dense"

    def a2a_compresses(self) -> bool:
        return self.compress_a2a and self.mode in ("int8", "fp8")

    def a2a_wire_mode(self) -> str:
        """The mode the 2d head scatter/gather legs run with: low-bit
        only when the policy enabled the cp_a2a site."""
        return self.mode if self.a2a_compresses() else "dense"

    def seq_groups(self) -> int:
        """cp_seq: how many sequence-stripe subgroups the ring visits
        (== cp under the flat ring geometry)."""
        return self.cp // self.subgroup

    def ring_hops(self) -> int:
        """Ring hops per layer per forward: cp-1 flat, cp_seq-1 under
        2d (the intra-subgroup merge rides the a2a legs instead)."""
        return self.seq_groups() - 1


def make_cp_comm(mesh, mode: str, cfg=None, policy=None,
                 chunk: int = 32, geometry: str = "ring",
                 subgroup: int = 0,
                 overlap: bool = True) -> Optional[CpComm]:
    """Build the engine's CpComm, or None when context parallelism is a
    no-op (mode "none", no mesh, or a trivial context axis). policy:
    same knob as make_tp_comm — only its "cp_ring" and "cp_a2a" sites
    are consulted (the TP sites belong to TpComm). geometry "2d"
    requires `subgroup` (cp_head) >= 2 dividing both cp and the query
    head count — each subgroup member owns heads/subgroup heads through
    the merge."""
    if mode not in MODES:
        raise ValueError(f"cp_collectives must be one of {MODES}, "
                         f"got {mode!r}")
    if geometry not in CP_GEOMETRIES:
        raise ValueError(f"cp geometry must be one of {CP_GEOMETRIES}, "
                         f"got {geometry!r}")
    if mode == "none" or mesh is None:
        return None
    cp = dict(mesh.shape).get(AXIS_CONTEXT, 1)
    if cp <= 1:
        return None
    if mode == "fp8" and not fp8_supported():
        raise ValueError(
            "cp_collectives='fp8': this toolchain has no fp8 dtype; "
            "use 'int8'")
    if chunk < 1:
        raise ValueError(f"comm chunk must be >= 1, got {chunk}")
    if geometry == "2d":
        if subgroup < 2:
            raise ValueError(
                "cp geometry '2d' needs a subgroup (cp_head) >= 2 — "
                f"got {subgroup}; pick the node-local device count "
                "(--serve_cp_subgroup)")
        if cp % subgroup:
            raise ValueError(
                f"cp geometry '2d': subgroup {subgroup} does not divide "
                f"the context axis {cp} (cp = cp_seq x cp_head needs an "
                "exact factorization)")
        if cfg is not None and cfg.num_attention_heads % subgroup:
            raise ValueError(
                f"cp geometry '2d': query head count "
                f"{cfg.num_attention_heads} is not divisible by the "
                f"subgroup {subgroup} — the head all-to-all gives each "
                "member heads/subgroup heads")
    else:
        if subgroup not in (0, 1):
            raise ValueError(
                f"cp geometry 'ring' takes no subgroup (got {subgroup}); "
                "select --serve_cp_geometry 2d to factor the axis")
        subgroup = 1
    pol = resolve_policy(policy)
    return CpComm(mesh=mesh, cp=cp, mode=mode, chunk=int(chunk),
                  compress_ring=pol.enabled("cp_ring"),
                  geometry=geometry, subgroup=int(subgroup),
                  overlap=bool(overlap),
                  compress_a2a=pol.enabled("cp_a2a"))


def _validate_cfg(cfg, tp: int, sites) -> None:
    """Fail at engine build, not mid-trace: every dimension an enabled
    site splits over the tensor axis must divide by tp — BOTH the
    contracting dim (the shard_map in_spec split) and, for the psum
    sites, the output width hidden_size (the two-step reduce splits the
    psum payload's last dim across peers)."""
    dims = {
        "attn_out": (("attention width (heads x head_dim)",
                      cfg.num_attention_heads * cfg.head_dim),
                     ("hidden size", cfg.hidden_size)),
        "mlp_out": (("ffn width", cfg.ffn_size),
                    ("hidden size", cfg.hidden_size)),
        "logits": (("vocab size", cfg.vocab_size),),
    }
    for site in sorted(sites):
        for label, dim in dims[site]:
            if dim % tp:
                raise ValueError(
                    f"compressed collectives: {label} {dim} is not "
                    f"divisible by tensor_parallel {tp} (site {site!r}; "
                    "disable it in the comm policy or change the "
                    "geometry)")
    if cfg.num_experts is not None:
        raise ValueError(
            "compressed collectives do not cover MoE expert dispatch — "
            "serve MoE models with --serve_compress_collectives none")
    if cfg.fp8_format is not None:
        raise ValueError(
            "compressed collectives with fp8 training matmuls "
            "(cfg.fp8_format) is untested — drop one of the two")


# ---------------------------------------------------------------------------
# the collective wrappers (inside shard_map bodies)
# ---------------------------------------------------------------------------


def compressed_psum(x: jnp.ndarray, axis_name: str, mode: str = "int8",
                    chunk: int = 32) -> jnp.ndarray:
    """Flash-AllReduce inside a shard_map body: quantize -> all-to-all ->
    exact local reduce -> re-quantize -> all-gather, scales riding
    alongside each phase. Falls back to ``jax.lax.psum`` on a trivial
    axis (nothing to compress) or under mode "dense"."""
    tp = jax.lax.axis_size(axis_name)
    if tp == 1 or mode == "dense":
        return jax.lax.psum(x, axis_name)
    last = x.ndim - 1
    w = x.shape[-1]
    if w % tp:
        raise ValueError(f"compressed_psum: last-dim width {w} not "
                         f"divisible by axis size {tp}")
    # chunk must tile the PER-DEVICE slice so the scale rows split
    # evenly through the all-to-all
    c = effective_chunk(w // tp, chunk)
    q, s = quantize_chunked(x, c, mode)
    q = jax.lax.all_to_all(q, axis_name, split_axis=last,
                           concat_axis=last, tiled=True)
    s = jax.lax.all_to_all(s, axis_name, split_axis=last,
                           concat_axis=last, tiled=True)
    # device i now holds every peer's slice i: dequantize exactly, reduce
    # at fp32 (the reduction itself is never low-bit — only the wire is)
    part = dequantize_chunked(q, s, jnp.float32)
    red = part.reshape(*part.shape[:-1], tp, w // tp).sum(-2)
    q2, s2 = quantize_chunked(red, c, mode)
    q2 = jax.lax.all_gather(q2, axis_name, axis=last, tiled=True)
    s2 = jax.lax.all_gather(s2, axis_name, axis=last, tiled=True)
    return dequantize_chunked(q2, s2, x.dtype)


def ring_permute(x: jnp.ndarray, axis_name: str, perm,
                 mode: str = "dense", chunk: int = 32) -> jnp.ndarray:
    """One ring hop inside a shard_map body: ``jax.lax.ppermute`` of x
    along `axis_name`, with the payload optionally quantized for the
    wire (int8/fp8 + fp32 scales riding alongside) and dequantized on
    arrival — the context-parallel ring-attention transport
    (inference/context_parallel/ring_kv.py). Dense modes move x as-is."""
    if mode in ("none", "dense"):
        return jax.lax.ppermute(x, axis_name, perm)
    c = effective_chunk(x.shape[-1], chunk)
    q, s = quantize_chunked(x, c, mode)
    q = jax.lax.ppermute(q, axis_name, perm)
    s = jax.lax.ppermute(s, axis_name, perm)
    return dequantize_chunked(q, s, x.dtype)


def grouped_all_to_all(x: jnp.ndarray, axis_name: str, split_axis: int,
                       concat_axis: int, groups,
                       mode: str = "dense",
                       chunk: int = 32) -> jnp.ndarray:
    """Subgroup-scoped tiled all_to_all inside a shard_map body — the
    2d CP geometry's head-scatter leg (site "cp_a2a"): each member of a
    `groups` row trades its split_axis slices with its peers only.
    Optionally low-bit on the wire (payload + fp32 scales, quantized
    along the last axis, like ring_permute)."""
    if mode in ("none", "dense"):
        return jax.lax.all_to_all(x, axis_name, split_axis=split_axis,
                                  concat_axis=concat_axis, tiled=True,
                                  axis_index_groups=groups)
    c = effective_chunk(x.shape[-1], chunk)
    q, s = quantize_chunked(x, c, mode)
    q = jax.lax.all_to_all(q, axis_name, split_axis=split_axis,
                           concat_axis=concat_axis, tiled=True,
                           axis_index_groups=groups)
    s = jax.lax.all_to_all(s, axis_name, split_axis=split_axis,
                           concat_axis=concat_axis, tiled=True,
                           axis_index_groups=groups)
    return dequantize_chunked(q, s, x.dtype)


def grouped_all_gather(x: jnp.ndarray, axis_name: str, gather_axis: int,
                       groups, mode: str = "dense",
                       chunk: int = 32) -> jnp.ndarray:
    """Subgroup-scoped tiled all_gather — the 2d CP geometry's
    head-gather leg (site "cp_a2a"): reassembles the full head dim from
    the members' head slices after the cross-subgroup ring. Same wire
    treatment as grouped_all_to_all (quantized along the LAST axis, so
    a non-last gather_axis still compresses)."""
    if mode in ("none", "dense"):
        return jax.lax.all_gather(x, axis_name, axis=gather_axis,
                                  tiled=True, axis_index_groups=groups)
    c = effective_chunk(x.shape[-1], chunk)
    q, s = quantize_chunked(x, c, mode)
    q = jax.lax.all_gather(q, axis_name, axis=gather_axis, tiled=True,
                           axis_index_groups=groups)
    s = jax.lax.all_gather(s, axis_name, axis=gather_axis, tiled=True,
                           axis_index_groups=groups)
    return dequantize_chunked(q, s, x.dtype)


def compressed_all_gather(x: jnp.ndarray, axis_name: str,
                          mode: str = "int8", chunk: int = 32,
                          gather_axis: Optional[int] = None) -> jnp.ndarray:
    """Low-bit all-gather inside a shard_map body: quantize the local
    shard, gather payload + scales, dequantize. Dense fallback on a
    trivial axis / mode "dense". gather_axis defaults to the last (the
    quantized) axis."""
    tp = jax.lax.axis_size(axis_name)
    last = x.ndim - 1
    ax = last if gather_axis is None else gather_axis
    if tp == 1 or mode == "dense":
        return jax.lax.all_gather(x, axis_name, axis=ax, tiled=True)
    if ax != last:
        raise ValueError("compressed_all_gather quantizes along the last "
                         f"axis; gather_axis {ax} != {last}")
    c = effective_chunk(x.shape[-1], chunk)
    q, s = quantize_chunked(x, c, mode)
    q = jax.lax.all_gather(q, axis_name, axis=last, tiled=True)
    s = jax.lax.all_gather(s, axis_name, axis=last, tiled=True)
    return dequantize_chunked(q, s, x.dtype)


# ---------------------------------------------------------------------------
# model-facing seams (GSPMD-compatible shard_map islands)
# ---------------------------------------------------------------------------


def row_parallel_matmul(x: jnp.ndarray, w: jnp.ndarray, tpc: TpComm,
                        site: str) -> jnp.ndarray:
    """x [..., K] @ w [K, N] with the contraction sharded over the
    tensor axis and the partial-sum reduction running as an EXPLICIT
    collective (dense psum or the compressed two-step), instead of
    GSPMD's inserted all-reduce. Sites the policy disabled keep the
    plain einsum (GSPMD stays free to place it)."""
    if tpc is None or site not in tpc.sites:
        return jnp.einsum("...k,kn->...n", x, w)
    if w.shape[0] % tpc.tp:
        raise ValueError(
            f"row_parallel_matmul[{site}]: contracting dim {w.shape[0]} "
            f"not divisible by tp {tpc.tp}")

    def body(xl, wl):
        part = jnp.einsum("...k,kn->...n", xl, wl)
        return compressed_psum(part, tpc.axis, mode=tpc.mode,
                               chunk=tpc.chunk)

    x_spec = P(*([None] * (x.ndim - 1)), tpc.axis)
    return jax.shard_map(
        body, mesh=tpc.mesh, in_specs=(x_spec, P(tpc.axis, None)),
        out_specs=P(), check_vma=False)(x, w)


def vocab_parallel_logits(x: jnp.ndarray, w: jnp.ndarray, tpc: TpComm,
                          tied: bool) -> jnp.ndarray:
    """Vocab-parallel logits projection with an EXPLICIT (optionally
    compressed) all-gather over the tensor axis: each shard computes its
    vocab slice, the gather re-assembles [..., V] for the sampler.
    tied: w is the [V, h] embedding table; untied: the [h, V] lm head."""
    if tpc is None or "logits" not in tpc.sites:
        if tied:
            return jnp.einsum("bsh,vh->bsv", x, w)
        return jnp.einsum("bsh,hv->bsv", x, w)
    v_dim = w.shape[0] if tied else w.shape[1]
    if v_dim % tpc.tp:
        raise ValueError(f"vocab_parallel_logits: vocab {v_dim} not "
                         f"divisible by tp {tpc.tp}")

    def body(xl, wl):
        if tied:
            local = jnp.einsum("bsh,vh->bsv", xl, wl)
        else:
            local = jnp.einsum("bsh,hv->bsv", xl, wl)
        return compressed_all_gather(local, tpc.axis, mode=tpc.mode,
                                     chunk=tpc.chunk)

    w_spec = P(tpc.axis, None) if tied else P(None, tpc.axis)
    return jax.shard_map(
        body, mesh=tpc.mesh, in_specs=(P(), w_spec),
        out_specs=P(), check_vma=False)(x, w)


# ---------------------------------------------------------------------------
# static byte accounting (telemetry counters + the comm_policy journal)
# ---------------------------------------------------------------------------


def _site_bytes(width: int, rows: int, tpc: TpComm,
                act_itemsize: int, kind: str) -> Dict[str, int]:
    """Wire bytes one site moves for `rows` tokens of a `width`-wide
    payload: {"dense": explicit-dense bytes, "compressed": this mode's
    bytes}. Uses the same wire model as the jaxpr auditor
    (analysis/taxonomy.wire_bytes_per_call), so the live counters and
    the golden manifests tell one story."""
    n = tpc.tp
    payload = rows * width * act_itemsize
    if kind == "all-reduce":
        dense = wire_bytes_per_call("psum", payload, n)
    else:
        dense = wire_bytes_per_call("all_gather", payload, n)
    if not tpc.compresses():
        return {"dense": dense, "compressed": dense}
    if kind == "all-reduce":
        c = effective_chunk(width // n, tpc.chunk)
        q = rows * width                      # int8/fp8: 1 byte/elt
        s = rows * (width // c) * 4           # fp32 scales
        comp = (wire_bytes_per_call("all_to_all", q + s, n)
                + wire_bytes_per_call("all_gather", q + s, n))
    else:
        c = effective_chunk(width, tpc.chunk)
        q = rows * width
        s = rows * (width // c) * 4
        comp = wire_bytes_per_call("all_gather", q + s, n)
    return {"dense": dense, "compressed": comp}


def forward_comm_bytes(cfg, tpc: Optional[TpComm], batch: int,
                       seq: int) -> Dict[str, int]:
    """Per-forward wire bytes of the explicit TP collectives for a
    [batch, seq] token pass: {"dense", "compressed"}. Zero when tpc is
    None (single-chip or mode none — GSPMD's collectives are not
    routed through the explicit seam and are not counted here)."""
    out = {"dense": 0, "compressed": 0}
    if tpc is None:
        return out
    rows = batch * seq
    act = jnp.dtype(cfg.dtype).itemsize
    per_layer = []
    if "attn_out" in tpc.sites:
        per_layer.append(_site_bytes(cfg.hidden_size, rows, tpc, act,
                                     "all-reduce"))
    if "mlp_out" in tpc.sites:
        per_layer.append(_site_bytes(cfg.hidden_size, rows, tpc, act,
                                     "all-reduce"))
    for b in per_layer:
        out["dense"] += b["dense"] * cfg.num_layers
        out["compressed"] += b["compressed"] * cfg.num_layers
    if "logits" in tpc.sites:
        b = _site_bytes(cfg.vocab_size, rows, tpc, act, "all-gather")
        out["dense"] += b["dense"]
        out["compressed"] += b["compressed"]
    return out


def cp_ring_comm_bytes(cfg, cpc: Optional[CpComm], batch: int,
                       seq: int) -> Dict[str, int]:
    """Per-forward wire bytes of the CP attention merge for a
    [batch, seq] token pass: {"dense", "compressed"} are the ring-hop
    rows (cp-1 hops per layer flat; cp_seq-1 hops at 1/subgroup the
    head payload under the 2d geometry); {"a2a_dense",
    "a2a_compressed"} are the 2d geometry's intra-subgroup head
    scatter/gather legs (site "cp_a2a" — zero under the flat ring).
    Each ring hop permutes the normalized partial output (fp32
    [batch, seq, heads, head_dim]) plus its log-sum-exp row (fp32
    [batch, seq, heads] — never compressed: it feeds the merge's
    exp/log directly). Same wire model as the jaxpr auditor, so the
    golden manifests and the live counters agree. Zero when cpc is
    None."""
    out = {"dense": 0, "compressed": 0, "a2a_dense": 0,
           "a2a_compressed": 0}
    if cpc is None:
        return out
    g = cpc.subgroup
    rows = batch * seq * cfg.num_attention_heads
    ring_rows = batch * seq * (cfg.num_attention_heads // g)
    o_payload = ring_rows * cfg.head_dim * 4
    lse_payload = ring_rows * 4
    hops = cpc.ring_hops() * cfg.num_layers
    dense_hop = (wire_bytes_per_call("ppermute", o_payload, cpc.cp)
                 + wire_bytes_per_call("ppermute", lse_payload, cpc.cp))
    out["dense"] = dense_hop * hops
    if not cpc.compresses():
        out["compressed"] = out["dense"]
    else:
        c = effective_chunk(cfg.head_dim, cpc.chunk)
        q = ring_rows * cfg.head_dim          # int8/fp8: 1 byte/elt
        s = ring_rows * (cfg.head_dim // c) * 4   # fp32 scales
        comp_hop = (wire_bytes_per_call("ppermute", q + s, cpc.cp)
                    + wire_bytes_per_call("ppermute", lse_payload,
                                          cpc.cp))
        out["compressed"] = comp_hop * hops
    if cpc.geometry != "2d":
        return out
    # the a2a legs, per layer: scatter moves the full-head partial
    # (o + lse) inside the subgroup; after the ring, gather reassembles
    # the full head dim from the members' slices. lse rides dense.
    o_full = rows * cfg.head_dim * 4
    lse_full = rows * 4
    legs = (wire_bytes_per_call("all_to_all", o_full + lse_full, g)
            + wire_bytes_per_call("all_gather", o_full, g))
    out["a2a_dense"] = legs * cfg.num_layers
    if not cpc.a2a_compresses():
        out["a2a_compressed"] = out["a2a_dense"]
        return out
    c = effective_chunk(cfg.head_dim, cpc.chunk)
    q = rows * cfg.head_dim
    s = rows * (cfg.head_dim // c) * 4
    comp_legs = (wire_bytes_per_call("all_to_all", q + s + lse_full, g)
                 + wire_bytes_per_call("all_gather", q + s, g))
    out["a2a_compressed"] = comp_legs * cfg.num_layers
    return out

"""Quantize/dequantize primitives for compressed collectives.

The stdlib-of-the-repo low-bit recipe the compressed TP collectives
(quant/collectives.py) are built from: per-chunk symmetric scaling along
the LAST axis, int8 (127-level clamp/round, the same recipe as
ops/kv_quant.py but chunked instead of per-vector) or fp8 e4m3 where the
toolchain carries the dtype. Chunked scales are what makes activation
quantization safe for communication: one outlier poisons only its own
`chunk` elements, not the whole tensor (Flash Communication 2412.04964's
fine-grained-scale argument).

Every recipe ships with a WORST-CASE round-trip error bound
(``quantization_error_bound``) that is a unit-tested invariant
(tests/test_quant_comm.py): for every element,

    |x - deq(quant(x))| <= bound(x)

  * int8: the symmetric scale is chunk_amax / 127, values land exactly in
    [-127, 127], so the only error is round-to-nearest: bound = scale / 2.
  * fp8 (e4m3fn, 3 mantissa bits): normals round within a relative
    half-ulp of 2^-4; subnormals (|u| < 2^-6 after scaling) within an
    absolute 2^-10 of the scaled value: bound = |x| * 2^-4 + scale * 2^-10.

No engine/model imports — this module is leaf-level like ops/kv_quant.py.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

#: fp8 transport format: e4m3fn (the forward-activation format; e5m2's
#: 2-bit mantissa would double the rounding error for no range benefit on
#: amax-normalized chunks)
FP8_DTYPE_NAME = "float8_e4m3fn"


def fp8_supported() -> bool:
    """Whether this jax/ml_dtypes build carries the fp8 transport dtype
    (the --serve_compress_collectives fp8 gate)."""
    return hasattr(jnp, FP8_DTYPE_NAME)


def _fp8_dtype():
    if not fp8_supported():
        raise ValueError(
            f"this toolchain has no jnp.{FP8_DTYPE_NAME}; use int8 "
            "compressed collectives instead")
    return getattr(jnp, FP8_DTYPE_NAME)


def effective_chunk(width: int, chunk: int) -> int:
    """The largest divisor of `width` that is <= `chunk` (>= 1): the
    scale granularity actually used when the requested chunk does not
    tile the quantized axis."""
    if width < 1:
        raise ValueError(f"width must be >= 1, got {width}")
    c = max(1, min(int(chunk), width))
    while width % c:
        c -= 1
    return c


def _chunked(x: jnp.ndarray, chunk: int) -> jnp.ndarray:
    """[..., W] -> [..., W/chunk, chunk] fp32 view."""
    w = x.shape[-1]
    if w % chunk:
        raise ValueError(f"chunk {chunk} does not divide width {w} "
                         "(use effective_chunk)")
    return x.astype(jnp.float32).reshape(*x.shape[:-1], w // chunk, chunk)


def quantize_chunked(x: jnp.ndarray, chunk: int, mode: str = "int8"
                     ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """[..., W] float -> (q low-bit [..., W], scales fp32 [..., W/chunk])
    with per-chunk symmetric max-abs scaling along the last axis."""
    xc = _chunked(x, chunk)
    amax = jnp.max(jnp.abs(xc), axis=-1, keepdims=True)
    if mode == "int8":
        scale = jnp.maximum(amax, 1e-8) / 127.0
        q = jnp.clip(jnp.round(xc / scale), -127, 127).astype(jnp.int8)
    elif mode == "fp8":
        dt = _fp8_dtype()
        scale = jnp.maximum(amax, 1e-8) / float(jnp.finfo(dt).max)
        q = (xc / scale).astype(dt)
    else:
        raise ValueError(f"unknown quantization mode {mode!r} "
                         "(expected 'int8' or 'fp8')")
    return q.reshape(x.shape), scale[..., 0]


def dequantize_chunked(q: jnp.ndarray, scales: jnp.ndarray,
                       dtype=jnp.float32) -> jnp.ndarray:
    """Inverse of quantize_chunked: scales broadcast back over their
    chunk."""
    w = q.shape[-1]
    chunk = w // scales.shape[-1]
    qc = q.astype(jnp.float32).reshape(*q.shape[:-1], w // chunk, chunk)
    return (qc * scales[..., None]).reshape(q.shape).astype(dtype)


def quantization_error_bound(x: jnp.ndarray, chunk: int,
                             mode: str = "int8") -> jnp.ndarray:
    """Per-element worst-case |x - deq(quant(x))| for the recipes above
    (module docstring derivation). Unit-tested invariant, and the number
    the parity gates' logit-error thresholds are derated from."""
    xc = _chunked(x, chunk)
    amax = jnp.max(jnp.abs(xc), axis=-1, keepdims=True)
    if mode == "int8":
        scale = jnp.maximum(amax, 1e-8) / 127.0
        bound = jnp.broadcast_to(scale / 2.0, xc.shape)
    elif mode == "fp8":
        dt = _fp8_dtype()
        scale = jnp.maximum(amax, 1e-8) / float(jnp.finfo(dt).max)
        bound = jnp.abs(xc) * 2.0 ** -4 + scale * 2.0 ** -10
    else:
        raise ValueError(f"unknown quantization mode {mode!r}")
    return bound.reshape(x.shape)

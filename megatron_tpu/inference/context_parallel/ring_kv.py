"""Ring attention over sequence-striped paged KV pools.

The device half of the CP serving engine: one partial-manual shard_map
island over the "context" mesh axis that (1) scatter-writes the new
K/V rows into the LOCAL pool shard (each rank owns the pages of its
sequence stripe — logical page l lives on rank ``l % cp``), (2) runs
the exact masked attention of ops/attention.py against the local
stripe only, producing a normalized (out, lse) partial, and (3) merges
the cp partials with the ring-attention merge algebra
(ops/ring_attention._merge_normalized) under one of two geometries:

  * "ring" — cp-1 ``ppermute`` hops around the flat context axis. The
    schedule is OVERLAPPED by default (CpComm.overlap): hop l+1's
    permute of the (o, lse) partial is issued BEFORE the merge compute
    over hop l's arrival, which is legal because the permute chain
    depends only on previous permute results, never on the merges —
    the accumulator hangs off each arrival separately. Same hop count,
    same wire bytes, numerics identical to the serial schedule; an
    async backend (TPU collective-permute-start/done) can run hop l+1
    under hop l's merge instead of exposing it.
  * "2d" — cp = cp_seq x cp_head (ATTENTION2D): a tiled head
    all-to-all inside each cp_head-sized subgroup trades full-head
    partials for ITS head slice of every member's partial (site
    "cp_a2a"), the members' stripes merge locally, then cp_seq-1 ring
    hops ACROSS subgroups (1/cp_head the payload) merge the rest, and
    an intra-subgroup all_gather restores the full head dim — TASP's
    topology-aware placement: the expensive ring traverses the slow
    fabric tier once, the chatty legs stay node-local.

The hop transport is quant/collectives.ring_permute — dense fp32 or
policy-gated int8/fp8 (site "cp_ring"); the 2d legs ride
grouped_all_to_all / grouped_all_gather (site "cp_a2a").

Mask semantics mirror ops/attention.py exactly so the CP engine stays
token-identical to the dense one:

  * decode (per_slot): key position g attends iff ``g < lengths[i] + 1``
    (+ the sliding-window floor), lengths being the pre-increment slot
    length — same as the dense engine's ``kv_lengths = cache_index + 1``.
  * chunk prefill: ``g <= off + q_idx`` causal, window ``g > q_pos - w``.

The local tables arriving here are PER-RANK views ([cp, rows, mpl],
sharded on dim 0): entry [r, i, j] holds rank r's local pool index of
logical page ``j*cp + r`` of row i, or the sentinel ``npl`` (== local
pool size) when that logical page is unallocated on r or out of the
row's span. Sentinel writes drop (scatter mode="drop"); sentinel reads
are masked out of the softmax.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from megatron_tpu.ops.ring_attention import _merge_normalized
from megatron_tpu.quant.collectives import (
    grouped_all_gather, grouped_all_to_all, ring_permute,
)


def _ring_hop(cpc, o, lse, perm):
    """One ring hop: the o partial over the (optionally compressed)
    cp_ring transport, the lse row always dense fp32."""
    no = ring_permute(o, cpc.axis, perm, mode=cpc.wire_mode(),
                      chunk=cpc.chunk)
    nl = jax.lax.ppermute(lse, cpc.axis, perm)
    return no, nl


def _ring_merge(cpc, o, lse, perm, hops):
    """Merge `hops` ring arrivals into the local partial.

    Serial schedule: permute -> merge -> permute -> ... (each hop's
    send waits for the previous merge in program order). Overlapped
    schedule (cpc.overlap): hop l+1's permute is issued BEFORE hop l's
    merge — valid because ``cur`` chains only through permutes and the
    accumulator hangs off each arrival separately, so the reorder is
    numerics-identical with the same hop count and wire bytes; it just
    stops the merge compute from serializing the collective chain."""
    acc_o, acc_lse = o, lse
    if hops <= 0:
        return acc_o, acc_lse
    if not cpc.overlap:
        cur_o, cur_lse = o, lse
        for _ in range(hops):
            cur_o, cur_lse = _ring_hop(cpc, cur_o, cur_lse, perm)
            acc_o, acc_lse = _merge_normalized((acc_o, acc_lse),
                                               cur_o, cur_lse)
        return acc_o, acc_lse
    nxt_o, nxt_lse = _ring_hop(cpc, o, lse, perm)
    for hop in range(hops):
        cur_o, cur_lse = nxt_o, nxt_lse
        if hop + 1 < hops:
            nxt_o, nxt_lse = _ring_hop(cpc, cur_o, cur_lse, perm)
        acc_o, acc_lse = _merge_normalized((acc_o, acc_lse),
                                           cur_o, cur_lse)
    return acc_o, acc_lse


def _merge_2d(cpc, o, lse):
    """The 2d-geometry merge: head scatter inside the subgroup, local
    merge of the members' stripes, overlapped ring across subgroups at
    1/subgroup the payload, head gather. Every rank ends with the full
    [B, S, Hq, D] result (replicated, like the flat ring)."""
    cp, g = cpc.cp, cpc.subgroup
    sg = cp // g
    bsz, s_len, hq, d = o.shape
    groups = [list(range(i * g, (i + 1) * g)) for i in range(sg)]
    a2a_mode = cpc.a2a_wire_mode()
    # head scatter: member h of each subgroup ends with head slice h of
    # every member's partial, stacked in member order on a leading dim
    o_st = grouped_all_to_all(o, cpc.axis, split_axis=2, concat_axis=0,
                              groups=groups, mode=a2a_mode,
                              chunk=cpc.chunk)
    l_st = jax.lax.all_to_all(lse, cpc.axis, split_axis=2,
                              concat_axis=0, tiled=True,
                              axis_index_groups=groups)
    o_st = o_st.reshape(g, bsz, s_len, hq // g, d)
    l_st = l_st.reshape(g, bsz, s_len, hq // g)
    acc_o, acc_lse = o_st[0], l_st[0]
    for m in range(1, g):
        acc_o, acc_lse = _merge_normalized((acc_o, acc_lse),
                                           o_st[m], l_st[m])
    # ring only across subgroups: rank (s, h) -> (s+1, h)
    perm = [(r, (r + g) % cp) for r in range(cp)]
    acc_o, acc_lse = _ring_merge(cpc, acc_o, acc_lse, perm, sg - 1)
    # head gather: the members' full-sequence head slices reassemble
    return grouped_all_gather(acc_o, cpc.axis, gather_axis=2,
                              groups=groups, mode=a2a_mode,
                              chunk=cpc.chunk)


def paged_ring_attention(cpc, q, k_new, v_new, kv_cache, loc_tables,
                         cache_index, per_slot, page_write_start=None,
                         page_write_end=None, sliding_window=None):
    """Cross-shard paged attention for one layer.

    q [B, S, Hq, D]; k_new/v_new [B, S, Hkv, D] (post-rope);
    kv_cache = (k_pool, v_pool) each [num_pages, page_size, Hkv, D]
    sharded over "context" on the pages dim; loc_tables [cp, B_t, mpl]
    sharded over "context" on dim 0. per_slot decode: cache_index =
    lengths [B] and S must be 1. Chunk prefill: cache_index = scalar
    chunk offset, B == 1, and the write fences bound the page writes.
    Returns (ctx [B, S, Hq, D] in q.dtype, (k_pool, v_pool) updated).
    """
    k_pool, v_pool = kv_cache
    cp, axis = cpc.cp, cpc.axis
    if per_slot and q.shape[1] != 1:
        raise ValueError(
            "context-parallel paged decode serves one token per slot "
            f"(no speculative rows); got S={q.shape[1]}")
    if not per_slot and q.shape[0] != 1:
        raise ValueError(
            f"context-parallel chunk prefill needs batch 1, got "
            f"{q.shape[0]}")
    if per_slot:
        page_write_start = jnp.int32(0)
        page_write_end = jnp.int32(2 ** 30)
    window = sliding_window

    def inner(qx, kn, vn, kp, vp, loc, idx, ws, we):
        r = jax.lax.axis_index(axis)
        npl, ps = kp.shape[0], kp.shape[1]
        loc = loc[0]                               # [B_t, mpl] local view
        mpl = loc.shape[1]
        B, S, Hq, D = qx.shape
        Hkv = kn.shape[2]

        # -- scatter-write this step's K/V into the local stripe -------
        if per_slot:
            pos = idx                              # [B] write positions
            lpage = pos // ps
            j = jnp.minimum(lpage // cp, mpl - 1)
            phys = jnp.take_along_axis(loc, j[:, None], axis=1)[:, 0]
            owned = (lpage % cp) == r
            tgt = jnp.where(owned, phys, npl)
            kp = kp.at[tgt, pos % ps].set(kn[:, 0].astype(kp.dtype),
                                          mode="drop")
            vp = vp.at[tgt, pos % ps].set(vn[:, 0].astype(vp.dtype),
                                          mode="drop")
        else:
            pos = idx + jnp.arange(S, dtype=jnp.int32)   # [S]
            lpage = pos // ps
            j = jnp.minimum(lpage // cp, mpl - 1)
            phys = jnp.take(loc[0], j, mode="clip")
            owned = ((lpage % cp) == r) & (pos >= ws) & (pos < we)
            tgt = jnp.where(owned, phys, npl)
            kp = kp.at[tgt, pos % ps].set(kn[0].astype(kp.dtype),
                                          mode="drop")
            vp = vp.at[tgt, pos % ps].set(vn[0].astype(vp.dtype),
                                          mode="drop")

        # -- gather the local stripe + its global token positions ------
        safe = jnp.minimum(loc, npl - 1)           # [B_t, mpl]
        kf = jnp.take(kp, safe, axis=0, mode="clip")
        vf = jnp.take(vp, safe, axis=0, mode="clip")
        s_loc = mpl * ps
        kf = kf.reshape(loc.shape[0], s_loc, Hkv, D)
        vf = vf.reshape(loc.shape[0], s_loc, Hkv, D)
        g_pos = ((jnp.arange(mpl, dtype=jnp.int32) * cp + r) * ps)[:, None] \
            + jnp.arange(ps, dtype=jnp.int32)[None, :]
        g_pos = g_pos.reshape(s_loc)               # global position per key
        valid = jnp.repeat(loc != npl, ps, axis=1)  # [B_t, s_loc]

        # -- exact masked partial softmax (ops/attention.py semantics) --
        scale = 1.0 / jnp.sqrt(jnp.asarray(D, jnp.float32))
        qf = qx.astype(jnp.float32) * scale
        groups = Hq // Hkv
        qg = qf.reshape(B, S, Hkv, groups, D)
        scores = jnp.einsum("bqhgd,bkhd->bhgqk", qg,
                            kf.astype(jnp.float32))
        if per_slot:
            kv_len = idx[:, None, None] + 1        # pre-increment length
            allowed = g_pos[None, None, :] < kv_len
            if window is not None:
                allowed &= g_pos[None, None, :] >= kv_len - window
        else:
            q_pos = (idx + jnp.arange(S, dtype=jnp.int32))[None, :, None]
            allowed = g_pos[None, None, :] <= q_pos
            if window is not None:
                allowed &= g_pos[None, None, :] > q_pos - window
        allowed &= valid[:, None, :]               # [B, S, s_loc]
        scores = jnp.where(allowed[:, None, None], scores, -jnp.inf)
        m_raw = jnp.max(scores, axis=-1)           # [B, Hkv, G, S]
        m_safe = jnp.where(jnp.isfinite(m_raw), m_raw, 0.0)
        p = jnp.exp(scores - m_safe[..., None])    # exp(-inf) == 0
        tot = jnp.sum(p, axis=-1)                  # [B, Hkv, G, S]
        o = jnp.einsum("bhgqk,bkhd->bqhgd", p, vf.astype(jnp.float32))
        tot_t = tot.transpose(0, 3, 1, 2)          # [B, S, Hkv, G]
        o = o / jnp.maximum(tot_t, 1e-30)[..., None]
        lse = jnp.where(tot_t > 0.0,
                        m_safe.transpose(0, 3, 1, 2)
                        + jnp.log(jnp.maximum(tot_t, 1e-30)),
                        -jnp.inf)
        o = o.reshape(B, S, Hq, D)
        lse = lse.reshape(B, S, Hq)

        # -- merge: all ranks end with the full result ------------------
        if cpc.geometry == "2d":
            acc_o = _merge_2d(cpc, o, lse)
        else:
            perm = [(i, (i + 1) % cp) for i in range(cp)]
            acc_o, _ = _ring_merge(cpc, o, lse, perm, cp - 1)
        return acc_o.astype(qx.dtype), kp, vp

    shard = P(axis)
    ctx, k_pool, v_pool = jax.shard_map(
        inner, mesh=cpc.mesh,
        in_specs=(P(), P(), P(), shard, shard, shard, P(), P(), P()),
        out_specs=(P(), shard, shard),
        axis_names={axis}, check_vma=False)(
            q, k_new, v_new, k_pool, v_pool, loc_tables,
            cache_index, page_write_start, page_write_end)
    return ctx, (k_pool, v_pool)

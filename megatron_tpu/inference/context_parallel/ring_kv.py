"""Ring attention over sequence-striped paged KV pools.

The device half of the CP serving engine: one partial-manual shard_map
island over the "context" mesh axis that (1) scatter-writes the new
K/V rows into the LOCAL pool shard (each rank owns the pages of its
sequence stripe — logical page l lives on rank ``l % cp``), (2) runs
the exact masked attention of ops/attention.py against the local
stripe only, producing a normalized (out, lse) partial, and (3) merges
the cp partials with cp-1 ``ppermute`` ring hops and the
ring-attention merge algebra (ops/ring_attention._merge_normalized).
The hop transport is quant/collectives.ring_permute — dense fp32 or
policy-gated int8/fp8 (site "cp_ring").

Mask semantics mirror ops/attention.py exactly so the CP engine stays
token-identical to the dense one:

  * decode (per_slot): key position g attends iff ``g < lengths[i] + 1``
    (+ the sliding-window floor), lengths being the pre-increment slot
    length — same as the dense engine's ``kv_lengths = cache_index + 1``.
  * chunk prefill: ``g <= off + q_idx`` causal, window ``g > q_pos - w``.

The local tables arriving here are PER-RANK views ([cp, rows, mpl],
sharded on dim 0): entry [r, i, j] holds rank r's local pool index of
logical page ``j*cp + r`` of row i, or the sentinel ``npl`` (== local
pool size) when that logical page is unallocated on r or out of the
row's span. Sentinel writes drop (scatter mode="drop"); sentinel reads
are masked out of the softmax.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from megatron_tpu.ops.ring_attention import _merge_normalized
from megatron_tpu.quant.collectives import ring_permute


def paged_ring_attention(cpc, q, k_new, v_new, kv_cache, loc_tables,
                         cache_index, per_slot, page_write_start=None,
                         page_write_end=None, sliding_window=None):
    """Cross-shard paged attention for one layer.

    q [B, S, Hq, D]; k_new/v_new [B, S, Hkv, D] (post-rope);
    kv_cache = (k_pool, v_pool) each [num_pages, page_size, Hkv, D]
    sharded over "context" on the pages dim; loc_tables [cp, B_t, mpl]
    sharded over "context" on dim 0. per_slot decode: cache_index =
    lengths [B] and S must be 1. Chunk prefill: cache_index = scalar
    chunk offset, B == 1, and the write fences bound the page writes.
    Returns (ctx [B, S, Hq, D] in q.dtype, (k_pool, v_pool) updated).
    """
    k_pool, v_pool = kv_cache
    cp, axis = cpc.cp, cpc.axis
    if per_slot and q.shape[1] != 1:
        raise ValueError(
            "context-parallel paged decode serves one token per slot "
            f"(no speculative rows); got S={q.shape[1]}")
    if not per_slot and q.shape[0] != 1:
        raise ValueError(
            f"context-parallel chunk prefill needs batch 1, got "
            f"{q.shape[0]}")
    if per_slot:
        page_write_start = jnp.int32(0)
        page_write_end = jnp.int32(2 ** 30)
    window = sliding_window

    def inner(qx, kn, vn, kp, vp, loc, idx, ws, we):
        r = jax.lax.axis_index(axis)
        npl, ps = kp.shape[0], kp.shape[1]
        loc = loc[0]                               # [B_t, mpl] local view
        mpl = loc.shape[1]
        B, S, Hq, D = qx.shape
        Hkv = kn.shape[2]

        # -- scatter-write this step's K/V into the local stripe -------
        if per_slot:
            pos = idx                              # [B] write positions
            lpage = pos // ps
            j = jnp.minimum(lpage // cp, mpl - 1)
            phys = jnp.take_along_axis(loc, j[:, None], axis=1)[:, 0]
            owned = (lpage % cp) == r
            tgt = jnp.where(owned, phys, npl)
            kp = kp.at[tgt, pos % ps].set(kn[:, 0].astype(kp.dtype),
                                          mode="drop")
            vp = vp.at[tgt, pos % ps].set(vn[:, 0].astype(vp.dtype),
                                          mode="drop")
        else:
            pos = idx + jnp.arange(S, dtype=jnp.int32)   # [S]
            lpage = pos // ps
            j = jnp.minimum(lpage // cp, mpl - 1)
            phys = jnp.take(loc[0], j, mode="clip")
            owned = ((lpage % cp) == r) & (pos >= ws) & (pos < we)
            tgt = jnp.where(owned, phys, npl)
            kp = kp.at[tgt, pos % ps].set(kn[0].astype(kp.dtype),
                                          mode="drop")
            vp = vp.at[tgt, pos % ps].set(vn[0].astype(vp.dtype),
                                          mode="drop")

        # -- gather the local stripe + its global token positions ------
        safe = jnp.minimum(loc, npl - 1)           # [B_t, mpl]
        kf = jnp.take(kp, safe, axis=0, mode="clip")
        vf = jnp.take(vp, safe, axis=0, mode="clip")
        s_loc = mpl * ps
        kf = kf.reshape(loc.shape[0], s_loc, Hkv, D)
        vf = vf.reshape(loc.shape[0], s_loc, Hkv, D)
        g_pos = ((jnp.arange(mpl, dtype=jnp.int32) * cp + r) * ps)[:, None] \
            + jnp.arange(ps, dtype=jnp.int32)[None, :]
        g_pos = g_pos.reshape(s_loc)               # global position per key
        valid = jnp.repeat(loc != npl, ps, axis=1)  # [B_t, s_loc]

        # -- exact masked partial softmax (ops/attention.py semantics) --
        scale = 1.0 / jnp.sqrt(jnp.asarray(D, jnp.float32))
        qf = qx.astype(jnp.float32) * scale
        groups = Hq // Hkv
        qg = qf.reshape(B, S, Hkv, groups, D)
        scores = jnp.einsum("bqhgd,bkhd->bhgqk", qg,
                            kf.astype(jnp.float32))
        if per_slot:
            kv_len = idx[:, None, None] + 1        # pre-increment length
            allowed = g_pos[None, None, :] < kv_len
            if window is not None:
                allowed &= g_pos[None, None, :] >= kv_len - window
        else:
            q_pos = (idx + jnp.arange(S, dtype=jnp.int32))[None, :, None]
            allowed = g_pos[None, None, :] <= q_pos
            if window is not None:
                allowed &= g_pos[None, None, :] > q_pos - window
        allowed &= valid[:, None, :]               # [B, S, s_loc]
        scores = jnp.where(allowed[:, None, None], scores, -jnp.inf)
        m_raw = jnp.max(scores, axis=-1)           # [B, Hkv, G, S]
        m_safe = jnp.where(jnp.isfinite(m_raw), m_raw, 0.0)
        p = jnp.exp(scores - m_safe[..., None])    # exp(-inf) == 0
        tot = jnp.sum(p, axis=-1)                  # [B, Hkv, G, S]
        o = jnp.einsum("bhgqk,bkhd->bqhgd", p, vf.astype(jnp.float32))
        tot_t = tot.transpose(0, 3, 1, 2)          # [B, S, Hkv, G]
        o = o / jnp.maximum(tot_t, 1e-30)[..., None]
        lse = jnp.where(tot_t > 0.0,
                        m_safe.transpose(0, 3, 1, 2)
                        + jnp.log(jnp.maximum(tot_t, 1e-30)),
                        -jnp.inf)
        o = o.reshape(B, S, Hq, D)
        lse = lse.reshape(B, S, Hq)

        # -- ring merge: cp-1 hops, all ranks end with the full result --
        perm = [(i, (i + 1) % cp) for i in range(cp)]
        acc_o, acc_lse = o, lse
        cur_o, cur_lse = o, lse
        for _ in range(cp - 1):
            cur_o = ring_permute(cur_o, axis, perm, mode=cpc.wire_mode(),
                                 chunk=cpc.chunk)
            cur_lse = jax.lax.ppermute(cur_lse, axis, perm)
            acc_o, acc_lse = _merge_normalized((acc_o, acc_lse),
                                               cur_o, cur_lse)
        return acc_o.astype(qx.dtype), kp, vp

    shard = P(axis)
    ctx, k_pool, v_pool = jax.shard_map(
        inner, mesh=cpc.mesh,
        in_specs=(P(), P(), P(), shard, shard, shard, P(), P(), P()),
        out_specs=(P(), shard, shard),
        axis_names={axis}, check_vma=False)(
            q, k_new, v_new, k_pool, v_pool, loc_tables,
            cache_index, page_write_start, page_write_end)
    return ctx, (k_pool, v_pool)

"""Sequence-striped page allocator for context-parallel serving.

Same host-side contract as inference/paging/pool.PagePool (all-or-
nothing alloc, refcounts, scratch page 0), plus one invariant the CP
attention island depends on: **logical page l of any sequence lives on
CP rank ``l % cp``**. The global page-id space [0, num_pages) is split
into cp contiguous ranges of ``num_pages // cp`` ids; rank r owns
global ids [r*npl, (r+1)*npl). ``alloc(n, logical_start)`` draws page j
of the run from the free list of rank ``(logical_start + j) % cp``, so
a freshly-allocated table row is striped by construction — and every
radix-cache hit re-uses pages that were inserted from striped rows, so
shared prefixes keep the invariant for free.

The scratch page (global 0) sits in rank 0's range; ranks r > 0 map
unallocated/out-of-span table entries to a per-rank sentinel instead
(the local-table builder in engine.py).
"""

from __future__ import annotations

from typing import List, Optional

from megatron_tpu.inference.paging.pool import PagePool


class StripedPagePool(PagePool):
    """PagePool whose free space is partitioned by owning CP rank."""

    def __init__(self, num_pages: int, cp: int):
        if cp < 1:
            raise ValueError(f"cp must be >= 1, got {cp}")
        if num_pages % cp:
            raise ValueError(
                f"num_pages {num_pages} must be a multiple of cp {cp} "
                "(equal per-rank pool shards)")
        super().__init__(num_pages)
        self.cp = cp
        self.pages_per_rank = num_pages // cp
        # re-home the flat LIFO free list into per-rank LIFO lists;
        # rank 0 loses one slot to the reserved scratch page
        npl = self.pages_per_rank
        self._free_by_rank: List[List[int]] = [
            [p for p in range((r + 1) * npl - 1, r * npl - 1, -1) if p != 0]
            for r in range(cp)
        ]
        self._free = None  # the flat list must never be touched again

    def owner(self, page: int) -> int:
        """CP rank whose pool shard holds this global page id."""
        return page // self.pages_per_rank

    @property
    def free_pages(self) -> int:
        return sum(len(f) for f in self._free_by_rank)

    def free_pages_by_rank(self) -> List[int]:
        """Per-CP-rank free page counts (the per-shard gauges)."""
        return [len(f) for f in self._free_by_rank]

    @property
    def used_pages(self) -> int:
        return (self.num_pages - 1) - self.free_pages

    def alloc(self, n: int = 1,
              logical_start: int = 0) -> Optional[List[int]]:
        """n fresh pages honoring the striping invariant: page j of the
        run comes from rank ``(logical_start + j) % cp``. All-or-nothing
        — None when ANY needed rank's shard can't cover its share (the
        caller evicts/preempts and retries; there is deliberately no
        cross-rank fallback, a page on the wrong rank would be invisible
        to that rank's attention shard)."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        need = [0] * self.cp
        for j in range(n):
            need[(logical_start + j) % self.cp] += 1
        if any(need[r] > len(self._free_by_rank[r]) for r in range(self.cp)):
            return None
        pages = []
        for j in range(n):
            p = self._free_by_rank[(logical_start + j) % self.cp].pop()
            self._refs[p] = 1
            pages.append(p)
        return pages

    def release(self, pages) -> int:
        """Drop one reference per page; pages reaching zero return to
        their OWNER rank's free list."""
        freed = 0
        for p in pages:
            if p == 0:  # SCRATCH_PAGE
                continue
            if self._refs[p] <= 0:
                raise ValueError(f"release of free page {p}")
            self._refs[p] -= 1
            if self._refs[p] == 0:
                self._free_by_rank[self.owner(p)].append(p)
                freed += 1
        return freed

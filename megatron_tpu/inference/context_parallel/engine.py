"""ContextParallelEngine: paged serving with sequence-sharded KV.

Subclass of PagedInferenceEngine that keeps EVERY host-side policy
unchanged — one global radix prefix tree, one chunked-prefill queue,
LIFO preemption, sliding-window release, the global [N, max_pages]
table rows — and restructures only the device side:

  * the KV page pools are sharded over the "context" mesh axis on the
    pages dimension, and allocation is striped so logical page l of any
    row lives on CP rank ``l % cp`` (pool.StripedPagePool);
  * the decode/chunk steps receive PER-RANK local tables
    ([cp, rows, pages_per_rank], sharded on dim 0) instead of the flat
    global row, which routes the per-layer attention through the
    ring-attention island (ring_kv.paged_ring_attention): each rank
    attends its own sequence stripe and the normalized partials merge
    under the selected geometry — the flat overlapped ring (cp-1
    ``ppermute`` hops, hop l+1 issued before hop l's merge) or the 2d
    cp_seq x cp_head factorization (head all-to-all inside a
    `cp_subgroup`-sized group, cp_seq-1 ring hops across groups);
  * the hop transport is quant/collectives.CpComm — dense fp32 or
    policy-gated int8/fp8 (site "cp_ring"), composable with the
    existing TP compressed collectives on a TP x CP mesh.

Because the host bookkeeping is inherited verbatim, radix hits,
mid-prefill preempt/resume and ragged prompt tails are exact by the
same arguments as the single-host paged engine; the parity gates in
tests/test_context_parallel.py pin greedy token identity against the
dense engine. int8 KV pools and speculative decoding are out of scope
(both rejected at build).
"""

from __future__ import annotations

from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from megatron_tpu.config import ModelConfig
from megatron_tpu.inference.context_parallel.pool import StripedPagePool
from megatron_tpu.inference.paging.engine import PagedInferenceEngine
from megatron_tpu.inference.paging.pool import SCRATCH_PAGE
from megatron_tpu.inference.paging.radix import RadixPrefixCache
from megatron_tpu.parallel.mesh import AXIS_CONTEXT
from megatron_tpu.quant.collectives import cp_ring_comm_bytes, make_cp_comm


class ContextParallelEngine(PagedInferenceEngine):
    """Paged serving engine over a TP x CP mesh (tp >= 1, cp >= 2)."""

    def __init__(self, cfg: ModelConfig, params: Any, num_slots: int = 8,
                 max_seq_len: Optional[int] = None,
                 page_size: int = 16, prefill_chunk: int = 32,
                 num_pages: Optional[int] = None,
                 vocab_size: Optional[int] = None, mesh=None,
                 want_logprobs: bool = True, metrics=None,
                 flight_recorder=None,
                 force_donate: Optional[bool] = None,
                 max_queue: Optional[int] = None,
                 compress_collectives: str = "none",
                 comm_policy=None,
                 comm_chunk: int = 32,
                 cp_collectives: str = "dense",
                 cp_comm_policy=None,
                 cp_geometry: str = "ring",
                 cp_subgroup: int = 0,
                 cp_overlap: bool = True):
        if mesh is None:
            raise ValueError(
                "ContextParallelEngine requires a mesh with a non-trivial "
                f"'{AXIS_CONTEXT}' axis")
        cp = dict(mesh.shape).get(AXIS_CONTEXT, 1)
        if cp <= 1:
            raise ValueError(
                f"ContextParallelEngine needs {AXIS_CONTEXT} >= 2 on the "
                f"mesh (got {cp}); use PagedInferenceEngine for cp == 1")
        self.cp = cp
        # set BEFORE super().__init__: the inherited step builders close
        # over cp_comm, and _fresh_caches rounds the pool to cp shards
        self.cp_comm = make_cp_comm(mesh, cp_collectives, cfg=cfg,
                                    policy=cp_comm_policy, chunk=comm_chunk,
                                    geometry=cp_geometry,
                                    subgroup=cp_subgroup,
                                    overlap=cp_overlap)
        if self.cp_comm is None:
            raise ValueError(
                f"cp_collectives={cp_collectives!r} disables the ring "
                "transport the CP engine is built on (use 'dense', 'int8' "
                "or 'fp8')")
        self._cp_bytes_for = {}
        super().__init__(
            cfg, params, num_slots=num_slots, max_seq_len=max_seq_len,
            kv_cache_int8=False, page_size=page_size,
            prefill_chunk=prefill_chunk, num_pages=num_pages,
            vocab_size=vocab_size, mesh=mesh,
            want_logprobs=want_logprobs, metrics=metrics,
            flight_recorder=flight_recorder, force_donate=force_donate,
            max_queue=max_queue, speculative=None,
            compress_collectives=compress_collectives,
            comm_policy=comm_policy, comm_chunk=comm_chunk)
        self._npl = self.num_pages // cp          # pool pages per rank
        self._mpl = -(-self.max_pages // cp)      # table slots per rank
        if self._npl - 1 < self._mpl:
            raise ValueError(
                f"num_pages={self.num_pages} over cp={cp} leaves "
                f"{self._npl} pages per rank — rank 0 (minus scratch) "
                f"cannot hold one full sequence ({self._mpl} pages)")
        # re-home the allocator: striped per-rank free lists under the
        # SAME refcount/scratch contract (nothing is allocated yet — the
        # base constructor only sized the pool)
        self.pool = StripedPagePool(self.num_pages, cp)
        self.prefix_cache = RadixPrefixCache(self.pool, self.page_size)
        self._m_pages_free.set(self.pool.free_pages)

        self._cp_bytes_for = {
            id(self._comm_tick_bytes): cp_ring_comm_bytes(
                cfg, self.cp_comm, num_slots, 1),
            id(self._comm_chunk_bytes): cp_ring_comm_bytes(
                cfg, self.cp_comm, 1, self.prefill_chunk),
        }
        self.stats.update({"cp_ring_steps": 0, "cp_comm_dense_bytes": 0,
                           "cp_comm_compressed_bytes": 0,
                           "cp_comm_a2a_dense_bytes": 0,
                           "cp_comm_a2a_compressed_bytes": 0,
                           "cp_admission_blocked": 0})
        self._cp_dry_shards: tuple = ()
        m = self.metrics
        self._m_cp_ring = m.counter(
            "engine_cp_ring_steps_total",
            "context-parallel ring hops executed (per layer per forward)")
        self._m_cp_dense = m.counter(
            "engine_cp_comm_dense_bytes_total",
            "wire bytes the CP ring hops would move dense")
        self._m_cp_comp = m.counter(
            "engine_cp_comm_compressed_bytes_total",
            "wire bytes the CP ring hops move at the configured mode")
        self._m_cp_a2a_dense = m.counter(
            "engine_cp_a2a_dense_bytes_total",
            "wire bytes the 2d geometry's head a2a legs would move dense")
        self._m_cp_a2a_comp = m.counter(
            "engine_cp_a2a_compressed_bytes_total",
            "wire bytes the 2d geometry's head a2a legs move at the "
            "configured mode")
        self._m_cp_shard_free = m.gauge(
            "engine_cp_shard_pages_free",
            "free pages in each CP rank's pool shard",
            label_names=("shard",))
        self._m_cp_blocked = m.counter(
            "engine_cp_admission_blocked_total",
            "page allocations blocked by an exhausted CP pool shard "
            "(striped-pool pressure, distinct from queue depth)",
            label_names=("shard",))
        self._set_shard_gauges()

    # ----- cache + shape policy -------------------------------------------

    def _fresh_caches(self):
        """Same pools as the paged engine, with the page count rounded up
        to a multiple of cp so every rank holds an equal shard (the
        striping arithmetic and the P(None, context, ...) placement both
        need exact divisibility)."""
        if self.num_pages is None:
            max_pages = -(-self.max_seq_len // self.page_size)
            self.num_pages = self.num_slots * max_pages + 1
        self.num_pages += (-self.num_pages) % self.cp
        return super()._fresh_caches()

    def _kv_sharding(self):
        """Pool placement: pages sharded over "context" — each rank holds
        its sequence stripe's pages. Heads stay replicated over "tensor":
        the ring island is full-manual over every mesh axis (compat.py
        shard_map shim), so a tensor-sharded heads dim would just be
        force-gathered at the island boundary each step."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh,
                             P(None, AXIS_CONTEXT, None, None, None))

    # ----- page accounting -------------------------------------------------

    def _alloc_pages(self, n: int,
                     logical_start: int = 0) -> Optional[List[int]]:
        """Striped allocation with per-rank-aware eviction: a failed
        alloc means SOME rank's shard is dry, so evict LRU cache-only
        pages (whatever ranks hold them) and retry until the striped
        grab fits or eviction runs dry. A final failure is attributed
        to the dry shard(s): counter + journal + the distinct 503
        detail (_overload_detail), so operators can tell striped-pool
        pressure from ordinary queue depth."""
        pages = self.pool.alloc(n, logical_start)
        while pages is None and self.prefix_cache.evict(max(n, 1)) > 0:
            pages = self.pool.alloc(n, logical_start)
        if pages is not None:
            self._m_pages_free.set(self.pool.free_pages)
            self._cp_dry_shards = ()
            return pages
        need = [0] * self.cp
        for j in range(n):
            need[(logical_start + j) % self.cp] += 1
        free = self.pool.free_pages_by_rank()
        dry = tuple(r for r in range(self.cp) if need[r] > free[r])
        self.stats["cp_admission_blocked"] += 1
        for r in dry:
            self._m_cp_blocked.inc(shard=str(r))
        if dry != self._cp_dry_shards:
            # once per episode, not per retried tick
            from megatron_tpu.telemetry import journal as _journal

            j = _journal.get_global_journal()
            if j is not None:
                j.emit("cp_admission_blocked", shards=list(dry),
                       need=need, free_by_rank=list(free), pages=n)
        self._cp_dry_shards = dry
        return None

    def _overload_detail(self) -> str:
        """Queue-full rejections name the dry shard(s) when striped-pool
        exhaustion — not decode throughput — is what's stalling
        admission (the 503 detail fleet operators key on)."""
        if self._cp_dry_shards:
            shards = ",".join(str(r) for r in self._cp_dry_shards)
            return (f"cp shard(s) {shards} exhausted (striped KV pool "
                    "pressure); ")
        return ""

    # ----- device tables ---------------------------------------------------

    def _loc_tables(self, rows: np.ndarray) -> np.ndarray:
        """Global table rows [M, max_pages] -> per-rank local tables
        [cp, M, mpl]: entry [r, i, j] is rank r's LOCAL pool index of
        logical page ``j*cp + r`` of row i. Unallocated entries (global
        SCRATCH_PAGE) map to local scratch on rank 0 (same masked-write
        semantics as the flat engine) and to the out-of-range sentinel
        ``npl`` elsewhere (writes drop, reads are masked)."""
        rows = np.asarray(rows, np.int32)
        cp, npl, mpl = self.cp, self._npl, self._mpl
        loc = np.full((cp, rows.shape[0], mpl), npl, np.int32)
        for r in range(cp):
            cols = rows[:, r::cp]
            if ((cols != SCRATCH_PAGE) & (cols // npl != r)).any():
                raise AssertionError(
                    f"page-striping invariant violated on rank {r}: a "
                    "logical page maps outside its owner's pool shard")
            loc[r, :, :cols.shape[1]] = np.where(
                cols == SCRATCH_PAGE, 0 if r == 0 else npl, cols - r * npl)
        return loc

    def _cp_table_device(self, loc: np.ndarray):
        from jax.sharding import NamedSharding, PartitionSpec as P

        sh = NamedSharding(self.mesh, P(AXIS_CONTEXT))
        return jax.device_put(jnp.asarray(loc), sh)

    def _decode_extra_args(self):
        if self._table_dirty or self._device_table is None:
            self._device_table = self._cp_table_device(
                self._loc_tables(self.tables))
            self._table_dirty = False
        return (self._device_table,)

    def _chunk_table_arg(self, row):
        return self._cp_table_device(
            self._loc_tables(np.asarray(row)[None, :]))

    # ----- telemetry -------------------------------------------------------

    def _count_comm(self, bytes_pair) -> None:
        super()._count_comm(bytes_pair)
        cp_pair = self._cp_bytes_for.get(id(bytes_pair))
        if cp_pair is None:
            return
        hops = self.cp_comm.ring_hops() * self.cfg.num_layers
        self.stats["cp_ring_steps"] += hops
        self.stats["cp_comm_dense_bytes"] += cp_pair["dense"]
        self.stats["cp_comm_compressed_bytes"] += cp_pair["compressed"]
        self._m_cp_ring.inc(hops)
        self._m_cp_dense.inc(cp_pair["dense"])
        self._m_cp_comp.inc(cp_pair["compressed"])
        if cp_pair.get("a2a_dense"):
            self.stats["cp_comm_a2a_dense_bytes"] += cp_pair["a2a_dense"]
            self.stats["cp_comm_a2a_compressed_bytes"] += (
                cp_pair["a2a_compressed"])
            self._m_cp_a2a_dense.inc(cp_pair["a2a_dense"])
            self._m_cp_a2a_comp.inc(cp_pair["a2a_compressed"])

    def _set_shard_gauges(self) -> None:
        for r, free in enumerate(self.pool.free_pages_by_rank()):
            self._m_cp_shard_free.set(free, shard=str(r))

    def step(self) -> int:
        served = super().step()
        self._set_shard_gauges()
        return served

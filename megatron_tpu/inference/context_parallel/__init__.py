"""Context-parallel serving: distributed chunked prefill + sequence-
sharded paged KV (ISSUE 19).

The paged serving stack (inference/paging/) keeps one host-side view of
every request — radix prefix cache, chunked prefill queue, preempt/
resume, sliding-window release — while this package re-homes the
device-side KV under it: the page pools are striped over the CP mesh
axis (logical page l of any sequence lives on rank ``l % cp``), each
chunk-prefill and decode step runs cross-shard attention through a
ring of ``ppermute`` hops (ops/ring_attention.py's merge algebra over
the paged pools), and the ring transport itself is policy-gated
compressible (quant/collectives.CpComm).

Exactness contract: greedy decode through the CP engine is
token-identical to the dense single-host engine, with logprob rows
matching to fp32 merge tolerance (tests/test_context_parallel.py).
"""

from megatron_tpu.inference.context_parallel.engine import (  # noqa: F401
    ContextParallelEngine,
)
from megatron_tpu.inference.context_parallel.pool import (  # noqa: F401
    StripedPagePool,
)

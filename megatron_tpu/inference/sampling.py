"""Token sampling: greedy / temperature / top-k / top-p.

Equivalent of megatron/text_generation/sampling.py (93 LoC), as one jittable
function. Filtering works on sorted logits so top-k and top-p compose, and
everything stays fixed-shape for XLA.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def sample_logits(
    logits: jnp.ndarray,          # [B, V] float
    key: Optional[jax.Array],
    temperature: float = 1.0,
    top_k: int = 0,
    top_p: float = 0.0,
    vocab_size: Optional[int] = None,
) -> jnp.ndarray:
    """Returns sampled token ids [B]. top_k=0/top_p=0 disable the filters;
    temperature 0 (or key None) is greedy (ref: sampling.py sample())."""
    logits = logits.astype(jnp.float32)
    if vocab_size is not None and vocab_size < logits.shape[-1]:
        # clamp padded vocab columns (ref: vocab boundary clamp)
        neg = jnp.finfo(jnp.float32).min
        mask = jnp.arange(logits.shape[-1]) < vocab_size
        logits = jnp.where(mask, logits, neg)

    greedy = key is None or temperature == 0.0
    if greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    logits = logits / temperature

    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, jnp.finfo(jnp.float32).min, logits)

    if top_p > 0.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix with cumulative prob >= top_p (always
        # keep the top token)
        cutoff_idx = jnp.sum(cum < top_p, axis=-1)  # [B]
        cutoff_logit = jnp.take_along_axis(
            sorted_logits, cutoff_idx[:, None], axis=-1)
        logits = jnp.where(logits < cutoff_logit,
                           jnp.finfo(jnp.float32).min, logits)

    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)

"""Token sampling: greedy / temperature / top-k / top-p.

Equivalent of megatron/text_generation/sampling.py (93 LoC), as one jittable
function. Filtering works on sorted logits so top-k and top-p compose, and
everything stays fixed-shape for XLA.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def sample_logits(
    logits: jnp.ndarray,          # [B, V] float
    key: Optional[jax.Array],
    temperature: float = 1.0,
    top_k: int = 0,
    top_p: float = 0.0,
    vocab_size: Optional[int] = None,
) -> jnp.ndarray:
    """Returns sampled token ids [B]. top_k=0/top_p=0 disable the filters;
    temperature 0 (or key None) is greedy (ref: sampling.py sample())."""
    logits = logits.astype(jnp.float32)
    if vocab_size is not None and vocab_size < logits.shape[-1]:
        # clamp padded vocab columns (ref: vocab boundary clamp)
        neg = jnp.finfo(jnp.float32).min
        mask = jnp.arange(logits.shape[-1]) < vocab_size
        logits = jnp.where(mask, logits, neg)

    greedy = key is None or temperature == 0.0
    if greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    logits = logits / temperature

    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, jnp.finfo(jnp.float32).min, logits)

    if top_p > 0.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix with cumulative prob >= top_p (always
        # keep the top token)
        cutoff_idx = jnp.sum(cum < top_p, axis=-1)  # [B]
        cutoff_logit = jnp.take_along_axis(
            sorted_logits, cutoff_idx[:, None], axis=-1)
        logits = jnp.where(logits < cutoff_logit,
                           jnp.finfo(jnp.float32).min, logits)

    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def filter_top_k_top_p(
    scaled: jnp.ndarray,          # [B, V] temperature-scaled logits
    top_k: jnp.ndarray,           # [B] int; 0 disables per row
    top_p: jnp.ndarray,           # [B] float; 0 disables per row
) -> jnp.ndarray:
    """Per-row top-k then top-p composition on sorted logits — THE one
    implementation of the filter semantics, shared by the batched
    sampler below and the speculative verify's accept/reject
    (inference/speculative.py, which flattens its [N, k+1, V] positions
    into the batch axis): the speculative exactness contract is that
    both paths draw from the IDENTICAL filtered distribution, so the
    composition must never fork. Rows with top_k<=0 / top_p<=0 keep all
    mass for that filter; each row's top token always survives. Masking
    only values BELOW the kth keeps the descending sort valid for the
    top-p pass, so one sort serves both filters."""
    neg = jnp.finfo(jnp.float32).min
    V = scaled.shape[-1]
    desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    kth = jnp.take_along_axis(
        desc, jnp.clip(top_k[:, None] - 1, 0, V - 1), axis=-1)
    cond_tk = (top_k[:, None] > 0) & (scaled < kth)
    scaled = jnp.where(cond_tk, neg, scaled)
    desc = jnp.where((top_k[:, None] > 0) & (desc < kth), neg, desc)
    cum = jnp.cumsum(jax.nn.softmax(desc, axis=-1), axis=-1)
    cutoff_idx = jnp.sum(cum < top_p[:, None], axis=-1, keepdims=True)
    cutoff = jnp.take_along_axis(desc, cutoff_idx, axis=-1)
    return jnp.where((top_p[:, None] > 0) & (scaled < cutoff),
                     neg, scaled)


def sample_logits_batched(
    logits: jnp.ndarray,          # [B, V] float
    keys: jnp.ndarray,            # [B, 2] per-row PRNG keys
    temperature: jnp.ndarray,     # [B] float; 0 = greedy for that row
    top_k: jnp.ndarray,           # [B] int; 0 disables
    top_p: jnp.ndarray,           # [B] float; 0 disables
    vocab_size: Optional[int] = None,
) -> jnp.ndarray:
    """Per-row sampling for the continuous-batching engine: every knob is
    a traced [B] array so heterogeneous requests (different temperatures,
    top-k/top-p) share ONE compiled decode step — the scalar sampler's
    static args would force a recompile per sampling config. Row semantics
    match sample_logits exactly: greedy rows ignore the filters, top-k and
    top-p compose on sorted logits, padded vocab columns are clamped.

    The expensive pieces run under lax.cond on what the batch actually
    needs: all-greedy traffic pays one argmax (no sort, no categorical),
    and the [B, V] filter sort only runs when some row has top-k/top-p.
    XLA:CPU's sort is scalar — unconditionally sorting every tick was
    ~3x the whole decode step (bench.py serving numbers)."""
    logits = logits.astype(jnp.float32)
    neg = jnp.finfo(jnp.float32).min
    V = logits.shape[-1]
    if vocab_size is not None and vocab_size < V:
        logits = jnp.where(jnp.arange(V) < vocab_size, logits, neg)

    # greedy rows bypass temperature/filters entirely (scalar fast path)
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def _sample(logits):
        t = temperature[:, None]
        scaled = logits / jnp.where(t > 0, t, 1.0)
        # filter semantics live in filter_top_k_top_p (shared with the
        # speculative verify step); same composition order as the
        # scalar sampler, one sort serves both filters
        scaled = jax.lax.cond(
            jnp.any((top_k > 0) | (top_p > 0)),
            lambda s: filter_top_k_top_p(s, top_k, top_p),
            lambda s: s, scaled)
        return jax.vmap(jax.random.categorical)(keys, scaled).astype(
            jnp.int32)

    sampled = jax.lax.cond(jnp.any(temperature > 0), _sample,
                           lambda _: greedy_tok, logits)
    return jnp.where(temperature > 0, sampled, greedy_tok)

"""Radix tree over token IDs at page granularity.

Requests that share a prompt prefix should share the K/V pages that
prefix produced instead of recomputing them — the dominant prefill cost
for shared-system-prompt traffic. The tree maps token-ID paths (in
whole-page steps of `page_size` tokens) to physical page ids in the
PagePool; a lookup walks the request's prompt and returns the longest
fully-matched run of pages, which the engine aliases into the new
slot's page table (one pool.retain per sharer).

Sharing is safe because shared pages are READ-ONLY by construction —
copy-on-write semantics: a slot never writes through its table into a
page the cache (or another slot) also references. The engine enforces
this two ways: (1) only FULL pages enter the tree, so the partially
filled tail page a sequence appends to during decode is always private;
(2) the first recomputed chunk after a hit starts one position inside
the shared span (to recompute the boundary token's teacher-forced
logprob exactly) and fences that overlap write onto the scratch page
(transformer.attention_block page_write_start). Divergence after the
shared span lands in freshly allocated pages — the "copy" of
copy-on-write is recomputation into a private page, never an in-place
edit of a shared one.

Each node also carries the teacher-forced logprobs of its page's tokens
(logprob of token t given tokens[0..t-1] depends only on the node's own
path, so it is as cacheable as the K/V), letting a cache hit return the
same prompt_logprobs a full prefill would.

Eviction is LRU over leaf nodes whose page no live slot references
(pool refcount 1 = the cache's own ref): under memory pressure the
engine asks for n pages back, oldest-touched leaves first; freeing a
leaf can expose its parent as the next candidate.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from megatron_tpu.inference.paging.pool import PagePool


class _Node:
    __slots__ = ("key", "page", "lp", "children", "parent", "last_used")

    def __init__(self, key: Tuple[int, ...], page: int, lp: np.ndarray,
                 parent: Optional["_Node"]):
        self.key = key          # this page's page_size token ids
        self.page = page        # physical page id (cache holds one ref)
        self.lp = lp            # teacher-forced logprobs of this span
        self.children: Dict[Tuple[int, ...], _Node] = {}
        self.parent = parent
        self.last_used = 0


class RadixPrefixCache:
    def __init__(self, pool: PagePool, page_size: int):
        self.pool = pool
        self.page_size = int(page_size)
        self._children: Dict[Tuple[int, ...], _Node] = {}  # root level
        self._clock = 0
        self._nodes = 0

    def __len__(self) -> int:
        return self._nodes

    def _touch(self, node: _Node) -> None:
        self._clock += 1
        node.last_used = self._clock

    def lookup(self, tokens: Sequence[int]
               ) -> Tuple[List[int], List[np.ndarray]]:
        """Longest fully-cached whole-page prefix of `tokens`:
        (physical pages, per-page logprob arrays). The caller aliases
        the pages (pool.retain) — the cache's own references are
        untouched."""
        ps = self.page_size
        toks = [int(t) for t in tokens]
        pages: List[int] = []
        lps: List[np.ndarray] = []
        level = self._children
        for off in range(0, (len(toks) // ps) * ps, ps):
            node = level.get(tuple(toks[off:off + ps]))
            if node is None:
                break
            self._touch(node)
            pages.append(node.page)
            lps.append(node.lp)
            level = node.children
        return pages, lps

    def insert(self, tokens: Sequence[int], pages: Sequence[int],
               logprobs: Sequence[float]) -> int:
        """Register a computed prefix: full page m of `tokens` maps to
        pages[m]. logprobs[t-1] is the teacher-forced logprob of
        tokens[t] (the engine's prompt_logprobs layout). Pages already in
        the tree are skipped (the existing copy stays authoritative);
        new nodes retain their page in the pool. Returns the number of
        nodes added."""
        ps = self.page_size
        toks = [int(t) for t in tokens]
        n_pages = min(len(toks) // ps, len(pages))
        level = self._children
        parent: Optional[_Node] = None
        added = 0
        for m in range(n_pages):
            key = tuple(toks[m * ps:(m + 1) * ps])
            node = level.get(key)
            if node is None:
                # lp for token positions [m*ps, (m+1)*ps) — position 0
                # has no logprob, so page 0's slice starts at index 0 of
                # the (position-1)-indexed logprob row
                lo = max(m * ps, 1)
                lp = np.asarray(logprobs[lo - 1:(m + 1) * ps - 1],
                                np.float32)
                node = _Node(key, int(pages[m]), lp, parent)
                self.pool.retain([node.page])
                level[key] = node
                self._nodes += 1
                added += 1
            self._touch(node)
            parent = node
            level = node.children
        return added

    def _evictable(self) -> List[_Node]:
        """Leaves whose page only the cache references, LRU first."""
        out = []

        def walk(level):
            for node in level.values():
                if node.children:
                    walk(node.children)
                elif self.pool.refcount(node.page) == 1:
                    out.append(node)

        walk(self._children)
        out.sort(key=lambda n: n.last_used)
        return out

    def evict(self, n_pages: int) -> int:
        """Release up to n_pages cache-only pages back to the pool,
        strictly LRU: candidates are re-derived after every removal,
        because freeing a leaf can expose its parent as an OLDER
        candidate than the next stale leaf. Returns how many pages were
        actually freed."""
        freed = 0
        while freed < n_pages:
            cands = self._evictable()
            if not cands:
                break
            self._remove(cands[0])
            freed += 1
        return freed

    def clear(self) -> int:
        """Drop every node (engine cache-rebuild path). Returns pages
        released."""
        released = 0

        def walk(level):
            nonlocal released
            for node in level.values():
                walk(node.children)
                self.pool.release([node.page])
                released += 1

        walk(self._children)
        self._children = {}
        self._nodes = 0
        return released

    def _remove(self, node: _Node) -> None:
        level = (node.parent.children if node.parent is not None
                 else self._children)
        del level[node.key]
        self.pool.release([node.page])
        self._nodes -= 1

"""PagedInferenceEngine: the serving engine over a shared page pool.

Drop-in paged mode of the slot engine (inference/engine.py,
``--serve_kv_paging``). The per-slot ``[N, max_seq_len, ...]`` cache rows
become one pool of fixed-size pages shared by every slot:

  * admission allocates pages for the PROMPT span only (a young sequence
    holds the pages it has, not its worst case); decode grows a slot one
    page at a time as its length crosses page boundaries;
  * requests sharing a prompt prefix alias the same refcounted pages via
    the radix tree (radix.py) and skip prefill for the shared span;
  * prompts enter the cache ``prefill_chunk`` tokens per tick, one chunk
    before each batched decode (scheduler.py), so one long prompt can
    never stall the whole batch;
  * under memory pressure the engine first evicts cache-only prefix
    pages (LRU), then preempts the lowest-priority slot — the most
    recently admitted request (LIFO, so later arrivals yield to earlier
    ones). A preempted request keeps its sampled tokens and PRNG chain
    (Request.resume_key) and resumes by teacher-forced recompute of
    prompt + generated, which is exact: it finishes with the tokens it
    would have produced without the preemption.

Parity gates (tests/test_serving_engine.py): token-identical to the slot
engine on the serving matrix — greedy, sampled, int8, ragged, preempted
— and zero decode recompiles after warmup (the decode step's shapes,
including the ``[N, max_pages]`` device page table, never change).
"""

from __future__ import annotations

import time
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from megatron_tpu.config import ModelConfig
from megatron_tpu.inference.engine import InferenceEngine, Request
from megatron_tpu.inference.paging.pool import SCRATCH_PAGE, PagePool
from megatron_tpu.inference.paging.radix import RadixPrefixCache
from megatron_tpu.inference.paging.scheduler import (
    ChunkedPrefillQueue, PrefillTask,
)
from megatron_tpu.inference.sampling import sample_logits_batched


class PagedInferenceEngine(InferenceEngine):
    """Slot scheduler + paged KV pool + radix prefix cache.

    Same threading contract as the base engine: submit() from any
    thread, step()/run_until_idle() from one driver thread.
    """

    def __init__(self, cfg: ModelConfig, params: Any, num_slots: int = 8,
                 max_seq_len: Optional[int] = None,
                 kv_cache_int8: bool = False,
                 page_size: int = 16, prefill_chunk: int = 32,
                 num_pages: Optional[int] = None,
                 vocab_size: Optional[int] = None, mesh=None,
                 want_logprobs: bool = True, metrics=None,
                 flight_recorder=None,
                 force_donate: Optional[bool] = None,
                 max_queue: Optional[int] = None,
                 speculative=None,
                 compress_collectives: str = "none",
                 comm_policy=None,
                 comm_chunk: int = 32):
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        if num_pages is not None and num_pages < 2:
            raise ValueError(
                f"num_pages must be >= 2 (page 0 is scratch), got {num_pages}")
        self.page_size = int(page_size)
        self.prefill_chunk = int(prefill_chunk)  # validated by the queue
        self.num_pages = num_pages
        self.max_pages = 0          # set by _fresh_caches (needs max_seq_len)
        self.prefix_cache: Optional[RadixPrefixCache] = None
        super().__init__(
            cfg, params, num_slots=num_slots, max_seq_len=max_seq_len,
            kv_cache_int8=kv_cache_int8, vocab_size=vocab_size, mesh=mesh,
            want_logprobs=want_logprobs, metrics=metrics,
            flight_recorder=flight_recorder, force_donate=force_donate,
            max_queue=max_queue, speculative=speculative,
            compress_collectives=compress_collectives,
            comm_policy=comm_policy, comm_chunk=comm_chunk)
        if self.num_pages - 1 < self.max_pages:
            raise ValueError(
                f"num_pages={self.num_pages} cannot hold even one full "
                f"sequence ({self.max_pages} pages of {self.page_size} for "
                f"max_seq_len {self.max_seq_len}, + the scratch page)")

        N = num_slots
        self.pool = PagePool(self.num_pages)
        self.prefix_cache = RadixPrefixCache(self.pool, self.page_size)
        # host page tables: tables[i] is slot i's logical->physical map.
        # Mid-prefill slots keep their REAL row in _pending_rows and a
        # scratch row here, so the shared decode table can never route an
        # idle-drift write into a half-filled (possibly shared) page.
        self.tables = np.zeros((N, self.max_pages), np.int32)
        self._pending_rows = {}
        self._device_table = None
        self._table_dirty = True
        self.prefill_queue = ChunkedPrefillQueue(self.prefill_chunk)
        self._chunk_step = self._build_chunk_step()
        # static per-chunk wire price for the compressed-collective
        # counters (one [1, C] forward; quant/collectives.py)
        from megatron_tpu.quant.collectives import forward_comm_bytes

        self._comm_chunk_bytes = forward_comm_bytes(
            cfg, self.tp_comm, 1, self.prefill_chunk)
        self._draft_chunk_step = (self._build_draft_chunk_step()
                                  if self._has_draft_model() else None)
        # admission order for the preemption policy (higher = younger)
        self._admit_seq = [0] * N
        self._admit_counter = 0
        # sliding-window release cursor: first page index of each slot
        # NOT yet released (lengths never shrink below the committed
        # value, so release progress is monotone — the per-tick scan
        # starts here instead of at page 0)
        self._window_cursor = [0] * N

        self.stats.update({
            "prefix_hits": 0, "prefix_misses": 0,
            "prefix_tokens_saved": 0, "prefill_tokens": 0,
            "prefill_chunks": 0, "preemptions": 0,
            "window_pages_released": 0,
        })
        m = self.metrics
        self._m_pages_total = m.gauge("engine_pages_total",
                                      "KV pool pages (minus scratch)")
        self._m_pages_free = m.gauge("engine_pages_free",
                                     "KV pool pages on the free list")
        self._m_prefix_hits = m.counter(
            "engine_prefix_cache_hits_total",
            "admissions that aliased cached prefix pages")
        self._m_prefix_misses = m.counter(
            "engine_prefix_cache_misses_total",
            "admissions with no cached prefix")
        self._m_prefix_saved = m.counter(
            "engine_prefix_tokens_saved_total",
            "prefill positions skipped via the prefix cache")
        self._m_preempted = m.counter(
            "engine_preemptions_total",
            "slots preempted under page-pool pressure")
        self._m_chunks = m.counter("engine_prefill_chunks_total",
                                   "chunked-prefill steps executed")
        self._m_window_released = m.counter(
            "engine_window_pages_released_total",
            "pages freed from behind the sliding attention window")
        self._m_chunk = m.histogram("engine_prefill_chunk_seconds",
                                    "one prefill chunk's wall time")
        self._m_pages_total.set(self.num_pages - 1)
        self._m_pages_free.set(self.pool.free_pages)

    # ----- cache + shape policy -------------------------------------------

    def _kernel_seq_multiple(self) -> int:
        # logical capacity is whole pages; the paged kernel's grid is
        # per-page, so the dense kernel's 128 constraint doesn't apply
        return self.page_size

    def _fresh_caches(self):
        """Paged pools [L, num_pages, page_size, kv_heads, head_dim]
        (int8: the 4-tuple with per-position scales). On the
        failed-step rebuild path every cached prefix dies with the pool
        bytes, and mid-prefill slots lose their computed chunks — fail
        them like the active ones the caller already failed."""
        if self.prefix_cache is not None:
            for i in sorted(self.prefill_queue.slots):
                req = self.slots[i]
                if req is not None:
                    self._clear_slot(i)
                    req._finish("engine cache rebuilt after a failed step")
            self.prefix_cache.clear()
            self._m_pages_free.set(self.pool.free_pages)
        if self.num_pages is None:
            # default pool = full slot-engine capacity (every slot can
            # grow to max_seq_len); shrink it to oversubscribe
            self.max_pages = -(-self.max_seq_len // self.page_size)
            self.num_pages = self.num_slots * self.max_pages + 1
        else:
            self.max_pages = -(-self.max_seq_len // self.page_size)
        cfg = self.cfg
        shape = (cfg.num_layers, self.num_pages, self.page_size,
                 cfg.n_kv_heads, cfg.head_dim)
        if self.kv_cache_int8:
            sshape = shape[:-1] + (1,)
            return (jnp.zeros(shape, jnp.int8), jnp.zeros(shape, jnp.int8),
                    jnp.zeros(sshape, jnp.float32),
                    jnp.zeros(sshape, jnp.float32))
        return (jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype))

    def _fresh_draft_caches(self):
        """Draft-model page pools (speculative decoding): the draft
        config's own layer/head geometry over the SAME page count and
        page size as the target pools, addressed through the SAME per-
        slot page tables — one allocation/refcount/prefix-aliasing
        story covers both trees (a page shared via the radix cache is
        shared in both pools, since both were written through the same
        table by the original prefill). Always bf16/f32."""
        dcfg = self.spec.draft_cfg
        shape = (dcfg.num_layers, self.num_pages, self.page_size,
                 dcfg.n_kv_heads, dcfg.head_dim)
        return (jnp.zeros(shape, dcfg.dtype), jnp.zeros(shape, dcfg.dtype))

    def _spec_paged(self) -> bool:
        return True

    # ----- jitted device steps --------------------------------------------

    def _build_decode_step(self):
        cfg, vocab, wlp = self.cfg, self.vocab_size, self.want_logprobs
        tp_comm = self.tp_comm
        # the CP engine sets cp_comm before super().__init__ so the same
        # builders serve it — a 3-D device table then routes the forward
        # through the ring-attention island (models/transformer.py)
        cp_comm = getattr(self, "cp_comm", None)
        from functools import partial

        from megatron_tpu.models.language_model import lm_forward

        @partial(jax.jit, donate_argnums=self._donate(),
                 **self._jit_sharding_kwargs(
                     ("rep", "rep", "kv", "rep", "rep")))
        def decode_step(params, caches, table, last_tok, lengths, keys,
                        temps, top_ks, top_ps):
            # identical to the slot decode step except K/V writes and
            # reads route through the page table (ops/attention.py picks
            # the paged flash-decode kernel on TPU, the gather elsewhere)
            logits, caches = lm_forward(cfg, params, last_tok[:, None],
                                        kv_caches=caches,
                                        cache_index=lengths,
                                        page_table=table,
                                        tp_comm=tp_comm,
                                        cp_comm=cp_comm)
            logits = logits[:, 0]
            split = jax.vmap(lambda k: jax.random.split(k, 2))(keys)
            new_keys, subs = split[:, 0], split[:, 1]
            toks = sample_logits_batched(logits, subs, temps, top_ks,
                                         top_ps, vocab)
            if wlp:
                lp = jnp.take_along_axis(
                    jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1),
                    toks[:, None], axis=-1)[:, 0]
            else:
                lp = jnp.zeros(toks.shape, jnp.float32)
            return toks, lp, caches, new_keys, lengths + 1

        return decode_step

    def _build_chunk_step(self):
        cfg, vocab, wlp = self.cfg, self.vocab_size, self.want_logprobs
        C = self.prefill_chunk
        tp_comm = self.tp_comm
        cp_comm = getattr(self, "cp_comm", None)
        from functools import partial

        from megatron_tpu.models.language_model import lm_forward

        @partial(jax.jit, donate_argnums=self._donate(),
                 **self._jit_sharding_kwargs(
                     ("rep", "rep", "rep", "kv", "rep")))
        def chunk_step(params, caches, table_row, tokens_ext, off,
                       write_start, write_end, sample_pos, key, temp,
                       top_k, top_p):
            """One prefill chunk of one prompt.

            tokens_ext [1, C+1]: the chunk's tokens at absolute positions
            off..off+C-1 plus the NEXT prompt token, so the chunk scores
            its last position's teacher-forced logprob without waiting
            for the next chunk. Writes outside [write_start, write_end)
            land on the scratch page (shared-prefix overlap + padded
            tail). Every call also samples from the logits at absolute
            position sample_pos (= prompt_len - 1); the host uses that
            token and the advanced key only on the final chunk, so
            non-final chunks never consume the request's PRNG chain."""
            logits, caches = lm_forward(cfg, params, tokens_ext[:, :C],
                                        kv_caches=caches, cache_index=off,
                                        page_table=table_row,
                                        page_write_start=write_start,
                                        page_write_end=write_end,
                                        tp_comm=tp_comm,
                                        cp_comm=cp_comm)
            if wlp:
                lsm = jax.nn.log_softmax(logits[0].astype(jnp.float32),
                                         axis=-1)
                plp = jnp.take_along_axis(
                    lsm, tokens_ext[0, 1:, None], axis=-1)[:, 0]
            else:
                plp = jnp.zeros((C,), jnp.float32)
            # non-final chunks pass a sample_pos outside this chunk; the
            # clamp keeps the (discarded) gather in bounds
            idx = jnp.clip(sample_pos - off, 0, C - 1)
            last = jnp.take_along_axis(
                logits, jnp.full((1, 1, 1), idx), axis=1)[:, 0]
            key, sub = jax.random.split(key)
            tok = sample_logits_batched(last, sub[None], temp[None],
                                        top_k[None], top_p[None], vocab)[0]
            if wlp:
                lp = jnp.take_along_axis(
                    jax.nn.log_softmax(last.astype(jnp.float32), axis=-1),
                    tok[None, None], axis=-1)[0, 0]
            else:
                lp = jnp.zeros((), jnp.float32)
            return tok, lp, plp, caches, key

        return chunk_step

    def _build_draft_chunk_step(self):
        """One prefill chunk of one prompt into the DRAFT page pools
        (speculative model drafter): same table row and scratch-page
        write fences as the target chunk, so shared-prefix aliasing and
        padded-tail parking behave identically for both trees. Write-
        only — the draft never scores prompt tokens."""
        dcfg = self.spec.draft_cfg
        from functools import partial

        from megatron_tpu.models.language_model import lm_forward

        @partial(jax.jit, donate_argnums=self._donate())
        def draft_chunk(dparams, dcaches, table_row, tokens_c, off,
                        write_start, write_end):
            _, dcaches = lm_forward(dcfg, dparams, tokens_c,
                                    kv_caches=dcaches, cache_index=off,
                                    page_table=table_row,
                                    page_write_start=write_start,
                                    page_write_end=write_end)
            return dcaches

        return draft_chunk

    # ----- page accounting -------------------------------------------------

    def _alloc_pages(self, n: int,
                     logical_start: int = 0) -> Optional[List[int]]:
        """n fresh pages, evicting LRU cache-only prefix pages if the
        free list can't cover it. None = still dry (caller defers or
        preempts). logical_start is the logical page index the run
        starts at within its row — ignored here, but the CP engine's
        striped pool draws each page from the rank owning that logical
        slot (inference/context_parallel/pool.py)."""
        pages = self.pool.alloc(n)
        if pages is None:
            self.prefix_cache.evict(n - self.pool.free_pages)
            pages = self.pool.alloc(n)
        if pages is not None:
            self._m_pages_free.set(self.pool.free_pages)
        return pages

    def _release_slot_pages(self, i: int) -> None:
        row = self._pending_rows.pop(i, self.tables[i])
        live = [int(p) for p in row if p != SCRATCH_PAGE]
        if live:
            self.pool.release(live)
        self.tables[i] = SCRATCH_PAGE
        self._table_dirty = True
        self._m_pages_free.set(self.pool.free_pages)

    def _clear_slot(self, i: int):
        self._release_slot_pages(i)
        self.prefill_queue.drop_slot(i)
        self._window_cursor[i] = 0
        super()._clear_slot(i)

    # ----- admission -------------------------------------------------------

    def _admit(self) -> int:
        n = 0
        for i in range(self.num_slots):
            if self.slots[i] is not None:
                continue
            with self._cv:
                req = self._queue.popleft() if self._queue else None
                if req is not None:
                    # visible to wait_idle(): popped but not yet in a slot
                    self._admitting += 1
            if req is None:
                break
            try:
                if not self._try_assign(i, req):
                    # pool can't cover the prompt right now: keep arrival
                    # order (front of the queue) and stop admitting —
                    # active slots retiring will free pages
                    with self._cv:
                        self._queue.appendleft(req)
                        self._m_queue.set(len(self._queue))
                    break
                n += 1
                with self._cv:
                    self._m_queue.set(len(self._queue))
            finally:
                with self._cv:
                    self._admitting -= 1
                self.last_progress_time = time.monotonic()
        return n

    def _try_assign(self, i: int, req: Request) -> bool:
        """Give req slot i: alias cached prefix pages, allocate the rest
        of the prompt span, queue the chunked prefill. False = defer
        (req untouched); a request no idle engine could EVER fit is
        failed loudly instead (returns True: req was consumed)."""
        resumed = req.resume_key is not None or bool(req.generated)
        toks = (np.concatenate([np.asarray(req.prompt, np.int32),
                                np.asarray(req.generated, np.int32)])
                if resumed else np.asarray(req.prompt, np.int32))
        p_ext = len(toks)
        ps = self.page_size
        hit_pages, hit_lps = self.prefix_cache.lookup(toks)
        span = len(hit_pages) * ps
        n_prompt_pages = -(-p_ext // ps)
        # retain the hits BEFORE allocating: _alloc_pages may evict
        # cache-only pages, and un-pinned hit pages are exactly that —
        # an eviction here would free a hit page and hand it back as
        # "fresh", mapping one physical page at two logical blocks
        self.pool.retain(hit_pages)
        fresh = self._alloc_pages(n_prompt_pages - len(hit_pages),
                                  logical_start=len(hit_pages))
        if fresh is None:
            self.pool.release(hit_pages)
            if self.num_active == 0:
                req._finish(
                    f"prompt needs {n_prompt_pages} pages but the pool has "
                    f"{self.pool.free_pages} free with no active slots to "
                    f"wait for (num_pages={self.num_pages})")
                self.stats["rejected"] += 1
                self._m_rejected.inc()
                return True
            return False
        self._m_pages_free.set(self.pool.free_pages)

        row = np.zeros(self.max_pages, np.int32)
        row[:len(hit_pages)] = hit_pages
        row[len(hit_pages):n_prompt_pages] = fresh
        self._pending_rows[i] = row
        self.slots[i] = req
        self._admit_counter += 1
        self._admit_seq[i] = self._admit_counter

        # recompute starts one position INSIDE the shared span so the
        # boundary token's teacher-forced logprob comes from real logits;
        # its K/V write is fenced onto scratch (write_start = span)
        start = max(span - 1, 0)
        task = PrefillTask(
            slot=i, tokens=toks, start=start, off=start,
            write_start=span,
            key=(np.asarray(req.resume_key) if req.resume_key is not None
                 else np.asarray(jax.random.PRNGKey(req.seed))),
            resumed=resumed, t_start=time.monotonic())
        if not resumed and span > 0:
            # cached teacher-forced logprobs for tokens 1..span-1; the
            # recomputed chunks continue seamlessly from token `span`
            task.plp_parts.extend(hit_lps)
        self.prefill_queue.add(task)

        if span > 0:
            self.stats["prefix_hits"] += 1
            self.stats["prefix_tokens_saved"] += start
            self._m_prefix_hits.inc()
            self._m_prefix_saved.inc(start)
        else:
            self.stats["prefix_misses"] += 1
            self._m_prefix_misses.inc()
        self.stats["admitted"] += 1
        self._m_admitted.inc()
        self._m_active.set(self.num_active)
        return True

    # ----- chunked prefill -------------------------------------------------

    def _prefill_tick(self) -> int:
        """Run at most ONE chunk of the oldest incomplete prefill.
        Returns 1 when a chunk ran (progress signal for run_until_idle)."""
        task = self.prefill_queue.peek()
        if task is None:
            return 0
        i = task.slot
        req = self.slots[i]
        C = self.prefill_chunk
        off = task.off
        toks_ext = np.zeros((1, C + 1), np.int32)
        avail = task.tokens[off:off + C + 1]
        toks_ext[0, :len(avail)] = avail
        row = self._pending_rows[i]
        t0 = time.monotonic()
        try:
            tok, lp, plp, caches, key = self._chunk_step(
                self.params, self.caches, self._chunk_table_arg(row),
                jnp.asarray(toks_ext), jnp.int32(off),
                jnp.int32(task.write_start), jnp.int32(task.total),
                jnp.int32(task.total - 1), jnp.asarray(task.key),
                jnp.float32(req.temperature), jnp.int32(req.top_k),
                jnp.float32(req.top_p))
            self.caches = caches
            if self._has_draft_model():
                # mirror the chunk into the draft pools through the same
                # table row and write fences
                self.draft_caches = self._draft_chunk_step(
                    self.draft_params, self.draft_caches,
                    self._chunk_table_arg(row),
                    jnp.asarray(toks_ext[:, :C]), jnp.int32(off),
                    jnp.int32(task.write_start), jnp.int32(task.total))
        except Exception as e:  # noqa: BLE001 - a failing chunk must fail
            # THIS request, not strand it un-signalled and kill the loop
            # (same contract as the slot engine's prefill failure)
            self._clear_slot(i)
            req._finish(f"prefill failed: {e}")
            self.stats["rejected"] += 1
            self._m_rejected.inc()
            if self._donate():
                # the failed call may have consumed the donated pools
                # (target AND draft trees)
                for j, other in enumerate(self.slots):
                    if other is not None:
                        self._clear_slot(j)
                        other._finish(f"prefill failed: {e}")
                self._rebuild_caches()
            self._m_active.set(self.num_active)
            return 1
        n = min(C, task.total - off)
        if self.want_logprobs:
            task.plp_parts.append(np.asarray(plp))
        self.stats["prefill_chunks"] += 1
        self.stats["prefill_tokens"] += n
        self._count_comm(self._comm_chunk_bytes)
        self._m_chunks.inc()
        self._m_chunk.observe(time.monotonic() - t0)
        if self.flight_recorder is not None:
            self.flight_recorder.heartbeat(
                f"prefill chunk slot {i} ({off}+{n}/{task.total})")
        if self.prefill_queue.advance(task, n):
            self._finish_prefill(i, task, tok, lp, key)
        return 1

    def _finish_prefill(self, i: int, task: PrefillTask, tok, lp, key):
        """The prompt is fully in the cache: publish the slot's table row
        to the shared decode table, arm the decode mirrors, record the
        first sampled token, and register the prompt's full pages in the
        radix tree."""
        self._sync_carry()
        req = self.slots[i]
        row = self._pending_rows.pop(i)
        self.tables[i] = row
        self._table_dirty = True
        p_ext = task.total
        self.lengths[i] = p_ext
        self.last_tok[i] = int(tok)
        self.temps[i] = req.temperature
        self.top_ks[i] = req.top_k
        self.top_ps[i] = req.top_p
        self.keys[i] = np.asarray(key)
        if self.spec is not None:
            self.spec_on[i] = bool(req.spec)
            self._spec_rows_dev = None
        req.generated.append(int(tok))
        req.logprobs.append(float(lp))
        if not task.resumed and self.want_logprobs:
            req.prompt_logprobs = [
                float(x) for x in np.concatenate(task.plp_parts)[:p_ext - 1]
            ] if task.plp_parts else []
        p0 = len(req.prompt)
        if p0 >= self.page_size:
            # only FULL pages of the ORIGINAL prompt enter the tree (the
            # partially-filled tail page stays private — decode writes
            # into it); resumes re-register recomputed pages, and insert
            # skips paths already cached
            self.prefix_cache.insert(req.prompt,
                                     [int(p) for p in
                                      row[:p0 // self.page_size]],
                                     req.prompt_logprobs)
        now = time.monotonic()
        self._m_prefill.observe(now - task.t_start)
        if not task.resumed:
            req.first_token_time = now
            if req.submit_time is not None:
                self._m_ttft.observe(now - req.submit_time)
        self._m_tokens.inc()
        if self._req_finished(req):
            self._retire(i)

    # ----- preemption ------------------------------------------------------

    def _preempt_one(self) -> bool:
        """Preempt the youngest active slot (LIFO — later arrivals yield
        pages to earlier ones). Its request re-enters the queue FRONT and
        resumes by exact teacher-forced recompute."""
        cands = [i for i in range(self.num_slots) if self.slots[i] is not None]
        if not cands:
            return False
        i = max(cands, key=lambda j: self._admit_seq[j])
        self._sync_carry()
        req = self.slots[i]
        if i not in self.prefill_queue.slots:
            # mid-decode: preserve the PRNG chain so the resumed request
            # samples exactly the tokens it would have sampled
            req.resume_key = self.keys[i].copy()
        self._clear_slot(i)
        with self._cv:
            self._queue.appendleft(req)
            self._m_queue.set(len(self._queue))
        self.stats["preemptions"] += 1
        self._m_preempted.inc()
        self._m_active.set(self.num_active)
        return True

    def _ensure_decode_pages(self) -> None:
        """Before a decode tick, every decodable slot needs real pages
        under its write span (lengths[i] .. lengths[i] + span - 1; span
        is 1 plain, k+1 speculative — rejected drafts roll back the
        length but the pages stay mapped for future growth, and shared
        prefix pages are never in the span). Allocate across page
        boundaries, preempting the youngest slot when the pool is dry.
        Each preemption frees that slot's pages, so this terminates."""
        span = self._decode_write_span()
        ps = self.page_size
        while True:
            rows = self._decode_rows()
            dry = False
            for i in rows:
                first = int(self.lengths[i]) // ps
                last_pg = (int(self.lengths[i]) + span - 1) // ps
                for pg in range(first, last_pg + 1):
                    if self.tables[i, pg] != SCRATCH_PAGE:
                        continue
                    pages = self._alloc_pages(1, logical_start=pg)
                    if pages is None:
                        if not self._preempt_one():
                            # unreachable: slot i itself is preemptible
                            return
                        dry = True
                        break  # re-derive rows (the victim may be gone)
                    self.tables[i, pg] = pages[0]
                    self._table_dirty = True
                if dry:
                    break
            if not dry:
                return

    # ----- stepping --------------------------------------------------------

    def _decode_rows(self):
        busy = self.prefill_queue.slots
        return [i for i, s in enumerate(self.slots)
                if s is not None and i not in busy]

    def _decode_extra_args(self):
        if self._table_dirty or self._device_table is None:
            self._device_table = self._commit_small(jnp.asarray(self.tables))
            self._table_dirty = False
        return (self._device_table,)

    def _chunk_table_arg(self, row):
        """Device form of one pending table row for the chunk step
        ([1, max_pages] here; the CP engine rebuilds it as per-rank
        local tables sharded over the context axis)."""
        return jnp.asarray(row[None, :])

    def _release_window_pages(self) -> None:
        """Sliding-window page release (Mistral; ROADMAP item 1): pages
        every position of which sits fully behind a slot's attention
        window can never be attended again — the decode mask only allows
        k_pos >= length + 1 - window and lengths never shrink below the
        committed value (speculative rollback rolls back only
        UNcommitted draft positions) — so the slot's reference goes back
        to the pool and the table entry parks on scratch (reads of it
        are exactly masked; scratch contents are finite activations, so
        the masked scores stay well-defined). Pages the radix prefix
        cache also holds keep their cache reference: a later request
        sharing the prompt still hits them."""
        window = self.cfg.sliding_window_size
        if window is None:
            return
        ps = self.page_size
        freed = 0
        for i in self._decode_rows():
            limit = int(self.lengths[i]) - int(window)
            if limit < ps:
                continue
            # O(1) amortized: at most one page per slot newly crosses
            # the window per tick, and the cursor never rewinds (a
            # cleared/preempted slot resets it in _clear_slot)
            for pg in range(self._window_cursor[i], limit // ps):
                if self.tables[i, pg] != SCRATCH_PAGE:
                    self.pool.release([int(self.tables[i, pg])])
                    self.tables[i, pg] = SCRATCH_PAGE
                    self._table_dirty = True
                    freed += 1
            self._window_cursor[i] = max(self._window_cursor[i],
                                         limit // ps)
        if freed:
            self.stats["window_pages_released"] += freed
            self._m_window_released.inc(freed)
            self._m_pages_free.set(self.pool.free_pages)

    def step(self) -> int:
        """One engine tick: admit, run one prefill chunk, then one
        batched decode for every slot whose prompt is fully cached.
        Returns slots served + chunks run (0 = idle)."""
        self._pre_tick()  # faults, staged weight swaps, deadline expiry
        self._admit()
        chunked = self._prefill_tick()
        if chunked:
            # chunked prefill with no decodable slots is still progress —
            # without this a long multi-chunk prompt would trip the
            # stalled() readiness check while prefilling normally
            self.last_progress_time = time.monotonic()
        self._release_window_pages()
        self._ensure_decode_pages()
        return self._decode_tick() + chunked

    def _retire(self, i: int):
        # base _retire -> _clear_slot releases this slot's page refs;
        # pages also held by the radix tree stay cached for future hits
        super()._retire(i)
        self._m_pages_free.set(self.pool.free_pages)

    # ----- state migration (fleet/migration.py) ----------------------------

    def _export_slot_kv(self, i: int):
        """Gather slot i's pages into the canonical [L, T, H, D] wire
        layout. None when any page of the span is gone (sliding-window
        release parked it on scratch) — there is no exact KV to ship, so
        the importer recompute-resumes from the migrated tokens (exact
        under the deterministic position-based window mask)."""
        length = int(self.lengths[i])
        ps = self.page_size
        if length <= 0:
            return None
        n_pages = -(-length // ps)
        row = self._pending_rows.get(i, self.tables[i])
        pages = [int(p) for p in row[:n_pages]]
        if any(p == SCRATCH_PAGE for p in pages):
            return None
        host = []
        for leaf in jax.device_get(self.caches):
            g = np.asarray(leaf)[:, pages]          # [L, n, ps, H, D]
            host.append(g.reshape(g.shape[0], n_pages * ps,
                                  *g.shape[3:])[:, :length])
        return self._pack_kv_sections(host, length)

    def _page_blocks(self, leaves: List[np.ndarray], j: int):
        """Page j's [L, page_size, ...] block of each canonical leaf
        (zero-padded past the committed length)."""
        ps = self.page_size
        blocks = []
        for leaf in leaves:
            block = np.zeros((leaf.shape[0], ps) + leaf.shape[2:],
                             leaf.dtype)
            end = min(leaf.shape[1] - j * ps, ps)
            block[:, :end] = leaf[:, j * ps:j * ps + end]
            blocks.append(jnp.asarray(block))
        return tuple(blocks)

    def _install_request_kv(self, req: Request, kv: dict,
                            sections) -> bool:
        """Paged install: allocate the span's pages, write each through
        the once-jitted page writer, publish the table row, and re-enter
        the prompt's full pages into the radix tree — the migrated
        request's prefix lineage survives the hop, so followers sharing
        its prompt hit on THIS replica too."""
        i = self._free_slot_for_import()
        if i is None:
            return False
        length = int(kv["length"])
        ps = self.page_size
        n_pages = -(-length // ps)
        pages = self._alloc_pages(n_pages)
        if pages is None:
            return False
        leaves = self._decode_kv_sections(kv, sections)
        writer = self._kv_install_writer()
        self._sync_carry()
        for j, pg in enumerate(pages):
            self.caches = writer(self.caches, self._page_blocks(leaves, j),
                                 jnp.int32(pg))
        row = np.zeros(self.max_pages, np.int32)
        row[:n_pages] = pages
        self.tables[i] = row
        self._table_dirty = True
        self._admit_counter += 1
        self._admit_seq[i] = self._admit_counter
        self._arm_imported_slot(i, req, length)
        p0 = len(req.prompt)
        if p0 >= ps and req.prompt_logprobs:
            # radix-prefix lineage: same full-pages-only rule as
            # _finish_prefill (the tail page is private — decode writes it)
            self.prefix_cache.insert(
                req.prompt, [int(p) for p in row[:p0 // ps]],
                req.prompt_logprobs)
        self._m_pages_free.set(self.pool.free_pages)
        return True

    # ----- fleet prefix directory (cross-replica radix sharing) ------------

    def export_prefix_state(self, tokens):
        """Package the radix-cached whole-page prefix of `tokens` for
        replication to a peer: (meta, sections) in the migration wire
        vocabulary (kind="prefix"), or None when nothing is cached."""
        toks = [int(t) for t in tokens]
        with self.paused():
            pages, lps = self.prefix_cache.lookup(toks)
            if not pages:
                return None
            ps = self.page_size
            span = len(pages) * ps
            host = []
            for leaf in jax.device_get(self.caches):
                g = np.asarray(leaf)[:, [int(p) for p in pages]]
                host.append(g.reshape(g.shape[0], span, *g.shape[3:]))
            kv_meta, sections = self._pack_kv_sections(host, span)
        meta = {"kind": "prefix", "tokens": toks[:span], "kv": kv_meta}
        # per-node logprob slices concatenate back into the engine's
        # (position-1)-indexed prompt_logprobs layout for tokens[1:span]
        sections["prefix_logprobs"] = (
            np.concatenate([np.asarray(x, np.float32) for x in lps])
            if lps else np.zeros(0, np.float32))
        return meta, sections

    def import_prefix_state(self, meta: dict, sections) -> int:
        """Install replicated prefix pages into this pool + radix tree.
        Returns pages added (0 = incompatible, lossy, or already
        cached). Only EXACT codecs enter the tree — a lossy prefix would
        silently poison every future request that hits it."""
        kv = meta.get("kv") or {}
        ok, _ = self._kv_import_compatible(kv)
        if not ok or not kv.get("exact"):
            return 0
        toks = [int(t) for t in meta.get("tokens", [])]
        span = int(kv.get("length", 0))
        ps = self.page_size
        if span <= 0 or span % ps != 0 or span > len(toks):
            return 0
        n_pages = span // ps
        with self.paused():
            have, _ = self.prefix_cache.lookup(toks)
            if len(have) >= n_pages:
                return 0  # the local copy stays authoritative
            pages = self._alloc_pages(n_pages)
            if pages is None:
                return 0
            leaves = self._decode_kv_sections(kv, sections)
            writer = self._kv_install_writer()
            for j, pg in enumerate(pages):
                self.caches = writer(self.caches,
                                     self._page_blocks(leaves, j),
                                     jnp.int32(pg))
            lp = np.asarray(sections.get("prefix_logprobs",
                                         np.zeros(0)), np.float32)
            added = self.prefix_cache.insert(toks[:span], pages, lp)
            # insert() retained the refs the tree owns; drop the
            # allocation refs so the pages become cache-only (evictable
            # under pressure), and so pages skipped as already-cached
            # free immediately
            self.pool.release(pages)
            self._m_pages_free.set(self.pool.free_pages)
        return added

"""Paged KV-cache serving: block pool + radix prefix cache + chunked
prefill.

The slot engine (inference/engine.py) reserves `max_seq_len` cache rows
per slot up front — a young sequence in a long cache wastes almost all
of them, and two requests sharing a system prompt each recompute and
store it. This package replaces the per-slot reservation with a shared
pool of fixed-size pages:

  * :mod:`pool` — the free-list page allocator with refcounts. KV
    storage becomes ``[layers, num_pages, page_size, kv_heads, head_dim]``
    and each slot holds an int32 page table mapping logical blocks to
    physical pages.
  * :mod:`radix` — a radix tree over token IDs at page granularity:
    requests sharing a prompt prefix map their tables onto the same
    refcounted pages and skip prefill for the shared span (copy-on-write
    when a partially-shared page is about to be written).
  * :mod:`scheduler` — the chunked-prefill queue: long prompts enter the
    cache `prefill_chunk` tokens per engine tick, interleaved with the
    batched decode, so one long prompt can never stall the batch.
  * :mod:`engine` — :class:`PagedInferenceEngine`, the drop-in paged
    mode of the serving engine (``--serve_kv_paging``). Token-identical
    to the slot engine on the serving test matrix
    (tests/test_serving_engine.py), zero decode recompiles after warmup.

The decode attention path reads through the table: the paged
flash-decode kernel (ops/pallas/paged_flash_decode.py) resolves pages
inside the Pallas grid on TPU; everywhere else ops/attention.py gathers
the pages into a dense view and the masked einsum computes identical
values.
"""

from megatron_tpu.inference.paging.engine import PagedInferenceEngine
from megatron_tpu.inference.paging.pool import PagePool
from megatron_tpu.inference.paging.radix import RadixPrefixCache
from megatron_tpu.inference.paging.scheduler import ChunkedPrefillQueue

__all__ = [
    "PagedInferenceEngine",
    "PagePool",
    "RadixPrefixCache",
    "ChunkedPrefillQueue",
]

"""Free-list page allocator with refcounts.

Host-side bookkeeping only — the physical pages live in the device
pools (``[layers, num_pages, page_size, kv_heads, head_dim]``); this
class decides which page index a logical block maps to. Page 0 is
reserved as the SCRATCH page: unallocated page-table entries point at
it, so out-of-range writes (padded prefill-chunk tails, decode writes
of idle slots) land somewhere harmless and reads of it are always
masked off by the valid-prefix length.

Refcounts make sharing safe: a slot's table and the radix prefix cache
each hold one reference per page they map; a page returns to the free
list only when the last holder releases it.
"""

from __future__ import annotations

from typing import Iterable, List, Optional

SCRATCH_PAGE = 0


class PagePool:
    """Fixed-capacity page allocator (page ids ``1..num_pages-1``)."""

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError(
                f"num_pages must be >= 2 (page 0 is the reserved scratch "
                f"page), got {num_pages}")
        self.num_pages = num_pages
        # LIFO free list: recently-released pages are re-used first (their
        # contents are dead by construction — refcount reached zero)
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        self._refs = [0] * num_pages

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return (self.num_pages - 1) - len(self._free)

    def refcount(self, page: int) -> int:
        return self._refs[page]

    def alloc(self, n: int = 1) -> Optional[List[int]]:
        """n fresh pages with refcount 1 each, or None when the pool
        can't cover the request (caller evicts/preempts and retries) —
        all-or-nothing, so a partial grab never leaks."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._refs[p] = 1
        return pages

    def retain(self, pages: Iterable[int]) -> None:
        """Add one reference per page (a new table row / radix node maps
        an already-live page)."""
        for p in pages:
            if p == SCRATCH_PAGE:
                continue
            if self._refs[p] <= 0:
                raise ValueError(f"retain of free page {p}")
            self._refs[p] += 1

    def release(self, pages: Iterable[int]) -> int:
        """Drop one reference per page; pages reaching zero return to
        the free list. Returns how many were actually freed."""
        freed = 0
        for p in pages:
            if p == SCRATCH_PAGE:
                continue
            if self._refs[p] <= 0:
                raise ValueError(f"release of free page {p}")
            self._refs[p] -= 1
            if self._refs[p] == 0:
                self._free.append(p)
                freed += 1
        return freed

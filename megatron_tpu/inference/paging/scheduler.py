"""Chunked-prefill queue: long prompts enter the cache chunk by chunk.

The slot engine prefills a whole prompt in one bucketed pass — a 2k-token
prompt stalls every active decode for the full prefill. The paged engine
instead admits the request immediately (slot + pages assigned) and
queues its prefill here; every engine tick runs AT MOST ONE chunk of
`chunk` tokens before the batched decode, so prefill work interleaves
with decode ticks and one long prompt can never stall the batch.

FIFO across requests: the oldest incomplete prefill finishes first
(chunks of one prompt are sequential anyway — chunk c+1 attends chunk
c's cache rows), which bounds time-to-first-token for the request at
the head of the line instead of spreading starvation evenly.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import List, Optional

import numpy as np


@dataclasses.dataclass
class PrefillTask:
    """One request's remaining prefill work."""

    slot: int
    tokens: np.ndarray        # [p] int32 — the full logical prompt
    start: int                # first position to compute (prefix-cache skip)
    off: int                  # next chunk offset (start <= off <= p)
    # teacher-forced logprob pieces accumulated chunk by chunk
    # (host-side; assembled into Request.prompt_logprobs at completion)
    plp_parts: List[np.ndarray] = dataclasses.field(default_factory=list)
    # first position whose K/V write lands in a real page — positions
    # below it sit in prefix-cache-shared pages, so the overlap query's
    # write is fenced onto the scratch page (copy-on-write)
    write_start: int = 0
    # PRNG chain the final chunk samples with: PRNGKey(seed) for a fresh
    # request, the preserved decode chain for a preemption resume
    key: Optional[np.ndarray] = None
    # resume of a preempted request: `tokens` is prompt + generated, the
    # recompute is teacher-forced, and prompt_logprobs/radix bookkeeping
    # for the original prompt already happened on the first admission
    resumed: bool = False
    # admission timestamp (monotonic) for the prefill-latency histogram
    t_start: float = 0.0

    @property
    def total(self) -> int:
        return int(len(self.tokens))

    @property
    def done(self) -> bool:
        return self.off >= self.total


class ChunkedPrefillQueue:
    def __init__(self, chunk: int):
        if chunk < 1:
            raise ValueError(f"prefill chunk must be >= 1, got {chunk}")
        self.chunk = int(chunk)
        self._tasks: deque[PrefillTask] = deque()

    def __len__(self) -> int:
        return len(self._tasks)

    @property
    def slots(self) -> set:
        """Slots currently mid-prefill (excluded from decode ticks)."""
        return {t.slot for t in self._tasks}

    def add(self, task: PrefillTask) -> None:
        if task.start >= task.total:
            raise ValueError(
                f"prefill task has nothing to compute (start {task.start} "
                f">= {task.total}); the prefix cache must leave at least "
                "the final prompt token to recompute")
        task.off = task.start
        self._tasks.append(task)

    def peek(self) -> Optional[PrefillTask]:
        """The task owed the next chunk (None when idle)."""
        return self._tasks[0] if self._tasks else None

    def advance(self, task: PrefillTask, n: int) -> bool:
        """Consume n computed tokens; True when the task completed (and
        was removed)."""
        task.off += n
        if task.done:
            self._tasks.remove(task)
            return True
        return False

    def drop_slot(self, slot: int) -> Optional[PrefillTask]:
        """Remove the task for a preempted/failed slot (None if that
        slot wasn't mid-prefill)."""
        for t in self._tasks:
            if t.slot == slot:
                self._tasks.remove(t)
                return t
        return None

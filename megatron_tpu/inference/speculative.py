"""Speculative decoding inside the engine's one jitted decode step.

Classic speculative sampling (Leviathan et al., arXiv 2211.17192): a
cheap drafter proposes ``k`` tokens per slot, ONE batched multi-token
target forward scores all ``k+1`` query positions, and an in-step exact
accept/reject keeps or replaces each draft so the emitted tokens follow
the target model's distribution exactly. A tick that accepts ``a``
drafts emits ``a+1`` tokens for one target forward — the throughput win
— and a tick that rejects everything still emits 1 token (never slower
in tokens per forward than plain decode).

Two pluggable drafters:

  * ``"ngram"`` — zero-weight prompt-lookup (PLD / arXiv 2304.04487
    family): propose the ``k`` tokens that followed the most recent
    earlier occurrence of the sequence's trailing n-gram. Proposal runs
    on the host (numpy over the request's own token history) and rides
    into the jitted step as a traced ``[N, k]`` array; great on
    repetitive / copy-heavy traffic, free everywhere else.
  * ``"model"`` — a small draft model sharing the engine's slot/page KV
    machinery through a SECOND cache tree: the draft proposes greedily
    via a ``lax.scan`` of k single-token forwards inside the same
    jitted step (plus one extra write-only forward so the draft cache
    covers the all-accepted case), then the target verifies. Admission
    prefill and the paged engine's chunked prefill write the draft
    cache through the same page tables and write fences as the target
    cache, so prefix-cache aliasing and preempt-resume recompute work
    identically for both trees.

Exactness contract (pinned by tests/test_speculative.py):

  * greedy (temperature 0): a draft is accepted iff it equals the
    target argmax given the accepted prefix, and the emitted token at
    every position IS that argmax — token-identical to non-speculative
    decode, bit for bit, for any drafter and any acceptance rate.
  * sampled (temperature > 0): both drafters propose deterministically
    (point-mass q), so standard speculative sampling reduces to: accept
    draft d with probability p(d) under the (temperature / top-k /
    top-p filtered) target distribution, else sample from the residual
    p with d removed and renormalized — the emitted token is an exact
    sample from p either way. Randomness is keyed by the request's PRNG
    chain AND the absolute token position (``fold_in(chain, position)``,
    not a per-tick split), so sampled output is chain-DETERMINISTIC:
    identical runs (same seed, same tick schedule) agree exactly. It is
    NOT schedule-independent — which drafts exist at a position depends
    on the tick alignment, and a preemption resume re-draws its
    boundary token through the prefill sampler's split-based chain — so
    only greedy output is invariant under preemption/scheduling
    (docs/serving.md pins this asymmetry).

Rollback: rejected drafts' K/V entries (written at positions past the
accepted length by the same multi-token forward) are invalidated purely
by the per-slot length roll-back — attention masks every row to its own
valid prefix, and the next tick overwrites those positions. The paged
engine's page table is untouched: speculative writes only ever land in
the slot's private tail pages (shared prefix pages hold only FULL pages
of the original prompt, strictly below the decode positions), so no
page is freed or re-mapped on rejection.

``k`` is static in the compiled step (drafts ride as a padded ``[N, k]``
dimension), so the engine still compiles exactly once at warmup — the
live ``decode_recompiles`` counter stays 0.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from megatron_tpu.config import ModelConfig

#: drafter registry — "ngram" is host-side prompt lookup, "model" a
#: draft network sharing the engine's cache machinery
DRAFTERS = ("ngram", "model")


@dataclasses.dataclass
class SpecConfig:
    """Engine-level speculative decoding configuration.

    k: drafted tokens per slot per tick (the verify forward takes
       [N, k+1] query rows). Emitted tokens per tick per slot range
       from 1 (all rejected) to k+1 (all accepted).
    drafter: "ngram" (zero-weight prompt lookup) or "model" (a small
       draft model with its own cache tree).
    ngram: trailing n-gram length the lookup drafter matches (it falls
       back to shorter suffixes down to 1 before giving up).
    draft_cfg/draft_params: the draft model ("model" drafter only).
       Must share the target's vocab; everything else (layers, heads,
       head_dim) is free — the draft keeps its own cache tree.
    """

    k: int = 4
    drafter: str = "ngram"
    ngram: int = 2
    draft_cfg: Optional[ModelConfig] = None
    draft_params: Any = None


def validate_spec(cfg: ModelConfig, spec: SpecConfig) -> None:
    if spec.k < 1:
        raise ValueError(f"spec k must be >= 1, got {spec.k}")
    if spec.drafter not in DRAFTERS:
        raise ValueError(
            f"unknown drafter {spec.drafter!r} (choose from {DRAFTERS})")
    if spec.drafter == "ngram" and spec.ngram < 1:
        raise ValueError(f"ngram must be >= 1, got {spec.ngram}")
    if spec.drafter == "model":
        if spec.draft_cfg is None or spec.draft_params is None:
            raise ValueError(
                "drafter='model' needs draft_cfg and draft_params "
                "(use drafter='ngram' for the zero-weight drafter)")
        if spec.draft_cfg.vocab_size != cfg.vocab_size:
            raise ValueError(
                f"draft vocab {spec.draft_cfg.vocab_size} != target vocab "
                f"{cfg.vocab_size} — verify compares token ids directly")


# ---------------------------------------------------------------------------
# n-gram / prompt-lookup drafter (host side)
# ---------------------------------------------------------------------------


def ngram_propose(history: np.ndarray, k: int, n: int) -> np.ndarray:
    """Propose k continuation tokens by prompt lookup: find the most
    recent EARLIER occurrence of the trailing n-gram of ``history`` and
    return the k tokens that followed it (falling back to shorter
    suffixes down to 1). When nothing matches, repeat the last token —
    a cheap guess the verifier will usually reject at cost 0 (the tick
    still emits its guaranteed token).

    Host-side vectorized numpy over one request's own token history
    (the per-tick proposal sits on the decode hot path serialized
    before the device step, so no Python-level window loop); the result
    rides into the jitted step as data, so the compiled step never
    changes shape."""
    history = np.asarray(history, np.int32)
    ln = len(history)
    out = np.full(k, history[-1] if ln else 0, np.int32)
    for nn in range(min(n, ln - 1), 0, -1):
        suffix = history[ln - nn:]
        # all windows history[i:i+nn] for i < ln-nn at once: match[i]
        # is True when the window equals the trailing n-gram
        windows = np.lib.stride_tricks.sliding_window_view(
            history[:ln - 1], nn)                     # [ln-nn, nn]
        match = (windows == suffix).all(axis=1)
        if not match.any():
            continue
        i = int(len(match) - 1 - np.argmax(match[::-1]))  # newest match
        cont = history[i + nn:i + nn + k]
        out[:len(cont)] = cont
        if 0 < len(cont) < k:
            out[len(cont):] = cont[-1]
        return out
    return out


# ---------------------------------------------------------------------------
# exact accept/reject (inside the jitted step)
# ---------------------------------------------------------------------------


def speculative_accept(
    logits: jnp.ndarray,      # [N, k+1, V] target logits per query row
    drafts: jnp.ndarray,      # [N, k] proposed tokens
    lengths: jnp.ndarray,     # [N] cache length (absolute position base)
    keys: jnp.ndarray,        # [N, 2] per-slot PRNG chain state
    temps: jnp.ndarray,       # [N] 0 = greedy
    top_ks: jnp.ndarray,      # [N]
    top_ps: jnp.ndarray,      # [N]
    vocab_size: Optional[int] = None,
    spec_rows: Optional[jnp.ndarray] = None,  # [N] bool; False = no spec
    want_logprobs: bool = True,
):
    """The exact accept/reject core. Returns
    ``(toks [N, k+1], lps [N, k+1], accepts [N])``.

    Row semantics (position j is the query fed token j: j=0 the slot's
    last sampled token, j>=1 draft j):

      * greedy rows: toks[:, j] is the target argmax at position j;
        draft j is accepted iff it equals toks[:, j-1] — so the emitted
        prefix toks[:, :accepts+1] is EXACTLY what non-speculative
        greedy decode would produce.
      * sampled rows: draft j is accepted with probability p_j(draft)
        under the filtered/scaled target distribution (point-mass
        proposal acceptance); a rejected position emits a sample from
        the residual (p with the draft removed, renormalized), and the
        bonus position k emits a full sample. Either way the emitted
        token is an exact draw from p_j.
      * rows with spec_rows=False accept nothing and emit ONE token
        sampled from the full distribution — greedy rows stay
        bit-identical to non-speculative decode.

    Randomness is keyed by absolute position: ``fold_in(chain, pos)``
    with pos = lengths + j, never a per-tick split — the chain state in
    ``keys`` is NOT consumed, so acceptance scheduling (and
    preempt/resume) cannot shift later draws.

    The caller emits ``toks[:, :accepts+1]``; positions past the first
    rejection are garbage by construction and must not be read.

    The heavy branches keep the engine's all-greedy fast path: the
    whole sampling machinery (softmax/uniform/categorical over
    [N, k+1, V]) runs under ``lax.cond(any(temps > 0))`` and the
    [N, k+1, V] filter sort under a nested cond on the top-k/top-p
    knobs — an all-greedy tick pays one argmax, exactly like
    sample_logits_batched."""
    raw32 = logits.astype(jnp.float32)
    N, K1, V = raw32.shape
    k = K1 - 1
    neg = jnp.finfo(jnp.float32).min
    clamped = raw32
    if vocab_size is not None and vocab_size < V:
        clamped = jnp.where(jnp.arange(V) < vocab_size, raw32, neg)
    greedy_t = jnp.argmax(clamped, axis=-1).astype(jnp.int32)   # [N, K1]
    greedy_match = drafts == greedy_t[:, :k]                    # [N, k]
    srow = (jnp.ones((N,), bool) if spec_rows is None
            else spec_rows.astype(bool))

    # positional PRNG: one subkey per (slot, absolute position), two
    # tagged draws per subkey (uniform accept test, categorical sample)
    pos = lengths[:, None] + jnp.arange(K1)[None, :]            # [N, K1]

    def _sampled(operand):
        clamped, pos = operand
        t = temps[:, None, None]
        scaled = clamped / jnp.where(t > 0, t, 1.0)

        def _filter(scaled):
            # THE batched sampler's filter (sampling.filter_top_k_top_p
            # — the exactness contract requires the identical filtered
            # distribution), with the k+1 positions flattened into the
            # batch axis and each row's knobs repeated per position
            from megatron_tpu.inference.sampling import filter_top_k_top_p

            flat = filter_top_k_top_p(
                scaled.reshape(N * K1, V),
                jnp.repeat(top_ks, K1), jnp.repeat(top_ps, K1))
            return flat.reshape(N, K1, V)

        fl = jax.lax.cond(jnp.any((top_ks > 0) | (top_ps > 0)),
                          _filter, lambda s: s, scaled)
        subs = jax.vmap(jax.vmap(jax.random.fold_in, (None, 0)),
                        (0, 0))(keys, pos)                      # [N, K1, 2]
        u = jax.vmap(jax.vmap(
            lambda s: jax.random.uniform(jax.random.fold_in(s, 0))
        ))(subs[:, :k])                                         # [N, k]
        p = jax.nn.softmax(fl, axis=-1)
        p_draft = jnp.take_along_axis(
            p[:, :k], drafts[..., None], axis=-1)[..., 0]       # [N, k]
        # spec-off rows must ignore the accept test entirely: emitting
        # the draft on a passed test AND sampling the full distribution
        # on a failed one would overweight the draft token
        accept = (u < p_draft) & srow[:, None]
        # residual = p minus the point-mass proposal, renormalized =
        # categorical over fl with the draft column removed. Spec-off
        # rows never ran the accept test, so they sample the FULL
        # distribution (no column removed).
        mask_d = jax.nn.one_hot(drafts, V, dtype=bool)
        resid = jnp.where(mask_d & srow[:, None, None], neg, fl[:, :k])
        ckeys = jax.vmap(jax.vmap(lambda s: jax.random.fold_in(s, 1))
                         )(subs)                                # [N, K1, 2]
        rej = jax.vmap(jax.vmap(jax.random.categorical)
                       )(ckeys[:, :k], resid).astype(jnp.int32)
        bonus = jax.vmap(jax.random.categorical)(
            ckeys[:, k], fl[:, k]).astype(jnp.int32)
        out = jnp.concatenate(
            [jnp.where(accept, drafts, rej), bonus[:, None]], axis=1)
        return out, accept

    out_s, accept_s = jax.lax.cond(
        jnp.any(temps > 0), _sampled,
        lambda op: (greedy_t, greedy_match), (clamped, pos))
    is_sampled = temps[:, None] > 0
    accept = jnp.where(is_sampled, accept_s, greedy_match) & srow[:, None]
    toks = jnp.where(is_sampled, out_s, greedy_t)
    # accepted prefix length: drafts accepted until the first rejection
    accepts = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1),
                      axis=1).astype(jnp.int32)
    if want_logprobs:
        # same convention as the non-speculative step: fp32 log-softmax
        # of the RAW logits at the emitted token
        lps = jnp.take_along_axis(
            jax.nn.log_softmax(raw32, axis=-1),
            toks[..., None], axis=-1)[..., 0]
    else:
        lps = jnp.zeros(toks.shape, jnp.float32)
    return toks, lps, accepts


# ---------------------------------------------------------------------------
# jitted step builders (slot + paged, ngram + model drafter)
# ---------------------------------------------------------------------------


def build_spec_decode_step(
    cfg: ModelConfig,
    spec: SpecConfig,
    vocab_size: Optional[int],
    want_logprobs: bool,
    donate_argnums: tuple,
    paged: bool,
):
    """One jitted speculative decode step for the engine.

    Signature (positional, matching the engines' splice convention —
    extra args between the cache trees and the carry):

      ngram:  (params, caches, [table], last_tok, lengths, keys, temps,
               top_ks, top_ps, spec_rows, drafts)
      model:  (params, caches, dparams, dcaches, [table], last_tok,
               lengths, keys, temps, top_ks, top_ps, spec_rows)

    Returns (toks [N, k+1], lps, accepts, caches, [dcaches], new_keys,
    new_lengths, new_last_tok). new_keys is the untouched chain state
    (randomness is positional — see speculative_accept) returned so the
    device carry layout matches the non-speculative step's.
    """
    from megatron_tpu.models.language_model import lm_forward

    k = spec.k
    dcfg = spec.draft_cfg
    neg = jnp.finfo(jnp.float32).min

    def _verify(params, caches, table, last, lens, keys, temps, tks, tps,
                spec_rows, drafts):
        kw = {"page_table": table} if paged else {}
        toks_in = jnp.concatenate([last[:, None], drafts], axis=1)
        logits, caches = lm_forward(cfg, params, toks_in, kv_caches=caches,
                                    cache_index=lens, **kw)
        toks, lps, accepts = speculative_accept(
            logits, drafts, lens, keys, temps, tks, tps,
            vocab_size=vocab_size, spec_rows=spec_rows,
            want_logprobs=want_logprobs)
        last_new = jnp.take_along_axis(toks, accepts[:, None], axis=1)[:, 0]
        return toks, lps, accepts, caches, keys, lens + accepts + 1, last_new

    if spec.drafter == "ngram":
        if paged:
            @partial(jax.jit, donate_argnums=donate_argnums)
            def spec_step(params, caches, table, last, lens, keys, temps,
                          tks, tps, spec_rows, drafts):
                return _verify(params, caches, table, last, lens, keys,
                               temps, tks, tps, spec_rows, drafts)
        else:
            @partial(jax.jit, donate_argnums=donate_argnums)
            def spec_step(params, caches, last, lens, keys, temps, tks,
                          tps, spec_rows, drafts):
                return _verify(params, caches, None, last, lens, keys,
                               temps, tks, tps, spec_rows, drafts)
        return spec_step

    V = cfg.vocab_size

    def _propose_and_verify(params, caches, dparams, dcaches, table, last,
                            lens, keys, temps, tks, tps, spec_rows):
        kw = {"page_table": table} if paged else {}

        def body(carry, _):
            dc, tok, ln = carry
            lg, dc = lm_forward(dcfg, dparams, tok[:, None], kv_caches=dc,
                                cache_index=ln, **kw)
            lg = lg[:, 0].astype(jnp.float32)
            if vocab_size is not None and vocab_size < V:
                lg = jnp.where(jnp.arange(V) < vocab_size, lg, neg)
            nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
            return (dc, nxt, ln + 1), nxt

        (dcaches, d_last, d_len), drafts = jax.lax.scan(
            body, (dcaches, last, lens), None, length=k)
        drafts = jnp.transpose(drafts)                   # [k, N] -> [N, k]
        # one extra write-only draft forward: position lengths+k holds
        # draft k's K/V so a fully-accepted tick leaves the draft cache
        # complete for the next tick's proposal
        _, dcaches = lm_forward(dcfg, dparams, d_last[:, None],
                                kv_caches=dcaches, cache_index=d_len, **kw)
        toks, lps, accepts, caches, keys, lens_new, last_new = _verify(
            params, caches, table, last, lens, keys, temps, tks, tps,
            spec_rows, drafts)
        return toks, lps, accepts, caches, dcaches, keys, lens_new, last_new

    if paged:
        @partial(jax.jit, donate_argnums=donate_argnums)
        def spec_step(params, caches, dparams, dcaches, table, last, lens,
                      keys, temps, tks, tps, spec_rows):
            return _propose_and_verify(params, caches, dparams, dcaches,
                                       table, last, lens, keys, temps,
                                       tks, tps, spec_rows)
    else:
        @partial(jax.jit, donate_argnums=donate_argnums)
        def spec_step(params, caches, dparams, dcaches, last, lens, keys,
                      temps, tks, tps, spec_rows):
            return _propose_and_verify(params, caches, dparams, dcaches,
                                       None, last, lens, keys, temps,
                                       tks, tps, spec_rows)
    return spec_step

"""Pipelined inference forward for pp > 1.

Equivalent of the reference's pipelined ForwardStep
(megatron/text_generation/forward_step.py:45-204): there, each decode step
streams (micro)batches through pipeline stages with NCCL p2p and the last
stage broadcasts logits back. Here the layer stack runs under shard_map
manual over the "pipe" axis — the stacked layer params and KV caches are
sharded over their leading (layer) axis, the hidden state rotates
stage-to-stage with lax.ppermute, and a final psum broadcasts the
last stage's logits to every stage (the reference's
broadcast_from_last_pipeline_stage, text_generation/communication.py).

Each stage computes only at its own tick (lax.cond), so one forward costs
Pn sequential stage-times — the unavoidable pipeline latency for a single
batch — and each stage's KV caches stay resident on its devices.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from megatron_tpu.config import ModelConfig
from megatron_tpu.models.language_model import final_hidden_norm, lm_logits
from megatron_tpu.models.transformer import block_forward
from megatron_tpu.ops.rotary import precompute_rope
from megatron_tpu.training.pipeline import _embed_onehot


def make_pipelined_lm_forward(cfg: ModelConfig, mesh: Mesh, num_stages: int):
    """Returns fwd(params, tokens, positions, caches, cache_index) ->
    (logits, caches) with the same contract as the lm_forward cached path
    (language_model.py), usable as generation's forward_fn."""
    Pn = num_stages
    L = cfg.num_layers
    if L % Pn:
        raise ValueError(f"num_layers={L} not divisible by stages {Pn}")
    Lp = L // Pn
    perm = [(i, (i + 1) % Pn) for i in range(Pn)]

    def pipelined(layers, other, tokens, positions, ck, cv, cache_index):
        params_local = dict(other, layers=layers)
        stage = jax.lax.axis_index("pipe")
        B, S = tokens.shape
        total = ck.shape[2]

        rope = None
        if cfg.position_embedding_type == "rotary":
            rope = precompute_rope(cfg.head_dim, max(cfg.seq_length, total),
                                   cfg.rope_theta, cfg.rope_scaling_factor)

        x0 = _embed_onehot(cfg, params_local, tokens, None,
                           positions=positions).astype(cfg.dtype)

        def tick(carry, t):
            state, ck, cv, logits = carry
            active = t == stage

            def compute(args):
                state, ck, cv = args
                x = jnp.where(stage == 0, x0, state)

                def lbody(x, sc):
                    lp, k1, v1 = sc
                    y, new_kv, _ = block_forward(
                        cfg, lp, x, rope, positions,
                        kv_cache=(k1, v1), cache_index=cache_index)
                    return y, new_kv

                y, (nk, nv) = jax.lax.scan(lbody, x, (layers, ck, cv))
                return y, nk, nv

            state2, ck2, cv2 = jax.lax.cond(
                active, compute, lambda a: a, (state, ck, cv))

            def mk_logits(_):
                h = final_hidden_norm(cfg, params_local, state2)
                return lm_logits(cfg, params_local, h).astype(jnp.float32)

            logits = jax.lax.cond(active & (stage == Pn - 1), mk_logits,
                                  lambda _: logits, None)
            state3 = jax.lax.ppermute(state2, "pipe", perm)
            return (state3, ck2, cv2, logits), None

        V = (cfg.vocab_size if not cfg.tie_embed_logits
             else params_local["embed"]["tokens"].shape[0])
        init = (jnp.zeros((B, S, cfg.hidden_size), cfg.dtype), ck, cv,
                jnp.zeros((B, S, V), jnp.float32))
        (state, ck, cv, logits), _ = jax.lax.scan(tick, init, jnp.arange(Pn))
        # zeros everywhere but the last stage: psum = broadcast
        logits = jax.lax.psum(logits, "pipe")
        return logits, ck, cv

    def fwd(params, tokens, positions, caches, cache_index):
        layers = params["layers"]
        other = {k: v for k, v in params.items() if k != "layers"}
        fn = jax.shard_map(
            pipelined,
            mesh=mesh,
            in_specs=(jax.tree.map(lambda _: P("pipe"), layers),
                      jax.tree.map(lambda _: P(), other),
                      P(), P(), P("pipe"), P("pipe"), P()),
            out_specs=(P(), P("pipe"), P("pipe")),
            axis_names={"pipe"},
            check_vma=False,
        )
        logits, ck, cv = fn(layers, other, tokens, positions,
                            caches[0], caches[1], cache_index)
        return logits, (ck, cv)

    return fwd

"""REST text-generation server.

Observability: GET /metrics returns the process metrics registry in
Prometheus text format (slot occupancy, queue depth, TTFT and per-token
latency histograms, admitted/retired counters, HTTP request counters —
docs/observability.md) and GET /healthz a liveness probe, alongside the
generation API below.

Equivalent of megatron/text_generation_server.py (241 LoC,
Flask + flask_restful) on the stdlib http.server — PUT/POST /api with the
same request schema:

  {"prompts": [...], "tokens_to_generate": N, "temperature": T,
   "top_k": K, "top_p": P, "add_BOS": bool, "logprobs": bool,
   "random_seed": S, "beam_width": W?}

beam_width switches to beam search (the reference's separate BEAM choice
int broadcast becomes just a field — no multi-rank choreography).

Two execution models behind the same schema:

  * engine_slots > 0 (default for the CLI): sampling requests go through
    the continuous-batching InferenceEngine — concurrent HTTP handlers
    each submit their prompts and SHARE every batched decode tick instead
    of serializing behind a lock (docs/serving.md). Beam search and
    scoring (tokens_to_generate == 0) still take the one-shot path.
  * engine_slots == 0: the reference's Flask-era shape — a global lock
    serializes whole requests through generate_tokens.
"""

from __future__ import annotations

import json
import contextlib
import threading
import time

import jax
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

from megatron_tpu.config import ModelConfig
from megatron_tpu.inference.api import (
    beam_search_and_post_process, generate_and_post_process,
)
from megatron_tpu.inference.engine import EngineOverloadedError
from megatron_tpu.telemetry.http import PROMETHEUS_CONTENT_TYPE
from megatron_tpu.telemetry.metrics import MetricsRegistry, default_registry

MAX_TOKENS_TO_GENERATE = 1024  # ref caps requests similarly
MAX_PROMPTS = 128
#: Retry-After hint on 503 queue-full rejections: one decode tick's
#: worth of backoff is enough for a slot to free in steady traffic
RETRY_AFTER_SECONDS = 1


class GenerationService:
    def __init__(self, cfg: ModelConfig, params: Any, tokenizer,
                 mesh=None, forward_fn=None, kv_cache_int8=False,
                 engine_slots: int = 0, engine_max_seq_len=None,
                 metrics: Optional[MetricsRegistry] = None,
                 engine_max_queue: Optional[int] = None,
                 kv_paging: bool = False, page_size: int = 16,
                 prefill_chunk: int = 32,
                 num_pages: Optional[int] = None):
        """mesh + forward_fn serve sharded models: the mesh becomes
        ambient around generation (GSPMD handles tp/cp), forward_fn is the
        pp>1 pipelined forward (ref ForwardStep, forward_step.py:45-204).

        engine_slots > 0 builds a continuous-batching InferenceEngine with
        that many KV-cache slots plus its background step-loop thread;
        concurrent sampling requests then share each decode tick.
        kv_paging swaps in the PagedInferenceEngine (shared page pool +
        radix prefix cache + chunked prefill, docs/serving.md);
        engine_max_queue bounds admission — overload answers 503 with
        Retry-After instead of growing queue latency without bound."""
        if kv_cache_int8 and forward_fn is not None:
            # fail at construction, not as a 500 on every request — the
            # pipelined forward threads bf16 cache pairs (the same guard
            # generate_tokens applies per call)
            raise ValueError(
                "kv_cache_int8 is not supported with a pipelined (pp>1) "
                "forward_fn — serve pp>1 models with bf16 KV caches")
        if engine_slots and forward_fn is not None:
            raise ValueError(
                "the continuous-batching engine runs the single-stage "
                "forward only — serve pp>1 models with engine_slots=0")
        self.cfg = cfg
        self.params = params
        self.tokenizer = tokenizer
        self.mesh = mesh
        self.forward_fn = forward_fn
        self.kv_cache_int8 = kv_cache_int8
        self.lock = threading.Lock()
        # one registry serves /metrics: the engine's slot/latency
        # collectors and the HTTP layer's request counters both land here
        self.metrics = metrics if metrics is not None else default_registry()
        self._m_requests = self.metrics.counter(
            "server_requests_total", "API requests by outcome",
            label_names=("status",))
        self._m_latency = self.metrics.histogram(
            "server_request_seconds", "API request wall time")
        self.engine = None
        if engine_slots:
            if kv_paging:
                from megatron_tpu.inference.paging import PagedInferenceEngine

                self.engine = PagedInferenceEngine(
                    cfg, params, num_slots=engine_slots,
                    max_seq_len=engine_max_seq_len,
                    kv_cache_int8=kv_cache_int8,
                    page_size=page_size, prefill_chunk=prefill_chunk,
                    num_pages=num_pages,
                    vocab_size=tokenizer.vocab_size, mesh=mesh,
                    metrics=self.metrics, max_queue=engine_max_queue)
            else:
                from megatron_tpu.inference.engine import InferenceEngine

                self.engine = InferenceEngine(
                    cfg, params, num_slots=engine_slots,
                    max_seq_len=engine_max_seq_len,
                    kv_cache_int8=kv_cache_int8,
                    vocab_size=tokenizer.vocab_size, mesh=mesh,
                    metrics=self.metrics, max_queue=engine_max_queue)
            self.engine.start()

    def shutdown(self) -> None:
        """Stop the engine's step-loop thread (no-op without an engine)."""
        if self.engine is not None:
            self.engine.stop()

    def _mesh_scope(self):
        return (jax.sharding.set_mesh(self.mesh) if self.mesh is not None
                else contextlib.nullcontext())

    def handle(self, req: dict) -> dict:
        prompts = req.get("prompts")
        if not isinstance(prompts, list) or not prompts:
            raise ValueError("prompts: non-empty list of strings required")
        if len(prompts) > MAX_PROMPTS:
            raise ValueError(f"at most {MAX_PROMPTS} prompts per request")
        if not all(isinstance(p, str) and p for p in prompts):
            raise ValueError("prompts must be non-empty strings")
        n = int(req.get("tokens_to_generate", 64))
        if not 0 <= n <= MAX_TOKENS_TO_GENERATE:
            raise ValueError(f"tokens_to_generate in [0, {MAX_TOKENS_TO_GENERATE}]")

        if req.get("beam_width"):
            with self.lock, self._mesh_scope():
                if self.forward_fn is not None:
                    raise ValueError(
                        "beam search is not supported on pipelined (pp>1) "
                        "serving; use sampling or serve at pp=1")
                texts, segments, scores = beam_search_and_post_process(
                    self.cfg, self.params, self.tokenizer, prompts,
                    tokens_to_generate=n,
                    beam_size=int(req["beam_width"]),
                    add_BOS=bool(req.get("add_BOS", False)),
                    length_penalty=float(req.get("length_penalty", 1.0)),
                    kv_cache_int8=self.kv_cache_int8)
                return {"text": texts, "segments": segments,
                        "scores": [float(s) for s in scores]}

        # continuous batching: no request lock — the engine's slot
        # scheduler interleaves every caller's prompts into shared decode
        # ticks (scoring still needs the one-shot teacher-forced pass);
        # the one-shot path serializes whole requests and makes the mesh
        # ambient here (the engine's driver thread scopes its own)
        use_engine = self.engine is not None and n > 0

        def generate():
            texts, segments, logprobs, _ = generate_and_post_process(
                self.cfg, self.params, self.tokenizer, prompts,
                tokens_to_generate=n,
                temperature=float(req.get("temperature", 1.0)),
                top_k_sampling=int(req.get("top_k", 0)),
                top_p_sampling=float(req.get("top_p", 0.0)),
                add_BOS=bool(req.get("add_BOS", False)),
                return_output_log_probs=bool(req.get("logprobs", False)),
                random_seed=int(req.get("random_seed", 0)),
                forward_fn=self.forward_fn,
                kv_cache_int8=self.kv_cache_int8,
                engine=self.engine if use_engine else None)
            out = {"text": texts, "segments": segments}
            if logprobs is not None:
                out["logprobs"] = [list(map(float, row)) for row in logprobs]
            return out

        if use_engine:
            return generate()
        with self.lock, self._mesh_scope():
            return generate()


def make_handler(service: GenerationService):
    class Handler(BaseHTTPRequestHandler):
        def _reply(self, code: int, payload: dict, headers=()):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for name, value in headers:
                self.send_header(name, value)
            self.end_headers()
            self.wfile.write(body)

        def _handle(self):
            t0 = time.monotonic()
            status = "500"
            try:
                length = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(length) or b"{}")
                payload = service.handle(req)
                status = "200"
                self._reply(200, payload)
            except EngineOverloadedError as e:
                # bounded admission (--serve_max_queue): overload degrades
                # to fast 503s clients can back off on, not queue latency
                status = "503"
                self._reply(503, {"message": str(e)},
                            headers=(("Retry-After",
                                      str(RETRY_AFTER_SECONDS)),))
            except ValueError as e:
                status = "400"
                self._reply(400, {"message": str(e)})
            except Exception as e:  # noqa: BLE001 — server must not die
                self._reply(500, {"message": f"internal error: {e}"})
            finally:
                service._m_requests.inc(status=status)
                service._m_latency.observe(time.monotonic() - t0)

        do_PUT = _handle
        do_POST = _handle

        def do_GET(self):
            # observability endpoints (Prometheus scrape + liveness); the
            # generation API stays PUT/POST /api
            path = self.path.split("?", 1)[0]
            if path == "/metrics":
                body = service.metrics.render().encode()
                self.send_response(200)
                self.send_header("Content-Type", PROMETHEUS_CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif path == "/healthz":
                alive = (service.engine is None
                         or service.engine._thread is None
                         or service.engine._thread.is_alive())
                self._reply(200 if alive else 500,
                            {"ok": bool(alive),
                             "engine": service.engine is not None})
            else:
                self._reply(404, {"message": "GET serves /metrics and "
                                             "/healthz; the API is "
                                             "PUT/POST /api"})

        def log_message(self, *a):  # quiet
            pass

    return Handler


def run_server(cfg: ModelConfig, params: Any, tokenizer,
               host: str = "0.0.0.0", port: int = 5000,
               mesh=None, forward_fn=None, kv_cache_int8=False,
               engine_slots: int = 0, engine_max_seq_len=None,
               engine_max_queue: Optional[int] = None,
               kv_paging: bool = False, page_size: int = 16,
               prefill_chunk: int = 32,
               num_pages: Optional[int] = None) -> None:
    service = GenerationService(cfg, params, tokenizer, mesh=mesh,
                                forward_fn=forward_fn,
                                kv_cache_int8=kv_cache_int8,
                                engine_slots=engine_slots,
                                engine_max_seq_len=engine_max_seq_len,
                                engine_max_queue=engine_max_queue,
                                kv_paging=kv_paging, page_size=page_size,
                                prefill_chunk=prefill_chunk,
                                num_pages=num_pages)
    server = ThreadingHTTPServer((host, port), make_handler(service))
    mode = (f"continuous batching, {engine_slots} slots"
            + (", paged KV + prefix cache" if kv_paging else "")
            if service.engine else "one-shot")
    print(f"serving generation API on http://{host}:{port}/api ({mode})")
    try:
        server.serve_forever()
    finally:
        service.shutdown()

"""REST text-generation server.

Observability: GET /metrics returns the process metrics registry in
Prometheus text format (slot occupancy, queue depth, TTFT and per-token
latency histograms, admitted/retired counters, HTTP request counters —
docs/observability.md), GET /healthz a liveness probe ("the process and
its step loop exist") and GET /readyz a readiness probe ("routing a
request here right now would not queue-stall": 503 until the decode step
is warmed, while draining or mid-reload, and when the step loop has
pending work but stopped making progress). The fleet router
(inference/fleet/router.py) and any k8s-style prober key off /readyz;
/healthz deliberately stays green through drains so an orchestrator does
not kill a replica that is merely finishing its in-flight work.

Fleet control plane (POST, docs/serving.md "Fleet"):

  /admin/drain    {"timeout_s": F, "handoff": [urls]?}
                                    stop admitting (new /api requests get
                                    503 + Retry-After); with handoff peers
                                    (the field, or --serve peers) migrate
                                    in-flight + queued requests to them,
                                    else wait for them to finish
  /admin/import   <binary frame>    accept a migrated request's state
                                    (fleet/migration.py wire format), run
                                    it to completion, return its output;
                                    409 on a torn/corrupt frame
  /admin/export_prefix {"tokens": [...]}
                                    pack a cached prefix's KV pages as a
                                    binary frame (404 when not cached)
  /admin/import_prefix <binary frame>
                                    install exported prefix pages into
                                    the local radix cache
  /admin/register_prefix {"tokens": [...]}
                                    ensure a prefix is radix-resident
                                    (prime with one greedy token if not)
  /admin/readmit  {}                resume admission after a drain
  /admin/reload   {"load": DIR, "iteration": N?}
                                    hot weight reload: manifest-verified
                                    committed checkpoint -> engine
                                    update_params between decode ticks
                                    (zero recompiles, zero dropped
                                    requests)
  /admin/profile  {"steps": N}      on-demand profiler capture: trace N
                                    decode ticks under live traffic into
                                    an xplane dir readable by
                                    tools/trace_report.py (also accepts
                                    ?steps=N query form; zero recompiles,
                                    zero overhead while disarmed)
  /admin/status                     (GET) draining/ready/weights_version/
                                    engine stats

Equivalent of megatron/text_generation_server.py (241 LoC,
Flask + flask_restful) on the stdlib http.server — PUT/POST /api with the
same request schema:

  {"prompts": [...], "tokens_to_generate": N, "temperature": T,
   "top_k": K, "top_p": P, "add_BOS": bool, "logprobs": bool,
   "random_seed": S, "beam_width": W?}

beam_width switches to beam search (the reference's separate BEAM choice
int broadcast becomes just a field — no multi-rank choreography).

Two execution models behind the same schema:

  * engine_slots > 0 (default for the CLI): sampling requests go through
    the continuous-batching InferenceEngine — concurrent HTTP handlers
    each submit their prompts and SHARE every batched decode tick instead
    of serializing behind a lock (docs/serving.md). Beam search and
    scoring (tokens_to_generate == 0) still take the one-shot path.
  * engine_slots == 0: the reference's Flask-era shape — a global lock
    serializes whole requests through generate_tokens.
"""

from __future__ import annotations

import json
import contextlib
import os
import signal
import sys
import threading
import time

import jax
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

from megatron_tpu.config import ModelConfig
from megatron_tpu.inference.api import (
    beam_search_and_post_process, generate_and_post_process,
)
from megatron_tpu.inference.engine import (
    EngineOverloadedError, RequestTimeoutError,
)
from megatron_tpu.telemetry.http import PROMETHEUS_CONTENT_TYPE
from megatron_tpu.telemetry.metrics import MetricsRegistry, default_registry

MAX_TOKENS_TO_GENERATE = 1024  # ref caps requests similarly
MAX_PROMPTS = 128
#: Retry-After hint on 503 queue-full rejections: one decode tick's
#: worth of backoff is enough for a slot to free in steady traffic
RETRY_AFTER_SECONDS = 1
#: engine progress-stall window before readiness flips (a hung device
#: step keeps the thread "alive" — only lack of progress reveals it)
STALL_THRESHOLD_SECONDS = 10.0


class ServiceDrainingError(RuntimeError):
    """The server is draining (SIGTERM grace or a rolling update): new
    requests answer 503 + Retry-After so the router re-routes them."""


def _lane_meshes(mesh, cp_lanes: int) -> list:
    """CP x DP device carving: every lane gets its own context-only
    cp-sized mesh over a distinct device group (the serving mesh's
    devices first, then the host's remaining devices). The incoming
    mesh must not SHARD params — tensor/pipe/expert > 1 refuse, since
    a lane could not replicate its params copy with a plain
    device_put; a `data` axis is pure replication for serving (the CLI
    mesh builder parks unused devices there) and is re-carved into
    lanes."""
    import numpy as np
    from jax.sharding import Mesh

    from megatron_tpu.parallel.mesh import AXIS_CONTEXT

    shape = dict(mesh.shape)
    cp = shape.get(AXIS_CONTEXT, 1)
    sharded = {a: n for a, n in shape.items()
               if n > 1 and a not in (AXIS_CONTEXT, "data")}
    if sharded:
        raise ValueError(
            "cp_lanes > 1 needs a context-only mesh (no tensor/pipe/"
            f"expert sharding); got {shape} — a tensor-sharded lane "
            "cannot replicate its params copy with a plain device_put")
    pool = list(mesh.devices.flat)
    seen = {d.id for d in pool}
    pool += [d for d in jax.devices() if d.id not in seen]
    need = cp_lanes * cp
    if len(pool) < need:
        raise ValueError(
            f"cp_lanes={cp_lanes} x cp={cp} needs {need} devices; "
            f"only {len(pool)} visible")
    return [Mesh(np.array(pool[i * cp:(i + 1) * cp]).reshape((cp,)),
                 (AXIS_CONTEXT,))
            for i in range(cp_lanes)]


class GenerationService:
    def __init__(self, cfg: ModelConfig, params: Any, tokenizer,
                 mesh=None, forward_fn=None, kv_cache_int8=False,
                 engine_slots: int = 0, engine_max_seq_len=None,
                 metrics: Optional[MetricsRegistry] = None,
                 engine_max_queue: Optional[int] = None,
                 kv_paging: bool = False, page_size: int = 16,
                 prefill_chunk: int = 32,
                 num_pages: Optional[int] = None,
                 request_timeout: Optional[float] = None,
                 reload_dir: Optional[str] = None,
                 weights_version: Optional[int] = None,
                 stall_threshold_s: float = STALL_THRESHOLD_SECONDS,
                 warmup: bool = False,
                 speculative: Optional[str] = None,
                 spec_k: int = 4,
                 draft_cfg=None, draft_params=None,
                 profile_dir: Optional[str] = None,
                 compress_collectives: str = "none",
                 comm_policy: Optional[str] = None,
                 cp_serving: bool = False,
                 cp_collectives: str = "dense",
                 cp_comm_policy: Optional[str] = None,
                 cp_geometry: str = "ring",
                 cp_subgroup: int = 0,
                 cp_overlap: bool = True,
                 cp_lanes: int = 1,
                 peers: Optional[list] = None):
        """mesh + forward_fn serve sharded models: the mesh becomes
        ambient around generation (GSPMD handles tp/cp), forward_fn is the
        pp>1 pipelined forward (ref ForwardStep, forward_step.py:45-204).

        engine_slots > 0 builds a continuous-batching InferenceEngine with
        that many KV-cache slots plus its background step-loop thread;
        concurrent sampling requests then share each decode tick.
        kv_paging swaps in the PagedInferenceEngine (shared page pool +
        radix prefix cache + chunked prefill, docs/serving.md);
        engine_max_queue bounds admission — overload answers 503 with
        Retry-After instead of growing queue latency without bound.

        request_timeout: default per-request deadline (seconds) on the
        engine path — a queued or mid-decode request past it fails with
        HTTP 504 instead of waiting forever (--serve_request_timeout).
        reload_dir: default checkpoint dir for POST /admin/reload;
        weights_version: iteration initially served (when loaded from a
        committed checkpoint), reported in responses + /admin/status.
        warmup=True defers readiness (/readyz stays 503) until warmup()
        has compiled the decode step — run_server drives it on a
        background thread so probes get answered during the compile.
        speculative: "ngram" or "model" turns on speculative decoding
        in the engine (--serve_speculative; docs/serving.md): spec_k
        drafts per slot verified by one multi-token target forward per
        tick, greedy output token-identical to plain decode. "model"
        needs draft_cfg + draft_params (a small draft network with its
        own cache tree). Requests may opt out per call with
        {"spec": false}.

        compress_collectives ("none"|"int8"|"fp8";
        --serve_compress_collectives): low-bit tensor-parallel
        collectives in the engine decode/prefill forward (quant/,
        docs/serving.md) — a no-op unless the mesh has a non-trivial
        tensor axis. comm_policy: path to a site-policy JSON
        (tools/trace_report.py --emit-comm-policy) choosing WHICH
        collectives compress from measured exposed fractions.

        cp_serving (--serve_context_parallel; docs/serving.md
        "Context-parallel long-context serving"): shard every sequence's
        paged KV over the mesh's "context" axis and run decode/prefill
        attention as a ring over the shards — million-token prompts
        whose KV exceeds one device's HBM. Needs kv_paging and a mesh
        with context >= 2; greedy output stays token-identical to the
        single-host paged engine. cp_collectives ("dense"|"int8"|"fp8")
        picks the ring-hop transport; cp_comm_policy is a site-policy
        JSON gating the "cp_ring" and "cp_a2a" sites.

        cp_geometry (--serve_cp_geometry): "ring" is the flat 1D
        sequence ring; "2d" factors the context axis into
        cp_seq x cp_head (cp_subgroup = cp_head, the node-local device
        count) — head all-to-all inside the subgroup, ring hops only
        across subgroups (docs/serving.md "CP geometry and overlap").
        cp_overlap picks the overlapped ring schedule (default; serial
        kept for A/B trace capture). cp_lanes > 1 (CP x DP): one host
        runs that many INDEPENDENT CP engine lanes, each over its own
        cp-sized device group with its own KV pool and queue; requests
        dispatch to the least-loaded lane and /metrics exposes one
        series per lane (lane="0", ...) that the fleet router's load
        scrape sums. Lanes need a context-only mesh (tp == 1) and do
        not compose with peers (migration handoff) or /admin/reload.

        peers: base URLs of sibling replicas (http://host:port). A drain
        (SIGTERM grace or /admin/drain) HANDS OFF in-flight and queued
        requests to them via the KV migration fabric
        (fleet/migration.py) instead of failing them — the degradation
        ladder per request is migrate -> recompute-resume -> retry ->
        reject, each rung journaled as `serve_migrate`."""
        if kv_cache_int8 and forward_fn is not None:
            # fail at construction, not as a 500 on every request — the
            # pipelined forward threads bf16 cache pairs (the same guard
            # generate_tokens applies per call)
            raise ValueError(
                "kv_cache_int8 is not supported with a pipelined (pp>1) "
                "forward_fn — serve pp>1 models with bf16 KV caches")
        if engine_slots and forward_fn is not None:
            raise ValueError(
                "the continuous-batching engine runs the single-stage "
                "forward only — serve pp>1 models with engine_slots=0")
        self.cfg = cfg
        self.params = params
        self.tokenizer = tokenizer
        self.mesh = mesh
        self.forward_fn = forward_fn
        self.kv_cache_int8 = kv_cache_int8
        self.request_timeout = request_timeout
        self.reload_dir = reload_dir
        # default output dir for /admin/profile captures (each capture
        # lands in its own plugins/profile/<session> subdir)
        self.profile_dir = profile_dir or "runs/serve_profile"
        self.weights_version = weights_version
        self.stall_threshold_s = stall_threshold_s
        self.draining = False
        self.reloading = False
        # readiness gate: set once the decode step is compiled (warmup()
        # ran, or no warmup was requested and first-request compile is
        # acceptable) — /readyz answers 503 until then so the router never
        # routes a request into a multi-second compile stall
        self._warmed = threading.Event()
        # one admin mutation (drain/readmit/reload) at a time — a rolling
        # update racing a second orchestrator must serialize, not interleave
        self._admin_lock = threading.Lock()
        self.lock = threading.Lock()
        # one registry serves /metrics: the engine's slot/latency
        # collectors and the HTTP layer's request counters both land here
        self.metrics = metrics if metrics is not None else default_registry()
        self._m_requests = self.metrics.counter(
            "server_requests_total", "API requests by outcome",
            label_names=("status",))
        self._m_latency = self.metrics.histogram(
            "server_request_seconds", "API request wall time")
        self.peers = [str(p).rstrip("/") for p in (peers or [])]
        self._m_migrations = self.metrics.counter(
            "server_migrations_total",
            "request handoffs by degradation-ladder outcome",
            label_names=("outcome",))
        # the KV-transfer comm ledger (manifest cost model: bytes on the
        # wire per migration frame). Deliberately SEPARATE from the
        # engine_comm_*_bytes_total TP-collective counters so the
        # compressed-collective ratio math stays uncontaminated.
        self._m_migrate_bytes = self.metrics.counter(
            "server_migrate_wire_bytes_total",
            "KV-state migration wire bytes (manifest cost model)",
            label_names=("direction",))
        self.engine = None
        self.engines: list = []
        self.cp_lanes = int(cp_lanes)
        if self.cp_lanes < 1:
            raise ValueError(f"cp_lanes must be >= 1, got {cp_lanes}")
        if self.cp_lanes > 1:
            if not cp_serving:
                raise ValueError(
                    "cp_lanes > 1 is the CP x DP geometry — it needs "
                    "--serve_context_parallel")
            if self.peers:
                raise ValueError(
                    "cp_lanes > 1 does not compose with migration "
                    "handoff peers yet — run one lane per replica to "
                    "keep handoff")
        if speculative and not engine_slots:
            raise ValueError(
                "speculative decoding runs inside the continuous-batching "
                "engine — serve with engine_slots > 0")
        if engine_slots:
            spec_cfg = None
            if speculative:
                from megatron_tpu.inference.speculative import SpecConfig

                spec_cfg = SpecConfig(k=spec_k, drafter=speculative,
                                      draft_cfg=draft_cfg,
                                      draft_params=draft_params)
            if cp_serving:
                from megatron_tpu.inference.context_parallel import (
                    ContextParallelEngine,
                )

                if not kv_paging:
                    raise ValueError(
                        "context-parallel serving runs over the paged "
                        "engine — enable kv_paging")
                if kv_cache_int8 or spec_cfg is not None:
                    raise ValueError(
                        "context-parallel serving supports neither int8 "
                        "KV pools nor speculative decoding")
                def _cp_engine(lane_mesh, lane_params, lane_metrics):
                    return ContextParallelEngine(
                        cfg, lane_params, num_slots=engine_slots,
                        max_seq_len=engine_max_seq_len,
                        page_size=page_size, prefill_chunk=prefill_chunk,
                        num_pages=num_pages,
                        vocab_size=tokenizer.vocab_size, mesh=lane_mesh,
                        metrics=lane_metrics, max_queue=engine_max_queue,
                        compress_collectives=compress_collectives,
                        comm_policy=comm_policy,
                        cp_collectives=cp_collectives,
                        cp_comm_policy=cp_comm_policy,
                        cp_geometry=cp_geometry,
                        cp_subgroup=cp_subgroup,
                        cp_overlap=cp_overlap)

                if self.cp_lanes > 1:
                    from jax.sharding import NamedSharding, PartitionSpec

                    from megatron_tpu.telemetry.metrics import (
                        LabeledRegistryView,
                    )

                    # every lane mesh is context-only (the serving mesh
                    # may carry a replication-only data axis the lanes
                    # re-carve), so each lane replicates its own params
                    # copy onto its device group
                    for i, lane_mesh in enumerate(
                            _lane_meshes(mesh, self.cp_lanes)):
                        lane_params = jax.device_put(
                            params, NamedSharding(lane_mesh,
                                                  PartitionSpec()))
                        self.engines.append(_cp_engine(
                            lane_mesh, lane_params,
                            LabeledRegistryView(self.metrics,
                                                lane=str(i))))
                    self.engine = self.engines[0]
                else:
                    self.engine = _cp_engine(mesh, params, self.metrics)
            elif kv_paging:
                from megatron_tpu.inference.paging import PagedInferenceEngine

                self.engine = PagedInferenceEngine(
                    cfg, params, num_slots=engine_slots,
                    max_seq_len=engine_max_seq_len,
                    kv_cache_int8=kv_cache_int8,
                    page_size=page_size, prefill_chunk=prefill_chunk,
                    num_pages=num_pages,
                    vocab_size=tokenizer.vocab_size, mesh=mesh,
                    metrics=self.metrics, max_queue=engine_max_queue,
                    speculative=spec_cfg,
                    compress_collectives=compress_collectives,
                    comm_policy=comm_policy)
            else:
                from megatron_tpu.inference.engine import InferenceEngine

                self.engine = InferenceEngine(
                    cfg, params, num_slots=engine_slots,
                    max_seq_len=engine_max_seq_len,
                    kv_cache_int8=kv_cache_int8,
                    vocab_size=tokenizer.vocab_size, mesh=mesh,
                    metrics=self.metrics, max_queue=engine_max_queue,
                    speculative=spec_cfg,
                    compress_collectives=compress_collectives,
                    comm_policy=comm_policy)
            if not self.engines:
                self.engines = [self.engine]
            for eng in self.engines:
                eng.start()
        if not (warmup and self.engine is not None):
            # no deferred warmup: the first request pays the compile (the
            # pre-fleet behavior) and readiness is green from the start
            self._warmed.set()

    def shutdown(self) -> None:
        """Stop every engine lane's step-loop thread (no-op without an
        engine)."""
        for eng in self.engines:
            eng.stop()

    # ----- fleet control plane (docs/serving.md "Fleet") -------------------

    def _journal(self, kind: str, **fields) -> None:
        from megatron_tpu.telemetry.journal import get_global_journal

        j = get_global_journal()
        if j is not None:
            j.emit(kind, **fields)

    def warmup(self) -> None:
        """Compile the engine's decode step + smallest prefill bucket with
        a throwaway request, then flip readiness green. Runs on a
        background thread (run_server) so /readyz answers 503 — not a
        connection timeout — during the multi-second compile."""
        if self.engine is not None and not self._warmed.is_set():
            import numpy as np

            t0 = time.monotonic()
            for eng in self.engines:
                eng.generate(np.array([[1]], np.int32),
                             np.array([1], np.int32), max_new_tokens=2)
            self._journal("serve_warmup", lanes=len(self.engines),
                          wall_s=round(time.monotonic() - t0, 3))
        self._warmed.set()

    def ready(self) -> tuple:
        """(ok, detail) for /readyz: would routing a request here right
        now queue-stall? 503 while unwarmed, draining, mid-reload, or when
        the step loop has pending work but stopped making progress."""
        detail: dict = {"warmed": self._warmed.is_set(),
                        "draining": self.draining,
                        "reloading": self.reloading}
        ok = detail["warmed"] and not self.draining and not self.reloading
        if self.engine is not None:
            alive = all(e._thread is None or e._thread.is_alive()
                        for e in self.engines)
            stalled = any(e.stalled(self.stall_threshold_s)
                          for e in self.engines)
            detail["step_loop_alive"] = alive
            detail["stalled"] = stalled
            ok = ok and alive and not stalled
        if self.weights_version is not None:
            detail["weights_version"] = self.weights_version
        detail["ok"] = ok
        return ok, detail

    def drain(self, timeout_s: float = 30.0,
              handoff_urls: Optional[list] = None) -> bool:
        """Stop admitting (new /api requests answer 503 + Retry-After) and
        wait for in-flight work to finish; True when fully drained within
        `timeout_s`. The server keeps serving probes and admin requests —
        readmit() undoes the drain.

        When handoff peers exist (`handoff_urls`, else the server's
        configured `peers`), in-flight and queued engine requests are
        MIGRATED to them first (migrate_out) instead of being waited on —
        their clients get full responses assembled from the peer's
        continuation, so a drain costs zero failed requests and near-zero
        added latency even with minutes of decoding still queued."""
        with self._admin_lock:
            self.draining = True
            peers = [str(p).rstrip("/") for p in
                     (handoff_urls if handoff_urls else self.peers)]
            self._journal("serve_drain_begin", timeout_s=timeout_s,
                          handoff_peers=len(peers))
            deadline = time.monotonic() + timeout_s
            if peers and self.engine is not None:
                self.migrate_out(peers, timeout_s=timeout_s)
            drained = all(
                eng.wait_idle(
                    timeout=max(deadline - time.monotonic(), 0.001))
                for eng in self.engines) if self.engine is not None \
                else True
            if drained:
                # even with an engine, beam-search and scoring requests
                # run one-shot under self.lock — a drain that ignored
                # them would report "drained" with a beam request still
                # mid-generation and let a reload swap params under it
                drained = self.lock.acquire(
                    timeout=max(deadline - time.monotonic(), 0.001))
                if drained:
                    self.lock.release()
            self._journal("serve_drain_done", drained=drained)
            return drained

    def readmit(self) -> None:
        """Resume admission after a drain (rolling-update readmit step)."""
        with self._admin_lock:
            self.draining = False
            self._journal("serve_readmit")

    # ----- KV-state migration (docs/fault_tolerance.md) --------------------

    def migrate_out(self, peers: list, timeout_s: float = 30.0) -> dict:
        """Hand off every in-flight and queued engine request to a peer.

        export_all_requests atomically empties the engine (its waiters
        stay blocked on req.done); each exported request then walks the
        degradation ladder in _handoff_one and its waiter is completed or
        failed accordingly. Returns {outcome: count}."""
        deadline = time.monotonic() + timeout_s
        exported = self.engine.export_all_requests()
        outcomes: dict = {}
        for req, meta, sections in exported:
            budget = max(deadline - time.monotonic(), 0.0)
            outcome = self._handoff_one(req, meta, sections, peers, budget)
            outcomes[outcome] = outcomes.get(outcome, 0) + 1
            self._m_migrations.inc(outcome=outcome)
        if exported:
            self._journal("serve_handoff", requests=len(exported),
                          peers=len(peers), **outcomes)
        return outcomes

    def _handoff_one(self, req, meta: dict, sections: dict, peers: list,
                     budget_s: float) -> str:
        """One request down the degradation ladder:

          migrate    POST the full state (KV pages + scales + chain) to a
                     peer's /admin/import; the peer finishes the request
                     token-identically and we complete the client's
                     response with its output
          recompute  same transfer WITHOUT the KV sections — the peer
                     recompute-resumes (teacher-forced prefill over
                     prompt + generated, exact via the migrated chain)
          retry      no peer accepted: fail the waiter as overloaded
                     (503 + Retry-After) so the router re-runs it — safe
                     for greedy and seeded requests (docs/serving.md)
          reject     the drain budget is already spent: fail as timed out
                     (504, non-retryable — the client's budget went with
                     it)

        Every rung attempt is journaled (`serve_migrate` stage="handoff")
        and the final outcome as stage="handoff_done". Returns the
        outcome label."""
        from megatron_tpu.inference.fleet import migration
        from megatron_tpu.training import resilience

        deadline = time.monotonic() + budget_s

        def _done(outcome: str) -> str:
            self._journal("serve_migrate", stage="handoff_done",
                          outcome=outcome, prompt_len=len(req.prompt),
                          generated=len(req.generated))
            return outcome

        rungs = []
        if "kv" in meta:
            rungs.append(("migrate", meta, sections))
        rungs.append(("recompute",
                      {k: v for k, v in meta.items() if k != "kv"},
                      {k: v for k, v in sections.items()
                       if not k.startswith("kv_")}))
        for rung, m, s in rungs:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            blob = migration.pack_state(m, s)
            # fault injection: migrate_fail:N tears the first N outbound
            # transfers — the peer's crc check must reject each one and
            # this loop must keep walking down the ladder
            blob = resilience.maybe_corrupt("migrate_fail", blob)
            for peer in peers:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                t0 = time.monotonic()
                status, body = migration.post_blob(
                    peer + "/admin/import", blob, timeout=remaining)
                ok = status == 200 and isinstance(body, dict)
                fields = {"stage": "handoff", "rung": rung, "ok": ok,
                          "peer": peer, "status": status,
                          "wire_bytes": len(blob),
                          "wall_s": round(time.monotonic() - t0, 3)}
                if not ok:
                    err = (body or {}).get("message") or (
                        body or {}).get("error")
                    if err:
                        fields["error"] = str(err)[:200]
                self._journal("serve_migrate", **fields)
                if not ok:
                    continue
                self._m_migrate_bytes.inc(len(blob), direction="out")
                req.generated[:] = [int(t) for t in
                                    body.get("generated", [])]
                lp = body.get("logprobs")
                if lp is not None:
                    req.logprobs[:] = [float(x) for x in lp]
                plp = body.get("prompt_logprobs")
                if plp and not req.prompt_logprobs:
                    req.prompt_logprobs = [float(x) for x in plp]
                req._finish()
                return _done("migrated" if body.get("path") == "kv_import"
                             else "recomputed")
        if time.monotonic() >= deadline:
            self._journal("serve_migrate", stage="handoff", rung="reject",
                          ok=False, reason="drain budget spent")
            self.engine._fail_timeout(req, "migrating")
            return _done("rejected")
        self._journal("serve_migrate", stage="handoff", rung="retry",
                      ok=True)
        req.overloaded = True
        req._finish(
            "handoff failed on every peer; request is retryable (the "
            "fleet router re-runs it — greedy and seeded requests replay "
            "identically)")
        return _done("retried")

    def import_state(self, blob: bytes) -> dict:
        """Accept a migration frame (POST /admin/import): verify the
        manifest + crc commit contract, rebuild the request in this
        engine (direct KV install or recompute-resume), run it to
        completion, and return its output for the exporter to complete
        the original client's response with. Torn transfers raise
        MigrationIntegrityError (HTTP 409) BEFORE touching the engine."""
        from megatron_tpu.inference.fleet import migration

        if self.engine is None:
            raise ValueError(
                "state import needs the continuous-batching engine "
                "(engine_slots > 0)")
        if self.draining:
            raise ServiceDrainingError(
                "server is draining; migrate elsewhere")
        meta, sections = migration.unpack_state(blob)
        if meta.get("kind") != "request":
            raise ValueError(
                f"expected a request-state frame, got {meta.get('kind')!r}")
        self._m_migrate_bytes.inc(len(blob), direction="in")
        req, path = self.engine.import_request_state(meta, sections)
        budget = meta.get("deadline_remaining_s")
        if budget is None:
            budget = self.request_timeout or 60.0
        if not req.done.wait(timeout=float(budget) + 5.0):
            raise RequestTimeoutError(
                "imported request did not complete within its migrated "
                "deadline")
        if req.timed_out:
            raise RequestTimeoutError(req.error or "deadline exceeded")
        if req.error:
            raise ValueError(req.error)
        return {"path": path,
                "generated": [int(t) for t in req.generated],
                "logprobs": [float(x) for x in req.logprobs],
                "prompt_logprobs": [float(x) for x in req.prompt_logprobs]}

    # ----- fleet prefix directory (page export) ----------------------------

    def _paged_engine(self):
        if self.engine is None or not hasattr(self.engine,
                                              "export_prefix_state"):
            raise ValueError(
                "prefix export/import needs the paged engine (kv_paging)")
        return self.engine

    def export_prefix_blob(self, tokens: list) -> Optional[bytes]:
        """Pack a cached prefix's pages for /admin/export_prefix; None
        when the radix cache holds nothing for it (HTTP 404)."""
        from megatron_tpu.inference.fleet import migration

        out = self._paged_engine().export_prefix_state(
            [int(t) for t in tokens])
        if out is None:
            return None
        blob = migration.pack_state(out[0], out[1])
        self._m_migrate_bytes.inc(len(blob), direction="out")
        return blob

    def import_prefix_blob(self, blob: bytes) -> dict:
        """Install a prefix frame into this replica's radix cache (POST
        /admin/import_prefix): the next prompt sharing the prefix radix-
        hits here without this replica ever having prefilled it."""
        from megatron_tpu.inference.fleet import migration

        eng = self._paged_engine()
        meta, sections = migration.unpack_state(blob)
        if meta.get("kind") != "prefix":
            raise ValueError(
                f"expected a prefix frame, got {meta.get('kind')!r}")
        self._m_migrate_bytes.inc(len(blob), direction="in")
        pages = eng.import_prefix_state(meta, sections)
        self._journal("serve_prefix_import", pages=pages,
                      wire_bytes=len(blob))
        return {"pages": pages}

    def register_prefix(self, tokens: list) -> dict:
        """Ensure a prefix (system prompt) is resident in this replica's
        radix cache (POST /admin/register_prefix), priming it with one
        greedy token through the engine if needed. The router calls this
        on one replica, then fans the resulting pages out to the rest
        via replicate_prefix (page export, no re-prefill)."""
        import numpy as np

        eng = self._paged_engine()
        toks = [int(t) for t in tokens]
        if not toks:
            raise ValueError("tokens: non-empty int list required")
        ps = eng.page_size
        pages, _ = eng.prefix_cache.lookup(toks)
        if len(pages) < len(toks) // ps:
            eng.generate(np.array([toks], np.int32),
                         np.array([len(toks)], np.int32), max_new_tokens=1)
            pages, _ = eng.prefix_cache.lookup(toks)
        self._journal("serve_prefix_register", tokens=len(toks),
                      pages=len(pages))
        return {"pages": len(pages), "tokens": len(toks)}

    def reload(self, load: Optional[str] = None,
               iteration: Optional[int] = None,
               apply_timeout_s: float = 60.0) -> int:
        """Hot weight reload: manifest-verify a committed checkpoint
        (fleet/reload.py — torn or bitrotted saves never reach a serving
        replica), stage it via engine.update_params, and wait for the
        between-tick swap. In-flight slots keep decoding; the jit cache
        key is unchanged so the swap costs zero recompiles (the live
        decode_recompiles counter is the regression gate). Returns the
        iteration now being served."""
        from megatron_tpu.inference.fleet.reload import load_verified_params

        if self.mesh is not None:
            raise ValueError(
                "hot reload on sharded (mesh) serving is not supported in "
                "v1 — the reload path would re-place params without their "
                "shardings; roll the replica instead (restart with the "
                "new checkpoint)")
        with self._admin_lock:
            src = load or self.reload_dir
            if not src:
                raise ValueError(
                    "no checkpoint dir to reload from: pass \"load\" in "
                    "the request or start the server with reload_dir=")
            self.reloading = True
            try:
                t0 = time.monotonic()
                params, it = load_verified_params(src, self.params,
                                                  iteration=iteration)
                if self.engine is not None:
                    applied = self.engine.update_params(params, version=it)
                    if not applied.wait(timeout=apply_timeout_s):
                        raise RuntimeError(
                            f"weight swap staged but not applied within "
                            f"{apply_timeout_s}s — is the step loop "
                            "wedged? (/readyz would say)")
                self.params = params
                self.weights_version = it
                self._journal("serve_weight_reload", version=it, load=src,
                              wall_s=round(time.monotonic() - t0, 3))
                return it
            finally:
                self.reloading = False

    def profile(self, steps: int = 4, timeout_s: float = 30.0,
                out_dir: Optional[str] = None) -> dict:
        """On-demand profiler capture under live traffic (POST
        /admin/profile): trace `steps` decode ticks into the xplane dir
        tools/trace_report.py reads. No restart, no admission pause —
        the step loop never checks a flag (the capture brackets it from
        this thread), so a disarmed server pays nothing and the capture
        itself causes zero decode recompiles. Begin/end are journaled so
        the incident timeline shows when the trace was cut."""
        if self.engine is None:
            raise ValueError(
                "on-demand profiling needs the continuous-batching "
                "engine (engine_slots > 0); one-shot servers can be "
                "traced externally with jax.profiler")
        steps = int(steps)
        if not 1 <= steps <= 10_000:
            raise ValueError("steps must be in [1, 10000]")
        timeout_s = float(timeout_s)
        if not 0 < timeout_s <= 600:
            # the capture holds the process-global profiler session (and
            # its in-memory trace buffer) for up to this long — an
            # unbounded client value could wedge profiling for days
            raise ValueError("timeout_s must be in (0, 600]")
        out = out_dir or self.profile_dir
        self._journal("profile_begin", source="admin", dir=out,
                      steps=steps)
        try:
            result = self.engine.capture_trace(
                out, ticks=steps, timeout_s=timeout_s)
        except BaseException as e:  # noqa: BLE001 - re-raised below: the
            # catch only journals the abort — a begin with no end would
            # mis-pair the NEXT window in the perfetto timeline, so this
            # one closes as aborted (busy lock, profiler error) first
            self._journal("profile_aborted", source="admin",
                          reason=type(e).__name__, flushed=False)
            raise
        self._journal("profile_end", source="admin", **result)
        return result

    def admin_status(self) -> dict:
        ok, detail = self.ready()
        out = {"ready": ok, "detail": detail, "draining": self.draining,
               "reloading": self.reloading,
               "weights_version": self.weights_version}
        if self.engine is not None:
            out["engine"] = dict(self.engine.stats)
            if len(self.engines) > 1:
                out["lanes"] = [dict(e.stats) for e in self.engines]
        return out

    def _mesh_scope(self):
        return (jax.sharding.set_mesh(self.mesh) if self.mesh is not None
                else contextlib.nullcontext())

    def _pick_lane(self):
        """Least-loaded engine lane by busy slots + queue depth — the
        same score replica_load computes fleet-side from the lane
        gauges, so in-host and cross-host dispatch agree."""
        if len(self.engines) <= 1:
            return self.engine
        return min(self.engines,
                   key=lambda e: e.num_active + len(e._queue))

    def handle(self, req: dict) -> dict:
        if self.draining:
            raise ServiceDrainingError(
                "server is draining; retry (the fleet router re-routes "
                "automatically)")
        prompts = req.get("prompts")
        if not isinstance(prompts, list) or not prompts:
            raise ValueError("prompts: non-empty list of strings required")
        if len(prompts) > MAX_PROMPTS:
            raise ValueError(f"at most {MAX_PROMPTS} prompts per request")
        if not all(isinstance(p, str) and p for p in prompts):
            raise ValueError("prompts must be non-empty strings")
        n = int(req.get("tokens_to_generate", 64))
        if not 0 <= n <= MAX_TOKENS_TO_GENERATE:
            raise ValueError(f"tokens_to_generate in [0, {MAX_TOKENS_TO_GENERATE}]")

        if req.get("beam_width"):
            with self.lock, self._mesh_scope():
                if self.forward_fn is not None:
                    raise ValueError(
                        "beam search is not supported on pipelined (pp>1) "
                        "serving; use sampling or serve at pp=1")
                texts, segments, scores = beam_search_and_post_process(
                    self.cfg, self.params, self.tokenizer, prompts,
                    tokens_to_generate=n,
                    beam_size=int(req["beam_width"]),
                    add_BOS=bool(req.get("add_BOS", False)),
                    length_penalty=float(req.get("length_penalty", 1.0)),
                    kv_cache_int8=self.kv_cache_int8)
                return {"text": texts, "segments": segments,
                        "scores": [float(s) for s in scores]}

        # continuous batching: no request lock — the engine's slot
        # scheduler interleaves every caller's prompts into shared decode
        # ticks (scoring still needs the one-shot teacher-forced pass);
        # the one-shot path serializes whole requests and makes the mesh
        # ambient here (the engine's driver thread scopes its own)
        use_engine = self.engine is not None and n > 0
        # CP x DP: dispatch this request to the least-loaded engine lane
        # (the in-host analogue of the fleet router's replica_load)
        engine = self._pick_lane() if use_engine else None
        # per-request deadline (engine path): a request may SHORTEN the
        # server default (--serve_request_timeout) but never extend past
        # it — the operator bound caps the router's retry worst case and
        # stops abandoned waiters from blocking slots, so a client
        # (including one sending an explicit null) cannot opt out of it
        deadline_s = req.get("deadline_s")
        if deadline_s is not None:
            try:
                deadline_s = float(deadline_s)
            except (TypeError, ValueError):
                raise ValueError("deadline_s must be a number (seconds)")
        if self.request_timeout is not None:
            deadline_s = (self.request_timeout if deadline_s is None
                          else min(deadline_s, self.request_timeout))
        # per-request speculative-decoding knob: passes through the
        # fleet router untouched (the router proxies request bodies
        # verbatim); a no-op unless the engine runs --serve_speculative.
        # Greedy output is identical either way — the knob only trades
        # per-token latency variance against throughput.
        spec = req.get("spec", True)
        if not isinstance(spec, bool):
            raise ValueError("spec must be a JSON boolean")

        def generate():
            v0 = self.weights_version
            texts, segments, logprobs, _ = generate_and_post_process(
                self.cfg, self.params, self.tokenizer, prompts,
                tokens_to_generate=n,
                temperature=float(req.get("temperature", 1.0)),
                top_k_sampling=int(req.get("top_k", 0)),
                top_p_sampling=float(req.get("top_p", 0.0)),
                add_BOS=bool(req.get("add_BOS", False)),
                return_output_log_probs=bool(req.get("logprobs", False)),
                random_seed=int(req.get("random_seed", 0)),
                forward_fn=self.forward_fn,
                kv_cache_int8=self.kv_cache_int8,
                engine=engine,
                deadline_s=deadline_s if use_engine else None,
                spec=spec)
            out = {"text": texts, "segments": segments}
            if logprobs is not None:
                out["logprobs"] = [list(map(float, row)) for row in logprobs]
            # which weight version served this request: only claimed when
            # it cannot lie — the version was the same before submit and
            # after completion (a drained rolling update guarantees it;
            # an undrained swap racing completion reports nothing)
            if v0 is not None and v0 == self.weights_version:
                out["weights_version"] = v0
            return out

        if use_engine:
            return generate()
        with self.lock, self._mesh_scope():
            return generate()


def make_handler(service: GenerationService):
    class Handler(BaseHTTPRequestHandler):
        def _reply(self, code: int, payload: dict, headers=()):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for name, value in headers:
                self.send_header(name, value)
            self.end_headers()
            self.wfile.write(body)

        def _read_json(self) -> dict:
            length = int(self.headers.get("Content-Length", 0))
            return json.loads(self.rfile.read(length) or b"{}")

        def _read_body(self) -> bytes:
            length = int(self.headers.get("Content-Length", 0))
            return self.rfile.read(length)

        def _reply_blob(self, blob: bytes):
            self.send_response(200)
            self.send_header("Content-Type", "application/octet-stream")
            self.send_header("Content-Length", str(len(blob)))
            self.end_headers()
            self.wfile.write(blob)

        def _handle(self):
            path = self.path.split("?", 1)[0]
            if path.startswith("/admin/"):
                self._handle_admin(path)
                return
            # anything else is the generation API (/api canonically; the
            # pre-fleet server accepted any path, kept for compatibility)
            t0 = time.monotonic()
            status = "500"
            try:
                req = self._read_json()
                payload = service.handle(req)
                status = "200"
                self._reply(200, payload)
            except ServiceDrainingError as e:
                # SIGTERM grace or a rolling update: fast 503 the router
                # re-routes; Retry-After hints standalone clients
                status = "503"
                self._reply(503, {"message": str(e), "draining": True},
                            headers=(("Retry-After",
                                      str(RETRY_AFTER_SECONDS)),))
            except EngineOverloadedError as e:
                # bounded admission (--serve_max_queue): overload degrades
                # to fast 503s clients can back off on, not queue latency
                status = "503"
                self._reply(503, {"message": str(e)},
                            headers=(("Retry-After",
                                      str(RETRY_AFTER_SECONDS)),))
            except RequestTimeoutError as e:
                # expired deadline (deadline_s / --serve_request_timeout):
                # the client's budget is spent — the router passes 504
                # through rather than retrying on its behalf
                status = "504"
                self._reply(504, {"message": str(e), "timeout": True})
            except ValueError as e:
                status = "400"
                self._reply(400, {"message": str(e)})
            except Exception as e:  # noqa: BLE001 — server must not die
                self._reply(500, {"message": f"internal error: {e}"})
            finally:
                service._m_requests.inc(status=status)
                service._m_latency.observe(time.monotonic() - t0)

        def _handle_admin(self, path: str):
            from megatron_tpu.inference.fleet.migration import (
                MigrationIntegrityError,
            )
            from megatron_tpu.inference.fleet.reload import (
                NoValidCheckpointError,
            )

            if path in ("/admin/import", "/admin/import_prefix"):
                # migration frames are binary (manifest + crc contract,
                # fleet/migration.py) — read raw, never through JSON
                try:
                    blob = self._read_body()
                    if path == "/admin/import":
                        self._reply(200, service.import_state(blob))
                    else:
                        self._reply(200, service.import_prefix_blob(blob))
                except MigrationIntegrityError as e:
                    # torn/corrupt transfer: the exporter walks down its
                    # degradation ladder on this status
                    self._reply(409, {"message": str(e), "torn": True})
                except ServiceDrainingError as e:
                    self._reply(503, {"message": str(e)},
                                headers=(("Retry-After",
                                          str(RETRY_AFTER_SECONDS)),))
                except RequestTimeoutError as e:
                    self._reply(504, {"message": str(e), "timeout": True})
                except ValueError as e:
                    self._reply(400, {"message": str(e)})
                except Exception as e:  # noqa: BLE001 — server must not die
                    self._reply(500, {"message": f"admin failed: {e}"})
                return
            try:
                req = self._read_json()
            except ValueError:
                self._reply(400, {"message": "admin body must be JSON"})
                return
            try:
                if path == "/admin/drain":
                    drained = service.drain(
                        float(req.get("timeout_s", 30.0)),
                        handoff_urls=req.get("handoff"))
                    self._reply(200, {"drained": drained, "draining": True})
                elif path == "/admin/readmit":
                    service.readmit()
                    self._reply(200, {"draining": False})
                elif path == "/admin/reload":
                    version = service.reload(
                        load=req.get("load"),
                        iteration=req.get("iteration"))
                    self._reply(200, {"version": version})
                elif path == "/admin/profile":
                    from urllib.parse import parse_qs, urlsplit

                    q = parse_qs(urlsplit(self.path).query)
                    steps = req.get("steps", q.get("steps", ["4"])[0])
                    timeout_s = req.get(
                        "timeout_s", q.get("timeout_s", ["30"])[0])
                    try:
                        self._reply(200, service.profile(
                            steps=int(steps), timeout_s=float(timeout_s),
                            out_dir=req.get("dir")))
                    except RuntimeError as e:
                        # another capture owns the process-global
                        # profiler session: conflict, retry later
                        self._reply(409, {"message": str(e)})
                elif path == "/admin/export_prefix":
                    blob = service.export_prefix_blob(
                        req.get("tokens") or [])
                    if blob is None:
                        self._reply(404,
                                    {"message": "prefix not cached here"})
                    else:
                        self._reply_blob(blob)
                elif path == "/admin/register_prefix":
                    self._reply(200, service.register_prefix(
                        req.get("tokens") or []))
                else:
                    self._reply(404, {"message": "POST /admin/"
                                      "{drain,readmit,reload,profile,"
                                      "import,export_prefix,"
                                      "import_prefix,register_prefix}"})
            except NoValidCheckpointError as e:
                # no verifiable committed checkpoint: an operator/ckpt
                # problem, not a server fault — 409 so the router's
                # rolling update stops and readmits the old weights
                self._reply(409, {"message": str(e)})
            except ValueError as e:
                self._reply(400, {"message": str(e)})
            except Exception as e:  # noqa: BLE001 — server must not die
                self._reply(500, {"message": f"admin failed: {e}"})

        do_PUT = _handle
        do_POST = _handle

        def do_GET(self):
            # observability endpoints (Prometheus scrape + probes); the
            # generation API stays PUT/POST /api
            path = self.path.split("?", 1)[0]
            if path == "/metrics":
                body = service.metrics.render().encode()
                self.send_response(200)
                self.send_header("Content-Type", PROMETHEUS_CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif path == "/healthz":
                # liveness: "the process + step loop exist" — stays green
                # through drains/reloads so an orchestrator doesn't kill a
                # replica that's merely finishing in-flight work
                alive = (service.engine is None
                         or service.engine._thread is None
                         or service.engine._thread.is_alive())
                self._reply(200 if alive else 500,
                            {"ok": bool(alive),
                             "engine": service.engine is not None})
            elif path == "/readyz":
                ok, detail = service.ready()
                self._reply(200 if ok else 503, detail)
            elif path == "/admin/status":
                self._reply(200, service.admin_status())
            else:
                self._reply(404, {"message": "GET serves /metrics, "
                                             "/healthz, /readyz, "
                                             "/admin/status; the API is "
                                             "PUT/POST /api"})

        def log_message(self, *a):  # quiet
            pass

    return Handler


def run_server(cfg: ModelConfig, params: Any, tokenizer,
               host: str = "0.0.0.0", port: int = 5000,
               mesh=None, forward_fn=None, kv_cache_int8=False,
               engine_slots: int = 0, engine_max_seq_len=None,
               engine_max_queue: Optional[int] = None,
               kv_paging: bool = False, page_size: int = 16,
               prefill_chunk: int = 32,
               num_pages: Optional[int] = None,
               request_timeout: Optional[float] = None,
               drain_timeout: float = 30.0,
               warmup: bool = False,
               port_file: Optional[str] = None,
               reload_dir: Optional[str] = None,
               weights_version: Optional[int] = None,
               stall_threshold_s: float = STALL_THRESHOLD_SECONDS,
               speculative: Optional[str] = None,
               spec_k: int = 4,
               draft_cfg=None, draft_params=None,
               profile_dir: Optional[str] = None,
               compress_collectives: str = "none",
               comm_policy: Optional[str] = None,
               cp_serving: bool = False,
               cp_collectives: str = "dense",
               cp_comm_policy: Optional[str] = None,
               cp_geometry: str = "ring",
               cp_subgroup: int = 0,
               cp_overlap: bool = True,
               cp_lanes: int = 1,
               peers: Optional[list] = None) -> None:
    """Serve until killed. SIGTERM/SIGINT triggers a graceful drain
    (mirroring DistributedSignalHandler): stop admitting (503 +
    Retry-After), finish in-flight requests up to `drain_timeout`, then
    exit cleanly; a second signal force-exits 128+signum immediately.
    With `peers` configured the drain first HANDS OFF in-flight and
    queued requests to those replicas over the KV migration fabric
    (docs/fault_tolerance.md "Serving state migration") — a preempted
    replica costs zero failed requests, not one retry per client.
    port=0 binds an ephemeral port; `port_file` (fleet subprocess
    choreography) publishes the bound port as {"port": N} once listening.
    warmup=True compiles the decode step before /readyz goes green."""
    service = GenerationService(cfg, params, tokenizer, mesh=mesh,
                                forward_fn=forward_fn,
                                kv_cache_int8=kv_cache_int8,
                                engine_slots=engine_slots,
                                engine_max_seq_len=engine_max_seq_len,
                                engine_max_queue=engine_max_queue,
                                kv_paging=kv_paging, page_size=page_size,
                                prefill_chunk=prefill_chunk,
                                num_pages=num_pages,
                                request_timeout=request_timeout,
                                reload_dir=reload_dir,
                                weights_version=weights_version,
                                stall_threshold_s=stall_threshold_s,
                                warmup=warmup,
                                speculative=speculative, spec_k=spec_k,
                                draft_cfg=draft_cfg,
                                draft_params=draft_params,
                                profile_dir=profile_dir,
                                compress_collectives=compress_collectives,
                                comm_policy=comm_policy,
                                cp_serving=cp_serving,
                                cp_collectives=cp_collectives,
                                cp_comm_policy=cp_comm_policy,
                                cp_geometry=cp_geometry,
                                cp_subgroup=cp_subgroup,
                                cp_overlap=cp_overlap,
                                cp_lanes=cp_lanes,
                                peers=peers)
    server = ThreadingHTTPServer((host, port), make_handler(service))
    bound_port = server.server_address[1]
    if port_file:
        # atomic publish: the parent polls this file — it must never read
        # a torn write
        tmp = port_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"port": bound_port, "pid": os.getpid()}, f)
        os.replace(tmp, port_file)

    received: list = []

    def _graceful(signum, frame):
        if received:
            # second signal: the drain is presumed wedged — die NOW,
            # unmaskably (DistributedSignalHandler semantics)
            sys.stderr.write(
                f"received {signal.Signals(signum).name} after "
                f"{signal.Signals(received[0]).name}; forcing exit "
                "without waiting for drain\n")
            sys.stderr.flush()
            os._exit(128 + signum)
        received.append(signum)

        def _shutdown():
            drained = service.drain(drain_timeout)
            print(f"drain {'complete' if drained else 'TIMED OUT'}; "
                  "shutting down", flush=True)
            server.shutdown()

        # drain off-signal-context: a handler must not block for seconds
        threading.Thread(target=_shutdown, daemon=True,
                         name="drain-on-signal").start()

    if threading.current_thread() is threading.main_thread():
        for s in (signal.SIGTERM, signal.SIGINT):
            signal.signal(s, _graceful)

    if warmup and service.engine is not None:
        # compile on a side thread so serve_forever answers probes (503,
        # not connection timeouts) during the warmup

        def _warmup():
            try:
                service.warmup()
            except Exception as e:  # noqa: BLE001 - a failed warmup keeps
                # readiness red (correct: don't route here) but the reason
                # must reach the log, not die with the thread
                sys.stderr.write(f"warmup failed: {e}\n")
                sys.stderr.flush()

        threading.Thread(target=_warmup, daemon=True,
                         name="serve-warmup").start()

    mode = (f"continuous batching, {engine_slots} slots"
            + (", paged KV + prefix cache" if kv_paging else "")
            + (f", context-parallel KV (cp="
               f"{getattr(service.engine, 'cp', 0)}, "
               f"{cp_geometry}"
               + (f" sub={cp_subgroup}" if cp_geometry == "2d" else "")
               + f" {'overlapped' if cp_overlap else 'serial'} "
               f"{getattr(getattr(service.engine, 'cp_comm', None), 'mode', '?')}"
               + (f", {cp_lanes} lanes" if cp_lanes > 1 else "")
               + ")"
               if cp_serving else "")
            + (f", speculative ({speculative}, k={spec_k})"
               if speculative else "")
            + (f", compressed collectives ({service.engine.tp_comm.mode}, "
               f"sites {sorted(service.engine.tp_comm.sites)})"
               if getattr(service.engine, "tp_comm", None) is not None
               else "")
            if service.engine else "one-shot")
    print(f"serving generation API on http://{host}:{bound_port}/api "
          f"({mode})", flush=True)
    try:
        server.serve_forever()
    finally:
        server.server_close()
        service.shutdown()

"""Autoregressive generation with functional KV caches.

Equivalent of megatron/text_generation/generation.py (429 LoC) +
forward_step.py (204): the reference's InferenceParams KV-cache dict and
token-at-a-time pipeline become a jitted lax.while_loop whose carry holds
the stacked per-layer caches; prompts of different lengths are handled the
reference's way — decode starts at the shortest prompt and forced prompt
tokens override samples until each row's prompt is exhausted
(generation.py:89-287 generate_tokens_probs_and_return_on_first_stage).

Early termination on EOD ends the while_loop when every row is done, so
short generations don't pay for max_new_tokens steps.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from megatron_tpu.config import ModelConfig
from megatron_tpu.inference.sampling import sample_logits
from megatron_tpu.models.language_model import lm_forward


@dataclasses.dataclass
class GenerationOutput:
    tokens: np.ndarray       # [B, total_len] int32 (prompt + generated)
    lengths: np.ndarray      # [B] generated sequence end (index past last)
    logprobs: np.ndarray     # [B, total_len-1] logprob of each emitted token


def _init_caches(cfg: ModelConfig, batch: int, total_len: int,
                 int8: bool = False):
    shape = (cfg.num_layers, batch, total_len, cfg.n_kv_heads, cfg.head_dim)
    if int8:
        # (k_q, v_q, k_scale, v_scale) — half the bytes of a bf16 cache;
        # format is detected by tuple arity in attention_block
        sshape = shape[:-1] + (1,)
        return (jnp.zeros(shape, jnp.int8), jnp.zeros(shape, jnp.int8),
                jnp.zeros(sshape, jnp.float32), jnp.zeros(sshape, jnp.float32))
    return (jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype))


def _default_fwd(cfg):
    """forward_fn contract: (params, tokens, positions, caches,
    cache_index) -> (logits, caches). Default = the single-stage cached
    lm_forward; pipelined.make_pipelined_lm_forward provides the pp>1
    version (ref forward_step.py:45-204)."""
    def fwd(params, toks, positions, caches, cache_index):
        return lm_forward(cfg, params, toks, positions=positions,
                          kv_caches=caches, cache_index=cache_index)
    return fwd


@partial(jax.jit, static_argnames=("cfg", "total_len", "prefill_len",
                                   "temperature", "top_k",
                                   "top_p", "vocab_size", "eod",
                                   "want_logprobs", "forward_fn",
                                   "kv_cache_int8"))
def _generate_jit(
    cfg: ModelConfig,
    params: Any,
    tokens: jnp.ndarray,    # [B, total_len], prompt tokens then pad
    lengths: jnp.ndarray,   # [B] prompt lengths
    key: jax.Array,
    total_len: int,
    prefill_len: int,
    temperature: float,
    top_k: int,
    top_p: float,
    vocab_size: Optional[int],
    eod: Optional[int],
    want_logprobs: bool = True,
    forward_fn=None,
    kv_cache_int8: bool = False,
):
    fwd = forward_fn or _default_fwd(cfg)
    B = tokens.shape[0]
    min_len = jnp.min(lengths)
    caches = _init_caches(cfg, B, total_len, int8=kv_cache_int8)

    # Prefill the prompt region in one pass — the reference likewise batches
    # the common prompt prefix. min_len is dynamic, so the prefill runs a
    # *static* bucketed length covering every prompt (>= max prompt length,
    # rounded up by the caller so a 5-token prompt with 2000 new tokens does
    # not pay a 2000-position prefill); decode overwrites cache entries for
    # positions it re-runs, with identical forced-token values.
    positions = jnp.arange(total_len)[None, :]
    logits_all, caches = fwd(params, tokens[:, :prefill_len],
                             positions[:, :prefill_len], caches, 0)

    # the full-prefill fp32 log_softmax ([B, S, V]) is only paid when the
    # caller wants per-token logprobs
    logprobs_all = (jax.nn.log_softmax(logits_all.astype(jnp.float32), axis=-1)
                    if want_logprobs else None)

    # carry: (t, tokens, caches, done, key, logprobs, last_logits)
    def body2(carry):
        t, tokens, caches, done, key, lp, last_logits = carry
        key, sub = jax.random.split(key)
        prev_logits = last_logits[:, 0]
        sampled = sample_logits(prev_logits, sub, temperature, top_k, top_p,
                                vocab_size)
        in_prompt = t < lengths
        forced = tokens[:, t]
        nxt = jnp.where(in_prompt | done, forced, sampled)
        if eod is not None:
            nxt = jnp.where(done, eod, nxt)
        tokens = tokens.at[:, t].set(nxt)
        step_lp = jnp.take_along_axis(
            jax.nn.log_softmax(prev_logits.astype(jnp.float32), axis=-1),
            nxt[:, None], axis=-1)[:, 0]
        lp = lp.at[:, t - 1].set(jnp.where(done, 0.0, step_lp))
        if eod is not None:
            done = done | ((nxt == eod) & ~in_prompt)
        step_pos = jax.lax.dynamic_slice_in_dim(positions, t, 1, axis=1)
        logits_step, caches = fwd(params, nxt[:, None], step_pos, caches, t)
        return (t + 1, tokens, caches, done, key, lp, logits_step)

    def cond2(carry):
        t, tokens, caches, done, key, lp, last = carry
        return (t < total_len) & ~jnp.all(done)

    # seed the loop at t = min_len with the prefill logits at min_len-1
    gather_idx = jnp.maximum(min_len - 1, 0)
    first_logits = jnp.take_along_axis(
        logits_all, jnp.full((B, 1, 1), gather_idx), axis=1)

    # teacher-forced logprobs for the prompt region
    lp0 = jnp.zeros((B, total_len - 1), jnp.float32)
    if want_logprobs:
        prompt_lp = jnp.take_along_axis(
            logprobs_all, tokens[:, 1:prefill_len + 1][..., None],
            axis=-1)[..., 0]
        valid = (jnp.arange(1, prefill_len + 1)[None, :] < lengths[:, None])
        lp0 = lp0.at[:, :prefill_len].set(jnp.where(valid, prompt_lp, 0.0))

    done0 = jnp.zeros((B,), bool)
    carry = (min_len, tokens, caches, done0, key, lp0, first_logits)
    t, tokens, caches, done, key, lp, _ = jax.lax.while_loop(cond2, body2, carry)

    if eod is not None:
        has_eod = jnp.any(
            (tokens == eod)
            & (jnp.arange(total_len)[None, :] >= lengths[:, None]), axis=1)
        first_eod = jnp.argmax(
            (tokens == eod)
            & (jnp.arange(total_len)[None, :] >= lengths[:, None]), axis=1)
        ends = jnp.where(has_eod, first_eod + 1, total_len)
    else:
        ends = jnp.full((B,), total_len)
    return tokens, ends, lp


def generate_tokens(
    cfg: ModelConfig,
    params: Any,
    prompts: np.ndarray,     # [B, max_prompt_len] int32, right-padded
    lengths: np.ndarray,     # [B]
    max_new_tokens: int,
    temperature: float = 1.0,
    top_k: int = 0,
    top_p: float = 0.0,
    vocab_size: Optional[int] = None,
    eod: Optional[int] = None,
    seed: int = 0,
    want_logprobs: bool = True,
    forward_fn=None,
    kv_cache_int8: bool = False,
) -> GenerationOutput:
    if kv_cache_int8 and forward_fn is not None:
        raise ValueError(
            "kv_cache_int8 is supported on the single-stage forward only "
            "(the pipelined pp>1 forward threads bf16 cache pairs)")
    B, max_prompt = prompts.shape
    total_len = max_prompt + max_new_tokens
    if (cfg.position_embedding_type == "absolute"
            and total_len > (cfg.max_position_embeddings or 0)):
        raise ValueError(
            f"prompt + tokens_to_generate = {total_len} exceeds "
            f"max_position_embeddings {cfg.max_position_embeddings} — "
            "absolute position embeddings would silently clamp")
    tokens = np.zeros((B, total_len), np.int32)
    tokens[:, :max_prompt] = prompts
    # bucketed static prefill length: covers the longest prompt, rounded up
    # to 64 so nearby prompt lengths share a compile
    prefill_len = min(total_len - 1, max(1, -(-max_prompt // 64) * 64))
    toks, ends, lp = _generate_jit(
        cfg, params, jnp.asarray(tokens), jnp.asarray(lengths, jnp.int32),
        jax.random.PRNGKey(seed), total_len, prefill_len, float(temperature),
        int(top_k), float(top_p), vocab_size, eod, want_logprobs,
        forward_fn, bool(kv_cache_int8))
    return GenerationOutput(tokens=np.asarray(toks), lengths=np.asarray(ends),
                            logprobs=np.asarray(lp))


def score_tokens(cfg: ModelConfig, params: Any, tokens: np.ndarray) -> np.ndarray:
    """Teacher-forced per-token logprobs [B, S-1]
    (ref: score_and_return_on_first_stage)."""
    t = jnp.asarray(tokens, jnp.int32)
    logits = lm_forward(cfg, params, t[:, :-1])
    lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    out = jnp.take_along_axis(lp, t[:, 1:][..., None], axis=-1)[..., 0]
    return np.asarray(out)


def beam_search_tokens(
    cfg: ModelConfig,
    params: Any,
    prompt: np.ndarray,       # [prompt_len] single prompt (ref: batch=1 only)
    max_new_tokens: int,
    beam_size: int,
    eod: int,
    length_penalty: float = 1.0,
    kv_cache_int8: bool = False,
) -> Tuple[np.ndarray, np.ndarray]:
    """Beam search for one prompt (the reference's beam path also requires
    batch 1, text_generation/api.py:147). Host-side loop over a jitted
    scoring step; returns (beams [beam_size, total], scores [beam_size]).
    The per-beam cache gathers are tree-mapped, so the int8 cache tuple
    flows through unchanged."""
    prompt = np.asarray(prompt, np.int32)
    plen = len(prompt)
    total = plen + max_new_tokens

    # Incremental decode on the same cached path as sampling (ref beam
    # search shares the cached ForwardStep, text_generation/generation.py:288):
    # prefill the prompt once at batch 1, tile the caches across beams, then
    # one single-token forward per emitted token with per-beam cache
    # reordering (gather over the batch axis) at each step.
    caches = _init_caches(cfg, 1, total, int8=kv_cache_int8)
    prefill_logits, caches = lm_forward(
        cfg, params, jnp.asarray(prompt)[None, :],
        positions=jnp.arange(plen)[None, :], kv_caches=caches, cache_index=0)
    caches = jax.tree.map(lambda c: jnp.repeat(c, beam_size, axis=1), caches)
    step_logits_dev = jnp.repeat(prefill_logits[:, -1], beam_size, axis=0)

    @jax.jit
    def decode_step(caches, parents, toks, t):
        caches = jax.tree.map(lambda c: jnp.take(c, parents, axis=1), caches)
        pos = jnp.full((beam_size, 1), t, jnp.int32)
        logits, caches = lm_forward(cfg, params, toks[:, None], positions=pos,
                                    kv_caches=caches, cache_index=t)
        return logits[:, 0], caches

    beams = np.tile(prompt[None, :], (beam_size, 1))
    scores = np.full((beam_size,), -1e9, np.float64)
    scores[0] = 0.0
    finished = []  # (score_with_penalty, tokens) — BeamHypotheses equivalent

    for t in range(plen, total):
        logits = np.asarray(step_logits_dev, np.float64)
        logprobs = logits - np.log(np.exp(logits - logits.max(-1, keepdims=True))
                                   .sum(-1, keepdims=True)) - logits.max(-1, keepdims=True)
        cand = scores[:, None] + logprobs  # [beams, V]
        flat = cand.reshape(-1)
        top = np.argpartition(-flat, 2 * beam_size)[: 2 * beam_size]
        top = top[np.argsort(-flat[top])]
        new_beams, new_scores, parents, new_toks = [], [], [], []
        for idx in top:
            b, v = divmod(int(idx), logits.shape[-1])
            seq = np.concatenate([beams[b], [v]])
            if v == eod:
                penalty = ((len(seq) - plen) ** length_penalty)
                finished.append((flat[idx] / penalty, seq))
            else:
                new_beams.append(seq)
                new_scores.append(flat[idx])
                parents.append(b)
                new_toks.append(v)
            if len(new_beams) == beam_size:
                break
        beams = np.stack([np.pad(s, (0, total - len(s))) for s in new_beams])[:, :t + 1]
        scores = np.asarray(new_scores)
        if len(finished) >= beam_size:
            best_possible = scores.max() / (max(1, t + 1 - plen) ** length_penalty)
            worst_kept = sorted(finished, key=lambda x: -x[0])[beam_size - 1][0]
            if worst_kept >= best_possible:
                break
        if t + 1 < total:
            step_logits_dev, caches = decode_step(
                caches, jnp.asarray(parents, jnp.int32),
                jnp.asarray(new_toks, jnp.int32), jnp.int32(t))

    for s, b in zip(scores, beams):
        penalty = (max(1, beams.shape[1] - plen) ** length_penalty)
        finished.append((s / penalty, np.concatenate([b, [eod]])))
    finished.sort(key=lambda x: -x[0])
    finished = finished[:beam_size]
    out_tokens = np.stack([np.pad(f[1], (0, total + 1 - len(f[1])),
                                  constant_values=eod) for f in finished])
    out_scores = np.asarray([f[0] for f in finished])
    return out_tokens, out_scores

"""Continuous-batching decode engine with a persistent slot-based KV cache.

The one-shot path (generation.py) allocates a dense [B, L, S, H] cache per
call and serves one request at a time — decode utilization collapses to a
single sequence's matmul. This engine owns ONE long-lived cache shaped
[L, num_slots, S, H, D] (optionally int8, ops/kv_quant.py) and runs a step
loop: every tick it admits queued requests into free slots (a bucketed
prefill writes the slot's rows) and then executes ONE batched single-token
decode for all slots — one jit-compiled step reused across traffic, no
recompiles after warmup. Sequences of different ages coexist because the
attention path masks each slot to its own valid prefix (per-slot lengths;
ops/attention.py kv_lengths, Pallas flash-decode on TPU).

Per-request sampling params (temperature/top_k/top_p) are traced [N]
arrays, not static — heterogeneous traffic shares the same compiled step
(sampling.sample_logits_batched). Each request carries its own PRNG chain
keyed off its seed, so a request's tokens never depend on which other
slots happen to be active (the interleaved-traffic parity invariant;
tests/test_serving_engine.py).

Greedy parity gate: a single request decoded through the engine is
token-identical to generation.generate_tokens — prefill logits come from
the same bucketed causal pass, and masking a decode step to the valid
prefix contributes exact zeros to the softmax, so the math matches
bit-for-bit.
"""

from __future__ import annotations

import contextlib
import dataclasses
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from megatron_tpu.config import ModelConfig
from megatron_tpu.inference.generation import GenerationOutput, _init_caches
from megatron_tpu.inference.sampling import sample_logits_batched
from megatron_tpu.telemetry import journal as _journal
from megatron_tpu.telemetry.metrics import MetricsRegistry, default_registry
from megatron_tpu.training import resilience

#: flash_decode (ops/pallas/flash_decode.py) requires the cache length
#: divisible by this; engines round max_seq_len UP to it on the TPU
#: kernel path so the fused kernel is never silently lost to the dense
#: fallback (the _pick_block -> ValueError -> dispatcher chain).
KERNEL_SEQ_MULTIPLE = 128

#: jax's profiler session is process-global (one trace at a time), so
#: on-demand captures serialize here — a second /admin/profile while one
#: is running answers 409 instead of corrupting the live session
_PROFILE_LOCK = threading.Lock()


class EngineOverloadedError(RuntimeError):
    """The engine's admission queue is at max_queue: the request was
    rejected, not queued. HTTP serving maps this to 503 + Retry-After."""


class RequestTimeoutError(RuntimeError):
    """A request's deadline expired while it was queued or mid-decode.
    HTTP serving maps this to 504 Gateway Timeout; the fleet router treats
    it as non-retryable (the client's budget is spent either way)."""


@dataclasses.dataclass
class Request:
    """One sequence's lifecycle through the engine."""
    prompt: np.ndarray                 # [p] int32 token ids
    max_new_tokens: int
    temperature: float = 0.0           # 0 = greedy
    top_k: int = 0
    top_p: float = 0.0
    eod: Optional[int] = None
    seed: int = 0
    # relative deadline: seconds after submit() by which the request must
    # COMPLETE. A queued or mid-decode request past it fails with
    # timed_out=True (HTTP 504) — waiters on done.wait() are signalled in
    # bounded time instead of waiting on an abandoned request forever,
    # which also bounds the router's retry worst case. None = no deadline.
    deadline_s: Optional[float] = None
    # engine-filled
    generated: List[int] = dataclasses.field(default_factory=list)
    logprobs: List[float] = dataclasses.field(default_factory=list)
    # per-request speculative-decoding knob: False pins this request to
    # one token per tick even on a speculating engine (its greedy output
    # is bit-identical either way; the knob exists for traffic classes
    # that want the lowest per-token latency variance). Ignored when the
    # engine was built without `speculative=`.
    spec: bool = True
    # preemption/resume (paged engine): the PRNG chain state at
    # preemption, so a recompute-resumed request samples the exact
    # tokens it would have sampled without the preemption
    resume_key: Optional[np.ndarray] = None
    # queue-overload rejection marker (submit with max_queue exceeded)
    overloaded: bool = False
    # deadline-expiry marker (engine-set; error carries the detail)
    timed_out: bool = False
    # absolute monotonic deadline (engine-stamped at submit)
    _deadline: Optional[float] = None
    # teacher-forced logprobs of prompt[1:] from the admission prefill
    # (the one-shot path returns these too; generation.py:136-141)
    prompt_logprobs: List[float] = dataclasses.field(default_factory=list)
    done: threading.Event = dataclasses.field(default_factory=threading.Event)
    error: Optional[str] = None
    # latency telemetry (monotonic clock): stamped by submit()/admission
    submit_time: Optional[float] = None
    first_token_time: Optional[float] = None

    @property
    def tokens(self) -> np.ndarray:
        """prompt + generated (eod included when emitted)."""
        return np.concatenate(
            [np.asarray(self.prompt, np.int32),
             np.asarray(self.generated, np.int32)])

    def _finish(self, error: Optional[str] = None):
        self.error = error
        self.done.set()


class InferenceEngine:
    """Slot scheduler + jitted prefill/decode steps over one shared cache.

    Not thread-safe for concurrent step() calls; submit() may be called
    from any thread (the HTTP handlers), step()/run_until_idle() from one
    driver thread (start() spawns it).
    """

    def __init__(self, cfg: ModelConfig, params: Any, num_slots: int = 8,
                 max_seq_len: Optional[int] = None,
                 kv_cache_int8: bool = False, prefill_bucket: int = 64,
                 vocab_size: Optional[int] = None, mesh=None,
                 want_logprobs: bool = True,
                 metrics: Optional[MetricsRegistry] = None,
                 flight_recorder=None,
                 force_donate: Optional[bool] = None,
                 max_queue: Optional[int] = None,
                 speculative=None,
                 compress_collectives: str = "none",
                 comm_policy=None,
                 comm_chunk: int = 32):
        if num_slots < 1:
            raise ValueError("num_slots must be >= 1")
        if max_queue is not None and max_queue < 1:
            raise ValueError("max_queue must be >= 1 (or None: unbounded)")
        # force_donate: override the backend-derived donation choice
        # (None = donate except on XLA:CPU). The jaxpr/donation auditor
        # sets True so CPU-traced audits check the TPU-shipped intent.
        self.force_donate = force_donate
        self.cfg = cfg
        self.params = params
        self.num_slots = num_slots
        self.max_queue = max_queue
        self.max_seq_len = self._round_seq_len(
            int(max_seq_len or cfg.seq_length))
        if (cfg.position_embedding_type == "absolute"
                and self.max_seq_len > (cfg.max_position_embeddings or 0)):
            raise ValueError(
                f"max_seq_len {self.max_seq_len} exceeds "
                f"max_position_embeddings {cfg.max_position_embeddings}")
        self.kv_cache_int8 = kv_cache_int8
        # migration wire codec for FLOAT caches (fleet/migration.py):
        # "raw" ships native bytes (exact); "int8"/"fp8" quantize via
        # quant/primitives.py (smaller, NOT bit-exact — importers that
        # require token identity recompute-resume instead). int8 caches
        # always ship their own quantized pages + scales verbatim
        # ("int8-native", exact). Operators set this attribute directly.
        self.kv_wire = "raw"
        self.kv_wire_chunk = 32
        self.prefill_bucket = prefill_bucket
        self.vocab_size = vocab_size
        self.mesh = mesh
        self.want_logprobs = want_logprobs
        # compressed TP collectives (quant/collectives.py,
        # --serve_compress_collectives): replace the decode forward's
        # tensor-parallel output reductions + logits gather with explicit
        # low-bit (int8/fp8) collectives. None when the flag is off or
        # the mesh's tensor axis is trivial (dense path unchanged). The
        # plan is STATIC at engine build — compiled into the decode
        # step, zero traced args, zero recompiles.
        from megatron_tpu.quant.collectives import (
            forward_comm_bytes, make_tp_comm,
        )

        self.tp_comm = make_tp_comm(mesh, compress_collectives, cfg=cfg,
                                    policy=comm_policy, chunk=comm_chunk)
        if self.tp_comm is not None and speculative is not None:
            raise ValueError(
                "compress_collectives with speculative decoding is not "
                "supported (the spec step is not threaded through the "
                "explicit TP collectives) — drop one of the two")
        # static wire-byte prices for the telemetry counters: what one
        # decode tick moves in this mode, and what the dense path would
        # have moved (their ratio IS the live compression ratio)
        self._comm_tick_bytes = forward_comm_bytes(
            cfg, self.tp_comm, num_slots, 1)
        self._comm_prefill_bytes = {}  # bucket P -> forward bytes

        N = num_slots
        # committed placement for params as well as caches: random-init
        # params (tests, bench) are UNCOMMITTED jit outputs while
        # checkpoint-loaded and hot-reloaded params (update_params) are
        # committed device_puts — without this, the first weight swap on a
        # random-init engine would split the decode step's jit cache key
        # and pay one recompile (the smoke test caught exactly that)
        self.params = self._commit(self.params)
        self.caches = self._commit_caches(self._fresh_caches())
        # speculative decoding (inference/speculative.py): k drafted
        # tokens per slot verified by ONE [N, k+1] target forward per
        # tick, exact accept/reject inside the jitted step. The draft-
        # model drafter keeps a SECOND cache tree threaded through the
        # same slot/page machinery as the target's.
        self.spec = speculative
        self.draft_params = None
        self.draft_caches = None
        self._spec_step = None
        self.spec_on = np.ones(N, bool)   # per-request knob mirror
        self._spec_rows_dev = None        # committed device copy
        if speculative is not None:
            from megatron_tpu.inference.speculative import validate_spec

            validate_spec(cfg, speculative)
            if speculative.drafter == "model":
                self.draft_params = self._commit(speculative.draft_params)
                self.draft_caches = self._commit(self._fresh_draft_caches())
        self.slots: List[Optional[Request]] = [None] * N
        self.lengths = np.zeros(N, np.int32)    # valid context per slot
        self.last_tok = np.zeros(N, np.int32)   # sampled, not yet in cache
        self.temps = np.zeros(N, np.float32)
        self.top_ks = np.zeros(N, np.int32)
        self.top_ps = np.zeros(N, np.float32)
        self.keys = np.zeros((N, 2), np.uint32)

        self._queue: deque[Request] = deque()
        self._cv = threading.Condition()
        self._thread: Optional[threading.Thread] = None
        self._stop = False
        # device-resident decode carry (last_tok, lengths, keys, temps,
        # top_ks, top_ps): steady-state ticks chain device arrays instead
        # of re-uploading 6 host arrays per token; admission events
        # invalidate it (None -> re-upload from the host mirrors)
        self._carry = None
        # hot weight reload: (params, version, applied_event) staged by
        # update_params(), swapped in BETWEEN decode ticks by the step
        # loop so in-flight slots never see a mid-tick change
        self._pending_params: Optional[tuple] = None
        self.params_version: Optional[Any] = None
        # admissions popped from the queue but not yet landed in a slot —
        # wait_idle() must not report idle while one is mid-prefill
        self._admitting = 0
        # state-migration pause (paused()): while _pause_count > 0 the
        # step loop parks BETWEEN ticks and raises _paused_evt, so an
        # exporter/importer can touch slot state without racing a tick
        self._pause_count = 0
        self._paused_evt = threading.Event()
        # once-jitted KV install writer (migration import) — separate jit
        # from the decode step, so imports cost zero decode recompiles
        self._kv_writer = None
        self._preempt_signalled = False  # preempt_replica fires once
        # last time the engine demonstrably made progress (an admission
        # or decode tick COMPLETED) — readiness uses stalled() to catch a
        # wedged step loop, the failure liveness can't see (the thread is
        # alive, just hung inside a device call)
        self.last_progress_time = time.monotonic()

        self._decode_step = self._build_decode_step()
        if self.spec is not None:
            self._spec_step = self._build_spec_step()
        self._prefill_steps = {}  # bucketed prompt length -> jitted fn
        self._draft_prefill_steps = {}  # same buckets, draft cache writes
        # observability for tests/metrics: monotonically-growing counters.
        # decode_recompiles counts decode-step compiles BEYOND the warmup
        # one — the "zero recompiles after warmup" invariant (PR 1) as a
        # runtime counter instead of a bench footnote
        self.stats = {"admitted": 0, "retired": 0, "ticks": 0,
                      "rejected": 0, "decode_recompiles": 0,
                      "timeouts": 0, "weight_reloads": 0,
                      "kv_exports": 0, "kv_imports": 0}
        if self.spec is not None:
            # spec_emitted counts every token the spec path emitted
            # (accepted drafts + the guaranteed token per row per tick);
            # spec_emitted / ticks = effective tokens per target forward
            self.stats.update({"spec_proposed": 0, "spec_accepted": 0,
                               "spec_emitted": 0})
        self._decode_cache_seen = 0  # compiles observed on _decode_step

        # Prometheus collectors (megatron_tpu/telemetry): shared with the
        # serving HTTP layer via the process-default registry unless a
        # test hands in its own. Flight recorder (optional): heartbeat
        # per tick so a wedged device step dumps a stall bundle.
        self.flight_recorder = flight_recorder
        m = metrics if metrics is not None else default_registry()
        self.metrics = m
        self._m_slots = m.gauge("engine_slots_total", "KV-cache slots")
        self._m_active = m.gauge("engine_slots_active",
                                 "slots with a live request")
        self._m_queue = m.gauge("engine_queue_depth",
                                "requests waiting for a slot")
        self._m_admitted = m.counter("engine_requests_admitted_total",
                                     "requests admitted into a slot")
        self._m_retired = m.counter("engine_requests_retired_total",
                                    "requests completed")
        self._m_rejected = m.counter("engine_requests_rejected_total",
                                     "requests rejected (invalid/oversized/"
                                     "failed prefill/queue full)")
        self._m_timeouts = m.counter(
            "engine_requests_timeout_total",
            "requests failed on an expired deadline (queued or mid-decode)")
        self._m_reloads = m.counter(
            "engine_weight_reloads_total",
            "hot weight swaps applied between decode ticks")
        self._m_ticks = m.counter("engine_ticks_total",
                                  "batched decode steps executed")
        self._m_tokens = m.counter("engine_tokens_generated_total",
                                   "tokens sampled across all requests")
        self._m_recompiles = m.counter(
            "engine_decode_recompiles_total",
            "decode-step compiles beyond warmup (invariant: 0)")
        self._m_ttft = m.histogram("engine_ttft_seconds",
                                   "submit -> first generated token")
        self._m_per_token = m.histogram(
            "engine_time_per_output_token_seconds",
            "per-request decode latency per generated token")
        self._m_prefill = m.histogram("engine_prefill_seconds",
                                      "admission prefill wall time")
        self._m_tick = m.histogram("engine_decode_tick_seconds",
                                   "batched decode tick wall time")
        self._m_spec_proposed = m.counter(
            "engine_spec_proposed_total",
            "draft tokens proposed to the speculative verify step")
        self._m_spec_accepted = m.counter(
            "engine_spec_accepted_total",
            "draft tokens accepted by the exact accept/reject")
        self._m_kv_exports = m.counter(
            "engine_kv_exports_total",
            "request states exported for migration")
        self._m_kv_imports = m.counter(
            "engine_kv_imports_total",
            "migrated request states imported, by resume path",
            label_names=("path",))
        self._m_spec_len = m.histogram(
            "engine_spec_accept_length",
            "accepted drafts per slot per tick (0..k)",
            buckets=(0.5, 1.5, 2.5, 3.5, 4.5, 6.5, 8.5, 12.5, 16.5))
        # compressed-collective accounting (quant/): dense = the bytes a
        # dense TP engine would have moved for the same work, compressed
        # = what this mode moves; dense/compressed = live compression
        # ratio (tools/telemetry_report.py serving section)
        self._m_comm_dense = m.counter(
            "engine_comm_dense_bytes_total",
            "TP-collective wire bytes the dense path would have moved")
        self._m_comm_compressed = m.counter(
            "engine_comm_compressed_bytes_total",
            "TP-collective wire bytes actually moved by this mode")
        if self.tp_comm is not None:
            self.stats.update({"comm_dense_bytes": 0,
                               "comm_compressed_bytes": 0})
            self._journal_comm_policy()
        self._m_slots.set(num_slots)

    # ----- cache + shape policy -------------------------------------------

    def _kernel_seq_multiple(self) -> int:
        """Cache-length divisibility the TPU decode kernel needs. The
        dense flash-decode kernel rejects caches not divisible by 128
        (_pick_block -> ValueError) and the dispatcher then SILENTLY
        falls back to the masked-einsum path — so engines round up
        instead of quietly losing the kernel. 1 = no constraint (CPU
        hosts interpret the kernel; the paged engine's grid is per-page
        and overrides this)."""
        if (self.cfg.attention_impl == "pallas"
                and jax.default_backend() != "cpu"):
            return KERNEL_SEQ_MULTIPLE
        return 1

    def _round_seq_len(self, n: int) -> int:
        m = self._kernel_seq_multiple()
        if m <= 1 or n % m == 0:
            return n
        rounded = -(-n // m) * m
        import warnings

        warnings.warn(
            f"engine max_seq_len {n} is not a multiple of {m}; rounding "
            f"up to {rounded} so the fused flash-decode kernel stays "
            "usable (a non-divisible cache would silently run the dense "
            "fallback every tick)", stacklevel=3)
        return rounded

    def _fresh_caches(self):
        """Host-built zeroed KV storage (overridden by the paged engine
        to build page pools instead of per-slot rows)."""
        return _init_caches(self.cfg, self.num_slots, self.max_seq_len,
                            int8=self.kv_cache_int8)

    def _fresh_draft_caches(self):
        """The draft model's second cache tree (speculative decoding,
        drafter='model'): same slots and length as the target cache,
        the draft config's own layer/head geometry, always bf16/f32 —
        the draft is small, quantizing it would buy little and cost a
        second quantization seam. Paged engine overrides with pools."""
        return _init_caches(self.spec.draft_cfg, self.num_slots,
                            self.max_seq_len, int8=False)

    def _rebuild_caches(self):
        """Replace every donated cache tree after a failed device call
        may have consumed the old buffers (prefill/decode failure
        recovery). Cached prefixes and draft state die with them."""
        self.caches = self._commit_caches(self._fresh_caches())
        if self.draft_caches is not None:
            self.draft_caches = self._commit(self._fresh_draft_caches())

    def _capacity_margin(self) -> int:
        """Sequence-capacity headroom a speculating engine reserves: a
        tick writes K/V at positions length..length+k, so the LAST tick
        of a request (length = prompt + max_new - 1) must still fit k
        more positions — admission rejects prompt + max_new past
        max_seq_len - k. 0 when speculation is off."""
        return self.spec.k if self.spec is not None else 0

    # ----- jitted device steps --------------------------------------------

    def _donate(self):
        # donate the persistent cache so each step updates it in place
        # (the whole point of a slot cache); XLA:CPU can't donate and
        # would warn every compile
        if self.force_donate is not None:
            return (1,) if self.force_donate else ()
        return (1,) if jax.default_backend() != "cpu" else ()

    def _commit(self, tree):
        """Place host-built arrays COMMITTED on the device, so a step's
        first call (host-uploaded carry/caches) and its steady state
        (jit outputs, always committed) share ONE jit cache entry. With
        any committed argument in the mix — which checkpoint-loaded
        params always are — mixed committedness otherwise splits the
        decode step into two compiled signatures, i.e. a wasted compile
        per engine that the decode_recompiles counter flags (and did:
        that is how this path was found). Mesh-ambient engines leave
        placement to GSPMD, as before."""
        if self.mesh is not None:
            return tree
        sharding = jax.sharding.SingleDeviceSharding(jax.devices()[0])
        return jax.tree.map(lambda a: jax.device_put(a, sharding), tree)

    def _kv_sharding(self):
        """Cache-leaf placement on a mesh engine: every cache leaf is
        5-D with kv_heads at axis 3 (dense rows, paged pools, and their
        int8 scale companions alike), sharded over "tensor" when it
        divides — matching the column-parallel wk/wv head sharding so
        cache writes stay local. None on mesh-less engines."""
        if self.mesh is None:
            return None
        from jax.sharding import NamedSharding, PartitionSpec as P

        tp = dict(self.mesh.shape).get("tensor", 1)
        if tp > 1 and self.cfg.n_kv_heads % tp == 0:
            return NamedSharding(self.mesh, P(None, None, None, "tensor",
                                              None))
        return NamedSharding(self.mesh, P())

    def _commit_caches(self, tree):
        """Mesh engines pin the cache layout explicitly (and the decode/
        prefill jits pin it back via out_shardings): without this the
        first tick's host-uploaded caches and the steady state's jit
        outputs split the decode step's cache key — the same wasted
        compile _commit fixes for single-device engines, which mesh
        engines used to pay (1 decode recompile after warmup)."""
        if self.mesh is None:
            return self._commit(tree)
        sh = self._kv_sharding()
        return jax.tree.map(lambda a: jax.device_put(a, sh), tree)

    def _commit_small(self, tree):
        """Committed replicated placement for the decode carry / page
        tables / knob rows on mesh engines (single-device engines: the
        ordinary commit)."""
        if self.mesh is None:
            return self._commit(tree)
        from jax.sharding import NamedSharding, PartitionSpec as P

        rep = NamedSharding(self.mesh, P())
        return jax.tree.map(lambda a: jax.device_put(a, rep), tree)

    def _jit_sharding_kwargs(self, out_template):
        """out_shardings kwargs for the decode/prefill jits on a mesh
        engine: "kv" entries take the pinned cache sharding, everything
        else replicated — so outputs re-enter the next call with
        byte-identical signatures (zero steady-state recompiles). {} on
        mesh-less engines (placement matches _commit already)."""
        if self.mesh is None:
            return {}
        from jax.sharding import NamedSharding, PartitionSpec as P

        rep = NamedSharding(self.mesh, P())
        kv = self._kv_sharding()

        def resolve(tag):
            if tag == "kv":
                return jax.tree.map(lambda _: kv, self.caches)
            return rep

        return {"out_shardings": tuple(resolve(t) for t in out_template)}

    def _build_decode_step(self):
        cfg, vocab, wlp = self.cfg, self.vocab_size, self.want_logprobs
        tp_comm = self.tp_comm
        from functools import partial

        from megatron_tpu.models.language_model import lm_forward

        @partial(jax.jit, donate_argnums=self._donate(),
                 **self._jit_sharding_kwargs(
                     ("rep", "rep", "kv", "rep", "rep")))
        def decode_step(params, caches, last_tok, lengths, keys, temps,
                        top_ks, top_ps):
            # one batched token for every slot: write K/V at each slot's
            # own position, attend each slot's own valid prefix
            logits, caches = lm_forward(cfg, params, last_tok[:, None],
                                        kv_caches=caches,
                                        cache_index=lengths,
                                        tp_comm=tp_comm)
            logits = logits[:, 0]
            split = jax.vmap(lambda k: jax.random.split(k, 2))(keys)
            new_keys, subs = split[:, 0], split[:, 1]
            toks = sample_logits_batched(logits, subs, temps, top_ks,
                                         top_ps, vocab)
            if wlp:
                lp = jnp.take_along_axis(
                    jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1),
                    toks[:, None], axis=-1)[:, 0]
            else:
                lp = jnp.zeros(toks.shape, jnp.float32)
            # toks/lengths+1 re-enter the next tick as the carry
            return toks, lp, caches, new_keys, lengths + 1

        return decode_step

    # ----- speculative decoding (inference/speculative.py) ----------------

    def _has_draft_model(self) -> bool:
        return self.spec is not None and self.spec.drafter == "model"

    def _spec_donate(self):
        """Donated argnums for the speculative step: the target cache
        tree, plus the draft cache tree for the model drafter (both are
        persistent engine state updated in place every tick)."""
        if not self._donate():
            return ()
        return (1, 3) if self._has_draft_model() else (1,)

    def _spec_paged(self) -> bool:
        """Whether the spec step threads a page table (overridden by the
        paged engine)."""
        return False

    def _build_spec_step(self):
        from megatron_tpu.inference.speculative import build_spec_decode_step

        return build_spec_decode_step(
            self.cfg, self.spec, self.vocab_size, self.want_logprobs,
            self._spec_donate(), paged=self._spec_paged())

    def _draft_prefill_step(self, P: int):
        """Jitted draft-cache prefill at bucket length P (model drafter
        only): write the prompt's K/V into the draft tree so the first
        spec tick's proposal scan sees the full context. No sampling —
        the draft never emits tokens directly."""
        fn = self._draft_prefill_steps.get(P)
        if fn is not None:
            return fn
        dcfg = self.spec.draft_cfg
        from functools import partial

        from megatron_tpu.models.language_model import lm_forward

        @partial(jax.jit, donate_argnums=self._donate())
        def draft_prefill(dparams, dcaches, tokens, slot):
            small = _init_caches(dcfg, 1, P, int8=False)
            _, small = lm_forward(dcfg, dparams, tokens,
                                  positions=jnp.arange(P)[None, :],
                                  kv_caches=small, cache_index=0)

            def paste(big, sm):
                idx = (0, slot) + (0,) * (big.ndim - 2)
                return jax.lax.dynamic_update_slice(
                    big, sm.astype(big.dtype), idx)

            return jax.tree.map(paste, dcaches, small)

        self._draft_prefill_steps[P] = draft_prefill
        return draft_prefill

    def _prefill_step(self, P: int):
        """Jitted prefill at static bucket length P (compiled once per
        bucket; nearby prompt lengths share a compile)."""
        fn = self._prefill_steps.get(P)
        if fn is not None:
            return fn
        cfg, int8, vocab = self.cfg, self.kv_cache_int8, self.vocab_size
        wlp = self.want_logprobs
        tp_comm = self.tp_comm
        from functools import partial

        from megatron_tpu.models.language_model import lm_forward

        @partial(jax.jit, donate_argnums=self._donate(),
                 **self._jit_sharding_kwargs(
                     ("rep", "rep", "rep", "kv", "rep")))
        def prefill(params, caches, tokens, length, slot, key, temp,
                    top_k, top_p):
            small = _init_caches(cfg, 1, P, int8=int8)
            logits, small = lm_forward(cfg, params, tokens,
                                       positions=jnp.arange(P)[None, :],
                                       kv_caches=small, cache_index=0,
                                       tp_comm=tp_comm)

            def paste(big, sm):
                idx = (0, slot) + (0,) * (big.ndim - 2)
                return jax.lax.dynamic_update_slice(
                    big, sm.astype(big.dtype), idx)

            caches = jax.tree.map(paste, caches, small)
            last = jnp.take_along_axis(
                logits, jnp.full((1, 1, 1), length - 1), axis=1)[:, 0]
            key, sub = jax.random.split(key)
            tok = sample_logits_batched(last, sub[None], temp[None],
                                        top_k[None], top_p[None], vocab)[0]
            if wlp:
                lp = jnp.take_along_axis(
                    jax.nn.log_softmax(last.astype(jnp.float32), axis=-1),
                    tok[None, None], axis=-1)[0, 0]
                # teacher-forced prompt logprobs (positions 1..P-1), like
                # the one-shot path; the caller slices to the real length
                plp = jnp.take_along_axis(
                    jax.nn.log_softmax(
                        logits[0, :P - 1].astype(jnp.float32), axis=-1),
                    tokens[0, 1:, None], axis=-1)[:, 0]
            else:
                lp = jnp.zeros((), jnp.float32)
                plp = jnp.zeros((P - 1,), jnp.float32)
            return tok, lp, plp, caches, key

        self._prefill_steps[P] = prefill
        return prefill

    # ----- scheduling ------------------------------------------------------

    def submit(self, req: Request) -> Request:
        """Queue a request; returns it (wait on req.done)."""
        req.submit_time = time.monotonic()
        p = len(req.prompt)
        if p == 0:
            req._finish("empty prompt")
            self.stats["rejected"] += 1
            self._m_rejected.inc()
            return req
        if req.max_new_tokens < 1:
            req._finish("max_new_tokens must be >= 1")
            self.stats["rejected"] += 1
            self._m_rejected.inc()
            return req
        margin = self._capacity_margin()
        if p + req.max_new_tokens > self.max_seq_len - margin:
            req._finish(
                f"prompt ({p}) + max_new_tokens ({req.max_new_tokens}) "
                f"exceeds engine max_seq_len {self.max_seq_len}"
                + (f" minus the speculative headroom {margin}"
                   if margin else ""))
            self.stats["rejected"] += 1
            self._m_rejected.inc()
            return req
        if req.deadline_s is not None:
            if req.deadline_s <= 0:
                req._finish("deadline_s must be > 0 (or None: no deadline)")
                self.stats["rejected"] += 1
                self._m_rejected.inc()
                return req
            req._deadline = req.submit_time + req.deadline_s
        if resilience.fault_armed("reject_admission"):
            # injected overload: every admission answers queue-full while
            # armed (drives the router's retry-on-503 path in tests)
            req.overloaded = True
            req._finish("engine queue full (injected: reject_admission); "
                        "retry later")
            self.stats["rejected"] += 1
            self._m_rejected.inc()
            return req
        with self._cv:
            if (self.max_queue is not None
                    and len(self._queue) >= self.max_queue):
                # bounded admission: overload degrades to fast rejection
                # (HTTP 503 upstream) instead of unbounded queue latency
                req.overloaded = True
                req._finish(
                    f"engine queue full ({self.max_queue} waiting); "
                    + self._overload_detail() + "retry later")
                self.stats["rejected"] += 1
                self._m_rejected.inc()
                return req
            self._queue.append(req)
            self._m_queue.set(len(self._queue))
            self._cv.notify_all()
        return req

    def _overload_detail(self) -> str:
        """Extra cause text for queue-full rejections — subclasses with
        a richer admission model (the CP engine's striped pools) name
        WHAT is actually blocking, so the 503 detail distinguishes
        resource exhaustion from plain queue depth."""
        return ""

    @property
    def num_active(self) -> int:
        return sum(1 for s in self.slots if s is not None)

    def _bucket(self, p: int) -> int:
        b = self.prefill_bucket
        return min(self.max_seq_len - 1, max(1, -(-p // b) * b))

    def _clear_slot(self, i: int):
        """Reset EVERY per-slot host mirror — a cleared slot must not
        leave sampling knobs behind, or the next carry upload would keep
        the batched sampler's filter branch live for stale rows. (This
        is the whole of the retire-path knob hygiene: every retire /
        timeout / preempt / stop path funnels through here, and the
        paired _sync_carry at each call site drops the device carry
        that still holds the old knobs — audited again for the
        speculative rollback path, whose accept/reject cond reads the
        same temps/top_ks/top_ps rows; regression-pinned by
        test_speculative.py's all-greedy filter-dead test.)"""
        self.slots[i] = None
        self.lengths[i] = 0
        self.last_tok[i] = 0
        self.temps[i] = 0.0
        self.top_ks[i] = 0
        self.top_ps[i] = 0.0
        if not self.spec_on[i]:
            self.spec_on[i] = True
            self._spec_rows_dev = None

    def _retire(self, i: int):
        req = self.slots[i]
        self._clear_slot(i)
        self.stats["retired"] += 1
        self._m_retired.inc()
        self._m_active.set(self.num_active)
        if req.first_token_time is not None and len(req.generated) > 1:
            # steady-state decode latency: exclude the prefill-produced
            # first token (that's what TTFT measures)
            self._m_per_token.observe(
                (time.monotonic() - req.first_token_time)
                / (len(req.generated) - 1))
        # drop the device carry: it still holds this slot's sampling
        # knobs, and a stale temperature/top_k>0 row would keep the
        # batched sampler's lax.cond filter branch (the [N, V] sort) live
        # for every remaining tick
        self._sync_carry()
        self._journal_request(req, "ok")
        req._finish()

    def _sync_carry(self):
        """Pull the device-authoritative decode carry back into the host
        mirrors and invalidate it (an admission is about to edit rows).
        last_tok/lengths host mirrors are updated every tick; only the
        per-slot PRNG chains live solely on device between events."""
        if self._carry is not None:
            self.keys = np.array(self._carry[2])
            self._carry = None

    def _admit(self) -> int:
        """Move queued requests into free slots; prefill each. Returns the
        number admitted this tick."""
        n = 0
        for i in range(self.num_slots):
            if self.slots[i] is not None:
                continue
            with self._cv:
                req = self._queue.popleft() if self._queue else None
                if req is not None:
                    # visible to wait_idle(): popped but not yet in a slot
                    self._admitting += 1
            if req is None:
                break
            try:
                n += self._admit_one(i, req)
            finally:
                with self._cv:
                    self._admitting -= 1
                self.last_progress_time = time.monotonic()
        return n

    def _admit_one(self, i: int, req: Request) -> int:
        """Prefill `req` into free slot `i`; returns 1 if admitted.

        A resumed request (a preserved PRNG chain and/or already-generated
        tokens — recompute-resume after preemption or migration) teacher-
        forces prompt + generated in one prefill and samples the NEXT
        token at the final position with the preserved chain: the exact
        token the interrupted decode tick would have sampled, greedy or
        not (the paged engine's _try_assign is the same contract)."""
        self._sync_carry()
        resumed = req.resume_key is not None or bool(req.generated)
        full = (np.concatenate([np.asarray(req.prompt, np.int32),
                                np.asarray(req.generated, np.int32)])
                if resumed else np.asarray(req.prompt, np.int32))
        p = len(full)
        P = self._bucket(p)
        toks = np.zeros((1, P), np.int32)
        toks[0, :p] = full
        key0 = (jnp.asarray(np.asarray(req.resume_key, np.uint32))
                if req.resume_key is not None
                else jax.random.PRNGKey(req.seed))
        t_prefill = time.monotonic()
        try:
            tok, lp, plp, caches, key = self._prefill_step(P)(
                self.params, self.caches, jnp.asarray(toks),
                jnp.int32(p), jnp.int32(i), key0,
                jnp.float32(req.temperature), jnp.int32(req.top_k),
                jnp.float32(req.top_p))
            self.caches = caches
            if self._has_draft_model():
                # mirror the prompt into the draft model's cache tree so
                # the first speculative tick proposes with full context
                self.draft_caches = self._draft_prefill_step(P)(
                    self.draft_params, self.draft_caches,
                    jnp.asarray(toks), jnp.int32(i))
        except Exception as e:  # noqa: BLE001 - a failing prefill
            # (fresh-bucket compile OOM etc.) must fail THIS request,
            # not strand it un-signalled and kill the step loop
            req._finish(f"prefill failed: {e}")
            self.stats["rejected"] += 1
            self._m_rejected.inc()
            if self._donate():
                # the failed call may have consumed the donated cache
                # buffers — continuing would poison every active slot
                # at the next decode tick (step() has the matching
                # recovery); fail the in-flight requests and restart
                # from fresh caches (target AND draft trees)
                for j, other in enumerate(self.slots):
                    if other is not None:
                        self._clear_slot(j)
                        other._finish(f"prefill failed: {e}")
                self._rebuild_caches()
                self._m_active.set(self.num_active)
            return 0
        self.slots[i] = req
        self.lengths[i] = p
        self.last_tok[i] = int(tok)
        self.temps[i] = req.temperature
        self.top_ks[i] = req.top_k
        self.top_ps[i] = req.top_p
        self.keys[i] = np.asarray(key)
        if self.spec is not None:
            self.spec_on[i] = bool(req.spec)
            self._spec_rows_dev = None
        req.generated.append(int(tok))
        req.logprobs.append(float(lp))
        if not resumed:
            req.prompt_logprobs = [float(x) for x in plp[:p - 1]]
        self.stats["admitted"] += 1
        self._count_comm_prefill(P)
        now = time.monotonic()
        self._m_prefill.observe(now - t_prefill)
        if not resumed:
            # TTFT is first-admission only: a resume's clock restarted
            req.first_token_time = now
            if req.submit_time is not None:
                self._m_ttft.observe(now - req.submit_time)
        self._m_admitted.inc()
        self._m_tokens.inc()
        self._m_active.set(self.num_active)
        with self._cv:
            self._m_queue.set(len(self._queue))
        if self._req_finished(req):
            self._retire(i)
        return 1

    def _req_finished(self, req: Request) -> bool:
        return (len(req.generated) >= req.max_new_tokens
                or (req.eod is not None and req.generated
                    and req.generated[-1] == req.eod))

    def step(self) -> int:
        """One engine tick: admit into free slots, then one batched decode
        for every active slot. Returns the number of active slots served
        (0 = idle)."""
        self._pre_tick()
        self._admit()
        return self._decode_tick()

    def _pre_tick(self) -> None:
        """Per-tick control-plane work shared by every engine subclass
        (the paged engine overrides step() and MUST call this first):
        serving fault injection (MEGATRON_TPU_FAULT, tick-indexed — a
        SIGKILLed/hung/slowed replica at a deterministic decode tick, so
        the router's failover paths are testable on CPU), staged weight
        swaps, and deadline expiry."""
        tick = self.stats["ticks"]
        resilience.maybe_kill("kill_replica", tick)
        if (not self._preempt_signalled
                and resilience.fault_active("preempt_replica", tick)):
            # once per process: ticks only advance on decode, and a
            # second SIGTERM would hit the server's immediate-exit path
            self._preempt_signalled = True
            resilience.maybe_signal("preempt_replica", tick)
        resilience.maybe_hang("hang_replica", tick)
        resilience.maybe_sleep("slow_tick", journal_once=True)
        self._apply_pending_params()
        self._expire_deadlines()

    # ----- hot weight reload ----------------------------------------------

    def update_params(self, params: Any, version: Any = None
                      ) -> threading.Event:
        """Stage a weight swap; the step loop applies it BETWEEN decode
        ticks, so in-flight slots keep decoding without interruption (their
        KV prefixes were computed by the old weights — a drained rolling
        update keeps per-request token identity; docs/serving.md).

        The new tree must match the old one in structure/shape/dtype and is
        committed with the same placement policy as __init__, so the jitted
        decode step's cache key is unchanged — a swap costs ZERO recompiles
        (the live decode_recompiles counter is the regression gate).

        Returns an Event set once the swap has been applied."""
        def check(old, new):
            if (old.shape, old.dtype) != (new.shape, new.dtype):
                raise ValueError(
                    f"update_params shape/dtype mismatch: {old.shape}/"
                    f"{old.dtype} vs {new.shape}/{new.dtype} — a "
                    "mismatched tree would recompile (or garble) the "
                    "decode step")

        jax.tree.map(check, self.params, params)
        applied = threading.Event()
        committed = self._commit(params)
        with self._cv:
            if self._pending_params is not None:
                # a staged-but-unapplied swap is superseded; its waiter
                # unblocks too (the newer weights subsume the older ones)
                self._pending_params[2].set()
            self._pending_params = (committed, version, applied)
            self._cv.notify_all()
        return applied

    def _apply_pending_params(self) -> None:
        with self._cv:
            pending = self._pending_params
            self._pending_params = None
        if pending is None:
            return
        new, version, applied = pending
        self.params = new
        self.params_version = version
        self.stats["weight_reloads"] += 1
        self._m_reloads.inc()
        j = _journal.get_global_journal()
        if j is not None:
            j.emit("weight_reload", version=version,
                   active=self.num_active)
        applied.set()

    # ----- deadlines -------------------------------------------------------

    def _expire_deadlines(self) -> None:
        """Fail queued and mid-decode requests past their deadline: their
        waiters unblock with timed_out=True within one tick of expiry
        instead of waiting on an abandoned request forever."""
        now = time.monotonic()
        expired = []
        with self._cv:
            for req in [r for r in self._queue
                        if r._deadline is not None and now > r._deadline]:
                self._queue.remove(req)
                expired.append(req)
            if expired:
                self._m_queue.set(len(self._queue))
        for req in expired:
            self._fail_timeout(req, "queued")
        for i in range(self.num_slots):
            req = self.slots[i]
            if (req is not None and req._deadline is not None
                    and now > req._deadline):
                self._clear_slot(i)
                # same carry hygiene as _retire: the cleared row's sampling
                # knobs must not keep the batched sampler's filter branch
                # live for the remaining ticks
                self._sync_carry()
                self._m_active.set(self.num_active)
                self._fail_timeout(req, "mid-decode")

    def _fail_timeout(self, req: Request, where: str) -> None:
        req.timed_out = True
        self.stats["timeouts"] += 1
        self._m_timeouts.inc()
        self._journal_request(req, "timeout")
        req._finish(
            f"deadline exceeded while {where} (deadline_s="
            f"{req.deadline_s}, generated {len(req.generated)} of "
            f"{req.max_new_tokens} tokens)")

    def wait_idle(self, timeout: Optional[float] = None) -> bool:
        """Block until no request is queued, mid-admission, or decoding
        (and no weight swap is pending). The drain step of a rolling
        update: stop routing work here, wait_idle, then reload. Returns
        False if `timeout` seconds pass first."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._cv:
                if (not self._queue and self._admitting == 0
                        and self.num_active == 0
                        and self._pending_params is None):
                    return True
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(0.01)

    def _journal_request(self, req: Request, status: str) -> None:
        """Per-request journal record (when a global journal is set):
        the SLO harness and tools/telemetry_report.py read TTFT/TPOT
        percentiles and failure counts off these."""
        j = _journal.get_global_journal()
        if j is None:
            return
        now = time.monotonic()
        fields = {"status": status, "prompt_len": len(req.prompt),
                  "new_tokens": len(req.generated)}
        if req.submit_time is not None:
            fields["wall_s"] = round(now - req.submit_time, 6)
            if req.first_token_time is not None:
                fields["ttft_s"] = round(
                    req.first_token_time - req.submit_time, 6)
                if len(req.generated) > 1:
                    fields["tpot_s"] = round(
                        (now - req.first_token_time)
                        / (len(req.generated) - 1), 6)
        j.emit("serve_request", **fields)
        if self.spec is not None:
            # cumulative speculative counters, one snapshot per retired
            # request (like goodput's cumulative records): the report
            # reads the LAST one for accept rate / tokens-per-forward
            j.emit("serve_spec",
                   proposed=self.stats["spec_proposed"],
                   accepted=self.stats["spec_accepted"],
                   emitted=self.stats["spec_emitted"],
                   ticks=self.stats["ticks"], k=self.spec.k,
                   drafter=self.spec.drafter)

    def _journal_comm_policy(self) -> None:
        """One `comm_policy` record per engine build: which collectives
        run compressed and the static per-tick wire prices — the journal
        side of the engine_comm_*_bytes_total counters (the report
        derives the compression ratio from either)."""
        j = _journal.get_global_journal()
        if j is None or self.tp_comm is None:
            return
        t = self._comm_tick_bytes
        j.emit("comm_policy", mode=self.tp_comm.mode,
               sites=sorted(self.tp_comm.sites), chunk=self.tp_comm.chunk,
               tp=self.tp_comm.tp,
               dense_bytes_per_tick=t["dense"],
               compressed_bytes_per_tick=t["compressed"],
               ratio=round(t["dense"] / max(t["compressed"], 1), 3))

    def _count_comm(self, bytes_pair) -> None:
        """Advance the compressed-collective byte counters by one
        forward's static wire price ({"dense", "compressed"})."""
        if self.tp_comm is None:
            return
        self.stats["comm_dense_bytes"] += bytes_pair["dense"]
        self.stats["comm_compressed_bytes"] += bytes_pair["compressed"]
        self._m_comm_dense.inc(bytes_pair["dense"])
        self._m_comm_compressed.inc(bytes_pair["compressed"])

    def _count_comm_prefill(self, P: int) -> None:
        """Prefill-pass comm accounting at bucket length P (computed
        once per bucket, like the jitted step itself)."""
        if self.tp_comm is None:
            return
        pair = self._comm_prefill_bytes.get(P)
        if pair is None:
            from megatron_tpu.quant.collectives import forward_comm_bytes

            pair = forward_comm_bytes(self.cfg, self.tp_comm, 1, P)
            self._comm_prefill_bytes[P] = pair
        self._count_comm(pair)

    def _decode_rows(self):
        """Slot indices the batched decode serves this tick (the paged
        engine excludes slots still mid-chunked-prefill)."""
        return [i for i, s in enumerate(self.slots) if s is not None]

    def _decode_extra_args(self):
        """Extra positional args spliced between caches and the carry in
        the decode-step call (the paged engine passes its device page
        table here)."""
        return ()

    def _decode_write_span(self) -> int:
        """Cache positions one decode tick writes per slot: 1 plain,
        k+1 speculative (the paged engine sizes page allocation off
        this)."""
        return 1 + self._capacity_margin()

    def _spec_rows_arg(self):
        """Committed device copy of the per-request spec knob mask
        (same caching pattern as the paged engine's device table — a
        fresh host upload every tick would flip the arg's committedness
        and split the jit cache key)."""
        if self._spec_rows_dev is None:
            self._spec_rows_dev = self._commit(jnp.asarray(self.spec_on))
        return self._spec_rows_dev

    def _propose_ngram(self) -> np.ndarray:
        """Host-side prompt-lookup proposals for every slot (drafter
        'ngram'): [N, k] int32, zeros for idle / spec-off rows (their
        drafts are dead — acceptance is forced to 0)."""
        from megatron_tpu.inference.speculative import ngram_propose

        k, n = self.spec.k, self.spec.ngram
        drafts = np.zeros((self.num_slots, k), np.int32)
        for i in range(self.num_slots):
            req = self.slots[i]
            if req is None or not self.spec_on[i]:
                continue
            drafts[i] = ngram_propose(
                np.concatenate([np.asarray(req.prompt, np.int32),
                                np.asarray(req.generated, np.int32)]),
                k, n)
        return drafts

    def _init_carry(self):
        """The device-resident decode carry, (re)built from the host
        mirrors after an admission/retire invalidated it — shared by
        the plain and speculative ticks (ONE layout; a carry change
        must hit both paths by construction)."""
        if self._carry is None:
            self._carry = self._commit_small(
                (jnp.asarray(self.last_tok),
                 jnp.asarray(self.lengths),
                 jnp.asarray(self.keys),
                 jnp.asarray(self.temps),
                 jnp.asarray(self.top_ks),
                 jnp.asarray(self.top_ps)))
        return self._carry

    def _fail_decode(self, active, e) -> None:
        """Decode-step failure recovery shared by the plain and
        speculative ticks: fail the in-flight requests (their waiters
        must unblock), drop the carry, and restore usable caches —
        donation may have consumed every cache tree."""
        for i in active:
            req = self.slots[i]
            self._clear_slot(i)
            req._finish(f"decode step failed: {e}")
        self._m_active.set(self.num_active)
        self._carry = None
        self._rebuild_caches()

    def _decode_tick_spec(self, active) -> int:
        """One speculative decode tick: propose k drafts per slot
        (host n-gram lookup, or the in-step draft-model scan), ONE
        [N, k+1] target verify forward, exact in-step accept/reject,
        then emit 1..k+1 tokens per slot. Rejected drafts roll back by
        the per-slot length alone — their K/V entries sit past the new
        length, masked off and overwritten next tick."""
        spec = self.spec
        last, lens, keys, temps, top_ks, top_ps = self._init_carry()
        pre = (self.params, self.caches)
        if self._has_draft_model():
            pre += (self.draft_params, self.draft_caches)
        pre += self._decode_extra_args()
        tail = (last, lens, keys, temps, top_ks, top_ps,
                self._spec_rows_arg())
        if spec.drafter == "ngram":
            tail += (self._commit(jnp.asarray(self._propose_ngram())),)
        t_tick = time.monotonic()
        try:
            out = self._spec_step(*pre, *tail)
        except Exception as e:  # noqa: BLE001 - shared recovery, then
            # surface the error to the driver
            self._fail_decode(active, e)
            raise
        if self._has_draft_model():
            (toks, lps, accepts, caches, dcaches, keys, lens, last) = out
            self.draft_caches = dcaches
        else:
            toks, lps, accepts, caches, keys, lens, last = out
        self.caches = caches
        self._carry = (last, lens, keys, temps, top_ks, top_ps)
        toks = np.asarray(toks)
        lps = np.asarray(lps)
        accepts = np.asarray(accepts)
        self.stats["ticks"] += 1
        self._m_ticks.inc()
        self._m_tick.observe(time.monotonic() - t_tick)
        self._track_decode_recompiles()
        if self.flight_recorder is not None:
            self.flight_recorder.heartbeat(
                f"spec tick {self.stats['ticks']} ({len(active)} active)")
        emitted_total = 0
        for i in active:
            req = self.slots[i]
            a = int(accepts[i])
            # device-side truth: the fed token + a accepted drafts are
            # now valid cache entries; toks[i, a] is next up
            self.lengths[i] += a + 1
            self.last_tok[i] = int(toks[i, a])
            if self.spec_on[i]:
                self.stats["spec_proposed"] += spec.k
                self.stats["spec_accepted"] += a
                self._m_spec_proposed.inc(spec.k)
                self._m_spec_accepted.inc(a)
                self._m_spec_len.observe(a)
            for j in range(a + 1):
                req.generated.append(int(toks[i, j]))
                req.logprobs.append(float(lps[i, j]))
                emitted_total += 1
                if self._req_finished(req):
                    # eod or max_new mid-speculation: later accepted
                    # tokens are "after the end" — a non-speculative
                    # run would never have produced them. The slot
                    # retires below, which resets the (now past-end)
                    # device mirrors with full carry hygiene.
                    break
            if self._req_finished(req):
                self._retire(i)
        self.stats["spec_emitted"] += emitted_total
        self._m_tokens.inc(emitted_total)
        self.last_progress_time = time.monotonic()
        return len(active)

    def _decode_tick(self) -> int:
        """One batched decode for every decodable slot; returns how many
        were served (0 = nothing to decode)."""
        active = self._decode_rows()
        if not active:
            return 0
        if self.spec is not None:
            return self._decode_tick_spec(active)
        last, lens, keys, temps, top_ks, top_ps = self._init_carry()
        t_tick = time.monotonic()
        try:
            toks, lps, caches, keys, lens = self._decode_step(
                self.params, self.caches, *self._decode_extra_args(),
                last, lens, keys, temps, top_ks, top_ps)
        except Exception as e:  # noqa: BLE001 - shared recovery, then
            # surface the error to the driver
            self._fail_decode(active, e)
            raise
        self.caches = caches
        # toks/lens/keys chain into the next tick on device; only the
        # sampled tokens (and logprobs) cross to the host each tick
        self._carry = (toks, lens, keys, temps, top_ks, top_ps)
        toks = np.asarray(toks)
        lps = np.asarray(lps)
        self.stats["ticks"] += 1
        self._m_ticks.inc()
        self._m_tick.observe(time.monotonic() - t_tick)
        self._m_tokens.inc(len(active))
        self._count_comm(self._comm_tick_bytes)
        self._track_decode_recompiles()
        if self.flight_recorder is not None:
            self.flight_recorder.heartbeat(
                f"tick {self.stats['ticks']} ({len(active)} active)")
        for i in active:
            req = self.slots[i]
            # the fed token is now in the cache; the sampled one is next up
            self.lengths[i] += 1
            tok = int(toks[i])
            self.last_tok[i] = tok
            req.generated.append(tok)
            req.logprobs.append(float(lps[i]))
            if self._req_finished(req):
                self._retire(i)
        self.last_progress_time = time.monotonic()
        return len(active)

    def stalled(self, threshold_s: float) -> bool:
        """True when the engine has pending work (active slots or queued
        requests) but has made no progress for `threshold_s` — the hung-
        step-loop signal readiness probes use. An IDLE engine is never
        stalled, however long it sits."""
        with self._cv:
            busy = (self.num_active > 0 or bool(self._queue)
                    or self._admitting > 0)
        return (busy and
                time.monotonic() - self.last_progress_time > threshold_s)

    def capture_trace(self, out_dir: str, ticks: int = 4,
                      timeout_s: float = 30.0) -> dict:
        """On-demand profiler capture of >= `ticks` decode ticks under
        live traffic (the /admin/profile endpoint; docs/observability.md
        "Runtime traces").

        Runs entirely on the CALLER's thread: jax's profiler session is
        process-global, so bracketing start/stop around the step loop
        from outside traces every device op the loop dispatches — the
        loop itself has NO per-tick check, no extra traced args (zero
        decode recompiles) and zero steady-state overhead when no
        capture is armed. Tick progress is read off ``stats["ticks"]``;
        an idle engine makes no ticks, so the window closes at
        `timeout_s` with whatever it saw (``complete`` says which).
        """
        if not _PROFILE_LOCK.acquire(blocking=False):
            raise RuntimeError(
                "a profiler capture is already in progress (the jax "
                "profiler traces the whole process; retry when it ends)")
        try:
            start_ticks = self.stats["ticks"]
            t0 = time.monotonic()
            jax.profiler.start_trace(out_dir)
            try:
                while (self.stats["ticks"] - start_ticks < ticks
                       and time.monotonic() - t0 < timeout_s):
                    time.sleep(0.005)
            finally:
                jax.profiler.stop_trace()
        finally:
            _PROFILE_LOCK.release()
        captured = self.stats["ticks"] - start_ticks
        return {"dir": out_dir, "ticks": int(captured),
                "requested_ticks": int(ticks),
                "complete": captured >= ticks,
                "wall_s": round(time.monotonic() - t0, 3)}

    def _track_decode_recompiles(self) -> None:
        """Enforce the zero-recompiles-after-warmup invariant as a live
        counter: the decode step's jit cache may grow by exactly ONE entry
        (warmup); any growth past that means a traced-vs-static leak crept
        in (e.g. a sampling knob going static) and every further tick is
        paying a compile."""
        step = self._spec_step if self.spec is not None else self._decode_step
        try:
            size = int(step._cache_size())
        except Exception:  # noqa: BLE001 - private API; tracking degrades
            return
        if size > self._decode_cache_seen:
            grew = size - self._decode_cache_seen
            if self._decode_cache_seen >= 1:  # beyond the warmup compile
                self.stats["decode_recompiles"] += grew
                self._m_recompiles.inc(grew)
            self._decode_cache_seen = size

    # ----- state migration (fleet/migration.py wire format) ----------------

    @contextlib.contextmanager
    def paused(self, timeout: float = 60.0):
        """Park the step loop BETWEEN ticks so the caller may touch slot
        state (request export/import). Counting, so nested pauses
        compose; a no-op when no loop thread is running (tests and batch
        drivers call step() themselves). Raises if the loop does not
        reach a tick boundary within `timeout` — a wedged device step,
        which the caller must not race."""
        with self._cv:
            self._pause_count += 1
            self._cv.notify_all()
        try:
            t = self._thread
            if (t is not None and t.is_alive()
                    and threading.current_thread() is not t):
                if not self._paused_evt.wait(timeout):
                    raise RuntimeError(
                        f"engine step loop did not pause within {timeout}s "
                        "(wedged device step?)")
            yield
        finally:
            with self._cv:
                self._pause_count -= 1
                if self._pause_count == 0:
                    self._paused_evt.clear()
                self._cv.notify_all()

    def _kv_geometry(self) -> dict:
        """The cache facts an importer must match (or fall back on)."""
        cfg = self.cfg
        return {
            "layers": int(cfg.num_layers),
            "kv_heads": int(cfg.n_kv_heads),
            "head_dim": int(cfg.head_dim),
            "dtype": jnp.empty((0,), cfg.dtype).dtype.name,
            "int8": bool(self.kv_cache_int8),
            "sliding_window": (None if cfg.sliding_window_size is None
                               else int(cfg.sliding_window_size)),
        }

    def _pack_kv_sections(self, leaves: List[np.ndarray], length: int
                          ) -> Tuple[dict, Dict[str, np.ndarray]]:
        """Encode canonical-layout KV leaves (each [L, T, H, D] host
        arrays, T = committed positions) into wire sections + a codec
        descriptor. Three codecs:

          int8-native  the int8 cache's own quantized pages + per-position
                       scales ride verbatim — exact w.r.t. what the source
                       would have decoded from (ops/kv_quant.py recipe)
          raw          float caches ship native bytes — exact
          int8 / fp8   opt-in lossy chunked wire (quant/primitives.py,
                       self.kv_wire) — ~2-4x fewer bytes, exact=False, so
                       a token-identity importer recompute-resumes
        """
        from megatron_tpu.quant import primitives as qp

        geo = self._kv_geometry()
        sections: Dict[str, np.ndarray] = {}
        if self.kv_cache_int8:
            k_q, v_q, k_s, v_s = leaves
            sections.update(kv_k=k_q, kv_v=v_q,
                            kv_k_scale=k_s, kv_v_scale=v_s)
            codec, exact = "int8-native", True
        elif self.kv_wire in ("int8", "fp8"):
            mode = self.kv_wire
            if mode == "fp8" and not qp.fp8_supported():
                mode = "int8"  # same gate as compressed collectives
            chunk = qp.effective_chunk(geo["head_dim"], self.kv_wire_chunk)
            for name, leaf in zip(("kv_k", "kv_v"), leaves):
                q, s = qp.quantize_chunked(jnp.asarray(leaf), chunk, mode)
                sections[name] = np.asarray(q)
                sections[name + "_scale"] = np.asarray(s)
            geo["wire_chunk"] = int(chunk)
            codec, exact = mode, False
        else:
            sections.update(kv_k=np.asarray(leaves[0]),
                            kv_v=np.asarray(leaves[1]))
            codec, exact = "raw", True
        meta = dict(geo, codec=codec, exact=exact, length=int(length))
        return meta, sections

    def _decode_kv_sections(self, kv: dict, sections: Dict[str, np.ndarray]
                            ) -> List[np.ndarray]:
        """Wire sections -> canonical host leaves matching THIS engine's
        cache tuple arity (inverse of _pack_kv_sections)."""
        codec = kv["codec"]
        if codec == "int8-native":
            return [sections[n] for n in
                    ("kv_k", "kv_v", "kv_k_scale", "kv_v_scale")]
        if codec == "raw":
            return [sections["kv_k"], sections["kv_v"]]
        from megatron_tpu.quant import primitives as qp

        dt = jnp.empty((0,), self.cfg.dtype).dtype
        return [np.asarray(qp.dequantize_chunked(
                    jnp.asarray(sections[n]),
                    jnp.asarray(sections[n + "_scale"]), dt))
                for n in ("kv_k", "kv_v")]

    def _export_slot_kv(self, i: int
                        ) -> Optional[Tuple[dict, Dict[str, np.ndarray]]]:
        """Host snapshot of slot i's committed KV (positions 0..length-1)
        in the canonical geometry-independent [L, T, H, D] layout, or
        None when no exact export exists (the importer recompute-resumes
        instead). The paged engine overrides this with a page gather."""
        length = int(self.lengths[i])
        if length <= 0:
            return None
        host = [np.asarray(leaf)[:, i, :length]
                for leaf in jax.device_get(self.caches)]
        return self._pack_kv_sections(host, length)

    def export_request_state(self, req: Request, include_kv: bool = True
                             ) -> Tuple[dict, Dict[str, np.ndarray]]:
        """Snapshot one request's FULL resumable state: tokens (prompt +
        generated), sampling knobs, seed, remaining deadline, PRNG chain
        + absolute position, and (for a decoding slot) its KV pages.
        Token-identity contract: an importer resuming from this snapshot
        emits exactly the tokens this engine would have — greedy AND
        sampled, because the chain keys migrate. Call with the step loop
        paused (self.paused()) or from the driver thread."""
        meta: Dict[str, Any] = {
            "kind": "request",
            "prompt": [int(t) for t in np.asarray(req.prompt).tolist()],
            "generated": [int(t) for t in req.generated],
            "logprobs": [float(x) for x in req.logprobs],
            "prompt_logprobs": [float(x) for x in req.prompt_logprobs],
            "max_new_tokens": int(req.max_new_tokens),
            "temperature": float(req.temperature),
            "top_k": int(req.top_k),
            "top_p": float(req.top_p),
            "eod": None if req.eod is None else int(req.eod),
            "seed": int(req.seed),
            "spec": bool(req.spec),
        }
        if req._deadline is not None:
            meta["deadline_remaining_s"] = round(
                max(req._deadline - time.monotonic(), 0.001), 6)
        sections: Dict[str, np.ndarray] = {}
        slot = next((i for i, s in enumerate(self.slots) if s is req), None)
        mid_prefill = (slot is not None and hasattr(self, "prefill_queue")
                       and slot in self.prefill_queue.slots)
        if slot is not None and not mid_prefill:
            self._sync_carry()
            sections["resume_key"] = np.asarray(self.keys[slot],
                                                np.uint32).copy()
            meta["position"] = int(self.lengths[slot])
            if include_kv:
                kv = self._export_slot_kv(slot)
                if kv is not None:
                    meta["kv"], kv_sections = kv[0], kv[1]
                    sections.update(kv_sections)
        elif req.resume_key is not None:
            # queued-but-previously-preempted: the chain survives even
            # though no slot state does (chunked prefills never consume
            # PRNG before the final chunk, so this resume stays exact)
            sections["resume_key"] = np.asarray(req.resume_key,
                                                np.uint32).copy()
        self.stats["kv_exports"] += 1
        self._m_kv_exports.inc()
        return meta, sections

    def export_all_requests(self, include_kv: bool = True
                            ) -> List[Tuple[Request, dict,
                                            Dict[str, np.ndarray]]]:
        """Atomically REMOVE every queued and active request and return
        [(live request, meta, sections), ...]. The engine is empty
        afterwards (a drain completes immediately); the caller owns
        completing or failing each returned Request — their waiters are
        still blocked on req.done."""
        out: List[Tuple[Request, dict, Dict[str, np.ndarray]]] = []
        with self.paused():
            self._sync_carry()
            for i in range(self.num_slots):
                req = self.slots[i]
                if req is None or req.done.is_set():
                    continue
                meta, sections = self.export_request_state(
                    req, include_kv=include_kv)
                self._clear_slot(i)
                out.append((req, meta, sections))
            self._sync_carry()
            self._m_active.set(self.num_active)
            with self._cv:
                queued = list(self._queue)
                self._queue.clear()
                self._m_queue.set(0)
            for req in queued:
                if req.done.is_set():
                    continue
                meta, sections = self.export_request_state(
                    req, include_kv=False)
                out.append((req, meta, sections))
        return out

    def _kv_import_compatible(self, kv: dict) -> Tuple[bool, str]:
        """Whether a transferred KV state can be installed DIRECTLY into
        this engine's cache (vs recompute-resume). (ok, reason)."""
        if self.mesh is not None:
            return False, "direct KV install on mesh engines is not wired"
        if self._has_draft_model():
            return False, "draft-model cache migration is not wired"
        geo = self._kv_geometry()
        for k in ("layers", "kv_heads", "head_dim"):
            if int(kv.get(k, -1)) != geo[k]:
                return False, f"geometry mismatch on {k}"
        codec = kv.get("codec")
        if codec == "int8-native":
            if not self.kv_cache_int8:
                return False, "int8-native transfer into a float cache"
        elif codec == "raw":
            if self.kv_cache_int8:
                return False, "raw transfer into an int8 cache"
            if kv.get("dtype") != geo["dtype"]:
                return False, "cache dtype mismatch"
        elif codec in ("int8", "fp8"):
            if self.kv_cache_int8:
                return False, "lossy wire into an int8 cache"
        else:
            return False, f"unknown codec {codec!r}"
        if int(kv["length"]) + self._capacity_margin() >= self.max_seq_len:
            return False, "migrated context exceeds this engine's capacity"
        return True, ""

    def _free_slot_for_import(self) -> Optional[int]:
        for i in range(self.num_slots):
            if self.slots[i] is None:
                return i
        return None

    def _kv_install_writer(self):
        """Once-jitted axis-1 paste: a [L, T, ...] block into the
        [L, N, T, ...] cache tree at a TRACED index (slot for the dense
        engine, page for the paged pool). Static shapes, its own jit —
        repeated imports never grow the decode step's cache (the
        zero-decode-recompiles invariant holds through migration)."""
        if self._kv_writer is None:
            from functools import partial

            @partial(jax.jit, donate_argnums=(0,) if self._donate() else ())
            def write(caches, blocks, at):
                def paste(big, sm):
                    idx = (0, at) + (0,) * (big.ndim - 2)
                    return jax.lax.dynamic_update_slice(
                        big, sm[:, None].astype(big.dtype), idx)

                return jax.tree.map(paste, caches, blocks)

            self._kv_writer = write
        return self._kv_writer

    def _install_request_kv(self, req: Request, kv: dict,
                            sections: Dict[str, np.ndarray]) -> bool:
        """Write the transferred KV into a free slot's cache rows (dense
        layout; the paged engine overrides with page allocation). False =
        no capacity, caller falls back to recompute-resume."""
        i = self._free_slot_for_import()
        if i is None:
            return False
        length = int(kv["length"])
        leaves = self._decode_kv_sections(kv, sections)
        blocks = []
        for leaf in leaves:
            row = np.zeros((leaf.shape[0], self.max_seq_len)
                           + leaf.shape[2:], leaf.dtype)
            row[:, :length] = leaf
            blocks.append(jnp.asarray(row))
        self._sync_carry()
        self.caches = self._kv_install_writer()(
            self.caches, tuple(blocks), jnp.int32(i))
        self._arm_imported_slot(i, req, length)
        return True

    def _arm_imported_slot(self, i: int, req: Request, length: int) -> None:
        """Slot bookkeeping shared by the dense and paged installs: the
        migrated request continues decoding at its absolute position with
        its migrated PRNG chain — no prefill, no re-sample."""
        req.submit_time = time.monotonic()
        if req.deadline_s is not None:
            req._deadline = req.submit_time + req.deadline_s
        self.slots[i] = req
        self.lengths[i] = length
        self.last_tok[i] = int(req.generated[-1])
        self.temps[i] = req.temperature
        self.top_ks[i] = req.top_k
        self.top_ps[i] = req.top_p
        self.keys[i] = np.asarray(req.resume_key, np.uint32)
        if self.spec is not None:
            self.spec_on[i] = bool(req.spec)
            self._spec_rows_dev = None
        self.stats["admitted"] += 1
        self._m_admitted.inc()
        self._m_active.set(self.num_active)
        self.last_progress_time = time.monotonic()
        with self._cv:
            self._cv.notify_all()  # wake an idle step loop

    def import_request_state(self, meta: dict,
                             sections: Dict[str, np.ndarray],
                             allow_inexact: bool = False
                             ) -> Tuple[Request, str]:
        """Rebuild a migrated request in THIS engine. Returns (req, path):
        path "kv_import" = the transferred pages were installed and decode
        continues at the migrated position; "recompute" = the request
        re-enters through submit() and teacher-forces prompt + generated
        (recompute-resume — exact, just re-spends prefill FLOPs). Both
        paths are token-identical to the uninterrupted source run unless
        the wire codec was lossy AND allow_inexact let it through. Journals
        a `serve_migrate` stage="import" row naming the path taken."""
        req = Request(
            prompt=np.asarray(meta["prompt"], np.int32),
            max_new_tokens=int(meta["max_new_tokens"]),
            temperature=float(meta.get("temperature", 0.0)),
            top_k=int(meta.get("top_k", 0)),
            top_p=float(meta.get("top_p", 0.0)),
            eod=meta.get("eod"),
            seed=int(meta.get("seed", 0)),
            deadline_s=meta.get("deadline_remaining_s"),
            spec=bool(meta.get("spec", True)))
        req.generated = [int(t) for t in meta.get("generated", [])]
        req.logprobs = [float(x) for x in meta.get("logprobs", [])]
        req.prompt_logprobs = [float(x) for x in
                               meta.get("prompt_logprobs", [])]
        if req.generated and len(req.generated) >= req.max_new_tokens:
            raise ValueError("migrated request is already complete")
        if "resume_key" in sections:
            req.resume_key = np.asarray(sections["resume_key"], np.uint32)
        kv = meta.get("kv")
        path, reason = "recompute", ""
        if kv is None:
            reason = "no KV in transfer"
        elif not req.generated or req.resume_key is None:
            reason = "no decode state rode along"
        elif int(kv["length"]) != len(req.prompt) + len(req.generated) - 1:
            reason = "inconsistent migrated position"
        elif not (kv.get("exact") or allow_inexact):
            reason = f"lossy wire codec {kv.get('codec')}"
        else:
            ok, reason = self._kv_import_compatible(kv)
            if ok:
                with self.paused():
                    if self._install_request_kv(req, kv, sections):
                        path = "kv_import"
                    else:
                        reason = "no free slot/pages for a direct install"
        if path == "recompute":
            # recompute-resume: the preempt-and-resume exactness
            # machinery (resume_key + generated teacher-forcing) is the
            # universal fallback — it only needs tokens and the chain
            self.submit(req)
        self.stats["kv_imports"] += 1
        self._m_kv_imports.inc(path=path)
        j = _journal.get_global_journal()
        if j is not None:
            fields = {"stage": "import", "path": path,
                      "prompt_len": len(req.prompt),
                      "generated": len(req.generated)}
            if kv is not None:
                fields["codec"] = kv.get("codec")
                fields["exact"] = bool(kv.get("exact"))
            if reason:
                fields["fallback_reason"] = reason
            j.emit("serve_migrate", **fields)
        return req, path

    # ----- driving ---------------------------------------------------------

    def _mesh_scope(self):
        import contextlib

        return (jax.sharding.set_mesh(self.mesh) if self.mesh is not None
                else contextlib.nullcontext())

    def run_until_idle(self) -> None:
        """Step until the queue and every slot drain (single-thread use:
        tests, benches, batch jobs)."""
        with self._mesh_scope():
            while True:
                served = self.step()
                with self._cv:
                    if served == 0 and not self._queue:
                        return

    def generate(self, prompts: np.ndarray, lengths: np.ndarray,
                 max_new_tokens: int, temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 0.0,
                 eod: Optional[int] = None, seed: int = 0,
                 deadline_s: Optional[float] = None,
                 spec: bool = True
                 ) -> GenerationOutput:
        """Batch convenience with generate_tokens' semantics: submit one
        request per row, drain, and repack [B, maxp+max_new] (rows padded
        with eod/0 past their end). The one-shot jitted loop runs EVERY
        row of a ragged batch to maxp + max_new_tokens, so shorter
        prompts get the difference as extra generated tokens — matched
        here so flipping a server between engine and one-shot mode never
        changes a response."""
        B, maxp = prompts.shape
        reqs = []
        # the queue-capacity check and the B submits happen under ONE
        # lock acquisition (the Condition lock is an RLock, so submit()
        # re-entering it is fine): a batch that can't fully queue is
        # rejected BEFORE submitting anything — otherwise the admitted
        # rows would decode to completion only to have their output
        # discarded when the rejected row raises below, burning decode
        # capacity exactly when the engine is overloaded. Checking and
        # submitting under separate acquisitions would let two
        # concurrent batches both pass the check and then trip the
        # per-row rejection mid-submission anyway.
        with self._cv:
            if (self.max_queue is not None
                    and len(self._queue) + B > self.max_queue):
                self.stats["rejected"] += B
                self._m_rejected.inc(B)
                raise EngineOverloadedError(
                    f"engine queue cannot take {B} more requests "
                    f"(max_queue={self.max_queue}); retry later")
            for b in range(B):
                p = int(lengths[b])
                reqs.append(self.submit(Request(
                    prompt=np.asarray(prompts[b, :p], np.int32),
                    max_new_tokens=maxp - p + max_new_tokens,
                    temperature=temperature, deadline_s=deadline_s,
                    top_k=top_k, top_p=top_p, eod=eod, seed=seed + b,
                    spec=spec)))
        if self._thread is None:
            self.run_until_idle()
        for r in reqs:
            r.done.wait()
        if any(r.overloaded for r in reqs):
            raise EngineOverloadedError(
                next(r.error for r in reqs if r.overloaded))
        if any(r.timed_out for r in reqs):
            raise RequestTimeoutError(
                next(r.error for r in reqs if r.timed_out))
        errs = [r.error for r in reqs if r.error]
        if errs:
            raise ValueError(errs[0])
        total = maxp + max_new_tokens
        pad = 0 if eod is None else eod
        tokens = np.full((B, total), pad, np.int32)
        ends = np.zeros(B, np.int64)
        lp = np.zeros((B, total - 1), np.float32)
        for b, r in enumerate(reqs):
            t = r.tokens
            tokens[b, :len(t)] = t
            ends[b] = len(t)
            # teacher-forced prompt region then generated tokens, matching
            # the one-shot path's row layout (lp[i] scores token i+1)
            lp[b, :len(r.prompt_logprobs)] = r.prompt_logprobs
            gen0 = int(lengths[b]) - 1  # logprob row index of first token
            lp[b, gen0:gen0 + len(r.logprobs)] = r.logprobs
        return GenerationOutput(tokens=tokens, lengths=ends, logprobs=lp)

    # ----- background thread (HTTP serving) --------------------------------

    def start(self) -> None:
        """Spawn the step-loop thread: concurrent submitters share each
        decode tick."""
        if self._thread is not None:
            return
        self._stop = False

        def loop():
            with self._mesh_scope():
                while True:
                    with self._cv:
                        while (not self._stop
                               and (self._pause_count > 0
                                    or (self.num_active == 0
                                        and not self._queue
                                        and self._pending_params is None))):
                            if self._pause_count > 0:
                                # state-migration pause: park between
                                # ticks and tell the pauser slot state is
                                # safe to touch (bounded wait — resume
                                # notifies, the timeout is a backstop)
                                self._paused_evt.set()
                                self._cv.wait(timeout=0.5)
                                continue
                            if self.flight_recorder is not None:
                                # an IDLE engine is healthy, not hung: keep
                                # beating (bounded wait) or the watchdog
                                # dumps a spurious stall bundle — fatally
                                # so under flight_recorder_abort
                                self.flight_recorder.heartbeat("idle")
                                self._cv.wait(timeout=1.0)
                            else:
                                self._cv.wait()
                        self._paused_evt.clear()
                        if self._stop:
                            return
                    try:
                        self.step()
                    except Exception as e:  # noqa: BLE001 - step() has
                        # already failed the affected requests; the loop
                        # must survive to serve the next ones (a dead
                        # driver thread would hang every future submit)
                        import traceback

                        print(f"inference-engine step error: {e}",
                              file=sys.stderr)
                        traceback.print_exc()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="inference-engine")
        self._thread.start()

    def stop(self) -> None:
        """Stop the step-loop thread and fail whatever it leaves behind:
        waiters on in-flight or still-queued requests block on done.wait()
        with no timeout, so every abandoned request must be signalled or
        its thread hangs forever."""
        if self._thread is None:
            return
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        self._thread.join(timeout=30)
        if self._thread.is_alive():
            # a stalled device step still owns the slot state — tearing
            # it down now would race the zombie and let a later start()
            # spawn a second concurrent step loop
            raise RuntimeError(
                "inference-engine step loop did not stop within 30s")
        self._thread = None
        with self._cv:
            leftovers = list(self._queue)
            self._queue.clear()
        for i in range(self.num_slots):
            req = self.slots[i]
            if req is not None:
                self._clear_slot(i)
                req._finish("engine stopped")
        for req in leftovers:
            req._finish("engine stopped")
        with self._cv:
            if self._pending_params is not None:
                # unblock a reload waiter — the swap will never be applied
                self._pending_params[2].set()
                self._pending_params = None
        self._carry = None
        self._m_active.set(0)
        self._m_queue.set(0)

from megatron_tpu.inference.sampling import sample_logits
from megatron_tpu.inference.generation import (
    GenerationOutput,
    generate_tokens,
    score_tokens,
    beam_search_tokens,
)
from megatron_tpu.inference.api import (
    generate_and_post_process,
    beam_search_and_post_process,
)

__all__ = [
    "sample_logits",
    "GenerationOutput",
    "generate_tokens",
    "score_tokens",
    "beam_search_tokens",
    "generate_and_post_process",
    "beam_search_and_post_process",
]

from megatron_tpu.inference.sampling import sample_logits, sample_logits_batched
from megatron_tpu.inference.generation import (
    GenerationOutput,
    generate_tokens,
    score_tokens,
    beam_search_tokens,
)
from megatron_tpu.inference.api import (
    generate_and_post_process,
    beam_search_and_post_process,
)
from megatron_tpu.inference.engine import InferenceEngine, Request
from megatron_tpu.inference.speculative import SpecConfig

__all__ = [
    "SpecConfig",
    "sample_logits",
    "sample_logits_batched",
    "GenerationOutput",
    "generate_tokens",
    "score_tokens",
    "beam_search_tokens",
    "generate_and_post_process",
    "beam_search_and_post_process",
    "InferenceEngine",
    "Request",
]

"""Text-level generation API.

Equivalent of megatron/text_generation/api.py (201 LoC) +
tokenization.py (118): tokenize+pad prompt batches, run generation, and
detokenize with segment boundaries. The reference's rank-0
broadcast-params-to-all-ranks choreography (api.py:93-115) has no
equivalent — a single-controller program has no ranks to convince.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from megatron_tpu.config import ModelConfig
from megatron_tpu.inference.generation import (
    beam_search_tokens, generate_tokens, score_tokens,
)


def tokenize_prompts(
    tokenizer, prompts: Sequence[str], max_prompt_len: Optional[int] = None,
    add_bos: bool = False,
) -> Tuple[np.ndarray, np.ndarray]:
    """Right-padded prompt batch + lengths (ref: tokenization.py:47)."""
    ids = []
    for p in prompts:
        t = list(tokenizer.tokenize(p))
        if add_bos and tokenizer.bos is not None:
            t = [tokenizer.bos] + t
        if max_prompt_len:
            t = t[:max_prompt_len]
        if not t:
            raise ValueError("empty prompt after tokenization")
        ids.append(t)
    lengths = np.asarray([len(t) for t in ids], np.int32)
    width = int(lengths.max())
    batch = np.full((len(ids), width), tokenizer.pad, np.int32)
    for i, t in enumerate(ids):
        batch[i, :len(t)] = t
    return batch, lengths


def generate_and_post_process(
    cfg: ModelConfig,
    params: Any,
    tokenizer,
    prompts: Sequence[str],
    tokens_to_generate: int = 64,
    temperature: float = 1.0,
    top_k_sampling: int = 0,
    top_p_sampling: float = 0.0,
    add_BOS: bool = False,
    return_output_log_probs: bool = False,
    random_seed: int = 0,
    forward_fn=None,
    kv_cache_int8: bool = False,
    engine=None,
    deadline_s=None,
    spec: bool = True,
):
    """(texts, segments, logprobs, tokens) like the reference's
    generate_and_post_process (api.py:19-90). forward_fn plugs in the
    pipelined pp>1 forward (inference/pipelined.py); engine routes the
    request through a continuous-batching InferenceEngine
    (inference/engine.py) instead of the one-shot generate_tokens — its
    slot scheduler lets concurrent callers share decode steps.
    deadline_s (engine path only) bounds each request's total wall time:
    past it the engine fails the request with RequestTimeoutError
    (HTTP 504) instead of leaving the caller waiting. spec=False pins
    the request to plain one-token-per-tick decode on a speculating
    engine (no-op otherwise); greedy output is identical either way."""
    if tokens_to_generate < 0:
        raise ValueError("tokens_to_generate must be >= 0")
    prompt_tokens, lengths = tokenize_prompts(tokenizer, prompts,
                                              add_bos=add_BOS)
    if tokens_to_generate == 0:
        # scoring mode (ref: tokens_to_generate==0 -> teacher-forced)
        lp = score_tokens(cfg, params, prompt_tokens)
        texts = [tokenizer.detokenize(t[:l]) for t, l in zip(prompt_tokens, lengths)]
        return texts, None, lp, prompt_tokens

    if engine is not None:
        # the engine owns its own forward and cache configuration — a
        # conflicting request must fail loudly, not be silently dropped
        if forward_fn is not None:
            raise ValueError(
                "engine= and forward_fn= are mutually exclusive (the "
                "continuous-batching engine runs the single-stage forward)")
        if bool(kv_cache_int8) != bool(engine.kv_cache_int8):
            raise ValueError(
                f"kv_cache_int8={kv_cache_int8} conflicts with the "
                f"engine's kv_cache_int8={engine.kv_cache_int8} — the "
                "cache mode is fixed when the engine is built")
        out = engine.generate(
            prompt_tokens, lengths, max_new_tokens=tokens_to_generate,
            temperature=temperature, top_k=top_k_sampling,
            top_p=top_p_sampling, eod=tokenizer.eod, seed=random_seed,
            deadline_s=deadline_s, spec=spec)
    else:
        out = generate_tokens(
            cfg, params, prompt_tokens, lengths,
            max_new_tokens=tokens_to_generate,
            temperature=temperature, top_k=top_k_sampling, top_p=top_p_sampling,
            vocab_size=tokenizer.vocab_size, eod=tokenizer.eod, seed=random_seed,
            want_logprobs=return_output_log_probs, forward_fn=forward_fn,
            kv_cache_int8=kv_cache_int8)

    texts, segments = [], []
    for row, end in zip(out.tokens, out.lengths):
        toks = row[: int(end)]
        texts.append(tokenizer.detokenize(toks))
        segments.append([tokenizer.detokenize([t]) for t in toks])
    logprobs = out.logprobs if return_output_log_probs else None
    return texts, segments, logprobs, out.tokens


def beam_search_and_post_process(
    cfg: ModelConfig,
    params: Any,
    tokenizer,
    prompts: Sequence[str],
    tokens_to_generate: int = 64,
    beam_size: int = 4,
    add_BOS: bool = False,
    length_penalty: float = 1.0,
    kv_cache_int8: bool = False,
):
    """(texts, segments, scores) — ref api.py:147-201 (batch of 1 only)."""
    if len(prompts) != 1:
        raise ValueError("beam search supports a single prompt (as in the reference)")
    prompt_tokens, lengths = tokenize_prompts(tokenizer, prompts,
                                              add_bos=add_BOS)
    beams, scores = beam_search_tokens(
        cfg, params, prompt_tokens[0, :int(lengths[0])],
        max_new_tokens=tokens_to_generate, beam_size=beam_size,
        eod=tokenizer.eod, length_penalty=length_penalty,
        kv_cache_int8=kv_cache_int8)
    texts = [tokenizer.detokenize(b) for b in beams]
    segments = [[tokenizer.detokenize([t]) for t in b] for b in beams]
    return texts, segments, scores

"""Replica lifecycle: subprocess handle + the replica entry point.

`ReplicaProcess` is the manager-side handle the fleet tests and
tools/run_serving_fleet.py use: spawn a real OS process serving the
generation API (`python -m megatron_tpu.inference.fleet.replica`), learn
its bound port through a port file (port 0 = ephemeral), wait for
readiness, SIGKILL/SIGTERM it, and respawn it on the SAME port so the
router's replica URL stays valid across a restart.

The chaos tests kill these processes for real — mid-stream, with
concurrent traffic in flight — which is the only honest way to prove the
router's failover story (mirrors PR 2's real subprocess kill tests for
training).

The child entry takes one JSON spec (--spec or --spec-file) instead of a
forest of flags, because every field is machine-built:

  {"preset": "tiny", "cfg": {"vocab_size": 65, "seq_length": 64},
   "seed": 0, "engine_slots": 2, "port": 0,
   "port_file": "/tmp/r0.port", "warmup": true,
   "load": "ckpts", "request_timeout": 30.0, "drain_timeout": 5.0}

Real deployments serve real checkpoints via
tools/run_text_generation_server.py; this entry exists so fleet logic is
testable with a tiny deterministic model (same seed => identical weights
on every replica => failover retries are token-identical).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional


class ReplicaProcess:
    """Spawn/monitor/kill one replica subprocess."""

    def __init__(self, spec: Dict[str, Any],
                 env: Optional[Dict[str, str]] = None,
                 python: str = sys.executable,
                 log_path: Optional[str] = None):
        self.spec = dict(spec)
        self.env = env
        self.python = python
        self.log_path = log_path
        self.proc: Optional[subprocess.Popen] = None
        self.port: Optional[int] = self.spec.get("port") or None
        port_file = self.spec.get("port_file")
        if not port_file:
            raise ValueError("spec needs a port_file so the parent can "
                             "learn the bound port")
        self.port_file = port_file

    @property
    def url(self) -> str:
        if self.port is None:
            raise RuntimeError("replica has no port yet (spawn + "
                               "wait_ready first)")
        host = self.spec.get("host", "127.0.0.1")
        return f"http://{host}:{self.port}"

    def spawn(self) -> "ReplicaProcess":
        """Start the subprocess; on respawn after a kill, rebind the SAME
        port the first run resolved, so the router's URL stays stable."""
        if self.proc is not None and self.proc.poll() is None:
            raise RuntimeError("replica already running")
        spec = dict(self.spec)
        if self.port is not None:
            spec["port"] = self.port
        if os.path.exists(self.port_file):
            os.unlink(self.port_file)
        env = dict(os.environ if self.env is None else self.env)
        log = (open(self.log_path, "ab") if self.log_path
               else subprocess.DEVNULL)
        try:
            self.proc = subprocess.Popen(
                [self.python, "-m",
                 "megatron_tpu.inference.fleet.replica",
                 "--spec", json.dumps(spec)],
                stdout=log, stderr=log, env=env)
        finally:
            if log is not subprocess.DEVNULL:
                log.close()
        return self

    def wait_port(self, timeout: float = 120.0) -> int:
        """Block until the child publishes its bound port (or dies)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.proc is not None and self.proc.poll() is not None:
                raise RuntimeError(
                    f"replica exited rc={self.proc.returncode} before "
                    f"publishing a port (log: {self.log_path})")
            try:
                with open(self.port_file) as f:
                    self.port = int(json.load(f)["port"])
                return self.port
            except (OSError, ValueError, KeyError):
                time.sleep(0.05)
        raise TimeoutError(f"replica did not publish a port within "
                           f"{timeout}s (log: {self.log_path})")

    def wait_ready(self, timeout: float = 120.0) -> None:
        """Block until /readyz answers 200 (includes warmup compile)."""
        if self.port is None:
            self.wait_port(timeout)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.proc is not None and self.proc.poll() is not None:
                raise RuntimeError(
                    f"replica exited rc={self.proc.returncode} before "
                    f"ready (log: {self.log_path})")
            try:
                with urllib.request.urlopen(self.url + "/readyz",
                                            timeout=2) as r:
                    if r.status == 200:
                        return
            except urllib.error.HTTPError:
                pass
            except (OSError, urllib.error.URLError):
                pass
            time.sleep(0.1)
        raise TimeoutError(f"replica at {self.url} not ready within "
                           f"{timeout}s (log: {self.log_path})")

    def kill(self) -> None:
        """SIGKILL — the unmaskable death the chaos tests need."""
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait(timeout=30)

    def terminate(self) -> None:
        """SIGTERM — the graceful-drain path."""
        if self.proc is not None and self.proc.poll() is None:
            self.proc.send_signal(signal.SIGTERM)

    def wait(self, timeout: Optional[float] = None) -> Optional[int]:
        if self.proc is None:
            return None
        return self.proc.wait(timeout=timeout)

    def poll(self) -> Optional[int]:
        return None if self.proc is None else self.proc.poll()

    def close(self) -> None:
        self.kill()


# ---------------------------------------------------------------------------
# child entry point


def _build_and_serve(spec: Dict[str, Any]) -> None:
    """Runs in the replica subprocess: build the tiny (or preset) model,
    optionally load committed weights, and serve until signalled."""
    from megatron_tpu.platform import ensure_platform

    ensure_platform()

    import jax

    from megatron_tpu.inference.server import run_server
    from megatron_tpu.models import presets
    from megatron_tpu.models.params import init_params
    from megatron_tpu.tokenizer.tokenizer import NullTokenizer

    if spec.get("telemetry_dir"):
        from megatron_tpu.telemetry.journal import (
            EventJournal, set_global_journal,
        )

        os.makedirs(spec["telemetry_dir"], exist_ok=True)
        set_global_journal(EventJournal(
            os.path.join(spec["telemetry_dir"], "events.jsonl")))

    preset = presets.PRESETS[spec.get("preset", "tiny")]
    cfg = preset(**spec.get("cfg", {}))
    tokenizer = NullTokenizer(int(spec.get("null_vocab",
                                           cfg.vocab_size - 1)))
    params = init_params(cfg, jax.random.PRNGKey(int(spec.get("seed", 0))))
    weights_version = None
    if spec.get("load"):
        from megatron_tpu.inference.fleet.reload import load_verified_params

        params, weights_version = load_verified_params(
            spec["load"], params, iteration=spec.get("iteration"))
        print(f"replica loaded weights iter {weights_version} "
              f"from {spec['load']}", flush=True)

    # context-parallel replica: build a context-only mesh of cp devices.
    # cp may be < the local device count — with cp_lanes > 1 one host
    # runs several independent CP engine lanes (CP x DP) and the
    # router-visible load is the lane sum (scrape.replica_load).
    mesh = None
    if spec.get("cp_serving"):
        import numpy as np
        from jax.sharding import Mesh

        cp = int(spec.get("cp", 2))
        mesh = Mesh(np.array(jax.devices()[:cp]).reshape(cp),
                    axis_names=("context",))

    run_server(
        cfg, params, tokenizer,
        host=spec.get("host", "127.0.0.1"),
        port=int(spec.get("port", 0)),
        mesh=mesh,
        engine_slots=int(spec.get("engine_slots", 2)),
        engine_max_seq_len=spec.get("max_seq_len"),
        engine_max_queue=spec.get("max_queue"),
        kv_cache_int8=bool(spec.get("kv_cache_int8", False)),
        kv_paging=bool(spec.get("kv_paging", False)),
        page_size=int(spec.get("page_size", 16)),
        prefill_chunk=int(spec.get("prefill_chunk", 32)),
        num_pages=spec.get("num_pages"),
        request_timeout=spec.get("request_timeout"),
        drain_timeout=float(spec.get("drain_timeout", 30.0)),
        stall_threshold_s=float(spec.get("stall_threshold_s", 10.0)),
        warmup=bool(spec.get("warmup", True)),
        # speculative decoding ("ngram" | "model"; the fleet entry only
        # wires the zero-weight ngram drafter — a draft checkpoint story
        # belongs to tools/run_text_generation_server.py)
        speculative=spec.get("speculative"),
        spec_k=int(spec.get("spec_k", 4)),
        # compressed TP collectives (--serve_compress_collectives /
        # --serve_comm_policy): pass through to the engine — a no-op on
        # the tiny single-device fleet replicas, wired so a sharded
        # replica spec serves compressed without a new entry point
        compress_collectives=spec.get("compress_collectives", "none"),
        comm_policy=spec.get("comm_policy"),
        # context-parallel serving spec keys (docs/serving.md "CP geometry
        # and overlap"): geometry/subgroup pick the 2D factorization,
        # cp_lanes > 1 packs multiple CP groups on one replica host
        cp_serving=bool(spec.get("cp_serving", False)),
        cp_collectives=spec.get("cp_collectives", "dense"),
        cp_comm_policy=spec.get("cp_comm_policy"),
        cp_geometry=spec.get("cp_geometry", "ring"),
        cp_subgroup=int(spec.get("cp_subgroup", 0)),
        cp_overlap=bool(spec.get("cp_overlap", True)),
        cp_lanes=int(spec.get("cp_lanes", 1)),
        port_file=spec.get("port_file"),
        reload_dir=spec.get("reload_dir") or spec.get("load"),
        weights_version=weights_version,
        # handoff peers (base URLs): a SIGTERM drain migrates in-flight +
        # queued requests to them (fleet/migration.py) instead of failing
        # them — the slo_harness --churn drill and the chaos tests set it
        peers=spec.get("peers"),
    )


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="serving replica (fleet subprocess entry)")
    g = ap.add_mutually_exclusive_group(required=True)
    g.add_argument("--spec", help="replica spec as one JSON object")
    g.add_argument("--spec-file", help="path to a JSON spec file")
    args = ap.parse_args(argv)
    if args.spec_file:
        with open(args.spec_file) as f:
            spec = json.load(f)
    else:
        spec = json.loads(args.spec)
    _build_and_serve(spec)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Traffic-replay SLO harness: offered load in, latency percentiles out.

Serving claims need the same discipline training claims got from bench.py:
measured percentiles under a FIXED OFFERED LOAD, not anecdotes. An
open-loop replay (requests fire at their scheduled times whether or not
earlier ones returned — the "millions of users" arrival model) is the
honest one: a closed loop would slow its own arrival rate exactly when the
system degrades, hiding the queueing collapse the SLO exists to catch.

The trace is deterministic (seeded exponential inter-arrivals ≈ Poisson at
`offered_rps`, seeded prompt/length mix), so two runs — or two fleet
configurations — see byte-identical traffic. TTFT/TPOT percentiles come
from the engine's own Prometheus histograms (telemetry/metrics.py),
scraped before and after the window and DIFFED, so warmup compiles and
unrelated traffic fall out; client-side wall-time percentiles ride along
as the end-to-end view (router retries included).

Used by tools/slo_harness.py (CLI: attach to a live fleet or spawn one)
and bench.py's `serve_slo_offered_load` line. Pure host code — no jax.
"""

from __future__ import annotations

import json
import random
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Sequence

from megatron_tpu.inference.fleet import scrape

#: (quantile, label) pairs every report carries
PERCENTILES = ((0.50, "p50"), (0.95, "p95"), (0.99, "p99"))


def make_trace(num_requests: int, offered_rps: float, *, seed: int = 0,
               vocab: int = 64, prompt_len: Sequence[int] = (4, 12),
               new_tokens: int = 16) -> List[Dict[str, Any]]:
    """Deterministic open-loop trace: `num_requests` generation requests
    with exponential inter-arrival times averaging 1/offered_rps seconds,
    prompts of uniform length in [prompt_len[0], prompt_len[1]] drawn from
    a NullTokenizer-style integer vocabulary. Each item is
    {"at_s", "prompts", "tokens_to_generate", "random_seed"}."""
    if offered_rps <= 0:
        raise ValueError("offered_rps must be > 0")
    rng = random.Random(seed)
    t = 0.0
    trace = []
    for i in range(num_requests):
        t += rng.expovariate(offered_rps)
        plen = rng.randint(prompt_len[0], prompt_len[1])
        prompt = " ".join(str(rng.randrange(1, vocab - 1))
                          for _ in range(plen))
        trace.append({"at_s": round(t, 6), "prompts": [prompt],
                      "tokens_to_generate": new_tokens, "temperature": 0.0,
                      "random_seed": i})
    return trace


def _fire(api_url: str, item: Dict[str, Any], timeout: float
          ) -> Dict[str, Any]:
    body = json.dumps({k: v for k, v in item.items() if k != "at_s"})
    req = urllib.request.Request(api_url, data=body.encode(),
                                 method="POST",
                                 headers={"Content-Type":
                                          "application/json"})
    t0 = time.monotonic()
    out: Dict[str, Any] = {"at_s": item["at_s"]}
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            resp.read()
            out["status"] = resp.status
    except urllib.error.HTTPError as e:
        e.read()
        out["status"] = e.code
    except (OSError, urllib.error.URLError) as e:
        out["status"] = 0
        out["error"] = str(e)
    out["wall_s"] = round(time.monotonic() - t0, 6)
    out["ok"] = out["status"] == 200
    return out


def replay(api_url: str, trace: List[Dict[str, Any]],
           timeout: float = 120.0) -> List[Dict[str, Any]]:
    """Fire the trace open-loop at `api_url` (one thread per request,
    launched at its scheduled offset) and return per-request results in
    trace order. Failures are recorded, never raised — the report decides
    what an error rate means."""
    results: List[Optional[Dict[str, Any]]] = [None] * len(trace)
    t0 = time.monotonic()

    def worker(idx: int, item: Dict[str, Any]) -> None:
        delay = item["at_s"] - (time.monotonic() - t0)
        if delay > 0:
            time.sleep(delay)
        results[idx] = _fire(api_url, item, timeout)

    threads = [threading.Thread(target=worker, args=(i, item), daemon=True)
               for i, item in enumerate(trace)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=timeout + trace[-1]["at_s"] + 10 if trace else 10)
    # a hung worker's placeholder keeps the schema (at_s/wall_s) so the
    # report can still be assembled — the degraded-fleet scenario is
    # exactly when the harness must NOT crash
    return [r if r is not None
            else {"at_s": trace[i]["at_s"], "wall_s": timeout, "status": 0,
                  "ok": False, "error": "worker hung"}
            for i, r in enumerate(results)]


def _client_percentiles(walls: List[float]) -> Dict[str, float]:
    if not walls:
        return {label: float("nan") for _, label in PERCENTILES}
    s = sorted(walls)
    return {label: round(s[min(len(s) - 1, int(q * len(s))) ], 6)
            for q, label in PERCENTILES}


def slo_report(results: List[Dict[str, Any]],
               metrics_before: List[scrape.Samples],
               metrics_after: List[scrape.Samples],
               offered_rps: float) -> Dict[str, Any]:
    """Assemble the SLO report: engine-side TTFT/TPOT percentiles from
    the diffed histogram windows (merged across replicas), client-side
    wall percentiles, achieved throughput, and the failure ledger."""
    deltas = [scrape.diff_samples(b, a)
              for b, a in zip(metrics_before, metrics_after)]
    ttft = {label: scrape.merged_histogram_percentile(
                deltas, "engine_ttft_seconds", q)
            for q, label in PERCENTILES}
    tpot = {label: scrape.merged_histogram_percentile(
                deltas, "engine_time_per_output_token_seconds", q)
            for q, label in PERCENTILES}
    ok = [r for r in results if r.get("ok")]
    failed = [r for r in results if not r.get("ok")]
    span = (max(r["at_s"] + r["wall_s"] for r in results)
            - min(r["at_s"] for r in results)) if results else 0.0
    by_status: Dict[str, int] = {}
    for r in results:
        key = str(r.get("status", 0))
        by_status[key] = by_status.get(key, 0) + 1
    return {
        "offered_rps": offered_rps,
        "achieved_rps": round(len(ok) / span, 3) if span > 0 else 0.0,
        "requests": len(results),
        "completed": len(ok),
        "failed": len(failed),
        "status_counts": by_status,
        "ttft_s": ttft,
        "tpot_s": tpot,
        "client_wall_s": _client_percentiles(
            [r["wall_s"] for r in ok if "wall_s" in r]),
    }


def run_slo(api_url: str, metrics_urls: List[str],
            trace: List[Dict[str, Any]], offered_rps: float,
            timeout: float = 120.0) -> Dict[str, Any]:
    """Scrape → replay → scrape → report. `api_url` is the front door
    (the router, or one replica for a solo baseline); `metrics_urls` are
    the REPLICA /metrics endpoints (the router's own histogram measures
    dispatch wall, not token latency). A replica whose scrape fails
    contributes an empty window (counted in scrape_errors) instead of
    killing the run."""
    def scrape_all() -> List[scrape.Samples]:
        out = []
        for u in metrics_urls:
            try:
                out.append(scrape.scrape(u, timeout=5.0))
            except (OSError, urllib.error.URLError, ValueError):
                out.append({})
        return out

    before = scrape_all()
    results = replay(api_url, trace, timeout=timeout)
    after = scrape_all()
    report = slo_report(results, before, after, offered_rps)
    # a failed BEFORE scrape matters as much as a failed AFTER one: its
    # empty window makes diff_samples keep the replica's full cumulative
    # history (warmup included) — the report must flag that the
    # percentiles are not cleanly windowed
    report["scrape_errors"] = sum(1 for s in before + after if not s)
    return report

"""Serving fleet control plane (docs/serving.md "Fleet").

The reference repo's L7 is one Flask process; "heavy traffic from millions
of users" needs a fleet that survives replica death and ships new weights
without dropping requests. This package is that control plane, composed
from machinery earlier PRs built:

  * router.py   — health-aware least-loaded front door over N replicas,
                  with per-replica circuit breaker, bounded
                  retry-with-backoff, failover, and rolling weight
                  updates (drain -> reload -> readmit, one replica at a
                  time). Dispatch reads the slot/queue Prometheus gauges
                  and /readyz the replicas already expose (PR 3).
  * replica.py  — subprocess replica handle + the `python -m
                  megatron_tpu.inference.fleet.replica` entry point the
                  chaos tests SIGKILL.
  * migration.py— KV-state migration wire format (manifest + per-section
                  crc commit contract, torn transfers rejected loudly),
                  the HTTP client half of request/prefix handoff, and
                  the fleet-level PrefixDirectory.
  * reload.py   — manifest-verified committed-checkpoint param loads
                  (PR 2's verify_checkpoint machinery) feeding
                  InferenceEngine.update_params hot swaps.
  * scrape.py   — minimal Prometheus text-format parsing (gauges +
                  histogram-bucket percentiles) for the router's prober
                  and the SLO harness.
  * slo.py      — offered-load traffic replay reporting TTFT/TPOT
                  percentiles from the telemetry histograms
                  (tools/slo_harness.py is the CLI).

Everything here is pure host code — zero new collectives (the golden comm
manifests are unchanged; tools/comm_report.py --check).
"""

from megatron_tpu.inference.fleet.migration import (  # noqa: F401
    MigrationIntegrityError, PrefixDirectory, pack_state, replicate_prefix,
    unpack_state,
)
from megatron_tpu.inference.fleet.reload import (  # noqa: F401
    load_verified_params, save_params_checkpoint,
)
from megatron_tpu.inference.fleet.router import (  # noqa: F401
    ReplicaRouter, RouterServer, fleet_retry_after,
)
from megatron_tpu.inference.fleet.replica import ReplicaProcess  # noqa: F401

__all__ = [
    "ReplicaRouter",
    "RouterServer",
    "ReplicaProcess",
    "MigrationIntegrityError",
    "PrefixDirectory",
    "fleet_retry_after",
    "load_verified_params",
    "pack_state",
    "replicate_prefix",
    "save_params_checkpoint",
    "unpack_state",
]

"""Manifest-verified weight loads for hot reload (docs/serving.md).

A rolling weight update must never push a torn or bitrotted checkpoint
into a serving replica: this module is the read-side bridge between PR 2's
crash-safe checkpoint commits (training/checkpointing.py: manifest commit
record, verify_checkpoint, list_valid_checkpoints) and the engine's
between-tick `update_params` swap. A checkpoint is only eligible when its
manifest verifies; on a garbage tracker or torn newest save the default
pick falls back to the newest VALID committed iteration, exactly like
training resume does.

`save_params_checkpoint` is the matching write-side helper for serving
tools and tests: a params-only checkpoint with the same staging ->
manifest -> rename commit discipline (and therefore readable by
`load_params_only`), without materializing a full TrainState.
"""

from __future__ import annotations

import os
import shutil
from typing import Any, Optional, Tuple

from megatron_tpu.training import checkpointing as ckpt


class NoValidCheckpointError(RuntimeError):
    """No committed checkpoint in the load dir passes manifest verify."""


def resolve_reload_iteration(load: str, iteration: Optional[int] = None,
                             deep: bool = False) -> int:
    """The iteration a reload should serve: `iteration` if pinned (it must
    verify — a pinned-but-corrupt checkpoint is an operator error worth a
    loud failure, not a silent fallback), else the newest iteration whose
    manifest verifies."""
    if iteration is not None:
        ok, detail = ckpt.verify_checkpoint(
            ckpt.checkpoint_dir(load, iteration), deep=deep)
        if not ok:
            raise NoValidCheckpointError(
                f"checkpoint iter {iteration} under {load} failed "
                f"verification: {detail}")
        return int(iteration)
    valid = ckpt.list_valid_checkpoints(load, deep=deep)
    if not valid:
        raise NoValidCheckpointError(
            f"no committed checkpoint under {load} passes manifest "
            "verification")
    return valid[-1]


def load_verified_params(load: str, params_template: Any,
                         iteration: Optional[int] = None,
                         deep: bool = False,
                         shardings=None) -> Tuple[Any, int]:
    """(params, iteration): manifest-verify then restore just the params
    subtree (fp32 master copies preferred when present, cast to the
    template's dtypes — checkpointing.load_params_only)."""
    it = resolve_reload_iteration(load, iteration, deep=deep)
    params = ckpt.load_params_only(load, params_template, iteration=it,
                                   shardings=shardings)
    return params, it


def save_params_checkpoint(save: str, iteration: int, params: Any) -> str:
    """Commit a params-only checkpoint at `iteration` under `save` with
    the full atomic discipline: stage -> orbax write -> manifest commit ->
    rename -> tracker bump. The saved tree is `{"params": ...}`, the shape
    load_params_only restores (no master subtree: serving saves are
    already in serving dtype)."""
    import orbax.checkpoint as ocp

    stage = ckpt._staging_dir(save, iteration)
    shutil.rmtree(stage, ignore_errors=True)
    os.makedirs(os.path.dirname(stage), exist_ok=True)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.join(stage, "state"), {"params": params}, force=True)
    ckptr.wait_until_finished()
    return ckpt._finalize(save, stage, iteration, consumed_samples=0,
                          config=None, keep_latest_k=None)

"""Replica router: health-aware least-loaded front door over N replicas.

One process is a single point of failure and a single jit queue; the fleet
story routes every client request through this router instead:

  * dispatch picks the READY replica with the lowest load score — busy
    slots + queue depth from the replica's own Prometheus gauges (scraped
    by a background prober), plus the router's live count of requests it
    has in flight there (the gauges go stale between scrapes; the local
    count covers the gap);
  * a failed attempt (connect error, timeout, HTTP 5xx) fails over to a
    different replica with bounded backoff — a SIGKILLed or hung replica
    costs the affected clients ONE retry, never a lost request;
  * per-replica circuit breaker: `breaker_failures` consecutive failures
    open the breaker for an exponentially growing window (capped), so a
    dead replica stops eating attempt budget; when the window expires the
    next dispatch is the half-open trial — success closes the breaker,
    failure re-opens it wider. The prober's consecutive /readyz successes
    also close it (a restarted replica is readmitted without burning a
    client request as the trial);
  * 503s (replica queue full / draining) are routed around WITHOUT
    breaker penalty — an overloaded replica is healthy, just busy;
  * HTTP 4xx pass through untouched (a malformed request fails the same
    on every replica — retrying would just triple the error rate).

Rolling weight update (docs/serving.md): one replica at a time — stop
routing to it, POST /admin/drain (in-flight requests finish), POST
/admin/reload (manifest-verified params swap, fleet/reload.py), POST
/admin/readmit, wait for /readyz, restore routing. Zero dropped requests
and zero decode-step recompiles, by construction and by test
(tests/test_fleet.py).

Retry honesty (docs/serving.md): greedy requests and requests carrying a
`random_seed` are deterministic in (prompt, knobs, seed), so a failover
retry recomputes the identical response the dead replica would have.
Sampled requests WITHOUT an explicit seed fall back to the server-side
default chain — a retry replays that chain, but across mixed weight
versions (mid rolling update) the replay is not guaranteed to match what
the dead replica would have emitted, so the router journals
`serve_retry_resampled` whenever such a request succeeds only after a
mid-flight replica failure. Handoff drains (drain_replica / SIGTERM with
peers) avoid the retry entirely: the PRNG chain migrates with the request
(fleet/migration.py) and the continuation is token-identical.

Global admission (fleet-wide): with `global_max_queue` set, dispatch
rejects up front when the whole fleet's queue depth (scraped load +
router-local in-flight counts) is at the bound — an honest fast 503 with
a fleet-derived Retry-After (queue depth / drain ETA, fleet_retry_after)
instead of burning an attempt sweep to discover that every replica is
individually full.

Pure host code: no jax import anywhere in the fleet control plane.
"""

from __future__ import annotations

import json
import math
import socket
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

from megatron_tpu.inference.fleet import scrape
from megatron_tpu.inference.fleet.migration import (
    PrefixDirectory, replicate_prefix,
)
from megatron_tpu.telemetry import journal as _journal
from megatron_tpu.telemetry.metrics import MetricsRegistry, default_registry

#: floor/legacy Retry-After on router-level 503 — dispatch now derives the
#: real hint from fleet state (fleet_retry_after); this constant survives
#: as the minimum and for callers that imported it
ROUTER_RETRY_AFTER_SECONDS = 1


def fleet_retry_after(queue_depth: float, routable: int,
                      per_replica_rps: float = 2.0,
                      drain_eta_s: Optional[float] = None,
                      min_s: int = ROUTER_RETRY_AFTER_SECONDS,
                      max_s: int = 60) -> int:
    """Honest Retry-After from fleet state: the seconds until the fleet
    can plausibly absorb one more request.

    With routable replicas, that is the time to work off the current
    fleet-wide queue depth at the fleet's aggregate service rate
    (`routable * per_replica_rps`). With NONE routable (every replica
    draining or dead), it is the drain ETA when the caller knows one,
    else the cap. Clamped to [min_s, max_s] — a Retry-After of 0 invites
    an immediate re-hit and one beyond the cap parks clients longer than
    any breaker/drain in this stack lasts."""
    if routable < 1:
        eta = max_s if drain_eta_s is None else drain_eta_s
    else:
        eta = queue_depth / (routable * max(per_replica_rps, 1e-6))
    return int(max(min_s, min(max_s, math.ceil(eta))))


class NoReplicaAvailableError(RuntimeError):
    """Every replica is breaker-open or unreachable."""


class ReplicaState:
    """Router-side view of one replica (all mutation under the router
    lock; the prober and dispatch threads both write here)."""

    def __init__(self, url: str):
        self.url = url.rstrip("/")
        # prober-owned
        self.alive = True         # /readyz answered at all
        self.ready = True         # /readyz said ok (optimistic at start:
        #                           the first probe corrects within one
        #                           interval; pessimistic-start would
        #                           blackhole traffic until the prober ran)
        self.ready_streak = 0     # consecutive successful probes
        self.load = 0.0           # scraped slots_active + queue_depth
        self.last_probe: Optional[float] = None
        # dispatch-owned
        self.outstanding = 0      # router requests in flight RIGHT NOW
        self.failures = 0         # consecutive dispatch failures
        self.breaker_opens = 0    # times opened since last success
        self.breaker_open_until = 0.0
        # rolling-update ownership: excluded from dispatch while True
        self.updating = False

    def breaker_open(self, now: float) -> bool:
        return self.breaker_open_until > now

    def snapshot(self) -> Dict[str, Any]:
        return {"url": self.url, "alive": self.alive, "ready": self.ready,
                "load": self.load, "outstanding": self.outstanding,
                "failures": self.failures,
                "breaker_open_until": self.breaker_open_until,
                "updating": self.updating}


class ReplicaRouter:
    """Dispatch + health logic (RouterServer wraps it in HTTP)."""

    def __init__(self, urls: List[str],
                 request_timeout: float = 60.0,
                 probe_interval: float = 0.5,
                 probe_timeout: float = 2.0,
                 max_attempts: Optional[int] = None,
                 retry_backoff_s: float = 0.05,
                 breaker_failures: int = 3,
                 breaker_base_s: float = 0.5,
                 breaker_max_s: float = 15.0,
                 readmit_streak: int = 2,
                 metrics: Optional[MetricsRegistry] = None,
                 global_max_queue: Optional[int] = None,
                 service_rate_rps: float = 2.0):
        """global_max_queue: fleet-wide admission bound — dispatch answers
        503 + fleet-derived Retry-After once the summed queue depth
        (scraped replica load + router in-flight) reaches it, replacing
        per-replica 503 discovery. service_rate_rps: assumed per-replica
        completion rate feeding the Retry-After math (fleet_retry_after);
        calibrate from the SLO harness, not precision-critical — it only
        shapes the backoff hint."""
        if not urls:
            raise ValueError("router needs at least one replica URL")
        self.replicas = [ReplicaState(u) for u in urls]
        self.global_max_queue = (int(global_max_queue)
                                 if global_max_queue is not None else None)
        self.service_rate_rps = float(service_rate_rps)
        #: fleet-level prefix directory: which replicas hold which
        #: registered prefixes (register_prefix fills it)
        self.prefix_directory = PrefixDirectory()
        self.request_timeout = float(request_timeout)
        self.probe_interval = float(probe_interval)
        self.probe_timeout = float(probe_timeout)
        # default attempt budget: every replica once, plus one half-open
        # retry — bounded, so a client never waits on an unbounded loop
        self.max_attempts = (int(max_attempts) if max_attempts
                             else len(urls) + 1)
        self.retry_backoff_s = float(retry_backoff_s)
        self.breaker_failures = int(breaker_failures)
        self.breaker_base_s = float(breaker_base_s)
        self.breaker_max_s = float(breaker_max_s)
        self.readmit_streak = int(readmit_streak)
        self._lock = threading.Lock()
        self._prober: Optional[threading.Thread] = None
        self._stop = threading.Event()

        m = metrics if metrics is not None else default_registry()
        self.metrics = m
        self._m_requests = m.counter("router_requests_total",
                                     "routed requests by outcome",
                                     label_names=("status",))
        self._m_retries = m.counter(
            "router_retries_total",
            "dispatch attempts beyond the first, per request")
        self._m_failovers = m.counter(
            "router_failovers_total",
            "requests that succeeded on a different replica after a "
            "failed attempt")
        self._m_breaker = m.counter(
            "router_breaker_opens_total",
            "circuit-breaker openings across the fleet")
        self._m_ready = m.gauge("router_replicas_ready",
                                "replicas currently routable")
        self._m_dispatch = m.histogram(
            "router_dispatch_seconds",
            "front-door request wall time (retries included)")
        self._m_admission = m.counter(
            "router_admission_rejected_total",
            "requests rejected by the fleet-wide admission bound")
        self._m_ready.set(len(self.replicas))

    # ----- health / probing ------------------------------------------------

    def start(self) -> "ReplicaRouter":
        """Spawn the background prober (idempotent)."""
        if self._prober is None:
            self._stop.clear()
            self._prober = threading.Thread(target=self._probe_loop,
                                            daemon=True,
                                            name="router-prober")
            self._prober.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._prober is not None:
            self._prober.join(timeout=5)
            self._prober = None

    def _probe_loop(self) -> None:
        while not self._stop.is_set():
            self.probe_once()
            self._stop.wait(self.probe_interval)

    def probe_once(self) -> None:
        """One probe round: /readyz + /metrics gauges for every replica
        (also callable directly from tests — no thread needed)."""
        for rep in self.replicas:
            ready, alive = False, False
            load = float("inf")
            try:
                with urllib.request.urlopen(rep.url + "/readyz",
                                            timeout=self.probe_timeout) as r:
                    alive = True
                    ready = r.status == 200
            except urllib.error.HTTPError as e:
                alive = True          # it answered; 503 = not ready
                ready = e.code == 200
            except (OSError, urllib.error.URLError):
                pass
            if ready:
                try:
                    load = scrape.replica_load(
                        scrape.scrape(rep.url + "/metrics",
                                      timeout=self.probe_timeout))
                except (OSError, urllib.error.URLError, ValueError):
                    load = 0.0        # ready but metrics raced — don't
                    #                   penalize below scraped replicas
            with self._lock:
                was_ready = rep.ready
                rep.alive = alive
                rep.ready = ready
                rep.load = load if ready else float("inf")
                rep.last_probe = time.monotonic()
                rep.ready_streak = rep.ready_streak + 1 if ready else 0
                if (ready and rep.ready_streak >= self.readmit_streak
                        and rep.breaker_open(time.monotonic())):
                    # a restarted replica proves itself via consecutive
                    # readiness probes — readmit without burning a client
                    # request as the half-open trial
                    rep.breaker_open_until = 0.0
                    rep.failures = 0
                    rep.breaker_opens = 0
                    self._journal("replica_readmitted", replica=rep.url)
                if was_ready != ready:
                    self._journal("replica_ready_change", replica=rep.url,
                                  ready=ready)
            self._m_ready.set(self._num_routable())

    def _num_routable(self) -> int:
        now = time.monotonic()
        with self._lock:
            return sum(1 for r in self.replicas
                       if r.ready and not r.breaker_open(now)
                       and not r.updating)

    # ----- dispatch --------------------------------------------------------

    def _pick(self, exclude: set) -> Optional[ReplicaState]:
        """Least-loaded routable replica not in `exclude`; falls back to
        breaker-closed-but-unready ones (probe lag at startup, or a fleet
        whose probes fail while requests would succeed), then None."""
        now = time.monotonic()
        with self._lock:
            open_ok = [r for r in self.replicas
                       if r not in exclude and not r.updating
                       and not r.breaker_open(now)]
            ready = [r for r in open_ok if r.ready]
            pool = ready or open_ok
            if not pool:
                return None
            return min(pool, key=lambda r: (r.load + r.outstanding,
                                            r.outstanding))

    def _post(self, url: str, body: bytes, timeout: float,
              content_type: str = "application/json"
              ) -> Tuple[int, Dict[str, str], bytes]:
        req = urllib.request.Request(
            url, data=body, method="POST",
            headers={"Content-Type": content_type})
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.status, dict(resp.headers), resp.read()
        except urllib.error.HTTPError as e:
            # non-2xx WITH a response (4xx/5xx): the transport worked
            return e.code, dict(e.headers or {}), e.read()

    def _record_failure(self, rep: ReplicaState, reason: str) -> None:
        with self._lock:
            rep.failures += 1
            if rep.failures >= self.breaker_failures:
                backoff = min(self.breaker_base_s * (2 ** rep.breaker_opens),
                              self.breaker_max_s)
                rep.breaker_open_until = time.monotonic() + backoff
                rep.breaker_opens += 1
                rep.failures = 0   # the half-open trial starts a new streak
                rep.ready_streak = 0
                self._m_breaker.inc()
                self._journal("replica_breaker_open", replica=rep.url,
                              backoff_s=round(backoff, 3), reason=reason)
        self._m_ready.set(self._num_routable())

    def _record_success(self, rep: ReplicaState) -> None:
        with self._lock:
            rep.failures = 0
            rep.breaker_opens = 0
            rep.breaker_open_until = 0.0

    def _fleet_queue_depth(self) -> float:
        """Fleet-wide queued+running work: the scraped per-replica load
        (busy slots + queue depth) plus the router's own in-flight counts
        (the gauges go stale between scrapes). An unreachable replica
        (load inf) contributes nothing — it holds no work we can count."""
        with self._lock:
            return sum((0.0 if r.load == float("inf") else r.load)
                       + r.outstanding for r in self.replicas)

    def _retry_after(self, depth: Optional[float] = None) -> int:
        if depth is None:
            depth = self._fleet_queue_depth()
        return fleet_retry_after(depth, self._num_routable(),
                                 per_replica_rps=self.service_rate_rps)

    def dispatch(self, body: bytes,
                 timeout: Optional[float] = None
                 ) -> Tuple[int, Dict[str, str], bytes]:
        """Route one /api request; returns (status, headers, body). Every
        failure path is bounded: at most max_attempts tries, each capped
        by request_timeout, with retry_backoff_s between full sweeps.
        With global_max_queue set, a fleet at the bound is rejected here
        (503 + fleet-derived Retry-After) before any attempt is spent."""
        t0 = time.monotonic()
        if self.global_max_queue is not None:
            depth = self._fleet_queue_depth()
            if depth >= self.global_max_queue:
                retry_after = self._retry_after(depth)
                self._m_admission.inc()
                self._m_requests.inc(status="503")
                self._journal("serve_admission", accepted=False,
                              queue_depth=round(depth, 1),
                              bound=self.global_max_queue,
                              retry_after_s=retry_after)
                return (503, {"Retry-After": str(retry_after)},
                        json.dumps({
                            "message": "fleet at admission bound "
                                       f"(queue depth {depth:.0f} >= "
                                       f"{self.global_max_queue}); retry "
                                       f"after {retry_after}s"}).encode())
        deadline = t0 + (timeout if timeout is not None
                         else self.request_timeout * self.max_attempts)
        tried: set = set()
        attempts = 0
        failed_mid_flight = False
        last: Tuple[int, Dict[str, str], bytes] = (
            503, {}, json.dumps({"message": "no replica available"}).encode())
        while attempts < self.max_attempts and time.monotonic() < deadline:
            rep = self._pick(tried)
            if rep is None and tried:
                # full sweep failed: back off once, then allow re-trying
                # replicas we already hit (their breaker may have closed,
                # or the 503 was momentary)
                time.sleep(self.retry_backoff_s)
                tried = set()
                rep = self._pick(tried)
            if rep is None:
                break
            attempts += 1
            if attempts > 1:
                self._m_retries.inc()
            with self._lock:
                rep.outstanding += 1
            try:
                status, headers, rbody = self._post(
                    rep.url + "/api", body,
                    timeout=min(self.request_timeout,
                                max(deadline - time.monotonic(), 0.001)))
            except (socket.timeout, TimeoutError, ConnectionError, OSError,
                    urllib.error.URLError) as e:
                self._record_failure(rep, f"{type(e).__name__}: {e}")
                tried.add(rep)
                failed_mid_flight = True
                last = (502, {}, json.dumps(
                    {"message": f"replica {rep.url} failed: {e}"}).encode())
                continue
            finally:
                with self._lock:
                    rep.outstanding = max(0, rep.outstanding - 1)
            if status == 503:
                # queue-full/draining: healthy, just busy — no breaker
                # penalty, try the next-least-loaded replica (MUST be
                # checked before the 5xx arm below, or every overloaded
                # reply would count toward opening the breaker)
                tried.add(rep)
                last = (status, headers, rbody)
                continue
            if status >= 500 and status != 504:
                # replica-side internal failure: penalize + fail over.
                # 504 is excluded: an expired request deadline means the
                # client's budget is already spent — retrying would double
                # the wasted compute, and deadline expiries on a healthy
                # replica must not open its breaker
                self._record_failure(rep, f"http {status}")
                tried.add(rep)
                failed_mid_flight = True
                last = (status, headers, rbody)
                continue
            # success or pass-through client error (4xx, 504 deadline)
            self._record_success(rep)
            wall = time.monotonic() - t0
            self._m_requests.inc(status=str(status))
            self._m_dispatch.observe(wall)
            if attempts > 1:
                self._m_failovers.inc()
            if status == 200 and failed_mid_flight:
                # retry honesty: a replica may have died MID-generation
                # and this success is a from-scratch re-run elsewhere —
                # flag the re-runs whose sampling the client did not pin
                self._maybe_journal_resample(body, rep, attempts)
            self._journal("serve_route", replica=rep.url, status=status,
                          attempts=attempts, wall_s=round(wall, 6))
            return status, headers, rbody
        # attempt budget or deadline exhausted
        status, headers, rbody = last
        if status == 503:
            # the honest hint: derived from live fleet state at give-up
            # time, not whatever constant the last replica answered with
            headers = dict(headers)
            headers["Retry-After"] = str(self._retry_after())
        wall = time.monotonic() - t0
        self._m_requests.inc(status=str(status))
        self._m_dispatch.observe(wall)
        self._journal("serve_route", replica=None, status=status,
                      attempts=attempts, wall_s=round(wall, 6),
                      exhausted=True)
        return status, headers, rbody

    def _maybe_journal_resample(self, request_body: bytes, rep,
                                attempts: int) -> None:
        """Journal `serve_retry_resampled` when a request that succeeded
        only after a mid-flight replica failure was sampled WITHOUT an
        explicit random_seed (docs/serving.md "Retry honesty"): greedy
        and client-seeded requests replay deterministically on the
        retry, unseeded sampled ones replay the server-default chain —
        honest under one weight version, not across a mid-update mix."""
        try:
            req = json.loads(request_body or b"{}")
        except ValueError:
            return
        if not isinstance(req, dict):
            return
        try:
            temperature = float(req.get("temperature", 1.0))
            n = int(req.get("tokens_to_generate", 64))
        except (TypeError, ValueError):
            return
        if temperature <= 0.0 or n <= 0 or "random_seed" in req:
            return
        self._journal("serve_retry_resampled", replica=rep.url,
                      attempts=attempts, seeded=False)

    # ----- rolling weight update ------------------------------------------

    def _admin(self, rep: ReplicaState, path: str, payload: Dict[str, Any],
               timeout: float) -> Tuple[int, Dict[str, Any]]:
        """Admin POST that NEVER raises: transport failures return status
        0, so rolling_update's cleanup (readmit + `updating = False`)
        always runs — an unreachable replica must not stay excluded from
        dispatch forever because its update turn threw."""
        try:
            status, _, body = self._post(rep.url + path,
                                         json.dumps(payload).encode(),
                                         timeout=timeout)
        except (OSError, urllib.error.URLError) as e:
            return 0, {"message": f"{type(e).__name__}: {e}"}
        try:
            return status, json.loads(body or b"{}")
        except ValueError:
            return status, {"message": body.decode("utf-8", "replace")}

    def drain_replica(self, url: str, handoff: bool = True,
                      timeout: float = 60.0) -> Dict[str, Any]:
        """Drain ONE replica with live-request handoff: stop routing to
        it, then POST /admin/drain naming the other replicas as handoff
        peers — its in-flight and queued requests MIGRATE to them
        (fleet/migration.py) instead of being waited out or failed. The
        pre-SIGTERM step for scale-down/preemption: after this returns
        drained=True the replica holds zero client state and can be
        killed without a single failed request. handoff=False falls back
        to the classic wait-for-idle drain."""
        target = url.rstrip("/")
        rep = next((r for r in self.replicas if r.url == target), None)
        if rep is None:
            raise ValueError(f"unknown replica {url!r}")
        peers = ([r.url for r in self.replicas if r is not rep]
                 if handoff else [])
        with self._lock:
            rep.updating = True   # unroute while the drain runs
        try:
            payload: Dict[str, Any] = {"timeout_s": timeout}
            if peers:
                payload["handoff"] = peers
            status, resp = self._admin(rep, "/admin/drain", payload,
                                       timeout=timeout + self.probe_timeout)
            self._journal("fleet_drain", replica=rep.url, status=status,
                          handoff_peers=len(peers),
                          drained=bool(resp.get("drained")))
            return {"replica": rep.url, "status": status,
                    "drained": bool(resp.get("drained")),
                    "handoff": peers, "response": resp}
        finally:
            # routing resumes only when the replica's own /readyz does
            # (it answers 503 while draining) — clearing the flag just
            # returns ownership to the prober
            with self._lock:
                rep.updating = False

    def register_prefix(self, tokens: List[int],
                        timeout: float = 60.0) -> Dict[str, Any]:
        """Fleet-wide prefix (system prompt) registration: prime ONE
        replica's radix cache with a real prefill, then fan its pages out
        to every other replica via page export
        (migration.replicate_prefix) — the prefix becomes a radix hit
        FLEET-WIDE for the cost of one prefill plus N-1 page transfers,
        and the prefix_directory records who holds it."""
        toks = [int(t) for t in tokens]
        if not toks:
            raise ValueError("tokens: non-empty int list required")
        rep = self._pick(set())
        if rep is None:
            raise NoReplicaAvailableError(
                "no routable replica to prime the prefix on")
        status, resp = self._admin(rep, "/admin/register_prefix",
                                   {"tokens": toks}, timeout=timeout)
        if status != 200:
            raise RuntimeError(
                f"prefix prime on {rep.url} failed (http {status}): "
                f"{resp.get('message', resp)}")
        self.prefix_directory.register(toks, rep.url)
        dests = [r.url for r in self.replicas if r is not rep]
        fanout = replicate_prefix(rep.url, dests, toks, timeout=timeout)
        for entry in fanout["replicated"]:
            if entry["status"] == 200:
                self.prefix_directory.register(toks, entry["url"])
        locations = self.prefix_directory.locations(toks)
        self._journal("fleet_prefix_register", tokens=len(toks),
                      primary=rep.url, pages=resp.get("pages"),
                      locations=len(locations),
                      wire_bytes=fanout["bytes"])
        return {"primary": rep.url, "pages": resp.get("pages"),
                "replicated": fanout["replicated"],
                "locations": locations, "wire_bytes": fanout["bytes"]}

    def rolling_update(self, load: Optional[str] = None,
                       iteration: Optional[int] = None,
                       drain_timeout: float = 60.0,
                       reload_timeout: float = 300.0,
                       ready_timeout: float = 60.0,
                       handoff: bool = False) -> List[Dict[str, Any]]:
        """Ship new weights across the fleet under live traffic, one
        replica at a time: unroute -> drain (in-flight requests finish on
        the old weights) -> reload (manifest-verified swap) -> readmit ->
        wait ready -> reroute. A request is therefore always served END TO
        END by one weight version. Stops at the first failing replica
        (readmitting it with its old weights) so a bad checkpoint can't
        take the whole fleet down; the survivors keep serving.

        handoff=True migrates each replica's in-flight requests to its
        peers during the drain instead of waiting them out — faster
        update turns under long-decode traffic, at the cost of those
        requests finishing on the OLD weights of a peer (which the
        one-version-per-request claim already allows: the whole request
        completes on whichever replica finishes it).

        Returns one result dict per replica attempted."""
        results: List[Dict[str, Any]] = []
        for rep in self.replicas:
            out: Dict[str, Any] = {"replica": rep.url}
            with self._lock:
                rep.updating = True
            self._journal("rolling_update_step", replica=rep.url,
                          phase="drain")
            try:
                drain_payload: Dict[str, Any] = {"timeout_s": drain_timeout}
                if handoff:
                    drain_payload["handoff"] = [
                        r.url for r in self.replicas if r is not rep]
                status, resp = self._admin(
                    rep, "/admin/drain", drain_payload,
                    timeout=drain_timeout + self.probe_timeout)
                out["drain"] = resp
                if status != 200 or not resp.get("drained"):
                    out["error"] = f"drain failed (http {status}): {resp}"
                    break
                self._journal("rolling_update_step", replica=rep.url,
                              phase="reload")
                payload: Dict[str, Any] = {}
                if load is not None:
                    payload["load"] = load
                if iteration is not None:
                    payload["iteration"] = iteration
                status, resp = self._admin(rep, "/admin/reload", payload,
                                           timeout=reload_timeout)
                out["reload"] = resp
                if status != 200:
                    out["error"] = f"reload failed (http {status}): {resp}"
                    break
                out["version"] = resp.get("version")
            finally:
                # ALWAYS readmit — a failed reload leaves the replica
                # serving its old weights, which beats serving nothing
                status, resp = self._admin(rep, "/admin/readmit", {},
                                           timeout=self.probe_timeout + 5)
                out["readmit"] = resp
                ok = self._wait_replica_ready(rep, ready_timeout)
                out["ready"] = ok
                with self._lock:
                    rep.updating = False
                self._journal("rolling_update_step", replica=rep.url,
                              phase="done", ok="error" not in out)
                results.append(out)
        return results

    def _wait_replica_ready(self, rep: ReplicaState,
                            timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(rep.url + "/readyz",
                                            timeout=self.probe_timeout) as r:
                    if r.status == 200:
                        return True
            except urllib.error.HTTPError:
                pass
            except (OSError, urllib.error.URLError):
                pass
            time.sleep(0.05)
        return False

    # ----- misc ------------------------------------------------------------

    def status(self) -> Dict[str, Any]:
        now = time.monotonic()
        with self._lock:
            reps = [dict(r.snapshot(), breaker_open=r.breaker_open(now))
                    for r in self.replicas]
        return {"replicas": reps, "routable": self._num_routable(),
                "queue_depth": round(self._fleet_queue_depth(), 1),
                "global_max_queue": self.global_max_queue,
                "retry_after_s": self._retry_after(),
                "prefixes": self.prefix_directory.snapshot()}

    def _journal(self, kind: str, **fields) -> None:
        j = _journal.get_global_journal()
        if j is not None:
            j.emit(kind, **fields)


def make_router_handler(router: ReplicaRouter):
    class Handler(BaseHTTPRequestHandler):
        def _reply(self, code: int, payload: Dict[str, Any], headers=()):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for name, value in headers:
                self.send_header(name, value)
            self.end_headers()
            self.wfile.write(body)

        def _proxy(self):
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length)
            status, headers, rbody = router.dispatch(body)
            self.send_response(status)
            self.send_header("Content-Type",
                             headers.get("Content-Type", "application/json"))
            self.send_header("Content-Length", str(len(rbody)))
            if "Retry-After" in headers:
                self.send_header("Retry-After", headers["Retry-After"])
            self.end_headers()
            self.wfile.write(rbody)

        def _handle_post(self):
            path = self.path.split("?", 1)[0]
            if path == "/api":
                self._proxy()
                return
            if path in ("/fleet/rolling_update", "/fleet/drain",
                        "/fleet/register_prefix"):
                length = int(self.headers.get("Content-Length", 0))
                try:
                    req = json.loads(self.rfile.read(length) or b"{}")
                except ValueError:
                    self._reply(400, {"message": "body must be JSON"})
                    return
                if path == "/fleet/rolling_update":
                    results = router.rolling_update(
                        load=req.get("load"),
                        iteration=req.get("iteration"),
                        drain_timeout=float(req.get("drain_timeout", 60.0)),
                        handoff=bool(req.get("handoff", False)))
                    ok = all("error" not in r for r in results)
                    self._reply(200 if ok else 500, {"results": results})
                    return
                try:
                    if path == "/fleet/drain":
                        self._reply(200, router.drain_replica(
                            str(req.get("url", "")),
                            handoff=bool(req.get("handoff", True)),
                            timeout=float(req.get("timeout_s", 60.0))))
                    else:
                        self._reply(200, router.register_prefix(
                            req.get("tokens") or [],
                            timeout=float(req.get("timeout_s", 60.0))))
                except NoReplicaAvailableError as e:
                    self._reply(503, {"message": str(e)})
                except ValueError as e:
                    self._reply(400, {"message": str(e)})
                except RuntimeError as e:
                    self._reply(502, {"message": str(e)})
                return
            self._reply(404, {"message": "POST serves /api, /fleet/"
                                         "rolling_update, /fleet/drain "
                                         "and /fleet/register_prefix"})

        do_POST = _handle_post
        do_PUT = _handle_post

        def do_GET(self):
            path = self.path.split("?", 1)[0]
            if path == "/metrics":
                from megatron_tpu.telemetry.http import (
                    PROMETHEUS_CONTENT_TYPE,
                )

                body = router.metrics.render().encode()
                self.send_response(200)
                self.send_header("Content-Type", PROMETHEUS_CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif path == "/healthz":
                self._reply(200, {"ok": True, "role": "router"})
            elif path == "/readyz":
                routable = router._num_routable()
                self._reply(200 if routable else 503,
                            {"ok": bool(routable), "routable": routable})
            elif path == "/fleet/status":
                self._reply(200, router.status())
            else:
                self._reply(404, {"message": "GET serves /metrics, "
                                             "/healthz, /readyz, "
                                             "/fleet/status"})

        def log_message(self, *a):  # quiet, like the replica servers
            pass

    return Handler


class RouterServer:
    """HTTP front door owning a ReplicaRouter + its serve thread."""

    def __init__(self, urls: List[str], host: str = "127.0.0.1",
                 port: int = 0, **router_kw):
        self.router = ReplicaRouter(urls, **router_kw)
        self._server = ThreadingHTTPServer(
            (host, port), make_router_handler(self.router))
        self.port = self._server.server_address[1]
        self.url = f"http://{host}:{self.port}"
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"router-:{self.port}")

    def start(self) -> "RouterServer":
        self.router.start()
        self._thread.start()
        return self

    def close(self) -> None:
        self.router.close()
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=10)

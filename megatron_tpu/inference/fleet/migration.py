"""KV-state migration wire format + fleet prefix directory.

This is the transport layer of the serving-churn story (docs/
fault_tolerance.md "Serving state migration"): the engine exports a
request's full resumable state (KV sections + scales, generated tokens,
PRNG resume key, sampling knobs — `InferenceEngine.export_request_state`)
as `(meta, sections)`, and this module turns that into ONE self-verifying
byte blob that can cross a process boundary:

    MAGIC | u32 manifest_len | manifest JSON | section payload bytes

The manifest is the commit record, borrowed from
`training/checkpointing.py`'s manifest + per-file crc contract: every
section's dtype/shape/offset/size/crc32 is committed in the header, and
`unpack_state` verifies ALL of it before handing a single array to the
engine. A torn transfer (truncated TCP stream, `migrate_fail` fault
injection) therefore fails loudly with `MigrationIntegrityError` on the
import side — the importer NEVER resumes from a half-received KV cache —
and the exporter walks down the degradation ladder
(migrate -> recompute-resume -> retry -> reject, server.py).

Also here, because they are fleet-level concerns with no engine state:

  * `post_blob` / `fetch_prefix` / `replicate_prefix` — the HTTP client
    half of the /admin/import, /admin/export_prefix and
    /admin/import_prefix endpoints (server.py is the other half);
  * `PrefixDirectory` — the router's fleet-level map from a registered
    prefix (system prompt) to the replicas known to hold its pages, so a
    prefix registered on replica A becomes a radix hit on replica B via
    page export instead of a re-prefill.

Pure host code: numpy + stdlib only (ml_dtypes for the bf16/fp8 wire
dtypes numpy cannot name). No jax import — the router process must be
able to relocate KV state without ever initialising a backend.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

MAGIC = b"MTPM"
FORMAT_VERSION = 1

#: header sanity bound: a manifest is a few KB of JSON; anything claiming
#: more is a corrupt length word, not a real manifest
_MAX_MANIFEST_BYTES = 16 * 1024 * 1024


class MigrationIntegrityError(RuntimeError):
    """A migration blob failed its commit contract (magic / manifest /
    length / crc). The transfer is torn or corrupt; the importer must
    reject it and the exporter must degrade down the ladder."""


def _dtype(name: str) -> np.dtype:
    """Resolve a manifest dtype name, falling back to ml_dtypes for the
    names numpy cannot construct (bfloat16, float8_e4m3fn, ...)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # baked into the jax toolchain

        return np.dtype(getattr(ml_dtypes, name))


def pack_state(meta: Dict[str, Any],
               sections: Dict[str, np.ndarray]) -> bytes:
    """Serialise `(meta, sections)` into one self-verifying blob.

    Section payloads are concatenated in sorted-name order; the manifest
    commits each section's dtype/shape/offset/size/crc32 plus the caller's
    `meta` dict, so `unpack_state` can verify the whole frame before
    reconstructing any array.
    """
    entries: Dict[str, Dict[str, Any]] = {}
    payload: List[bytes] = []
    offset = 0
    for name in sorted(sections):
        arr = np.ascontiguousarray(sections[name])
        raw = arr.tobytes()
        entries[name] = {
            "dtype": arr.dtype.name,
            "shape": list(arr.shape),
            "offset": offset,
            "size": len(raw),
            "crc32": f"{zlib.crc32(raw) & 0xFFFFFFFF:08x}",
        }
        payload.append(raw)
        offset += len(raw)
    head = json.dumps(
        {"format": FORMAT_VERSION, "meta": meta, "sections": entries},
        sort_keys=True).encode("utf-8")
    return b"".join(
        [MAGIC, len(head).to_bytes(4, "big"), head] + payload)


def unpack_state(blob: bytes) -> Tuple[Dict[str, Any], Dict[str, np.ndarray]]:
    """Verify and deserialise a `pack_state` blob.

    Raises MigrationIntegrityError on ANY contract violation: bad magic,
    truncated header, unknown format version, payload shorter/longer than
    the manifest committed, or a per-section crc mismatch. Returns the
    `(meta, sections)` the exporter packed.
    """
    if len(blob) < len(MAGIC) + 4 or blob[:len(MAGIC)] != MAGIC:
        raise MigrationIntegrityError(
            "migration blob: bad magic (not a migration frame, or the "
            "header itself was torn)")
    head_len = int.from_bytes(blob[len(MAGIC):len(MAGIC) + 4], "big")
    body_at = len(MAGIC) + 4
    if head_len > _MAX_MANIFEST_BYTES or body_at + head_len > len(blob):
        raise MigrationIntegrityError(
            f"migration blob: manifest length {head_len} exceeds frame "
            f"({len(blob)} bytes) — torn header")
    try:
        frame = json.loads(blob[body_at:body_at + head_len].decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise MigrationIntegrityError(
            f"migration blob: manifest is not valid JSON ({e})") from e
    if frame.get("format") != FORMAT_VERSION:
        raise MigrationIntegrityError(
            f"migration blob: format {frame.get('format')!r} != "
            f"{FORMAT_VERSION}")
    entries = frame.get("sections", {})
    payload = blob[body_at + head_len:]
    total = sum(int(e["size"]) for e in entries.values())
    if len(payload) != total:
        raise MigrationIntegrityError(
            f"migration blob: payload is {len(payload)} bytes, manifest "
            f"committed {total} — torn transfer")
    sections: Dict[str, np.ndarray] = {}
    for name, e in entries.items():
        raw = payload[int(e["offset"]):int(e["offset"]) + int(e["size"])]
        if len(raw) != int(e["size"]):
            raise MigrationIntegrityError(
                f"migration blob: section {name!r} truncated")
        crc = f"{zlib.crc32(raw) & 0xFFFFFFFF:08x}"
        if crc != e["crc32"]:
            raise MigrationIntegrityError(
                f"migration blob: section {name!r} crc {crc} != committed "
                f"{e['crc32']}")
        sections[name] = np.frombuffer(
            raw, dtype=_dtype(e["dtype"])).reshape(e["shape"])
    return frame.get("meta", {}), sections


def blob_wire_bytes(blob: bytes) -> int:
    """The manifest cost model: what the comm ledger charges for a
    transfer is exactly what went on the wire — the full frame."""
    return len(blob)


# ----- HTTP client half ------------------------------------------------


def post_blob(url: str, blob: bytes,
              timeout: float = 60.0) -> Tuple[int, Dict[str, Any]]:
    """POST a migration blob as application/octet-stream.

    Returns (status, parsed-JSON-body-or-{}). Transport errors surface as
    status 0 with the error text under "error" — callers treat any
    non-200 as a failed rung and degrade, so exceptions never escape.
    """
    req = urllib.request.Request(
        url, data=blob, method="POST",
        headers={"Content-Type": "application/octet-stream"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            body = resp.read()
            status = resp.status
    except urllib.error.HTTPError as e:
        body = e.read()
        status = e.code
    except (urllib.error.URLError, OSError, TimeoutError) as e:
        return 0, {"error": str(e)}
    try:
        return status, json.loads(body.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError):
        return status, {}


def fetch_prefix(url: str, tokens: Sequence[int],
                 timeout: float = 60.0) -> Optional[bytes]:
    """GET a packed prefix-state blob from a replica's
    /admin/export_prefix. Returns None when the replica does not hold the
    prefix (404) or cannot be reached."""
    body = json.dumps({"tokens": [int(t) for t in tokens]}).encode("utf-8")
    req = urllib.request.Request(
        url, data=body, method="POST",
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            if resp.status != 200:
                return None
            return resp.read()
    except (urllib.error.URLError, OSError, TimeoutError):
        return None


def replicate_prefix(src_url: str, dest_urls: Sequence[str],
                     tokens: Sequence[int],
                     timeout: float = 60.0) -> Dict[str, Any]:
    """Fan a cached prefix out from one replica to its peers via page
    export: fetch the packed pages from `src_url`'s /admin/export_prefix
    and POST them to each destination's /admin/import_prefix.

    Returns {"replicated": [{"url", "status", "pages"}...], "bytes": N}
    where bytes is the wire cost of ONE transfer (the same blob is reused
    for every destination; the ledger multiplies by fan-out).
    """
    blob = fetch_prefix(src_url + "/admin/export_prefix", tokens,
                        timeout=timeout)
    if blob is None:
        return {"replicated": [], "bytes": 0}
    out: List[Dict[str, Any]] = []
    for dest in dest_urls:
        status, body = post_blob(dest + "/admin/import_prefix", blob,
                                 timeout=timeout)
        out.append({"url": dest, "status": status,
                    "pages": int(body.get("pages", 0)) if body else 0})
    return {"replicated": out, "bytes": blob_wire_bytes(blob)}


# ----- fleet prefix directory ------------------------------------------


class PrefixDirectory:
    """Fleet-level map: registered prefix -> replicas known to hold its
    pages. The router records every successful register/replicate here so
    dispatch (and operators, via snapshot()) can see which replicas will
    radix-hit a given system prompt. Thread-safe; host memory only."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._where: Dict[Tuple[int, ...], set] = {}

    def register(self, tokens: Sequence[int], url: str) -> None:
        key = tuple(int(t) for t in tokens)
        with self._lock:
            self._where.setdefault(key, set()).add(url)

    def forget_replica(self, url: str) -> None:
        with self._lock:
            for urls in self._where.values():
                urls.discard(url)

    def locations(self, tokens: Sequence[int]) -> List[str]:
        key = tuple(int(t) for t in tokens)
        with self._lock:
            return sorted(self._where.get(key, ()))

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [{"prefix_len": len(k), "prefix_head": list(k[:8]),
                     "replicas": sorted(v)}
                    for k, v in self._where.items()]

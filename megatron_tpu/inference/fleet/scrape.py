"""Minimal Prometheus text-format scraping for the fleet control plane.

The router's prober reads the slot/queue gauges each replica already
exposes on /metrics (megatron_tpu/telemetry/metrics.py), and the SLO
harness reads TTFT/TPOT percentiles off the engine histograms — across
process boundaries, so the in-process Histogram.percentile() helper is out
of reach and the text exposition is the contract. This parser covers
exactly what our registry renders (and standard Prometheus clients emit
compatibly): `name{label="v",...} value` sample lines, `#` comments.

No jax import — the router is pure host code.
"""

from __future__ import annotations

import math
import re
import urllib.request
from typing import Dict, List, Tuple

#: parsed exposition: metric name -> list of (labels, value)
Samples = Dict[str, List[Tuple[Dict[str, str], float]]]

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)\s*$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
#: strict mode: the whole brace interior must be well-formed pairs
#: (commas inside quoted values are fine — the value part is quoted)
_LABEL_PAIR = r'[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'
_LABELS_FULL_RE = re.compile(
    rf"^(?:{_LABEL_PAIR})(?:,{_LABEL_PAIR})*,?$")
_TYPE_RE = re.compile(
    r"^#\s+TYPE\s+([a-zA-Z_:][a-zA-Z0-9_:]*)\s+(\w+)\s*$")
_HELP_RE = re.compile(r"^#\s+HELP\s+([a-zA-Z_:][a-zA-Z0-9_:]*)\s?(.*)$")
#: valid label-value escapes per the text format 0.0.4
_ESCAPE_RE = re.compile(r'\\(.)')
#: suffixes a histogram family's samples carry beyond its TYPE name
_HISTOGRAM_SUFFIXES = ("_bucket", "_sum", "_count")


class ScrapeFormatError(ValueError):
    """Strict-mode parse failure: the exposition violates the format."""


def _unescape_label(value: str, strict: bool) -> str:
    def sub(m):
        c = m.group(1)
        if c == "n":
            return "\n"
        if c in ('"', "\\"):
            return c
        if strict:
            raise ScrapeFormatError(
                f"invalid label escape \\{c} (only \\\\, \\\", \\n)")
        # lenient: a third-party exposition's unknown escape passes
        # through VERBATIM (backslash kept) — dropping the backslash
        # would silently change the label value it keys series by
        return m.group(0)
    return _ESCAPE_RE.sub(sub, value)


def parse_prom_text(text: str, strict: bool = False) -> Samples:
    """Parse Prometheus text exposition into {name: [(labels, value)]}.

    strict=True enforces the format instead of skipping what doesn't
    parse: every non-comment line must be a valid sample with a parsable
    value, every sample's family must have been declared by a `# TYPE`
    line (histograms cover their `_bucket`/`_sum`/`_count` series), a
    family must not be re-declared, and label escapes must be the three
    legal ones. This is the round-trip gate on our own exposition
    (telemetry/metrics.py) — a renderer regression fails loudly here
    rather than silently dropping series off the router's scrape."""
    out: Samples = {}
    types: Dict[str, str] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            m = _TYPE_RE.match(line)
            if m:
                name, kind = m.groups()
                if strict and name in types:
                    raise ScrapeFormatError(
                        f"line {lineno}: family {name} re-declared")
                types[name] = kind
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            if strict:
                raise ScrapeFormatError(
                    f"line {lineno}: not a sample line: {line!r}")
            continue
        name, labelstr, raw = m.groups()
        if strict:
            family = name
            if family not in types:
                for suffix in _HISTOGRAM_SUFFIXES:
                    base = name[:-len(suffix)] if name.endswith(suffix) \
                        else None
                    if base and types.get(base) == "histogram":
                        family = base
                        break
                else:
                    raise ScrapeFormatError(
                        f"line {lineno}: sample {name} has no # TYPE "
                        "declaration")
            if labelstr and not _LABELS_FULL_RE.match(labelstr):
                # a malformed fragment between/after valid pairs would
                # otherwise be silently dropped
                raise ScrapeFormatError(
                    f"line {lineno}: malformed labels {{{labelstr}}}")
        labels = {k: _unescape_label(v, strict)
                  for k, v in _LABEL_RE.findall(labelstr or "")}
        try:
            value = float(raw)
        except ValueError:
            if strict:
                raise ScrapeFormatError(
                    f"line {lineno}: unparsable value {raw!r}")
            continue
        out.setdefault(name, []).append((labels, value))
    return out


def parse_prom_metadata(text: str) -> Dict[str, Dict[str, str]]:
    """{family: {"type": kind, "help": unescaped help}} off the comment
    lines — the metadata half of the round-trip with
    telemetry/metrics.py (_escape_help is the inverse)."""
    out: Dict[str, Dict[str, str]] = {}
    for line in text.splitlines():
        line = line.strip()
        m = _HELP_RE.match(line)
        if m:
            name, help_ = m.groups()
            # single-pass unescape, the inverse of metrics._escape_help
            out.setdefault(name, {})["help"] = re.sub(
                r"\\(.)",
                lambda e: "\n" if e.group(1) == "n" else e.group(1),
                help_)
            continue
        m = _TYPE_RE.match(line)
        if m:
            out.setdefault(m.group(1), {})["type"] = m.group(2)
    return out


def scrape(url: str, timeout: float = 2.0) -> Samples:
    """GET a /metrics endpoint and parse it (raises on transport errors —
    the caller decides what a failed scrape means for health)."""
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return parse_prom_text(resp.read().decode("utf-8", "replace"))


def _match(labels: Dict[str, str], want: Dict[str, str]) -> bool:
    return all(labels.get(k) == v for k, v in want.items())


def sample_value(samples: Samples, name: str,
                 default: float = float("nan"), **labels) -> float:
    """First sample of `name` matching `labels` (gauges/counters)."""
    for got, value in samples.get(name, ()):
        if _match(got, labels):
            return value
    return default


def sample_sum(samples: Samples, name: str,
               default: float = float("nan"), **labels) -> float:
    """Sum of every sample of `name` matching `labels` — how a
    multi-lane replica (CP x DP: one gauge series per engine lane)
    rolls up to one number. `default` when no sample matches."""
    total, seen = 0.0, False
    for got, value in samples.get(name, ()):
        if _match(got, labels):
            total += value
            seen = True
    return total if seen else default


def histogram_percentile(samples: Samples, name: str, q: float,
                         **labels) -> float:
    """q-quantile from `name`'s cumulative `_bucket` series — same
    upper-bound-of-bucket semantics as the in-process
    Histogram.percentile(), so a test can assert the two views agree.
    NaN when the histogram is empty or absent."""
    buckets: List[Tuple[float, float]] = []  # (le, cumulative count)
    for got, value in samples.get(f"{name}_bucket", ()):
        if "le" not in got or not _match(
                {k: v for k, v in got.items() if k != "le"}, labels):
            continue
        le = float("inf") if got["le"] in ("+Inf", "inf") else float(got["le"])
        buckets.append((le, value))
    if not buckets:
        return float("nan")
    buckets.sort(key=lambda b: b[0])
    total = buckets[-1][1]
    if total <= 0:
        return float("nan")
    rank = q * total
    finite = [b for b in buckets if not math.isinf(b[0])]
    for le, cum in buckets:
        if cum >= rank:
            return finite[-1][0] if math.isinf(le) and finite else le
    return finite[-1][0] if finite else float("nan")


def diff_samples(before: Samples, after: Samples) -> Samples:
    """after - before, per (name, labels): turns cumulative counters and
    histogram bucket counts into a windowed view, so an SLO report covers
    exactly the replayed traffic — warmup compiles and earlier traffic
    fall out of the percentiles. Samples absent from `before` (a replica
    restarted mid-window, or a metric first observed inside it) keep
    their `after` value. Meaningless for gauges; callers only diff
    counters/histograms."""
    out: Samples = {}
    for name, rows in after.items():
        brows = before.get(name, [])
        out[name] = [
            (labels,
             value - next((v for bl, v in brows if bl == labels), 0.0))
            for labels, value in rows]
    return out


def merge_samples(parts: List[Samples]) -> Samples:
    """Concatenate scraped expositions (fleet-wide percentiles: bucket
    series from every replica are SUMMED per `le` by histogram_percentile
    callers via merge_histograms; plain samples just accumulate)."""
    out: Samples = {}
    for p in parts:
        for name, rows in p.items():
            out.setdefault(name, []).extend(rows)
    return out


def merged_histogram_percentile(parts: List[Samples], name: str, q: float,
                                **labels) -> float:
    """Fleet-wide quantile: sum the cumulative bucket counts per bound
    across replicas, then take the percentile of the merged histogram."""
    sums: Dict[float, float] = {}
    for samples in parts:
        for got, value in samples.get(f"{name}_bucket", ()):
            if "le" not in got or not _match(
                    {k: v for k, v in got.items() if k != "le"}, labels):
                continue
            le = (float("inf") if got["le"] in ("+Inf", "inf")
                  else float(got["le"]))
            sums[le] = sums.get(le, 0.0) + value
    if not sums:
        return float("nan")
    merged: Samples = {f"{name}_bucket": [
        ({"le": "+Inf" if math.isinf(le) else repr(le)}, cum)
        for le, cum in sums.items()]}
    return histogram_percentile(merged, name, q)


def replica_load(samples: Samples,
                 default: float = float("inf")) -> float:
    """Dispatch load score off the engine gauges PR 3 added: busy slots +
    queued requests, SUMMED across label sets — a CP x DP replica
    exposes one series per engine lane (lane="0", "1", ...) and its
    load is the fleet-visible total. Missing gauges (scrape raced
    server startup) score as `default` so the router prefers replicas
    it can actually see."""
    active = sample_sum(samples, "engine_slots_active")
    queued = sample_sum(samples, "engine_queue_depth")
    if math.isnan(active) and math.isnan(queued):
        return default
    return ((0.0 if math.isnan(active) else active)
            + (0.0 if math.isnan(queued) else queued))

"""Minimal Prometheus text-format scraping for the fleet control plane.

The router's prober reads the slot/queue gauges each replica already
exposes on /metrics (megatron_tpu/telemetry/metrics.py), and the SLO
harness reads TTFT/TPOT percentiles off the engine histograms — across
process boundaries, so the in-process Histogram.percentile() helper is out
of reach and the text exposition is the contract. This parser covers
exactly what our registry renders (and standard Prometheus clients emit
compatibly): `name{label="v",...} value` sample lines, `#` comments.

No jax import — the router is pure host code.
"""

from __future__ import annotations

import math
import re
import urllib.request
from typing import Dict, List, Tuple

#: parsed exposition: metric name -> list of (labels, value)
Samples = Dict[str, List[Tuple[Dict[str, str], float]]]

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)\s*$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_prom_text(text: str) -> Samples:
    """Parse Prometheus text exposition into {name: [(labels, value)]}."""
    out: Samples = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        name, labelstr, raw = m.groups()
        # single-pass unescape: sequential str.replace would corrupt a
        # literal backslash before 'n' ('\\n' -> newline instead of \n)
        labels = {k: re.sub(r'\\(["\\n])',
                            lambda e: "\n" if e.group(1) == "n"
                            else e.group(1), v)
                  for k, v in _LABEL_RE.findall(labelstr or "")}
        try:
            value = float(raw)
        except ValueError:
            continue
        out.setdefault(name, []).append((labels, value))
    return out


def scrape(url: str, timeout: float = 2.0) -> Samples:
    """GET a /metrics endpoint and parse it (raises on transport errors —
    the caller decides what a failed scrape means for health)."""
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return parse_prom_text(resp.read().decode("utf-8", "replace"))


def _match(labels: Dict[str, str], want: Dict[str, str]) -> bool:
    return all(labels.get(k) == v for k, v in want.items())


def sample_value(samples: Samples, name: str,
                 default: float = float("nan"), **labels) -> float:
    """First sample of `name` matching `labels` (gauges/counters)."""
    for got, value in samples.get(name, ()):
        if _match(got, labels):
            return value
    return default


def histogram_percentile(samples: Samples, name: str, q: float,
                         **labels) -> float:
    """q-quantile from `name`'s cumulative `_bucket` series — same
    upper-bound-of-bucket semantics as the in-process
    Histogram.percentile(), so a test can assert the two views agree.
    NaN when the histogram is empty or absent."""
    buckets: List[Tuple[float, float]] = []  # (le, cumulative count)
    for got, value in samples.get(f"{name}_bucket", ()):
        if "le" not in got or not _match(
                {k: v for k, v in got.items() if k != "le"}, labels):
            continue
        le = float("inf") if got["le"] in ("+Inf", "inf") else float(got["le"])
        buckets.append((le, value))
    if not buckets:
        return float("nan")
    buckets.sort(key=lambda b: b[0])
    total = buckets[-1][1]
    if total <= 0:
        return float("nan")
    rank = q * total
    finite = [b for b in buckets if not math.isinf(b[0])]
    for le, cum in buckets:
        if cum >= rank:
            return finite[-1][0] if math.isinf(le) and finite else le
    return finite[-1][0] if finite else float("nan")


def diff_samples(before: Samples, after: Samples) -> Samples:
    """after - before, per (name, labels): turns cumulative counters and
    histogram bucket counts into a windowed view, so an SLO report covers
    exactly the replayed traffic — warmup compiles and earlier traffic
    fall out of the percentiles. Samples absent from `before` (a replica
    restarted mid-window, or a metric first observed inside it) keep
    their `after` value. Meaningless for gauges; callers only diff
    counters/histograms."""
    out: Samples = {}
    for name, rows in after.items():
        brows = before.get(name, [])
        out[name] = [
            (labels,
             value - next((v for bl, v in brows if bl == labels), 0.0))
            for labels, value in rows]
    return out


def merge_samples(parts: List[Samples]) -> Samples:
    """Concatenate scraped expositions (fleet-wide percentiles: bucket
    series from every replica are SUMMED per `le` by histogram_percentile
    callers via merge_histograms; plain samples just accumulate)."""
    out: Samples = {}
    for p in parts:
        for name, rows in p.items():
            out.setdefault(name, []).extend(rows)
    return out


def merged_histogram_percentile(parts: List[Samples], name: str, q: float,
                                **labels) -> float:
    """Fleet-wide quantile: sum the cumulative bucket counts per bound
    across replicas, then take the percentile of the merged histogram."""
    sums: Dict[float, float] = {}
    for samples in parts:
        for got, value in samples.get(f"{name}_bucket", ()):
            if "le" not in got or not _match(
                    {k: v for k, v in got.items() if k != "le"}, labels):
                continue
            le = (float("inf") if got["le"] in ("+Inf", "inf")
                  else float(got["le"]))
            sums[le] = sums.get(le, 0.0) + value
    if not sums:
        return float("nan")
    merged: Samples = {f"{name}_bucket": [
        ({"le": "+Inf" if math.isinf(le) else repr(le)}, cum)
        for le, cum in sums.items()]}
    return histogram_percentile(merged, name, q)


def replica_load(samples: Samples,
                 default: float = float("inf")) -> float:
    """Dispatch load score off the engine gauges PR 3 added: busy slots +
    queued requests. Missing gauges (scrape raced server startup) score as
    `default` so the router prefers replicas it can actually see."""
    active = sample_value(samples, "engine_slots_active")
    queued = sample_value(samples, "engine_queue_depth")
    if math.isnan(active) and math.isnan(queued):
        return default
    return ((0.0 if math.isnan(active) else active)
            + (0.0 if math.isnan(queued) else queued))

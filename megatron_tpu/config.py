"""Typed configuration for megatron_tpu.

Replaces the reference's argparse god-namespace (megatron/arguments.py, 1,103
LoC; megatron/global_vars.py get_args()) with frozen dataclasses. The CLI
layer in megatron_tpu/arguments.py maps reference flag names onto these, so
flag-level parity is preserved without mutable global state.

Field names deliberately follow the reference flags (hidden_size,
num_attention_heads, ...) so that configs can round-trip through checkpoints
the way the reference pickles its args namespace
(ref: megatron/checkpointing.py:267-285).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence, Tuple

import jax.numpy as jnp

# ---------------------------------------------------------------------------
# enums (ref: megatron/model/enums.py)
# ---------------------------------------------------------------------------

POSITION_EMBEDDING_TYPES = ("rotary", "absolute")
NORMALIZATION_TYPES = ("layernorm", "rmsnorm")
# GLU family per ref megatron/model/glu_activations.py plus plain variants.
ACTIVATION_TYPES = ("gelu", "gelu_tanh", "geglu", "swiglu", "reglu", "liglu", "relu", "squared_relu")
GLU_ACTIVATIONS = ("geglu", "swiglu", "reglu", "liglu")
# "padding": bidirectional with a per-row key padding mask (BERT-style
# encoders); requires an attention_mask input end-to-end.
ATTN_MASK_TYPES = ("causal", "bidirectional", "padding")
ATTENTION_IMPLS = ("xla", "pallas", "ring", "ulysses")
RECOMPUTE_POLICIES = ("none", "selective", "full")
DTYPES = {"bfloat16": jnp.bfloat16, "float16": jnp.float16, "float32": jnp.float32}


def _resolve_dtype(name: str):
    if name not in DTYPES:
        raise ValueError(f"unknown dtype {name!r}; one of {sorted(DTYPES)}")
    return DTYPES[name]


# ---------------------------------------------------------------------------
# model architecture
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ModelConfig:
    """Architecture of one decoder-only (or encoder) transformer LM.

    One configurable block covers the union of the reference's model zoo
    (GPT/Llama/Falcon/Mistral assertion-shell subclasses,
    ref: megatron/model/{gpt_model,llama_model,falcon_model,mistral_model}.py).
    Presets live in megatron_tpu/models/presets.py.
    """

    num_layers: int
    hidden_size: int
    num_attention_heads: int
    vocab_size: int
    seq_length: int

    # encoder-decoder models (T5) may give the two stacks different
    # depths (ref: --encoder_num_layers / --decoder_num_layers,
    # megatron/arguments.py); None = num_layers. Decoder-only models
    # ignore both.
    encoder_num_layers: Optional[int] = None
    decoder_num_layers: Optional[int] = None

    # grouped-/multi-query attention (ref: transformer.py:450-465
    # num_attention_heads_kv broadcast trick). None => MHA.
    num_kv_heads: Optional[int] = None
    # head dim override (defaults to hidden_size // num_attention_heads)
    kv_channels: Optional[int] = None
    # MLP width. None => 4*hidden for non-GLU, (8/3)*hidden rounded for GLU
    # presets set it explicitly (e.g. llama-2 7B: 11008).
    ffn_hidden_size: Optional[int] = None

    # position embeddings (ref: megatron/model/positional_embeddings.py)
    position_embedding_type: str = "rotary"
    rope_theta: float = 10000.0
    # linear position-interpolation RoPE scaling (ref --rope_scaling_factor)
    rope_scaling_factor: float = 1.0
    max_position_embeddings: Optional[int] = None  # for absolute pos-emb

    # norms / activations
    normalization: str = "rmsnorm"
    layernorm_epsilon: float = 1e-5
    activation: str = "swiglu"
    # Falcon-style parallel attention: mlp(ln(x)) + attn(ln(x)) in one
    # residual add (ref: transformer.py parallel_attn), optionally with a
    # second dedicated mlp layernorm (Falcon-40B parallel_layernorm).
    parallel_attn: bool = False
    parallel_layernorm: bool = False
    # post-LN layer convention (ref --use_post_ln): no pre-norm, each layer
    # ends with its own LN (reusing the ln1 slot), no final stack norm
    use_post_ln: bool = False
    # residual taken from the LN output instead of the LN input
    # (ref --apply_residual_connection_post_layernorm)
    apply_residual_post_ln: bool = False
    # post-attention norm applied before mlp (standard pre-LN stack)

    # biases (llama/falcon: none; gpt: all)
    use_bias_linear: bool = False
    use_bias_qkv: bool = False

    # tied input/output embeddings (gpt/falcon: tied; llama/mistral: untied)
    tie_embed_logits: bool = False

    # Mistral sliding-window attention (ref: transformer.py:528-536)
    sliding_window_size: Optional[int] = None

    # Mixture-of-Experts (beyond the reference): GShard/Switch einsum
    # dispatch with capacity; Mixtral-style renormalized top-k gates.
    # None = dense MLP. See ops/moe.py.
    num_experts: Optional[int] = None
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_loss_coeff: float = 1e-2
    moe_z_loss_coeff: float = 0.0
    moe_renorm_gates: bool = True
    # "capacity": GShard grouped capacity dispatch (einsum, EP-shardable);
    # "dropless": sort-based dispatch over lax.ragged_dot — NO token ever
    # dropped and no dense [.., E, C] dispatch FLOPs; under ep > 1 rows
    # travel an explicit expert-axis all-to-all (moe_block_dropless_ep)
    moe_dispatch: str = "capacity"
    # Receive-buffer factor for dropless dispatch under expert
    # parallelism: each expert shard accepts up to n_local*top_k*factor
    # rows per step. None = ep (mathematically dropless for any routing,
    # the default); smaller trades FLOPs/memory (both scale with the
    # buffer) for greedy source-order drops when routing is imbalanced
    # beyond factor x fair share.
    moe_ep_buffer_factor: Optional[float] = None
    # GShard token-group size for dispatch: capacity is enforced within
    # fixed-size groups of tokens so the combine/dispatch tensors are
    # [G, Sg, E, Cg] — linear in total tokens — instead of the global
    # [N, E, C] quadratic form. 0 = auto (largest divisor of seq_length
    # <= 2048). Must divide seq_length when set.
    moe_group_size: int = 0

    # regularization
    hidden_dropout: float = 0.0
    attention_dropout: float = 0.0
    # LIMA per-layer linear dropout ramp (ref: transformer.py:994-1001)
    lima_dropout: bool = False

    # initialization (ref: arguments.py --init_method_std)
    init_method_std: float = 0.02
    # scale init of output-facing mats by 1/sqrt(2*num_layers)
    use_scaled_init: bool = True

    # numerics
    params_dtype: str = "bfloat16"
    # fp8 training GEMMs (ref: TransformerEngine autocast,
    # megatron/model/transformer.py:962-1043): None | "e4m3" | "hybrid"
    # (e4m3 forward, e5m2 grads). Current-scaling TPU substitution for
    # the DelayedScaling recipe — see ops/fp8.py for the design argument.
    fp8_format: Optional[str] = None
    fp8_margin: int = 0          # ref --fp8_margin: scale back-off 2^-m
    fp8_wgrad: bool = True       # ref --no_fp8_wgrad: fp32 wgrad GEMM
    # compute softmax / norms in fp32 (ref: attention_softmax_in_fp32)
    softmax_fp32: bool = True
    attn_mask_type: str = "causal"

    # chunked fused logits+cross-entropy (beyond the reference): compute
    # the LM head and CE over sequence chunks of this many tokens, with
    # per-chunk logits rematerialized in the backward — the full [B,S,V]
    # logits buffer (plus its fp32 CE intermediates and gradient) never
    # lives in HBM. 0 = unchunked. Must divide seq_length.
    ce_chunk_size: int = 0

    # attention implementation: "xla" einsum path, "pallas" flash kernel
    # (falls back to xla for unsupported shapes), or "ring" context-parallel
    # ring attention (requires an ambient mesh with a "context" axis).
    attention_impl: str = "xla"
    # route full-sequence attention through the flash template's
    # custom-vjp kernel (ops/pallas/flash_template.py) so training never
    # pays the XLA-generated O(S^2) attention gradient; --no_flash_bwd
    # is the escape hatch (dense gradient, loudly logged). Only
    # meaningful under attention_impl="pallas".
    flash_bwd: bool = True

    # BERT-style extras (ref: megatron/model/bert_model.py,
    # language_model.py Embedding tokentype path)
    num_tokentypes: int = 0
    # adds pooler + binary (NSP/SOP) head + MLM transform head params
    bert_binary_head: bool = False

    # ----- derived helpers -------------------------------------------------

    @property
    def head_dim(self) -> int:
        return self.kv_channels or self.hidden_size // self.num_attention_heads

    @property
    def n_kv_heads(self) -> int:
        return self.num_kv_heads or self.num_attention_heads

    @property
    def is_glu(self) -> bool:
        return self.activation in GLU_ACTIVATIONS

    @property
    def ffn_size(self) -> int:
        if self.ffn_hidden_size is not None:
            return self.ffn_hidden_size
        if self.is_glu:
            # llama convention: 2/3 * 4h rounded up to multiple of 256
            raw = int(2 * 4 * self.hidden_size / 3)
            return 256 * ((raw + 255) // 256)
        return 4 * self.hidden_size

    @property
    def dtype(self):
        return _resolve_dtype(self.params_dtype)

    def validate(self) -> "ModelConfig":
        if self.position_embedding_type not in POSITION_EMBEDDING_TYPES:
            raise ValueError(f"bad position_embedding_type {self.position_embedding_type}")
        if self.normalization not in NORMALIZATION_TYPES:
            raise ValueError(f"bad normalization {self.normalization}")
        if self.activation not in ACTIVATION_TYPES:
            raise ValueError(f"bad activation {self.activation}")
        if self.attn_mask_type not in ATTN_MASK_TYPES:
            raise ValueError(f"bad attn_mask_type {self.attn_mask_type}")
        if self.attention_impl not in ATTENTION_IMPLS:
            raise ValueError(f"bad attention_impl {self.attention_impl}")
        if self.fp8_format not in (None, "e4m3", "hybrid"):
            raise ValueError(
                f"fp8_format={self.fp8_format!r} must be None, 'e4m3' or "
                "'hybrid' (ref --fp8_e4m3 / --fp8_hybrid)")
        if self.use_post_ln and self.parallel_attn:
            raise ValueError("use_post_ln is incompatible with parallel_attn")
        if self.hidden_size % self.num_attention_heads and self.kv_channels is None:
            raise ValueError("num_attention_heads must divide hidden_size")
        if self.num_attention_heads % self.n_kv_heads:
            raise ValueError("num_attention_heads must be divisible by num_kv_heads")
        if self.position_embedding_type == "absolute" and not self.max_position_embeddings:
            raise ValueError("absolute position embeddings need max_position_embeddings")
        if self.parallel_layernorm and not self.parallel_attn:
            raise ValueError("parallel_layernorm requires parallel_attn")
        if self.num_experts is not None:
            if self.num_experts < 1:
                raise ValueError("num_experts must be >= 1")
            if not 1 <= self.moe_top_k <= self.num_experts:
                raise ValueError(
                    f"moe_top_k={self.moe_top_k} must be in "
                    f"[1, num_experts={self.num_experts}]")
            if self.moe_dispatch not in ("capacity", "dropless"):
                raise ValueError(
                    f"moe_dispatch={self.moe_dispatch!r} must be "
                    "'capacity' or 'dropless'")
            if self.moe_group_size < 0:
                raise ValueError("moe_group_size must be >= 0")
            if (self.moe_ep_buffer_factor is not None
                    and self.moe_ep_buffer_factor <= 0):
                # <= 0 would zero every shard's receive buffer and the MoE
                # layer would silently drop every routed token (ADVICE r5
                # low #2)
                raise ValueError(
                    f"moe_ep_buffer_factor={self.moe_ep_buffer_factor} "
                    "must be > 0 (None = exact dropless)")
            if self.moe_group_size and self.seq_length % self.moe_group_size:
                raise ValueError(
                    f"moe_group_size={self.moe_group_size} must divide "
                    f"seq_length={self.seq_length}")
        if self.ce_chunk_size < 0:
            raise ValueError("ce_chunk_size must be >= 0")
        if self.ce_chunk_size and self.seq_length % self.ce_chunk_size:
            raise ValueError(
                f"ce_chunk_size={self.ce_chunk_size} must divide "
                f"seq_length={self.seq_length}")
        return self

    # FLOPs per token for one fwd pass, used for MFU accounting
    # (ref formula: megatron/model/language_model.py:370-384).
    def flops_per_token_fwd(self, seq_length: Optional[int] = None) -> float:
        s = seq_length or self.seq_length
        h, hd = self.hidden_size, self.head_dim
        nq, nkv = self.num_attention_heads, self.n_kv_heads
        f = self.ffn_size
        per_layer = 0.0
        per_layer += 2 * h * (nq + 2 * nkv) * hd        # qkv proj
        per_layer += 2 * 2 * s * nq * hd                # qk^T and av (causal ~ /2 but count full)
        per_layer += 2 * nq * hd * h                    # out proj
        mlp_in_width = f * (2 if self.is_glu else 1)
        mlp = 2 * h * mlp_in_width + 2 * f * h
        if self.num_experts is not None:
            # each token visits top_k experts; router matmul is extra
            mlp = mlp * self.moe_top_k + 2 * h * self.num_experts
        per_layer += mlp
        total = self.num_layers * per_layer
        total += 2 * h * self.vocab_size                # logits
        return float(total)


# ---------------------------------------------------------------------------
# parallel topology
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ParallelConfig:
    """Parallel topology over one device mesh.

    Replaces the reference's process-group builder
    (megatron/core/parallel_state.py:51-199). Mesh axis order is
    ("data", "pipe", "context", "tensor"); tensor is the fastest-varying
    axis so TP collectives ride the innermost ICI links, matching the
    reference's TP-innermost-contiguous rank layout
    (parallel_state.py:68-82 docstring).
    """

    tensor_parallel: int = 1
    pipeline_parallel: int = 1
    # context/sequence-dimension sharding with ring attention — the
    # long-context axis (beyond reference parity; ref has only
    # Korthikanti-style SP, see SURVEY.md §2.2).
    context_parallel: int = 1
    # expert parallelism: a sub-axis of data parallelism that MoE expert
    # weights shard over (E % expert_parallel == 0); dense params are
    # replicated over it and the batch shards over (data, expert), so it
    # behaves as extra DP outside MoE blocks. Decoupled from dp so the
    # expert count never constrains the data-parallel degree.
    expert_parallel: int = 1
    # data_parallel: None => derived from device count
    data_parallel: Optional[int] = None
    # Korthikanti sequence parallelism: shard residual-stream activations
    # along seq over the *tensor* axis outside matmul blocks
    # (ref: layers.py:225-236,285-296,691-692).
    sequence_parallel: bool = False
    # number of virtual-pipeline chunks per stage (interleaved 1F1B),
    # ref: schedules.py:253-502. None => non-interleaved.
    virtual_pipeline_parallel: Optional[int] = None

    def derive_data_parallel(self, n_devices: int) -> int:
        model_devices = (self.tensor_parallel * self.pipeline_parallel
                         * self.context_parallel * self.expert_parallel)
        if n_devices % model_devices:
            raise ValueError(
                f"{n_devices} devices not divisible by "
                f"tp*pp*cp*ep={model_devices}")
        dp = n_devices // model_devices
        if self.data_parallel is not None and self.data_parallel != dp:
            raise ValueError(
                f"data_parallel={self.data_parallel} inconsistent with "
                f"{n_devices} devices / (tp*pp*cp*ep={model_devices})")
        return dp

    def validate(self) -> "ParallelConfig":
        for name in ("tensor_parallel", "pipeline_parallel",
                     "context_parallel", "expert_parallel"):
            if getattr(self, name) < 1:
                raise ValueError(f"{name} must be >= 1")
        if self.virtual_pipeline_parallel is not None:
            if self.pipeline_parallel < 2:
                raise ValueError("interleaved schedule needs pipeline_parallel >= 2")
            if self.virtual_pipeline_parallel < 2:
                raise ValueError("virtual_pipeline_parallel must be >= 2")
        if self.sequence_parallel and self.tensor_parallel == 1:
            # ref disables SP when tp==1 (arguments.py:331-341)
            return dataclasses.replace(self, sequence_parallel=False)
        return self


# ---------------------------------------------------------------------------
# optimizer / schedule
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class OptimizerConfig:
    """Adam/SGD + lr schedule + mixed-precision policy.

    Mirrors megatron/optimizer/* and megatron/optimizer_param_scheduler.py.
    fp32 master weights and fp32 grad accumulation are the default, like the
    reference's bf16 path (arguments.py: bf16 => accumulate_allreduce_grads_in_fp32).
    """

    optimizer: str = "adam"
    lr: float = 3e-4
    min_lr: float = 0.0
    lr_decay_style: str = "cosine"  # constant | linear | cosine | inverse-square-root
    lr_decay_iters: Optional[int] = None
    lr_warmup_iters: int = 0
    lr_warmup_fraction: Optional[float] = None

    adam_beta1: float = 0.9
    adam_beta2: float = 0.999
    adam_eps: float = 1e-8
    sgd_momentum: float = 0.9
    weight_decay: float = 0.01
    # weight-decay ramp (ref: start_weight_decay/end_weight_decay/incr style)
    start_weight_decay: Optional[float] = None
    end_weight_decay: Optional[float] = None
    weight_decay_incr_style: str = "constant"  # constant | linear | cosine

    # per-group LR/WD multipliers: ((path_regex, lr_mult, wd_mult), ...) —
    # first matching pattern wins, unmatched params use (1.0, 1.0). The
    # param "group" is a path predicate over the param tree, replacing the
    # reference's torch param_groups carrying lr_mult/wd_mult
    # (ref: optimizer_param_scheduler.py:124-127, optimizer/__init__.py:16-59)
    param_group_mults: tuple = ()

    clip_grad: float = 1.0
    # ZeRO-1: shard optimizer state over the data axis
    # (ref: megatron/optimizer/distrib_optimizer.py, 700 LoC -> sharding specs)
    use_distributed_optimizer: bool = False
    # keep fp32 master params for bf16/fp16 training
    # (ref: Float16OptimizerWithFloat16Params, optimizer.py:508-563)
    fp32_master_weights: bool = True
    # dynamic loss scaling for fp16 (never needed for bf16)
    loss_scale: Optional[float] = None  # None => dynamic when fp16
    initial_loss_scale: float = 2.0**32
    min_loss_scale: float = 1.0
    loss_scale_window: int = 1000
    hysteresis: int = 2
    log_num_zeros_in_grad: bool = False


@dataclass(frozen=True)
class TrainingConfig:
    """Top-level run config: batching, duration, recompute, checkpoints.

    Mirrors the 'training' / 'checkpointing' / 'mixed precision' argument
    groups (megatron/arguments.py).
    """

    micro_batch_size: int = 1
    global_batch_size: int = 1
    # batch-size rampup: (start_batch, increment, ramp_samples)
    # (ref: megatron/microbatches.py RampupBatchsizeNumMicroBatches)
    rampup_batch_size: Optional[Tuple[int, int, int]] = None
    train_iters: Optional[int] = None
    train_samples: Optional[int] = None
    eval_interval: int = 1000
    eval_iters: int = 100
    seed: int = 1234
    # per-pipeline-stage seed offset policy (ref: initialize.py:179-193)
    seed_pipeline_offset: int = 100
    data_parallel_random_init: bool = False

    # activation recompute (ref: transformer.py:1110-1176)
    # none | selective | full | "block:N" (remat only the first N layers
    # per stack/pipeline-chunk) | "uniform:N" (chunked two-level remat,
    # sqrt-remat carry storage) — ref --recompute_method +
    # --recompute_num_layers, transformer.py:1110-1172
    recompute_granularity: str = "none"

    # checkpointing
    save: Optional[str] = None
    load: Optional[str] = None
    save_interval: Optional[int] = None
    exit_interval: Optional[int] = None
    exit_duration_in_mins: Optional[int] = None
    finetune: bool = False
    no_load_optim: bool = False
    no_load_rng: bool = False
    # overlap checkpoint serialization/writes with training compute
    # (training/checkpointing.py AsyncCheckpointSaver); --no_async_save
    # falls back to blocking saves
    async_save: bool = True
    # retention: keep only the newest K committed checkpoints (staging dirs
    # and whatever the tracker points at are never pruned); None = keep all
    keep_latest_k: Optional[int] = None

    # async goodput loop (training/prefetch.py + the lagged-metrics train
    # loop; docs/performance.md "Async goodput loop"). --no_async_loop
    # restores the fully synchronous loop — it stays the differential-test
    # oracle: loss curves are bitwise-identical between the two.
    async_loop: bool = True
    # bounded device-side double-buffer depth of the background batch
    # prefetcher (>=1 when async_loop; 0 keeps host->device placement on
    # the critical path even with the async loop on)
    prefetch_depth: int = 2
    # fetch step metrics (loss/lr/grad_norm) K steps late so dispatch of
    # the next step overlaps the current one; the divergence sentinel,
    # logger, goodput accounting and flight-recorder heartbeat all consume
    # the lagged stream (sentinel trip latency grows by K — bounded; the
    # rollback discards the in-flight steps, docs/fault_tolerance.md)
    metrics_lag: int = 1
    # persistent XLA compilation cache directory
    # (jax_compilation_cache_dir): crash-resume restarts and re-runs pay
    # the goodput `compile` bucket once; cache hits surface in step
    # records and the recompile tracker
    compilation_cache_dir: Optional[str] = None

    # divergence sentinel (training/resilience.py): abort — or roll back,
    # with rollback_on_divergence — after this many CONSECUTIVE
    # non-finite/skipped optimizer steps; 0 disables
    divergence_patience: int = 100
    # trip when the loss exceeds factor * EMA for loss_spike_patience
    # consecutive steps; 0.0 disables spike detection
    loss_spike_factor: float = 0.0
    loss_spike_patience: int = 5
    # on sentinel trip: reload the newest valid checkpoint and fast-forward
    # the data past the poison window instead of aborting
    rollback_on_divergence: bool = False
    # give up (DivergenceError) after this many rollbacks — a model that
    # re-diverges every time is genuinely diverging, not unlucky
    max_rollbacks: int = 3

    # preemption + elastic resume + sentinels
    # (docs/fault_tolerance.md "Preemption and elastic resume"):
    # deadline on the expedited SIGTERM-notice checkpoint — the first
    # SIGTERM drains the async pipeline and forces a SYNCHRONOUS
    # committed save (bypassing --save_interval); if the commit misses
    # this many seconds the process force-exits
    # resilience.PREEMPT_TIMEOUT_EXIT_CODE instead of overstaying the
    # notice window. 0 disables the deadline (wait however long).
    preempt_save_timeout: float = 600.0
    # step-deadline hang watchdog (training/resilience.py StepWatchdog):
    # if no step completes for this many seconds, dump a flight-recorder
    # bundle, journal `hang_detected`, and abort cleanly with
    # resilience.HANG_EXIT_CODE instead of hanging until the scheduler's
    # timeout kill destroys the evidence. Must exceed the longest
    # legitimate heartbeat gap (a step + the worst eval/save stall).
    # 0 disables.
    step_timeout_s: float = 0.0
    # opt-in silent-data-corruption sentinel: every N steps re-run the
    # jitted train step on the retained (state, batch) and compare the
    # committed outputs BITWISE; a mismatch journals `sdc_detected` with
    # the leaf paths and aborts (resilience.SDCError). Costs one state
    # copy + one extra step per check. 0 disables.
    replay_check_interval: int = 0
    # journal a crc32 fingerprint of every host batch (`data_crc` on step
    # records) — the sample-identity evidence elastic-resume tests diff
    # across topologies; negligible cost, off by default
    log_data_fingerprint: bool = False

    # multi-host coordination (training/coordination.py;
    # docs/fault_tolerance.md "Multi-host coordination"): shared directory
    # for the file-backed agreement seam — signal agreement, peer-death
    # poison records, two-phase checkpoint commit, restart barrier.
    # None + jax.process_count() > 1 selects the jax.distributed KV-store
    # backend automatically; None single-process disables coordination
    # entirely (byte-identical single-host behavior).
    coordination_dir: Optional[str] = None
    # declare a peer dead after this many seconds without a heartbeat (or
    # immediately on its poison record); survivors exit
    # resilience.PEER_ABORT_EXIT_CODE with `peer_abort` journaled instead
    # of wedging in the next collective. 0 disables peer-death detection
    # (poison records still observed).
    peer_death_timeout_s: float = 60.0

    # --save_interval auto: derive the checkpoint cadence from measured
    # commit latency (save_interval ~= (preempt grace - p95 commit) /
    # p50 step), re-derived as measurements accrue and journaled as
    # `cadence_retune` on every change (resilience.CheckpointCadenceTuner)
    save_interval_auto: bool = False
    # lower clamp on the autotuned cadence, in steps
    save_interval_floor: int = 25

    # logging
    log_interval: int = 100
    tensorboard_dir: Optional[str] = None
    wandb_logger: bool = False
    wandb_project: str = "megatron_tpu"
    wandb_name: Optional[str] = None
    timing_log_level: int = 0
    # per-span wall-clock to the writer each log_interval
    # (ref --log_timers_to_tensorboard, training.py:500-525)
    log_timers_to_tensorboard: bool = False
    # opt-in jax.profiler trace window — the TPU-native deep-profiling
    # story (where the reference reaches for nsys/nvtx): traces device +
    # host activity for iterations [profile_step_start, profile_step_end)
    # into profile_dir (default: tensorboard_dir)
    profile: bool = False
    profile_step_start: int = 10
    profile_step_end: int = 12
    profile_dir: Optional[str] = None
    # SIGUSR1 mid-run arms a bounded trace window of this many steps —
    # on-demand incident profiling with no restart and no --profile
    # (docs/observability.md "Runtime traces")
    profile_signal_steps: int = 2

    # telemetry (megatron_tpu/telemetry; docs/observability.md):
    # structured event journal (per-step records, goodput ledger,
    # checkpoint/rollback/fault events) written as append-only JSONL under
    # this dir; None disables
    telemetry_dir: Optional[str] = None
    # journal rotation threshold (segments beyond the live file + 2 are
    # dropped, so disk stays bounded on unbounded runs); 0 disables
    # rotation (one unbounded file, e.g. under an external log shipper)
    journal_max_mb: float = 64.0
    # sidecar Prometheus /metrics listener for the train loop (the serving
    # server mounts /metrics on its own port); None disables, 0 binds a
    # free port
    metrics_port: Optional[int] = None
    # flight recorder: watchdog armed by a per-step heartbeat that dumps
    # all-thread stacks + the journal tail to a bundle when a step stalls
    # past the deadline, then optionally SIGABRTs so the supervisor
    # restarts the process with the evidence on disk
    flight_recorder: bool = False
    flight_recorder_deadline_s: float = 600.0
    flight_recorder_abort: bool = False

    # run only the validation loop, then exit (ref --eval_only)
    eval_only: bool = False

    # iterations whose update is skipped — crude fault injection
    # (ref --skip_iters, training.py:397-425)
    skip_iters: tuple = ()

    # extra per-log-interval scalars (ref --log_params_norm,
    # --log_memory_to_tensorboard)
    log_params_norm: bool = False
    log_memory: bool = False
    log_batch_size: bool = False
    log_world_size: bool = False

    # loss averaging for instruction tuning (ref finetune.py scalar_loss_mask)
    scalar_loss_mask: float = 0.0
    variable_seq_lengths: bool = False
    # validation metrics registry names (ref: --metrics, megatron/metrics.py)
    metrics: Tuple[str, ...] = ()

    def num_microbatches(self, global_batch: Optional[int], data_parallel: int) -> int:
        gbs = global_batch or self.global_batch_size
        denom = self.micro_batch_size * data_parallel
        if gbs % denom:
            raise ValueError(
                f"global batch {gbs} not divisible by micro_batch*dp={denom}")
        return gbs // denom

    def validate(self) -> "TrainingConfig":
        g = self.recompute_granularity
        if g.startswith(("block:", "uniform:")):
            kind = g.split(":", 1)[0]
            try:
                n = int(g.split(":", 1)[1])
                ok = n >= (1 if kind == "uniform" else 0)
            except ValueError:
                ok = False
            if not ok:
                raise ValueError(
                    f"bad recompute_granularity {g!r} — form is "
                    f"'{kind}:<N>' with N a "
                    + ("positive chunk size" if kind == "uniform"
                       else "non-negative layer count"))
        elif g not in RECOMPUTE_POLICIES:
            raise ValueError(f"bad recompute_granularity {g}")
        if self.flight_recorder and self.flight_recorder_deadline_s <= 0:
            raise ValueError(
                f"flight_recorder_deadline_s="
                f"{self.flight_recorder_deadline_s} must be > 0 (seconds "
                "without a step heartbeat before the stall bundle dumps)")
        if self.journal_max_mb < 0:
            raise ValueError(
                "journal_max_mb must be >= 0 (0 disables rotation: one "
                "unbounded journal file)")
        if self.prefetch_depth < 0:
            raise ValueError(
                "prefetch_depth must be >= 0 (0 disables the background "
                "prefetcher; use --no_async_loop for the fully "
                "synchronous loop)")
        if self.metrics_lag < 0:
            raise ValueError(
                "metrics_lag must be >= 0 (0 fetches metrics inside each "
                "step, the synchronous behavior)")
        if self.preempt_save_timeout < 0:
            raise ValueError(
                "preempt_save_timeout must be >= 0 seconds (0 disables "
                "the preemption-save deadline)")
        if self.step_timeout_s < 0:
            raise ValueError(
                "step_timeout_s must be >= 0 seconds (0 disables the "
                "step-deadline hang watchdog)")
        if self.replay_check_interval < 0:
            raise ValueError(
                "replay_check_interval must be >= 0 steps (0 disables "
                "the SDC replay check)")
        if self.peer_death_timeout_s < 0:
            raise ValueError(
                "peer_death_timeout_s must be >= 0 seconds (0 disables "
                "heartbeat-based peer-death detection)")
        if self.save_interval_auto and self.save_interval is not None:
            raise ValueError(
                "--save_interval auto and a fixed --save_interval are "
                "mutually exclusive")
        if self.save_interval_auto and not self.preempt_save_timeout:
            raise ValueError(
                "--save_interval auto derives the cadence from the "
                "--preempt_save_timeout grace window; set a positive one")
        if self.save_interval_floor < 1:
            raise ValueError("save_interval_floor must be >= 1 step")
        if self.train_iters is None and self.train_samples is None:
            pass  # inference / tooling use
        return self


# ---------------------------------------------------------------------------
# convenience bundle
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class RunConfig:
    model: ModelConfig
    parallel: ParallelConfig = field(default_factory=ParallelConfig)
    optimizer: OptimizerConfig = field(default_factory=OptimizerConfig)
    training: TrainingConfig = field(default_factory=TrainingConfig)

    def validate(self) -> "RunConfig":
        self.model.validate()
        self.parallel.validate()
        self.training.validate()
        return self

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d: dict) -> "RunConfig":
        return RunConfig(
            model=ModelConfig(**d["model"]),
            parallel=ParallelConfig(**d["parallel"]),
            optimizer=OptimizerConfig(**d["optimizer"]),
            training=TrainingConfig(**{k: (tuple(v) if k == "rampup_batch_size" and v else v)
                                       for k, v in d["training"].items()}),
        )

"""Tokenizer dispatch + vocab padding.

Equivalent of megatron/tokenizer/tokenizer.py (build_tokenizer with
SentencePiece / Falcon-HF / GPT-2 BPE / BERT wordpiece backends, plus the
pad-to-multiple rule at tokenizer.py:45-62). Backends here:

  * SentencePieceTokenizer — llama-family .model files, loaded through HF
    transformers' (tokenizers-backed) LlamaTokenizerFast, special-token
    aware like the reference's _SentencePieceTokenizer.
  * HFTokenizer — any HF repo/dir via AutoTokenizer (the reference's
    _FalconTokenizer generalized).
  * GPT2BPETokenizer — own byte-level BPE (gpt2_bpe.py).
  * NullTokenizer — identity int tokenizer for tests/benchmarks
    (vocab_size given; "tokens" are space-separated ints).
"""

from __future__ import annotations

import abc
from typing import List, Optional


def pad_vocab_size(orig_vocab_size: int, make_vocab_size_divisible_by: int = 128,
                   tensor_parallel: int = 1) -> int:
    """Pad so the vocab divides evenly across TP shards
    (ref: _vocab_size_with_padding)."""
    mult = make_vocab_size_divisible_by * tensor_parallel
    return mult * ((orig_vocab_size + mult - 1) // mult)


class AbstractTokenizer(abc.ABC):
    name: str = "abstract"

    @property
    @abc.abstractmethod
    def vocab_size(self) -> int: ...

    @abc.abstractmethod
    def tokenize(self, text: str) -> List[int]: ...

    def detokenize(self, ids) -> str:
        raise NotImplementedError

    @property
    def eod(self) -> int:
        raise NotImplementedError

    @property
    def pad(self) -> int:
        raise NotImplementedError

    @property
    def bos(self) -> Optional[int]:
        return None


class _HFBase(AbstractTokenizer):
    def __init__(self, hf_tokenizer):
        self._t = hf_tokenizer

    @property
    def vocab_size(self) -> int:
        return len(self._t)

    def tokenize(self, text: str) -> List[int]:
        return self._t.encode(text, add_special_tokens=False)

    def detokenize(self, ids) -> str:
        return self._t.decode(list(map(int, ids)), skip_special_tokens=False)

    @property
    def eod(self) -> int:
        t = self._t
        if t.eos_token_id is not None:
            return t.eos_token_id
        raise ValueError("tokenizer has no eos token")

    @property
    def bos(self) -> Optional[int]:
        return self._t.bos_token_id

    @property
    def pad(self) -> int:
        t = self._t
        return t.pad_token_id if t.pad_token_id is not None else self.eod


class SentencePieceTokenizer(_HFBase):
    """Llama-family sentencepiece model (ref: _SentencePieceTokenizer,
    incl. --vocab_extra_ids / new-token handling via HF's additional
    special tokens)."""

    name = "sentencepiece"

    def __init__(self, model_file: str, vocab_extra_ids: int = 0,
                 new_tokens: bool = True):
        from transformers import LlamaTokenizerFast

        t = LlamaTokenizerFast(vocab_file=model_file, legacy=False)
        if vocab_extra_ids and new_tokens:
            t.add_special_tokens({"additional_special_tokens": [
                f"<extra_id_{i}>" for i in range(vocab_extra_ids)]})
        super().__init__(t)


class HFTokenizer(_HFBase):
    """AutoTokenizer wrapper (ref: _FalconTokenizer)."""

    name = "hf"

    def __init__(self, name_or_path: str):
        from transformers import AutoTokenizer

        super().__init__(AutoTokenizer.from_pretrained(name_or_path))


class GPT2BPETokenizer(AbstractTokenizer):
    name = "gpt2"

    def __init__(self, vocab_file: str, merges_file: str):
        from megatron_tpu.tokenizer.gpt2_bpe import GPT2BPE

        self._t = GPT2BPE(vocab_file, merges_file)
        self._eod = self._t.encoder.get("<|endoftext|>")

    @property
    def vocab_size(self) -> int:
        return len(self._t.encoder)

    def tokenize(self, text: str) -> List[int]:
        return self._t.encode(text)

    def detokenize(self, ids) -> str:
        return self._t.decode(ids)

    @property
    def eod(self) -> int:
        return self._eod

    @property
    def pad(self) -> int:
        return self._eod


class NullTokenizer(AbstractTokenizer):
    """ints-in, ints-out; id `vocab_size` is EOD (for tests/benches)."""

    name = "null"

    def __init__(self, vocab_size: int):
        self._vs = int(vocab_size) + 1

    @property
    def vocab_size(self) -> int:
        return self._vs

    def tokenize(self, text: str) -> List[int]:
        return [int(t) for t in text.split()]

    def detokenize(self, ids) -> str:
        return " ".join(str(int(i)) for i in ids)

    @property
    def eod(self) -> int:
        return self._vs - 1

    @property
    def pad(self) -> int:
        return self._vs - 1


def build_tokenizer(
    tokenizer_type: str,
    *,
    vocab_file: Optional[str] = None,
    merges_file: Optional[str] = None,
    tokenizer_model: Optional[str] = None,
    name_or_path: Optional[str] = None,
    vocab_size: Optional[int] = None,
    vocab_extra_ids: int = 0,
    new_tokens: bool = True,
) -> AbstractTokenizer:
    """Dispatch by type name (ref: build_tokenizer, tokenizer.py:12-44).
    Reference type names are accepted as aliases."""
    t = tokenizer_type.lower()
    if t in ("sentencepiecetokenizer", "sentencepiece"):
        return SentencePieceTokenizer(tokenizer_model or vocab_file,
                                      vocab_extra_ids, new_tokens)
    if t in ("falcontokenizer", "hftokenizer", "hf", "autotokenizer"):
        return HFTokenizer(name_or_path or vocab_file or "tiiuae/falcon-7b")
    if t in ("gpt2bpetokenizer", "gpt2"):
        if not (vocab_file and merges_file):
            raise ValueError("GPT2 BPE needs vocab_file and merges_file")
        return GPT2BPETokenizer(vocab_file, merges_file)
    if t in ("nulltokenizer", "null"):
        if vocab_size is None:
            raise ValueError("NullTokenizer needs vocab_size")
        return NullTokenizer(vocab_size)
    raise ValueError(f"unknown tokenizer_type {tokenizer_type!r}")

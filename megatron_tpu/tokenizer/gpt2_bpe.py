"""Byte-level BPE (GPT-2 style), implemented fresh.

Counterpart of the reference's vendored gpt2_tokenization.py (321 LoC).
Standard algorithm: reversible byte<->unicode mapping, greedy lowest-rank
pair merges, GPT-2 pre-tokenization regex. Files: vocab.json (token ->
id) + merges.txt (one merge per line).
"""

from __future__ import annotations

import json
from functools import lru_cache
from typing import Dict, List, Tuple

import regex as re

_PRETOKENIZE = re.compile(
    r"""'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+| ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+"""
)


@lru_cache()
def bytes_to_unicode() -> Dict[int, str]:
    """Map every byte to a printable unicode char (reversible)."""
    bs = (list(range(ord("!"), ord("~") + 1))
          + list(range(ord("\xa1"), ord("\xac") + 1))
          + list(range(ord("\xae"), ord("\xff") + 1)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, (chr(c) for c in cs)))


def _pairs(word: Tuple[str, ...]):
    return set(zip(word[:-1], word[1:]))


class GPT2BPE:
    def __init__(self, vocab_file: str, merges_file: str):
        with open(vocab_file, encoding="utf-8") as f:
            self.encoder: Dict[str, int] = json.load(f)
        self.decoder = {v: k for k, v in self.encoder.items()}
        with open(merges_file, encoding="utf-8") as f:
            lines = f.read().split("\n")
        merges = [tuple(l.split()) for l in lines
                  if l and not l.startswith("#version")]
        self.bpe_ranks = {m: i for i, m in enumerate(merges)}
        self.byte_encoder = bytes_to_unicode()
        self.byte_decoder = {v: k for k, v in self.byte_encoder.items()}
        self._cache: Dict[str, str] = {}

    def _bpe(self, token: str) -> str:
        if token in self._cache:
            return self._cache[token]
        word = tuple(token)
        pairs = _pairs(word)
        while pairs:
            best = min(pairs, key=lambda p: self.bpe_ranks.get(p, float("inf")))
            if best not in self.bpe_ranks:
                break
            first, second = best
            new_word: List[str] = []
            i = 0
            while i < len(word):
                try:
                    j = word.index(first, i)
                except ValueError:
                    new_word.extend(word[i:])
                    break
                new_word.extend(word[i:j])
                i = j
                if i < len(word) - 1 and word[i + 1] == second:
                    new_word.append(first + second)
                    i += 2
                else:
                    new_word.append(word[i])
                    i += 1
            word = tuple(new_word)
            if len(word) == 1:
                break
            pairs = _pairs(word)
        out = " ".join(word)
        self._cache[token] = out
        return out

    def encode(self, text: str) -> List[int]:
        ids: List[int] = []
        for tok in re.findall(_PRETOKENIZE, text):
            tok = "".join(self.byte_encoder[b] for b in tok.encode("utf-8"))
            ids.extend(self.encoder[t] for t in self._bpe(tok).split(" "))
        return ids

    def decode(self, ids) -> str:
        text = "".join(self.decoder[int(i)] for i in ids)
        return bytearray(self.byte_decoder[c] for c in text).decode(
            "utf-8", errors="replace")

from megatron_tpu.tokenizer.tokenizer import (
    AbstractTokenizer,
    build_tokenizer,
    pad_vocab_size,
)

__all__ = ["AbstractTokenizer", "build_tokenizer", "pad_vocab_size"]

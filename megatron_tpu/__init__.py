"""megatron_tpu: a TPU-native LLM training framework.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of
epfLLM/Megatron-LLM (reference layout documented in SURVEY.md): 3D-parallel
(DP x PP x TP) + sequence/context-parallel training and finetuning of
GPT / Llama / Llama-2 / CodeLlama / Falcon / Mistral model families, with
mixed precision, a ZeRO-1-style sharded optimizer, instruction tuning,
HF weight interop, and an incremental-decoding inference service.

Design principles (TPU-first, not a port):
  * One ``jax.sharding.Mesh`` with axes ("data", "pipe", "context", "tensor")
    replaces the reference's NCCL process groups
    (ref: megatron/core/parallel_state.py).
  * Parallel linears are sharded einsums under GSPMD; XLA inserts and
    overlaps the collectives the reference hand-writes in
    megatron/core/tensor_parallel/{layers,mappings}.py.
  * Pipeline parallelism is shard_map + ppermute microbatch rotation
    (ref: megatron/schedules.py 1F1B).
  * Mutable global state (get_args(), parallel_state, rng tracker) becomes
    typed config dataclasses and threaded PRNG keys.
"""

__version__ = "0.1.0"

from megatron_tpu import compat as _compat  # installs jax API shims on import

from megatron_tpu.config import (
    ModelConfig,
    ParallelConfig,
    OptimizerConfig,
    TrainingConfig,
)
from megatron_tpu.parallel.mesh import MeshRuntime, build_mesh

__all__ = [
    "ModelConfig",
    "ParallelConfig",
    "OptimizerConfig",
    "TrainingConfig",
    "MeshRuntime",
    "build_mesh",
    "__version__",
]

"""Platform selection helper.

Some hosting environments pre-import jax via sitecustomize and pin
JAX_PLATFORMS to a TPU plugin before user code runs, so the standard env
var cannot force CPU for tests/CI. MEGATRON_TPU_FORCE_PLATFORM wins if set:
entry points call ensure_platform() before touching any jax API that would
initialize a backend.
"""

from __future__ import annotations

import os
import re


def force_cpu(n_devices: int = 8) -> None:
    """Force the CPU platform with >= n_devices virtual devices.

    Must run before the first jax API call that initializes a backend —
    sitecustomize may pin a TPU plugin via JAX_PLATFORMS, making env vars
    set later ineffective. Mutates os.environ (callers that must not leak
    the override into child processes should snapshot/restore around this).
    """
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    count = max(n_devices, int(m.group(1)) if m else 0)
    flag = f"--xla_force_host_platform_device_count={count}"
    if m:
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", flag, flags)
    else:
        flags = (flags + " " + flag).strip()
    os.environ["XLA_FLAGS"] = flags
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


def ensure_platform() -> None:
    forced = os.environ.get("MEGATRON_TPU_FORCE_PLATFORM")
    if not forced:
        return
    import jax

    jax.config.update("jax_platforms", forced)


# Peak dense bf16 FLOP/s by TPU generation (public spec-sheet numbers),
# keyed by substrings of jax's device_kind. Single source of truth for the
# MFU denominator in bench.py / tools/bench_sweep.py.
_PEAK_BF16 = {
    "v4": 275e12,
    "v5 lite": 197e12, "v5e": 197e12, "v5litepod": 197e12,
    "v5p": 459e12, "v5 p": 459e12,
    "v6e": 918e12, "v6 lite": 918e12,
}


def peak_bf16_flops(device) -> float:
    """Peak bf16 FLOP/s for a jax device; falls back to the v5e figure for
    unknown generations (conservative: over-reports nothing newer)."""
    kind = getattr(device, "device_kind", str(device)).lower()
    return next((v for k, v in _PEAK_BF16.items() if k in kind), 197e12)

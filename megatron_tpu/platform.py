"""Platform selection helper.

Some hosting environments pre-import jax via sitecustomize and pin
JAX_PLATFORMS to a TPU plugin before user code runs, so the standard env
var cannot force CPU for tests/CI. MEGATRON_TPU_FORCE_PLATFORM wins if set:
entry points call ensure_platform() before touching any jax API that would
initialize a backend.
"""

from __future__ import annotations

import os


def ensure_platform() -> None:
    forced = os.environ.get("MEGATRON_TPU_FORCE_PLATFORM")
    if not forced:
        return
    import jax

    jax.config.update("jax_platforms", forced)

#!/usr/bin/env python
"""BERT pretraining entry point (ref: pretrain_bert.py).

Data: a sentence-level indexed dataset (one sequence per sentence, document
boundaries preserved — produce with tools/preprocess_data.py and a sentence
splitter upstream).

  python pretrain_bert.py --num_layers 12 --hidden_size 768 \
      --num_attention_heads 12 --seq_length 512 --vocab_size 30592 \
      --data_path data/sents --mask_token_id 103 --cls_token_id 101 \
      --sep_token_id 102 --pad_token_id 0 --train_iters 10000 ...
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from megatron_tpu.platform import ensure_platform

ensure_platform()

from megatron_tpu.parallel.distributed import initialize_distributed

initialize_distributed()

from megatron_tpu.arguments import args_to_run_config, parse_args


def extra_args(p):
    g = p.add_argument_group("bert")
    g.add_argument("--mask_token_id", type=int, default=103)
    g.add_argument("--cls_token_id", type=int, default=101)
    g.add_argument("--sep_token_id", type=int, default=102)
    g.add_argument("--pad_token_id", type=int, default=0)
    g.add_argument("--no_binary_head", action="store_true")
    return p


def main(argv=None):
    import dataclasses

    import numpy as np

    from megatron_tpu.data.bert_dataset import BertDataset
    from megatron_tpu.data.indexed_dataset import make_dataset
    from megatron_tpu.data.samplers import PretrainingSampler, build_data_loader
    from megatron_tpu.models.bert import bert_loss
    from megatron_tpu.training.pretrain import TrainLoop

    args = parse_args(argv, extra_args_provider=extra_args)
    cfg = args_to_run_config(args)
    # BERT-ify the model config (ref: BertModel flags)
    cfg = dataclasses.replace(
        cfg,
        model=dataclasses.replace(
            cfg.model,
            attn_mask_type="padding",
            num_tokentypes=2,
            bert_binary_head=not args.no_binary_head,
            tie_embed_logits=True,
            position_embedding_type="absolute",
            max_position_embeddings=cfg.model.max_position_embeddings
            or cfg.model.seq_length,
        ).validate())
    if not args.data_path:
        raise SystemExit("--data_path is required")

    t = cfg.training
    indexed = make_dataset(args.data_path[0])
    n_train = (t.train_iters or 1000) * t.global_batch_size
    train_ds = BertDataset(
        indexed, num_samples=n_train, max_seq_length=cfg.model.seq_length,
        mask_token=args.mask_token_id, cls_token=args.cls_token_id,
        sep_token=args.sep_token_id, pad_token=args.pad_token_id,
        vocab_size=cfg.model.vocab_size, seed=t.seed,
        masked_lm_prob=args.mask_prob,
        short_seq_prob=args.short_seq_prob,
        binary_head=not args.no_binary_head)

    def train_iter_factory(consumed, gbs):
        sampler = PretrainingSampler(len(train_ds), consumed, gbs, 0, 1)
        return build_data_loader(train_ds, sampler,
                                 prefetch=args.num_workers)

    def bert_loss_fn(model_cfg, p, b, key, sharder=None):
        kw = {"sharder": sharder} if sharder is not None else {}
        return bert_loss(model_cfg, p, b, dropout_key=key, **kw)

    loop = TrainLoop(cfg, loss_fn=bert_loss_fn)
    loop.train(train_iter_factory)


if __name__ == "__main__":
    main()

"""Benchmark: llama-architecture training-step MFU on one TPU chip.

Prints one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Method: jitted full training step (fwd + bwd + Adam with fp32 masters,
selective recompute, bf16 compute) on a llama-family model sized to fit one
chip's HBM alongside optimizer state. MFU = achieved model FLOP/s over the
chip's peak bf16 FLOP/s, with model FLOPs = 3x forward (fwd + 2x bwd), the
convention the reference's FLOP formula supports
(ref: megatron/model/language_model.py:370-384).

Baseline (BASELINE.md): the reference's Llama-2-7B finetune does ~0.9k
tokens/s per A100-80GB => MFU = 900 * 6 * 6.74e9 / 312e12 = 0.1166.
vs_baseline is our MFU / that.

tools/bench_sweep.py imports headline_config/build_step/time_step so sweep
points are measured with exactly the headline methodology.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

BASELINE_MFU = 900 * 6 * 6.74e9 / 312e12  # reference A100 finetune


def headline_config(seq_length: int = 2048):
    """The headline bench geometry: llama-family, ~640M params — fits one
    chip's HBM with fp32 master + Adam moments."""
    from megatron_tpu.models import presets

    return presets.tiny(
        vocab_size=32000, seq_length=seq_length, hidden_size=2048,
        num_layers=10, num_attention_heads=16, num_kv_heads=16,
        ffn_hidden_size=5504, params_dtype="bfloat16",
        attention_impl="pallas",
    )


def build_step(cfg, micro_bs: int, granularity: str):
    """(state, jitted_step, batch) for one config; fresh state every call."""
    import jax
    import jax.numpy as jnp

    from megatron_tpu.config import OptimizerConfig, TrainingConfig
    from megatron_tpu.models.params import init_params
    from megatron_tpu.training.optimizer import init_train_state
    from megatron_tpu.training.train_step import make_train_step

    opt_cfg = OptimizerConfig(lr=1e-4, lr_decay_style="constant")
    tcfg = TrainingConfig(micro_batch_size=micro_bs,
                          global_batch_size=micro_bs,
                          recompute_granularity=granularity, seed=0)
    rng = np.random.default_rng(0)
    batch = {
        "tokens": jnp.asarray(
            rng.integers(0, cfg.vocab_size, (micro_bs, cfg.seq_length)),
            jnp.int32),
        "labels": jnp.asarray(
            rng.integers(0, cfg.vocab_size, (micro_bs, cfg.seq_length)),
            jnp.int32),
        "loss_mask": jnp.ones((micro_bs, cfg.seq_length), jnp.float32),
    }
    params = init_params(cfg, jax.random.PRNGKey(0))
    state = init_train_state(opt_cfg, params)
    step = jax.jit(
        make_train_step(cfg, opt_cfg, tcfg, num_microbatches=1,
                        train_iters=1000),
        donate_argnums=(0,),
    )
    return state, step, batch


def time_step(state, step, batch, iters: int = 5):
    """(seconds_per_step, loss, state) after a 2-step warmup. Syncs via a
    host transfer (float()) — on the axon TPU plugin block_until_ready
    returns without waiting."""
    state, metrics = step(state, batch)
    float(metrics["loss"])
    state, metrics = step(state, batch)
    float(metrics["loss"])
    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = step(state, batch)
    loss = float(metrics["loss"])
    dt = (time.perf_counter() - t0) / iters
    return dt, loss, state


def is_oom(e: Exception) -> bool:
    return "RESOURCE_EXHAUSTED" in str(e) or "memory" in str(e).lower()


# operating points searched by main(), best MFU wins. First entry is the
# round-2 verified point (mbs 4, selective, 0.5303 MFU) so even a
# quick/degraded run reports a sane number; the chunked-CE variants free
# the ~2 GB [B,S,V] logits residency and may unlock recompute=none or
# mbs 8 (sweep showed both OOM unchunked).
CANDIDATES = (
    dict(micro_bs=4, granularity="selective", ce_chunk=0),
    dict(micro_bs=4, granularity="none", ce_chunk=512),
    dict(micro_bs=8, granularity="selective", ce_chunk=512),
    dict(micro_bs=4, granularity="selective", ce_chunk=512),
    dict(micro_bs=8, granularity="selective", ce_chunk=0),
)


def _cfg_for(cfg, ce_chunk):
    """Apply a candidate's config variant (single source for measuring AND
    profiling — they must never diverge)."""
    import dataclasses

    if ce_chunk:
        return dataclasses.replace(cfg, ce_chunk_size=ce_chunk).validate()
    return cfg


def _measure(cfg, micro_bs, granularity, ce_chunk, iters=5):
    """(dt, loss) or raises; applies the chunked-CE variant."""
    import gc

    cfg = _cfg_for(cfg, ce_chunk)
    state, step, batch = build_step(cfg, micro_bs, granularity)
    try:
        dt, loss, state = time_step(state, step, batch, iters=iters)
        return dt, loss
    finally:
        del state, step, batch
        gc.collect()


def main():
    import jax

    from megatron_tpu.models.params import num_params
    from megatron_tpu.platform import peak_bf16_flops

    cfg = headline_config()
    n_params = num_params(cfg)
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", str(dev)).lower()
    peak = peak_bf16_flops(dev)
    flops_per_token = 3.0 * cfg.flops_per_token_fwd()  # fwd + bwd(2x)

    quick = bool(os.environ.get("MEGATRON_TPU_BENCH_QUICK"))
    candidates = CANDIDATES[:1] if quick else CANDIDATES
    # stop starting new candidates past this elapsed budget so the one
    # JSON line always lands inside the driver's timeout
    budget_s = float(os.environ.get("MEGATRON_TPU_BENCH_BUDGET_S", "420"))

    best = None        # (mfu, cand, dt, loss)
    sweep = []

    def emit_best():
        """Print the one-line JSON for the best point found so far."""
        mfu, cand, dt, loss_val = best
        tokens_per_sec = cand["micro_bs"] * cfg.seq_length / dt
        print(json.dumps({
            "metric": "llama_train_step_mfu",
            "value": round(mfu, 4),
            "unit": "fraction_of_peak_bf16",
            "vs_baseline": round(mfu / BASELINE_MFU, 3),
            "detail": {
                "tokens_per_sec_per_chip": round(tokens_per_sec),
                "step_ms": round(dt * 1e3, 2),
                "n_params": n_params,
                "loss": loss_val,
                "device": str(dev),
                "device_kind": kind,
                "peak_flops_assumed": peak,
                "micro_bs": cand["micro_bs"],
                "recompute": cand["granularity"],
                "ce_chunk": cand["ce_chunk"],
                "attention": "pallas(splash)",
                "sweep": sweep,
            },
        }), flush=True)

    # if the driver times the process out mid-search, flush the best
    # measured point instead of losing the round's number entirely
    import signal

    def on_term(signum, frame):
        if best is not None:
            emit_best()
        sys.exit(0 if best is not None else 1)

    signal.signal(signal.SIGTERM, on_term)

    t_start = time.perf_counter()
    for cand in candidates:
        if best is not None and time.perf_counter() - t_start > budget_s:
            print("# bench budget reached, stopping search", file=sys.stderr)
            break
        try:
            dt, loss = _measure(cfg, **cand)
        except Exception as e:
            if not is_oom(e):
                raise
            sweep.append({**cand, "oom": True})
            print(f"# {cand} OOM", file=sys.stderr)
            continue
        tps = cand["micro_bs"] * cfg.seq_length / dt
        mfu = tps * flops_per_token / peak
        sweep.append({**cand, "mfu": round(mfu, 4),
                      "step_ms": round(dt * 1e3, 2)})
        print(f"# {cand} mfu={mfu:.4f}", file=sys.stderr)
        if best is None or mfu > best[0]:
            best = (mfu, cand, dt, loss)
    if best is None:
        raise RuntimeError("every bench operating point OOMed")
    mfu, cand, dt, loss_val = best

    profile_dir = os.environ.get("MEGATRON_TPU_PROFILE_DIR")
    if profile_dir:
        # re-run the winner under the profiler (trace excludes compile)
        state, step, batch = build_step(_cfg_for(cfg, cand["ce_chunk"]),
                                        cand["micro_bs"],
                                        cand["granularity"])
        _, _, state = time_step(state, step, batch, iters=1)
        jax.profiler.start_trace(profile_dir)
        try:
            time_step(state, step, batch, iters=3)
        finally:
            jax.profiler.stop_trace()

    signal.signal(signal.SIGTERM, signal.SIG_DFL)
    emit_best()


if __name__ == "__main__":
    main()

"""Benchmark: llama-architecture training-step MFU on one TPU chip.

Prints one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Method: jitted full training step (fwd + bwd + Adam with fp32 masters,
selective recompute, bf16 compute) on a llama-family model sized to fit one
chip's HBM alongside optimizer state. MFU = achieved model FLOP/s over the
chip's peak bf16 FLOP/s, with model FLOPs = 3x forward (fwd + 2x bwd), the
convention the reference's FLOP formula supports
(ref: megatron/model/language_model.py:370-384).

Baseline (BASELINE.md): the reference's Llama-2-7B finetune does ~0.9k
tokens/s per A100-80GB => MFU = 900 * 6 * 6.74e9 / 312e12 = 0.1166.
vs_baseline is our MFU / that.
"""

import json
import os
import sys
import time

sys.path.insert(0, ".")

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    from megatron_tpu.config import OptimizerConfig, TrainingConfig
    from megatron_tpu.models import presets
    from megatron_tpu.models.params import init_params, num_params
    from megatron_tpu.training.optimizer import init_train_state
    from megatron_tpu.training.train_step import make_train_step

    # llama-family geometry, ~640M params: fits HBM with fp32 master+moments
    cfg = presets.tiny(
        vocab_size=32000, seq_length=2048, hidden_size=2048, num_layers=10,
        num_attention_heads=16, num_kv_heads=16, ffn_hidden_size=5504,
        params_dtype="bfloat16", attention_impl="pallas",
    )
    n_params = num_params(cfg)

    opt_cfg = OptimizerConfig(lr=1e-4, lr_decay_style="constant")
    micro_bs = 4

    rng = np.random.default_rng(0)
    batch = {
        "tokens": jnp.asarray(rng.integers(0, cfg.vocab_size, (micro_bs, cfg.seq_length)), jnp.int32),
        "labels": jnp.asarray(rng.integers(0, cfg.vocab_size, (micro_bs, cfg.seq_length)), jnp.int32),
        "loss_mask": jnp.ones((micro_bs, cfg.seq_length), jnp.float32),
    }

    # try no recompute first (fastest when activations fit HBM), fall back
    # to selective on OOM. Warmup syncs via host transfer (float()) — on
    # the axon TPU plugin block_until_ready returns without waiting.
    recompute = None
    for granularity in ("none", "selective"):
        tcfg = TrainingConfig(micro_batch_size=micro_bs,
                              global_batch_size=micro_bs,
                              recompute_granularity=granularity, seed=0)
        params = init_params(cfg, jax.random.PRNGKey(0))
        state = init_train_state(opt_cfg, params)
        step = jax.jit(
            make_train_step(cfg, opt_cfg, tcfg, num_microbatches=1,
                            train_iters=1000),
            donate_argnums=(0,),
        )
        try:
            state, metrics = step(state, batch)
            float(metrics["loss"])
            state, metrics = step(state, batch)
            float(metrics["loss"])
            recompute = granularity
            break
        except Exception as e:  # XlaRuntimeError OOM etc.
            if "RESOURCE_EXHAUSTED" not in str(e) and "memory" not in str(e).lower():
                raise
            # free the failed attempt before the fallback allocates
            del params, state, step
            print(f"# recompute={granularity} OOM, retrying", file=sys.stderr)
    if recompute is None:
        raise RuntimeError("both recompute granularities OOMed")

    iters = 5
    profile_dir = os.environ.get("MEGATRON_TPU_PROFILE_DIR")
    if profile_dir:
        jax.profiler.start_trace(profile_dir)
    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = step(state, batch)
    loss_val = float(metrics["loss"])
    dt = (time.perf_counter() - t0) / iters
    if profile_dir:
        jax.profiler.stop_trace()

    tokens_per_sec = micro_bs * cfg.seq_length / dt
    flops_per_token = 3.0 * cfg.flops_per_token_fwd()  # fwd + bwd(2x)
    achieved = tokens_per_sec * flops_per_token

    from megatron_tpu.platform import peak_bf16_flops

    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", str(dev)).lower()
    peak = peak_bf16_flops(dev)
    mfu = achieved / peak

    baseline_mfu = 900 * 6 * 6.74e9 / 312e12  # reference A100 finetune
    print(json.dumps({
        "metric": "llama_train_step_mfu",
        "value": round(mfu, 4),
        "unit": "fraction_of_peak_bf16",
        "vs_baseline": round(mfu / baseline_mfu, 3),
        "detail": {
            "tokens_per_sec_per_chip": round(tokens_per_sec),
            "step_ms": round(dt * 1e3, 2),
            "n_params": n_params,
            "loss": loss_val,
            "device": str(dev),
            "device_kind": kind,
            "peak_flops_assumed": peak,
            "recompute": recompute,
            "attention": "pallas(splash)",
        },
    }))


if __name__ == "__main__":
    main()

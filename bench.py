"""Benchmark: llama-architecture training-step MFU on one TPU chip.

Prints one JSON line per metric, the headline last:
  {"metric": "serve_decode_throughput_toks_per_s", ...}   (full runs)
  {"metric": "llama_train_step_mfu", "value": N, ...}     (always, LAST)

Method: jitted full training step (fwd + bwd + Adam with fp32 masters,
selective recompute, bf16 compute) on a llama-family model sized to fit one
chip's HBM alongside optimizer state. MFU = achieved model FLOP/s over the
chip's peak bf16 FLOP/s, with model FLOPs = 3x forward (fwd + 2x bwd), the
convention the reference's FLOP formula supports
(ref: megatron/model/language_model.py:370-384).

Baseline (BASELINE.md): the reference's Llama-2-7B finetune does ~0.9k
tokens/s per A100-80GB => MFU = 900 * 6 * 6.74e9 / 312e12 = 0.1166.
vs_baseline is our MFU / that.

Resilience: the TPU tunnel in this environment is known to flap (backend
init raises UNAVAILABLE or hangs outright). Backend init is therefore
probed in a kill-safe subprocess with a timeout, retried until the budget
expires; the process ALWAYS emits exactly one parseable JSON line — on
total failure it carries "error": "tpu_unavailable" instead of rc 1.

Beyond the 637M headline point, two honest 7B-class numbers ride along in
"detail" when time remains (BASELINE.md's north star is Llama-2-7B, which
cannot *train* on one 16 GB chip):
  - largest_trainable: the biggest llama-geometry model whose full train
    step fits on-chip (descending search), with its own MFU;
  - serving_int8_7b: Llama-2-7B-geometry int8-weight decode throughput
    (random weights; weights alone are 14 GB bf16, so int8 is what makes
    7B serving on this chip possible at all).

tools/bench_sweep.py imports headline_config/build_step/time_step so sweep
points are measured with exactly the headline methodology.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

BASELINE_MFU = 900 * 6 * 6.74e9 / 312e12  # reference A100 finetune

# Goodput ledger for the whole bench process (set by main() once jax is
# up): timed step iterations are attributed productive, XLA compiles from
# the recompile tracker, the remainder (probe waits, host param fills,
# serving drains) lands in `other`. Rides the headline JSON line as
# detail["goodput"] so the driver's record of a round says not just the
# MFU but where the bench's wall-clock went (tools/telemetry_report.py
# prints the same split for training journals).
GOODPUT = None


def headline_config(seq_length: int = 2048):
    """The headline bench geometry: llama-family, ~640M params — fits one
    chip's HBM with fp32 master + Adam moments."""
    from megatron_tpu.models import presets

    return presets.tiny(
        vocab_size=32000, seq_length=seq_length, hidden_size=2048,
        num_layers=10, num_attention_heads=16, num_kv_heads=16,
        ffn_hidden_size=5504, params_dtype="bfloat16",
        attention_impl="pallas",
    )


def build_step(cfg, micro_bs: int, granularity: str):
    """(state, jitted_step, batch) for one config; fresh state every call."""
    import jax
    import jax.numpy as jnp

    from megatron_tpu.config import OptimizerConfig, TrainingConfig
    from megatron_tpu.models.params import init_params
    from megatron_tpu.training.optimizer import init_train_state
    from megatron_tpu.training.train_step import make_train_step

    opt_cfg = OptimizerConfig(lr=1e-4, lr_decay_style="constant")
    tcfg = TrainingConfig(micro_batch_size=micro_bs,
                          global_batch_size=micro_bs,
                          recompute_granularity=granularity, seed=0)
    rng = np.random.default_rng(0)
    batch = {
        "tokens": jnp.asarray(
            rng.integers(0, cfg.vocab_size, (micro_bs, cfg.seq_length)),
            jnp.int32),
        "labels": jnp.asarray(
            rng.integers(0, cfg.vocab_size, (micro_bs, cfg.seq_length)),
            jnp.int32),
        "loss_mask": jnp.ones((micro_bs, cfg.seq_length), jnp.float32),
    }
    params = init_params(cfg, jax.random.PRNGKey(0))
    state = init_train_state(opt_cfg, params)
    step = jax.jit(
        make_train_step(cfg, opt_cfg, tcfg, num_microbatches=1,
                        train_iters=1000),
        donate_argnums=(0,),
    )
    return state, step, batch


def time_step(state, step, batch, iters: int = 5):
    """(seconds_per_step, loss, state) after a 2-step warmup. Syncs via a
    host transfer (float()) — on the axon TPU plugin block_until_ready
    returns without waiting."""
    state, metrics = step(state, batch)
    float(metrics["loss"])
    state, metrics = step(state, batch)
    float(metrics["loss"])
    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = step(state, batch)
    loss = float(metrics["loss"])
    dt = (time.perf_counter() - t0) / iters
    return dt, loss, state


def is_oom(e: Exception) -> bool:
    return "RESOURCE_EXHAUSTED" in str(e) or "memory" in str(e).lower()


# ---------------------------------------------------------------------------
# backend probe: the tunnel can make jax.devices() hang, not just raise, so
# the probe must run in a subprocess we can kill (memory note
# axon-tpu-tunnel-fragility; VERDICT r2 "what's weak" #1)

def probe_backend(timeout_s: float = 60.0):
    """(ok, message) — try jax backend init in a kill-safe subprocess."""
    import subprocess

    code = "import jax; d = jax.devices(); print(d[0].platform, len(d))"
    try:
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return False, f"probe timed out after {timeout_s:.0f}s"
    except Exception as e:  # pragma: no cover - spawn failure
        return False, f"probe spawn failed: {e}"
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()
        return False, tail[-1][:300] if tail else f"probe rc={r.returncode}"
    return True, r.stdout.strip()


def wait_for_backend(deadline: float, probe_timeout: float = 60.0,
                     retry_every_s: float = 60.0,
                     max_identical_failures: int = 2):
    """Retry probe_backend until success or deadline. (ok, attempts_log).

    Fail-fast on a DEAD (not flapping) backend: once max_identical_failures
    consecutive probes fail with the same signature, the tunnel is down the
    same way every time and further probes only burn the budget — BENCH_r05
    spent 7x60s on identical timeouts before emitting tpu_unavailable.
    Failures whose messages differ (a genuinely flapping tunnel changing
    state) keep retrying until the deadline. Set
    MEGATRON_TPU_BENCH_PROBE_PERSIST=1 to restore retry-until-deadline."""
    if os.environ.get("MEGATRON_TPU_BENCH_PROBE_PERSIST"):
        max_identical_failures = 1 << 30
    log = []
    while True:
        t_probe = time.perf_counter()
        remaining = deadline - t_probe
        if remaining <= 5:
            return False, log
        ok, msg = probe_backend(min(probe_timeout, remaining))
        log.append(msg)
        print(f"# backend probe: {'ok' if ok else 'DOWN'}: {msg}",
              file=sys.stderr)
        if ok:
            return True, log
        if (len(log) >= max_identical_failures
                and len(set(log[-max_identical_failures:])) == 1):
            print(f"# backend probe: {max_identical_failures} identical "
                  "failures — backend is down, failing fast "
                  "(MEGATRON_TPU_BENCH_PROBE_PERSIST=1 to keep retrying)",
                  file=sys.stderr)
            return False, log
        # pace retries: one probe start per retry_every_s, budget allowing
        sleep = retry_every_s - (time.perf_counter() - t_probe)
        if sleep > 0:
            time.sleep(min(sleep, max(0.0, deadline - time.perf_counter())))


# operating points searched by main(), best MFU wins. First entry is the
# round-2 verified point (mbs 4, selective, 0.5303 MFU) so even a
# quick/degraded run reports a sane number; the chunked-CE variants free
# the ~2 GB [B,S,V] logits residency and may unlock recompute=none or
# mbs 8 (sweep showed both OOM unchunked).
CANDIDATES = (
    dict(micro_bs=4, granularity="selective", ce_chunk=0),
    dict(micro_bs=4, granularity="none", ce_chunk=512),
    dict(micro_bs=8, granularity="selective", ce_chunk=512),
    dict(micro_bs=4, granularity="selective", ce_chunk=512),
    dict(micro_bs=8, granularity="selective", ce_chunk=0),
)


def _cfg_for(cfg, ce_chunk):
    """Apply a candidate's config variant (single source for measuring AND
    profiling — they must never diverge)."""
    import dataclasses

    if ce_chunk:
        return dataclasses.replace(cfg, ce_chunk_size=ce_chunk).validate()
    return cfg


def _measure(cfg, micro_bs, granularity, ce_chunk, iters=5):
    """(dt, loss) or raises; applies the chunked-CE variant."""
    import gc

    cfg = _cfg_for(cfg, ce_chunk)
    state, step, batch = build_step(cfg, micro_bs, granularity)
    try:
        dt, loss, state = time_step(state, step, batch, iters=iters)
        if GOODPUT is not None:
            GOODPUT.attribute("productive", dt * iters)
        return dt, loss
    finally:
        del state, step, batch
        gc.collect()


# ---------------------------------------------------------------------------
# extra 7B-class points (VERDICT r2 next-round #3)

def largest_candidates():
    """Llama-geometry configs, descending by params; the search reports the
    first whose full train step fits on-chip."""
    from megatron_tpu.models import presets

    geoms = (  # (hidden, layers, heads)
        (2816, 18, 22),
        (2560, 18, 20),
        (2560, 14, 20),
        (2304, 14, 18),
    )
    out = []
    for h, L, nh in geoms:
        ffn = int(round(8 * h / 3 / 256)) * 256
        out.append(presets.tiny(
            vocab_size=32000, seq_length=2048, hidden_size=h, num_layers=L,
            num_attention_heads=nh, num_kv_heads=nh, ffn_hidden_size=ffn,
            params_dtype="bfloat16", attention_impl="pallas"))
    return out


def largest_trainable_bench(deadline, peak):
    """Largest on-chip-trainable llama geometry + its MFU, or an error
    record. Descending search; per-geometry (mbs, recompute) tiers from
    fastest to most memory-frugal — chunked CE throughout, selective
    first, then full and sqrt-remat (uniform:N) which trade step time for
    fitting a bigger model (the metric here is SIZE, not MFU)."""
    from megatron_tpu.models.params import num_params

    for cfg in largest_candidates():
        ce_chunk = 512 if cfg.seq_length % 512 == 0 else 0
        # sqrt-remat chunk must DIVIDE the layer count (scan_with_remat
        # raises otherwise — and that ValueError is not an OOM, it would
        # abort the whole search): nearest divisor of L to sqrt(L), >1
        L = cfg.num_layers
        divs = [d for d in range(2, L + 1) if L % d == 0]
        chunk = min(divs, key=lambda d: abs(d - L ** 0.5)) if divs else 1
        tiers = [(2, "selective"), (1, "selective"), (1, "full")]
        if chunk > 1:
            tiers.append((1, f"uniform:{chunk}"))
        for mbs, gran in tiers:
            if deadline - time.perf_counter() < 45:
                return {"error": "budget_exhausted"}
            try:
                dt, loss = _measure(cfg, mbs, gran, ce_chunk, iters=3)
            except Exception as e:
                if not is_oom(e):
                    return {"error": str(e)[:300]}
                print(f"# largest: h={cfg.hidden_size} L={cfg.num_layers} "
                      f"mbs={mbs} {gran} OOM", file=sys.stderr)
                continue
            n = num_params(cfg)
            tps = mbs * cfg.seq_length / dt
            mfu = tps * 3.0 * cfg.flops_per_token_fwd() / peak
            return {
                "n_params": n,
                "hidden": cfg.hidden_size, "layers": cfg.num_layers,
                "micro_bs": mbs, "seq": cfg.seq_length,
                "recompute": gran,
                "mfu": round(mfu, 4),
                "tokens_per_sec_per_chip": round(tps),
                "step_ms": round(dt * 1e3, 2), "loss": loss,
            }
    return {"error": "all_geometries_oom"}


def _host_random_params(cfg, seed=0, std=0.02):
    """Random param tree built on HOST (numpy) from eval_shape — a 7B bf16
    tree must never materialize on a 16 GB device."""
    import jax

    from megatron_tpu.models.params import init_params

    shapes = jax.eval_shape(lambda k: init_params(cfg, k),
                            jax.eval_shape(lambda: jax.random.PRNGKey(0)))
    rng = np.random.default_rng(seed)

    def mk(s):
        return (rng.standard_normal(s.shape, np.float32) * std).astype(s.dtype)

    return jax.tree.map(mk, shapes)


_SERVING_HOST_CACHE = {}


def serving_int8_7b_bench(deadline, cfg=None, B=4, prompt_len=64,
                          new_tokens=128, mode="int8"):
    """Llama-2-7B geometry, int8 or fp8(e4m3) weights, decode tokens/s
    (random weights — throughput is weight-value-independent). Ref north
    star: BASELINE.md; the fp8 point answers VERDICT r4 #7's fp8 half.
    The host random tree is cached per geometry so the int8 and fp8
    points pay the 7B host fill once."""
    from megatron_tpu.inference.generation import generate_tokens
    from megatron_tpu.models import presets
    from megatron_tpu.models.params import num_params
    from megatron_tpu.ops.weight_quant import quantize_params_for_serving

    cfg = cfg or presets.llama("7B", version=2, seq_length=2048)
    if deadline - time.perf_counter() < 60:
        return {"error": "budget_exhausted"}
    try:
        import jax

        # quantize on host, then place the int8 tree on-device ONCE —
        # _generate_jit traces params, so numpy leaves would re-transfer
        # ~7 GB inside every (timed) call
        key = (cfg.hidden_size, cfg.num_layers, cfg.vocab_size,
               cfg.seq_length)
        if key not in _SERVING_HOST_CACHE:
            _SERVING_HOST_CACHE[key] = _host_random_params(cfg)
        params = jax.device_put(
            quantize_params_for_serving(_SERVING_HOST_CACHE[key], mode=mode))
        rng = np.random.default_rng(0)
        prompts = rng.integers(0, cfg.vocab_size, (B, prompt_len)).astype(np.int32)
        lengths = np.full((B,), prompt_len, np.int32)

        def run():
            return generate_tokens(cfg, params, prompts, lengths,
                                   max_new_tokens=new_tokens, temperature=1.0,
                                   top_k=1, eod=None, want_logprobs=False)

        run()  # compile + transfer
        t0 = time.perf_counter()
        run()
        dt = time.perf_counter() - t0
        tps = B * new_tokens / dt
        return {
            "n_params": num_params(cfg),
            "batch": B, "prompt_len": prompt_len, "new_tokens": new_tokens,
            "decode_tokens_per_sec": round(tps, 1),
            "weights": ("int8 (per-channel symmetric)" if mode == "int8"
                        else "fp8 e4m3 (per-channel amax)"),
        }
    except Exception as e:
        return {"error": str(e)[:300]}


def serving_engine_bench(deadline, num_slots=4, prompt_len=8, new_tokens=24):
    """Offered-load continuous-batching throughput: submit num_slots
    concurrent requests to an InferenceEngine (inference/engine.py) and
    time the drain against handling the same requests sequentially
    through generate_tokens — one shared jitted batched decode step vs a
    per-request loop. Returns the full metric line; vs_baseline is the
    speedup over sequential handling (> 1 = continuous batching wins, and
    it grows with concurrency until the chip saturates). Geometry rides
    on headline_config so hermetic tests stay tiny."""
    line = {"metric": "serve_decode_throughput_toks_per_s", "value": 0.0,
            "unit": "tokens_per_sec", "vs_baseline": 0.0}
    if deadline - time.perf_counter() < 30:
        line["error"] = "budget_exhausted"
        return line
    try:
        import jax

        from megatron_tpu.inference.engine import InferenceEngine
        from megatron_tpu.inference.generation import generate_tokens
        from megatron_tpu.models.params import init_params

        cfg = headline_config()
        if jax.default_backend() == "cpu" and cfg.hidden_size > 512:
            # CPU runs are recipe/sanity runs (docs/serving.md): the 640M
            # headline geometry takes longer than the whole budget host-
            # side, so shrink to a llama-shaped model that finishes in
            # seconds; the TPU number is the real metric
            from megatron_tpu.models import presets

            cfg = presets.tiny(
                vocab_size=8192, seq_length=256, hidden_size=256,
                num_layers=4, num_attention_heads=8, num_kv_heads=8,
                ffn_hidden_size=512, params_dtype="float32")
        params = init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        prompts = rng.integers(
            0, cfg.vocab_size, (num_slots, prompt_len)).astype(np.int32)
        lengths = np.full((num_slots,), prompt_len, np.int32)
        eng = InferenceEngine(cfg, params, num_slots=num_slots,
                              max_seq_len=min(cfg.seq_length, 128))

        # warmup compiles both paths: the engine's prefill bucket + the
        # one batched decode step, and the baseline's generate loop
        eng.generate(prompts[:1], lengths[:1], max_new_tokens=new_tokens)
        generate_tokens(cfg, params, prompts[:1], lengths[:1],
                        max_new_tokens=new_tokens, temperature=0.0,
                        want_logprobs=False)

        t0 = time.perf_counter()
        for i in range(num_slots):
            generate_tokens(cfg, params, prompts[i:i + 1], lengths[i:i + 1],
                            max_new_tokens=new_tokens, temperature=0.0,
                            want_logprobs=False)
        t_seq = max(time.perf_counter() - t0, 1e-9)

        def compiles():
            try:  # jitted-fn cache size = number of distinct compiles
                return int(eng._decode_step._cache_size())
            except Exception:  # noqa: BLE001 - diagnostics only
                return -1

        warm = compiles()
        t0 = time.perf_counter()
        eng.generate(prompts, lengths, max_new_tokens=new_tokens)
        t_eng = max(time.perf_counter() - t0, 1e-9)

        tps = num_slots * new_tokens / t_eng
        line.update(
            value=round(tps, 1),
            vs_baseline=round(t_seq / t_eng, 3),
            detail={
                "num_slots": num_slots, "prompt_len": prompt_len,
                "new_tokens": new_tokens,
                "engine_drain_s": round(t_eng, 4),
                "sequential_s": round(t_seq, 4),
                "decode_recompiles_after_warmup": (
                    compiles() - warm if warm >= 0 else -1),
                "hidden": cfg.hidden_size, "layers": cfg.num_layers,
            })
    except Exception as e:  # noqa: BLE001 - the metric line must emit
        line["error"] = str(e)[:300]
    return line


def serve_prefix_cache_bench(deadline, num_requests=8, shared_len=64,
                             unique_len=8, new_tokens=4):
    """Shared-system-prompt traffic through the paged engine
    (inference/paging/): every request is a shared `shared_len`-token
    system prefix plus a distinct `unique_len`-token user suffix — the
    "millions of users, one prompt template" shape. The first request
    populates the radix prefix cache; the rest alias its pages and skip
    prefill for the shared span. value = total prompt tokens / prefill
    tokens actually computed (deterministic — read off the engine's
    counters, not wall clocks); vs_baseline is the wall-time speedup of
    the same traffic vs the slot engine, which recomputes every prefix."""
    line = {"metric": "serve_prefix_cache_speedup", "value": 0.0,
            "unit": "x_prefill_tokens", "vs_baseline": 0.0}
    if deadline - time.perf_counter() < 30:
        line["error"] = "budget_exhausted"
        return line
    try:
        import jax

        from megatron_tpu.inference.engine import InferenceEngine, Request
        from megatron_tpu.inference.paging import PagedInferenceEngine
        from megatron_tpu.models import presets
        from megatron_tpu.models.params import init_params

        cfg = headline_config()
        if jax.default_backend() == "cpu" and cfg.hidden_size > 512:
            # CPU runs are recipe/sanity runs (docs/serving.md): shrink to
            # a llama-shaped model that finishes in seconds
            cfg = presets.tiny(
                vocab_size=8192, seq_length=256, hidden_size=256,
                num_layers=4, num_attention_heads=8, num_kv_heads=8,
                ffn_hidden_size=512, params_dtype="float32")
        params = init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        shared = rng.integers(1, cfg.vocab_size, shared_len)
        prompts = [np.concatenate([
            shared, rng.integers(1, cfg.vocab_size, unique_len),
        ]).astype(np.int32) for _ in range(num_requests)]

        def drive(eng):
            # first request alone (populates the prefix cache), then the
            # rest concurrently — the arrival pattern a warm template sees
            t0 = time.perf_counter()
            r0 = eng.submit(Request(prompt=prompts[0],
                                    max_new_tokens=new_tokens))
            eng.run_until_idle()
            rest = [eng.submit(Request(prompt=p, max_new_tokens=new_tokens))
                    for p in prompts[1:]]
            eng.run_until_idle()
            for r in [r0] + rest:
                if r.error:
                    raise RuntimeError(r.error)
            return time.perf_counter() - t0

        # page-aligned so neither engine warns about seq-len rounding
        max_len = -(-(shared_len + unique_len + new_tokens + 16) // 16) * 16
        paged = PagedInferenceEngine(cfg, params, num_slots=4,
                                     max_seq_len=max_len,
                                     page_size=16, prefill_chunk=32,
                                     want_logprobs=False)
        drive(paged)  # warmup: compiles chunk + decode steps
        # drop the warmup's radix entries so the measured drive IS the
        # documented cold-template scenario (r0 populates, the rest
        # alias) — without this every request including r0 hits the
        # warm cache and `value` overstates the cold-traffic savings
        paged.prefix_cache.clear()
        warm_computed = paged.stats["prefill_tokens"]
        warm_hits = paged.stats["prefix_hits"]
        t_paged = drive(paged)
        computed = paged.stats["prefill_tokens"] - warm_computed

        slot = InferenceEngine(cfg, params, num_slots=4,
                               max_seq_len=max_len,
                               want_logprobs=False)
        drive(slot)  # warmup
        t_slot = drive(slot)

        total_prompt = num_requests * (shared_len + unique_len)
        line.update(
            value=round(total_prompt / max(computed, 1), 3),
            vs_baseline=round(t_slot / max(t_paged, 1e-9), 3),
            detail={
                "num_requests": num_requests, "shared_len": shared_len,
                "unique_len": unique_len,
                "prefill_tokens_computed": int(computed),
                "prefill_tokens_total": int(total_prompt),
                "prefix_hits": int(paged.stats["prefix_hits"] - warm_hits),
                "paged_wall_s": round(t_paged, 4),
                "slot_wall_s": round(t_slot, 4),
                "decode_recompiles_after_warmup": int(
                    paged.stats["decode_recompiles"]),
                "hidden": cfg.hidden_size, "layers": cfg.num_layers,
            })
    except Exception as e:  # noqa: BLE001 - the metric line must emit
        line["error"] = str(e)[:300]
    return line


def serve_speculative_bench(deadline, num_slots=4, prompt_len=16,
                            new_tokens=64, spec_k=8, reps=3):
    """Speculative-decoding throughput on high-acceptance greedy
    traffic (inference/speculative.py): the same requests drained
    through a plain engine and through one running the zero-weight
    n-gram drafter at k=spec_k. The model's weights are ZEROED so its
    greedy continuation is constant — after a couple of warm-up tokens
    the drafter's prompt-lookup proposals match the target argmax
    every tick, i.e. the documented high-acceptance (repetitive /
    copy-heavy) traffic shape as an upper bound. What the ratio then
    measures is the ENGINE mechanics claim: k+1 tokens emitted per
    single [N, k+1] verify forward, with the accept rate reported
    alongside so the number can be derated for real traffic. Greedy
    parity is asserted inside the bench (spec tokens must equal the
    plain engine's), and "speculation off" IS the baseline engine —
    the non-speculative code path is untouched by the feature."""
    line = {"metric": "serve_speculative_speedup", "value": 0.0,
            "unit": "tokens_per_sec", "vs_baseline": 0.0}
    if deadline - time.perf_counter() < 30:
        line["error"] = "budget_exhausted"
        return line
    try:
        import jax
        import jax.numpy as jnp

        from megatron_tpu.inference.engine import InferenceEngine
        from megatron_tpu.inference.speculative import SpecConfig
        from megatron_tpu.models import presets
        from megatron_tpu.models.params import init_params

        cfg = headline_config()
        if jax.default_backend() == "cpu" and cfg.hidden_size > 512:
            # CPU runs are recipe/sanity runs (docs/serving.md): shrink
            # to a llama-shaped model that finishes in seconds
            cfg = presets.tiny(
                vocab_size=8192, seq_length=256, hidden_size=256,
                num_layers=4, num_attention_heads=8, num_kv_heads=8,
                ffn_hidden_size=512, params_dtype="float32")
        params = jax.tree.map(lambda a: jnp.zeros_like(a),
                              init_params(cfg, jax.random.PRNGKey(0)))
        rng = np.random.default_rng(0)
        prompts = rng.integers(
            1, cfg.vocab_size, (num_slots, prompt_len)).astype(np.int32)
        lengths = np.full((num_slots,), prompt_len, np.int32)

        base = InferenceEngine(cfg, params, num_slots=num_slots,
                               max_seq_len=128, want_logprobs=False)
        spec = InferenceEngine(cfg, params, num_slots=num_slots,
                               max_seq_len=128, want_logprobs=False,
                               speculative=SpecConfig(k=spec_k,
                                                      drafter="ngram"))
        # warmup compiles both decode steps + the shared prefill bucket
        base.generate(prompts[:1], lengths[:1], max_new_tokens=new_tokens)
        spec.generate(prompts[:1], lengths[:1], max_new_tokens=new_tokens)

        # median of `reps` interleaved drains: the 2-core host's wall
        # clocks are noisy, and interleaving keeps background load from
        # biasing one engine's measurements
        t_bases, t_specs = [], []
        prop0, acc0 = spec.stats["spec_proposed"], spec.stats["spec_accepted"]
        for _ in range(reps):
            t0 = time.perf_counter()
            want = base.generate(prompts, lengths,
                                 max_new_tokens=new_tokens)
            t_bases.append(max(time.perf_counter() - t0, 1e-9))
            t0 = time.perf_counter()
            got = spec.generate(prompts, lengths,
                                max_new_tokens=new_tokens)
            t_specs.append(max(time.perf_counter() - t0, 1e-9))
            if not np.array_equal(want.tokens, got.tokens):
                raise RuntimeError("speculative greedy output diverged "
                                   "from the plain engine")
        t_base = sorted(t_bases)[reps // 2]
        t_spec = sorted(t_specs)[reps // 2]

        proposed = spec.stats["spec_proposed"] - prop0
        accepted = spec.stats["spec_accepted"] - acc0
        tps = num_slots * new_tokens / t_spec
        line.update(
            value=round(tps, 1),
            vs_baseline=round(t_base / t_spec, 3),
            detail={
                "num_slots": num_slots, "prompt_len": prompt_len,
                "new_tokens": new_tokens, "spec_k": spec_k,
                "drafter": "ngram",
                "baseline_toks_per_s": round(
                    num_slots * new_tokens / t_base, 1),
                "accept_rate": round(accepted / max(proposed, 1), 3),
                "spec_wall_s": round(t_spec, 4),
                "baseline_wall_s": round(t_base, 4),
                "decode_recompiles_after_warmup": int(
                    spec.stats["decode_recompiles"]),
                "model": "zero-weights (constant greedy continuation — "
                         "high-acceptance upper bound; derate by the "
                         "accept rate for real traffic)",
                "hidden": cfg.hidden_size, "layers": cfg.num_layers,
            })
    except Exception as e:  # noqa: BLE001 - the metric line must emit
        line["error"] = str(e)[:300]
    return line


def serve_slo_bench(deadline, num_replicas=2, engine_slots=2,
                    num_requests=18, offered_rps=3.0, new_tokens=8):
    """Offered-load SLO replay through the fleet router
    (inference/fleet/): an in-process fleet of `num_replicas` replica
    servers behind a RouterServer receives a deterministic open-loop
    trace at `offered_rps` (tools/slo_harness.py inlined), and the line
    reports TTFT/TPOT p50/p95/p99 scraped off the engines' Prometheus
    histograms (diffed around the window, so warmup compiles fall out).
    value = achieved completed-requests/s; vs_baseline = achieved/offered
    (1.0 = the fleet keeps up with the offered load; every request must
    complete — a lost request zeroes the line). Tiny deterministic
    geometry on every backend: this measures the control plane's latency
    distribution under load, not model throughput (the throughput story
    is serve_decode_throughput_toks_per_s)."""
    line = {"metric": "serve_slo_offered_load", "value": 0.0,
            "unit": "requests_per_sec", "vs_baseline": 0.0}
    if deadline - time.perf_counter() < 60:
        line["error"] = "budget_exhausted"
        return line
    services, servers, threads = [], [], []
    router = None
    try:
        import threading
        from http.server import ThreadingHTTPServer

        import jax

        from megatron_tpu.inference.fleet import slo
        from megatron_tpu.inference.fleet.router import RouterServer
        from megatron_tpu.inference.server import (
            GenerationService, make_handler,
        )
        from megatron_tpu.models import presets
        from megatron_tpu.models.params import init_params
        from megatron_tpu.telemetry.metrics import MetricsRegistry
        from megatron_tpu.tokenizer.tokenizer import NullTokenizer

        cfg = presets.tiny(vocab_size=64, seq_length=64)
        tok = NullTokenizer(cfg.vocab_size - 1)
        params = init_params(cfg, jax.random.PRNGKey(0))
        urls = []
        for _ in range(num_replicas):
            # per-replica registries: shared default_registry would merge
            # both engines' histograms before the scrape even runs
            # warmup=True defers the warmed flag so svc.warmup() below
            # actually compiles (with the default it's a no-op and the
            # jit compile would land INSIDE the measured SLO window)
            svc = GenerationService(cfg, params, tok,
                                    engine_slots=engine_slots,
                                    engine_max_seq_len=64,
                                    metrics=MetricsRegistry(),
                                    warmup=True)
            srv = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(svc))
            th = threading.Thread(target=srv.serve_forever, daemon=True)
            th.start()
            svc.warmup()
            services.append(svc)
            servers.append(srv)
            threads.append(th)
            urls.append(f"http://127.0.0.1:{srv.server_address[1]}")
        router = RouterServer(urls).start()
        trace = slo.make_trace(num_requests, offered_rps,
                               vocab=cfg.vocab_size, new_tokens=new_tokens)
        report = slo.run_slo(router.url + "/api",
                             [u + "/metrics" for u in urls], trace,
                             offered_rps, timeout=60.0)
        value = report["achieved_rps"] if report["failed"] == 0 else 0.0
        line.update(
            value=value,
            vs_baseline=round(value / offered_rps, 3),
            detail={
                "num_replicas": num_replicas,
                "engine_slots": engine_slots,
                "requests": report["requests"],
                "completed": report["completed"],
                "failed": report["failed"],
                "ttft_s": report["ttft_s"],
                "tpot_s": report["tpot_s"],
                "client_wall_s": report["client_wall_s"],
                "new_tokens": new_tokens,
                "hidden": cfg.hidden_size, "layers": cfg.num_layers,
            })
    except Exception as e:  # noqa: BLE001 - the metric line must emit
        line["error"] = str(e)[:300]
    finally:
        if router is not None:
            router.close()
        for srv in servers:
            srv.shutdown()
            srv.server_close()
        for svc in services:
            svc.shutdown()
    return line


def serve_compressed_comm_bench(deadline, num_slots=4, prompt_len=8,
                                new_tokens=24, reps=3):
    """Compressed TP collectives for serving (megatron_tpu/quant/,
    Flash Communication 2412.04964): value = the contract-verified
    wire-byte reduction between the committed decode_tp2_dense and
    decode_tp2_int8 golden comm manifests — DETERMINISTIC (read off the
    repo, asserted >= 3x by tools/comm_report.py --check and the tier-1
    tests, so a silent revert to dense transport zeroes this line too).
    vs_baseline = the dense/int8 wall ratio of the same greedy traffic
    through two real engines on a tp=2 mesh — informational on CPU
    (2 fake devices on 2 cores pay quantize/dequantize compute without
    real interconnect to save; the byte counters are the gate, the chip
    window turns the wall number real). Needs >= 2 devices for the wall
    leg; the byte ratio emits regardless."""
    line = {"metric": "serve_compressed_comm", "value": 0.0,
            "unit": "x_wire_bytes", "vs_baseline": 0.0}
    try:
        from megatron_tpu.analysis import contracts

        dense_m = contracts.load_manifest("decode_tp2_dense")
        int8_m = contracts.load_manifest("decode_tp2_int8")
        ratio = contracts.compression_ratio(int8_m, dense_m)
        detail = {
            "dense_wire_bytes": dense_m["jaxpr"]["total_wire_bytes"],
            "int8_wire_bytes": int8_m["jaxpr"]["total_wire_bytes"],
            "manifests": ["decode_tp2_dense", "decode_tp2_int8"],
        }
        line.update(value=round(ratio, 3), detail=detail)
    except Exception as e:  # noqa: BLE001 - the metric line must emit
        line["error"] = str(e)[:300]
        return line
    if deadline - time.perf_counter() < 30:
        detail["wall"] = "budget_exhausted"
        return line
    try:
        import jax

        if len(jax.devices()) < 2:
            detail["wall"] = "needs >= 2 devices for the tp=2 wall leg"
            return line

        from megatron_tpu.config import ModelConfig, ParallelConfig
        from megatron_tpu.inference.engine import InferenceEngine
        from megatron_tpu.models.params import init_params, param_specs
        from megatron_tpu.parallel.mesh import build_mesh
        from megatron_tpu.parallel.sharding import shard_tree

        cfg = ModelConfig(
            num_layers=4, hidden_size=128, num_attention_heads=8,
            num_kv_heads=4, ffn_hidden_size=256, vocab_size=1024,
            seq_length=64, params_dtype="float32").validate()
        params = init_params(cfg, jax.random.PRNGKey(0))
        rt = build_mesh(ParallelConfig(tensor_parallel=2),
                        devices=jax.devices()[:2])
        sparams = shard_tree(rt, params, param_specs(cfg))
        dense = InferenceEngine(cfg, sparams, num_slots=num_slots,
                                max_seq_len=64, mesh=rt.mesh,
                                want_logprobs=False)
        comp = InferenceEngine(cfg, sparams, num_slots=num_slots,
                               max_seq_len=64, mesh=rt.mesh,
                               want_logprobs=False,
                               compress_collectives="int8")
        rng = np.random.default_rng(0)
        prompts = rng.integers(
            1, cfg.vocab_size, (num_slots, prompt_len)).astype(np.int32)
        lengths = np.full((num_slots,), prompt_len, np.int32)
        # warmup compiles both decode steps + the shared prefill bucket
        dense.generate(prompts[:1], lengths[:1], max_new_tokens=new_tokens)
        comp.generate(prompts[:1], lengths[:1], max_new_tokens=new_tokens)
        t_d, t_c = [], []
        for _ in range(reps):
            t0 = time.perf_counter()
            dense.generate(prompts, lengths, max_new_tokens=new_tokens)
            t_d.append(max(time.perf_counter() - t0, 1e-9))
            t0 = time.perf_counter()
            comp.generate(prompts, lengths, max_new_tokens=new_tokens)
            t_c.append(max(time.perf_counter() - t0, 1e-9))
        wall_d = sorted(t_d)[reps // 2]
        wall_c = sorted(t_c)[reps // 2]
        line["vs_baseline"] = round(wall_d / wall_c, 3)
        detail.update({
            "dense_wall_s": round(wall_d, 4),
            "int8_wall_s": round(wall_c, 4),
            "counter_dense_bytes": comp.stats["comm_dense_bytes"],
            "counter_compressed_bytes": comp.stats["comm_compressed_bytes"],
            "decode_recompiles_after_warmup": int(
                comp.stats["decode_recompiles"]),
            "num_slots": num_slots, "new_tokens": new_tokens,
            "hidden": cfg.hidden_size, "layers": cfg.num_layers,
            "wall_note": ("CPU wall is informational: fake devices share "
                          "the host cores, so the quantize math costs "
                          "show and the saved interconnect bytes don't"),
        })
    except Exception as e:  # noqa: BLE001 - pre-headline lines must never
        # cost the run its headline
        detail["wall_error"] = str(e)[:300]
    return line


def serve_longctx_prefill_bench(deadline, prompt_len=192, page_size=8,
                                prefill_chunk=32, new_tokens=4, reps=3,
                                cfg=None):
    """Context-parallel long-context serving
    (megatron_tpu/inference/context_parallel/): one long prompt chunk-
    prefilled through the CP engine — the prompt's paged KV sequence-
    striped over a cp=2 mesh, every chunk ring-attended across the
    shards. value = CP prefill throughput (prompt tokens/s, median of
    reps); vs_baseline = single-host-paged / CP wall ratio of the same
    traffic — informational on CPU (fake devices share host cores and
    the ring hops become memcpy; on a chip the win is CAPACITY: per-
    device KV bytes drop by 1/cp, which is what lets the million-token
    prompt fit at all). The gates riding in detail are real everywhere:
    greedy tokens must match the single-host paged engine exactly and
    decode must not recompile after warmup."""
    line = {"metric": "serve_longctx_prefill", "value": 0.0,
            "unit": "prompt_toks_per_s", "vs_baseline": 0.0}
    if deadline - time.perf_counter() < 30:
        line["error"] = "budget_exhausted"
        return line
    try:
        import jax

        if len(jax.devices()) < 2:
            line["error"] = "needs >= 2 devices for the cp=2 mesh"
            return line

        from megatron_tpu.config import ModelConfig, ParallelConfig
        from megatron_tpu.inference.context_parallel import (
            ContextParallelEngine,
        )
        from megatron_tpu.inference.paging import PagedInferenceEngine
        from megatron_tpu.models.params import init_params, param_specs
        from megatron_tpu.parallel.mesh import build_mesh
        from megatron_tpu.parallel.sharding import shard_tree

        if cfg is None:
            cfg = ModelConfig(
                num_layers=4, hidden_size=128, num_attention_heads=8,
                num_kv_heads=4, ffn_hidden_size=256, vocab_size=1024,
                seq_length=256, params_dtype="float32").validate()
        params = init_params(cfg, jax.random.PRNGKey(0))
        rt = build_mesh(ParallelConfig(context_parallel=2),
                        devices=jax.devices()[:2])
        sparams = shard_tree(rt, params, param_specs(cfg))
        kw = dict(num_slots=2, max_seq_len=cfg.seq_length,
                  page_size=page_size, prefill_chunk=prefill_chunk,
                  want_logprobs=False)
        base = PagedInferenceEngine(cfg, params, **kw)
        cpe = ContextParallelEngine(cfg, sparams, mesh=rt.mesh, **kw)
        rng = np.random.default_rng(0)
        prompts = rng.integers(
            1, cfg.vocab_size, (1, prompt_len)).astype(np.int32)
        lengths = np.full((1,), prompt_len, np.int32)
        # warmup compiles chunk + decode steps on both engines, and the
        # greedy-parity gate rides on the warmup outputs
        a = base.generate(prompts, lengths, max_new_tokens=new_tokens)
        b = cpe.generate(prompts, lengths, max_new_tokens=new_tokens)
        tokens_match = bool((a.tokens == b.tokens).all())
        t_b, t_c = [], []
        for _ in range(reps):
            t0 = time.perf_counter()
            base.generate(prompts, lengths, max_new_tokens=new_tokens)
            t_b.append(max(time.perf_counter() - t0, 1e-9))
            t0 = time.perf_counter()
            cpe.generate(prompts, lengths, max_new_tokens=new_tokens)
            t_c.append(max(time.perf_counter() - t0, 1e-9))
        wall_b = sorted(t_b)[reps // 2]
        wall_c = sorted(t_c)[reps // 2]
        line["value"] = round(prompt_len / wall_c, 2)
        line["vs_baseline"] = round(wall_b / wall_c, 3)
        line["detail"] = {
            "prompt_len": prompt_len, "cp": cpe.cp,
            "prefill_chunk": prefill_chunk, "page_size": page_size,
            "greedy_tokens_match_single_host": tokens_match,
            "decode_recompiles_after_warmup": int(
                cpe.stats["decode_recompiles"]),
            "cp_ring_steps": int(cpe.stats["cp_ring_steps"]),
            "cp_ring_dense_bytes": int(cpe.stats["cp_comm_dense_bytes"]),
            "per_device_kv_fraction": round(1.0 / cpe.cp, 3),
            "single_host_wall_s": round(wall_b, 4),
            "cp_wall_s": round(wall_c, 4),
            "wall_note": ("CPU wall is informational: fake devices share "
                          "host cores; the chip-real win is 1/cp KV "
                          "bytes per device (capacity), byte-priced in "
                          "the decode_tp2_cp2/prefill_cp2 manifests"),
        }
        if not tokens_match:
            line["error"] = "greedy tokens diverged from single-host paged"
    except Exception as e:  # noqa: BLE001 - the metric line must emit
        line["error"] = str(e)[:300]
    return line


def serve_cp_overlap_bench(deadline, prompt_len=96, page_size=8,
                           prefill_chunk=32, new_tokens=6, cfg=None,
                           trace=True):
    """Comm-compute overlapped CP ring (ISSUE 20 tentpole): the same
    cp=2 engine with the serial hop schedule (permute -> merge -> permute)
    vs the overlapped one (hop l+1's collective-permute issued before hop
    l's merge, double-buffered carry). The deterministic gates are what
    CPU can prove: the committed decode_cp2_overlap golden's ppermute
    rows EQUAL the serial ring ledger's (decode_tp2_cp2) — the overlap
    moves zero extra hops/bytes — plus greedy parity vs the single-host
    paged engine for BOTH schedules, identical ring-step/byte counters,
    and zero decode recompiles. value/vs_baseline = serial/overlapped
    wall ratio (informational on CPU: fake devices share host cores);
    with trace=True both runs are captured under jax.profiler and the
    collective-permute EXPOSED fractions (telemetry/tracing/analyze.py)
    ride in detail — on a chip that delta IS the win."""
    line = {"metric": "serve_cp_overlap", "value": 0.0,
            "unit": "serial_over_overlapped_wall", "vs_baseline": 0.0}
    if deadline - time.perf_counter() < 30:
        line["error"] = "budget_exhausted"
        return line
    try:
        import shutil
        import tempfile

        import jax

        if len(jax.devices()) < 2:
            line["error"] = "needs >= 2 devices for the cp=2 mesh"
            return line

        from megatron_tpu.analysis import contracts
        from megatron_tpu.config import ModelConfig, ParallelConfig
        from megatron_tpu.inference.context_parallel import (
            ContextParallelEngine,
        )
        from megatron_tpu.inference.paging import PagedInferenceEngine
        from megatron_tpu.models.params import init_params, param_specs
        from megatron_tpu.parallel.mesh import build_mesh
        from megatron_tpu.parallel.sharding import shard_tree

        # gate 1 — the committed manifests: overlap must move EXACTLY
        # the serial ring's hops and bytes (the ledger keys op counts,
        # not order, so any extra/missing permute would show here)
        def _ppermute_rows(name):
            man = json.loads(contracts.manifest_path(name).read_text())
            return {k: (v["count"], v["total_wire_bytes"])
                    for k, v in man["jaxpr"]["collectives"].items()
                    if k.startswith("ppermute")}

        hops_match = (_ppermute_rows("decode_cp2_overlap")
                      == _ppermute_rows("decode_tp2_cp2"))

        if cfg is None:
            cfg = ModelConfig(
                num_layers=4, hidden_size=128, num_attention_heads=8,
                num_kv_heads=4, ffn_hidden_size=256, vocab_size=1024,
                seq_length=256, params_dtype="float32").validate()
        params = init_params(cfg, jax.random.PRNGKey(0))
        rt = build_mesh(ParallelConfig(context_parallel=2),
                        devices=jax.devices()[:2])
        sparams = shard_tree(rt, params, param_specs(cfg))
        kw = dict(num_slots=2, max_seq_len=cfg.seq_length,
                  page_size=page_size, prefill_chunk=prefill_chunk,
                  want_logprobs=False)
        base = PagedInferenceEngine(cfg, params, **kw)
        serial = ContextParallelEngine(cfg, sparams, mesh=rt.mesh,
                                       cp_overlap=False, **kw)
        over = ContextParallelEngine(cfg, sparams, mesh=rt.mesh,
                                     cp_overlap=True, **kw)
        rng = np.random.default_rng(0)
        prompts = rng.integers(
            1, cfg.vocab_size, (1, prompt_len)).astype(np.int32)
        lengths = np.full((1,), prompt_len, np.int32)
        # warmup compiles everything; gate 2 (greedy parity) rides on it
        ref = base.generate(prompts, lengths, max_new_tokens=new_tokens)
        t0 = time.perf_counter()
        out_s = serial.generate(prompts, lengths, max_new_tokens=new_tokens)
        warm_walls = {"serial": max(time.perf_counter() - t0, 1e-9)}
        t0 = time.perf_counter()
        out_o = over.generate(prompts, lengths, max_new_tokens=new_tokens)
        warm_walls["overlapped"] = max(time.perf_counter() - t0, 1e-9)
        parity = {
            "serial": bool((ref.tokens == out_s.tokens).all()),
            "overlapped": bool((ref.tokens == out_o.tokens).all()),
        }

        def _timed(eng, trace_dir=None):
            if trace_dir is not None:
                jax.profiler.start_trace(trace_dir)
            t0 = time.perf_counter()
            try:
                eng.generate(prompts, lengths, max_new_tokens=new_tokens)
            finally:
                wall = max(time.perf_counter() - t0, 1e-9)
                if trace_dir is not None:
                    jax.profiler.stop_trace()
            return wall

        def _exposed_frac(trace_dir):
            from megatron_tpu.telemetry.tracing import (
                analyze_events, classify_xspace, find_xplane_files,
                load_xspace,
            )

            events = []
            for f in find_xplane_files(trace_dir):
                events.extend(classify_xspace(load_xspace(f)))
            for c in analyze_events(events).collectives:
                if c.op == "collective-permute":
                    return round(c.exposed_frac, 4)
            return None

        exposed = {}
        walls = {}
        trace_error = None
        if trace:
            tmp = tempfile.mkdtemp(prefix="cp_overlap_trace_")
            try:
                for tag, eng in (("serial", serial), ("overlapped", over)):
                    d = os.path.join(tmp, tag)
                    try:
                        walls[tag] = _timed(eng, trace_dir=d)
                        exposed[tag] = _exposed_frac(d)
                    except Exception as e:  # noqa: BLE001 - the trace
                        # delta is informational; the gates must emit
                        walls.setdefault(tag, _timed(eng))
                        exposed[tag] = None
                        trace_error = str(e)[:200]
            finally:
                shutil.rmtree(tmp, ignore_errors=True)
        else:
            # gates-only mode (tier-1 rides here): the warmup walls stand
            # in for the A/B — compile-inclusive, so the ratio is even
            # more informational than the traced CPU one; the
            # deterministic gates below are the point
            walls = warm_walls

        ratio = walls["serial"] / walls["overlapped"]
        line["value"] = round(ratio, 3)
        line["vs_baseline"] = round(ratio, 3)
        steps_eq = (int(serial.stats["cp_ring_steps"])
                    == int(over.stats["cp_ring_steps"]))
        bytes_eq = (int(serial.stats["cp_comm_dense_bytes"])
                    == int(over.stats["cp_comm_dense_bytes"]))
        recompiles = (int(serial.stats["decode_recompiles"])
                      + int(over.stats["decode_recompiles"]))
        delta = None
        if exposed.get("serial") is not None \
                and exposed.get("overlapped") is not None:
            delta = round(exposed["serial"] - exposed["overlapped"], 4)
        line["detail"] = {
            "cp": over.cp, "prompt_len": prompt_len,
            "golden_hops_bytes_match_serial_ring": hops_match,
            "greedy_tokens_match_single_host": parity,
            "ring_steps_equal": steps_eq,
            "ring_bytes_equal": bytes_eq,
            "decode_recompiles_after_warmup": recompiles,
            "serial_wall_s": round(walls["serial"], 4),
            "overlapped_wall_s": round(walls["overlapped"], 4),
            "exposed_frac_serial": exposed.get("serial"),
            "exposed_frac_overlapped": exposed.get("overlapped"),
            "exposed_frac_delta": delta,
            "wall_note": ("CPU wall/exposure deltas are informational "
                          "(fake devices share host cores); the "
                          "deterministic gates — golden hop/byte match, "
                          "greedy parity, equal ring counters, zero "
                          "recompiles — hold everywhere"),
        }
        if trace_error:
            line["detail"]["trace_error"] = trace_error
        if not hops_match:
            line["error"] = ("overlapped ring ledger diverged from the "
                             "serial ring's ppermute rows")
        elif not (parity["serial"] and parity["overlapped"]):
            line["error"] = "greedy tokens diverged from single-host paged"
        elif not (steps_eq and bytes_eq):
            line["error"] = "ring step/byte counters diverged"
    except Exception as e:  # noqa: BLE001 - the metric line must emit
        line["error"] = str(e)[:300]
    return line


def async_loop_bench(deadline, stall_ms=20.0, iters=14, skip_gaps=2):
    """Async-goodput-loop micro-bench (ISSUE 5 acceptance; CPU-able): a
    tiny TrainLoop is fed an iterator with an injected stall_ms host stall
    per batch, synchronous loop vs async loop (prefetch + lagged metrics).
    Steady-state per-step wall comes from journal step-event timestamp
    gaps (the first `skip_gaps` gaps carry compile/pipeline-fill and are
    dropped). recovered_stall_frac = (sync - async) / injected stall; the
    two runs' final goodput splits ride along so the data_wait share drop
    is visible in the headline detail, and the measured data waits are
    attributed into the bench's own goodput ledger."""
    import shutil
    import tempfile

    from megatron_tpu.config import (
        ModelConfig, OptimizerConfig, RunConfig, TrainingConfig,
    )
    from megatron_tpu.telemetry.journal import read_events
    from megatron_tpu.training.pretrain import TrainLoop

    if deadline - time.perf_counter() < 60:
        return {"error": "budget_exhausted"}
    import jax

    # one row per data shard; on a multi-device mesh (the 8-fake-device
    # test conftest) shrink the geometry so the aggregate step stays in
    # the stall-dominated-if-unoverlapped regime instead of 8x the work
    n_dev = jax.device_count()
    gbs = n_dev
    h, seq, vocab = (256, 128, 512) if n_dev == 1 else (128, 64, 256)
    model = ModelConfig(
        num_layers=2, hidden_size=h, num_attention_heads=4, num_kv_heads=4,
        ffn_hidden_size=2 * h, vocab_size=vocab, seq_length=seq,
        params_dtype="float32").validate()
    rng = np.random.default_rng(0)
    proto = {
        "tokens": rng.integers(0, vocab, (gbs, seq)).astype(np.int64),
        "labels": rng.integers(0, vocab, (gbs, seq)).astype(np.int64),
        "loss_mask": np.ones((gbs, seq), np.float32),
    }

    def factory(consumed, gbs):
        def gen():
            while True:
                time.sleep(stall_ms / 1000.0)  # the injected host stall
                yield proto
        return gen()

    tmp = tempfile.mkdtemp(prefix="mtpu_async_bench_")
    cache = os.path.join(tmp, "cache")
    old_cache = jax.config.jax_compilation_cache_dir
    old_min_compile = jax.config.jax_persistent_cache_min_compile_time_secs

    def run(tag, async_on, train_iters):
        tele = os.path.join(tmp, tag)
        cfg = RunConfig(
            model=model,
            optimizer=OptimizerConfig(lr=1e-3, lr_decay_style="constant"),
            training=TrainingConfig(
                micro_batch_size=1, global_batch_size=gbs,
                train_iters=train_iters, log_interval=1 << 30,
                seed=0, async_loop=async_on, telemetry_dir=tele,
                compilation_cache_dir=cache))
        loop = TrainLoop(cfg, log=lambda m: None)
        loop.train(factory)
        evs, _ = read_events(os.path.join(tele, "events.jsonl"))
        steps = [e for e in evs if e["kind"] == "step"]
        final = [e for e in evs if e["kind"] == "goodput"][-1]
        gaps = [b["ts"] - a["ts"] for a, b in zip(steps, steps[1:])]
        gaps = gaps[skip_gaps:]
        waits = [e["data_wait_ms"] for e in steps[1 + skip_gaps:]]
        return {
            "steady_step_ms_mean": round(1e3 * sum(gaps) / max(len(gaps), 1),
                                         2),
            "steady_data_wait_ms_mean": round(
                sum(waits) / max(len(waits), 1), 3),
            "goodput": {k: final[k] for k in
                        ("goodput", "productive_s", "data_wait_s",
                         "compile_s", "wall_s")},
        }

    try:
        # throwaway warm-up run populates the shared compilation cache so
        # the two timed runs pay the same (near-zero) compile cost
        run("warm", True, 2)
        sync = run("sync", False, iters)
        asyn = run("async", True, iters)
        n_gaps = iters - 1 - skip_gaps
        # wall-gap recovery: noisy on a busy host (step-time variance rides
        # the numerator) but the end-to-end truth
        recovered = ((sync["steady_step_ms_mean"]
                      - asyn["steady_step_ms_mean"]) / stall_ms)
        # critical-path recovery: the stall still felt by the loop is
        # exactly the steady-state queue-pop wait — sleep-based, low-noise.
        # If the async loop were stall-bound (step < stall) pops would
        # block on the sleeping worker and this correctly reports < 1.
        recovered_wait = 1.0 - asyn["steady_data_wait_ms_mean"] / stall_ms
        if GOODPUT is not None:
            GOODPUT.attribute(
                "data_wait", sync["goodput"]["data_wait_s"]
                + asyn["goodput"]["data_wait_s"])
            GOODPUT.attribute(
                "productive", sync["goodput"]["productive_s"]
                + asyn["goodput"]["productive_s"])
        return {
            "stall_ms": stall_ms, "iters": iters, "steady_gaps": n_gaps,
            "recovered_stall_frac": round(recovered, 3),
            "recovered_wait_frac": round(recovered_wait, 3),
            "sync": sync, "async": asyn,
        }
    except Exception as e:  # noqa: BLE001 - extras must never kill the run
        return {"error": str(e)[:300]}
    finally:
        try:
            jax.config.update("jax_compilation_cache_dir", old_cache)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              old_min_compile)
            # restoring the CONFIG is not enough: the TrainLoops above
            # latched jax's cache module onto the tmp dir (deleted below),
            # and without a reset every later compile in this process
            # would consult/serialize against a dead path. reset_cache()
            # un-latches; the next compile re-initializes from the
            # restored config (the bench's own .jax_cache, or None).
            from jax.experimental.compilation_cache import (
                compilation_cache as _cc,
            )

            _cc.reset_cache()
        except Exception:  # noqa: BLE001
            pass
        shutil.rmtree(tmp, ignore_errors=True)


def preempt_save_bench(deadline, preempt_iter=4, train_iters=64):
    """SIGTERM -> committed-checkpoint wall time (CPU-able, pre-headline):
    a tiny TrainLoop is preempted at an exact step via the `preempt_at`
    fault (which self-delivers a real SIGTERM), takes the expedited
    synchronous-save path, and the journal's `preemption` event reports
    notice->commit latency — the preemption notice budget, tracked across
    PRs so checkpoint growth or save-path regressions show up as a number
    rather than as lost work on the next real preemption."""
    import shutil
    import tempfile

    from megatron_tpu.config import (
        ModelConfig, OptimizerConfig, RunConfig, TrainingConfig,
    )
    from megatron_tpu.telemetry.journal import read_events
    from megatron_tpu.training import checkpointing, resilience
    from megatron_tpu.training.pretrain import TrainLoop

    line = {"metric": "preempt_save_latency_ms", "value": 0.0,
            "unit": "ms_sigterm_to_committed_checkpoint",
            "vs_baseline": 0.0, "detail": {}}
    if deadline - time.perf_counter() < 45:
        line["error"] = "budget_exhausted"
        return line
    import jax

    n_dev = jax.device_count()
    gbs = n_dev
    h, seq, vocab = (256, 128, 512) if n_dev == 1 else (128, 64, 256)
    model = ModelConfig(
        num_layers=2, hidden_size=h, num_attention_heads=4, num_kv_heads=4,
        ffn_hidden_size=2 * h, vocab_size=vocab, seq_length=seq,
        params_dtype="float32").validate()
    rng = np.random.default_rng(0)
    proto = {
        "tokens": rng.integers(0, vocab, (gbs, seq)).astype(np.int64),
        "labels": rng.integers(0, vocab, (gbs, seq)).astype(np.int64),
        "loss_mask": np.ones((gbs, seq), np.float32),
    }

    def factory(consumed, gbs_):
        def gen():
            while True:
                yield proto
        return gen()

    tmp = tempfile.mkdtemp(prefix="mtpu_preempt_bench_")
    prev_fault = os.environ.get(resilience.FAULT_ENV)
    try:
        os.environ[resilience.FAULT_ENV] = f"preempt_at:{preempt_iter}"
        tele = os.path.join(tmp, "tele")
        save = os.path.join(tmp, "ckpt")
        cfg = RunConfig(
            model=model,
            optimizer=OptimizerConfig(lr=1e-3, lr_decay_style="constant"),
            training=TrainingConfig(
                micro_batch_size=1, global_batch_size=gbs,
                train_iters=train_iters, log_interval=1 << 30,
                seed=0, save=save, telemetry_dir=tele,
                preempt_save_timeout=120.0))
        loop = TrainLoop(cfg, log=lambda m: None)
        loop.train(factory)
        evs, _ = read_events(os.path.join(tele, "events.jsonl"))
        pre = [e for e in evs if e["kind"] == "preemption"]
        if not pre:
            line["error"] = "no preemption event journaled"
            return line
        if checkpointing.read_tracker(save) != preempt_iter:
            line["error"] = (f"tracker {checkpointing.read_tracker(save)} "
                             f"!= preempt iteration {preempt_iter}")
            return line
        line["value"] = float(pre[-1]["notice_to_commit_ms"])
        line["detail"] = {
            "save_latency_ms": pre[-1]["save_latency_ms"],
            "iteration": pre[-1]["iteration"],
            "n_params": sum(int(np.prod(x.shape))
                            for x in jax.tree.leaves(loop.state.params)),
            "async_save": True,
        }
    except Exception as e:  # noqa: BLE001 - pre-headline lines must never
        # kill the run (the headline MFU contract)
        line["error"] = str(e)[:300]
    finally:
        if prev_fault is None:
            os.environ.pop(resilience.FAULT_ENV, None)
        else:
            os.environ[resilience.FAULT_ENV] = prev_fault
        shutil.rmtree(tmp, ignore_errors=True)
    return line


def train_attention_bwd_bench(deadline, b=2, s=512, hq=4, hkv=2, d=64,
                              iters=3):
    """Custom-vjp flash gradient step vs the XLA-grad step (pre-headline,
    ISSUE 16). The deterministic gate — and the thing tracked across
    PRs — is that the GRADIENT jaxpr of attention(impl='pallas')
    contains the template's pallas kernels (the fused recompute
    backward) and that --no_flash_bwd's doesn't: `value` is the wall
    speedup of the flash grad step over the dense one and is
    informational only (on a CPU host the kernels run under the pallas
    interpreter, so wall there measures the interpreter, not the
    kernels — the gate is what must hold)."""
    import warnings

    line = {"metric": "train_attention_bwd_speedup", "value": 0.0,
            "unit": "x_wall_vs_xla_grad", "vs_baseline": 0.0, "detail": {}}
    if deadline - time.perf_counter() < 30:
        line["error"] = "budget_exhausted"
        return line
    import unittest.mock

    import jax
    import jax.numpy as jnp

    from megatron_tpu.ops.attention import attention

    try:
        on_cpu = jax.default_backend() == "cpu"
        env = {"MEGATRON_TPU_FLASH_INTERPRET": "1"} if on_cpu else {}
        rng = np.random.default_rng(3)
        q, k, v = (jnp.asarray(rng.standard_normal((b, s, h, d)),
                               jnp.float32)
                   for h in (hq, hkv, hkv))

        def loss_flash(q, k, v):
            return jnp.sum(jnp.square(attention(q, k, v, impl="pallas")))

        def loss_dense(q, k, v):
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")  # the deliberate loud path
                return jnp.sum(jnp.square(
                    attention(q, k, v, impl="pallas", flash_bwd=False)))

        def wall(f):
            g = jax.jit(jax.grad(f, argnums=(0, 1, 2)))
            jax.block_until_ready(g(q, k, v))  # compile
            t0 = time.perf_counter()
            for _ in range(iters):
                out = g(q, k, v)
            jax.block_until_ready(out)
            return (time.perf_counter() - t0) / iters

        with unittest.mock.patch.dict(os.environ, env):
            jx_flash = str(jax.make_jaxpr(
                jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v))
            jx_dense = str(jax.make_jaxpr(
                jax.grad(loss_dense, argnums=(0, 1, 2)))(q, k, v))
            gate = ("pallas_call" in jx_flash
                    and "pallas_call" not in jx_dense)
            t_flash = wall(loss_flash)
            t_dense = wall(loss_dense)

        line["value"] = round(t_dense / max(t_flash, 1e-9), 3)
        line["detail"] = {
            "bwd_jaxpr_has_kernel": "pallas_call" in jx_flash,
            "dense_jaxpr_kernel_free": "pallas_call" not in jx_dense,
            "kernel_calls_in_grad": jx_flash.count("pallas_call"),
            "flash_grad_ms": round(t_flash * 1e3, 2),
            "xla_grad_ms": round(t_dense * 1e3, 2),
            "interpret_mode": on_cpu,
            "geometry": {"b": b, "s": s, "hq": hq, "hkv": hkv, "d": d},
        }
        if not gate:
            line["error"] = ("flash bwd gate failed: gradient jaxpr "
                             "missing the pallas kernels (or the dense "
                             "escape hatch still contains them)")
    except Exception as e:  # noqa: BLE001 - pre-headline lines must never
        # kill the run (the headline MFU contract)
        line["error"] = str(e)[:300]
    return line


def moe_dispatch_bench(deadline, peak):
    """Iso-parameter 4-expert/top-2 MoE at the headline geometry, capacity
    vs dropless dispatch MFU (useful-FLOP accounting like
    tools/bench_sweep.py --experts). Round-3 capacity dispatch measured
    0.239 MFU single-chip (builder-measured); the dropless ragged_dot path
    is the designed fix — this records both so the gain is driver-capturable."""
    import dataclasses

    cfg = headline_config()
    moe = dataclasses.replace(
        cfg, num_experts=4, moe_top_k=2,
        ffn_hidden_size=cfg.ffn_size // 4).validate()
    out = {}
    for mode in ("capacity", "dropless"):
        if deadline - time.perf_counter() < 60:
            out[mode] = {"error": "budget_exhausted"}
            continue
        mcfg = dataclasses.replace(moe, moe_dispatch=mode).validate()
        try:
            dt, loss = _measure(mcfg, 4, "selective", 0, iters=3)
        except Exception as e:  # noqa: BLE001
            out[mode] = {"error": str(e)[:200]}
            continue
        tps = 4 * mcfg.seq_length / dt
        # useful FLOPs: top_k of E experts active per token
        mfu = tps * 3.0 * mcfg.flops_per_token_fwd() / peak
        out[mode] = {"mfu": round(mfu, 4),
                     "tokens_per_sec_per_chip": round(tps),
                     "step_ms": round(dt * 1e3, 2)}
    return out


def run_extras(deadline, peak, extras):
    """Fill `extras` in place (SIGTERM handler reads it concurrently)."""
    extras["largest_trainable"] = largest_trainable_bench(deadline, peak)
    # the async-loop point early: it is cheap (tiny model, warm cache) and
    # is the round's record of the data-stall recovery the loop buys
    extras["async_loop"] = async_loop_bench(deadline)
    # MoE before the serving pair: on a tight window the two 7B serving
    # runs must not starve the capacity-vs-dropless comparison
    extras["moe_dispatch"] = moe_dispatch_bench(deadline, peak)
    extras["serving_int8_7b"] = serving_int8_7b_bench(deadline)
    extras["serving_fp8_7b"] = serving_int8_7b_bench(deadline, mode="fp8")


def emit_error(error, detail=None):
    """The never-null contract: any failure mode still yields one parseable
    line with the standard envelope (VERDICT r2 next-round #1)."""
    print(json.dumps({
        "metric": "llama_train_step_mfu",
        "value": 0.0,
        "unit": "fraction_of_peak_bf16",
        "vs_baseline": 0.0,
        "error": error,
        "detail": detail or {},
    }), flush=True)


def main():
    import signal

    budget_s = float(os.environ.get("MEGATRON_TPU_BENCH_BUDGET_S", "420"))
    t_start = time.perf_counter()
    deadline = t_start + budget_s

    # SIGTERM during the probe phase or backend init (both can consume the
    # whole budget on a wedged tunnel) must still produce the JSON line
    def on_term_early(signum, frame):
        emit_error("tpu_unavailable",
                   {"note": "SIGTERM during backend probe/init",
                    "budget_s": budget_s})
        sys.exit(0)

    signal.signal(signal.SIGTERM, on_term_early)

    # When the env intends CPU (tests / explicit override), backend init is
    # local and cannot hang — skip the subprocess probe entirely.
    on_cpu = (os.environ.get("JAX_PLATFORMS", "").startswith("cpu")
              or os.environ.get("MEGATRON_TPU_FORCE_PLATFORM") == "cpu")
    if not on_cpu:
        ok, probe_log = wait_for_backend(deadline)
        if not ok:
            emit_error("tpu_unavailable",
                       {"probe_attempts": len(probe_log),
                        "probe_log": probe_log[-5:], "budget_s": budget_s})
            return

    import jax

    global GOODPUT
    from megatron_tpu.telemetry import GoodputTracker, recompile_tracker

    GOODPUT = GoodputTracker()
    _compiles0 = recompile_tracker().snapshot()

    # Persistent compilation cache: a retry after a tunnel flap (or the
    # driver's end-of-round run) skips the multi-minute compile, so a short
    # tunnel window suffices for a number (VERDICT r3 next-round #1).
    # MEGATRON_TPU_JAX_CACHE="" (empty) disables — the hermetic test runs
    # use it: enabling the cache latches the whole pytest PROCESS onto it,
    # and same-process write-then-deserialize-execute crashes this
    # jax/XLA:CPU (tests/conftest.py note).
    cache_dir = os.environ.get(
        "MEGATRON_TPU_JAX_CACHE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache"))
    if cache_dir:
        try:
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              1.0)
        except Exception as e:  # noqa: BLE001 - cache is best-effort
            print(f"# compilation cache unavailable: {e}", file=sys.stderr)

    if os.environ.get("MEGATRON_TPU_BENCH_SERVING_ONLY"):
        # local recipe (docs/serving.md): just the serving metrics, skip
        # the multi-minute training-step search. Never set by the driver.
        print(json.dumps(serving_engine_bench(deadline)), flush=True)
        print(json.dumps(serve_prefix_cache_bench(deadline)), flush=True)
        print(json.dumps(serve_speculative_bench(deadline)), flush=True)
        print(json.dumps(serve_compressed_comm_bench(deadline)), flush=True)
        print(json.dumps(serve_longctx_prefill_bench(deadline)), flush=True)
        print(json.dumps(serve_cp_overlap_bench(deadline)), flush=True)
        print(json.dumps(serve_slo_bench(deadline)), flush=True)
        return

    from megatron_tpu.models.params import num_params
    from megatron_tpu.platform import peak_bf16_flops

    cfg = headline_config()
    n_params = num_params(cfg)
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", str(dev)).lower()
    peak = peak_bf16_flops(dev)
    flops_per_token = 3.0 * cfg.flops_per_token_fwd()  # fwd + bwd(2x)

    quick = bool(os.environ.get("MEGATRON_TPU_BENCH_QUICK"))
    candidates = CANDIDATES[:1] if quick else CANDIDATES
    extras_mode = os.environ.get("MEGATRON_TPU_BENCH_EXTRAS", "auto")
    want_extras = (extras_mode == "1"
                   or (extras_mode == "auto" and dev.platform == "tpu"))
    # the candidate search stops opening new points past this, leaving the
    # rest of the *remaining* budget (probe time already spent) for the
    # 7B-class extras
    now = time.perf_counter()
    search_deadline = (now + 0.55 * (deadline - now)
                       if want_extras else deadline)

    best = None        # (mfu, cand, dt, loss)
    sweep = []
    extras = {}

    def emit_best():
        """Print the one-line JSON for the best point found so far, and
        drop a copy into bench_evidence/ so every successful run leaves a
        committed artifact (claims and evidence cannot drift —
        VERDICT r3 next-round #9)."""
        mfu, cand, dt, loss_val = best
        tokens_per_sec = cand["micro_bs"] * cfg.seq_length / dt
        detail = {
            "tokens_per_sec_per_chip": round(tokens_per_sec),
            "step_ms": round(dt * 1e3, 2),
            "n_params": n_params,
            "loss": loss_val,
            "device": str(dev),
            "device_kind": kind,
            "peak_flops_assumed": peak,
            "micro_bs": cand["micro_bs"],
            "recompute": cand["granularity"],
            "ce_chunk": cand["ce_chunk"],
            "attention": "pallas(flash_template)",
            "sweep": sweep,
        }
        detail.update(extras)
        cdelta = recompile_tracker().delta(_compiles0)
        GOODPUT.attribute("compile", cdelta["compile_seconds"]
                          + cdelta["trace_seconds"])
        detail["goodput"] = dict(GOODPUT.report(),
                                 compiles=int(cdelta["compiles"]))
        line = {
            "metric": "llama_train_step_mfu",
            "value": round(mfu, 4),
            "unit": "fraction_of_peak_bf16",
            "vs_baseline": round(mfu / BASELINE_MFU, 3),
            "detail": detail,
        }
        print(json.dumps(line), flush=True)
        if dev.platform != "tpu":
            # CPU sanity/test runs must not masquerade as TPU evidence
            return
        try:
            import datetime

            ev_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                  "bench_evidence")
            os.makedirs(ev_dir, exist_ok=True)
            line["ts"] = datetime.datetime.now(
                datetime.timezone.utc).isoformat(timespec="seconds")
            with open(os.path.join(ev_dir, "last_success.json"), "w") as f:
                json.dump(line, f, indent=1)
        except Exception as e:  # noqa: BLE001 - evidence is best-effort
            print(f"# evidence bundle write failed: {e}", file=sys.stderr)

    # if the driver times the process out mid-search, flush the best
    # measured point instead of losing the round's number entirely
    def on_term(signum, frame):
        if best is not None:
            emit_best()
        else:
            emit_error("tpu_unavailable",
                       {"note": "SIGTERM before any point measured",
                        "budget_s": budget_s})
        sys.exit(0)

    signal.signal(signal.SIGTERM, on_term)

    for cand in candidates:
        if best is not None and time.perf_counter() > search_deadline:
            print("# bench search budget reached, stopping", file=sys.stderr)
            break
        try:
            dt, loss = _measure(cfg, **cand)
        except Exception as e:
            if not is_oom(e):
                if best is not None:
                    # a tunnel flap mid-search must not discard the round's
                    # already-measured number
                    sweep.append({**cand, "error": str(e)[:200]})
                    print(f"# {cand} failed non-OOM, keeping best: {e}",
                          file=sys.stderr)
                    break
                raise
            sweep.append({**cand, "oom": True})
            print(f"# {cand} OOM", file=sys.stderr)
            continue
        tps = cand["micro_bs"] * cfg.seq_length / dt
        mfu = tps * flops_per_token / peak
        sweep.append({**cand, "mfu": round(mfu, 4),
                      "step_ms": round(dt * 1e3, 2)})
        print(f"# {cand} mfu={mfu:.4f}", file=sys.stderr)
        if best is None or mfu > best[0]:
            best = (mfu, cand, dt, loss)
    if best is None:
        raise RuntimeError("every bench operating point OOMed")

    # from here on `best` exists: nothing post-search (extras, profiler) may
    # cost the round its number
    try:
        if not quick:
            # serving metrics ride as their own JSON lines BEFORE the
            # headline (and before any extras lines — the only positional
            # contract is that the headline MFU line comes LAST for the
            # driver; consumers of serving metrics must match on "metric")
            print(json.dumps(serving_engine_bench(deadline)), flush=True)
            print(json.dumps(serve_prefix_cache_bench(deadline)),
                  flush=True)
            print(json.dumps(serve_speculative_bench(deadline)),
                  flush=True)
            print(json.dumps(serve_compressed_comm_bench(deadline)),
                  flush=True)
            print(json.dumps(serve_longctx_prefill_bench(deadline)),
                  flush=True)
            # overlapped-ring CP gate: golden hop/byte match + greedy
            # parity (exposed-fraction trace delta rides in detail)
            print(json.dumps(serve_cp_overlap_bench(deadline)),
                  flush=True)
            print(json.dumps(serve_slo_bench(deadline)), flush=True)
            # preemption notice budget: SIGTERM -> committed checkpoint
            print(json.dumps(preempt_save_bench(deadline)), flush=True)
            # flash bwd gate: the gradient jaxpr must contain the
            # template's kernels (wall speedup informational)
            print(json.dumps(train_attention_bwd_bench(deadline)),
                  flush=True)
        if want_extras:
            run_extras(deadline, peak, extras)

        mfu, cand, dt, loss_val = best
        profile_dir = os.environ.get("MEGATRON_TPU_PROFILE_DIR")
        if profile_dir:
            # re-run the winner under the profiler (trace excludes compile)
            state, step, batch = build_step(_cfg_for(cfg, cand["ce_chunk"]),
                                            cand["micro_bs"],
                                            cand["granularity"])
            _, _, state = time_step(state, step, batch, iters=1)
            jax.profiler.start_trace(profile_dir)
            try:
                time_step(state, step, batch, iters=3)
            finally:
                jax.profiler.stop_trace()
            try:
                # attach the comm/compute/exposed split to the headline
                # detail: when a chip window finally appears, the round's
                # record carries the Flash-Communication numbers, not
                # just MFU (megatron_tpu/telemetry/tracing/)
                from megatron_tpu.telemetry.tracing import (
                    analyze_events, classify_xspace, find_xplane_files,
                    load_xspace,
                )

                trace_events = []
                for f in find_xplane_files(profile_dir):
                    trace_events.extend(
                        classify_xspace(load_xspace(f)))
                rep = analyze_events(trace_events).to_dict(top=0)
                extras["trace_split"] = {
                    k: rep[k] for k in ("module", "busy_s",
                                        "exposed_collective_s",
                                        "collectives")}
            except Exception as e:  # noqa: BLE001 - the trace stays on
                # disk either way; a decode hiccup must not cost the
                # round its headline
                extras["trace_split_error"] = str(e)[:200]
    except Exception as e:  # noqa: BLE001
        extras["post_search_error"] = str(e)[:300]
        print(f"# post-search work failed, keeping best: {e}", file=sys.stderr)

    signal.signal(signal.SIGTERM, signal.SIG_DFL)
    emit_best()


def run():
    """__main__ wrapper enforcing the never-null contract even on
    unexpected exceptions (rc stays 0, the line stays parseable)."""
    try:
        main()
    except SystemExit:
        raise
    except Exception as e:  # noqa: BLE001 - contract: always emit JSON
        import traceback

        traceback.print_exc()
        emit_error(f"{type(e).__name__}: {e}"[:300])


if __name__ == "__main__":
    run()

"""The one flash kernel family (ops/pallas/flash_template.py) vs dense
references, in interpret mode on the CPU suite.

Three layers of proof:

  1. masks.py predicate unit tests — every block-skip predicate proven
     against a dense boolean reference (ANY of `visible` over the tile),
     exhaustively over the edges: the causal frontier, the decode
     ``kv_len + Sq - 1`` mq boundary, and the window LOWER edge (the new
     windowed block skip).
  2. parity matrix — each template instantiation (prefill fwd, the
     custom-vjp bwd, decode, paged decode, both mq variants) vs the
     dense einsum path over causal x kv_lengths x window x paged x mq;
     bwd grads vs jax.grad of the dense reference.
  3. dispatch gates — attention(impl="pallas") routes the gradient
     through the template (jaxpr contains the pallas call) when
     flash_bwd is on, and falls back LOUDLY (warning) when it can't or
     when --no_flash_bwd asks it not to.

The same kernels compile for real on TPU (bench.py headline path)."""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_tpu.ops.attention import attention
from megatron_tpu.ops.pallas import masks

RNG = np.random.default_rng(11)


# ---------------------------------------------------------------------------
# 1. mask predicates vs the dense boolean reference
# ---------------------------------------------------------------------------


def _dense_block_live(ki, blk, q_positions, causal, window):
    """Reference: the tile is live iff ANY (q, k) element in it is
    visible — computed from the element rule, no interval shortcuts."""
    k_positions = np.arange(ki * blk, (ki + 1) * blk)
    vis = masks.visible(q_positions[:, None], k_positions[None, :],
                        causal=causal, window=window)
    return bool(np.any(vis))


@pytest.mark.parametrize("window", [None, 1, 3, 8, 64])
@pytest.mark.parametrize("causal", [True, False])
def test_prefill_block_live_matches_dense(causal, window):
    blk_q, blk_k = 8, 8
    for delta in (0, 5, 64):
        for qi in range(6):
            q_pos = np.arange(qi * blk_q, (qi + 1) * blk_q) + delta
            for ki in range(8):
                want = _dense_block_live(ki, blk_k, q_pos, causal, window)
                got = masks.prefill_block_live(
                    qi, ki, blk_q, blk_k, causal=causal, window=window,
                    delta=delta)
                assert bool(got) == want, (qi, ki, delta)


@pytest.mark.parametrize("window", [None, 1, 4, 16])
@pytest.mark.parametrize("sq", [1, 4])
def test_decode_block_live_matches_dense(sq, window):
    """Including the mq boundary: the deepest query sits at
    kv_len + sq - 2, so the last live causal block is the one containing
    it — checked for every kv_len around every block edge."""
    blk = 8
    nk = 6
    for kv_len in range(1, blk * nk + 1):
        q_pos = kv_len - 1 + np.arange(sq)
        for ki in range(nk):
            want = _dense_block_live(ki, blk, q_pos, True, window)
            got = masks.decode_block_live(ki, blk, kv_len, sq, window=window)
            assert bool(got) == want, (kv_len, ki)


def test_window_lower_edge_is_tight():
    """The windowed skip keeps exactly the tiles intersecting
    (q_lo - W, q_hi]: the tile just below the window's lower edge is
    dead, the one containing the edge is live."""
    blk = 8
    # queries at [32, 39]; W=4: the shallowest query sees (28, 32], so
    # tile 3 (cols 24..31) is live only through its top columns 29..31
    assert masks.block_live(3, blk, 32, 39, window=4)
    assert not masks.block_live(2, blk, 32, 39, window=4)   # cols 16..23
    # W=1: the band is (31, 39] — tile 3's last column (31) is exactly
    # NOT in it, tile 4 is
    assert not masks.block_live(3, blk, 32, 39, window=1)
    assert masks.block_live(3, blk, 32, 39, window=2)       # 31 > 30
    assert masks.block_live(4, blk, 32, 39, window=1)
    assert not masks.block_live(5, blk, 32, 39, window=None)  # causal edge
    assert masks.block_live(5, blk, 32, 47, window=None)


def test_decode_positions_are_the_causal_rule():
    """The historical decode mask k_pos < kv_len + q_idx IS `visible`
    at q_pos = kv_len - 1 + q_idx — the unification the template rests
    on."""
    kv_len, groups, sq, blk = 13, 2, 3, 8
    rows = sq * groups
    q_pos, k_pos = masks.decode_positions(1, blk, kv_len, groups, rows)
    got = masks.visible(q_pos, k_pos, causal=True)
    q_idx = np.arange(rows)[:, None] // groups
    legacy = (np.arange(blk)[None, :] + blk) < kv_len + q_idx
    np.testing.assert_array_equal(np.asarray(got), legacy)


# ---------------------------------------------------------------------------
# 2. parity matrix (interpret mode)
# ---------------------------------------------------------------------------


def _qkv(b=1, s=128, hq=4, hkv=2, d=32, skv=None):
    skv = s if skv is None else skv
    q = jnp.asarray(RNG.standard_normal((b, s, hq, d)), jnp.float32)
    k = jnp.asarray(RNG.standard_normal((b, skv, hkv, d)), jnp.float32)
    v = jnp.asarray(RNG.standard_normal((b, skv, hkv, d)), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("window", [None, 48])
@pytest.mark.parametrize("causal", [True, False])
def test_template_forward_parity(causal, window):
    from megatron_tpu.ops.pallas.flash_template import flash_mha

    q, k, v = _qkv()
    got = flash_mha(q, k, v, sliding_window=window, causal=causal,
                    block_q=64, block_k=64)
    want = attention(q, k, v, sliding_window=window,
                     mask_type="causal" if causal else "bidirectional")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("window", [None, 48])
@pytest.mark.parametrize("hq,hkv", [(2, 2), (4, 2)])
def test_template_bwd_grads_vs_dense_jax_grad(hq, hkv, window):
    """The recompute backward (dq + dk/dv kernels behind custom_vjp) vs
    jax.grad of the dense einsum, causal x window x GQA."""
    from megatron_tpu.ops.pallas.flash_template import flash_mha

    q, k, v = _qkv(hq=hq, hkv=hkv)

    def f_flash(q, k, v):
        return jnp.sum(jnp.square(flash_mha(q, k, v, sliding_window=window,
                                            block_q=64, block_k=64)))

    def f_ref(q, k, v):
        return jnp.sum(jnp.square(attention(q, k, v, sliding_window=window)))

    gf = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gf, gr):
        scale = float(jnp.max(jnp.abs(b)))
        np.testing.assert_allclose(np.asarray(a) / scale,
                                   np.asarray(b) / scale,
                                   rtol=2e-2, atol=2e-3, err_msg=f"d{name}")


@pytest.mark.parametrize("window", [None, 40])
@pytest.mark.parametrize("sq", [1, 3])
def test_decode_window_parity(sq, window):
    """Decode instantiations (sq=1 plain, sq>1 speculative mq) with the
    sliding-window knob vs the masked einsum."""
    from megatron_tpu.ops.pallas.flash_decode import (flash_decode,
                                                      flash_decode_mq)

    q, k, v = _qkv(b=3, s=sq, skv=256, hq=4, hkv=2, d=32)
    lens = jnp.asarray([1, 100, 256 - sq + 1], jnp.int32)
    fn = flash_decode if sq == 1 else flash_decode_mq
    got = fn(q, k, v, lens, sliding_window=window, block_k=128)
    want = attention(q, k, v, kv_lengths=lens, sliding_window=window,
                     impl="xla")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-3)


def _paged(k, v, ps):
    """Chop a dense [B, S, hkv, d] cache into a shared page pool with
    page 0 reserved as scratch; returns (k_pages, v_pages, table)."""
    b, s, hkv, d = k.shape
    npages = s // ps
    kp = [jnp.zeros((ps, hkv, d), k.dtype)]
    vp = [jnp.zeros((ps, hkv, d), v.dtype)]
    table = np.zeros((b, npages), np.int32)
    for bi in range(b):
        for p in range(npages):
            table[bi, p] = len(kp)
            kp.append(k[bi, p * ps:(p + 1) * ps])
            vp.append(v[bi, p * ps:(p + 1) * ps])
    return jnp.stack(kp), jnp.stack(vp), jnp.asarray(table)


@pytest.mark.parametrize("window", [None, 40])
@pytest.mark.parametrize("sq", [1, 3])
def test_paged_decode_window_parity(sq, window):
    """The paged knob: same body, page-table index maps — vs the dense
    gather reference, including sliding window."""
    from megatron_tpu.ops.pallas.paged_flash_decode import (
        paged_flash_decode, paged_flash_decode_mq)

    ps = 64
    q, k, v = _qkv(b=3, s=sq, skv=256, hq=4, hkv=2, d=32)
    kp, vp, table = _paged(k, v, ps)
    lens = jnp.asarray([1, 100, 256 - sq + 1], jnp.int32)
    fn = paged_flash_decode if sq == 1 else paged_flash_decode_mq
    got = fn(q, kp, vp, table, lens, sliding_window=window)
    want = attention(q, k, v, kv_lengths=lens, sliding_window=window,
                     impl="xla")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-3)


# ---------------------------------------------------------------------------
# 3. dispatch gates
# ---------------------------------------------------------------------------


def test_dispatch_uses_template_bwd_when_forced(monkeypatch):
    """With interpret forced, attention(impl='pallas') routes through the
    template and the GRADIENT jaxpr contains the pallas kernels — the
    deterministic form of the bench gate (no XLA-generated O(S^2)
    attention gradient)."""
    monkeypatch.setenv("MEGATRON_TPU_FLASH_INTERPRET", "1")
    q, k, v = _qkv()

    def loss(q, k, v):
        return jnp.sum(attention(q, k, v, impl="pallas"))

    jaxpr = str(jax.make_jaxpr(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v))
    assert "pallas_call" in jaxpr
    out = attention(q, k, v, impl="pallas")
    want = attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-3, atol=2e-3)


def test_dispatch_no_flash_bwd_is_loud_and_dense(monkeypatch):
    """--no_flash_bwd: same numbers, NO pallas call in the jaxpr, and a
    warning so the dense gradient can't sneak in silently."""
    monkeypatch.setenv("MEGATRON_TPU_FLASH_INTERPRET", "1")
    q, k, v = _qkv()
    with pytest.warns(UserWarning, match="flash_bwd disabled"):
        out = attention(q, k, v, impl="pallas", flash_bwd=False)
    want = attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-3, atol=2e-3)

    def loss(q, k, v):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            return jnp.sum(attention(q, k, v, impl="pallas",
                                     flash_bwd=False))

    jaxpr = str(jax.make_jaxpr(jax.grad(loss))(q, k, v))
    assert "pallas_call" not in jaxpr


def test_dispatch_geometry_fallback_is_loud(monkeypatch):
    """A geometry the template can't instantiate (seq longer than the
    default block but not divisible by it) falls back to XLA with a
    warning naming the gradient."""
    monkeypatch.setenv("MEGATRON_TPU_FLASH_INTERPRET", "1")
    q, k, v = _qkv(s=300, hq=2, hkv=1, d=16)
    with pytest.warns(UserWarning, match="O\\(S\\^2\\)"):
        out = attention(q, k, v, impl="pallas")
    want = attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-3, atol=2e-3)


def test_dispatch_stays_dense_on_cpu_without_forcing(monkeypatch):
    """CPU sanity runs must not pay the pallas interpreter: without the
    env var, impl='pallas' runs the fused XLA path."""
    monkeypatch.delenv("MEGATRON_TPU_FLASH_INTERPRET", raising=False)
    q, k, v = _qkv()

    def loss(q, k, v):
        return jnp.sum(attention(q, k, v, impl="pallas"))

    jaxpr = str(jax.make_jaxpr(loss)(q, k, v))
    assert "pallas_call" not in jaxpr

"""Static-analysis subsystem: AST linter, jaxpr auditor, comm contracts.

Tier-1 gates added by this suite:
  * the linter is CLEAN over megatron_tpu/ (every violation fixed or
    allowlisted with a reason) and each rule provably fires on seeded
    violations;
  * the train step and engine decode step trace with ZERO host
    callbacks and full donation of their mutable state;
  * the golden comm contracts hold at jaxpr level for every config (an
    injected hidden collective fails the check, proven here too).
"""

import json
import subprocess
import sys
import textwrap
from pathlib import Path

import jax
import jax.numpy as jnp
import pytest

from megatron_tpu.analysis import ast_lint, contracts, jaxpr_audit, targets

REPO = Path(__file__).resolve().parent.parent
PKG = REPO / "megatron_tpu"


# ---------------------------------------------------------------------------
# AST linter
# ---------------------------------------------------------------------------


def test_lint_repo_clean():
    """The acceptance gate: megatron_tpu/ lints clean at HEAD."""
    findings = ast_lint.lint_paths([str(PKG)])
    assert findings == [], "\n".join(map(str, findings))


_SEEDED = textwrap.dedent("""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from functools import partial
    from jax.experimental.shard_map import shard_map as smap

    @partial(jax.jit, donate_argnums=(0,))
    def step(state, batch):
        loss = jnp.sum(state - batch)
        print("loss", loss)
        host = np.asarray(state)
        if jnp.sum(loss) > 0:
            loss = loss * 2.0
        return loss + float(batch)

    def exchange(x):
        return jax.lax.ragged_all_to_all(x, x, x, x, x, x, axis_name="ep")

    def risky():
        try:
            return jax.device_count()
        except Exception:
            return 0
""")


def test_lint_rules_fire(tmp_path):
    f = tmp_path / "seeded.py"
    f.write_text(_SEEDED)
    findings = ast_lint.lint_paths([str(f)])
    rules = {x.rule for x in findings}
    assert {"host-sync", "banned-api", "broad-except",
            "traced-branch"} <= rules, findings
    msgs = "\n".join(map(str, findings))
    assert "print()" in msgs
    assert "np.asarray" in msgs
    assert "float(batch)" in msgs
    assert "ragged_all_to_all" in msgs
    assert "jax.experimental.shard_map" in msgs


def test_lint_traced_detection_via_call_chain(tmp_path):
    """A helper called from a shard_map body is traced transitively."""
    f = tmp_path / "chained.py"
    f.write_text(textwrap.dedent("""
        import jax

        def helper(x):
            return x.item()

        def body(x):
            return helper(x)

        fn = jax.shard_map(body, mesh=None, in_specs=(), out_specs=())
    """))
    findings = ast_lint.lint_paths([str(f)])
    assert any(x.rule == "host-sync" and ".item()" in x.message
               for x in findings), findings


def test_lint_allowlist_requires_reason(tmp_path):
    good = tmp_path / "good.py"
    good.write_text(textwrap.dedent("""
        try:
            pass
        except Exception:  # noqa: BLE001 - degraded mode is intended here
            pass
    """))
    assert ast_lint.lint_paths([str(good)]) == []

    bare = tmp_path / "bare.py"
    bare.write_text(textwrap.dedent("""
        try:
            pass
        except Exception:  # jaxlint: disable=broad-except
            pass
    """))
    findings = ast_lint.lint_paths([str(bare)])
    # the reasonless disable both fails to suppress and is itself flagged
    assert any("without a reason" in x.message for x in findings), findings
    assert any("swallows everything" in x.message for x in findings)


def test_lint_multiline_disable_comment(tmp_path):
    f = tmp_path / "multi.py"
    f.write_text(textwrap.dedent("""
        try:
            pass
        # jaxlint: disable=broad-except - reason spanning a comment
        # block right above the handler
        except Exception:
            pass
    """))
    assert ast_lint.lint_paths([str(f)]) == []


def test_lint_static_idioms_not_flagged(tmp_path):
    """`x is None` guards and static-config branches stay legal in
    traced code (the pipeline/attention idioms)."""
    f = tmp_path / "idioms.py"
    f.write_text(textwrap.dedent("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def fn(x: jnp.ndarray, key=None, mode: str = "causal"):
            if key is not None and x is not None:
                x = x + 1
            if mode == "causal":
                x = x * 2
            return x
    """))
    assert ast_lint.lint_paths([str(f)]) == []


def test_jaxlint_cli(tmp_path):
    """Acceptance: non-zero on a seeded violation, zero on the repo."""
    f = tmp_path / "seeded.py"
    f.write_text(_SEEDED)
    cli = str(REPO / "tools" / "jaxlint.py")
    bad = subprocess.run([sys.executable, cli, str(tmp_path)],
                         capture_output=True, text=True)
    assert bad.returncode == 1, bad.stdout + bad.stderr
    assert "host-sync" in bad.stdout
    clean = subprocess.run([sys.executable, cli],
                           capture_output=True, text=True)
    assert clean.returncode == 0, clean.stdout + clean.stderr


# ---------------------------------------------------------------------------
# jaxpr auditor: detectors provably fire
# ---------------------------------------------------------------------------


def _ctx_mesh():
    from megatron_tpu.config import ParallelConfig
    from megatron_tpu.parallel.mesh import build_mesh

    return build_mesh(ParallelConfig(context_parallel=2)).mesh


def test_auditor_counts_scan_collectives():
    mesh = _ctx_mesh()
    from jax.sharding import PartitionSpec as P

    def body(x):
        def tick(c, _):
            return jax.lax.ppermute(c, "context", [(0, 1), (1, 0)]), None

        out, _ = jax.lax.scan(tick, x, None, length=3)
        return out

    fn = jax.shard_map(body, mesh=mesh, in_specs=(P("context"),),
                      out_specs=P("context"), check_vma=False)
    x = jax.ShapeDtypeStruct((4, 8), jnp.float32)
    rep = jaxpr_audit.audit_jaxpr(jax.make_jaxpr(fn)(x))
    [c] = rep.collectives
    assert c.primitive == "ppermute" and c.calls == 3
    assert c.axes == ("context",)
    assert c.bytes_per_call == 2 * 8 * 4  # local shard [2, 8] f32


def test_auditor_flags_rank0_scan_carry():
    """The jax 0.4.37 hazard: rank-0 inexact scan carries inside
    shard_map bodies (training/pipeline.py keeps them [1]-shaped)."""
    mesh = _ctx_mesh()
    from jax.sharding import PartitionSpec as P

    def body(x):
        def tick(c, _):
            return c + 1.0, None

        s, _ = jax.lax.scan(tick, jnp.float32(0), None, length=2)
        return x + s

    fn = jax.shard_map(body, mesh=mesh, in_specs=(P("context"),),
                      out_specs=P("context"), check_vma=False)
    x = jax.ShapeDtypeStruct((4, 8), jnp.float32)
    rep = jaxpr_audit.audit_jaxpr(jax.make_jaxpr(fn)(x))
    assert len(rep.scalar_carries) == 1
    assert rep.scalar_carries[0].dtype == "float32"

    # the repo convention — [1]-shaped carry — is clean
    def body_ok(x):
        def tick(c, _):
            return c + 1.0, None

        s, _ = jax.lax.scan(tick, jnp.zeros((1,), jnp.float32), None,
                            length=2)
        return x + s[0]

    fn = jax.shard_map(body_ok, mesh=mesh, in_specs=(P("context"),),
                      out_specs=P("context"), check_vma=False)
    rep = jaxpr_audit.audit_jaxpr(jax.make_jaxpr(fn)(x))
    assert rep.scalar_carries == []


def test_auditor_flags_manual_axis_constraint():
    """A with_sharding_constraint naming a manually-bound axis inside a
    shard_map body (this toolchain rejects it at lowering; constrain()
    skips them — the auditor proves none slipped through)."""
    mesh = _ctx_mesh()
    from jax.sharding import NamedSharding, PartitionSpec as P

    def body(x):
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P("context")))

    fn = jax.shard_map(body, mesh=mesh, in_specs=(P("context"),),
                      out_specs=P("context"), check_vma=False)
    x = jax.ShapeDtypeStruct((4, 8), jnp.float32)
    rep = jaxpr_audit.audit_jaxpr(jax.make_jaxpr(fn)(x))
    assert len(rep.manual_constraints) == 1
    assert "context" in rep.manual_constraints[0].axes

    # constrain() skips the same spec at trace time — clean audit
    from megatron_tpu.parallel.sharding import constrain

    def body_ok(x):
        return constrain(x, P("context"))

    fn = jax.shard_map(body_ok, mesh=mesh, in_specs=(P("context"),),
                      out_specs=P("context"), check_vma=False)
    rep = jaxpr_audit.audit_jaxpr(jax.make_jaxpr(fn)(x))
    assert rep.manual_constraints == []


def test_auditor_flags_callbacks_and_promotions():
    def fn(x):
        jax.debug.print("x {x}", x=x)
        return x.astype(jnp.float32) * 2

    x = jax.ShapeDtypeStruct((64, 64), jnp.bfloat16)
    rep = jaxpr_audit.audit_jaxpr(jax.make_jaxpr(fn)(x),
                                  promotion_threshold_bytes=1024)
    assert [c.primitive for c in rep.callbacks] == ["debug_callback"]
    assert len(rep.promotions) == 1
    assert rep.promotions[0].bytes_out == 64 * 64 * 4


def test_auditor_donation_report():
    def f(state, batch):
        return {"w": state["w"] + batch["tokens"].sum()}

    state = {"w": jax.ShapeDtypeStruct((128, 128), jnp.float32)}
    batch = {"tokens": jax.ShapeDtypeStruct((128, 128), jnp.float32)}
    lowered = jax.jit(f, donate_argnums=(0,)).lower(state, batch)
    rep = jaxpr_audit.audit_donation(lowered)
    assert any("w" in p for p in rep.donated)
    over = rep.undonated_over(1, allow=(r"tokens",))
    assert over == [], over  # batch is the only non-donated input


# ---------------------------------------------------------------------------
# production-program audits (the acceptance assertions)
# ---------------------------------------------------------------------------


def test_train_step_audit_clean():
    """Train step (dp8 + ZeRO-1): zero host callbacks, full state
    donation, no rank-0 shard_map carries, no manual-axis constraints,
    no silent half->f32 promotions (the fp32-master design upcasts via
    grad accumulation, not convert-on-activation)."""
    t = contracts.CONFIGS["train_dp8_zero1"]()
    rep = jaxpr_audit.audit_jaxpr(t.jaxpr(), t.name)
    assert rep.callbacks == []
    assert rep.scalar_carries == []
    assert rep.manual_constraints == []

    don = jaxpr_audit.audit_donation(t.lowered())
    # args_info tree: (state, batch); every state leaf must be donated
    state_undonated = [p for p, _ in don.undonated
                       if not any(k in p for k in
                                  ("tokens", "labels", "loss_mask"))]
    assert state_undonated == [], state_undonated
    assert len(don.donated) > 10  # params + masters + moments + scalars


def test_train_step_flash_bwd_audit_clean():
    """The train step with attention routed through the flash template
    (ISSUE 16): the GRADIENT path runs the custom-vjp pallas kernels —
    pallas calls visibly in the step jaxpr (fwd, remat fwd, dq, dk/dv;
    the deterministic form of bench's train_attention_bwd_speedup gate)
    — with the same cleanliness contract as the einsum step: zero host
    callbacks, zero unexpected promotions, full state donation."""
    t = targets.flash_bwd_train_step_target()
    jaxpr = t.jaxpr()
    assert str(jaxpr).count("pallas_call") >= 3  # fwd + bwd kernels

    rep = jaxpr_audit.audit_jaxpr(jaxpr, t.name)
    assert rep.callbacks == []
    assert rep.scalar_carries == []
    assert rep.manual_constraints == []
    assert rep.promotions == [], rep.promotions

    don = jaxpr_audit.audit_donation(t.lowered())
    state_undonated = [p for p, _ in don.undonated
                       if not any(k in p for k in
                                  ("tokens", "labels", "loss_mask"))]
    assert state_undonated == [], state_undonated
    assert len(don.donated) > 10


def test_decode_step_audit_clean():
    """Engine decode step: zero collectives (single-device contract),
    zero host callbacks, the KV cache donated. The only tolerated
    bf16->f32 promotions are the softmax_fp32 numerics (K upcast per
    layer) — anything else is a new silent upcast."""
    t = targets.decode_step_target()
    rep = jaxpr_audit.audit_jaxpr(t.jaxpr(), t.name)
    assert rep.collectives == []
    assert rep.callbacks == []
    # allowlist: attention's softmax_fp32 upcasts K ([slots, S, Hkv, D])
    # once per layer inside the layer scan — intended numerics
    # (ops/attention.py kf = k.astype(f32)); bound it so a new upcast
    # (e.g. the whole cache, or V too) still fails
    unexpected = [p for p in rep.promotions
                  if not (p.shape == (4, 32, 2, 8) and p.calls == 4)]
    assert unexpected == [], unexpected
    assert len(rep.promotions) <= 1

    don = jaxpr_audit.audit_donation(t.lowered())
    assert len(don.donated) == 2, don.donated  # the k/v cache stacks


def test_paged_decode_step_audit_clean():
    """Paged engine decode step (page-table gather + scatter): same
    contract as the slot decode step — zero collectives, zero host
    callbacks, the page pools donated; the only tolerated bf16->f32
    promotion is softmax_fp32's per-layer K upcast (here the GATHERED
    [slots, max_pages*page_size, Hkv, D] view)."""
    t = targets.paged_decode_step_target()
    rep = jaxpr_audit.audit_jaxpr(t.jaxpr(), t.name)
    assert rep.collectives == []
    assert rep.callbacks == []
    unexpected = [p for p in rep.promotions
                  if not (p.shape == (4, 32, 2, 8) and p.calls == 4)]
    assert unexpected == [], unexpected
    assert len(rep.promotions) <= 1

    don = jaxpr_audit.audit_donation(t.lowered())
    assert len(don.donated) == 2, don.donated  # the k/v page pools


@pytest.mark.parametrize("builder", ["spec_decode_step_target",
                                     "spec_paged_decode_step_target"])
def test_spec_decode_step_audit_clean(builder):
    """Speculative decode step (slot AND paged, model drafter): zero
    collectives, ZERO host callbacks — the draft-proposal scan and the
    exact accept/reject (uniform draws, residual categoricals) must all
    stay on device — and FULL donation of BOTH cache trees (2 target
    k/v stacks + 2 draft k/v stacks). bf16->f32 promotions are bounded
    to the known small intermediates: the per-layer softmax_fp32 K
    upcasts of target and draft (the draft's multiplied through its
    k-step proposal scan), the [N, k+1] verify attention slices, and
    the [N, (k+1,) V] logits rows the accept math scores — anything
    cache-sized is a new silent upcast and fails."""
    t = getattr(targets, builder)()
    rep = jaxpr_audit.audit_jaxpr(t.jaxpr(), t.name)
    assert rep.collectives == []
    assert rep.callbacks == []
    # every tolerated promotion is tiny (K-upcast slices, verify rows,
    # logits rows); the full caches/pools would be >= 4*32*2*8 * layers
    import math

    too_big = [p for p in rep.promotions
               if math.prod(p.shape) > 4 * 32 * 2 * 8]
    assert too_big == [], too_big
    assert len(rep.promotions) <= 12, rep.promotions

    don = jaxpr_audit.audit_donation(t.lowered())
    # target k/v stacks + draft k/v stacks
    assert len(don.donated) == 4, don.donated


# ---------------------------------------------------------------------------
# golden comm contracts
# ---------------------------------------------------------------------------

ALL_CONFIGS = sorted(contracts.CONFIGS)


def test_golden_manifests_exist():
    """Acceptance: >= 5 parallel configs pinned."""
    present = [n for n in ALL_CONFIGS if contracts.manifest_path(n).exists()]
    assert len(present) >= 5, present
    assert present == ALL_CONFIGS, "manifest missing — run " \
        "'python tools/comm_report.py --regen'"


@pytest.mark.parametrize("name", ALL_CONFIGS)
def test_golden_contract_jaxpr(name):
    problems = contracts.check_contract(name, level="jaxpr")
    assert problems == [], "\n".join(problems) + \
        "\n(intentional comm change? regen: python tools/comm_report.py " \
        f"--regen {name})"


@pytest.mark.slow  # ~25s: XLA-compiles 5 tiny SPMD programs (the jaxpr
# level above runs in tier-1; this adds the GSPMD-inserted collectives)
@pytest.mark.parametrize("name", [n for n in ALL_CONFIGS
                                  if n not in ("moe_ep2",)])
def test_golden_contract_hlo(name):
    problems = contracts.check_contract(name, level="hlo")
    assert problems == [], "\n".join(problems)


def test_injected_collective_breaks_contract():
    """Acceptance: a hidden extra collective fails the golden check."""
    from jax.sharding import PartitionSpec as P
    from megatron_tpu.ops.ulysses import ulysses_attention
    from megatron_tpu.parallel.mesh import AXIS_CONTEXT

    mesh = _ctx_mesh()
    B, S, Hq, Hkv, D = 2, 32, 4, 2, 8

    def body(q, k, v):
        out = ulysses_attention(q, k, v, inner_impl="xla")
        # the smuggled collective a PR might introduce by accident
        return out + 0.0 * jax.lax.psum(out, AXIS_CONTEXT)

    inner = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(None, AXIS_CONTEXT),) * 3,
        out_specs=P(None, AXIS_CONTEXT), check_vma=False)

    def fn(q, k, v):
        return jax.grad(lambda q, k, v: inner(q, k, v).sum(),
                        argnums=(0, 1, 2))(q, k, v)

    q = jax.ShapeDtypeStruct((B, S, Hq, D), jnp.float32)
    kv = jax.ShapeDtypeStruct((B, S, Hkv, D), jnp.float32)
    tampered = targets.AuditTarget(name="ulysses_cp2", fn=fn,
                                   args=(q, kv, kv), mesh=mesh)
    fresh = contracts.build_manifest("ulysses_cp2", include_hlo=False,
                                     target=tampered)
    problems = contracts.check_contract("ulysses_cp2", level="jaxpr",
                                        fresh=fresh)
    assert problems, "tampered manifest passed the golden check"
    assert any("psum" in p for p in problems), problems


def test_contract_catches_callback_regression():
    """A host callback smuggled into an audited program trips the
    scalar checks, not just the collective table."""
    t = targets.decode_step_target()

    def with_cb(*args):
        out = t.fn(*args)
        jax.debug.print("tok {t}", t=out[0])
        return out

    tampered = targets.AuditTarget(name="decode_single", fn=with_cb,
                                   args=t.args)
    fresh = contracts.build_manifest("decode_single", include_hlo=False,
                                     target=tampered)
    problems = contracts.check_contract("decode_single", level="jaxpr",
                                        fresh=fresh)
    assert any("host_callbacks" in p for p in problems), problems


# ---------------------------------------------------------------------------
# comm_report CLI
# ---------------------------------------------------------------------------


def test_comm_report_prints_table(capsys):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "_comm_report", REPO / "tools" / "comm_report.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rc = mod.main(["--config", "train_pp2"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "train_pp2" in out
    assert "ppermute[pipe]" in out
    assert "host_callbacks=0" in out

"""Chunked fused logits+cross-entropy (beyond the reference, which
materializes the full [B,S,V] logits — gpt_model.py:18-42). The chunked
path must be numerically identical to the unchunked one: the softmax is
complete within a chunk because CE is per-token; only the sequence axis
is split."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_tpu.models import presets
from megatron_tpu.models.language_model import lm_loss
from megatron_tpu.models.params import init_params


def _batch(cfg, batch=2, seq=None, seed=0, masked=False):
    seq = seq or cfg.seq_length
    rng = np.random.default_rng(seed)
    b = {"tokens": jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)),
                               jnp.int32),
         "labels": jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)),
                               jnp.int32)}
    if masked:
        b["loss_mask"] = jnp.asarray(rng.integers(0, 2, (batch, seq)),
                                     jnp.float32)
    return b


@pytest.mark.parametrize("tie", [False, True])
@pytest.mark.parametrize("masked", [False, True])
def test_chunked_ce_matches_unchunked(tie, masked):
    cfg = presets.tiny(seq_length=32, tie_embed_logits=tie)
    chunked = dataclasses.replace(cfg, ce_chunk_size=8).validate()
    params = init_params(cfg, jax.random.PRNGKey(0))
    batch = _batch(cfg, masked=masked)

    loss0, aux0 = lm_loss(cfg, params, batch)
    loss1, aux1 = lm_loss(chunked, params, batch)
    np.testing.assert_allclose(float(loss0), float(loss1), rtol=1e-6)
    np.testing.assert_allclose(float(aux0["ntokens"]), float(aux1["ntokens"]))

    g0 = jax.grad(lambda p: lm_loss(cfg, p, batch)[0])(params)
    g1 = jax.grad(lambda p: lm_loss(chunked, p, batch)[0])(params)
    for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_chunked_ce_full_size_chunk():
    """C == S is a single remat'd chunk (drops the forward logits copy),
    not a silent no-op; numbers still match."""
    cfg = presets.tiny(seq_length=32)
    chunked = dataclasses.replace(cfg, ce_chunk_size=32).validate()
    params = init_params(cfg, jax.random.PRNGKey(0))
    batch = _batch(cfg)
    loss0, _ = lm_loss(cfg, params, batch)
    loss1, _ = lm_loss(chunked, params, batch)
    np.testing.assert_allclose(float(loss0), float(loss1), rtol=1e-6)
    g0 = jax.grad(lambda p: lm_loss(cfg, p, batch)[0])(params)
    g1 = jax.grad(lambda p: lm_loss(chunked, p, batch)[0])(params)
    for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_chunked_ce_falls_back_on_non_tiling_seq():
    """variable_seq_lengths batches shorter than seq_length: when the chunk
    doesn't tile the actual sequence, the unchunked path runs (same loss,
    no crash)."""
    cfg = presets.tiny(seq_length=32)
    chunked = dataclasses.replace(cfg, ce_chunk_size=8).validate()
    params = init_params(cfg, jax.random.PRNGKey(0))
    batch = _batch(cfg, seq=12)  # 12 % 8 != 0 -> fallback
    loss0, _ = lm_loss(cfg, params, batch)
    loss1, _ = lm_loss(chunked, params, batch)
    np.testing.assert_allclose(float(loss0), float(loss1), rtol=1e-6)


def test_chunked_ce_validate_rejects_non_divisor():
    with pytest.raises(ValueError):
        presets.tiny(seq_length=32, ce_chunk_size=7)


def test_chunked_ce_in_pipeline_last_stage():
    """pp=2 with chunked CE on the last stage matches the unpipelined
    unchunked loss."""
    from megatron_tpu.config import ParallelConfig
    from megatron_tpu.parallel.mesh import build_mesh
    from megatron_tpu.parallel.sharding import shard_tree
    from megatron_tpu.models.params import param_specs
    from megatron_tpu.training.pipeline import make_pipeline_loss_fn

    cfg = presets.tiny(vocab_size=64, seq_length=16, num_layers=4,
                       hidden_size=32, num_attention_heads=4, num_kv_heads=2,
                       ffn_hidden_size=64)
    chunked = dataclasses.replace(cfg, ce_chunk_size=4).validate()
    rt = build_mesh(ParallelConfig(pipeline_parallel=2))
    params = init_params(cfg, jax.random.PRNGKey(0))
    sp = shard_tree(rt, params, param_specs(cfg))
    rng = np.random.default_rng(0)
    batch = {
        "tokens": jnp.asarray(rng.integers(0, 64, (8, 16)), jnp.int32),
        "labels": jnp.asarray(rng.integers(0, 64, (8, 16)), jnp.int32),
        "loss_mask": jnp.ones((8, 16), jnp.float32),
    }
    pp_loss_fn = make_pipeline_loss_fn(chunked, rt.mesh, num_stages=2,
                                       num_microbatches=4, recompute="full")
    with jax.sharding.set_mesh(rt.mesh):
        loss_pp, _ = jax.jit(lambda p, b: pp_loss_fn(p, b, None))(sp, batch)
    loss_ref = lm_loss(cfg, params, batch)[0]
    np.testing.assert_allclose(float(loss_pp), float(loss_ref), rtol=1e-5)


def test_chunked_ce_under_tensor_parallel():
    """tp=2 sharded run with chunking matches the unsharded unchunked loss
    (the per-chunk logits keep the vocab-sharded 'logits' spec)."""
    from megatron_tpu.config import ParallelConfig
    from megatron_tpu.parallel.mesh import build_mesh
    from megatron_tpu.parallel.sharding import (
        activation_spec, constrain, logits_spec, shard_tree,
    )
    from megatron_tpu.models.params import param_specs

    cfg = presets.tiny(seq_length=32, vocab_size=64)
    chunked = dataclasses.replace(cfg, ce_chunk_size=8).validate()
    params = init_params(cfg, jax.random.PRNGKey(0))
    batch = _batch(cfg)
    loss0, _ = lm_loss(cfg, params, batch)

    def sharder(x, role):
        if role == "residual":
            return constrain(x, activation_spec(False))
        if role == "logits":
            return constrain(x, logits_spec())
        return x

    rt = build_mesh(ParallelConfig(tensor_parallel=2))
    with jax.sharding.set_mesh(rt.mesh):
        sp = shard_tree(rt, params, param_specs(cfg))
        loss1, _ = jax.jit(
            lambda p, b: lm_loss(chunked, p, b, sharder=sharder))(sp, batch)
    np.testing.assert_allclose(float(loss0), float(loss1),
                               rtol=1e-5, atol=1e-6)

"""Multi-host mechanics on CPU: two real jax.distributed processes build
the global mesh, feed per-host batch shards, and run one training step
(counterpart of the reference's multi-node path, initialize.py:124-167 —
which needs real GPUs + torchrun; here it runs hermetically)."""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# This jax's XLA:CPU client cannot execute cross-process COMPUTATIONS: a
# device_put of a host array to a non-addressable sharding (each process
# holds only its slice of the global batch) routes through a multihost
# device broadcast that the CPU backend rejects with exactly this
# message. On a real TPU backend the same code path works; the step test
# must skip, not fail, so the suite stays green on CPU CI while still
# running under MEGATRON_TPU_TEST_PLATFORM=tpu captures (ROADMAP item).
# The skip is NARROW now: everything that is not an XLA program — the
# jax.distributed coordination service, its KV store, barriers, and the
# whole training/coordination.py protocol suite — runs FOR REAL on CPU
# under the shared `jax_cluster` harness (test_two_process_host_broadcast
# below + tests/test_coordination.py), so only the device-collective step
# itself remains TPU-gated.
_CPU_MULTIHOST_UNSUPPORTED = "Multiprocess computations aren't implemented"

_WORKER = r"""
import os, sys
sys.path.insert(0, %(repo)r)
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
pid = int(sys.argv[1])

from megatron_tpu.parallel.distributed import (
    build_multihost_mesh, host_batch_slice, initialize_distributed,
    put_process_local_batch,
)
assert initialize_distributed(coordinator_address=%(coord)r,
                              num_processes=2, process_id=pid)
assert jax.process_count() == 2
assert len(jax.devices()) == 8

import jax.numpy as jnp
import numpy as np
from megatron_tpu.config import OptimizerConfig, ParallelConfig, TrainingConfig
from megatron_tpu.models import presets
from megatron_tpu.models.params import init_params, param_specs
from megatron_tpu.parallel.sharding import shard_tree
from megatron_tpu.training.optimizer import init_train_state, train_state_specs
from megatron_tpu.training.train_step import make_train_step
from jax.sharding import NamedSharding, PartitionSpec as P

par = ParallelConfig(tensor_parallel=2)
rt = build_multihost_mesh(par)
assert rt.dp == 4, rt.dp
# data axis must be outermost across processes: each host's addressable
# mesh rows are contiguous
rows = {d.process_index for d in rt.mesh.devices[:2].ravel()}
assert rows == {0}, rows

cfg = presets.tiny(vocab_size=64, seq_length=16, num_layers=2,
                   hidden_size=32, num_attention_heads=4, num_kv_heads=2,
                   ffn_hidden_size=64)
opt = OptimizerConfig(lr=1e-3, lr_decay_style="constant")
tcfg = TrainingConfig(micro_batch_size=1, global_batch_size=8, seed=0)

params = init_params(cfg, jax.random.PRNGKey(0))
params = shard_tree(rt, params, param_specs(cfg))
state = init_train_state(opt, params)
step = make_train_step(cfg, opt, tcfg, num_microbatches=2, train_iters=4)

GB = tcfg.global_batch_size
lo, hi = host_batch_slice(rt, GB)
assert (hi - lo) == GB // 2, (lo, hi)
# deterministic global batch; each host materializes only its slice
rng = np.random.default_rng(0)
tokens = rng.integers(0, 64, (GB, 16)).astype(np.int32)
labels = rng.integers(0, 64, (GB, 16)).astype(np.int32)
local = {
    "tokens": tokens[lo:hi],
    "labels": labels[lo:hi],
    "loss_mask": np.ones((hi - lo, 16), np.float32),
}
batch = put_process_local_batch(rt, local, GB)

with jax.sharding.set_mesh(rt.mesh):
    jstep = jax.jit(step, donate_argnums=(0,))
    state, metrics = jstep(state, batch)
    loss = float(metrics["loss"])
print(f"WORKER{pid} loss={loss:.6f}", flush=True)
"""


_BCAST_WORKER = r"""
import numpy as np
from megatron_tpu.training.coordination import (
    ClusterCoordinator, KVBackend)

assert jax.process_count() == 2
c = ClusterCoordinator(KVBackend(), pid, 2, peer_death_timeout_s=10,
                       poll_s=0.05)
c.topology_barrier(60)
# host-data broadcast (the multihost-utils use case for SMALL host values:
# agreed config, sampler seeds, resolved checkpoint iteration) over the
# coordination service instead of an XLA device collective — which is why
# it runs for real on XLA:CPU
payload = {"seed": 1234, "resume_iteration": 40,
           "order": list(np.arange(4).tolist())} if pid == 0 else None
got = c.broadcast(payload, root=0, key="run_cfg", timeout_s=60)
assert got == {"seed": 1234, "resume_iteration": 40, "order": [0, 1, 2, 3]}
# rendezvous so neither side tears the service down under the other
c.publish_value("done", True)
import time
deadline = time.monotonic() + 60
while c.read_value("done", host=1 - pid) is None:
    assert time.monotonic() < deadline
    time.sleep(0.05)
print(f"BCAST{pid} OK", flush=True)
"""


def test_two_process_host_broadcast(jax_cluster):
    """The broadcast this file used to skip wholesale, run FOR REAL: two
    jax.distributed CPU processes agree on one host value through the
    coordination service's KV store (training/coordination.py broadcast).
    Only the XLA *device* broadcast remains TPU-gated (test below)."""
    results = jax_cluster(_BCAST_WORKER, nprocs=2, devices_per_proc=1,
                          timeout=240)
    for i, (rc, out) in enumerate(results):
        assert rc == 0, f"worker {i} failed:\n{out}"
        assert f"BCAST{i} OK" in out


@pytest.mark.slow  # 10s measured on CPU — where it only SKIPS anyway
# (multiprocess XLA:CPU computations unimplemented; the non-XLA half of
# multihost — coordination service, KV store, host broadcast — runs for
# real above); device-collective coverage runs under
# MEGATRON_TPU_TEST_PLATFORM=tpu
def test_two_process_distributed_step(tmp_path):
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    coord = f"localhost:{port}"
    script = tmp_path / "worker.py"
    script.write_text(_WORKER % {"repo": REPO, "coord": coord})

    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [subprocess.Popen([sys.executable, str(script), str(i)],
                              stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                              text=True, env=env)
             for i in range(2)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            # the peer of a crashed worker can wedge in a collective;
            # collect what it printed and let the skip check below decide
            p.kill()
            out, _ = p.communicate()
        outs.append(out)
    if any(_CPU_MULTIHOST_UNSUPPORTED in out for out in outs):
        pytest.skip(
            "this jax's CPU backend cannot device_put to a non-addressable "
            f"sharding ({_CPU_MULTIHOST_UNSUPPORTED!r}: the per-host batch "
            "placement routes through a multihost broadcast XLA:CPU does "
            "not implement); run with MEGATRON_TPU_TEST_PLATFORM=tpu for "
            "real multi-process coverage")
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
    losses = []
    for i, out in enumerate(outs):
        line = [ln for ln in out.splitlines() if ln.startswith(f"WORKER{i}")][0]
        losses.append(float(line.split("loss=")[1]))
    # both processes computed the same global step
    assert abs(losses[0] - losses[1]) < 1e-6
    assert np.isfinite(losses[0])

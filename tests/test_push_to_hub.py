"""push_to_hub tool (ref: tools/push_to_hub.py — validation + dry-run;
the actual upload needs network and is exercised only in real runs)."""

import json

import pytest


def test_dry_run_on_hf_dir(tmp_path, capsys):
    from tools import push_to_hub

    d = tmp_path / "hf"
    d.mkdir()
    (d / "config.json").write_text(json.dumps({"model_type": "llama"}))
    (d / "pytorch_model.bin").write_bytes(b"\0" * 128)
    out = push_to_hub.main([str(d), "--hub_repo", "me/test", "--dry_run"])
    assert out == str(d)
    cap = capsys.readouterr().out
    assert "dry run" in cap and "pytorch_model.bin" in cap


def test_rejects_non_model_dir(tmp_path):
    from tools import push_to_hub

    d = tmp_path / "empty"
    d.mkdir()
    with pytest.raises(SystemExit, match="does not look like"):
        push_to_hub.main([str(d), "--hub_repo", "me/test", "--dry_run"])

"""Corpus cleanup + dedup tool (compact counterpart of the reference's
tools/openwebtext/ pipeline)."""

import json

import numpy as np

from tools.clean_corpus import clean_corpus, clean_text, url_ok


def _doc(words, url=None):
    return {"text": " ".join(words), "url": url}


def test_url_blacklist():
    bl = {"spam.com"}
    assert url_ok("https://good.org/a", bl)
    assert not url_ok("https://spam.com/a", bl)
    assert not url_ok("https://sub.spam.com/a", bl)
    assert not url_ok("ftp://weird", bl)
    assert url_ok(None, bl)


def test_clean_text_normalizes():
    assert clean_text("a b   c") == "a b c"
    assert clean_text("x\n\n\n\n\ny") == "x\n\ny"
    # control characters stripped
    assert clean_text("a\x00b\x07c") == "abc"


def test_exact_and_near_dedup():
    rng = np.random.default_rng(0)
    base = [str(int(x)) for x in rng.integers(0, 1000, 200)]
    near = list(base)
    near[3] = "CHANGED"  # one-word edit: still a near-duplicate
    distinct = [str(int(x)) for x in rng.integers(0, 1000, 200)]
    docs = [_doc(base), _doc(base), _doc(near), _doc(distinct)]
    kept, report = clean_corpus(docs, min_words=10)
    assert report["exact_dup"] == 1
    assert report["near_dup"] == 1
    assert report["kept"] == 2


def test_short_and_blacklisted_dropped(tmp_path):
    from tools import clean_corpus as cc

    rng = np.random.default_rng(1)
    long_words = [str(int(x)) for x in rng.integers(0, 1000, 150)]
    docs = [
        _doc(long_words, "https://ok.org/1"),
        _doc(["too", "short"], "https://ok.org/2"),
        _doc(long_words[::-1], "https://bad.net/3"),
    ]
    inp = tmp_path / "in.jsonl"
    inp.write_text("".join(json.dumps(d) + "\n" for d in docs))
    bl = tmp_path / "bl.txt"
    bl.write_text("bad.net\n")
    out = tmp_path / "out.jsonl"
    report = cc.main(["--input", str(inp), "--output", str(out),
                      "--blacklist", str(bl), "--min_words", "100"])
    assert report == {"total": 3, "bad_url": 1, "too_short": 1,
                      "exact_dup": 0, "near_dup": 0, "kept": 1}
    lines = out.read_text().splitlines()
    assert len(lines) == 1 and json.loads(lines[0])["url"] == "https://ok.org/1"


def test_url_blacklist_www_and_port():
    bl = {"weather.com", "spam.com"}
    assert not url_ok("https://www.weather.com/x", bl)   # www prefix
    assert not url_ok("http://spam.com:80/a", bl)        # explicit port
    assert not url_ok("http://user:pw@spam.com/a", bl)   # userinfo
    assert url_ok("https://wa.com/x", {"a.com"})         # no prefix mangling


def test_blacklist_edge_cases():
    # scheme-less URL still hits the blacklist
    assert not url_ok("spam.com/article", {"spam.com"})
    # ZWNJ (Cf) survives cleanup; NUL (Cc) does not
    assert clean_text("a‌b\x00c") == "a‌bc"


def test_blacklist_file_with_www(tmp_path):
    import json as _json

    from tools import clean_corpus as cc

    words = [str(i) for i in range(150)]
    inp = tmp_path / "in.jsonl"
    inp.write_text(_json.dumps(
        {"text": " ".join(words), "url": "https://spam.com/x"}) + "\n")
    bl = tmp_path / "bl.txt"
    bl.write_text("www.spam.com\n")  # published blacklists often have www.
    out = tmp_path / "out.jsonl"
    report = cc.main(["--input", str(inp), "--output", str(out),
                      "--blacklist", str(bl), "--min_words", "100"])
    assert report["bad_url"] == 1 and report["kept"] == 0


def test_surrogates_and_weird_schemes():
    # lone surrogate (what json.loads yields for \ud800) must not crash
    kept, report = clean_corpus(
        [{"text": "x \ud800 " + " ".join(str(i) for i in range(150))}],
        min_words=100)
    assert report["kept"] == 1
    assert "\ud800" not in kept[0]["text"]
    # non-http schemes stay rejected; host:port without scheme still matches
    assert not url_ok("javascript:alert(1)", set())
    assert not url_ok("mailto:a@spam.com", set())
    assert not url_ok("spam.com:8080/x", {"spam.com"})
    # library callers get www-normalized blacklists too
    _, rep = clean_corpus(
        [{"text": " ".join(str(i) for i in range(150)),
          "url": "https://spam.com/x"}],
        blacklist={"www.spam.com"}, min_words=100)
    assert rep["bad_url"] == 1

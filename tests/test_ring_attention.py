"""Ring attention vs single-device attention (no reference counterpart —
the reference has no context parallelism; gate is exact-math equivalence)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_tpu.config import ParallelConfig
from megatron_tpu.ops.attention import attention
from megatron_tpu.ops.ring_attention import ring_attention_sharded
from megatron_tpu.parallel.mesh import build_mesh

RNG = np.random.default_rng(42)


def _qkv(b=2, s=32, hq=4, hkv=2, d=16):
    q = RNG.standard_normal((b, s, hq, d)).astype(np.float32)
    k = RNG.standard_normal((b, s, hkv, d)).astype(np.float32)
    v = RNG.standard_normal((b, s, hkv, d)).astype(np.float32)
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


@pytest.mark.parametrize("cp", [2, 4])
@pytest.mark.parametrize("mask_type,window", [
    ("causal", None), ("causal", 8), ("causal", 3), ("causal", 40),
    ("bidirectional", None),
])
def test_ring_matches_dense(cp, mask_type, window):
    rt = build_mesh(ParallelConfig(context_parallel=cp))
    q, k, v = _qkv()
    want = attention(q, k, v, mask_type=mask_type, sliding_window=window)
    with jax.sharding.set_mesh(rt.mesh):
        got = jax.jit(lambda q, k, v: ring_attention_sharded(
            q, k, v, rt.mesh, mask_type=mask_type, sliding_window=window))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_ring_grads_match_dense():
    rt = build_mesh(ParallelConfig(context_parallel=4))
    q, k, v = _qkv(b=1, s=16, hq=2, hkv=1, d=8)

    def dense_loss(q, k, v):
        return jnp.sum(jnp.square(attention(q, k, v)))

    def ring_loss(q, k, v):
        return jnp.sum(jnp.square(ring_attention_sharded(q, k, v, rt.mesh)))

    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    with jax.sharding.set_mesh(rt.mesh):
        g_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)


@pytest.mark.slow  # 9s measured cacheless (PR 4 tier-1 re-budget);
# the other three ring-grads parity cases stay tier-1
def test_ring_zigzag_window_grads_match_dense():
    """Sliding-window causal now rides the zig-zag balanced path — its
    stripe-skip predicates must be gradient-exact too."""
    rt = build_mesh(ParallelConfig(context_parallel=4))
    q, k, v = _qkv(b=1, s=32, hq=2, hkv=1, d=8)

    def dense_loss(q, k, v):
        return jnp.sum(jnp.square(attention(q, k, v, sliding_window=6)))

    def ring_loss(q, k, v):
        return jnp.sum(jnp.square(ring_attention_sharded(
            q, k, v, rt.mesh, sliding_window=6)))

    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    with jax.sharding.set_mesh(rt.mesh):
        g_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)


@pytest.mark.parametrize("cp", [2, 4])
def test_ring_flash_inner_matches_dense(cp):
    """The flash-stripe zig-zag path (forced through the pallas
    interpreter on CPU) is value-exact against dense attention — no
    per-hop dense score buffer, same math (VERDICT r3 next-round #5)."""
    rt = build_mesh(ParallelConfig(context_parallel=cp))
    q, k, v = _qkv()
    want = attention(q, k, v, mask_type="causal")
    with jax.sharding.set_mesh(rt.mesh):
        got = jax.jit(lambda q, k, v: ring_attention_sharded(
            q, k, v, rt.mesh, inner_impl="flash"))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_ring_flash_inner_grads_match_dense():
    """Whole-ring custom_vjp: per-stripe kernel backwards with the global
    lse must sum to the exact dense gradient, dk/dv rotating home."""
    rt = build_mesh(ParallelConfig(context_parallel=4))
    q, k, v = _qkv(b=1, s=32, hq=2, hkv=1, d=8)

    def dense_loss(q, k, v):
        return jnp.sum(jnp.square(attention(q, k, v)))

    def ring_loss(q, k, v):
        return jnp.sum(jnp.square(ring_attention_sharded(
            q, k, v, rt.mesh, inner_impl="flash")))

    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    with jax.sharding.set_mesh(rt.mesh):
        g_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)


def test_ring_flash_inner_gqa_grads():
    """GQA: kernel runs per query head; dk/dv group-sum back to kv heads."""
    rt = build_mesh(ParallelConfig(context_parallel=2))
    q, k, v = _qkv(b=1, s=16, hq=4, hkv=2, d=8)

    def dense_loss(q, k, v):
        return jnp.sum(jnp.square(attention(q, k, v)))

    def ring_loss(q, k, v):
        return jnp.sum(jnp.square(ring_attention_sharded(
            q, k, v, rt.mesh, inner_impl="flash")))

    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    with jax.sharding.set_mesh(rt.mesh):
        g_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)


@pytest.mark.parametrize("cp,window", [(2, 8), (4, 6), (2, 3), (4, 40)])
def test_ring_flash_inner_window_matches_dense(cp, window):
    """Sliding windows on the kernel path: the stripe delta + static
    window band must reproduce dense windowed attention exactly."""
    rt = build_mesh(ParallelConfig(context_parallel=cp))
    q, k, v = _qkv()
    want = attention(q, k, v, mask_type="causal", sliding_window=window)
    with jax.sharding.set_mesh(rt.mesh):
        got = jax.jit(lambda q, k, v: ring_attention_sharded(
            q, k, v, rt.mesh, inner_impl="flash",
            sliding_window=window))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_ring_flash_inner_window_grads_match_dense():
    rt = build_mesh(ParallelConfig(context_parallel=4))
    q, k, v = _qkv(b=1, s=32, hq=2, hkv=1, d=8)

    def dense_loss(q, k, v):
        return jnp.sum(jnp.square(attention(q, k, v, sliding_window=6)))

    def ring_loss(q, k, v):
        return jnp.sum(jnp.square(ring_attention_sharded(
            q, k, v, rt.mesh, inner_impl="flash", sliding_window=6)))

    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    with jax.sharding.set_mesh(rt.mesh):
        g_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)


@pytest.mark.parametrize("mask_type", ["bidirectional", "causal"])
def test_contiguous_ring_flash_matches_dense(mask_type):
    """The contiguous ring's flash inner (bidirectional CP, and causal
    shapes zig-zag can't stripe) — values AND grads vs dense."""
    from megatron_tpu import compat

    if compat.SHARD_MAP_SHIMMED and mask_type == "bidirectional":
        pytest.skip(
            "old-toolchain XLA: the contiguous ring's bidirectional flash "
            "inner lowers an axis_index the SPMD partitioner turns into a "
            "PartitionId instruction it then rejects as UNIMPLEMENTED "
            "(the causal variant and every einsum ring path compile fine; "
            "kernel is covered on real TPU via "
            "MEGATRON_TPU_TEST_PLATFORM=tpu captures)")
    rt = build_mesh(ParallelConfig(context_parallel=4))
    q, k, v = _qkv(b=1, s=32, hq=4, hkv=2, d=8)
    want = attention(q, k, v, mask_type=mask_type)

    def make(impl):
        # mask_type='causal' with S % (2*cp) == 0 would take the zig-zag
        # branch; drive the contiguous one via a non-zigzag length
        return lambda q, k, v: ring_attention_sharded(
            q, k, v, rt.mesh, mask_type=mask_type, inner_impl=impl)

    if mask_type == "causal":
        # 36 = 4*9: divisible by cp, not by 2*cp — contiguous branch
        q, k, v = _qkv(b=1, s=36, hq=4, hkv=2, d=8)
        want = attention(q, k, v, mask_type=mask_type)
    with jax.sharding.set_mesh(rt.mesh):
        got = jax.jit(make("flash"))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)

    def dense_loss(q, k, v):
        return jnp.sum(jnp.square(attention(q, k, v, mask_type=mask_type)))

    def ring_loss(q, k, v):
        return jnp.sum(jnp.square(make("flash")(q, k, v)))

    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    with jax.sharding.set_mesh(rt.mesh):
        g_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)


def test_cp_chunked_prefill_warns_decode_does_not():
    """Single-token decode against a longer cache is the DESIGNED CP
    serving path (flash-decoding by the partitioner) — silent; a
    multi-token pass into cached context (chunked prefill) is the one
    genuine fallback and stays loud."""
    import warnings as w

    k = jnp.asarray(RNG.standard_normal((1, 16, 2, 8)).astype(np.float32))
    v = jnp.asarray(RNG.standard_normal((1, 16, 2, 8)).astype(np.float32))

    q1 = jnp.asarray(RNG.standard_normal((1, 1, 2, 8)).astype(np.float32))
    with w.catch_warnings(record=True) as caught:
        w.simplefilter("always")
        attention(q1, k, v, impl="ring", q_offset=15)
    assert not any("chunked prefill" in str(c.message) for c in caught)

    q4 = jnp.asarray(RNG.standard_normal((1, 4, 2, 8)).astype(np.float32))
    with w.catch_warnings(record=True) as caught:
        w.simplefilter("always")
        attention(q4, k, v, impl="ring", q_offset=12)
    assert any("chunked prefill" in str(c.message) for c in caught)


def test_model_forward_with_ring_impl():
    """Full model with attention_impl='ring' on a cp=2 mesh matches the
    xla-impl forward."""
    from megatron_tpu import compat

    if compat.SHARD_MAP_SHIMMED:
        pytest.skip(
            "old-toolchain XLA: embedding ring attention inside the full "
            "lm_forward jit trips the sharding-remover pass (RET_CHECK "
            "replacing the SPMDFullToShardShape custom-call chain, "
            "hlo_instruction.cc) on this XLA; the ring kernel itself is "
            "covered by the standalone parity tests above")
    from megatron_tpu.models import presets
    from megatron_tpu.models.params import init_params
    from megatron_tpu.models.language_model import lm_forward

    cfg_xla = presets.tiny(vocab_size=64, seq_length=32)
    cfg_ring = presets.tiny(vocab_size=64, seq_length=32, attention_impl="ring")
    params = init_params(cfg_xla, jax.random.PRNGKey(0))
    tokens = jnp.asarray(RNG.integers(0, 64, (2, 32)), jnp.int32)
    want = lm_forward(cfg_xla, params, tokens)
    rt = build_mesh(ParallelConfig(context_parallel=2))
    with jax.sharding.set_mesh(rt.mesh):
        got = jax.jit(lambda p, t: lm_forward(cfg_ring, p, t))(params, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_zigzag_fallback_when_seq_not_divisible():
    """S % 2cp != 0 falls back to the contiguous path, still exact."""
    rt = build_mesh(ParallelConfig(context_parallel=4))
    rng = np.random.default_rng(3)
    S = 20  # 20 % 8 != 0, but 20 % 4 == 0
    q = jnp.asarray(rng.standard_normal((1, S, 4, 16)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((1, S, 2, 16)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((1, S, 2, 16)), jnp.float32)
    want = attention(q, k, v)
    with jax.sharding.set_mesh(rt.mesh):
        got = jax.jit(lambda q, k, v: ring_attention_sharded(
            q, k, v, rt.mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_flash_attention_shims_are_flash_template():
    """flash_attention.py is a re-export facade over the one kernel
    family in flash_template.py — the ring stripes, the paged decode
    specialization and direct flash_mha callers must all resolve to the
    SAME functions, not drifting copies."""
    from megatron_tpu.ops.pallas import flash_attention as fa
    from megatron_tpu.ops.pallas import flash_template as ft

    assert fa._fwd is ft._fwd
    assert fa._bwd is ft._bwd
    assert fa.flash_mha is ft.flash_mha
    assert fa._NEG_INF == ft._NEG_INF
    assert fa._pick_block is ft._pick_block


def test_ring_flash_dispatches_into_template_kernel(monkeypatch):
    """The ring stripes' inner flash forward really lands in the
    flash_template kernel (under MEGATRON_TPU_FLASH_INTERPRET=1 on CPU)
    — count calls through the facade the stripe resolves at call time."""
    from megatron_tpu.ops.pallas import flash_attention as fa

    monkeypatch.setenv("MEGATRON_TPU_FLASH_INTERPRET", "1")
    calls = {"n": 0}
    real_fwd = fa._fwd

    def counting_fwd(*args, **kwargs):
        calls["n"] += 1
        return real_fwd(*args, **kwargs)

    monkeypatch.setattr(fa, "_fwd", counting_fwd)
    rt = build_mesh(ParallelConfig(context_parallel=2))
    q, k, v = _qkv()
    want = attention(q, k, v)
    with jax.sharding.set_mesh(rt.mesh):
        # fresh jit instance: a cached trace would bypass the wrapper
        got = jax.jit(lambda q, k, v: ring_attention_sharded(
            q, k, v, rt.mesh, inner_impl="flash"))(q, k, v)
    assert calls["n"] > 0
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)

"""Numerics tests for core ops vs numpy closed forms
(counterpart of reference tests/test_activations.py and
megatron/mpu/tests/test_cross_entropy.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_tpu.ops.activations import apply_activation
from megatron_tpu.ops.attention import attention
from megatron_tpu.ops.cross_entropy import cross_entropy_loss
from megatron_tpu.ops.normalization import layernorm, rmsnorm
from megatron_tpu.ops.rotary import apply_rotary_emb, precompute_rope

RNG = np.random.default_rng(0)


def test_rmsnorm():
    x = RNG.standard_normal((2, 5, 16)).astype(np.float32)
    w = RNG.standard_normal(16).astype(np.float32)
    got = rmsnorm(jnp.asarray(x), jnp.asarray(w), eps=1e-5)
    want = x / np.sqrt((x**2).mean(-1, keepdims=True) + 1e-5) * w
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_layernorm():
    x = RNG.standard_normal((2, 5, 16)).astype(np.float32)
    w = RNG.standard_normal(16).astype(np.float32)
    b = RNG.standard_normal(16).astype(np.float32)
    got = layernorm(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b), eps=1e-5)
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    want = (x - mu) / np.sqrt(var + 1e-5) * w + b
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("name", ["swiglu", "geglu", "reglu", "liglu"])
def test_glu_closed_form(name):
    """GLU = act(gate) * up on a halved last dim
    (ref tests/test_activations.py checks the same closed forms)."""
    x = RNG.standard_normal((3, 8)).astype(np.float32)
    gate, up = x[:, :4], x[:, 4:]
    got = np.asarray(apply_activation(name, jnp.asarray(x)))
    if name == "geglu":
        import math
        erf = np.vectorize(math.erf)
        want = gate * 0.5 * (1 + erf(gate / np.sqrt(2))) * up
    else:
        acts = {
            "swiglu": lambda g: g * (1 / (1 + np.exp(-g))),
            "reglu": lambda g: np.maximum(g, 0),
            "liglu": lambda g: g,
        }
        want = acts[name](gate) * up
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_rope_rotation_preserves_norm():
    cos, sin = precompute_rope(8, 32)
    q = jnp.asarray(RNG.standard_normal((1, 16, 2, 8)).astype(np.float32))
    k = jnp.asarray(RNG.standard_normal((1, 16, 2, 8)).astype(np.float32))
    qr, kr = apply_rotary_emb(q, k, cos, sin)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(qr), axis=-1),
        np.linalg.norm(np.asarray(q), axis=-1), rtol=1e-5)
    # position 0 is the identity rotation
    np.testing.assert_allclose(np.asarray(qr)[:, 0], np.asarray(q)[:, 0], rtol=1e-6)


def test_rope_relative_property():
    """Scores depend only on relative distance: rotating q,k by equal offset
    leaves q . k unchanged."""
    cos, sin = precompute_rope(8, 64)
    q = jnp.asarray(RNG.standard_normal((1, 1, 1, 8)).astype(np.float32))
    k = jnp.asarray(RNG.standard_normal((1, 1, 1, 8)).astype(np.float32))
    pos_a = jnp.asarray([[5]])
    pos_b = jnp.asarray([[2]])
    qa, ka = apply_rotary_emb(q, k, cos, sin, pos_a), apply_rotary_emb(q, k, cos, sin, pos_b)
    # dot(q@p, k@p+d) invariant to p
    q5, _ = apply_rotary_emb(q, k, cos, sin, jnp.asarray([[5]]))
    _, k8 = apply_rotary_emb(q, k, cos, sin, jnp.asarray([[8]]))
    q15, _ = apply_rotary_emb(q, k, cos, sin, jnp.asarray([[15]]))
    _, k18 = apply_rotary_emb(q, k, cos, sin, jnp.asarray([[18]]))
    d1 = float(jnp.sum(q5 * k8))
    d2 = float(jnp.sum(q15 * k18))
    assert abs(d1 - d2) < 1e-4


def test_rope_scaling_interpolates():
    cos1, _ = precompute_rope(8, 64, scaling_factor=1.0)
    cos2, _ = precompute_rope(8, 64, scaling_factor=2.0)
    # position 2p at scale 2 == position p at scale 1
    np.testing.assert_allclose(np.asarray(cos2)[10], np.asarray(cos1)[5], atol=1e-6)


def _ref_attention(q, k, v, causal=True, window=None):
    b, sq, hq, d = q.shape
    _, skv, hkv, _ = k.shape
    g = hq // hkv
    k = np.repeat(k, g, axis=2)
    v = np.repeat(v, g, axis=2)
    scores = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
    qpos = np.arange(sq)[:, None]
    kpos = np.arange(skv)[None, :]
    mask = np.ones((sq, skv), bool)
    if causal:
        mask &= kpos <= qpos
    if window is not None:
        mask &= kpos > qpos - window
    scores = np.where(mask, scores, -1e30)
    p = np.exp(scores - scores.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", p, v)


def test_attention_gqa_causal():
    q = RNG.standard_normal((2, 8, 4, 16)).astype(np.float32)
    k = RNG.standard_normal((2, 8, 2, 16)).astype(np.float32)
    v = RNG.standard_normal((2, 8, 2, 16)).astype(np.float32)
    got = attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    want = _ref_attention(q, k, v)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_attention_sliding_window():
    q = RNG.standard_normal((1, 12, 2, 8)).astype(np.float32)
    k = RNG.standard_normal((1, 12, 2, 8)).astype(np.float32)
    v = RNG.standard_normal((1, 12, 2, 8)).astype(np.float32)
    got = attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), sliding_window=4)
    want = _ref_attention(q, k, v, window=4)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_cross_entropy_matches_numpy():
    logits = RNG.standard_normal((2, 6, 32)).astype(np.float32)
    targets = RNG.integers(0, 32, (2, 6))
    mean, per_tok = cross_entropy_loss(jnp.asarray(logits), jnp.asarray(targets))
    lse = np.log(np.exp(logits).sum(-1))
    want = lse - np.take_along_axis(logits, targets[..., None], -1)[..., 0]
    np.testing.assert_allclose(per_tok, want, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(mean, want.mean(), rtol=1e-5)


def test_cross_entropy_label_smoothing_and_mask():
    logits = RNG.standard_normal((1, 4, 16)).astype(np.float32)
    targets = RNG.integers(0, 16, (1, 4))
    mask = np.array([[1, 1, 0, 1]], np.float32)
    eps = 0.1
    mean, per_tok = cross_entropy_loss(
        jnp.asarray(logits), jnp.asarray(targets),
        loss_mask=jnp.asarray(mask), label_smoothing=eps)
    lse = np.log(np.exp(logits).sum(-1))
    tl = np.take_along_axis(logits, targets[..., None], -1)[..., 0]
    want = lse - (1 - eps) * tl - eps * logits.mean(-1)
    np.testing.assert_allclose(per_tok, want, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(mean, (want * mask).sum() / mask.sum(), rtol=1e-5)

"""Paged-KV subsystem units (inference/paging/ + the paged kernel).

Pool/radix/scheduler tests are pure host bookkeeping (no compiles);
the kernel test runs the Pallas paged flash-decode in interpret mode
against a gather + masked-softmax reference. Engine-level parity lives
in tests/test_serving_engine.py (the serving matrix).
"""

import numpy as np
import pytest

from megatron_tpu.inference.paging.pool import SCRATCH_PAGE, PagePool
from megatron_tpu.inference.paging.radix import RadixPrefixCache
from megatron_tpu.inference.paging.scheduler import (
    ChunkedPrefillQueue, PrefillTask,
)

# ---------------------------------------------------------------------------
# page pool


def test_pool_alloc_release_refcount():
    pool = PagePool(6)  # pages 1..5 usable
    assert pool.free_pages == 5 and pool.used_pages == 0
    a = pool.alloc(2)
    assert len(a) == 2 and all(p != SCRATCH_PAGE for p in a)
    assert pool.free_pages == 3 and pool.used_pages == 2
    pool.retain(a)  # second holder
    assert pool.release(a) == 0  # refs drop 2 -> 1, nothing freed
    assert pool.release(a) == 2  # 1 -> 0: both return
    assert pool.free_pages == 5


def test_pool_alloc_all_or_nothing():
    pool = PagePool(4)
    assert pool.alloc(5) is None  # over-ask leaks nothing
    assert pool.free_pages == 3
    assert pool.alloc(3) is not None
    assert pool.alloc(1) is None


def test_pool_misuse_raises():
    pool = PagePool(4)
    (p,) = pool.alloc(1)
    pool.release([p])
    with pytest.raises(ValueError):
        pool.release([p])  # double release
    with pytest.raises(ValueError):
        pool.retain([p])  # retain of a free page
    with pytest.raises(ValueError):
        PagePool(1)  # no room beyond the scratch page
    # scratch page is never tracked
    pool.retain([SCRATCH_PAGE])
    pool.release([SCRATCH_PAGE])


# ---------------------------------------------------------------------------
# radix prefix cache


def _cache(ps=4, pages=32):
    pool = PagePool(pages)
    return pool, RadixPrefixCache(pool, ps)


def test_radix_insert_lookup_longest_prefix():
    pool, cache = _cache()
    toks = list(range(10, 22))  # 12 tokens = 3 full pages
    pages = pool.alloc(3)
    lps = [float(-t) for t in range(1, 12)]  # scores tokens 1..11
    assert cache.insert(toks, pages, lps) == 3
    # full match
    hit, hlps = cache.lookup(toks)
    assert hit == pages
    np.testing.assert_allclose(np.concatenate(hlps), lps)
    # partial match: first 8 tokens shared, then diverges
    hit, _ = cache.lookup(toks[:8] + [99, 98, 97, 96])
    assert hit == pages[:2]
    # sub-page tails never match
    hit, _ = cache.lookup(toks[:6])
    assert hit == pages[:1]
    assert cache.lookup([1, 2, 3, 4])[0] == []


def test_radix_insert_skips_existing_nodes():
    pool, cache = _cache()
    toks = list(range(8))
    pages = pool.alloc(2)
    cache.insert(toks, pages, [0.0] * 7)
    dup = pool.alloc(2)  # a second slot recomputed the same prefix
    assert cache.insert(toks, dup, [0.0] * 7) == 0  # existing copy wins
    assert cache.lookup(toks)[0] == pages
    assert pool.refcount(pages[0]) == 2  # alloc + cache
    assert pool.refcount(dup[0]) == 1  # duplicate stays slot-private


def test_radix_evict_lru_leaves_only():
    pool, cache = _cache()
    old = list(range(8))
    new = list(range(100, 108))
    p_old, p_new = pool.alloc(2), pool.alloc(2)
    cache.insert(old, p_old, [0.0] * 7)
    cache.insert(new, p_new, [0.0] * 7)
    pool.release(p_old)
    pool.release(p_new)  # cache is now the only holder
    cache.lookup(new)  # touch: `new` is most-recently-used
    assert cache.evict(2) == 2
    assert cache.lookup(old)[0] == []  # LRU path died first
    assert cache.lookup(new)[0] == p_new


def test_radix_evict_spares_pages_slots_still_reference():
    pool, cache = _cache()
    toks = list(range(8))
    pages = pool.alloc(2)  # the "slot's" references
    cache.insert(toks, pages, [0.0] * 7)
    assert cache.evict(2) == 0  # refcount 2: not evictable
    pool.release(pages)
    assert cache.evict(2) == 2  # now cache-only -> evictable
    assert pool.free_pages == pool.num_pages - 1


def test_radix_clear_releases_everything():
    pool, cache = _cache()
    pages = pool.alloc(3)
    cache.insert(list(range(12)), pages, [0.0] * 11)
    pool.release(pages)
    assert cache.clear() == 3
    assert len(cache) == 0 and pool.free_pages == pool.num_pages - 1


# ---------------------------------------------------------------------------
# chunked-prefill queue


def test_prefill_queue_fifo_and_advance():
    q = ChunkedPrefillQueue(chunk=4)
    t1 = PrefillTask(slot=0, tokens=np.arange(10, dtype=np.int32),
                     start=0, off=0)
    t2 = PrefillTask(slot=1, tokens=np.arange(6, dtype=np.int32),
                     start=0, off=0)
    q.add(t1)
    q.add(t2)
    assert q.slots == {0, 1}
    assert q.peek() is t1  # oldest incomplete first
    assert not q.advance(t1, 4)
    assert q.peek() is t1  # still t1 until it completes
    assert not q.advance(t1, 4)
    assert q.advance(t1, 2)  # 10/10 done, removed
    assert q.peek() is t2
    assert q.advance(t2, 6)
    assert q.peek() is None and len(q) == 0


def test_prefill_queue_drop_slot_and_validation():
    q = ChunkedPrefillQueue(chunk=4)
    t = PrefillTask(slot=3, tokens=np.arange(8, dtype=np.int32),
                    start=2, off=0)
    q.add(t)
    assert t.off == 2  # add() rewinds off to start
    assert q.drop_slot(3) is t
    assert q.drop_slot(3) is None
    with pytest.raises(ValueError):
        # a fully-cached prompt must leave >= 1 position to recompute
        q.add(PrefillTask(slot=0, tokens=np.arange(4, dtype=np.int32),
                          start=4, off=0))
    with pytest.raises(ValueError):
        ChunkedPrefillQueue(chunk=0)


# ---------------------------------------------------------------------------
# paged flash-decode kernel (interpret mode on CPU)


def test_paged_flash_decode_matches_gather_reference():
    """Page-table KV gather inside the Pallas grid vs a dense gather +
    masked softmax: GQA, per-row prefix lengths, scratch-mapped entries,
    sliding window."""
    import jax.numpy as jnp

    from megatron_tpu.ops.pallas.paged_flash_decode import paged_flash_decode

    rng = np.random.default_rng(0)
    B, P, ps, Hq, Hkv, D = 3, 9, 8, 4, 2, 16
    max_pages = 4
    q = jnp.asarray(rng.standard_normal((B, 1, Hq, D)), jnp.float32)
    kp = jnp.asarray(rng.standard_normal((P, ps, Hkv, D)), jnp.float32)
    vp = jnp.asarray(rng.standard_normal((P, ps, Hkv, D)), jnp.float32)
    table = rng.integers(1, P, (B, max_pages)).astype(np.int32)
    table[0, 1:] = 0  # unallocated entries point at scratch
    lens = np.asarray([1, 17, 32], np.int32)

    def ref(window=None):
        k = np.asarray(kp)[table].reshape(B, -1, Hkv, D)
        v = np.asarray(vp)[table].reshape(B, -1, Hkv, D)
        qg = (np.asarray(q, np.float64) / np.sqrt(D)).reshape(
            B, 1, Hkv, Hq // Hkv, D)
        s = np.einsum("bqhgd,bkhd->bhgqk", qg, k.astype(np.float64))
        k_pos = np.arange(max_pages * ps)[None, :]
        allowed = k_pos < lens[:, None]
        if window is not None:
            allowed &= k_pos >= lens[:, None] - window
        s = np.where(allowed[:, None, None, None, :], s, -np.inf)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        o = np.einsum("bhgqk,bkhd->bqhgd", p, v.astype(np.float64))
        return o.reshape(B, 1, Hq, D)

    out = paged_flash_decode(q, kp, vp, jnp.asarray(table),
                             jnp.asarray(lens))
    np.testing.assert_allclose(np.asarray(out), ref(), atol=2e-6)
    out_w = paged_flash_decode(q, kp, vp, jnp.asarray(table),
                               jnp.asarray(lens), sliding_window=8)
    np.testing.assert_allclose(np.asarray(out_w), ref(window=8), atol=2e-6)


def test_paged_flash_decode_rejects_bad_shapes():
    import jax.numpy as jnp

    from megatron_tpu.ops.pallas.paged_flash_decode import paged_flash_decode

    q = jnp.zeros((2, 1, 4, 8))
    kp = jnp.zeros((4, 8, 2, 8))
    table = jnp.zeros((2, 2), jnp.int32)
    lens = jnp.zeros((2,), jnp.int32)
    with pytest.raises(ValueError, match="single-token"):
        paged_flash_decode(jnp.zeros((2, 3, 4, 8)), kp, kp, table, lens)
    with pytest.raises(ValueError, match="multiple of 8"):
        paged_flash_decode(q, jnp.zeros((4, 6, 2, 8)),
                           jnp.zeros((4, 6, 2, 8)), table, lens)
    with pytest.raises(ValueError, match="rows"):
        paged_flash_decode(q, kp, kp, jnp.zeros((3, 2), jnp.int32), lens)


def test_attention_page_table_gather_matches_dense():
    """attention(page_table=...) on CPU gathers pages into the identical
    dense view: single-token decode (kv_lengths) and chunked prefill
    (causal + q_offset) both match the dense cache bit-for-bit."""
    import jax.numpy as jnp

    from megatron_tpu.ops.attention import attention

    rng = np.random.default_rng(1)
    B, P, ps, H, D = 2, 7, 4, 2, 8
    max_pages = 3
    kp = jnp.asarray(rng.standard_normal((P, ps, H, D)), jnp.float32)
    vp = jnp.asarray(rng.standard_normal((P, ps, H, D)), jnp.float32)
    table = jnp.asarray(rng.integers(1, P, (B, max_pages)), jnp.int32)
    dense_k = kp[table].reshape(B, -1, H, D)
    dense_v = vp[table].reshape(B, -1, H, D)

    # decode shape
    q = jnp.asarray(rng.standard_normal((B, 1, H, D)), jnp.float32)
    lens = jnp.asarray([3, 12], jnp.int32)
    got = attention(q, kp, vp, kv_lengths=lens, page_table=table)
    want = attention(q, dense_k, dense_v, kv_lengths=lens)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    # chunked-prefill shape (batch 1, causal with offset)
    qc = jnp.asarray(rng.standard_normal((1, 4, H, D)), jnp.float32)
    got = attention(qc, kp, vp, mask_type="causal", q_offset=5,
                    page_table=table[:1])
    want = attention(qc, dense_k[:1], dense_v[:1], mask_type="causal",
                     q_offset=5)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


# ---------------------------------------------------------------------------
# sliding-window page release (ROADMAP item 1, Mistral)


def test_sliding_window_release_parity_and_accounting():  # ~5s measured
    """Pages fully behind the attention window return to the pool while
    the request still decodes — token-identical to the slot engine
    (masked positions contribute exactly nothing, so reading the
    scratch page in their place changes no value), with honest pool
    accounting: released pages are re-allocatable, radix-held prompt
    pages survive for future prefix hits, and a drained engine holds
    only the radix references."""
    import jax

    from megatron_tpu.inference.engine import InferenceEngine
    from megatron_tpu.inference.paging import PagedInferenceEngine
    from megatron_tpu.models import presets
    from megatron_tpu.models.params import init_params

    cfg = presets.tiny(vocab_size=64, seq_length=128, num_layers=2,
                       sliding_window_size=16)
    params = init_params(cfg, jax.random.PRNGKey(0))
    slot = InferenceEngine(cfg, params, num_slots=2, max_seq_len=128)
    paged = PagedInferenceEngine(cfg, params, num_slots=2,
                                 max_seq_len=128, page_size=8,
                                 prefill_chunk=16)
    rng = np.random.default_rng(0)
    prompts = rng.integers(1, 64, (2, 12)).astype(np.int32)
    lengths = np.full((2,), 12, np.int32)
    a = slot.generate(prompts, lengths, max_new_tokens=60)
    b = paged.generate(prompts, lengths, max_new_tokens=60)
    np.testing.assert_array_equal(a.tokens, b.tokens)
    np.testing.assert_allclose(a.logprobs, b.logprobs, atol=1e-5)
    # sequences reached length 72 with window 16: pages behind the
    # window were freed DURING decode, not just at retirement
    assert paged.stats["window_pages_released"] > 0
    assert paged.stats["decode_recompiles"] == 0
    # drained: only the radix prefix cache still references pages (one
    # full 8-token page per 12-token prompt)
    held = [p for p in range(1, paged.num_pages)
            if paged.pool.refcount(p) > 0]
    assert len(held) == 2, held
    assert (paged.pool.free_pages
            == paged.num_pages - 1 - len(held))
    # the freed pages are genuinely reusable: the same traffic drains
    # again (prefix hits alias the surviving radix pages)
    hits0 = paged.stats["prefix_hits"]
    b2 = paged.generate(prompts, lengths, max_new_tokens=60)
    np.testing.assert_array_equal(a.tokens, b2.tokens)
    assert paged.stats["prefix_hits"] > hits0


def test_window_release_noop_without_window():
    """No sliding window configured => the release pass never runs and
    the counter stays zero (the pre-existing lifetime story holds)."""
    import jax

    from megatron_tpu.inference.paging import PagedInferenceEngine
    from megatron_tpu.models import presets
    from megatron_tpu.models.params import init_params

    cfg = presets.tiny(vocab_size=64, seq_length=64, num_layers=2)
    params = init_params(cfg, jax.random.PRNGKey(0))
    paged = PagedInferenceEngine(cfg, params, num_slots=1,
                                 max_seq_len=64, page_size=8,
                                 prefill_chunk=16)
    prompts = np.arange(1, 9, dtype=np.int32)[None]
    paged.generate(prompts, np.array([8], np.int32), max_new_tokens=20)
    assert paged.stats["window_pages_released"] == 0


# ---------------------------------------------------------------------------
# engine sizing / rejection edges (host-only where possible)


def test_paged_engine_rejects_undersized_pool():
    import jax

    from megatron_tpu.inference.paging import PagedInferenceEngine
    from megatron_tpu.models import presets
    from megatron_tpu.models.params import init_params

    cfg = presets.tiny(vocab_size=64, seq_length=64)
    params = init_params(cfg, jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="cannot hold even one"):
        PagedInferenceEngine(cfg, params, num_slots=2, max_seq_len=64,
                             page_size=8, num_pages=4)
    with pytest.raises(ValueError, match="num_pages"):
        PagedInferenceEngine(cfg, params, num_slots=1, max_seq_len=64,
                             page_size=8, num_pages=1)
    with pytest.raises(ValueError, match="page_size"):
        PagedInferenceEngine(cfg, params, num_slots=1, max_seq_len=64,
                             page_size=0)

"""Ulysses (all-to-all) context parallelism vs dense attention
(beyond reference parity, like ring attention)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_tpu.config import ParallelConfig
from megatron_tpu.ops.attention import attention
from megatron_tpu.ops.ulysses import ulysses_attention_sharded
from megatron_tpu.parallel.mesh import build_mesh

RNG = np.random.default_rng(11)


def _qkv(s=32, hq=8, hkv=4, d=16):
    q = jnp.asarray(RNG.standard_normal((2, s, hq, d)), jnp.float32)
    k = jnp.asarray(RNG.standard_normal((2, s, hkv, d)), jnp.float32)
    v = jnp.asarray(RNG.standard_normal((2, s, hkv, d)), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("cp", [2, 4])
@pytest.mark.parametrize("mask_type,window", [
    ("causal", None), ("causal", 8), ("bidirectional", None)])
def test_ulysses_matches_dense(cp, mask_type, window):
    rt = build_mesh(ParallelConfig(context_parallel=cp))
    q, k, v = _qkv()
    want = attention(q, k, v, mask_type=mask_type, sliding_window=window)
    with jax.sharding.set_mesh(rt.mesh):
        got = jax.jit(lambda q, k, v: ulysses_attention_sharded(
            q, k, v, rt.mesh, mask_type=mask_type,
            sliding_window=window))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_ulysses_grads_match_dense():
    rt = build_mesh(ParallelConfig(context_parallel=2))
    q, k, v = _qkv()

    def f_u(q, k, v):
        return jnp.sum(jnp.square(ulysses_attention_sharded(q, k, v, rt.mesh)))

    def f_d(q, k, v):
        return jnp.sum(jnp.square(attention(q, k, v)))

    with jax.sharding.set_mesh(rt.mesh):
        gu = jax.jit(jax.grad(f_u, argnums=(0, 1, 2)))(q, k, v)
    gd = jax.grad(f_d, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gu, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_model_forward_with_ulysses_impl():
    """attention(impl='ulysses') through the model dispatch under a
    context mesh."""
    from megatron_tpu.models import presets
    from megatron_tpu.models.language_model import lm_loss
    from megatron_tpu.models.params import init_params, param_specs
    from megatron_tpu.parallel.sharding import shard_tree

    cfg = presets.tiny(vocab_size=64, seq_length=32, num_layers=2,
                       hidden_size=32, num_attention_heads=4, num_kv_heads=2,
                       ffn_hidden_size=64)
    import dataclasses

    cfg_u = dataclasses.replace(cfg, attention_impl="ulysses")
    params = init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    batch = {"tokens": jnp.asarray(rng.integers(0, 64, (2, 32)), jnp.int32),
             "labels": jnp.asarray(rng.integers(0, 64, (2, 32)), jnp.int32)}
    l_ref = float(lm_loss(cfg, params, batch)[0])
    rt = build_mesh(ParallelConfig(context_parallel=2))
    sp = shard_tree(rt, params, param_specs(cfg_u))
    with jax.sharding.set_mesh(rt.mesh):
        l_u = float(jax.jit(lambda p, b: lm_loss(cfg_u, p, b)[0])(sp, batch))
    np.testing.assert_allclose(l_ref, l_u, rtol=1e-5)


def test_ulysses_rejects_indivisible_heads():
    rt = build_mesh(ParallelConfig(context_parallel=4))
    q, k, v = _qkv(hq=8, hkv=2)  # hkv=2 not divisible by cp=4
    with jax.sharding.set_mesh(rt.mesh):
        with pytest.raises(ValueError, match="ulysses"):
            ulysses_attention_sharded(q, k, v, rt.mesh)

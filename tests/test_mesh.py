"""Topology tests (counterpart of reference tests/test_parallel_state.py)."""

import jax
import numpy as np
import pytest

from megatron_tpu.config import ParallelConfig
from megatron_tpu.parallel.mesh import MESH_AXES, build_mesh
from megatron_tpu.parallel.sharding import zero1_spec
from jax.sharding import PartitionSpec as P


def test_eight_fake_devices():
    assert len(jax.devices()) == 8


@pytest.mark.parametrize("tp,pp,cp,dp", [
    (2, 2, 1, 2), (4, 1, 1, 2), (1, 4, 1, 2), (2, 1, 2, 2), (8, 1, 1, 1), (1, 1, 1, 8),
])
def test_mesh_shapes(tp, pp, cp, dp):
    rt = build_mesh(ParallelConfig(tensor_parallel=tp, pipeline_parallel=pp,
                                   context_parallel=cp))
    assert rt.mesh.axis_names == MESH_AXES
    assert rt.mesh.shape["tensor"] == tp
    assert rt.mesh.shape["pipe"] == pp
    assert rt.mesh.shape["context"] == cp
    assert rt.dp == dp


def test_tensor_axis_innermost():
    """TP must map to adjacent device ids (the reference's
    TP-innermost-contiguous layout, parallel_state.py:68-82)."""
    rt = build_mesh(ParallelConfig(tensor_parallel=4))
    ids = np.vectorize(lambda d: d.id)(rt.mesh.devices)
    # within one tp group, device ids are consecutive
    first_group = ids[0, 0, 0, 0, :]
    assert list(first_group) == list(range(first_group[0], first_group[0] + 4))


def test_invalid_topology():
    with pytest.raises(ValueError):
        build_mesh(ParallelConfig(tensor_parallel=3))


def test_data_parallel_mismatch():
    with pytest.raises(ValueError):
        build_mesh(ParallelConfig(tensor_parallel=2, data_parallel=8))


def test_zero1_spec():
    # first unsharded divisible dim picks up the batch (data+expert) axes
    s = zero1_spec(P(None, "tensor"), (64, 128), dp=4)
    assert s == P(("data", "expert"), "tensor")
    s = zero1_spec(P("pipe", None, "tensor"), (2, 64, 128), dp=4)
    assert s == P("pipe", ("data", "expert"), "tensor")
    # indivisible dims stay replicated
    s = zero1_spec(P(None), (63,), dp=4)
    assert s == P(None)
    # dp=1 is a no-op
    assert zero1_spec(P(None, "tensor"), (64, 128), dp=1) == P(None, "tensor")
    # expert-sharded MoE weights: state shards over bare data (dp/ep)
    s = zero1_spec(P("pipe", "expert", None, "tensor"), (2, 8, 64, 128),
                   dp=4, ep=2)
    assert s == P("pipe", "expert", "data", "tensor")
    # already data-sharded: unchanged
    s = zero1_spec(P(("data", "expert"), None), (8, 64), dp=4, ep=2)
    assert s == P(("data", "expert"), None)

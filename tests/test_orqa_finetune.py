"""ORQA supervised retriever finetuning (counterparts: reference
tasks/orqa/supervised/{data.py,finetune.py,eval_utils.py} — untested
upstream)."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_tpu.models.biencoder import biencoder_config, biencoder_init_params
from tasks.orqa_finetune import (
    NQSupervisedDataset, load_dpr_json, normalize_question, orqa_loss,
)

CFG = biencoder_config(num_layers=2, hidden_size=32, num_attention_heads=4,
                       vocab_size=96, seq_length=24, params_dtype="float32",
                       hidden_dropout=0.0, attention_dropout=0.0)


def _tokenize(text):
    return [int(t) for t in text.split()]


def _dpr_rows(n, vocab=90, n_hard=3, n_simple=2, rng=None):
    """Learnable toy NQ: question and its positive context share a
    signature token; negatives use other samples' signatures."""
    rng = rng or np.random.default_rng(0)
    rows = []
    for i in range(n):
        sig = 10 + (i % 40)
        # the pair is fully determined by the signature token: retrieval is
        # learnable (and eval sigs are in-distribution with train sigs)
        mk = lambda s: {"title": "5", "text": f"{s} {s}"}
        rows.append({
            "question": f"{sig} {sig}?",
            "answers": [str(sig)],
            "positive_ctxs": [mk(sig)],
            "hard_negative_ctxs": [mk(10 + ((i + k + 1) % 40))
                                   for k in range(n_hard)],
            "negative_ctxs": [mk(10 + ((i + k + 7) % 40))
                              for k in range(n_simple)],
        })
    return rows


def test_load_dpr_json_and_normalize(tmp_path):
    rows = _dpr_rows(5)
    rows.append({"question": "no positive?", "answers": [],
                 "positive_ctxs": [], "hard_negative_ctxs": [],
                 "negative_ctxs": []})
    p = tmp_path / "nq.json"
    p.write_text(json.dumps(rows))
    samples = load_dpr_json(str(p))
    assert len(samples) == 5  # positive-less row dropped
    assert not samples[0]["question"].endswith("?")
    assert normalize_question("abc?") == "abc"
    assert normalize_question("abc") == "abc"


def test_dataset_shapes_and_determinism(tmp_path):
    samples = [dict(question=r["question"].rstrip("?"),
                    pos_context=r["positive_ctxs"][0],
                    hard_negative_context=r["hard_negative_ctxs"],
                    negative_context=r["negative_ctxs"],
                    answers=r["answers"]) for r in _dpr_rows(6)]
    train = NQSupervisedDataset(samples, _tokenize, 24, cls_id=1, sep_id=2,
                                pad_id=0, evaluate=False, num_neg=4)
    it = train[0]
    assert it["query_tokens"].shape == (24,)
    assert it["query_tokens"][0] == 1
    nq = int(it["query_pad_mask"].sum())
    assert it["query_tokens"][nq - 1] == 2
    # 3 hard + 2 simple pad-cycled to 4 static negative rows
    assert it["neg_context_tokens"].shape == (4, 24)
    assert int(it["neg_context_pad_mask"][:4].sum()) > 0
    np.testing.assert_array_equal(train[0]["neg_context_tokens"],
                                  it["neg_context_tokens"])
    # context = [CLS] title [SEP] text...
    assert it["context_tokens"][0] == 1 and it["context_tokens"][2] == 2

    ev = NQSupervisedDataset(samples, _tokenize, 24, cls_id=1, sep_id=2,
                             pad_id=0, evaluate=True, val_hard_neg=2,
                             val_other_neg=1)
    e = ev[0]
    assert e["neg_context_tokens"].shape == (3, 24)  # 1 simple + 2 hard

    # fewer negatives than requested -> all-pad filler rows
    short = NQSupervisedDataset(samples, _tokenize, 24, cls_id=1, sep_id=2,
                                pad_id=0, evaluate=False, num_neg=8)
    s = short[0]
    assert s["neg_context_tokens"].shape == (8, 24)
    assert int(s["neg_context_pad_mask"][5:].sum()) == 0


def _batch(samples, n, num_neg):
    ds = NQSupervisedDataset(samples, _tokenize, 24, cls_id=1, sep_id=2,
                             pad_id=0, evaluate=False, num_neg=num_neg)
    items = [ds[i] for i in range(n)]
    return {k: jnp.asarray(np.stack([it[k] for it in items]))
            for k in items[0]}


@pytest.mark.slow  # 24s measured cacheless (PR 4 tier-1 re-budget);
# test_orqa_eval_invariant_to_tail_padding keeps orqa coverage in tier-1
def test_orqa_loss_grads_and_neg_candidates():
    samples = [dict(question=r["question"].rstrip("?"),
                    pos_context=r["positive_ctxs"][0],
                    hard_negative_context=r["hard_negative_ctxs"],
                    negative_context=r["negative_ctxs"],
                    answers=r["answers"]) for r in _dpr_rows(4)]
    params = biencoder_init_params(CFG, jax.random.PRNGKey(0),
                                   ict_head_size=16)
    b0 = _batch(samples, 4, num_neg=0)
    loss0, aux0 = orqa_loss(CFG, params, b0, topk=(1, 2))
    assert np.isfinite(float(loss0))
    assert "top1_acc" in aux0 and "top2_acc" in aux0
    # negatives enlarge the candidate set -> loss can only grow at init
    b3 = _batch(samples, 4, num_neg=3)
    loss3, _ = orqa_loss(CFG, params, b3, topk=(1,))
    assert float(loss3) > float(loss0) - 1e-4
    g = jax.grad(lambda p: orqa_loss(CFG, p, b3)[0])(params)
    assert float(jnp.abs(g["query"]["ict_head"]["w"]).sum()) > 0
    assert float(jnp.abs(g["context"]["ict_head"]["w"]).sum()) > 0
    # score scaling changes the loss
    loss_s, _ = orqa_loss(CFG, params, b3, score_scaling=True)
    assert abs(float(loss_s) - float(loss3)) > 1e-6


def test_orqa_eval_invariant_to_tail_padding():
    """A non-divisible eval set must report the same stats as a divisible
    batching: padded rows' candidates are masked out of the score matrix
    (regression: duplicated row-0 candidates inflated ranks)."""
    import functools

    from megatron_tpu.config import (
        OptimizerConfig, ParallelConfig, RunConfig, TrainingConfig,
    )
    from megatron_tpu.models.biencoder import (
        biencoder_init_params, biencoder_param_specs,
    )
    from megatron_tpu.training.pretrain import TrainLoop
    from tasks.orqa_finetune import orqa_eval, orqa_loss

    samples = [dict(question=r["question"].rstrip("?"),
                    pos_context=r["positive_ctxs"][0],
                    hard_negative_context=r["hard_negative_ctxs"],
                    negative_context=r["negative_ctxs"],
                    answers=r["answers"]) for r in _dpr_rows(12)]
    valid = NQSupervisedDataset(samples, _tokenize, 24, cls_id=1, sep_id=2,
                                pad_id=0, evaluate=True, val_hard_neg=2,
                                val_other_neg=1)
    cfg = RunConfig(
        model=CFG, parallel=ParallelConfig(),
        optimizer=OptimizerConfig(lr=1e-3, lr_decay_style="constant"),
        training=TrainingConfig(micro_batch_size=1, global_batch_size=8,
                                train_iters=1))
    loop = TrainLoop(
        cfg, log=lambda s: None,
        init_params_fn=functools.partial(biencoder_init_params,
                                         ict_head_size=16),
        param_specs_fn=biencoder_param_specs,
        loss_fn=lambda m, p, b, k, sharder=None: orqa_loss(m, p, b),
        fixed_num_microbatches=1)
    padded = orqa_eval(loop, valid, batch=8, topk=(1, 5))
    # the 4-row tail is padded with copies of its row 0; with masking its
    # candidate set is exactly 4 pos + 12 negs, so 1-based ranks are <= 16.
    # Without masking the duplicated candidates push random-init ranks
    # toward the 32-candidate range (measured ~16.5 mean pre-fix).
    tail = NQSupervisedDataset(samples[8:], _tokenize, 24, cls_id=1,
                               sep_id=2, pad_id=0, evaluate=True,
                               val_hard_neg=2, val_other_neg=1)
    t = orqa_eval(loop, tail, batch=8, topk=(1, 5))
    assert t["rank"] <= 16.0
    # aggregation bookkeeping: full eval == sample-weighted head/tail evals
    head = NQSupervisedDataset(samples[:8], _tokenize, 24, cls_id=1,
                               sep_id=2, pad_id=0, evaluate=True,
                               val_hard_neg=2, val_other_neg=1)
    h = orqa_eval(loop, head, batch=8, topk=(1, 5))
    np.testing.assert_allclose(padded["rank"],
                               (8 * h["rank"] + 4 * t["rank"]) / 12,
                               rtol=1e-6)
    for k in ("top1_acc", "top5_acc"):
        np.testing.assert_allclose(padded[k], (8 * h[k] + 4 * t[k]) / 12,
                                   atol=1e-9)


@pytest.mark.slow
def test_orqa_harness_end_to_end(tmp_path):
    """tasks.main RET-FINETUNE-NQ on toy DPR data: runs, evals, learns
    in-batch retrieval above chance. ~85s of finetune iterations —
    multi-minute, deselectable with -m 'not slow' (conftest marker doc)."""
    from tasks import main as tasks_main

    train = tmp_path / "train.json"
    dev = tmp_path / "dev.json"
    train.write_text(json.dumps(_dpr_rows(64)))
    dev.write_text(json.dumps(_dpr_rows(16, rng=np.random.default_rng(7))))

    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        tasks_main.main([
            "--task", "RET-FINETUNE-NQ", "--train_data", str(train),
            "--valid_data", str(dev), "--epochs", "60",
            "--num_layers", "2", "--hidden_size", "32",
            "--num_attention_heads", "4", "--seq_length", "64",
            "--retriever_seq_length", "24",
            "--vocab_size", "128", "--tokenizer_type", "null",
            "--micro_batch_size", "1", "--global_batch_size", "8",
            "--lr", "1e-2", "--weight_decay", "0.0",
            "--lr_decay_style", "constant",
            "--log_interval", "120", "--ict_head_size", "16",
            "--train_with_neg", "--train_hard_neg", "2",
            "--val_av_rank_hard_neg", "3", "--val_av_rank_other_neg", "2",
            "--retriever_report_topk_accuracies", "1", "5",
            "--cls_token_id", "1", "--sep_token_id", "2", "--pad_token_id", "0",
        ])
    out = buf.getvalue()
    assert "rank" in out and "top1_acc" in out
    # measured at this config: top1 50%, top5 75%, mean rank 4.9 of 48
    # (accuracies reported in percent, the reference convention)
    top1 = float(out.rsplit("top1_acc = ", 1)[1].split()[0])
    top5 = float(out.rsplit("top5_acc = ", 1)[1].split()[0])
    rank = float(out.rsplit("rank = ", 1)[1].split()[0])
    assert top1 > 100.0 / 8   # uniform over the 48-candidate set is 100/48
    assert top5 > 100.0 / 4
    assert rank < 15        # random mean rank is ~24.5

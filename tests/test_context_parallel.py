"""Context-parallel serving (inference/context_parallel/): striped page
pool units, compressed ring-permute transport, and the engine parity
gates — greedy traffic through the CP engine (chunked distributed
prefill, sequence-striped paged KV, ring-attention decode) must be
token-identical to the dense single-host engine, with logprob parity
and zero decode recompiles after warmup, through radix prefix hits and
mid-prefill preempt/resume."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_tpu.config import ParallelConfig
from megatron_tpu.inference.context_parallel import (
    ContextParallelEngine, StripedPagePool,
)
from megatron_tpu.inference.engine import InferenceEngine, Request
from megatron_tpu.inference.paging.pool import SCRATCH_PAGE
from megatron_tpu.models import presets
from megatron_tpu.models.params import init_params, param_specs
from megatron_tpu.parallel.mesh import build_mesh
from megatron_tpu.parallel.sharding import shard_tree
from megatron_tpu.quant.collectives import (
    cp_ring_comm_bytes, make_cp_comm, ring_permute,
)

CFG = presets.tiny(vocab_size=64, seq_length=64)
PARAMS = init_params(CFG, jax.random.PRNGKey(0))


# ---------------------------------------------------------------------------
# striped page pool


def test_striped_pool_ownership_and_striping():
    # 8 pages over cp=2: rank 0 owns 1..3 (0 is scratch), rank 1 owns 4..7
    pool = StripedPagePool(8, 2)
    assert pool.pages_per_rank == 4
    assert pool.free_pages_by_rank() == [3, 4]
    pages = pool.alloc(4)  # logical 0..3 -> ranks 0,1,0,1
    assert [pool.owner(p) for p in pages] == [0, 1, 0, 1]
    assert pool.free_pages_by_rank() == [1, 2]
    # logical_start continues the stripe mid-sequence
    more = pool.alloc(2, logical_start=4)  # logical 4,5 -> ranks 0,1
    assert [pool.owner(p) for p in more] == [0, 1]


def test_striped_pool_all_or_nothing_per_rank():
    pool = StripedPagePool(8, 2)
    # rank 0 has 3 usable pages: an alloc needing 4 even-logical pages
    # must fail WITHOUT draining rank 1
    assert pool.alloc(7) is None
    assert pool.free_pages_by_rank() == [3, 4]
    # 6 logical pages = 3 per rank fits exactly
    pages = pool.alloc(6)
    assert pages is not None
    assert pool.free_pages_by_rank() == [0, 1]
    # release returns each page to its owner's free list
    pool.release(pages)
    assert pool.free_pages_by_rank() == [3, 4]


def test_striped_pool_misuse_raises():
    pool = StripedPagePool(8, 2)
    with pytest.raises(ValueError):
        StripedPagePool(9, 2)  # not divisible by cp
    (p,) = pool.alloc(1)
    pool.release([p])
    with pytest.raises(ValueError):
        pool.release([p])  # double release
    # scratch page is never tracked
    pool.retain([SCRATCH_PAGE])
    pool.release([SCRATCH_PAGE])


# ---------------------------------------------------------------------------
# ring transport + byte model


def test_ring_permute_dense_and_int8():
    from jax.sharding import PartitionSpec as P

    rt = build_mesh(ParallelConfig(context_parallel=2),
                    devices=jax.devices()[:2])
    perm = [(0, 1), (1, 0)]
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((2, 4, 32)), jnp.float32)

    def run(mode):
        body = lambda s: ring_permute(s, "context", perm, mode=mode,  # noqa: E731
                                      chunk=16)
        return jax.shard_map(body, mesh=rt.mesh, in_specs=(P("context"),),
                             out_specs=P("context"), axis_names={"context"},
                             check_vma=False)(x)

    want = jnp.roll(x, 1, axis=0)  # shard r receives shard r-1's rows
    np.testing.assert_array_equal(np.asarray(run("dense")), np.asarray(want))
    got = np.asarray(run("int8"))
    # per-chunk symmetric int8: bounded roundtrip error, not identity
    err = np.max(np.abs(got - np.asarray(want)))
    assert 0 < err <= np.max(np.abs(np.asarray(x))) / 127 + 1e-6
    # the wire really moves int8 payloads
    body = lambda s: ring_permute(s, "context", perm, mode="int8")  # noqa: E731
    fn = jax.shard_map(body, mesh=rt.mesh, in_specs=(P("context"),),
                       out_specs=P("context"), axis_names={"context"},
                       check_vma=False)
    assert "i8[" in str(jax.make_jaxpr(fn)(x))


def test_cp_ring_byte_model():
    rt = build_mesh(ParallelConfig(context_parallel=2),
                    devices=jax.devices()[:2])
    dense = make_cp_comm(rt.mesh, "dense", cfg=CFG)
    int8 = make_cp_comm(rt.mesh, "int8", cfg=CFG)
    b_dense = cp_ring_comm_bytes(CFG, dense, 2, 1)
    b_int8 = cp_ring_comm_bytes(CFG, int8, 2, 1)
    assert b_dense["dense"] == b_dense["compressed"]
    assert b_int8["dense"] == b_dense["dense"]
    assert 0 < b_int8["compressed"] < b_int8["dense"]
    # the policy can pin cp_ring dense: byte model collapses to dense
    gated = make_cp_comm(rt.mesh, "int8", cfg=CFG,
                         policy={"cp_ring": False})
    assert not gated.compresses() and gated.wire_mode() == "dense"
    b_gated = cp_ring_comm_bytes(CFG, gated, 2, 1)
    assert b_gated["compressed"] == b_gated["dense"]
    # cp=1 / mode none build no transport
    solo = build_mesh(ParallelConfig(), devices=jax.devices()[:1])
    assert make_cp_comm(solo.mesh, "int8", cfg=CFG) is None
    assert make_cp_comm(rt.mesh, "none", cfg=CFG) is None


# ---------------------------------------------------------------------------
# engine parity gates (real tiny model, cp=2 mesh)


@pytest.fixture(scope="module")
def cp_setup():
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 (fake) devices")
    rt = build_mesh(ParallelConfig(context_parallel=2),
                    devices=jax.devices()[:2])
    sparams = shard_tree(rt, PARAMS, param_specs(CFG))
    dense = InferenceEngine(CFG, PARAMS, num_slots=2, max_seq_len=64)
    cpe = ContextParallelEngine(CFG, sparams, num_slots=2, max_seq_len=64,
                                page_size=8, prefill_chunk=8, mesh=rt.mesh)
    return rt, dense, cpe


def _req(prompt, n=6):
    return Request(prompt=np.asarray(prompt, np.int32), max_new_tokens=n)


def _run(eng, prompt, n=6):
    req = eng.submit(_req(prompt, n))
    eng.run_until_idle()
    assert req.error is None, req.error
    return req


def test_cp_parity_multichunk_ragged(cp_setup):
    """A 13-token prompt: 2 chunks, neither aligned to page_size * cp —
    the ragged tail crosses a shard boundary mid-page. Token-identical
    with full logprob parity."""
    _, dense, cpe = cp_setup
    prompts = np.asarray([[3, 7, 11, 2, 9, 4, 1, 8, 5, 6, 2, 3, 7]],
                         np.int32)
    lengths = np.asarray([13], np.int32)
    a = dense.generate(prompts, lengths, max_new_tokens=8, temperature=0.0)
    b = cpe.generate(prompts, lengths, max_new_tokens=8, temperature=0.0)
    np.testing.assert_array_equal(a.tokens, b.tokens)
    np.testing.assert_allclose(a.logprobs, b.logprobs, rtol=1e-5, atol=1e-5)
    assert cpe.stats["cp_ring_steps"] > 0


def test_cp_parity_radix_hit_mid_shard(cp_setup):
    """Two requests sharing a 3-page (24-token) prefix: the second
    aliases cached pages whose stripe ends mid-shard (page 3 of the
    follow-up starts on rank 1). Exactness must survive the alias."""
    _, dense, cpe = cp_setup
    prefix = list(range(5, 29))  # 24 tokens = 3 full pages
    tail_a, tail_b = [30, 31], [40, 41, 42]
    _run(cpe, prefix + tail_a)
    hits0 = cpe.stats["prefix_hits"]
    got = _run(cpe, prefix + tail_b)
    assert cpe.stats["prefix_hits"] > hits0
    want = _run(dense, prefix + tail_b)
    assert got.generated == want.generated
    np.testing.assert_allclose(got.logprobs, want.logprobs,
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(got.prompt_logprobs, want.prompt_logprobs,
                               rtol=1e-5, atol=1e-5)


def test_cp_parity_preempt_resume_mid_prefill(cp_setup):
    """Preempt a CP request while its chunked prefill is mid-flight: the
    resume recomputes through the striped pools and must finish with
    exactly the tokens it would have produced without the preemption."""
    _, dense, cpe = cp_setup
    prompt = [int(t) for t in
              np.random.default_rng(7).integers(1, 64, 40)]
    req = cpe.submit(_req(prompt, 6))
    cpe.step()  # admit + first chunk
    cpe.step()  # second chunk (prompt is 5 chunks of 8)
    assert cpe.prefill_queue.peek() is not None  # mid-prefill
    pre0 = cpe.stats["preemptions"]
    assert cpe._preempt_one()
    assert cpe.stats["preemptions"] == pre0 + 1
    cpe.run_until_idle()
    assert req.error is None, req.error
    want = _run(dense, prompt, 6)
    assert req.generated == want.generated
    np.testing.assert_allclose(req.logprobs, want.logprobs,
                               rtol=1e-5, atol=1e-5)


def test_cp_zero_decode_recompiles_after_warmup(cp_setup):
    """Order-dependent on the parity tests above having driven real
    traffic: the decode step must have compiled exactly once."""
    _, _, cpe = cp_setup
    assert cpe.stats["decode_recompiles"] == 0


# ---------------------------------------------------------------------------
# construction validation + host-side table building


def test_cp_engine_rejects_bad_geometry(cp_setup):
    rt, _, _ = cp_setup
    with pytest.raises(ValueError, match="requires a mesh"):
        ContextParallelEngine(CFG, PARAMS, mesh=None)
    solo = build_mesh(ParallelConfig(), devices=jax.devices()[:1])
    with pytest.raises(ValueError, match="cp == 1"):
        ContextParallelEngine(CFG, PARAMS, mesh=solo.mesh)
    with pytest.raises(ValueError, match="ring transport"):
        ContextParallelEngine(CFG, PARAMS, mesh=rt.mesh,
                              max_seq_len=64, cp_collectives="none")


def test_cp_engine_rounds_pool_to_cp_multiple(cp_setup):
    rt, _, _ = cp_setup
    sparams = shard_tree(rt, PARAMS, param_specs(CFG))
    eng = ContextParallelEngine(CFG, sparams, num_slots=2, max_seq_len=64,
                                page_size=8, prefill_chunk=8, mesh=rt.mesh,
                                num_pages=11)
    assert eng.num_pages == 12 and eng.pool.pages_per_rank == 6


def test_make_cp_comm_2d_validation():
    if len(jax.devices()) < 4:
        pytest.skip("needs >= 4 (fake) devices")
    rt4 = build_mesh(ParallelConfig(context_parallel=4),
                     devices=jax.devices()[:4])
    with pytest.raises(ValueError, match="geometry must be one of"):
        make_cp_comm(rt4.mesh, "dense", cfg=CFG, geometry="3d")
    with pytest.raises(ValueError, match="subgroup .cp_head. >= 2"):
        make_cp_comm(rt4.mesh, "dense", cfg=CFG, geometry="2d", subgroup=0)
    with pytest.raises(ValueError, match="does not divide"):
        make_cp_comm(rt4.mesh, "dense", cfg=CFG, geometry="2d", subgroup=3)
    # the head all-to-all hands each member heads/subgroup heads — a
    # head count the subgroup doesn't divide fails at build
    cfg_h2 = presets.tiny(vocab_size=64, seq_length=64,
                          num_attention_heads=2, num_kv_heads=2)
    with pytest.raises(ValueError, match="head count"):
        make_cp_comm(rt4.mesh, "dense", cfg=cfg_h2, geometry="2d",
                     subgroup=4)
    with pytest.raises(ValueError, match="takes no subgroup"):
        make_cp_comm(rt4.mesh, "dense", cfg=CFG, subgroup=2)
    two_d = make_cp_comm(rt4.mesh, "dense", cfg=CFG, geometry="2d",
                         subgroup=2)
    assert two_d.seq_groups() == 2 and two_d.ring_hops() == 1
    flat = make_cp_comm(rt4.mesh, "dense", cfg=CFG)
    assert flat.subgroup == 1 and flat.ring_hops() == 3


def test_cp_2d_byte_model_a2a_rows():
    if len(jax.devices()) < 4:
        pytest.skip("needs >= 4 (fake) devices")
    rt4 = build_mesh(ParallelConfig(context_parallel=4),
                     devices=jax.devices()[:4])
    flat = make_cp_comm(rt4.mesh, "dense", cfg=CFG)
    two_d = make_cp_comm(rt4.mesh, "dense", cfg=CFG, geometry="2d",
                         subgroup=2)
    b_flat = cp_ring_comm_bytes(CFG, flat, 2, 1)
    b_2d = cp_ring_comm_bytes(CFG, two_d, 2, 1)
    # the flat ring never runs a2a legs
    assert b_flat["a2a_dense"] == b_flat["a2a_compressed"] == 0
    # 2d: 1 cross-subgroup hop at half the head payload vs 3 full-
    # payload flat hops => ring wire drops by 6x; the a2a legs appear
    assert b_2d["dense"] * 6 == b_flat["dense"]
    assert b_2d["a2a_dense"] > 0
    # int8 a2a compresses the o payload; the cp_a2a policy pins it dense
    i8 = make_cp_comm(rt4.mesh, "int8", cfg=CFG, geometry="2d",
                      subgroup=2)
    b_i8 = cp_ring_comm_bytes(CFG, i8, 2, 1)
    assert 0 < b_i8["a2a_compressed"] < b_i8["a2a_dense"]
    gated = make_cp_comm(rt4.mesh, "int8", cfg=CFG, geometry="2d",
                         subgroup=2, policy={"cp_a2a": False})
    assert gated.a2a_wire_mode() == "dense" and gated.compresses()
    b_gated = cp_ring_comm_bytes(CFG, gated, 2, 1)
    assert b_gated["a2a_compressed"] == b_gated["a2a_dense"]
    assert b_gated["compressed"] < b_gated["dense"]  # ring still int8


def test_cp_loc_tables_striping_and_invariant(cp_setup):
    _, _, cpe = cp_setup
    npl, mpl = cpe._npl, cpe._mpl
    row = np.zeros((1, cpe.max_pages), np.int32)
    # logical 0 -> rank 0 local 1; logical 1 -> rank 1 local 2
    row[0, 0], row[0, 1] = 1, npl + 2
    loc = cpe._loc_tables(row)
    assert loc.shape == (2, 1, mpl)
    assert loc[0, 0, 0] == 1 and loc[1, 0, 0] == 2
    # unallocated tail: local scratch on rank 0, sentinel elsewhere
    assert loc[0, 0, 1] == 0 and loc[1, 0, 1] == npl
    # a page on the wrong rank is a loud invariant violation
    bad = np.zeros((1, cpe.max_pages), np.int32)
    bad[0, 1] = 1  # logical 1 must live on rank 1, page 1 is rank 0's
    with pytest.raises(AssertionError, match="striping invariant"):
        cpe._loc_tables(bad)


# ---------------------------------------------------------------------------
# geometry x transport parity matrix (ISSUE 20): every cell must stay
# token-identical to the dense single-host engine through fresh ragged
# traffic, radix prefix hits, and mid-prefill preempt/resume, with zero
# decode recompiles. Dense transports also hold logprobs to 1e-5; int8
# cells carry the ring/a2a quantization noise in the logprobs (bounded,
# measured <= 1.5e-3 at this geometry) while the argmax stays exact.


MATRIX = {
    "ring_serial_dense": dict(cp=2, cp_overlap=False),
    "ring_overlap_dense": dict(cp=2, cp_overlap=True),
    "ring_overlap_int8": dict(cp=2, cp_overlap=True,
                              cp_collectives="int8"),
    "2d_dense": dict(cp=4, cp_geometry="2d", cp_subgroup=2),
    "2d_int8": dict(cp=4, cp_geometry="2d", cp_subgroup=2,
                    cp_collectives="int8"),
}


def _logprob_atol(cell: str) -> float:
    return 5e-3 if "int8" in cell else 1e-5


# tier-1 keeps the two NEW geometries' default transport (the tentpole
# gates); the other cells ride the slow suite — serial-ring parity also
# runs inside tier-1's bench line (serve_cp_overlap A/Bs serial vs
# overlapped with greedy-parity gates), and int8 transport keeps its
# tier-1 roundtrip/jaxpr units above. The 870s suite budget is why.
_TIER1_CELLS = ("2d_dense", "ring_overlap_dense")


def _matrix_cells():
    return [c if c in _TIER1_CELLS
            else pytest.param(c, marks=pytest.mark.slow)
            for c in sorted(MATRIX)]


@pytest.fixture(scope="module")
def matrix_cache():
    """Lazily built engines, one per matrix cell, shared across the
    scenario tests so each cell compiles its steps exactly once."""
    return {}


def _matrix_engine(cache, cell):
    if cell not in cache:
        spec = dict(MATRIX[cell])
        cp = spec.pop("cp")
        if len(jax.devices()) < cp:
            pytest.skip(f"needs >= {cp} (fake) devices")
        rt = build_mesh(ParallelConfig(context_parallel=cp),
                        devices=jax.devices()[:cp])
        sp = shard_tree(rt, PARAMS, param_specs(CFG))
        cache[cell] = ContextParallelEngine(
            CFG, sp, num_slots=2, max_seq_len=64, page_size=8,
            prefill_chunk=8, mesh=rt.mesh, **spec)
    return cache[cell]


@pytest.mark.parametrize("cell", _matrix_cells())
def test_cp_matrix_fresh_ragged_parity(cp_setup, matrix_cache, cell):
    _, dense, _ = cp_setup
    eng = _matrix_engine(matrix_cache, cell)
    prompts = np.asarray([[3, 7, 11, 2, 9, 4, 1, 8, 5, 6, 2, 3, 7]],
                         np.int32)
    lengths = np.asarray([13], np.int32)
    a = dense.generate(prompts, lengths, max_new_tokens=8, temperature=0.0)
    b = eng.generate(prompts, lengths, max_new_tokens=8, temperature=0.0)
    np.testing.assert_array_equal(a.tokens, b.tokens)
    np.testing.assert_allclose(a.logprobs, b.logprobs,
                               atol=_logprob_atol(cell), rtol=0)
    assert eng.stats["cp_ring_steps"] > 0


@pytest.mark.parametrize("cell", _matrix_cells())
def test_cp_matrix_radix_hit_parity(cp_setup, matrix_cache, cell):
    """The second request aliases 3 cached prefix pages whose stripe
    spans every rank; exactness must survive the alias in each
    geometry/transport combination."""
    _, dense, _ = cp_setup
    eng = _matrix_engine(matrix_cache, cell)
    prefix = list(range(5, 29))  # 24 tokens = 3 full pages
    _run(eng, prefix + [30, 31])
    hits0 = eng.stats["prefix_hits"]
    got = _run(eng, prefix + [40, 41, 42])
    assert eng.stats["prefix_hits"] > hits0
    want = _run(dense, prefix + [40, 41, 42])
    assert got.generated == want.generated
    np.testing.assert_allclose(got.logprobs, want.logprobs,
                               atol=_logprob_atol(cell), rtol=0)


@pytest.mark.parametrize("cell", _matrix_cells())
def test_cp_matrix_preempt_resume_parity(cp_setup, matrix_cache, cell):
    """Preempt mid-prefill, resume, and still land the exact tokens the
    uninterrupted run produces — per geometry and transport."""
    _, dense, _ = cp_setup
    eng = _matrix_engine(matrix_cache, cell)
    prompt = [int(t) for t in
              np.random.default_rng(11).integers(1, 64, 40)]
    req = eng.submit(_req(prompt, 6))
    eng.step()  # admit + first chunk
    eng.step()  # second chunk (prompt is 5 chunks of 8)
    assert eng.prefill_queue.peek() is not None  # mid-prefill
    assert eng._preempt_one()
    eng.run_until_idle()
    assert req.error is None, req.error
    want = _run(dense, prompt, 6)
    assert req.generated == want.generated
    np.testing.assert_allclose(req.logprobs, want.logprobs,
                               atol=_logprob_atol(cell), rtol=0)


def test_cp_matrix_zero_decode_recompiles(matrix_cache):
    """Order-dependent on the matrix scenarios above: every cell's
    decode step must have compiled exactly once across fresh + radix +
    preempt traffic."""
    assert matrix_cache, "matrix scenarios did not run"
    for cell, eng in sorted(matrix_cache.items()):
        assert eng.stats["decode_recompiles"] == 0, cell


# ---------------------------------------------------------------------------
# satellite 1 (ISSUE 20): striped-pool exhaustion is a first-class
# admission signal — the dry shard is named in the 503 detail, counted
# per shard, and journaled once per episode.


def test_cp_pool_exhaustion_names_dry_shards(tmp_path):
    import json as _json

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 (fake) devices")
    from megatron_tpu.telemetry.journal import (
        EventJournal, set_global_journal,
    )

    rt = build_mesh(ParallelConfig(context_parallel=2),
                    devices=jax.devices()[:2])
    sp = shard_tree(rt, PARAMS, param_specs(CFG))
    eng = ContextParallelEngine(CFG, sp, num_slots=2, max_seq_len=64,
                                page_size=8, prefill_chunk=8, mesh=rt.mesh)
    set_global_journal(EventJournal(str(tmp_path)))
    try:
        # drain the pool: striped pairs, then each rank's uneven tail
        # (rank 0's shard is one short — the scratch page lives there)
        f = eng.pool.free_pages_by_rank()
        grabbed = eng._alloc_pages(2 * min(f))
        assert grabbed is not None
        for r, extra in enumerate(f):
            for _ in range(extra - min(f)):
                tail = eng._alloc_pages(1, logical_start=r)
                assert tail is not None
                grabbed += tail
        assert eng.pool.free_pages == 0
        assert eng._overload_detail() == ""
        # both shards dry: the striped pair cannot fit anywhere
        assert eng._alloc_pages(2) is None
        assert eng.stats["cp_admission_blocked"] == 1
        blocked = eng.metrics.counter("engine_cp_admission_blocked_total",
                                      label_names=("shard",))
        assert blocked.value(shard="0") == 1.0
        assert blocked.value(shard="1") == 1.0
        assert "cp shard(s) 0,1 exhausted" in eng._overload_detail()
        # a retried tick re-counts but does NOT re-journal (per episode)
        assert eng._alloc_pages(2) is None
        assert eng.stats["cp_admission_blocked"] == 2
        # the 503 rejection carries the shard detail, distinct from
        # plain queue depth
        eng.max_queue = 0
        rej = eng.submit(_req([1, 2, 3], 2))
        assert rej.overloaded
        assert "cp shard(s) 0,1 exhausted" in rej.error
        # free one rank-1 page: only shard 0 now blocks a striped pair —
        # a NEW episode (different dry set) journals again
        page1 = next(p for p in grabbed if eng.pool.owner(p) == 1)
        eng.pool.release([page1])
        assert eng._alloc_pages(2) is None
        assert "cp shard(s) 0 exhausted" in eng._overload_detail()
        # a successful grab (the freed rank-1 page) clears the episode
        got = eng._alloc_pages(1, logical_start=1)
        assert got is not None
        assert eng._overload_detail() == ""
    finally:
        set_global_journal(None)
    events = [_json.loads(line)
              for line in open(tmp_path / "events.jsonl")]
    dry = [e for e in events if e["kind"] == "cp_admission_blocked"]
    assert [e["shards"] for e in dry] == [[0, 1], [0]]
    assert dry[0]["free_by_rank"] == [0, 0] and dry[0]["need"] == [1, 1]


# ---------------------------------------------------------------------------
# CP x DP fleet geometry (ISSUE 20 tentpole part 3): one host, multiple
# independent CP engine lanes behind one GenerationService.


def test_cp_lanes_service_dispatch_and_metrics():
    if len(jax.devices()) < 4:
        pytest.skip("needs >= 4 (fake) devices")
    from megatron_tpu.inference.fleet.scrape import (
        parse_prom_text, replica_load, sample_sum,
    )
    from megatron_tpu.inference.server import GenerationService
    from megatron_tpu.telemetry.metrics import MetricsRegistry
    from megatron_tpu.tokenizer.tokenizer import NullTokenizer

    rt = build_mesh(ParallelConfig(context_parallel=2),
                    devices=jax.devices()[:2])
    sp = shard_tree(rt, PARAMS, param_specs(CFG))
    svc = GenerationService(CFG, sp, NullTokenizer(CFG.vocab_size - 1),
                            mesh=rt.mesh, engine_slots=2,
                            engine_max_seq_len=64, kv_paging=True,
                            page_size=8, prefill_chunk=8,
                            cp_serving=True, cp_lanes=2,
                            metrics=MetricsRegistry())
    try:
        # two live lanes over disjoint cp-sized device groups
        assert len(svc.engines) == 2
        d0 = {d.id for d in svc.engines[0].mesh.devices.flat}
        d1 = {d.id for d in svc.engines[1].mesh.devices.flat}
        assert len(d0) == 2 and len(d1) == 2 and not (d0 & d1)
        # a real request through the dispatch path completes on a lane
        out = svc.handle({"prompts": ["5 9 13 2 7"],
                          "tokens_to_generate": 4, "temperature": 0.0})
        assert out["text"] and out["text"][0]
        # least-loaded pick: busy slots + queue depth, min wins
        class _Lane:
            def __init__(self, busy, queued):
                self.num_active = busy
                self._queue = [None] * queued

        real = svc.engines
        svc.engines = [_Lane(2, 1), _Lane(1, 1)]
        assert svc._pick_lane() is svc.engines[1]
        svc.engines = real
        # per-lane series share one exposition; the fleet load scrape
        # SUMS lanes into the replica's dispatch score
        svc.engines[1]._m_active.set(2.0)
        text = svc.metrics.render()
        assert 'lane="0"' in text and 'lane="1"' in text
        samples = parse_prom_text(text)
        assert sample_sum(samples, "engine_slots_total") == 4.0
        assert replica_load(samples) == sample_sum(
            samples, "engine_slots_active") + sample_sum(
                samples, "engine_queue_depth", default=0.0)
        assert replica_load(samples) >= 2.0
    finally:
        svc.shutdown()


def test_cp_lanes_validation():
    from megatron_tpu.inference.server import (
        GenerationService, _lane_meshes,
    )
    from megatron_tpu.tokenizer.tokenizer import NullTokenizer

    tok = NullTokenizer(CFG.vocab_size - 1)
    with pytest.raises(ValueError, match="serve_context_parallel"):
        GenerationService(CFG, PARAMS, tok, cp_lanes=2)
    with pytest.raises(ValueError, match="migration"):
        GenerationService(CFG, PARAMS, tok, cp_serving=True, cp_lanes=2,
                          peers=["http://sibling:9000"])
    if len(jax.devices()) >= 4:
        # a tensor-sharded mesh cannot carve replicated lanes
        rt = build_mesh(ParallelConfig(tensor_parallel=2,
                                       context_parallel=2),
                        devices=jax.devices()[:4])
        with pytest.raises(ValueError, match="context-only mesh"):
            _lane_meshes(rt.mesh, 2)
    if len(jax.devices()) == 8:
        rt4 = build_mesh(ParallelConfig(context_parallel=4),
                         devices=jax.devices()[:4])
        with pytest.raises(ValueError, match="only 8 visible"):
            _lane_meshes(rt4.mesh, 3)  # 12 devices needed

"""Context-parallel serving (inference/context_parallel/): striped page
pool units, compressed ring-permute transport, and the engine parity
gates — greedy traffic through the CP engine (chunked distributed
prefill, sequence-striped paged KV, ring-attention decode) must be
token-identical to the dense single-host engine, with logprob parity
and zero decode recompiles after warmup, through radix prefix hits and
mid-prefill preempt/resume."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_tpu.config import ParallelConfig
from megatron_tpu.inference.context_parallel import (
    ContextParallelEngine, StripedPagePool,
)
from megatron_tpu.inference.engine import InferenceEngine, Request
from megatron_tpu.inference.paging.pool import SCRATCH_PAGE
from megatron_tpu.models import presets
from megatron_tpu.models.params import init_params, param_specs
from megatron_tpu.parallel.mesh import build_mesh
from megatron_tpu.parallel.sharding import shard_tree
from megatron_tpu.quant.collectives import (
    cp_ring_comm_bytes, make_cp_comm, ring_permute,
)

CFG = presets.tiny(vocab_size=64, seq_length=64)
PARAMS = init_params(CFG, jax.random.PRNGKey(0))


# ---------------------------------------------------------------------------
# striped page pool


def test_striped_pool_ownership_and_striping():
    # 8 pages over cp=2: rank 0 owns 1..3 (0 is scratch), rank 1 owns 4..7
    pool = StripedPagePool(8, 2)
    assert pool.pages_per_rank == 4
    assert pool.free_pages_by_rank() == [3, 4]
    pages = pool.alloc(4)  # logical 0..3 -> ranks 0,1,0,1
    assert [pool.owner(p) for p in pages] == [0, 1, 0, 1]
    assert pool.free_pages_by_rank() == [1, 2]
    # logical_start continues the stripe mid-sequence
    more = pool.alloc(2, logical_start=4)  # logical 4,5 -> ranks 0,1
    assert [pool.owner(p) for p in more] == [0, 1]


def test_striped_pool_all_or_nothing_per_rank():
    pool = StripedPagePool(8, 2)
    # rank 0 has 3 usable pages: an alloc needing 4 even-logical pages
    # must fail WITHOUT draining rank 1
    assert pool.alloc(7) is None
    assert pool.free_pages_by_rank() == [3, 4]
    # 6 logical pages = 3 per rank fits exactly
    pages = pool.alloc(6)
    assert pages is not None
    assert pool.free_pages_by_rank() == [0, 1]
    # release returns each page to its owner's free list
    pool.release(pages)
    assert pool.free_pages_by_rank() == [3, 4]


def test_striped_pool_misuse_raises():
    pool = StripedPagePool(8, 2)
    with pytest.raises(ValueError):
        StripedPagePool(9, 2)  # not divisible by cp
    (p,) = pool.alloc(1)
    pool.release([p])
    with pytest.raises(ValueError):
        pool.release([p])  # double release
    # scratch page is never tracked
    pool.retain([SCRATCH_PAGE])
    pool.release([SCRATCH_PAGE])


# ---------------------------------------------------------------------------
# ring transport + byte model


def test_ring_permute_dense_and_int8():
    from jax.sharding import PartitionSpec as P

    rt = build_mesh(ParallelConfig(context_parallel=2),
                    devices=jax.devices()[:2])
    perm = [(0, 1), (1, 0)]
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((2, 4, 32)), jnp.float32)

    def run(mode):
        body = lambda s: ring_permute(s, "context", perm, mode=mode,  # noqa: E731
                                      chunk=16)
        return jax.shard_map(body, mesh=rt.mesh, in_specs=(P("context"),),
                             out_specs=P("context"), axis_names={"context"},
                             check_vma=False)(x)

    want = jnp.roll(x, 1, axis=0)  # shard r receives shard r-1's rows
    np.testing.assert_array_equal(np.asarray(run("dense")), np.asarray(want))
    got = np.asarray(run("int8"))
    # per-chunk symmetric int8: bounded roundtrip error, not identity
    err = np.max(np.abs(got - np.asarray(want)))
    assert 0 < err <= np.max(np.abs(np.asarray(x))) / 127 + 1e-6
    # the wire really moves int8 payloads
    body = lambda s: ring_permute(s, "context", perm, mode="int8")  # noqa: E731
    fn = jax.shard_map(body, mesh=rt.mesh, in_specs=(P("context"),),
                       out_specs=P("context"), axis_names={"context"},
                       check_vma=False)
    assert "i8[" in str(jax.make_jaxpr(fn)(x))


def test_cp_ring_byte_model():
    rt = build_mesh(ParallelConfig(context_parallel=2),
                    devices=jax.devices()[:2])
    dense = make_cp_comm(rt.mesh, "dense", cfg=CFG)
    int8 = make_cp_comm(rt.mesh, "int8", cfg=CFG)
    b_dense = cp_ring_comm_bytes(CFG, dense, 2, 1)
    b_int8 = cp_ring_comm_bytes(CFG, int8, 2, 1)
    assert b_dense["dense"] == b_dense["compressed"]
    assert b_int8["dense"] == b_dense["dense"]
    assert 0 < b_int8["compressed"] < b_int8["dense"]
    # the policy can pin cp_ring dense: byte model collapses to dense
    gated = make_cp_comm(rt.mesh, "int8", cfg=CFG,
                         policy={"cp_ring": False})
    assert not gated.compresses() and gated.wire_mode() == "dense"
    b_gated = cp_ring_comm_bytes(CFG, gated, 2, 1)
    assert b_gated["compressed"] == b_gated["dense"]
    # cp=1 / mode none build no transport
    solo = build_mesh(ParallelConfig(), devices=jax.devices()[:1])
    assert make_cp_comm(solo.mesh, "int8", cfg=CFG) is None
    assert make_cp_comm(rt.mesh, "none", cfg=CFG) is None


# ---------------------------------------------------------------------------
# engine parity gates (real tiny model, cp=2 mesh)


@pytest.fixture(scope="module")
def cp_setup():
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 (fake) devices")
    rt = build_mesh(ParallelConfig(context_parallel=2),
                    devices=jax.devices()[:2])
    sparams = shard_tree(rt, PARAMS, param_specs(CFG))
    dense = InferenceEngine(CFG, PARAMS, num_slots=2, max_seq_len=64)
    cpe = ContextParallelEngine(CFG, sparams, num_slots=2, max_seq_len=64,
                                page_size=8, prefill_chunk=8, mesh=rt.mesh)
    return rt, dense, cpe


def _req(prompt, n=6):
    return Request(prompt=np.asarray(prompt, np.int32), max_new_tokens=n)


def _run(eng, prompt, n=6):
    req = eng.submit(_req(prompt, n))
    eng.run_until_idle()
    assert req.error is None, req.error
    return req


def test_cp_parity_multichunk_ragged(cp_setup):
    """A 13-token prompt: 2 chunks, neither aligned to page_size * cp —
    the ragged tail crosses a shard boundary mid-page. Token-identical
    with full logprob parity."""
    _, dense, cpe = cp_setup
    prompts = np.asarray([[3, 7, 11, 2, 9, 4, 1, 8, 5, 6, 2, 3, 7]],
                         np.int32)
    lengths = np.asarray([13], np.int32)
    a = dense.generate(prompts, lengths, max_new_tokens=8, temperature=0.0)
    b = cpe.generate(prompts, lengths, max_new_tokens=8, temperature=0.0)
    np.testing.assert_array_equal(a.tokens, b.tokens)
    np.testing.assert_allclose(a.logprobs, b.logprobs, rtol=1e-5, atol=1e-5)
    assert cpe.stats["cp_ring_steps"] > 0


def test_cp_parity_radix_hit_mid_shard(cp_setup):
    """Two requests sharing a 3-page (24-token) prefix: the second
    aliases cached pages whose stripe ends mid-shard (page 3 of the
    follow-up starts on rank 1). Exactness must survive the alias."""
    _, dense, cpe = cp_setup
    prefix = list(range(5, 29))  # 24 tokens = 3 full pages
    tail_a, tail_b = [30, 31], [40, 41, 42]
    _run(cpe, prefix + tail_a)
    hits0 = cpe.stats["prefix_hits"]
    got = _run(cpe, prefix + tail_b)
    assert cpe.stats["prefix_hits"] > hits0
    want = _run(dense, prefix + tail_b)
    assert got.generated == want.generated
    np.testing.assert_allclose(got.logprobs, want.logprobs,
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(got.prompt_logprobs, want.prompt_logprobs,
                               rtol=1e-5, atol=1e-5)


def test_cp_parity_preempt_resume_mid_prefill(cp_setup):
    """Preempt a CP request while its chunked prefill is mid-flight: the
    resume recomputes through the striped pools and must finish with
    exactly the tokens it would have produced without the preemption."""
    _, dense, cpe = cp_setup
    prompt = [int(t) for t in
              np.random.default_rng(7).integers(1, 64, 40)]
    req = cpe.submit(_req(prompt, 6))
    cpe.step()  # admit + first chunk
    cpe.step()  # second chunk (prompt is 5 chunks of 8)
    assert cpe.prefill_queue.peek() is not None  # mid-prefill
    pre0 = cpe.stats["preemptions"]
    assert cpe._preempt_one()
    assert cpe.stats["preemptions"] == pre0 + 1
    cpe.run_until_idle()
    assert req.error is None, req.error
    want = _run(dense, prompt, 6)
    assert req.generated == want.generated
    np.testing.assert_allclose(req.logprobs, want.logprobs,
                               rtol=1e-5, atol=1e-5)


def test_cp_zero_decode_recompiles_after_warmup(cp_setup):
    """Order-dependent on the parity tests above having driven real
    traffic: the decode step must have compiled exactly once."""
    _, _, cpe = cp_setup
    assert cpe.stats["decode_recompiles"] == 0


# ---------------------------------------------------------------------------
# construction validation + host-side table building


def test_cp_engine_rejects_bad_geometry(cp_setup):
    rt, _, _ = cp_setup
    with pytest.raises(ValueError, match="requires a mesh"):
        ContextParallelEngine(CFG, PARAMS, mesh=None)
    solo = build_mesh(ParallelConfig(), devices=jax.devices()[:1])
    with pytest.raises(ValueError, match="cp == 1"):
        ContextParallelEngine(CFG, PARAMS, mesh=solo.mesh)
    with pytest.raises(ValueError, match="ring transport"):
        ContextParallelEngine(CFG, PARAMS, mesh=rt.mesh,
                              max_seq_len=64, cp_collectives="none")


def test_cp_engine_rounds_pool_to_cp_multiple(cp_setup):
    rt, _, _ = cp_setup
    sparams = shard_tree(rt, PARAMS, param_specs(CFG))
    eng = ContextParallelEngine(CFG, sparams, num_slots=2, max_seq_len=64,
                                page_size=8, prefill_chunk=8, mesh=rt.mesh,
                                num_pages=11)
    assert eng.num_pages == 12 and eng.pool.pages_per_rank == 6


def test_cp_loc_tables_striping_and_invariant(cp_setup):
    _, _, cpe = cp_setup
    npl, mpl = cpe._npl, cpe._mpl
    row = np.zeros((1, cpe.max_pages), np.int32)
    # logical 0 -> rank 0 local 1; logical 1 -> rank 1 local 2
    row[0, 0], row[0, 1] = 1, npl + 2
    loc = cpe._loc_tables(row)
    assert loc.shape == (2, 1, mpl)
    assert loc[0, 0, 0] == 1 and loc[1, 0, 0] == 2
    # unallocated tail: local scratch on rank 0, sentinel elsewhere
    assert loc[0, 0, 1] == 0 and loc[1, 0, 1] == npl
    # a page on the wrong rank is a loud invariant violation
    bad = np.zeros((1, cpe.max_pages), np.int32)
    bad[0, 1] = 1  # logical 1 must live on rank 1, page 1 is rank 0's
    with pytest.raises(AssertionError, match="striping invariant"):
        cpe._loc_tables(bad)

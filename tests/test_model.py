"""Model forward tests: shapes, determinism, preset coverage, KV-cache
equivalence (counterpart of reference tests/test_layernorm_order.py's
single-layer end-to-end check)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_tpu.models import presets
from megatron_tpu.models.language_model import lm_forward, lm_loss
from megatron_tpu.models.params import init_params, num_params, param_specs, param_shapes


def _batch(cfg, batch=2, seq=16, seed=0):
    rng = np.random.default_rng(seed)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)), jnp.int32)
    labels = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)), jnp.int32)
    return {"tokens": tokens, "labels": labels,
            "loss_mask": jnp.ones((batch, seq), jnp.float32)}


@pytest.mark.parametrize("kw", [
    dict(),                                                      # llama-ish
    dict(normalization="layernorm", activation="gelu",
         use_bias_linear=True, use_bias_qkv=True,
         tie_embed_logits=True, position_embedding_type="absolute"),  # gpt-ish
    dict(normalization="layernorm", activation="gelu",
         parallel_attn=True, tie_embed_logits=True, num_kv_heads=1),  # falcon-ish
    dict(normalization="layernorm", activation="gelu", parallel_attn=True,
         parallel_layernorm=True, tie_embed_logits=True),        # falcon-40b-ish
    dict(sliding_window_size=8),                                 # mistral-ish
])
def test_forward_shapes_all_variants(kw):
    if kw.get("position_embedding_type") == "absolute":
        kw["max_position_embeddings"] = 128
    cfg = presets.tiny(**kw)
    params = init_params(cfg, jax.random.PRNGKey(0))
    batch = _batch(cfg)
    logits = lm_forward(cfg, params, batch["tokens"])
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert jnp.isfinite(logits).all()


def test_param_tree_matches_specs_and_shapes():
    cfg = presets.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    specs = param_specs(cfg)
    shapes = param_shapes(cfg)
    assert jax.tree.structure(params) == jax.tree.structure(shapes)
    flat_p = jax.tree.leaves(params)
    flat_s = jax.tree.leaves(shapes)
    for p, s in zip(flat_p, flat_s):
        assert p.shape == s.shape
    # spec tree mirrors param tree (specs are leaves)
    from jax.sharding import PartitionSpec as P
    spec_struct = jax.tree.structure(specs, is_leaf=lambda x: isinstance(x, P))
    assert spec_struct == jax.tree.structure(params)


def test_deterministic_forward_and_init():
    cfg = presets.tiny()
    p1 = init_params(cfg, jax.random.PRNGKey(7))
    p2 = init_params(cfg, jax.random.PRNGKey(7))
    assert all((a == b).all() for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)))
    batch = _batch(cfg)
    l1 = lm_forward(cfg, p1, batch["tokens"])
    l2 = lm_forward(cfg, p2, batch["tokens"])
    np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))


def test_loss_runs_and_is_finite():
    cfg = presets.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    loss, aux = lm_loss(cfg, params, _batch(cfg))
    assert np.isfinite(float(loss))
    # random init: loss should be near ln(vocab)
    assert abs(float(loss) - np.log(cfg.vocab_size)) < 1.0


@pytest.mark.slow  # 11s measured cacheless (PR 4 tier-1 re-budget);
# block-recompute ordering + loss tests keep remat coverage in tier-1
def test_recompute_policies_agree():
    cfg = presets.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    batch = _batch(cfg)

    def loss_fn(recompute):
        def f(p):
            return lm_loss(cfg, p, batch, recompute=recompute)[0]
        return f

    g_none = jax.grad(loss_fn("none"))(params)
    for rec in ("full", "selective", "block:1", "block:2", "uniform:2"):
        g = jax.grad(loss_fn(rec))(params)
        for a, b in zip(jax.tree.leaves(g_none), jax.tree.leaves(g)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5, err_msg=rec)


def test_block_recompute_memory_ordering():
    """--recompute_method block must actually trade memory: XLA's own
    buffer-assignment peak for grad-of-loss must order
    none >= block:half >= full (ref transformer.py:1148-1172 'fully use
    the device memory')."""
    cfg = presets.tiny(vocab_size=128, seq_length=512, hidden_size=256,
                       num_layers=8, num_attention_heads=4, num_kv_heads=4,
                       ffn_hidden_size=512)
    params = init_params(cfg, jax.random.PRNGKey(0))
    batch = _batch(cfg, batch=4, seq=512)

    def temps(recompute):
        # temp_size (sum of live temporaries) is the metric that sees the
        # saved layer activations; XLA:CPU's heap-peak simulation reuses
        # buffers too aggressively to discriminate policies
        f = jax.jit(jax.grad(
            lambda p: lm_loss(cfg, p, batch, recompute=recompute)[0]))
        return int(f.lower(params).compile()
                   .memory_analysis().temp_size_in_bytes)

    t_none, t_block, t_full = temps("none"), temps("block:4"), temps("full")
    # measured 738 MB / 435 MB / 101 MB at this geometry — block:half
    # sits squarely between the extremes
    assert t_none > 1.3 * t_block > 1.3 * t_full, (t_none, t_block, t_full)


def test_kv_cache_matches_full_forward():
    """Incremental decode with per-layer caches == full forward
    (ref: InferenceParams path, text_generation/forward_step.py)."""
    cfg = presets.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = _batch(cfg, batch=1, seq=8)["tokens"]
    full = lm_forward(cfg, params, tokens)

    L, B, S = cfg.num_layers, 1, 8
    caches = (
        jnp.zeros((L, B, S, cfg.n_kv_heads, cfg.head_dim), jnp.float32),
        jnp.zeros((L, B, S, cfg.n_kv_heads, cfg.head_dim), jnp.float32),
    )
    # prefill 4 tokens, then decode one at a time
    pos = jnp.arange(8)[None, :]
    logits, caches = lm_forward(cfg, params, tokens[:, :4], positions=pos[:, :4],
                                kv_caches=caches, cache_index=0)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(full[:, :4]),
                               rtol=2e-3, atol=2e-3)
    for t in range(4, 8):
        logits, caches = lm_forward(cfg, params, tokens[:, t:t + 1],
                                    positions=pos[:, t:t + 1],
                                    kv_caches=caches, cache_index=t)
        np.testing.assert_allclose(np.asarray(logits[:, 0]), np.asarray(full[:, t]),
                                   rtol=2e-3, atol=2e-3)


def test_lima_dropout_ramp():
    from megatron_tpu.models.language_model import _layer_dropout_rates
    cfg = presets.tiny(hidden_dropout=0.3, lima_dropout=True, num_layers=4)
    rates = np.asarray(_layer_dropout_rates(cfg))
    np.testing.assert_allclose(rates, [0.0, 0.1, 0.2, 0.3], atol=1e-6)


def test_preset_param_counts():
    """Sanity: llama-2-7B parameter count ~6.7e9."""
    cfg = presets.llama("7B", version=2)
    n = num_params(cfg)
    assert 6.5e9 < n < 7.0e9
    cfg = presets.falcon("7B")
    n = num_params(cfg)
    assert 6.5e9 < n < 7.5e9
    cfg = presets.mistral("7B")
    n = num_params(cfg)
    assert 7.0e9 < n < 7.5e9


@pytest.mark.slow  # 11s measured cacheless (PR 4 tier-1 re-budget);
# forward_shapes_all_variants covers the post-LN wiring in tier-1
def test_post_ln_convention():
    """--use_post_ln: no pre-norm, per-layer output norm, no final stack
    norm (ref transformer.py:660-664, :1278-1281)."""
    import dataclasses

    cfg = presets.tiny(vocab_size=64, seq_length=16, num_layers=2,
                       hidden_size=32, num_attention_heads=4, num_kv_heads=2,
                       ffn_hidden_size=64, normalization="layernorm")
    post = dataclasses.replace(cfg, use_post_ln=True)
    params = init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    toks = jnp.asarray(rng.integers(0, 64, (2, 16)), jnp.int32)

    out_pre = lm_forward(cfg, params, toks)
    out_post = lm_forward(post, params, toks)
    assert out_pre.shape == out_post.shape
    # genuinely different layouts
    assert float(jnp.abs(out_pre - out_post).max()) > 1e-3
    # post-LN output is normalized by the last layer's own LN: a change to
    # final_ln params must NOT affect it (final norm skipped)
    p2 = jax.tree.map(lambda x: x, params)
    p2["final_ln"] = {k: v * 3.0 for k, v in params["final_ln"].items()}
    np.testing.assert_allclose(np.asarray(lm_forward(post, p2, toks)),
                               np.asarray(out_post), rtol=1e-6)
    # residual-post-layernorm variant runs and differs from both
    rpl = dataclasses.replace(cfg, apply_residual_post_ln=True)
    out_rpl = lm_forward(rpl, params, toks)
    assert float(jnp.abs(out_rpl - out_pre).max()) > 1e-3
    # both train
    batch = {"tokens": toks, "labels": toks,
             "loss_mask": jnp.ones((2, 16), jnp.float32)}
    for c in (post, rpl):
        g = jax.grad(lambda p: lm_loss(c, p, batch)[0])(params)
        assert all(np.isfinite(np.asarray(x)).all()
                   for x in jax.tree.leaves(jax.device_get(g)))

"""finetune.py CLI end-to-end on instruction data + tensor-parallel
generation parity (previously untested surfaces)."""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_finetune_cli_instruction_data(tmp_path):
    # ~35s: finetune.py subprocess with a cold jax start + fresh compile
    # (deselectable with -m 'not slow', conftest marker doc)
    """preprocess_instruct_data -> finetune.py --data_type instruction:
    the reference's instruction-tuning recipe as a hermetic test."""
    rng = np.random.default_rng(0)
    jsonl = tmp_path / "chats.jsonl"
    with open(jsonl, "w") as f:
        for _ in range(40):
            conv = [
                {"role": "prompter",
                 "text": " ".join(str(int(x)) for x in rng.integers(0, 80, 8))},
                {"role": "assistant",
                 "text": " ".join(str(int(x)) for x in rng.integers(0, 80, 10))},
            ]
            f.write(json.dumps({"conversation": conv}) + "\n")

    env = {k: v for k, v in os.environ.items()}
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["JAX_PLATFORMS"] = "cpu"
    env["MEGATRON_TPU_FORCE_PLATFORM"] = "cpu"
    prefix = str(tmp_path / "instr")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools/preprocess_instruct_data.py"),
         "--input", str(jsonl), "--output_prefix", prefix,
         "--tokenizer_type", "null", "--vocab_size", "97"],
        env=env, capture_output=True, text=True, cwd=REPO, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]

    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "finetune.py"),
         "--num_layers", "2", "--hidden_size", "32",
         "--num_attention_heads", "4", "--seq_length", "64",
         "--vocab_size", "128", "--fp32",
         "--data_path", prefix, "--data_type", "instruction",
         "--micro_batch_size", "1", "--global_batch_size", "8",
         "--train_iters", "4", "--log_interval", "2",
         "--lr", "1e-3", "--lr_decay_style", "constant",
         "--eval_interval", "100"],
        env=env, capture_output=True, text=True, cwd=REPO, timeout=600)
    assert out.returncode == 0, out.stderr[-3000:]
    assert "lm loss" in out.stdout


def test_generation_parity_under_tensor_parallel():
    """generate_tokens with tp=2-sharded params must emit the same tokens
    as the unsharded model (greedy)."""
    from megatron_tpu.config import ParallelConfig
    from megatron_tpu.inference.generation import generate_tokens
    from megatron_tpu.models import presets
    from megatron_tpu.models.params import init_params, param_specs
    from megatron_tpu.parallel.mesh import build_mesh
    from megatron_tpu.parallel.sharding import shard_tree

    cfg = presets.tiny(vocab_size=64, seq_length=32, num_layers=2,
                       hidden_size=32, num_attention_heads=4, num_kv_heads=2,
                       ffn_hidden_size=64)
    params = init_params(cfg, jax.random.PRNGKey(0))
    prompts = np.asarray([[5, 11, 3], [9, 2, 0]], np.int32)
    lengths = np.asarray([3, 2], np.int32)
    base = generate_tokens(cfg, params, prompts, lengths, max_new_tokens=6,
                           top_k=1, eod=63, want_logprobs=False)

    rt = build_mesh(ParallelConfig(tensor_parallel=2))
    sharded = shard_tree(rt, params, param_specs(cfg))
    with jax.sharding.set_mesh(rt.mesh):
        got = generate_tokens(cfg, sharded, prompts, lengths,
                              max_new_tokens=6, top_k=1, eod=63,
                              want_logprobs=False)
    np.testing.assert_array_equal(base.tokens, got.tokens)
    np.testing.assert_array_equal(base.lengths, got.lengths)

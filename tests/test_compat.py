"""compat.py shim branches: what the shims actually allow, pinned.

The jaxlint banned-API rules (megatron_tpu/analysis/ast_lint.py) encode
what this toolchain can't run; these tests keep the two in sync — if a
jax upgrade makes a shim a no-op, the linter tests here say which rules
can be retired (ISSUE 6 satellite).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from megatron_tpu import compat
from megatron_tpu.analysis import ast_lint
from megatron_tpu.config import ParallelConfig
from megatron_tpu.parallel.mesh import ambient_mesh_shape, build_mesh


def _mesh(cp=2):
    return build_mesh(ParallelConfig(context_parallel=cp)).mesh


def test_install_is_idempotent():
    """Every entry point imports the package (and so installs) — a
    second install must not stack wrappers or flip behavior."""
    before = (jax.shard_map, jax.lax.axis_size,
              jax.sharding.get_abstract_mesh, compat.SHARD_MAP_SHIMMED)
    compat.install()
    after = (jax.shard_map, jax.lax.axis_size,
             jax.sharding.get_abstract_mesh, compat.SHARD_MAP_SHIMMED)
    assert before == after


def test_axis_size_inside_shard_map():
    mesh = _mesh(cp=2)
    got = {}

    def body(x):
        got["one"] = jax.lax.axis_size("context")
        return x

    fn = jax.shard_map(body, mesh=mesh, in_specs=(P("context"),),
                      out_specs=P("context"), check_vma=False)
    fn(jnp.zeros((4, 4)))
    assert got["one"] == 2


def test_axis_size_tuple_and_unbound():
    """The shim multiplies tuple axes and raises NameError on unbound
    names (both branches of compat._install_axis_size)."""
    mesh = _mesh(cp=2)
    got = {}

    def body(x):
        got["pair"] = jax.lax.axis_size(("data", "context"))
        with pytest.raises(NameError):
            jax.lax.axis_size("no-such-axis")
        return x

    fn = jax.shard_map(body, mesh=mesh,
                      in_specs=(P(("data", "context")),),
                      out_specs=P(("data", "context")), check_vma=False)
    fn(jnp.zeros((8, 4)))
    # full-manual (shim) binds all axes: data=4 x context=2. On a jax
    # whose shard_map honors axis_names, only context would be bound —
    # the test would catch that semantic shift too.
    assert got["pair"] == 8 if compat.SHARD_MAP_SHIMMED else got["pair"] >= 2


def test_abstract_mesh_normalizes_to_none():
    """jax 0.4.37 returns an empty TUPLE when no mesh is set; the shim
    normalizes to None so `mesh is None or not mesh.shape` guards work."""
    m = jax.sharding.get_abstract_mesh()
    assert m is None or hasattr(m, "shape")
    assert ambient_mesh_shape() == {}


def test_set_mesh_publishes_to_all_accessors():
    mesh = _mesh(cp=2)
    with jax.sharding.set_mesh(mesh):
        am = jax.sharding.get_abstract_mesh()
        assert am is not None and dict(am.shape)["context"] == 2
        assert ambient_mesh_shape()["context"] == 2
        # legacy thread_resources path: bare-PartitionSpec constraints
        # inside jit must resolve against the ambient mesh
        out = jax.jit(lambda x: jax.lax.with_sharding_constraint(
            x, P("context")))(jnp.zeros((4, 4)))
        assert out.shape == (4, 4)
    assert ambient_mesh_shape() == {}


def test_shard_map_shim_full_manual_semantics():
    """The shim ignores axis_names (promotes ALL axes to manual): an
    axis OUTSIDE axis_names is still bound inside the body. That is the
    documented numerically-equivalent degradation — if it changes (jax
    upgrade making partial-auto real), SHARD_MAP_SHIMMED must be False
    and the skip-gated kernel tests come back."""
    if not compat.SHARD_MAP_SHIMMED:
        pytest.skip("native jax.shard_map: partial-auto is real here")
    mesh = _mesh(cp=2)
    got = {}

    def body(x):
        # "data" was NOT in axis_names; full-manual still binds it
        got["data"] = jax.lax.axis_size("data")
        return jax.lax.psum(x, "context")

    fn = jax.shard_map(body, mesh=mesh, in_specs=(P("context"),),
                      out_specs=P("context"), axis_names={"context"},
                      check_vma=False)
    out = fn(jnp.ones((4, 4)))
    assert got["data"] == 4
    np.testing.assert_allclose(np.asarray(out), 2.0 * np.ones((4, 4)))


def test_shard_map_shim_flag_matches_reality():
    native = jax.shard_map.__module__.startswith("jax._src") and \
        not hasattr(jax.shard_map, "__wrapped__")
    assert compat.SHARD_MAP_SHIMMED == (not native)


# ---------------------------------------------------------------------------
# linter <-> shim sync
# ---------------------------------------------------------------------------


def test_linter_bans_what_the_toolchain_lacks():
    """On the shimmed toolchain, ragged_all_to_all / partial-auto
    shard_map / direct experimental imports must be linter-banned; the
    moe transport probe must agree (CPU: dense exchange)."""
    snippet = (
        "import jax\n"
        "from jax.experimental.shard_map import shard_map\n"
        "def f(x):\n"
        "    y = jax.lax.ragged_all_to_all(x, x, x, x, x, x,"
        " axis_name='ep')\n"
        "    return jax.shard_map(lambda a: a, mesh=None, in_specs=(),"
        " out_specs=(), auto=frozenset({'data'}))\n"
    )
    findings = ast_lint.lint_source(snippet, "snippet.py")
    msgs = "\n".join(f.message for f in findings)
    assert "ragged_all_to_all" in msgs
    assert "jax.experimental.shard_map" in msgs
    assert "partial-auto" in msgs

    if compat.SHARD_MAP_SHIMMED:
        from megatron_tpu.ops.moe import _use_ragged_transport

        # retire the banned-api lint rule when this starts failing: the
        # toolchain grew a ragged_all_to_all the CPU transport probe accepts
        assert jax.default_backend() != "cpu" or not _use_ragged_transport()


def test_linter_rules_registry_complete():
    """Every rule the docs promise exists and is enforced by default."""
    assert set(ast_lint.RULES) == {
        "host-sync", "banned-api", "internal-api", "broad-except",
        "traced-branch"}

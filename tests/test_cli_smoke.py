"""CLI entry-point smoke tests (ISSUE 13 satellite).

The tools/ CLIs are the operational face of the analysis subsystems —
and the only consumers of some code paths (argparse wiring, by-path
module loading). In-process tests import their modules, which can keep
passing while the actual ``python tools/X.py`` invocation rots (a bad
shebang-era import, a renamed flag, a sys.path assumption). Each runs
here as a REAL subprocess, the way an operator runs it.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "tests", "fixtures", "tiny_cpu.xplane.pb")


def _run(args, timeout=240, env_extra=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.update(env_extra or {})
    return subprocess.run([sys.executable] + args, cwd=REPO,
                         capture_output=True, text=True, timeout=timeout,
                         env=env)


def test_jaxlint_cli_clean_at_head():
    out = _run([os.path.join("tools", "jaxlint.py")])
    assert out.returncode == 0, out.stdout + out.stderr


def test_comm_report_cli_check():
    # one cheap config keeps the smoke fast; the full matrix is gated
    # in-process by tests/test_analysis.py
    out = _run([os.path.join("tools", "comm_report.py"), "--check",
                "--config", "ulysses_cp2"],
               env_extra={"XLA_FLAGS":
                          "--xla_force_host_platform_device_count=8"})
    assert out.returncode == 0, out.stdout + out.stderr
    assert "comm contracts: OK" in out.stdout


@pytest.mark.slow  # subprocess retrace ~3s on a loaded 2-core host; the
# in-process jaxpr golden check (test_analysis) covers both CP configs
# in tier-1
def test_comm_report_cli_check_cp():
    # the context-parallel chunked-prefill manifest: ring ppermute
    # ledger must rebuild clean (one --config per name — the flag
    # appends single values)
    out = _run([os.path.join("tools", "comm_report.py"), "--check",
                "--config", "prefill_cp2"],
               env_extra={"XLA_FLAGS":
                          "--xla_force_host_platform_device_count=8"})
    assert out.returncode == 0, out.stdout + out.stderr
    assert "comm contracts: OK" in out.stdout


@pytest.mark.slow  # subprocess retrace of two CP decode configs (~8s);
# test_analysis gates both in-process in tier-1
def test_comm_report_cli_check_cp_geometry():
    # the topology-aware manifests (ISSUE 20): the overlapped-ring
    # ledger (must equal the serial ring's hop rows — overlap moves
    # exposed time, not bytes) and the 2D cp=4 geometry's a2a +
    # cross-subgroup ring ledger
    out = _run([os.path.join("tools", "comm_report.py"), "--check",
                "--config", "decode_cp2_overlap",
                "--config", "decode_cp4_2d"],
               env_extra={"XLA_FLAGS":
                          "--xla_force_host_platform_device_count=8"})
    assert out.returncode == 0, out.stdout + out.stderr
    assert "comm contracts: OK" in out.stdout


def test_comm_report_cli_diff():
    # the dense-vs-compressed reduction as one command (ISSUE 15
    # satellite) — reads golden JSON only, no jax import
    out = _run([os.path.join("tools", "comm_report.py"), "--diff",
                "decode_tp2_dense", "decode_tp2_int8"])
    assert out.returncode == 0, out.stdout + out.stderr
    assert "wire-byte ratio decode_tp2_dense / decode_tp2_int8" in out.stdout
    assert "[q]" in out.stdout  # compressed entries are marked


def test_trace_report_cli_emit_comm_policy(tmp_path):
    # exposure-driven policy derivation straight off the checked-in
    # fixture trace, through the by-path loader (still no jax import)
    pol = tmp_path / "policy.json"
    out = _run([os.path.join("tools", "trace_report.py"), FIXTURE,
                "--emit-comm-policy", str(pol)])
    assert out.returncode == 0, out.stdout + out.stderr
    doc = json.loads(pol.read_text())
    # the fixture's all-reduce is 87% exposed => psum sites compress;
    # no all-gather / collective-permute / all-to-all was measured =>
    # the logits, cp_ring, and cp_a2a sites stay dense
    assert doc["sites"] == {"attn_out": True, "mlp_out": True,
                            "logits": False, "cp_ring": False,
                            "cp_a2a": False}
    assert doc["exposure"]["all-reduce"] > 0.8
    # per-site exposed fractions: each site reports ITS collective
    # kind's measured exposure — the ring (collective-permute) and a2a
    # legs are separable in a 2D-geometry trace
    assert doc["site_exposure"]["attn_out"] == doc["exposure"]["all-reduce"]
    assert doc["site_exposure"]["cp_ring"] == 0.0
    assert doc["site_exposure"]["cp_a2a"] == 0.0


def test_trace_report_cli_help_and_fixture():
    out = _run([os.path.join("tools", "trace_report.py"), "--help"])
    assert out.returncode == 0, out.stderr
    assert "xplane" in out.stdout
    # and a real parse through the subprocess entry point
    out = _run([os.path.join("tools", "trace_report.py"), FIXTURE,
                "--format", "json"])
    assert out.returncode == 0, out.stdout + out.stderr
    assert json.loads(out.stdout)["report"]["module"] == "jit_fixture_step"


def test_telemetry_report_cli_help():
    out = _run([os.path.join("tools", "telemetry_report.py"), "--help"])
    assert out.returncode == 0, out.stderr
    assert "--perfetto" in out.stdout


@pytest.mark.parametrize("missing", ["/nonexistent/trace/dir"])
def test_trace_report_cli_missing_input_is_rc1(missing):
    out = _run([os.path.join("tools", "trace_report.py"), missing])
    assert out.returncode == 1
    assert "no *.xplane.pb" in out.stderr

"""Fault-tolerance tests: divergence sentinel, fault-injection harness,
multi-signal handler, and the REAL crash/recovery acceptance paths —
subprocess training runs killed mid-save and poisoned with NaN windows
(ISSUE 2: crash-safe training).

Since ISSUE 5 the subprocess runs here exercise the ASYNC goodput loop by
default (background prefetcher + lagged metrics): the kill/resume and
rollback bitwise assertions below double as the prefetcher-x-resilience
interplay acceptance — no sample lost or duplicated across a
prefetch-queue rebuild. The --no_async_loop oracle differentials live in
tests/test_prefetch.py (in-process) and the slow-marked subprocess parity
test at the bottom of this file."""

import json
import os
import re
import signal
import subprocess
import sys

import numpy as np
import pytest

from megatron_tpu.training import resilience
from megatron_tpu.training.resilience import DivergenceSentinel

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- sentinel unit tests -----------------------------------------------------


def test_sentinel_nonfinite_patience():
    s = DivergenceSentinel(patience=3, spike_factor=0.0)
    assert s.observe(1.0) is None
    assert s.observe(float("nan")) is None
    assert s.observe(2.0, skipped=True) is None  # skipped counts as bad...
    assert s.observe(1.0) is None                # ...but a good step resets
    assert s.observe(float("inf")) is None
    assert s.observe(None, skipped=True) is None
    trip = s.observe(float("nan"))
    assert trip and "3 consecutive" in trip
    s.reset()
    assert s.observe(float("nan")) is None


def test_sentinel_streak_override_survives_restart():
    """The optimizer's checkpointed skip streak overrides the host counter:
    a resume that lands mid-NaN (or a crash loop faster than patience)
    keeps accumulating instead of restarting from zero."""
    s = DivergenceSentinel(patience=50, spike_factor=0.0)
    # fresh sentinel after a restart; the restored state already carries 49
    # consecutive skips
    trip = s.observe(float("nan"), skipped=True, streak=50)
    assert trip and "50 consecutive" in trip
    s.reset()
    assert s.observe(float("nan"), streak=10) is None
    assert s.nonfinite_streak == 10
    assert s.observe(1.0, streak=0) is None  # finite step resets as usual
    assert s.nonfinite_streak == 0


def test_sentinel_disabled():
    s = DivergenceSentinel(patience=0, spike_factor=0.0)
    for _ in range(50):
        assert s.observe(float("nan")) is None


def test_sentinel_loss_spike():
    s = DivergenceSentinel(patience=0, spike_factor=2.0, spike_patience=3,
                           warmup_steps=5, ema_alpha=0.5)
    for _ in range(10):
        assert s.observe(1.0) is None
    ema_before = s.ema
    assert s.observe(5.0) is None  # spike 1
    assert s.observe(5.0) is None  # spike 2
    assert s.ema == ema_before     # spikes are NOT folded into the EMA
    assert s.observe(1.0) is None  # recovery resets the spike streak
    assert s.observe(5.0) is None
    assert s.observe(5.0) is None
    trip = s.observe(5.0)
    assert trip and "loss_spike_factor" in trip
    # no trip during warmup regardless of ratio
    s2 = DivergenceSentinel(patience=0, spike_factor=2.0, spike_patience=1,
                            warmup_steps=100)
    for loss in (1.0, 100.0, 1.0, 100.0):
        assert s2.observe(loss) is None


# -- fault harness -----------------------------------------------------------


def test_fault_env_parsing(monkeypatch):
    monkeypatch.setenv(resilience.FAULT_ENV,
                       "kill_during_save:4, nan_loss:3:2,slow_save:250")
    assert resilience.fault_args("kill_during_save") == (4,)
    assert resilience.fault_args("nan_loss") == (3, 2)
    assert resilience.fault_args("nope") is None
    assert resilience.fault_active("kill_during_save", 4)
    assert not resilience.fault_active("kill_during_save", 5)
    assert [i for i in range(8) if resilience.fault_active("nan_loss", i)] \
        == [3, 4]
    monkeypatch.setenv(resilience.FAULT_ENV, "nan_loss:7")
    assert [i for i in range(10) if resilience.fault_active("nan_loss", i)] \
        == [7]
    monkeypatch.setenv(resilience.FAULT_ENV, "bad:spec:x")
    with pytest.raises(ValueError, match="malformed"):
        resilience.fault_args("bad")
    monkeypatch.setenv(resilience.FAULT_ENV, "")
    assert resilience.fault_args("nan_loss") is None


def test_poison_batch_makes_loss_nonfinite():
    batch = {"tokens": np.ones((2, 4), np.int64),
             "labels": np.ones((2, 4), np.int64),
             "loss_mask": np.ones((2, 4), np.float32)}
    out = resilience.poison_batch(batch)
    assert np.isinf(out["loss_mask"]).any()
    assert np.isfinite(batch["loss_mask"]).all()  # original untouched
    # masked-mean loss through an inf mask is non-finite
    losses = np.ones((2, 4), np.float32)
    loss = float((losses * out["loss_mask"]).sum() / out["loss_mask"].sum())
    assert not np.isfinite(loss)


# -- signal handler ----------------------------------------------------------


def test_signal_handler_records_multiple_signals():
    from megatron_tpu.training.signal_handler import DistributedSignalHandler

    with DistributedSignalHandler(signals=(signal.SIGUSR1,)) as h:
        assert h.signals_received() == ()
        os.kill(os.getpid(), signal.SIGUSR1)
        assert h.signals_received() == (signal.SIGUSR1,)
    # legacy single-sig ctor still works
    with DistributedSignalHandler(sig=signal.SIGUSR2) as h:
        os.kill(os.getpid(), signal.SIGUSR2)
        assert h.signals_received() == (signal.SIGUSR2,)


def test_signal_handler_second_signal_forces_exit():
    """A wedged flush can't block termination: the second signal os._exits
    with 128+signum. Needs a subprocess (os._exit would kill pytest)."""
    sh_path = os.path.join(REPO, "megatron_tpu", "training",
                           "signal_handler.py")
    script = f"""
import importlib.util, os, signal, sys, time
# load the module file directly: the package import would drag in jax,
# which is ~8s of interpreter start for a test about signal delivery
spec = importlib.util.spec_from_file_location("sh", {sh_path!r})
sh = importlib.util.module_from_spec(spec); spec.loader.exec_module(sh)
DistributedSignalHandler = sh.DistributedSignalHandler
with DistributedSignalHandler() as h:
    os.kill(os.getpid(), signal.SIGTERM)
    assert h.signals_received() == (signal.SIGTERM,)
    print("first recorded", flush=True)
    os.kill(os.getpid(), signal.SIGTERM)   # simulates a wedged flush
    time.sleep(30)
    print("NOT REACHED", flush=True)
"""
    out = subprocess.run([sys.executable, "-c", script],
                         env={**os.environ, "JAX_PLATFORMS": "cpu"},
                         capture_output=True, text=True, timeout=120)
    assert "first recorded" in out.stdout
    assert "NOT REACHED" not in out.stdout
    assert out.returncode == 128 + signal.SIGTERM
    assert "forcing exit" in out.stderr


# -- subprocess crash/recovery acceptance ------------------------------------


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    from tools import preprocess_data

    tmp = tmp_path_factory.mktemp("corpus")
    rng = np.random.default_rng(0)
    jsonl = tmp / "docs.jsonl"
    with open(jsonl, "w") as f:
        for _ in range(150):
            n = int(rng.integers(20, 60))
            f.write(json.dumps({"text": " ".join(
                str(int(x)) for x in rng.integers(0, 97, n))}) + "\n")
    prefix = str(tmp / "corpus")
    preprocess_data.main(["--input", str(jsonl), "--output_prefix", prefix,
                          "--tokenizer_type", "null", "--vocab_size", "97",
                          "--append_eod"])
    return prefix


def _run_pretrain(corpus, save, extra=(), fault=None, train_iters=8,
                  timeout=420):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["MEGATRON_TPU_FORCE_PLATFORM"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    # NB: never give these subprocesses a shared persistent XLA compile
    # cache: the fault harness SIGKILLs runs mid-flight, which can tear a
    # cache write and crash every later run that loads the entry (observed
    # as glibc heap corruption). Each run compiles from scratch.
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    env.pop(resilience.FAULT_ENV, None)
    if fault:
        env[resilience.FAULT_ENV] = fault
    return subprocess.run([
        sys.executable, os.path.join(REPO, "pretrain_gpt.py"),
        "--num_layers", "2", "--hidden_size", "32",
        "--num_attention_heads", "4", "--vocab_size", "128",
        "--seq_length", "32", "--use_rms_norm", "--glu_activation", "swiglu",
        "--fp32", "--micro_batch_size", "2", "--global_batch_size", "4",
        "--train_iters", str(train_iters), "--log_interval", "1",
        "--lr", "1e-3", "--lr_decay_style", "constant",
        "--data_path", corpus, "--split", "95,5,0",
        "--eval_interval", "100", "--save", save, "--load", save,
        "--save_interval", "2", *extra],
        env=env, capture_output=True, text=True, cwd=REPO, timeout=timeout)


def _losses_by_iteration(stdout):
    out = {}
    for m in re.finditer(r"iteration (\d+)/\d+ \|.*?lm loss: ([0-9.einf-]+)",
                         stdout):
        out[int(m.group(1))] = m.group(2)
    return out


@pytest.mark.slow  # 42s (3 subprocess runs) measured cacheless (PR 4
# re-budget); tier-1 keeps the rollback + abort subprocess runs and the
# in-process kill-free differentials (tests/test_prefetch.py)
def test_kill_during_save_resume_bitwise(tmp_path, corpus):
    """Acceptance: a run SIGKILLed mid-save (fault harness) leaves an
    uncommitted staging dir and an intact last checkpoint; the restart
    falls back to it (here through a garbage tracker too) and its
    post-resume loss curve is bitwise-identical to an uninterrupted run."""
    from megatron_tpu.training import checkpointing

    # A: uninterrupted reference run
    ref = _run_pretrain(corpus, str(tmp_path / "ref"))
    assert ref.returncode == 0, ref.stderr[-3000:]
    ref_losses = _losses_by_iteration(ref.stdout)
    assert set(ref_losses) == set(range(1, 9))

    # B1: killed while finalizing the iteration-4 checkpoint
    save = str(tmp_path / "crash")
    b1 = _run_pretrain(corpus, save, fault="kill_during_save:4")
    assert b1.returncode == -signal.SIGKILL, (b1.returncode, b1.stderr[-2000:])
    assert "kill_during_save firing" in b1.stderr
    # iteration 2 committed; iteration 4 left as an uncommitted staging dir
    assert checkpointing.read_tracker(save) == 2
    assert os.path.exists(
        checkpointing.checkpoint_dir(save, 4) + checkpointing.STAGING_SUFFIX)
    assert checkpointing.list_valid_checkpoints(save) == [2]

    # simulate the tracker itself torn by the crash: resume must FALL BACK
    with open(os.path.join(save, checkpointing.TRACKER), "w") as f:
        f.write("")

    # B2: restart resumes from the last committed checkpoint and finishes
    b2 = _run_pretrain(corpus, save)
    assert b2.returncode == 0, b2.stderr[-3000:]
    assert "falling back to iteration 2" in b2.stderr
    assert "removed uncommitted staging dirs: ['iter_0000004.tmp']" in b2.stderr
    assert not os.path.exists(
        checkpointing.checkpoint_dir(save, 4) + checkpointing.STAGING_SUFFIX)
    assert "loaded checkpoint at iteration 2" in b2.stdout
    b2_losses = _losses_by_iteration(b2.stdout)
    assert set(b2_losses) == set(range(3, 9))
    # bitwise-identical post-resume loss curve at the same iterations
    for it in range(3, 9):
        assert b2_losses[it] == ref_losses[it], (
            f"iteration {it}: resumed {b2_losses[it]} != "
            f"uninterrupted {ref_losses[it]}")
    assert checkpointing.read_tracker(save) == 8


def test_nan_window_aborts_without_rollback(tmp_path, corpus):
    """Acceptance: an injected NaN-loss window trips the sentinel into a
    clean abort — non-zero exit with a diagnostic — without
    --rollback_on_divergence."""
    out = _run_pretrain(corpus, str(tmp_path / "abort"),
                        extra=("--divergence_patience", "3"),
                        fault="nan_loss:3:4")
    assert out.returncode != 0
    assert "divergence sentinel tripped" in out.stdout
    assert "DivergenceError" in out.stderr
    assert "consecutive non-finite" in out.stderr
    # it tripped at iteration 5 (3 poisoned steps from 3) and went no further
    assert 8 not in _losses_by_iteration(out.stdout)


@pytest.mark.slow  # 3 tiny subprocess pretrain runs, ~60s on the 2-core host
def test_async_loop_subprocess_parity_with_kill_and_resume(tmp_path, corpus):
    """Oracle differential at the CLI level (ISSUE 5 acceptance): an async
    (default) run SIGKILLed mid-flight and resumed must reproduce, bitwise,
    the loss curve of an UNINTERRUPTED --no_async_loop run — the prefetch
    queue dies with the process and is rebuilt at the checkpoint's
    consumed_samples watermark with no sample loss or duplication."""
    ref = _run_pretrain(corpus, str(tmp_path / "sync_ref"),
                        extra=("--no_async_loop",))
    assert ref.returncode == 0, ref.stderr[-3000:]
    ref_losses = _losses_by_iteration(ref.stdout)
    assert set(ref_losses) == set(range(1, 9))

    save = str(tmp_path / "async_crash")
    k = _run_pretrain(corpus, save, fault="kill_at:6")
    assert k.returncode == -signal.SIGKILL, (k.returncode, k.stderr[-2000:])
    losses = _losses_by_iteration(k.stdout)
    # the pre-kill iterations the crashed async run DID report match the
    # synchronous oracle bitwise
    for it, v in losses.items():
        assert v == ref_losses[it], (it, v, ref_losses[it])

    r = _run_pretrain(corpus, save)
    assert r.returncode == 0, r.stderr[-3000:]
    # resumes from whatever save had COMMITTED at kill time (the iter-4
    # async save may still be in flight when kill_at:6 lands — falling
    # back to 2 is the correct crash semantics, and parity must hold
    # from either watermark)
    m = re.search(r"loaded checkpoint at iteration (\d+)", r.stdout)
    assert m and int(m.group(1)) in (2, 4), r.stdout[-2000:]
    losses.update(_losses_by_iteration(r.stdout))
    assert set(losses) >= set(range(1, 9)) - {5}  # 5 may die un-reported
    for it in sorted(set(losses) & set(ref_losses)):
        assert losses[it] == ref_losses[it], (
            f"iteration {it}: async kill/resume {losses[it]} != "
            f"sync oracle {ref_losses[it]}")
    from megatron_tpu.training import checkpointing

    assert checkpointing.read_tracker(save) == 8


def test_nan_window_rollback_and_continue(tmp_path, corpus):
    """Acceptance: with --rollback_on_divergence the same NaN window rolls
    back to the last good checkpoint, fast-forwards past the poison window,
    and the run completes."""
    out = _run_pretrain(corpus, str(tmp_path / "roll"),
                        extra=("--divergence_patience", "3",
                               "--rollback_on_divergence",
                               "--keep_latest_k", "2"),
                        fault="nan_loss:3:3")
    assert out.returncode == 0, out.stderr[-3000:]
    assert "rolled back to checkpoint at iteration 4" in out.stdout
    assert "post-rollback fast-forward" in out.stdout
    assert "iteration 8/8" in out.stdout
    losses = _losses_by_iteration(out.stdout)
    # post-rollback iterations trained for real, with finite losses
    for it in (6, 7, 8):
        assert float(losses[it]) == float(losses[it])  # not NaN
    from megatron_tpu.training import checkpointing

    save = str(tmp_path / "roll")
    assert checkpointing.read_tracker(save) == 8
    # keep_latest_k=2 retention pruned the older checkpoints
    assert len(checkpointing.list_valid_checkpoints(save)) <= 2

"""Fault-tolerance tests: divergence sentinel, fault-injection harness,
multi-signal handler, and the REAL crash/recovery acceptance paths —
subprocess training runs killed mid-save and poisoned with NaN windows
(ISSUE 2: crash-safe training).

Since ISSUE 5 the subprocess runs here exercise the ASYNC goodput loop by
default (background prefetcher + lagged metrics): the kill/resume and
rollback bitwise assertions below double as the prefetcher-x-resilience
interplay acceptance — no sample lost or duplicated across a
prefetch-queue rebuild. The --no_async_loop oracle differentials live in
tests/test_prefetch.py (in-process) and the slow-marked subprocess parity
test at the bottom of this file."""

import json
import os
import re
import signal
import subprocess
import sys

import numpy as np
import pytest

from megatron_tpu.training import resilience
from megatron_tpu.training.resilience import DivergenceSentinel

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- sentinel unit tests -----------------------------------------------------


def test_sentinel_nonfinite_patience():
    s = DivergenceSentinel(patience=3, spike_factor=0.0)
    assert s.observe(1.0) is None
    assert s.observe(float("nan")) is None
    assert s.observe(2.0, skipped=True) is None  # skipped counts as bad...
    assert s.observe(1.0) is None                # ...but a good step resets
    assert s.observe(float("inf")) is None
    assert s.observe(None, skipped=True) is None
    trip = s.observe(float("nan"))
    assert trip and "3 consecutive" in trip
    s.reset()
    assert s.observe(float("nan")) is None


def test_sentinel_streak_override_survives_restart():
    """The optimizer's checkpointed skip streak overrides the host counter:
    a resume that lands mid-NaN (or a crash loop faster than patience)
    keeps accumulating instead of restarting from zero."""
    s = DivergenceSentinel(patience=50, spike_factor=0.0)
    # fresh sentinel after a restart; the restored state already carries 49
    # consecutive skips
    trip = s.observe(float("nan"), skipped=True, streak=50)
    assert trip and "50 consecutive" in trip
    s.reset()
    assert s.observe(float("nan"), streak=10) is None
    assert s.nonfinite_streak == 10
    assert s.observe(1.0, streak=0) is None  # finite step resets as usual
    assert s.nonfinite_streak == 0


def test_sentinel_disabled():
    s = DivergenceSentinel(patience=0, spike_factor=0.0)
    for _ in range(50):
        assert s.observe(float("nan")) is None


def test_sentinel_loss_spike():
    s = DivergenceSentinel(patience=0, spike_factor=2.0, spike_patience=3,
                           warmup_steps=5, ema_alpha=0.5)
    for _ in range(10):
        assert s.observe(1.0) is None
    ema_before = s.ema
    assert s.observe(5.0) is None  # spike 1
    assert s.observe(5.0) is None  # spike 2
    assert s.ema == ema_before     # spikes are NOT folded into the EMA
    assert s.observe(1.0) is None  # recovery resets the spike streak
    assert s.observe(5.0) is None
    assert s.observe(5.0) is None
    trip = s.observe(5.0)
    assert trip and "loss_spike_factor" in trip
    # no trip during warmup regardless of ratio
    s2 = DivergenceSentinel(patience=0, spike_factor=2.0, spike_patience=1,
                            warmup_steps=100)
    for loss in (1.0, 100.0, 1.0, 100.0):
        assert s2.observe(loss) is None


# -- fault harness -----------------------------------------------------------


def test_fault_env_parsing(monkeypatch):
    monkeypatch.setenv(resilience.FAULT_ENV,
                       "kill_during_save:4, nan_loss:3:2,slow_save:250")
    assert resilience.fault_args("kill_during_save") == (4,)
    assert resilience.fault_args("nan_loss") == (3, 2)
    assert resilience.fault_args("nope") is None
    assert resilience.fault_active("kill_during_save", 4)
    assert not resilience.fault_active("kill_during_save", 5)
    assert [i for i in range(8) if resilience.fault_active("nan_loss", i)] \
        == [3, 4]
    monkeypatch.setenv(resilience.FAULT_ENV, "nan_loss:7")
    assert [i for i in range(10) if resilience.fault_active("nan_loss", i)] \
        == [7]
    monkeypatch.setenv(resilience.FAULT_ENV, "bad:spec:x")
    with pytest.raises(ValueError, match="malformed"):
        resilience.fault_args("bad")
    monkeypatch.setenv(resilience.FAULT_ENV, "")
    assert resilience.fault_args("nan_loss") is None


def test_poison_batch_makes_loss_nonfinite():
    batch = {"tokens": np.ones((2, 4), np.int64),
             "labels": np.ones((2, 4), np.int64),
             "loss_mask": np.ones((2, 4), np.float32)}
    out = resilience.poison_batch(batch)
    assert np.isinf(out["loss_mask"]).any()
    assert np.isfinite(batch["loss_mask"]).all()  # original untouched
    # masked-mean loss through an inf mask is non-finite
    losses = np.ones((2, 4), np.float32)
    loss = float((losses * out["loss_mask"]).sum() / out["loss_mask"].sum())
    assert not np.isfinite(loss)


def test_new_fault_kinds_parse_and_fire(monkeypatch):
    monkeypatch.setenv(resilience.FAULT_ENV,
                       "preempt_at:4,hang_step:6,corrupt_step:8")
    assert resilience.fault_active("preempt_at", 4)
    assert not resilience.fault_active("preempt_at", 5)
    assert resilience.fault_active("hang_step", 6)
    assert resilience.fault_active("corrupt_step", 8)
    assert not resilience.fault_active("corrupt_step", 4)


def test_maybe_signal_delivers_sigterm(monkeypatch):
    """preempt_at self-delivers a REAL SIGTERM that the run's own handler
    sees — a notice, not maybe_kill's unmaskable death."""
    from megatron_tpu.training.signal_handler import DistributedSignalHandler

    monkeypatch.setenv(resilience.FAULT_ENV, "preempt_at:7")
    with DistributedSignalHandler() as h:
        resilience.maybe_signal("preempt_at", 6)  # not armed for 6
        assert h.signals_received() == ()
        assert h.first_signal() is None
        resilience.maybe_signal("preempt_at", 7)
        assert h.signals_received() == (signal.SIGTERM,)
        signum, arrived = h.first_signal()
        assert signum == signal.SIGTERM and arrived > 0


def test_batch_fingerprint_identity():
    rng = np.random.default_rng(0)
    a = {"tokens": rng.integers(0, 9, (2, 4)),
         "labels": rng.integers(0, 9, (2, 4))}
    # key-insertion order must not matter; content must
    b = {"labels": a["labels"].copy(), "tokens": a["tokens"].copy()}
    assert resilience.batch_fingerprint(a) == resilience.batch_fingerprint(b)
    c = {"tokens": a["tokens"].copy(), "labels": a["labels"].copy()}
    c["tokens"][0, 0] += 1
    assert resilience.batch_fingerprint(a) != resilience.batch_fingerprint(c)
    # poisoning after fingerprinting never changes the identity (the loop
    # fingerprints BEFORE host_batch_faults)
    fp = resilience.batch_fingerprint(a)
    resilience.poison_batch(dict(a, loss_mask=np.ones((2, 4), np.float32)))
    assert resilience.batch_fingerprint(a) == fp


def test_tree_bitwise_mismatch():
    a = {"x": np.array([1.0, np.nan], np.float32),
         "y": {"z": np.array([0.0], np.float32)}}
    same = {"x": a["x"].copy(), "y": {"z": a["y"]["z"].copy()}}
    assert resilience.tree_bitwise_mismatch(a, same) == []  # NaN == NaN bits
    neg = {"x": a["x"].copy(), "y": {"z": np.array([-0.0], np.float32)}}
    bad = resilience.tree_bitwise_mismatch(a, neg)
    assert len(bad) == 1 and "z" in bad[0]  # -0.0 differs BITWISE from 0.0


def test_step_watchdog_unit():
    fired = []
    wd = resilience.StepWatchdog(0.15, lambda age: fired.append(age),
                                 poll_s=0.02).start()
    try:
        import time as _t

        # clock starts at the first beat: no fire while un-beaten (the
        # initial-compile exemption)
        _t.sleep(0.4)
        assert not fired
        # regular beats keep it alive
        for _ in range(5):
            wd.beat()
            _t.sleep(0.05)
        assert not fired
        # silence past the deadline fires exactly once
        _t.sleep(0.5)
        assert len(fired) == 1 and fired[0] >= 0.15
        _t.sleep(0.3)
        assert len(fired) == 1  # single-shot
    finally:
        wd.stop()


# -- signal handler ----------------------------------------------------------


def test_signal_handler_records_multiple_signals():
    from megatron_tpu.training.signal_handler import DistributedSignalHandler

    with DistributedSignalHandler(signals=(signal.SIGUSR1,)) as h:
        assert h.signals_received() == ()
        os.kill(os.getpid(), signal.SIGUSR1)
        assert h.signals_received() == (signal.SIGUSR1,)
    # legacy single-sig ctor still works
    with DistributedSignalHandler(sig=signal.SIGUSR2) as h:
        os.kill(os.getpid(), signal.SIGUSR2)
        assert h.signals_received() == (signal.SIGUSR2,)


def test_signal_handler_second_signal_forces_exit():
    """A wedged flush can't block termination: the second signal os._exits
    with 128+signum. Needs a subprocess (os._exit would kill pytest)."""
    sh_path = os.path.join(REPO, "megatron_tpu", "training",
                           "signal_handler.py")
    script = f"""
import importlib.util, os, signal, sys, time
# load the module file directly: the package import would drag in jax,
# which is ~8s of interpreter start for a test about signal delivery
spec = importlib.util.spec_from_file_location("sh", {sh_path!r})
sh = importlib.util.module_from_spec(spec); spec.loader.exec_module(sh)
DistributedSignalHandler = sh.DistributedSignalHandler
with DistributedSignalHandler() as h:
    os.kill(os.getpid(), signal.SIGTERM)
    assert h.signals_received() == (signal.SIGTERM,)
    print("first recorded", flush=True)
    os.kill(os.getpid(), signal.SIGTERM)   # simulates a wedged flush
    time.sleep(30)
    print("NOT REACHED", flush=True)
"""
    out = subprocess.run([sys.executable, "-c", script],
                         env={**os.environ, "JAX_PLATFORMS": "cpu"},
                         capture_output=True, text=True, timeout=120)
    assert "first recorded" in out.stdout
    assert "NOT REACHED" not in out.stdout
    assert out.returncode == 128 + signal.SIGTERM
    assert "forcing exit" in out.stderr


# -- subprocess crash/recovery acceptance ------------------------------------


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    from tools import preprocess_data

    tmp = tmp_path_factory.mktemp("corpus")
    rng = np.random.default_rng(0)
    jsonl = tmp / "docs.jsonl"
    with open(jsonl, "w") as f:
        for _ in range(150):
            n = int(rng.integers(20, 60))
            f.write(json.dumps({"text": " ".join(
                str(int(x)) for x in rng.integers(0, 97, n))}) + "\n")
    prefix = str(tmp / "corpus")
    preprocess_data.main(["--input", str(jsonl), "--output_prefix", prefix,
                          "--tokenizer_type", "null", "--vocab_size", "97",
                          "--append_eod"])
    return prefix


def _run_pretrain(corpus, save, extra=(), fault=None, train_iters=8,
                  timeout=420):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["MEGATRON_TPU_FORCE_PLATFORM"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    # NB: never give these subprocesses a shared persistent XLA compile
    # cache: the fault harness SIGKILLs runs mid-flight, which can tear a
    # cache write and crash every later run that loads the entry (observed
    # as glibc heap corruption). Each run compiles from scratch.
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    env.pop(resilience.FAULT_ENV, None)
    if fault:
        env[resilience.FAULT_ENV] = fault
    return subprocess.run([
        sys.executable, os.path.join(REPO, "pretrain_gpt.py"),
        "--num_layers", "2", "--hidden_size", "32",
        "--num_attention_heads", "4", "--vocab_size", "128",
        "--seq_length", "32", "--use_rms_norm", "--glu_activation", "swiglu",
        "--fp32", "--micro_batch_size", "2", "--global_batch_size", "4",
        "--train_iters", str(train_iters), "--log_interval", "1",
        "--lr", "1e-3", "--lr_decay_style", "constant",
        "--data_path", corpus, "--split", "95,5,0",
        "--eval_interval", "100", "--save", save, "--load", save,
        "--save_interval", "2", *extra],
        env=env, capture_output=True, text=True, cwd=REPO, timeout=timeout)


def _losses_by_iteration(stdout):
    out = {}
    for m in re.finditer(r"iteration (\d+)/\d+ \|.*?lm loss: ([0-9.einf-]+)",
                         stdout):
        out[int(m.group(1))] = m.group(2)
    return out


@pytest.mark.slow  # 42s (3 subprocess runs) measured cacheless (PR 4
# re-budget); tier-1 keeps the rollback + abort subprocess runs and the
# in-process kill-free differentials (tests/test_prefetch.py)
def test_kill_during_save_resume_bitwise(tmp_path, corpus):
    """Acceptance: a run SIGKILLed mid-save (fault harness) leaves an
    uncommitted staging dir and an intact last checkpoint; the restart
    falls back to it (here through a garbage tracker too) and its
    post-resume loss curve is bitwise-identical to an uninterrupted run."""
    from megatron_tpu.training import checkpointing

    # A: uninterrupted reference run
    ref = _run_pretrain(corpus, str(tmp_path / "ref"))
    assert ref.returncode == 0, ref.stderr[-3000:]
    ref_losses = _losses_by_iteration(ref.stdout)
    assert set(ref_losses) == set(range(1, 9))

    # B1: killed while finalizing the iteration-4 checkpoint
    save = str(tmp_path / "crash")
    b1 = _run_pretrain(corpus, save, fault="kill_during_save:4")
    assert b1.returncode == -signal.SIGKILL, (b1.returncode, b1.stderr[-2000:])
    assert "kill_during_save firing" in b1.stderr
    # iteration 2 committed; iteration 4 left as an uncommitted staging dir
    assert checkpointing.read_tracker(save) == 2
    assert os.path.exists(
        checkpointing.checkpoint_dir(save, 4) + checkpointing.STAGING_SUFFIX)
    assert checkpointing.list_valid_checkpoints(save) == [2]

    # simulate the tracker itself torn by the crash: resume must FALL BACK
    with open(os.path.join(save, checkpointing.TRACKER), "w") as f:
        f.write("")

    # B2: restart resumes from the last committed checkpoint and finishes
    b2 = _run_pretrain(corpus, save)
    assert b2.returncode == 0, b2.stderr[-3000:]
    assert "falling back to iteration 2" in b2.stderr
    assert "removed uncommitted staging dirs: ['iter_0000004.tmp']" in b2.stderr
    assert not os.path.exists(
        checkpointing.checkpoint_dir(save, 4) + checkpointing.STAGING_SUFFIX)
    assert "loaded checkpoint at iteration 2" in b2.stdout
    b2_losses = _losses_by_iteration(b2.stdout)
    assert set(b2_losses) == set(range(3, 9))
    # bitwise-identical post-resume loss curve at the same iterations
    for it in range(3, 9):
        assert b2_losses[it] == ref_losses[it], (
            f"iteration {it}: resumed {b2_losses[it]} != "
            f"uninterrupted {ref_losses[it]}")
    assert checkpointing.read_tracker(save) == 8


def test_nan_window_aborts_without_rollback(tmp_path, corpus):
    """Acceptance: an injected NaN-loss window trips the sentinel into a
    clean abort — non-zero exit with a diagnostic — without
    --rollback_on_divergence."""
    out = _run_pretrain(corpus, str(tmp_path / "abort"),
                        extra=("--divergence_patience", "3"),
                        fault="nan_loss:3:4")
    assert out.returncode != 0
    assert "divergence sentinel tripped" in out.stdout
    assert "DivergenceError" in out.stderr
    assert "consecutive non-finite" in out.stderr
    # it tripped at iteration 5 (3 poisoned steps from 3) and went no further
    assert 8 not in _losses_by_iteration(out.stdout)


@pytest.mark.slow  # 3 tiny subprocess pretrain runs, ~60s on the 2-core host
def test_async_loop_subprocess_parity_with_kill_and_resume(tmp_path, corpus):
    """Oracle differential at the CLI level (ISSUE 5 acceptance): an async
    (default) run SIGKILLed mid-flight and resumed must reproduce, bitwise,
    the loss curve of an UNINTERRUPTED --no_async_loop run — the prefetch
    queue dies with the process and is rebuilt at the checkpoint's
    consumed_samples watermark with no sample loss or duplication."""
    ref = _run_pretrain(corpus, str(tmp_path / "sync_ref"),
                        extra=("--no_async_loop",))
    assert ref.returncode == 0, ref.stderr[-3000:]
    ref_losses = _losses_by_iteration(ref.stdout)
    assert set(ref_losses) == set(range(1, 9))

    save = str(tmp_path / "async_crash")
    k = _run_pretrain(corpus, save, fault="kill_at:6")
    assert k.returncode == -signal.SIGKILL, (k.returncode, k.stderr[-2000:])
    losses = _losses_by_iteration(k.stdout)
    # the pre-kill iterations the crashed async run DID report match the
    # synchronous oracle bitwise
    for it, v in losses.items():
        assert v == ref_losses[it], (it, v, ref_losses[it])

    r = _run_pretrain(corpus, save)
    assert r.returncode == 0, r.stderr[-3000:]
    # resumes from whatever save had COMMITTED at kill time (the iter-4
    # async save may still be in flight when kill_at:6 lands — falling
    # back to 2 is the correct crash semantics, and parity must hold
    # from either watermark)
    m = re.search(r"loaded checkpoint at iteration (\d+)", r.stdout)
    assert m and int(m.group(1)) in (2, 4), r.stdout[-2000:]
    losses.update(_losses_by_iteration(r.stdout))
    assert set(losses) >= set(range(1, 9)) - {5}  # 5 may die un-reported
    for it in sorted(set(losses) & set(ref_losses)):
        assert losses[it] == ref_losses[it], (
            f"iteration {it}: async kill/resume {losses[it]} != "
            f"sync oracle {ref_losses[it]}")
    from megatron_tpu.training import checkpointing

    assert checkpointing.read_tracker(save) == 8


def test_preemption_notice_checkpoint_and_exit(tmp_path, corpus):
    """Acceptance (ISSUE 11): a SIGTERM preemption notice at an exact step
    (preempt_at fault) takes the expedited path — committed checkpoint
    bypassing --save_interval, `preemption` journal event inside
    --preempt_save_timeout, exit 0 — and the checkpoint is tagged so
    retention can never prune it."""
    from megatron_tpu.training import checkpointing
    from megatron_tpu.telemetry.journal import read_events

    save = str(tmp_path / "pre")
    tele = str(tmp_path / "tele")
    out = _run_pretrain(corpus, save, fault="preempt_at:3",
                        extra=("--telemetry_dir", tele,
                               "--preempt_save_timeout", "120",
                               # save_interval=2 would save at 2 anyway;
                               # prove the bypass with an interval the run
                               # never reaches
                               "--save_interval", "100"))
    assert out.returncode == 0, (out.returncode, out.stderr[-3000:])
    assert "preempt_at firing at iteration 3" in out.stderr
    assert "expedited synchronous save" in out.stdout
    assert "preemption checkpoint committed at iteration 3" in out.stdout
    # the notice ended the run: nothing past iteration 3
    losses = _losses_by_iteration(out.stdout)
    assert set(losses) == {1, 2, 3}
    # committed + tagged; the tag survives into verify's manifest read
    assert checkpointing.read_tracker(save) == 3
    ckpt = checkpointing.checkpoint_dir(save, 3)
    assert checkpointing.verify_checkpoint(ckpt, deep=True)[0]
    assert checkpointing.checkpoint_tags(ckpt) == ("preemption",)
    evs, _ = read_events(os.path.join(tele, "events.jsonl"))
    pre = [e for e in evs if e["kind"] == "preemption"]
    assert len(pre) == 1
    assert pre[0]["iteration"] == 3 and pre[0]["signal"] == "SIGTERM"
    assert 0 < pre[0]["notice_to_commit_ms"] < 120 * 1000
    # satellite: run_end tells preemption from operator interrupt
    run_end = [e for e in evs if e["kind"] == "run_end"][-1]
    assert run_end["received_signal"] == "SIGTERM"


@pytest.mark.slow  # one ~7s subprocess run; the deadline machinery is
# unit-covered by test_step_watchdog_unit and the tier-1 preemption run
def test_preempt_save_timeout_forces_exit(tmp_path, corpus):
    """A preemption save wedged past --preempt_save_timeout (here: the
    barrier on a slow_save-delayed in-flight async commit) force-exits
    PREEMPT_TIMEOUT_EXIT_CODE with `preemption_timeout` journaled instead
    of overstaying the notice window."""
    from megatron_tpu.telemetry.journal import read_events

    tele = str(tmp_path / "tele")
    out = _run_pretrain(corpus, str(tmp_path / "wedge"),
                        fault="slow_save:8000,preempt_at:3",
                        extra=("--telemetry_dir", tele,
                               "--preempt_save_timeout", "0.5"))
    assert out.returncode == resilience.PREEMPT_TIMEOUT_EXIT_CODE, (
        out.returncode, out.stderr[-3000:])
    assert "exceeded --preempt_save_timeout" in out.stderr
    evs, _ = read_events(os.path.join(tele, "events.jsonl"))
    assert [e for e in evs if e["kind"] == "preemption_timeout"]
    assert not [e for e in evs if e["kind"] == "preemption"]


def test_hang_step_watchdog_bundle_and_abort(tmp_path, corpus):
    """Acceptance (ISSUE 11): a hung step (hang_step fault) is ended by
    the --step_timeout_s watchdog — flight-recorder bundle on disk,
    `hang_detected` journaled, clean HANG_EXIT_CODE abort — NOT by the
    test runner's timeout kill."""
    from megatron_tpu.telemetry.journal import read_events

    tele = str(tmp_path / "tele")
    out = _run_pretrain(corpus, str(tmp_path / "hang"),
                        fault="hang_step:3", train_iters=6,
                        extra=("--telemetry_dir", tele,
                               "--step_timeout_s", "2"))
    assert out.returncode == resilience.HANG_EXIT_CODE, (
        out.returncode, out.stderr[-3000:])
    assert "hang_step firing at iteration 3" in out.stderr
    assert "step watchdog" in out.stdout
    bundles_dir = os.path.join(tele, "flight_bundles")
    bundles = os.listdir(bundles_dir)
    assert len(bundles) == 1
    bundle = os.path.join(bundles_dir, bundles[0])
    assert os.path.exists(os.path.join(bundle, "stacks.txt"))
    assert os.path.exists(os.path.join(bundle, "meta.json"))
    with open(os.path.join(bundle, "stacks.txt")) as f:
        # the hung thread's stack is in the bundle — the evidence a
        # timeout kill would have destroyed
        assert "maybe_hang" in f.read()
    evs, _ = read_events(os.path.join(tele, "events.jsonl"))
    hangs = [e for e in evs if e["kind"] == "hang_detected"]
    assert hangs and hangs[0]["iteration"] == 3


def test_replay_check_detects_corrupt_step(tmp_path):
    """Acceptance (ISSUE 11): the --replay_check_interval SDC sentinel.
    In-process pair on one tiny model: a clean run replays
    bitwise-identical; with corrupt_step armed the same run journals
    `sdc_detected` naming the mismatching leaf and aborts (SDCError)."""
    import jax

    from megatron_tpu.config import (
        ModelConfig, OptimizerConfig, RunConfig, TrainingConfig,
    )
    from megatron_tpu.telemetry.journal import read_events
    from megatron_tpu.training.pretrain import TrainLoop

    model = ModelConfig(
        num_layers=2, hidden_size=32, num_attention_heads=4, num_kv_heads=4,
        ffn_hidden_size=64, vocab_size=64, seq_length=16,
        params_dtype="float32").validate()
    rng = np.random.default_rng(0)
    # conftest's 8-fake-device CPU mesh: gbs 8 = micro 1 x dp 8
    proto = {"tokens": rng.integers(0, 64, (8, 16)).astype(np.int64),
             "labels": rng.integers(0, 64, (8, 16)).astype(np.int64),
             "loss_mask": np.ones((8, 16), np.float32)}

    def factory(consumed, gbs):
        def gen():
            while True:
                yield proto
        return gen()

    def run(tele, fault):
        os.environ.pop(resilience.FAULT_ENV, None)
        if fault:
            os.environ[resilience.FAULT_ENV] = fault
        try:
            cfg = RunConfig(
                model=model,
                optimizer=OptimizerConfig(lr=1e-3,
                                          lr_decay_style="constant"),
                training=TrainingConfig(
                    micro_batch_size=1, global_batch_size=8, train_iters=4,
                    log_interval=1 << 30, seed=0, telemetry_dir=str(tele),
                    replay_check_interval=2))
            loop = TrainLoop(cfg, log=lambda m: None)
            loop.train(factory)
        finally:
            os.environ.pop(resilience.FAULT_ENV, None)
        evs, _ = read_events(os.path.join(str(tele), "events.jsonl"))
        return evs

    evs = run(tmp_path / "clean", None)
    checks = [(e["iteration"], e["ok"]) for e in evs
              if e["kind"] == "replay_check"]
    assert checks == [(2, True), (4, True)]
    assert not [e for e in evs if e["kind"] == "sdc_detected"]

    with pytest.raises(resilience.SDCError, match="iteration 2"):
        run(tmp_path / "sdc", "corrupt_step:2")
    evs, _ = read_events(os.path.join(str(tmp_path / "sdc"),
                                      "events.jsonl"))
    sdc = [e for e in evs if e["kind"] == "sdc_detected"]
    assert len(sdc) == 1 and sdc[0]["iteration"] == 2
    assert sdc[0]["leaves"] and "params" in sdc[0]["leaves"][0]
    assert [e for e in evs if e["kind"] == "fault_injection"
            and e["fault"] == "corrupt_step"]
    # jax still healthy after the corruption round-trip
    assert np.isfinite(float(jax.numpy.sum(jax.numpy.ones(3))))


@pytest.mark.slow  # ~5s subprocess run; the sentinel itself is tier-1
# via the in-process test above — this covers only the CLI wiring + exit
def test_replay_check_cli_corrupt_step(tmp_path, corpus):
    from megatron_tpu.telemetry.journal import read_events

    tele = str(tmp_path / "tele")
    out = _run_pretrain(corpus, str(tmp_path / "sdc"),
                        fault="corrupt_step:4",
                        extra=("--telemetry_dir", tele,
                               "--replay_check_interval", "2"))
    assert out.returncode != 0
    assert "SDCError" in out.stderr
    evs, _ = read_events(os.path.join(tele, "events.jsonl"))
    sdc = [e for e in evs if e["kind"] == "sdc_detected"]
    assert sdc and sdc[0]["iteration"] == 4 and sdc[0]["leaves"]


def test_nan_window_rollback_and_continue(tmp_path, corpus):
    """Acceptance: with --rollback_on_divergence the same NaN window rolls
    back to the last good checkpoint, fast-forwards past the poison window,
    and the run completes."""
    out = _run_pretrain(corpus, str(tmp_path / "roll"),
                        extra=("--divergence_patience", "3",
                               "--rollback_on_divergence",
                               "--keep_latest_k", "2"),
                        fault="nan_loss:3:3")
    assert out.returncode == 0, out.stderr[-3000:]
    assert "rolled back to checkpoint at iteration 4" in out.stdout
    assert "post-rollback fast-forward" in out.stdout
    assert "iteration 8/8" in out.stdout
    losses = _losses_by_iteration(out.stdout)
    # post-rollback iterations trained for real, with finite losses
    for it in (6, 7, 8):
        assert float(losses[it]) == float(losses[it])  # not NaN
    from megatron_tpu.training import checkpointing

    save = str(tmp_path / "roll")
    assert checkpointing.read_tracker(save) == 8
    # keep_latest_k=2 retention pruned the older checkpoints
    assert len(checkpointing.list_valid_checkpoints(save)) <= 2

"""fp8 training GEMMs (the TransformerEngine parity row, ops/fp8.py):
quantization numerics, gradient structure, end-to-end training vs bf16,
and CLI wiring. On CPU XLA upcasts the f8 operands, so results are exactly
the quantize->matmul->rescale reference — which is what these tests pin;
real-f8-MXU behavior is on the tunnel capture list (tools/fp8_probe.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_tpu.ops.fp8 import E4M3, E5M2, fp8_matmul


def _ref_q(t, fmax):
    s = fmax / max(float(jnp.max(jnp.abs(t))), 1e-12)
    return t.astype(jnp.float32) * s, s


def test_fp8_matmul_forward_is_quantized_matmul():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((4, 8, 16)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((16, 32)), jnp.float32)
    out = fp8_matmul(x, w)
    xs, sx = _ref_q(x, float(jnp.finfo(E4M3).max))
    ws, sw = _ref_q(w, float(jnp.finfo(E4M3).max))
    ref = (xs.astype(E4M3).astype(jnp.float32)
           @ ws.astype(E4M3).astype(jnp.float32)) / (sx * sw)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)
    # and the quantized product is a real approximation of the fp32 one
    full = np.asarray(x @ w)
    err = np.abs(np.asarray(out) - full).max() / np.abs(full).max()
    assert err < 0.05, err


def test_fp8_matmul_margin_backs_off_scale():
    """Margin divides the quantization scale by 2^m. Because e4m3 is a
    FLOAT format, a power-of-two rescale is exact away from the
    over/underflow boundaries — so outputs match margin=0 bit-for-bit on
    ordinary data (asserted: margin costs nothing) and the headroom only
    matters for values that would saturate under a stale scale (moot
    under current scaling, kept for reference CLI parity)."""
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((8, 16)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)
    out0 = fp8_matmul(x, w, margin=0)
    out2 = fp8_matmul(x, w, margin=2)
    full = np.asarray(x @ w)
    for o in (out0, out2):
        assert np.abs(np.asarray(o) - full).max() / np.abs(full).max() < 0.1
    np.testing.assert_array_equal(np.asarray(out0), np.asarray(out2))


def test_fp8_inf_amax_degrades_to_unit_scale():
    """An inf in the tensor must poison only itself, not the whole GEMM:
    amax=inf -> scale 1 (NOT fmax/inf = 0, which is finite and would NaN
    every element through the epilogue divide)."""
    x = jnp.asarray([[1.0, jnp.inf], [2.0, 3.0]], jnp.float32)
    w = jnp.eye(2, dtype=jnp.float32)
    out = np.asarray(fp8_matmul(x, w))
    assert np.isfinite(out[1]).all(), out  # untouched row stays finite
    assert not np.isfinite(out[0]).all()   # the inf row saturates/infs


def test_fp8_matmul_grads_match_quantized_reference():
    """bwd must be the e5m2(g) x e4m3(w/x) GEMMs with the scale epilogue —
    checked against hand-built quantized grads (hybrid format)."""
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((4, 16)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)
    g = jnp.asarray(rng.standard_normal((4, 8)), jnp.float32)

    def f(x, w):
        return jnp.sum(fp8_matmul(x, w) * g)

    dx, dw = jax.grad(f, argnums=(0, 1))(x, w)

    xs, sx = _ref_q(x, float(jnp.finfo(E4M3).max))
    ws, sw = _ref_q(w, float(jnp.finfo(E4M3).max))
    gs, sg = _ref_q(g, float(jnp.finfo(E5M2).max))
    x8 = xs.astype(E4M3).astype(jnp.float32)
    w8 = ws.astype(E4M3).astype(jnp.float32)
    g8 = gs.astype(E5M2).astype(jnp.float32)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(g8 @ w8.T) / (sg * sw),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(dw), np.asarray(x8.T @ g8) / (sx * sg),
                               rtol=1e-5, atol=1e-6)


def test_fp8_no_wgrad_runs_fp32_wgrad():
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((32, 16)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)
    g = jnp.asarray(rng.standard_normal((32, 8)), jnp.float32)

    def loss(wgrad):
        def f(x, w):
            return jnp.sum(fp8_matmul(x, w, fp8_wgrad=wgrad) * g)
        return jax.grad(f, argnums=1)(x, w)

    dw_fp8 = np.asarray(loss(True))
    dw_hi = np.asarray(loss(False))
    # the higher-precision wgrad is closer to the true fp32 wgrad computed
    # on the same quantized activations
    xs, sx = _ref_q(x, float(jnp.finfo(E4M3).max))
    x8 = xs.astype(E4M3).astype(jnp.float32)
    true = np.asarray(x8.T @ g) / sx
    assert np.abs(dw_hi - true).max() <= np.abs(dw_fp8 - true).max() + 1e-6


@pytest.mark.slow  # 12s measured cacheless (PR 4 tier-1 re-budget);
# the TP-sharding exactness + probe tests keep fp8 coverage in tier-1
def test_fp8_training_tracks_bf16():
    """10 optimizer steps on a tiny llama: the fp8-hybrid loss curve stays
    within a few percent of the bf16 curve and both learn (the reference's
    TE fp8 contract — numerically-degraded-but-training)."""
    from megatron_tpu.models import presets
    from megatron_tpu.models.language_model import lm_loss
    from megatron_tpu.models.params import init_params
    from megatron_tpu.config import OptimizerConfig
    from megatron_tpu.training.optimizer import (init_train_state,
                                                 make_optimizer_step)

    def run(fp8_format):
        cfg = presets.tiny(vocab_size=128, seq_length=32, hidden_size=64,
                           num_layers=2, num_attention_heads=4,
                           ffn_hidden_size=128, params_dtype="float32",
                           fp8_format=fp8_format)
        params = init_params(cfg, jax.random.PRNGKey(0))
        opt = OptimizerConfig(lr=1e-3, lr_decay_style="constant")
        state = init_train_state(opt, params)
        step_fn = make_optimizer_step(opt, train_iters=10)
        rng = np.random.default_rng(0)
        batch = {
            "tokens": jnp.asarray(rng.integers(0, 128, (4, 32)), jnp.int32),
            "labels": jnp.asarray(rng.integers(0, 128, (4, 32)), jnp.int32),
            "loss_mask": jnp.ones((4, 32), jnp.float32)}

        @jax.jit
        def one(state):
            loss, grads = jax.value_and_grad(
                lambda p: lm_loss(cfg, p, batch)[0])(state.params)
            state, _ = step_fn(state, grads)
            return state, loss

        losses = []
        for _ in range(10):
            state, loss = one(state)
            losses.append(float(loss))
        return losses

    bf = run(None)
    f8 = run("hybrid")
    assert all(np.isfinite(f8))
    assert f8[-1] < f8[0]  # fp8 training learns
    for a, b in zip(f8, bf):
        assert abs(a - b) / b < 0.05, (a, b)


def test_fp8_exact_under_tensor_parallel_sharding():
    """fp8 GEMMs compose with GSPMD sharding: the per-tensor amax is a
    global reduction over the sharded weight, so tp2 x dp loss and grads
    equal the unsharded run exactly (fp32 params on CPU)."""
    from jax.sharding import NamedSharding
    from megatron_tpu.config import ParallelConfig
    from megatron_tpu.models import presets
    from megatron_tpu.models.language_model import lm_loss
    from megatron_tpu.models.params import init_params, param_specs
    from megatron_tpu.parallel.mesh import build_mesh
    from megatron_tpu.parallel.sharding import batch_spec, shard_tree

    cfg = presets.tiny(vocab_size=128, seq_length=32, hidden_size=64,
                       num_layers=2, num_attention_heads=4,
                       ffn_hidden_size=128, params_dtype="float32",
                       fp8_format="hybrid")
    params = init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    batch = {"tokens": jnp.asarray(rng.integers(0, 128, (4, 32)), jnp.int32),
             "labels": jnp.asarray(rng.integers(0, 128, (4, 32)), jnp.int32),
             "loss_mask": jnp.ones((4, 32), jnp.float32)}
    l_ref, g_ref = jax.value_and_grad(
        lambda p: lm_loss(cfg, p, batch)[0])(params)

    rt = build_mesh(ParallelConfig(tensor_parallel=2,
                                   sequence_parallel=True))
    sp = shard_tree(rt, params, param_specs(cfg))
    sb = {k: jax.device_put(v, NamedSharding(rt.mesh, batch_spec()))
          for k, v in batch.items()}
    with jax.sharding.set_mesh(rt.mesh):
        l_tp, g_tp = jax.jit(jax.value_and_grad(
            lambda p, b: lm_loss(cfg, p, b)[0]))(sp, sb)
    np.testing.assert_allclose(float(l_tp), float(l_ref), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(g_tp), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


def test_fp8_cli_flags():
    from megatron_tpu.arguments import args_to_run_config, parse_args

    BASE = ["--num_layers", "2", "--hidden_size", "32",
            "--num_attention_heads", "4", "--seq_length", "32",
            "--vocab_size", "128", "--micro_batch_size", "1",
            "--global_batch_size", "1"]

    run = args_to_run_config(parse_args(
        BASE + ["--fp8_hybrid", "--fp8_margin", "1", "--no_fp8_wgrad"]))
    assert run.model.fp8_format == "hybrid"
    assert run.model.fp8_margin == 1
    assert run.model.fp8_wgrad is False

    import pytest

    with pytest.raises(ValueError, match="both fp8"):
        args_to_run_config(parse_args(
            BASE + ["--fp8_e4m3", "--fp8_hybrid"]))

"""Checkpoint save/load tests, including cross-topology restore — the
capability that replaces the reference's offline reshard tool-chain
(tools/checkpoint_util.py)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_tpu.config import OptimizerConfig, ParallelConfig
from megatron_tpu.models import presets
from megatron_tpu.models.params import init_params, param_specs
from megatron_tpu.parallel.mesh import build_mesh
from megatron_tpu.parallel.sharding import shard_tree
from megatron_tpu.training import checkpointing
from megatron_tpu.training.optimizer import init_train_state


def _state(seed=0):
    cfg = presets.tiny(vocab_size=64, seq_length=16)
    params = init_params(cfg, jax.random.PRNGKey(seed))
    opt_cfg = OptimizerConfig(lr=1e-3)
    return cfg, init_train_state(opt_cfg, params)


def test_save_load_roundtrip(tmp_path):
    cfg, state = _state()
    save = str(tmp_path / "ckpt")
    path = checkpointing.save_checkpoint(save, state, iteration=7,
                                         consumed_samples=123,
                                         config={"model": {"num_layers": 2}})
    assert os.path.exists(os.path.join(save, checkpointing.TRACKER))
    assert checkpointing.read_tracker(save) == 7

    _, template = _state(seed=99)  # different values, same structure
    restored, it, consumed = checkpointing.load_checkpoint(save, template)
    assert it == 7 and consumed == 123
    for a, b in zip(jax.tree.leaves(restored.params), jax.tree.leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(restored.mu), jax.tree.leaves(state.mu)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_finetune_load_resets_optimizer(tmp_path):
    cfg, state = _state()
    # dirty the moments so we can see them reset
    state = state.replace(mu=jax.tree.map(lambda x: x + 1.0, state.mu),
                          step=jnp.asarray(55, jnp.int32))
    save = str(tmp_path / "ckpt")
    checkpointing.save_checkpoint(save, state, iteration=55,
                                  consumed_samples=999)
    _, template = _state(seed=99)
    restored, it, consumed = checkpointing.load_checkpoint(
        save, template, finetune=True)
    assert it == 0 and consumed == 0
    assert int(restored.step) == 0
    for a, b in zip(jax.tree.leaves(restored.mu), jax.tree.leaves(template.mu)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # but weights came from the checkpoint
    for a, b in zip(jax.tree.leaves(restored.params), jax.tree.leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_cross_topology_restore(tmp_path):
    """Save unsharded, restore onto a tp=4 mesh — no reshard tool needed."""
    cfg, state = _state()
    save = str(tmp_path / "ckpt")
    checkpointing.save_checkpoint(save, state, iteration=1)

    rt = build_mesh(ParallelConfig(tensor_parallel=4))
    specs = param_specs(cfg)
    params_sharded = shard_tree(rt, init_params(cfg, jax.random.PRNGKey(9)), specs)
    template = init_train_state(OptimizerConfig(lr=1e-3), params_sharded)
    from megatron_tpu.training.optimizer import train_state_specs
    from megatron_tpu.parallel.sharding import tree_shardings

    from jax.sharding import NamedSharding, PartitionSpec as P

    st_specs = train_state_specs(specs, params_sharded, rt.dp, zero1=True)
    shardings = jax.tree.map(
        lambda s: NamedSharding(rt.mesh, s), st_specs,
        is_leaf=lambda s: isinstance(s, P))
    restored, _, _ = checkpointing.load_checkpoint(
        save, template, shardings=shardings)
    wq = restored.params["layers"]["attn"]["wq"]
    assert "tensor" in str(wq.sharding.spec)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(wq)),
        np.asarray(jax.device_get(state.params["layers"]["attn"]["wq"])))


def test_cross_topology_restore_expert_axis(tmp_path):
    """MoE checkpoints are topology-free across the EXPERT axis too:
    save unsharded, restore onto ep2 x tp2 (expert weights sharded E/ep,
    ZeRO-1 over the combined batch axes), then back onto a dp-only mesh —
    expert weights exact both ways."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from megatron_tpu.training.optimizer import train_state_specs

    cfg = presets.tiny(vocab_size=64, seq_length=16, num_experts=4,
                       moe_top_k=2, ffn_hidden_size=32)
    params = init_params(cfg, jax.random.PRNGKey(3))
    state = init_train_state(OptimizerConfig(lr=1e-3), params)
    save = str(tmp_path / "moe_ckpt")
    checkpointing.save_checkpoint(save, state, iteration=1)
    ref_w = np.asarray(jax.device_get(params["layers"]["moe"]["w_in"]))

    for par in (ParallelConfig(expert_parallel=2, tensor_parallel=2),
                ParallelConfig()):
        rt = build_mesh(par)
        specs = param_specs(cfg)
        sharded = shard_tree(rt, init_params(cfg, jax.random.PRNGKey(9)),
                             specs)
        template = init_train_state(OptimizerConfig(lr=1e-3), sharded)
        st_specs = train_state_specs(specs, sharded, rt.dp, zero1=True,
                                     ep=rt.ep)
        shardings = jax.tree.map(
            lambda s: NamedSharding(rt.mesh, s), st_specs,
            is_leaf=lambda s: isinstance(s, P))
        restored, _, _ = checkpointing.load_checkpoint(
            save, template, shardings=shardings)
        w = restored.params["layers"]["moe"]["w_in"]
        if rt.ep > 1:
            assert "expert" in str(w.sharding.spec)
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(w)), ref_w)


def test_missing_checkpoint_raises(tmp_path):
    _, template = _state()
    with pytest.raises(FileNotFoundError):
        checkpointing.load_checkpoint(str(tmp_path / "nope"), template)


def test_config_compat_check():
    checkpointing.check_config_compatibility(
        {"model": {"num_layers": 2}}, {"model": {"num_layers": 2}})
    with pytest.raises(ValueError, match="num_layers"):
        checkpointing.check_config_compatibility(
            {"model": {"num_layers": 2}}, {"model": {"num_layers": 4}})
    # same-shape drift (weights restore cleanly but the forward function
    # differs) must be caught too — the silent-killer class
    with pytest.raises(ValueError, match="rope_theta"):
        checkpointing.check_config_compatibility(
            {"model": {"rope_theta": 1e4}}, {"model": {"rope_theta": 1e6}})
    # all mismatches reported at once
    with pytest.raises(ValueError, match="(?s)normalization.*activation"):
        checkpointing.check_config_compatibility(
            {"model": {"normalization": "rmsnorm", "activation": "swiglu"}},
            {"model": {"normalization": "layernorm", "activation": "gelu"}})


def test_resume_with_mismatched_config_raises(tmp_path):
    """A resume against a same-shape-drifted config fails loudly BEFORE
    restore, and --finetune deliberately bypasses the check (VERDICT r3
    next-round #3; ref: check_checkpoint_args, checkpointing.py:35-66)."""
    import dataclasses

    cfg, state = _state()
    _, template = _state(seed=99)
    saved_cfg = {"model": dataclasses.asdict(cfg), "parallel": {},
                 "optimizer": {}, "training": {}}
    checkpointing.save_checkpoint(str(tmp_path), state, iteration=1,
                                  consumed_samples=4, config=saved_cfg)

    drifted = {**saved_cfg,
               "model": {**saved_cfg["model"], "rope_theta": 1e6}}
    with pytest.raises(ValueError, match="rope_theta"):
        checkpointing.load_checkpoint(str(tmp_path), template,
                                      config=drifted)
    # same config resumes; finetune adopts the weights despite the drift
    _, it, _ = checkpointing.load_checkpoint(str(tmp_path), template,
                                             config=saved_cfg)
    assert it == 1
    restored, it, consumed = checkpointing.load_checkpoint(
        str(tmp_path), template, config=drifted, finetune=True)
    assert (it, consumed) == (0, 0)
    # a topology change is NOT an architecture change: parallel/training
    # sections are never part of the check
    retopo = {**saved_cfg, "parallel": {"tensor_parallel": 2}}
    checkpointing.load_checkpoint(str(tmp_path), template, config=retopo)


def test_checkpoint_util_copy_and_cast(tmp_path):
    """tools/checkpoint_util.py: copy a checkpoint, cast params to bf16,
    drop optimizer state; result loads and matches (ref checkpoint_util's
    remaining real uses — resharding itself is free here)."""
    import jax
    import numpy as np

    from megatron_tpu.config import (
        ModelConfig, OptimizerConfig, ParallelConfig, RunConfig,
        TrainingConfig,
    )
    from megatron_tpu.models.params import init_params
    from megatron_tpu.training import checkpointing
    from megatron_tpu.training.optimizer import init_train_state
    from tools import checkpoint_util

    model = ModelConfig(num_layers=2, hidden_size=32, num_attention_heads=4,
                        num_kv_heads=2, ffn_hidden_size=64, vocab_size=64,
                        seq_length=16, params_dtype="float32").validate()
    cfg = RunConfig(model=model, parallel=ParallelConfig(),
                    optimizer=OptimizerConfig(lr=1e-3,
                                              lr_decay_style="constant"),
                    training=TrainingConfig(micro_batch_size=1,
                                            global_batch_size=1))
    params = init_params(model, jax.random.PRNGKey(3))
    state = init_train_state(cfg.optimizer, params)
    src = str(tmp_path / "src")
    checkpointing.save_checkpoint(src, state, 7, 123, config=cfg.to_dict())

    dst = str(tmp_path / "dst")
    checkpoint_util.main(["--load", src, "--save", dst,
                          "--target_params_dtype", "bfloat16",
                          "--params_only"])

    assert checkpointing.read_tracker(dst) == 7
    import json
    import os

    meta = json.load(open(os.path.join(
        checkpointing.checkpoint_dir(dst, 7), "meta.json")))
    assert meta["config"]["model"]["params_dtype"] == "bfloat16"
    model_bf16 = ModelConfig(**meta["config"]["model"]).validate()
    p2 = checkpointing.load_params_only(
        dst, init_params(model_bf16, jax.random.PRNGKey(0)))
    a = np.asarray(jax.tree.leaves(p2)[0], np.float32)
    b = np.asarray(jax.tree.leaves(params)[0], np.float32)
    np.testing.assert_allclose(a, b, rtol=1e-2, atol=1e-2)  # bf16 round


def _truncate_largest_state_file(ckpt_dir):
    """Chop the biggest array file in half — a torn write."""
    import glob

    files = [p for p in glob.glob(os.path.join(ckpt_dir, "state", "**", "*"),
                                  recursive=True) if os.path.isfile(p)]
    big = max(files, key=os.path.getsize)
    with open(big, "r+b") as f:
        f.truncate(os.path.getsize(big) // 2)
    return big


def test_save_is_manifested_and_verifiable(tmp_path):
    """Every save commits a manifest; verify_checkpoint passes shallow and
    deep; a flipped byte fails only the deep check, a truncation both."""
    _, state = _state()
    save = str(tmp_path / "ckpt")
    checkpointing.save_checkpoint(save, state, 5, 50)
    path = checkpointing.checkpoint_dir(save, 5)
    assert os.path.exists(os.path.join(path, checkpointing.MANIFEST))
    assert checkpointing.verify_checkpoint(path)[0]
    assert checkpointing.verify_checkpoint(path, deep=True)[0]
    assert checkpointing.list_valid_checkpoints(save) == [5]

    big = _truncate_largest_state_file(path)
    ok, detail = checkpointing.verify_checkpoint(path)
    assert not ok and "size mismatch" in detail

    # same-size corruption: only the deep (crc32) check catches it
    checkpointing.save_checkpoint(save, state, 5, 50)  # fresh re-save
    import glob

    files = [p for p in glob.glob(os.path.join(path, "state", "**", "*"),
                                  recursive=True) if os.path.isfile(p)]
    big = max(files, key=os.path.getsize)
    size = os.path.getsize(big)
    with open(big, "r+b") as f:
        f.seek(size // 2)
        f.write(bytes((b ^ 0xFF) for b in open(big, "rb").read()[size // 2:
                                                                 size // 2 + 64]))
    assert os.path.getsize(big) == size
    ok, _ = checkpointing.verify_checkpoint(path)
    assert ok  # shallow: sizes still match
    ok, detail = checkpointing.verify_checkpoint(path, deep=True)
    assert not ok and "checksum mismatch" in detail


def test_corrupt_scenarios_resolve_to_newest_valid(tmp_path):
    """The ISSUE's corrupt-checkpoint matrix: truncated array file, garbage
    tracker, stale staging dir, missing meta.json — each resolves to the
    newest VALID checkpoint via fallback resume instead of raising."""
    import json
    import warnings

    cfg, state = _state()
    _, template = _state(seed=99)
    save = str(tmp_path / "a")
    for it in (2, 4, 6):
        checkpointing.save_checkpoint(save, state, it, it * 10)

    # 1) truncated array file in the newest checkpoint
    _truncate_largest_state_file(checkpointing.checkpoint_dir(save, 6))
    with pytest.warns(UserWarning, match="falling back to iteration 4"):
        _, it, consumed = checkpointing.load_checkpoint(save, template)
    assert (it, consumed) == (4, 40)

    # 2) garbage tracker on top of that (torn tracker write)
    with open(os.path.join(save, checkpointing.TRACKER), "w") as f:
        f.write("\x00garbage")
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert checkpointing.read_tracker(save) is None
        _, it, _ = checkpointing.load_checkpoint(save, template)
    assert it == 4
    assert any("tracker" in str(x.message) for x in w)

    # 3) stale staging dir: never listed as valid, cleaned by fallback
    stage = checkpointing.checkpoint_dir(save, 8) + checkpointing.STAGING_SUFFIX
    os.makedirs(os.path.join(stage, "state"))
    with open(os.path.join(stage, "state", "junk"), "w") as f:
        f.write("x")
    assert checkpointing.list_valid_checkpoints(save) == [2, 4]
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        _, it, _ = checkpointing.load_checkpoint(save, template)
    assert it == 4
    assert not os.path.exists(stage)

    # 4) missing meta.json (manifest present -> detected)
    save_b = str(tmp_path / "b")
    for it in (2, 4):
        checkpointing.save_checkpoint(save_b, state, it, it * 10)
    os.remove(os.path.join(checkpointing.checkpoint_dir(save_b, 4),
                           "meta.json"))
    ok, detail = checkpointing.verify_checkpoint(
        checkpointing.checkpoint_dir(save_b, 4))
    assert not ok and "meta.json" in detail
    with pytest.warns(UserWarning, match="falling back to iteration 2"):
        _, it, consumed = checkpointing.load_checkpoint(save_b, template)
    assert (it, consumed) == (2, 20)

    # an explicitly pinned iteration still fails hard on corruption
    with pytest.raises(Exception):
        checkpointing.load_checkpoint(save_b, template, iteration=4)


def test_async_saver_commits_prunes_and_flushes(tmp_path):
    """AsyncCheckpointSaver: commits on wait/close, keep_latest_k prunes
    only committed older checkpoints, init cleans stale staging dirs."""
    _, state = _state()
    save = str(tmp_path / "ckpt")
    stale = checkpointing.checkpoint_dir(save, 9) + checkpointing.STAGING_SUFFIX
    os.makedirs(stale)
    logs = []
    saver = checkpointing.AsyncCheckpointSaver(save, keep_latest_k=2,
                                               log=logs.append)
    assert not os.path.exists(stale)  # init cleanup
    for it in (1, 2, 3):
        saver.save(state, it, it * 10)
    saver.close()
    assert checkpointing.read_tracker(save) == 3
    assert checkpointing.list_valid_checkpoints(save) == [2, 3]
    assert any("pruned" in l for l in logs)
    # everything still on disk verifies deep
    for it in (2, 3):
        assert checkpointing.verify_checkpoint(
            checkpointing.checkpoint_dir(save, it), deep=True)[0]


def test_async_save_overlaps_compute(tmp_path, monkeypatch):
    """Acceptance: train-loop stall per save is measurably below the
    synchronous baseline. A slow_save fault injects a 400 ms commit delay;
    the async save() call must return well before it while the sync path
    eats it in-line. (Real no-fault stalls are printed as bench evidence.)"""
    import time

    _, state = _state()

    def stall(async_save, tag, env):
        monkeypatch.setenv("MEGATRON_TPU_FAULT", env)
        saver = checkpointing.AsyncCheckpointSaver(
            str(tmp_path / tag), async_save=async_save)
        t0 = time.monotonic()
        saver.save(state, 1, 10)
        dt = time.monotonic() - t0
        saver.close()
        return dt

    async_stall = stall(True, "a", "slow_save:400")
    sync_stall = stall(False, "s", "slow_save:400")
    assert async_stall < sync_stall
    assert sync_stall >= 0.4  # ate the injected commit delay in-line
    assert async_stall < 0.4  # returned before the commit finished

    monkeypatch.delenv("MEGATRON_TPU_FAULT")
    real_async = stall(True, "ra", "")
    real_sync = stall(False, "rs", "")
    print(f"save stall: async {real_async*1e3:.1f} ms vs "
          f"sync {real_sync*1e3:.1f} ms (no fault), "
          f"{async_stall*1e3:.1f} vs {sync_stall*1e3:.1f} ms (400 ms commit delay)")
    for tag in ("a", "s", "ra", "rs"):
        assert checkpointing.list_valid_checkpoints(str(tmp_path / tag)) == [1]


def test_load_params_only_corruption_not_masked(tmp_path):
    """Real corruption of the fp32 master arrays must RAISE, not silently
    fall back to params (the bare-except bug this PR removes)."""
    import dataclasses

    import jax.numpy as jnp

    cfg, state = _state()
    # give the checkpoint a real master tree (bf16 params + fp32 master)
    bf16 = jax.tree.map(lambda x: x.astype(jnp.bfloat16), state.params)
    state = dataclasses.replace(
        state, params=bf16,
        master=jax.tree.map(lambda x: x.astype(jnp.float32), state.params))
    save = str(tmp_path / "ckpt")
    checkpointing.save_checkpoint(save, state, 7, 70)
    # sanity: intact checkpoint restores via the master tree
    p = checkpointing.load_params_only(save, bf16, iteration=7)
    assert jax.tree.leaves(p)[0].dtype == jnp.bfloat16

    _truncate_largest_state_file(checkpointing.checkpoint_dir(save, 7))
    with pytest.raises(Exception):
        checkpointing.load_params_only(save, bf16, iteration=7)


def test_pre_field_checkpoint_still_loads(tmp_path):
    """A checkpoint whose TrainState predates a newly added field (e.g.
    nonfinite_streak) still restores — the missing field fills from the
    template with a warning, everything else comes from the checkpoint."""
    import json

    import orbax.checkpoint as ocp

    cfg, state = _state()
    save = str(tmp_path / "old")
    # simulate the pre-PR on-disk format: same layout, state tree WITHOUT
    # the new field
    old_tree = {"params": state.params, "master": None, "mu": state.mu,
                "nu": state.nu, "step": state.step, "scaler": None}
    path = checkpointing.checkpoint_dir(save, 5)
    os.makedirs(save, exist_ok=True)
    ck = ocp.StandardCheckpointer()
    ck.save(os.path.join(path, "state"), old_tree)
    ck.wait_until_finished()
    with open(os.path.join(path, "meta.json"), "w") as f:
        json.dump({"iteration": 5, "consumed_train_samples": 50,
                   "checkpoint_version": "tpu-1.0", "config": {}}, f)
    with open(os.path.join(save, checkpointing.TRACKER), "w") as f:
        f.write("5")

    _, template = _state(seed=99)
    with pytest.warns(UserWarning, match="predates TrainState fields"):
        restored, it, consumed = checkpointing.load_checkpoint(save, template)
    assert (it, consumed) == (5, 50)
    assert int(restored.nonfinite_streak) == 0
    for a, b in zip(jax.tree.leaves(restored.params),
                    jax.tree.leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # a checkpoint with fields we do NOT know still fails hard
    new_tree = dict(old_tree, from_the_future=state.step)
    path2 = checkpointing.checkpoint_dir(save, 7)
    ck.save(os.path.join(path2, "state"), new_tree)
    ck.wait_until_finished()
    with open(os.path.join(path2, "meta.json"), "w") as f:
        json.dump({"iteration": 7, "consumed_train_samples": 70,
                   "checkpoint_version": "tpu-1.0", "config": {}}, f)
    with pytest.raises(ValueError, match="unknown TrainState fields"):
        checkpointing.load_checkpoint(save, template, iteration=7)


def test_resave_crash_window_recovers_displaced_checkpoint(tmp_path):
    """A same-iteration re-save shoves the old committed dir aside before
    publishing (never rmtree-first). If the process dies between the two
    renames, the displaced `.old` dir is the ONLY copy — resume must
    rename it back and load it."""
    _, state = _state()
    _, template = _state(seed=99)
    save = str(tmp_path)
    checkpointing.save_checkpoint(save, state, 4, 40)
    final = checkpointing.checkpoint_dir(save, 4)
    # simulate the kill between "old shoved aside" and "new published"
    os.replace(final, final + checkpointing.DISPLACED_SUFFIX)
    assert checkpointing.list_valid_checkpoints(save) == []
    with pytest.warns(UserWarning, match="falling back to iteration 4"):
        restored, it, consumed = checkpointing.load_checkpoint(save, template)
    assert (it, consumed) == (4, 40)
    assert os.path.isdir(final)
    assert not os.path.exists(final + checkpointing.DISPLACED_SUFFIX)
    # and a re-save over the recovered dir commits cleanly
    checkpointing.save_checkpoint(save, state, 4, 44)
    assert checkpointing.verify_checkpoint(final, deep=True)[0]


def test_cleanup_staging_age_guard(tmp_path):
    """checkpoint_util-style external pruning must not delete a staging
    dir that a live run's async save is writing into."""
    save = str(tmp_path)
    stage = checkpointing.checkpoint_dir(save, 3) + checkpointing.STAGING_SUFFIX
    os.makedirs(os.path.join(stage, "state"))
    with open(os.path.join(stage, "state", "d"), "w") as f:
        f.write("x")  # freshly written => a live writer
    assert checkpointing.cleanup_staging(save, min_age_seconds=3600) == []
    assert os.path.isdir(stage)
    # the owner (age 0) still removes it
    assert checkpointing.cleanup_staging(save) == ["iter_0000003.tmp"]
    assert not os.path.exists(stage)


def test_checkpoint_util_verify_and_prune(tmp_path, capsys):
    """tools/checkpoint_util.py verify/prune subcommands on tiny real
    checkpoints (ISSUE 2 satellite)."""
    from tools import checkpoint_util

    _, state = _state()
    save = str(tmp_path / "run")
    for it in (1, 2, 3):
        checkpointing.save_checkpoint(save, state, it, it)

    results = checkpoint_util.main(["verify", "--load", save, "--deep"])
    assert [ok for _, ok in results] == [True, True, True]

    _truncate_largest_state_file(checkpointing.checkpoint_dir(save, 2))
    with pytest.raises(SystemExit):
        checkpoint_util.main(["verify", "--load", save])
    out = capsys.readouterr().out
    assert "INVALID" in out and "size mismatch" in out

    pruned = checkpoint_util.main(["prune", "--load", save,
                                   "--keep_latest_k", "1", "--dry_run"])
    assert pruned == [1, 2]
    assert checkpointing.committed_iterations(save) == [1, 2, 3]
    pruned = checkpoint_util.main(["prune", "--load", save,
                                   "--keep_latest_k", "1"])
    assert pruned == [1, 2]
    assert checkpointing.committed_iterations(save) == [3]
    assert checkpointing.read_tracker(save) == 3


def test_restore_never_uses_sharding_from_file_fallback(tmp_path, recwarn):
    """Every restore path passes explicit target shardings (template leaf
    placement when the caller gives none) — orbax's sharding-from-file
    fallback is deprecated-ish and unsafe across topologies (VERDICT r2
    weak #8)."""
    import warnings

    import jax
    from megatron_tpu.config import OptimizerConfig
    from megatron_tpu.models import presets
    from megatron_tpu.models.params import init_params
    from megatron_tpu.training import checkpointing
    from megatron_tpu.training.optimizer import init_train_state

    cfg = presets.tiny(vocab_size=64, seq_length=16, hidden_size=32,
                       num_layers=2, num_attention_heads=4, num_kv_heads=2,
                       ffn_hidden_size=64)
    params = init_params(cfg, jax.random.PRNGKey(0))
    state = init_train_state(OptimizerConfig(lr=1e-3), params)
    save = str(tmp_path / "ckpt")
    checkpointing.save_checkpoint(save, state, 3, 12)

    with warnings.catch_warnings():
        warnings.simplefilter("error", UserWarning)
        restored, it, consumed = checkpointing.load_checkpoint(save, state)
        assert (it, consumed) == (3, 12)
        p = checkpointing.load_params_only(save, params)
    jax.block_until_ready(restored.params)
    jax.block_until_ready(p)

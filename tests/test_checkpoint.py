"""Checkpoint save/load tests, including cross-topology restore — the
capability that replaces the reference's offline reshard tool-chain
(tools/checkpoint_util.py)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_tpu.config import OptimizerConfig, ParallelConfig
from megatron_tpu.models import presets
from megatron_tpu.models.params import init_params, param_specs
from megatron_tpu.parallel.mesh import build_mesh
from megatron_tpu.parallel.sharding import shard_tree
from megatron_tpu.training import checkpointing
from megatron_tpu.training.optimizer import init_train_state


def _state(seed=0):
    cfg = presets.tiny(vocab_size=64, seq_length=16)
    params = init_params(cfg, jax.random.PRNGKey(seed))
    opt_cfg = OptimizerConfig(lr=1e-3)
    return cfg, init_train_state(opt_cfg, params)


def test_save_load_roundtrip(tmp_path):
    cfg, state = _state()
    save = str(tmp_path / "ckpt")
    path = checkpointing.save_checkpoint(save, state, iteration=7,
                                         consumed_samples=123,
                                         config={"model": {"num_layers": 2}})
    assert os.path.exists(os.path.join(save, checkpointing.TRACKER))
    assert checkpointing.read_tracker(save) == 7

    _, template = _state(seed=99)  # different values, same structure
    restored, it, consumed = checkpointing.load_checkpoint(save, template)
    assert it == 7 and consumed == 123
    for a, b in zip(jax.tree.leaves(restored.params), jax.tree.leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(restored.mu), jax.tree.leaves(state.mu)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_finetune_load_resets_optimizer(tmp_path):
    cfg, state = _state()
    # dirty the moments so we can see them reset
    state = state.replace(mu=jax.tree.map(lambda x: x + 1.0, state.mu),
                          step=jnp.asarray(55, jnp.int32))
    save = str(tmp_path / "ckpt")
    checkpointing.save_checkpoint(save, state, iteration=55,
                                  consumed_samples=999)
    _, template = _state(seed=99)
    restored, it, consumed = checkpointing.load_checkpoint(
        save, template, finetune=True)
    assert it == 0 and consumed == 0
    assert int(restored.step) == 0
    for a, b in zip(jax.tree.leaves(restored.mu), jax.tree.leaves(template.mu)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # but weights came from the checkpoint
    for a, b in zip(jax.tree.leaves(restored.params), jax.tree.leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_cross_topology_restore(tmp_path):
    """Save unsharded, restore onto a tp=4 mesh — no reshard tool needed."""
    cfg, state = _state()
    save = str(tmp_path / "ckpt")
    checkpointing.save_checkpoint(save, state, iteration=1)

    rt = build_mesh(ParallelConfig(tensor_parallel=4))
    specs = param_specs(cfg)
    params_sharded = shard_tree(rt, init_params(cfg, jax.random.PRNGKey(9)), specs)
    template = init_train_state(OptimizerConfig(lr=1e-3), params_sharded)
    from megatron_tpu.training.optimizer import train_state_specs
    from megatron_tpu.parallel.sharding import tree_shardings

    from jax.sharding import NamedSharding, PartitionSpec as P

    st_specs = train_state_specs(specs, params_sharded, rt.dp, zero1=True)
    shardings = jax.tree.map(
        lambda s: NamedSharding(rt.mesh, s), st_specs,
        is_leaf=lambda s: isinstance(s, P))
    restored, _, _ = checkpointing.load_checkpoint(
        save, template, shardings=shardings)
    wq = restored.params["layers"]["attn"]["wq"]
    assert "tensor" in str(wq.sharding.spec)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(wq)),
        np.asarray(jax.device_get(state.params["layers"]["attn"]["wq"])))


def test_cross_topology_restore_expert_axis(tmp_path):
    """MoE checkpoints are topology-free across the EXPERT axis too:
    save unsharded, restore onto ep2 x tp2 (expert weights sharded E/ep,
    ZeRO-1 over the combined batch axes), then back onto a dp-only mesh —
    expert weights exact both ways."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from megatron_tpu.training.optimizer import train_state_specs

    cfg = presets.tiny(vocab_size=64, seq_length=16, num_experts=4,
                       moe_top_k=2, ffn_hidden_size=32)
    params = init_params(cfg, jax.random.PRNGKey(3))
    state = init_train_state(OptimizerConfig(lr=1e-3), params)
    save = str(tmp_path / "moe_ckpt")
    checkpointing.save_checkpoint(save, state, iteration=1)
    ref_w = np.asarray(jax.device_get(params["layers"]["moe"]["w_in"]))

    for par in (ParallelConfig(expert_parallel=2, tensor_parallel=2),
                ParallelConfig()):
        rt = build_mesh(par)
        specs = param_specs(cfg)
        sharded = shard_tree(rt, init_params(cfg, jax.random.PRNGKey(9)),
                             specs)
        template = init_train_state(OptimizerConfig(lr=1e-3), sharded)
        st_specs = train_state_specs(specs, sharded, rt.dp, zero1=True,
                                     ep=rt.ep)
        shardings = jax.tree.map(
            lambda s: NamedSharding(rt.mesh, s), st_specs,
            is_leaf=lambda s: isinstance(s, P))
        restored, _, _ = checkpointing.load_checkpoint(
            save, template, shardings=shardings)
        w = restored.params["layers"]["moe"]["w_in"]
        if rt.ep > 1:
            assert "expert" in str(w.sharding.spec)
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(w)), ref_w)


def test_missing_checkpoint_raises(tmp_path):
    _, template = _state()
    with pytest.raises(FileNotFoundError):
        checkpointing.load_checkpoint(str(tmp_path / "nope"), template)


def test_config_compat_check():
    checkpointing.check_config_compatibility(
        {"model": {"num_layers": 2}}, {"model": {"num_layers": 2}})
    with pytest.raises(ValueError, match="num_layers"):
        checkpointing.check_config_compatibility(
            {"model": {"num_layers": 2}}, {"model": {"num_layers": 4}})
    # same-shape drift (weights restore cleanly but the forward function
    # differs) must be caught too — the silent-killer class
    with pytest.raises(ValueError, match="rope_theta"):
        checkpointing.check_config_compatibility(
            {"model": {"rope_theta": 1e4}}, {"model": {"rope_theta": 1e6}})
    # all mismatches reported at once
    with pytest.raises(ValueError, match="(?s)normalization.*activation"):
        checkpointing.check_config_compatibility(
            {"model": {"normalization": "rmsnorm", "activation": "swiglu"}},
            {"model": {"normalization": "layernorm", "activation": "gelu"}})


def test_resume_with_mismatched_config_raises(tmp_path):
    """A resume against a same-shape-drifted config fails loudly BEFORE
    restore, and --finetune deliberately bypasses the check (VERDICT r3
    next-round #3; ref: check_checkpoint_args, checkpointing.py:35-66)."""
    import dataclasses

    cfg, state = _state()
    _, template = _state(seed=99)
    saved_cfg = {"model": dataclasses.asdict(cfg), "parallel": {},
                 "optimizer": {}, "training": {}}
    checkpointing.save_checkpoint(str(tmp_path), state, iteration=1,
                                  consumed_samples=4, config=saved_cfg)

    drifted = {**saved_cfg,
               "model": {**saved_cfg["model"], "rope_theta": 1e6}}
    with pytest.raises(ValueError, match="rope_theta"):
        checkpointing.load_checkpoint(str(tmp_path), template,
                                      config=drifted)
    # same config resumes; finetune adopts the weights despite the drift
    _, it, _ = checkpointing.load_checkpoint(str(tmp_path), template,
                                             config=saved_cfg)
    assert it == 1
    restored, it, consumed = checkpointing.load_checkpoint(
        str(tmp_path), template, config=drifted, finetune=True)
    assert (it, consumed) == (0, 0)
    # a topology change is NOT an architecture change: parallel/training
    # sections are never part of the check
    retopo = {**saved_cfg, "parallel": {"tensor_parallel": 2}}
    checkpointing.load_checkpoint(str(tmp_path), template, config=retopo)


def test_checkpoint_util_copy_and_cast(tmp_path):
    """tools/checkpoint_util.py: copy a checkpoint, cast params to bf16,
    drop optimizer state; result loads and matches (ref checkpoint_util's
    remaining real uses — resharding itself is free here)."""
    import jax
    import numpy as np

    from megatron_tpu.config import (
        ModelConfig, OptimizerConfig, ParallelConfig, RunConfig,
        TrainingConfig,
    )
    from megatron_tpu.models.params import init_params
    from megatron_tpu.training import checkpointing
    from megatron_tpu.training.optimizer import init_train_state
    from tools import checkpoint_util

    model = ModelConfig(num_layers=2, hidden_size=32, num_attention_heads=4,
                        num_kv_heads=2, ffn_hidden_size=64, vocab_size=64,
                        seq_length=16, params_dtype="float32").validate()
    cfg = RunConfig(model=model, parallel=ParallelConfig(),
                    optimizer=OptimizerConfig(lr=1e-3,
                                              lr_decay_style="constant"),
                    training=TrainingConfig(micro_batch_size=1,
                                            global_batch_size=1))
    params = init_params(model, jax.random.PRNGKey(3))
    state = init_train_state(cfg.optimizer, params)
    src = str(tmp_path / "src")
    checkpointing.save_checkpoint(src, state, 7, 123, config=cfg.to_dict())

    dst = str(tmp_path / "dst")
    checkpoint_util.main(["--load", src, "--save", dst,
                          "--target_params_dtype", "bfloat16",
                          "--params_only"])

    assert checkpointing.read_tracker(dst) == 7
    import json
    import os

    meta = json.load(open(os.path.join(
        checkpointing.checkpoint_dir(dst, 7), "meta.json")))
    assert meta["config"]["model"]["params_dtype"] == "bfloat16"
    model_bf16 = ModelConfig(**meta["config"]["model"]).validate()
    p2 = checkpointing.load_params_only(
        dst, init_params(model_bf16, jax.random.PRNGKey(0)))
    a = np.asarray(jax.tree.leaves(p2)[0], np.float32)
    b = np.asarray(jax.tree.leaves(params)[0], np.float32)
    np.testing.assert_allclose(a, b, rtol=1e-2, atol=1e-2)  # bf16 round


def test_restore_never_uses_sharding_from_file_fallback(tmp_path, recwarn):
    """Every restore path passes explicit target shardings (template leaf
    placement when the caller gives none) — orbax's sharding-from-file
    fallback is deprecated-ish and unsafe across topologies (VERDICT r2
    weak #8)."""
    import warnings

    import jax
    from megatron_tpu.config import OptimizerConfig
    from megatron_tpu.models import presets
    from megatron_tpu.models.params import init_params
    from megatron_tpu.training import checkpointing
    from megatron_tpu.training.optimizer import init_train_state

    cfg = presets.tiny(vocab_size=64, seq_length=16, hidden_size=32,
                       num_layers=2, num_attention_heads=4, num_kv_heads=2,
                       ffn_hidden_size=64)
    params = init_params(cfg, jax.random.PRNGKey(0))
    state = init_train_state(OptimizerConfig(lr=1e-3), params)
    save = str(tmp_path / "ckpt")
    checkpointing.save_checkpoint(save, state, 3, 12)

    with warnings.catch_warnings():
        warnings.simplefilter("error", UserWarning)
        restored, it, consumed = checkpointing.load_checkpoint(save, state)
        assert (it, consumed) == (3, 12)
        p = checkpointing.load_params_only(save, params)
    jax.block_until_ready(restored.params)
    jax.block_until_ready(p)

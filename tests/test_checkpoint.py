"""Checkpoint save/load tests, including cross-topology restore — the
capability that replaces the reference's offline reshard tool-chain
(tools/checkpoint_util.py)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_tpu.config import OptimizerConfig, ParallelConfig
from megatron_tpu.models import presets
from megatron_tpu.models.params import init_params, param_specs
from megatron_tpu.parallel.mesh import build_mesh
from megatron_tpu.parallel.sharding import shard_tree
from megatron_tpu.training import checkpointing
from megatron_tpu.training.optimizer import init_train_state


def _state(seed=0):
    cfg = presets.tiny(vocab_size=64, seq_length=16)
    params = init_params(cfg, jax.random.PRNGKey(seed))
    opt_cfg = OptimizerConfig(lr=1e-3)
    return cfg, init_train_state(opt_cfg, params)


def test_save_load_roundtrip(tmp_path):
    cfg, state = _state()
    save = str(tmp_path / "ckpt")
    path = checkpointing.save_checkpoint(save, state, iteration=7,
                                         consumed_samples=123,
                                         config={"model": {"num_layers": 2}})
    assert os.path.exists(os.path.join(save, checkpointing.TRACKER))
    assert checkpointing.read_tracker(save) == 7

    _, template = _state(seed=99)  # different values, same structure
    restored, it, consumed = checkpointing.load_checkpoint(save, template)
    assert it == 7 and consumed == 123
    for a, b in zip(jax.tree.leaves(restored.params), jax.tree.leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(restored.mu), jax.tree.leaves(state.mu)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_finetune_load_resets_optimizer(tmp_path):
    cfg, state = _state()
    # dirty the moments so we can see them reset
    state = state.replace(mu=jax.tree.map(lambda x: x + 1.0, state.mu),
                          step=jnp.asarray(55, jnp.int32))
    save = str(tmp_path / "ckpt")
    checkpointing.save_checkpoint(save, state, iteration=55,
                                  consumed_samples=999)
    _, template = _state(seed=99)
    restored, it, consumed = checkpointing.load_checkpoint(
        save, template, finetune=True)
    assert it == 0 and consumed == 0
    assert int(restored.step) == 0
    for a, b in zip(jax.tree.leaves(restored.mu), jax.tree.leaves(template.mu)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # but weights came from the checkpoint
    for a, b in zip(jax.tree.leaves(restored.params), jax.tree.leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_cross_topology_restore(tmp_path):
    """Save unsharded, restore onto a tp=4 mesh — no reshard tool needed."""
    cfg, state = _state()
    save = str(tmp_path / "ckpt")
    checkpointing.save_checkpoint(save, state, iteration=1)

    rt = build_mesh(ParallelConfig(tensor_parallel=4))
    specs = param_specs(cfg)
    params_sharded = shard_tree(rt, init_params(cfg, jax.random.PRNGKey(9)), specs)
    template = init_train_state(OptimizerConfig(lr=1e-3), params_sharded)
    from megatron_tpu.training.optimizer import train_state_specs
    from megatron_tpu.parallel.sharding import tree_shardings

    from jax.sharding import NamedSharding, PartitionSpec as P

    st_specs = train_state_specs(specs, params_sharded, rt.dp, zero1=True)
    shardings = jax.tree.map(
        lambda s: NamedSharding(rt.mesh, s), st_specs,
        is_leaf=lambda s: isinstance(s, P))
    restored, _, _ = checkpointing.load_checkpoint(
        save, template, shardings=shardings)
    wq = restored.params["layers"]["attn"]["wq"]
    assert "tensor" in str(wq.sharding.spec)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(wq)),
        np.asarray(jax.device_get(state.params["layers"]["attn"]["wq"])))


def test_missing_checkpoint_raises(tmp_path):
    _, template = _state()
    with pytest.raises(FileNotFoundError):
        checkpointing.load_checkpoint(str(tmp_path / "nope"), template)


def test_config_compat_check():
    checkpointing.check_config_compatibility(
        {"model": {"num_layers": 2}}, {"model": {"num_layers": 2}})
    with pytest.raises(ValueError, match="num_layers"):
        checkpointing.check_config_compatibility(
            {"model": {"num_layers": 2}}, {"model": {"num_layers": 4}})

"""MSDP dialogue-prompting harness (counterpart: reference tasks/msdp/ —
prompt construction, generation driving, token-F1 evaluation)."""

import json

import pytest

from tasks.msdp import (
    build_knowledge_input, build_response_input, corpus_f1, evaluate_f1,
    first_line_continuation, generate_file, normalize_answer,
    read_knowledge_prompts, read_response_prompt, token_f1, word_tokenize,
)


def test_normalize_answer_strips_articles_punct_case():
    assert normalize_answer("The  Quick, (brown) fox!") == "quick brown fox"


def test_token_f1_exact_and_partial():
    p, r, f = token_f1("the cat sat", "the cat sat")
    assert f == pytest.approx(1.0)
    p, r, f = token_f1("cat dog", "cat bird fish")
    # 1 common token; precision 1/2, recall 1/3
    assert p == pytest.approx(0.5)
    assert r == pytest.approx(1 / 3)
    assert f == pytest.approx(2 * 0.5 * (1 / 3) / (0.5 + 1 / 3))


def test_token_f1_empty_gold_excluded_empty_guess_zero():
    assert token_f1("anything", "") == (None, None, None)
    assert token_f1("", "gold") == (0.0, 0.0, 0.0)
    # corpus mean skips the empty-gold pair entirely
    p, r, f = corpus_f1(["a b", "ignored"], ["a b", ""])
    assert f == pytest.approx(1.0)


def test_word_tokenize_splits_punctuation():
    assert word_tokenize("Hello, world!") == ["Hello", ",", "world", "!"]


def test_prompt_files_and_input_construction(tmp_path):
    kfile = tmp_path / "k.jsonl"
    kfile.write_text(
        json.dumps({"jazz what do you like?": ["( ex1 ) jazz => fact one",
                                               "( ex2 ) jazz => fact two"]})
        + "\n")
    prompts = read_knowledge_prompts(str(kfile))
    line = "jazz\thi there [SEP] what do you like?"
    inp = build_knowledge_input(line, prompts)
    assert inp.endswith("( what do you like? ) jazz =>")
    assert "fact one \n" in inp and "fact two \n" in inp

    rfile = tmp_path / "r.txt"
    rfile.write_text("example a\nexample b\nexample c\n")
    prompt = read_response_prompt(str(rfile), 2)
    assert prompt == "example a \nexample b \n"
    line = "jazz\tfirst [SEP] tell me more.\tJazz is music."
    inp = build_response_input(line, prompt)
    assert inp.startswith("example a \nexample b \nTopic: jazz. ")
    assert "User says: tell me more ." in inp
    assert "We know that: Jazz is music ." in inp
    assert inp.endswith("System replies:")


def test_first_line_continuation():
    assert first_line_continuation("PROMPT gen text\nsecond", 6) == "gen text"


def test_generate_file_and_evaluate_f1(tmp_path):
    kfile = tmp_path / "k.jsonl"
    kfile.write_text(json.dumps({"t q1": ["( e ) t => f"]}) + "\n"
                     + json.dumps({"t q2": ["( e ) t => g"]}) + "\n")
    samples = tmp_path / "in.tsv"
    samples.write_text("t\ta [SEP] q1\nt\tq2\n")
    out = tmp_path / "out.txt"

    def fake_gen(prompt):
        return prompt + " the answer is blue \n trailing junk"

    n = generate_file(str(samples), str(out), "knowledge", str(kfile),
                      fake_gen)
    assert n == 2
    lines = out.read_text().splitlines()
    assert lines == ["the answer is blue", "the answer is blue"]

    gold = tmp_path / "gold.txt"
    gold.write_text("the answer is blue\nno_passages_used\n")
    p, r, f1 = evaluate_f1(str(out), str(gold))
    assert f1 == pytest.approx(1.0)  # empty-gold second pair excluded


def test_evaluate_f1_strips_endoftext_from_guesses(tmp_path):
    guess = tmp_path / "guess.txt"
    guess.write_text("blue sky<|endoftext|>\n")
    gold = tmp_path / "gold.txt"
    gold.write_text("blue sky\n")
    _, _, f1 = evaluate_f1(str(guess), str(gold))
    assert f1 == pytest.approx(1.0)


def test_generate_file_bad_prompt_type(tmp_path):
    with pytest.raises(ValueError):
        generate_file("x", "y", "nope", "z", lambda s: s)

"""Pallas flash-attention kernel vs the XLA einsum path (interpret mode on
the CPU suite; the same kernels compile for real on TPU — see bench.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_tpu.ops.attention import attention
from megatron_tpu.ops.pallas.flash_attention import flash_attention, supported

RNG = np.random.default_rng(7)


def _qkv(b=1, s=256, hq=4, hkv=2, d=64):
    q = jnp.asarray(RNG.standard_normal((b, s, hq, d)), jnp.float32)
    k = jnp.asarray(RNG.standard_normal((b, s, hkv, d)), jnp.float32)
    v = jnp.asarray(RNG.standard_normal((b, s, hkv, d)), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("window", [None, 64])
def test_flash_forward_matches_xla(window):
    q, k, v = _qkv()
    got = flash_attention(q, k, v, sliding_window=window,
                          block_q=128, block_k=128)
    want = attention(q, k, v, sliding_window=window)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-3)


def test_flash_mha_no_gqa():
    q, k, v = _qkv(hq=4, hkv=4)
    got = flash_attention(q, k, v, block_q=128, block_k=128)
    want = attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-3)


def test_flash_grads_match_xla():
    q, k, v = _qkv(s=256, hq=2, hkv=1, d=64)

    def f_flash(q, k, v):
        return jnp.sum(jnp.square(flash_attention(q, k, v, block_q=128,
                                                  block_k=128)))

    def f_ref(q, k, v):
        return jnp.sum(jnp.square(attention(q, k, v)))

    gf = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gf, gr):
        scale = float(jnp.max(jnp.abs(b)))
        np.testing.assert_allclose(np.asarray(a) / scale, np.asarray(b) / scale,
                                   rtol=2e-2, atol=2e-3, err_msg=f"d{name}")


def test_supported_predicate_and_rejection():
    assert supported(512, 512, 128, 128)
    assert not supported(200, 200, 128, 128)
    assert not supported(512, 256, 128, 128)
    q, k, v = _qkv(s=200)
    with pytest.raises(ValueError, match="flash kernel"):
        flash_attention(q[:, :200], k[:, :200], v[:, :200],
                        block_q=128, block_k=128)


def test_model_dispatch_falls_back_cleanly():
    """attention(impl='pallas') uses the kernel when shapes allow and the
    XLA path otherwise (decode steps)."""
    q, k, v = _qkv(s=256)
    out = attention(q, k, v, impl="pallas")
    want = attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-3, atol=2e-3)
    # decode shape (q_len != kv_len) silently uses XLA
    out2 = attention(q[:, :1], k, v, impl="pallas", q_offset=255)
    assert out2.shape == (1, 1, 4, 64)


@pytest.mark.parametrize("window", [None, 64])
def test_splash_path_matches_xla_gqa(window):
    """The TPU dispatch path (splash MQA kernel): GQA with grouped — not
    replicated — K/V, causal and sliding-window masks."""
    from megatron_tpu.ops.pallas.flash_attention import _splash_attention

    q, k, v = _qkv(s=256, hq=4, hkv=2, d=128)
    qt, kt, vt = (jnp.transpose(x, (0, 2, 1, 3)) for x in (q, k, v))
    got = jnp.transpose(_splash_attention(qt, kt, vt, True, window),
                        (0, 2, 1, 3))
    want = attention(q, k, v, sliding_window=window)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-3)


def test_splash_path_grads_finite():
    from megatron_tpu.ops.pallas.flash_attention import _splash_attention

    q, k, v = _qkv(s=256, hq=2, hkv=1, d=128)
    qt, kt, vt = (jnp.transpose(x, (0, 2, 1, 3)) for x in (q, k, v))

    def f(qt, kt, vt):
        return jnp.sum(jnp.square(_splash_attention(qt, kt, vt, True, 64)))

    grads = jax.grad(f, argnums=(0, 1, 2))(qt, kt, vt)
    for g in grads:
        assert np.isfinite(np.asarray(g)).all()

"""Multi-host coordination tests (ISSUE 12): the cross-process agreement
seam (training/coordination.py) and the four protocols wired through the
train loop — signal agreement (one host's SIGTERM drains ALL hosts),
coordinated abort (peer death/poison -> PEER_ABORT_EXIT_CODE, not a
wedged collective), two-phase checkpoint commit (no tracker flips unless
every host staged), and the restart/resume barrier.

Three layers of evidence:
  * in-process units over the FileBackend (two coordinators, one dir);
  * REAL 2-process jax.distributed drills over the KV backend
    (the shared `jax_cluster` conftest harness — the coordination
    service works for real on CPU; only cross-process XLA computations
    don't, see tests/test_multihost.py);
  * REAL two-host CLI acceptance: two pretrain_gpt.py processes sharing
    only a --coordination_dir (one single-device JAX process per "host",
    replicated data/seed — exactly the file-backend cluster shape),
    driven by the per-host faults preempt_host/kill_host/kill_during_save.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from megatron_tpu.training import checkpointing, coordination, resilience
from megatron_tpu.training.coordination import (
    ClusterCoordinator, CommitAborted, CoordinationError, FileBackend,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- backend + protocol units (FileBackend, in-process) -----------------------


def _pair(tmp_path, timeout=1.0, poll=0.02):
    d = str(tmp_path / "coord")
    a = ClusterCoordinator(FileBackend(d), 0, 2,
                           peer_death_timeout_s=timeout, poll_s=poll)
    b = ClusterCoordinator(FileBackend(d), 1, 2,
                           peer_death_timeout_s=timeout, poll_s=poll)
    return a, b


def _concurrently(fa, fb):
    out = {}
    t = threading.Thread(target=lambda: out.update(a=fa()))
    t.start()
    out["b"] = fb()
    t.join()
    return out["a"], out["b"]


def test_file_backend_atomic_records(tmp_path):
    be = FileBackend(str(tmp_path / "c"))
    assert be.get_all("sig") == {}
    be.put("sig/0", "hello")
    be.put("sig/0", "world")  # overwrite
    be.put("sig/1", "x")
    assert be.get_all("sig") == {"0": "world", "1": "x"}
    be.delete("sig/0")
    be.delete("sig/0")  # idempotent
    assert be.get_all("sig") == {"1": "x"}


def test_topology_barrier_and_mismatch(tmp_path):
    a, b = _pair(tmp_path)
    ra, rb = _concurrently(lambda: a.topology_barrier(5),
                           lambda: b.topology_barrier(5))
    assert sorted(ra) == sorted(rb) == [0, 1]
    # a lone host times out with the missing hosts named
    lone = ClusterCoordinator(FileBackend(str(tmp_path / "solo")), 0, 2,
                              peer_death_timeout_s=1, poll_s=0.02)
    with pytest.raises(CoordinationError, match=r"hosts \[1\] missing"):
        lone.topology_barrier(0.3)
    # world-size disagreement is loud, not a hang
    d3 = str(tmp_path / "mismatch")
    c0 = ClusterCoordinator(FileBackend(d3), 0, 2, poll_s=0.02)
    c1 = ClusterCoordinator(FileBackend(d3), 1, 3, poll_s=0.02)
    c1._put("topo/1", num_hosts=3)

    with pytest.raises(CoordinationError, match="disagreement"):
        c0.topology_barrier(5)


def test_signal_agreement_union_and_exit_iteration(tmp_path):
    a, b = _pair(tmp_path)
    assert b.cluster_signals() == {} and b.notice_host() is None
    a.publish_signals(["SIGTERM"])
    a.publish_signals(["SIGTERM"])  # idempotent
    assert b.cluster_signals()[0]["signals"] == ["SIGTERM"]
    assert b.notice_host() == 0
    # hosts at different iterations agree on the MAX (nobody steps back)
    (ta, na), (tb, nb) = _concurrently(
        lambda: a.agree_exit_iteration(5, 5),
        lambda: b.agree_exit_iteration(3, 5))
    assert (ta, na) == (tb, nb) == (5, 0)


def test_completion_ack_resolves_late_notice(tmp_path):
    """A host that reaches train_iters publishes a NON-BLOCKING exit ack;
    a preemption notice published AFTER it left the loop still resolves
    the drainer's agreement — to the completer's final iteration — rather
    than waiting on a host that will never run another pass."""
    a, b = _pair(tmp_path)
    a.ack_exit(50)  # completer: records its position, does NOT wait
    b.publish_signals(["SIGTERM"])  # the notice lands a moment later
    target, nh = b.agree_exit_iteration(47, 5)
    assert (target, nh) == (50, 1)


def test_commit_reattempt_needs_fresh_votes(tmp_path):
    """A re-save of the SAME iteration (divergence rollback re-traverses
    committed iterations) must wait for the peers' votes for THIS
    attempt — stale votes from the earlier commit never satisfy it."""
    a, b = _pair(tmp_path)
    _concurrently(lambda: a.commit_barrier(7, "a0", 5),
                  lambda: b.commit_barrier(7, "b0", 5))  # attempt 0
    _concurrently(lambda: a.commit_barrier(7, "a1", 5),
                  lambda: b.commit_barrier(7, "b1", 5))  # attempt 1: new votes
    # one-sided re-attempt: two generations of leftover votes exist, and
    # none of them count — the lone voter aborts
    with pytest.raises(CommitAborted, match="attempt 2"):
        a.commit_barrier(7, "a2", 0.4)


def test_two_phase_commit_agreement_and_abort(tmp_path):
    a, b = _pair(tmp_path)
    _concurrently(lambda: a.commit_barrier(7, "ca", 5),
                  lambda: b.commit_barrier(7, "cb", 5))
    # one-sided staging: the lone voter ABORTS (tracker never flips)
    with pytest.raises(CommitAborted, match="iteration 8"):
        a.commit_barrier(8, "ca", 0.4)
    # a peer's poison record aborts the wait immediately, with the cause
    b.publish_abort("hang", iteration=9)
    t0 = time.monotonic()
    with pytest.raises(CommitAborted, match="hang"):
        a.commit_barrier(9, "ca", 30.0)
    assert time.monotonic() - t0 < 5.0


def test_peer_abort_and_heartbeat_death(tmp_path, monkeypatch):
    a, b = _pair(tmp_path, timeout=0.3)
    b.heartbeat()
    assert a.check_peers() is None
    # a peer SEEN heartbeating that goes silent past the timeout is a
    # peer_death verdict
    deadline = time.monotonic() + 5
    verdict = None
    while verdict is None and time.monotonic() < deadline:
        verdict = a.check_peers()
        time.sleep(0.05)
    assert verdict == {"host": 1, "cause": "peer_death",
                       "detail": verdict["detail"]}
    # a peer that has NEVER heartbeat is judged against the STARTUP
    # window (its process may still be booting), not the steady-state
    # death window
    a2 = ClusterCoordinator(FileBackend(str(tmp_path / "n")), 0, 2,
                            peer_death_timeout_s=0.1, poll_s=0.02)
    monkeypatch.setenv(coordination.STARTUP_TIMEOUT_ENV, "0.4")
    t0 = time.monotonic()
    v = None
    while v is None and time.monotonic() < t0 + 5:
        v = a2.dead_peer()
        time.sleep(0.03)
    assert v == 1
    assert time.monotonic() - t0 >= 0.35  # 0.1s death window NOT applied
    # an explicit poison record wins over silence and names its cause
    b2 = ClusterCoordinator(a.backend, 1, 2, peer_death_timeout_s=0.3,
                            poll_s=0.02)
    b2.publish_abort("sdc", iteration=4)
    v = a.check_peers()
    assert v["host"] == 1 and v["cause"] == "sdc"
    # own abort record is never a PEER abort
    assert b2.peer_abort() is None


def test_stale_incarnation_records_are_invisible(tmp_path):
    """A crashed-and-restarted host's old SIGTERM/abort records must be
    dead on arrival — the file backend's directory outlives processes."""
    a, b = _pair(tmp_path)
    b.publish_abort("hang")
    b.publish_signals(["SIGTERM"])
    assert a.peer_abort() is not None
    # host 1 restarts: new boot nonce, old records filtered out
    ClusterCoordinator(a.backend, 1, 2, poll_s=0.02)
    assert a.peer_abort() is None
    assert a.cluster_signals() == {}


def test_resume_agreement_intersection(tmp_path):
    a, b = _pair(tmp_path)
    ra, rb = _concurrently(lambda: a.agree_resume_iteration([2, 4, 6], 5),
                           lambda: b.agree_resume_iteration([2, 4], 5))
    assert ra == rb == 4  # newest valid EVERYWHERE, not anyone's tracker
    a2, b2 = _pair(tmp_path / "n2")
    ra, rb = _concurrently(lambda: a2.agree_resume_iteration([2], 5),
                           lambda: b2.agree_resume_iteration([], 5))
    assert ra is rb is None  # empty intersection: fresh start everywhere


def test_broadcast_and_published_value(tmp_path):
    a, b = _pair(tmp_path)
    got, _ = _concurrently(
        lambda: b.broadcast(None, root=0, key="cfg", timeout_s=5),
        lambda: a.broadcast({"interval": 40}, root=0, key="cfg"))
    assert got == {"interval": 40}
    a.publish_value("cadence", 37)
    assert b.read_value("cadence") == 37
    assert b.read_value("cadence", host=1) is None


def test_sideband_watchdog_fires_on_poison(tmp_path):
    a, b = _pair(tmp_path, timeout=5.0, poll=0.02)
    fired = []
    a.start_watchdog(fired.append)
    try:
        time.sleep(0.1)
        assert not fired
        b.publish_abort("hang", iteration=3)
        deadline = time.monotonic() + 5
        while not fired and time.monotonic() < deadline:
            time.sleep(0.02)
        assert fired and fired[0]["cause"] == "hang"
        # and it heartbeat while watching
        assert a._fresh("hb").get(0) is not None
    finally:
        a.stop_watchdog()


def test_single_process_gets_no_coordinator(tmp_path, monkeypatch):
    """process_count()==1 with no host-identity env: for_training returns
    None — the byte-identical single-host contract."""
    from megatron_tpu.config import TrainingConfig

    monkeypatch.delenv(coordination.COORD_HOST_ENV, raising=False)
    monkeypatch.delenv(coordination.COORD_NUM_HOSTS_ENV, raising=False)
    t = TrainingConfig(coordination_dir=str(tmp_path / "c"))
    assert coordination.for_training(t, log=lambda m: None) is None
    # env identity + dir => file backend coordinator, heartbeating from
    # construction (the startup barriers judge liveness by this, long
    # before the train loop finishes building its model)
    monkeypatch.setenv(coordination.COORD_HOST_ENV, "1")
    monkeypatch.setenv(coordination.COORD_NUM_HOSTS_ENV, "2")
    c = coordination.for_training(t, log=lambda m: None)
    assert isinstance(c.backend, FileBackend) and (c.host, c.num_hosts) == (1, 2)
    assert c._fresh("hb").get(1) is not None  # immediate first beat
    assert c._watchdog is not None  # publish-only sideband running
    c.close()
    # half-set env is a loud error, not a silent solo run
    monkeypatch.delenv(coordination.COORD_NUM_HOSTS_ENV)
    with pytest.raises(ValueError, match="must be set together"):
        coordination.for_training(t, log=lambda m: None)


# -- per-host faults + cadence tuner units ------------------------------------


def test_host_faults_parse_and_target_one_host(monkeypatch):
    monkeypatch.setenv(resilience.FAULT_ENV,
                       "kill_host:1:4,preempt_host:0:3")
    assert resilience.host_fault_active("kill_host", 1, 4)
    assert not resilience.host_fault_active("kill_host", 0, 4)
    assert not resilience.host_fault_active("kill_host", 1, 5)
    assert resilience.host_fault_active("preempt_host", 0, 3)
    # preempt_host self-delivers a real SIGTERM only on the named host
    from megatron_tpu.training.signal_handler import DistributedSignalHandler

    with DistributedSignalHandler() as h:
        resilience.maybe_signal_host(1, 3)  # wrong host: nothing
        assert h.signals_received() == ()
        resilience.maybe_signal_host(0, 3)
        assert h.signals_received() == (signal.SIGTERM,)


def test_cadence_tuner_formula_and_retune():
    t = resilience.CheckpointCadenceTuner(grace_s=100.0, floor_steps=5)
    assert t.interval() is None  # no step sample yet
    for _ in range(10):
        t.note_step(1.0)
    for _ in range(10):
        t.note_commit(10.0)
    # (grace 100 - p95 commit 10) / p50 step 1 = 90
    assert t.interval() == 90
    r = t.retune()
    assert r["to_interval"] == 90 and r["from_interval"] is None
    assert t.retune() is None  # unchanged: no re-journal
    # commit latency grows -> interval shrinks, floor clamps
    for _ in range(50):
        t.note_commit(99.5)
    assert t.interval() == 5
    assert t.retune()["to_interval"] == 5
    # seeding from a journal adopts commit + preemption latencies
    t2 = resilience.CheckpointCadenceTuner(grace_s=20.0, floor_steps=2)
    n = t2.seed_from_journal([
        {"kind": "checkpoint_commit", "seconds": 4.0},
        {"kind": "preemption", "save_latency_ms": 6000.0},
        {"kind": "step"},
    ])
    assert n == 2
    t2.note_step(2.0)
    # p95 of [4, 6] = 6 -> (20 - 6) / 2 = 7
    assert t2.interval() == 7
    with pytest.raises(ValueError, match="positive"):
        resilience.CheckpointCadenceTuner(grace_s=0.0)


def test_save_interval_auto_flag_wiring():
    from megatron_tpu.arguments import args_to_run_config, parse_args

    base = ["--num_layers", "2", "--hidden_size", "32",
            "--num_attention_heads", "4", "--vocab_size", "64",
            "--seq_length", "16", "--micro_batch_size", "1",
            "--global_batch_size", "1", "--train_iters", "1", "--fp32"]
    cfg = args_to_run_config(parse_args(
        base + ["--save_interval", "auto", "--save_interval_floor", "7",
                "--coordination_dir", "/tmp/c",
                "--peer_death_timeout_s", "9"]))
    t = cfg.training
    assert t.save_interval is None and t.save_interval_auto
    assert t.save_interval_floor == 7
    assert t.coordination_dir == "/tmp/c"
    assert t.peer_death_timeout_s == 9.0
    cfg = args_to_run_config(parse_args(base + ["--save_interval", "3"]))
    assert cfg.training.save_interval == 3
    assert not cfg.training.save_interval_auto
    with pytest.raises(SystemExit):
        args_to_run_config(parse_args(base + ["--save_interval",
                                              "sometimes"]))


# -- two-phase commit through checkpointing._finalize -------------------------


class _StubCoordinator:
    """num_hosts>1 coordinator double for _finalize: records votes,
    optionally refuses agreement."""

    def __init__(self, agree=True):
        self.num_hosts = 2
        self.host = 0
        self.votes = []
        self.agree = agree

    def commit_barrier(self, iteration, crc, timeout_s=None):
        self.votes.append((iteration, crc))
        if not self.agree:
            raise CommitAborted(f"stub refused iteration {iteration}")


def _stage_fake_checkpoint(save, iteration):
    stage = checkpointing.checkpoint_dir(str(save), iteration) \
        + checkpointing.STAGING_SUFFIX
    os.makedirs(os.path.join(stage, "state"))
    with open(os.path.join(stage, "state", "blob"), "w") as f:
        f.write("bytes")
    return stage


def test_finalize_two_phase_commit_and_abort(tmp_path):
    save = tmp_path / "ckpt"
    # agreement: vote carries the per-host manifest crc, tracker flips
    stage = _stage_fake_checkpoint(save, 3)
    coord = _StubCoordinator(agree=True)
    path = checkpointing._finalize(str(save), stage, 3, 30, None, None,
                                   coordinator=coord)
    assert os.path.isdir(path) and checkpointing.read_tracker(str(save)) == 3
    assert len(coord.votes) == 1 and coord.votes[0][0] == 3
    assert len(coord.votes[0][1]) == 8  # crc32 hex of the manifest
    # refusal: CommitAborted propagates, tracker UNFLIPPED, staging kept
    stage = _stage_fake_checkpoint(save, 5)
    bad = _StubCoordinator(agree=False)
    with pytest.raises(CommitAborted):
        checkpointing._finalize(str(save), stage, 5, 50, None, None,
                                coordinator=bad)
    assert checkpointing.read_tracker(str(save)) == 3
    assert os.path.isdir(stage)
    assert checkpointing.list_valid_checkpoints(str(save)) == [3]


def test_saver_journals_commit_abort(tmp_path):
    """AsyncCheckpointSaver surfaces a refused commit as `commit_abort`
    in the journal and re-raises at the next wait()."""
    import jax.numpy as jnp

    from megatron_tpu.config import OptimizerConfig
    from megatron_tpu.training.optimizer import init_train_state

    class _Journal:
        def __init__(self):
            self.events = []

        def emit(self, kind, **fields):
            self.events.append({"kind": kind, **fields})

        def flush(self):
            pass

    state = init_train_state(
        OptimizerConfig(lr=1e-3, lr_decay_style="constant"),
        {"w": jnp.ones((2,), jnp.float32)})
    journal = _Journal()
    saver = checkpointing.AsyncCheckpointSaver(
        str(tmp_path / "s"), journal=journal,
        coordinator=_StubCoordinator(agree=False))
    saver.save(state, 1, 10)
    with pytest.raises(CommitAborted):
        saver.wait()
    kinds = [e["kind"] for e in journal.events]
    assert kinds == ["checkpoint_begin", "commit_abort"]
    assert journal.events[1]["iteration"] == 1
    assert checkpointing.read_tracker(str(tmp_path / "s")) is None


def test_event_counters_on_metrics_registry(tmp_path):
    """Satellite: preemption/hang/SDC/elastic-resume/peer-abort events
    move Prometheus counters transparently through RunTelemetry.emit —
    and through the saver-facing journal_sink."""
    from megatron_tpu import telemetry
    from megatron_tpu.config import TrainingConfig
    from megatron_tpu.telemetry.metrics import MetricsRegistry

    reg = MetricsRegistry()
    tcfg = TrainingConfig(telemetry_dir=str(tmp_path / "tele"))
    rt = telemetry.for_training(tcfg, log=lambda m: None, registry=reg)
    try:
        rt.emit("preemption", iteration=3, notice_host=0)
        rt.emit("peer_abort", host=1, cause="hang")
        rt.emit("peer_abort", host=1, cause="peer_death")
        rt.emit("elastic_resume", from_dp=4, to_dp=2)
        rt.emit("hang_detected", iteration=5)
        rt.emit("sdc_detected", iteration=6)
        rt.journal_sink().emit("commit_abort", iteration=7, reason="x")
        rt.emit("cadence_retune", to_interval=40)
        text = reg.render()
    finally:
        rt.close()
    for needle in ("train_preemptions_total 1",
                   "train_peer_aborts_total 2",
                   "train_elastic_resumes_total 1",
                   "train_hangs_total 1",
                   "train_sdc_total 1",
                   "train_commit_aborts_total 1",
                   "train_cadence_retunes_total 1"):
        assert needle in text, (needle, text)
    # the sink ALSO journaled (the saver path writes events, not just
    # counters)
    from megatron_tpu.telemetry.journal import read_events

    evs, _ = read_events(os.path.join(str(tmp_path / "tele"),
                                      "events.jsonl"))
    assert [e for e in evs if e["kind"] == "commit_abort"]


def test_telemetry_report_merges_hosts(tmp_path):
    """Satellite: one command over N per-host journals — preemption
    notices by notice_host, peer aborts by (host, cause), commit
    aborts."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import telemetry_report
    finally:
        sys.path.pop(0)

    def write(path, events):
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            for e in events:
                f.write(json.dumps(e) + "\n")

    j0 = str(tmp_path / "h0" / "events.jsonl")
    j1 = str(tmp_path / "h1" / "events.jsonl")
    write(j0, [
        {"ts": 1, "kind": "run_start", "host": 0, "num_hosts": 2},
        {"ts": 2, "kind": "preemption", "iteration": 4, "notice_host": 1},
        {"ts": 3, "kind": "commit_abort", "iteration": 6, "host": 0},
    ])
    write(j1, [
        {"ts": 1, "kind": "run_start", "host": 1, "num_hosts": 2},
        {"ts": 2, "kind": "preemption", "iteration": 4, "notice_host": 1},
        {"ts": 3, "kind": "peer_abort", "host": 0, "cause": "hang"},
        {"ts": 4, "kind": "cadence_retune", "to_interval": 40},
    ])
    summary = telemetry_report.summarize(
        telemetry_report.load_journals([j0, j1]))
    co = summary["coordination"]
    assert co["hosts"] == [0, 1]
    # ONE cluster preemption journaled by BOTH hosts dedups to one
    # notice (identity = notice_host + iteration); per-host observations
    # (peer_abort) stay counted as observations
    assert co["preemption_notices_by_host"] == {"host 1": 1}
    assert co["peer_aborts"] == {"host 0: hang": 1}
    assert co["commit_aborts"]["total"] == 1
    assert co["commit_aborts"]["iterations"] == [6]
    assert co["cadence_retunes"]["last_interval"] == 40
    text = telemetry_report.render(summary)
    assert "peer aborts" in text and "host 0: hang: 1" in text
    assert "preemption notices" in text


# -- REAL 2-process jax.distributed KV-backend drill --------------------------


_KV_DRILL = r"""
import time
from megatron_tpu.training.coordination import (
    ClusterCoordinator, CommitAborted, KVBackend)

c = ClusterCoordinator(KVBackend(), pid, 2, peer_death_timeout_s=10,
                       poll_s=0.05)
c.topology_barrier(60)
print(f"P{pid} topo ok", flush=True)

# signal agreement: the notice lands on host 0 only; host 1 reads the
# cluster union and both agree on the max acked iteration
if pid == 0:
    c.publish_signals(["SIGTERM"])
deadline = time.monotonic() + 30
while not c.cluster_signals():
    assert time.monotonic() < deadline, "union never arrived"
    time.sleep(0.05)
assert c.notice_host() == 0
target, nh = c.agree_exit_iteration(3 + pid, 30)
assert (target, nh) == (4, 0), (target, nh)
print(f"P{pid} exit agreement ok", flush=True)

# two-phase commit: both staged -> both proceed
c.commit_barrier(7, f"crc{pid}", 30)
print(f"P{pid} commit ok", flush=True)
# one-sided staging aborts (host 1 deliberately never votes for 9)
if pid == 0:
    try:
        c.commit_barrier(9, "crc0", 1.0)
        print("P0 COMMIT-9-DID-NOT-ABORT", flush=True)
    except CommitAborted:
        print("P0 commit 9 aborted as required", flush=True)

# host-data broadcast over the KV store (no XLA collective involved)
val = c.broadcast({"interval": 40} if pid == 0 else None, root=0,
                  key="cfg", timeout_s=30)
assert val == {"interval": 40}, val
print(f"P{pid} broadcast ok", flush=True)

# poison record visibility (LAST: a poison record aborts commit
# barriers by design, so nothing protocol-shaped can follow it)
if pid == 1:
    c.publish_abort("hang", iteration=5)
if pid == 0:
    deadline = time.monotonic() + 30
    v = None
    while v is None and time.monotonic() < deadline:
        v = c.peer_abort()
        time.sleep(0.05)
    assert v and v["cause"] == "hang" and v["host"] == 1, v
    print("P0 poison ok", flush=True)

# exit rendezvous over plain records (each publishes done, waits for the
# peer's) so neither tears down the coordination service under the other
c.publish_value("done", True)
deadline = time.monotonic() + 60
while c.read_value("done", host=1 - pid) is None:
    assert time.monotonic() < deadline, "peer never finished"
    time.sleep(0.05)
print(f"P{pid} DRILL-OK", flush=True)
"""


def test_kv_backend_two_process_drill(jax_cluster):
    """All four protocols over the REAL jax.distributed KV store between
    two CPU processes — the backend a real cluster uses, with zero extra
    infrastructure."""
    results = jax_cluster(_KV_DRILL, nprocs=2, devices_per_proc=1,
                          timeout=240)
    for i, (rc, out) in enumerate(results):
        assert rc == 0, f"worker {i} failed:\n{out}"
        assert f"P{i} DRILL-OK" in out
    assert "P0 commit 9 aborted as required" in results[0][1]
    assert "COMMIT-9-DID-NOT-ABORT" not in results[0][1]
    assert "P0 poison ok" in results[0][1]


# -- two-host CLI acceptance --------------------------------------------------


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    from tools import preprocess_data

    tmp = tmp_path_factory.mktemp("corpus")
    rng = np.random.default_rng(0)
    jsonl = tmp / "docs.jsonl"
    with open(jsonl, "w") as f:
        for _ in range(150):
            n = int(rng.integers(20, 60))
            f.write(json.dumps({"text": " ".join(
                str(int(x)) for x in rng.integers(0, 97, n))}) + "\n")
    prefix = str(tmp / "corpus")
    preprocess_data.main(["--input", str(jsonl), "--output_prefix", prefix,
                          "--tokenizer_type", "null", "--vocab_size", "97",
                          "--append_eod"])
    return prefix


def _host_cmd(corpus, save, tele, coord_dir, train_iters, save_interval,
              extra=()):
    cmd = [
        sys.executable, os.path.join(REPO, "pretrain_gpt.py"),
        "--num_layers", "2", "--hidden_size", "32",
        "--num_attention_heads", "4", "--vocab_size", "128",
        "--seq_length", "32", "--use_rms_norm", "--glu_activation", "swiglu",
        "--fp32", "--micro_batch_size", "2", "--global_batch_size", "4",
        "--train_iters", str(train_iters), "--log_interval", "1",
        "--lr", "1e-3", "--lr_decay_style", "constant",
        "--data_path", corpus, "--split", "95,5,0",
        "--eval_interval", "10000", "--save", save, "--load", save,
        "--save_interval", str(save_interval),
        "--telemetry_dir", tele,
        "--preempt_save_timeout", "120", *extra]
    if coord_dir:
        cmd += ["--coordination_dir", coord_dir]
    return cmd


def _run_two_hosts(corpus, base, coord_dir, fault_by_host=None,
                   train_iters=8, save_interval=2, extra=(),
                   peer_death_timeout="3", timeout=300):
    """Two pretrain_gpt.py processes = two single-device 'hosts' sharing
    only the coordination dir (replicated data/seed). Returns
    [(rc, stdout+stderr), ...] per host."""
    procs = []
    for host in range(2):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["MEGATRON_TPU_FORCE_PLATFORM"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        env.pop("JAX_COMPILATION_CACHE_DIR", None)
        env.pop(resilience.FAULT_ENV, None)
        env[coordination.COORD_HOST_ENV] = str(host)
        env[coordination.COORD_NUM_HOSTS_ENV] = "2"
        env[coordination.STARTUP_TIMEOUT_ENV] = "120"
        fault = (fault_by_host or {}).get(host)
        if fault:
            env[resilience.FAULT_ENV] = fault
        cmd = _host_cmd(corpus, os.path.join(base, f"save{host}"),
                        os.path.join(base, f"tele{host}"), coord_dir,
                        train_iters, save_interval,
                        extra=tuple(extra)
                        + ("--peer_death_timeout_s", peer_death_timeout))
        procs.append(subprocess.Popen(
            cmd, env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=REPO))
    # drain BOTH pipes concurrently: a sequential communicate() lets the
    # not-yet-waited host fill its 64KB stdout pipe and block in print
    # mid-pass — its main thread then never reaches the signal check
    # while its sideband keeps heartbeating, which reads as a live host
    # ignoring the cluster (a real debugging episode, not a hypothetical)
    chunks = [[] for _ in procs]
    readers = [threading.Thread(target=lambda p=p, c=c: c.append(
        p.stdout.read()), daemon=True) for p, c in zip(procs, chunks)]
    for r in readers:
        r.start()
    out = []
    deadline = time.monotonic() + timeout
    for p, c, r in zip(procs, chunks, readers):
        try:
            p.wait(timeout=max(deadline - time.monotonic(), 1.0))
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()
        r.join(timeout=30)
        out.append((p.returncode, c[0] if c else ""))
    return out


def _events(base, host):
    from megatron_tpu.telemetry.journal import read_events

    evs, _ = read_events(os.path.join(base, f"tele{host}", "events.jsonl"))
    return evs


def test_sigterm_one_host_drains_both(tmp_path, corpus):
    """Acceptance (ISSUE 12): a SIGTERM delivered to ONE host
    (preempt_host:0:3) drains and checkpoints BOTH hosts — both exit 0
    with one cluster-consistent committed checkpoint tagged `preemption`,
    and both journals record the same notice_host and commit."""
    base = str(tmp_path)
    coord_dir = os.path.join(base, "coord")
    results = _run_two_hosts(
        corpus, base, coord_dir,
        # the notice lands on host 0 ONLY; host 1 must learn of it
        # through the agreement seam. The notice fires past the compile
        # (iteration 25) and train_iters is far beyond reach, so both
        # hosts are mid-run when they drain — the agreed iteration is
        # whatever the slower host had acked, never the end of the run.
        fault_by_host={0: "preempt_host:0:25", 1: "preempt_host:0:25"},
        # the 2-core host runs both compiles concurrently (and tier-1 may
        # have background load): heartbeat cadence degrades badly during
        # the overlap, so the death window must be startup-grade here —
        # peer death is the NEXT test's subject
        train_iters=3000, save_interval=10000, peer_death_timeout="90")
    for host, (rc, out) in enumerate(results):
        # captured; surfaces BOTH hosts' tails when any assert fails
        print(f"===== host {host} rc={rc} =====\n{out[-4000:]}")
    for host, (rc, out) in enumerate(results):
        assert rc == 0, f"host {host}: rc={rc}\n{out[-4000:]}"
        assert "preemption notice: expedited synchronous save" in out, (
            host, out[-3000:])
    assert "preempt_host firing on host 0" in results[0][1]
    assert "preempt_host firing" not in results[1][1]

    # ONE cluster-consistent committed checkpoint: same iteration on both
    # hosts, both tagged, both deep-verified
    trackers = [checkpointing.read_tracker(os.path.join(base, f"save{h}"))
                for h in range(2)]
    assert trackers[0] == trackers[1] and trackers[0] is not None, trackers
    assert trackers[0] >= 25  # at or past the notice step, never before
    assert trackers[0] < 3000  # and nowhere near normal completion
    for h in range(2):
        ckpt = checkpointing.checkpoint_dir(
            os.path.join(base, f"save{h}"), trackers[h])
        assert checkpointing.verify_checkpoint(ckpt, deep=True)[0]
        assert "preemption" in checkpointing.checkpoint_tags(ckpt)

    # both journals: `preemption` with the SAME notice_host and iteration
    pres = []
    for h in range(2):
        evs = _events(base, h)
        pre = [e for e in evs if e["kind"] == "preemption"]
        assert len(pre) == 1, (h, [e["kind"] for e in evs])
        assert pre[0]["notice_host"] == 0
        assert pre[0]["host"] == h
        pres.append(pre[0])
        run_end = [e for e in evs if e["kind"] == "run_end"][-1]
        assert run_end["received_signal"] == "SIGTERM"
    assert pres[0]["iteration"] == pres[1]["iteration"] == trackers[0]

    # --perfetto round-trip (ISSUE 13): BOTH hosts' real journals render
    # as one schema-valid timeline — two host processes, step spans, and
    # the cluster preemption visible as an instant on each
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import telemetry_report
    finally:
        sys.path.pop(0)
    from test_telemetry import validate_trace_events

    out_json = os.path.join(base, "cluster.perfetto.json")
    trace = telemetry_report.write_perfetto(
        [os.path.join(base, f"tele{h}", "events.jsonl")
         for h in range(2)], out_json)
    assert validate_trace_events(trace)
    assert os.path.exists(out_json)
    procs = {e["args"]["name"] for e in trace["traceEvents"]
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert any("host 0" in p for p in procs)
    assert any("host 1" in p for p in procs)
    assert {e["pid"] for e in trace["traceEvents"]} == {0, 1}
    for pid in (0, 1):
        assert any(e["ph"] == "X" and e["name"].startswith("step ")
                   and e["pid"] == pid for e in trace["traceEvents"])
        assert any(e["ph"] == "i" and e["name"] == "preemption"
                   and e["pid"] == pid for e in trace["traceEvents"])


def test_sigkill_one_host_peer_abort_within_timeout(tmp_path, corpus):
    """Acceptance (ISSUE 12): SIGKILL of one host mid-run → the survivor
    exits PEER_ABORT_EXIT_CODE with a `peer_abort` journal event within
    --peer_death_timeout_s — not a test-timeout kill."""
    base = str(tmp_path)
    coord_dir = os.path.join(base, "coord")
    t0 = time.monotonic()
    results = _run_two_hosts(
        corpus, base, coord_dir,
        fault_by_host={1: "kill_host:1:4"},
        # long enough that the survivor is still mid-run when the
        # detection window closes (the kill lands after both compiles,
        # so steady-state heartbeats make 4s a safe window)
        train_iters=2000, save_interval=100000, peer_death_timeout="4",
        extra=("--log_interval", "100"), timeout=240)
    wall = time.monotonic() - t0
    rc1, out1 = results[1]
    assert rc1 == -signal.SIGKILL, (rc1, out1[-2000:])
    assert "kill_host firing on host 1" in out1
    rc0, out0 = results[0]
    assert rc0 == resilience.PEER_ABORT_EXIT_CODE, (rc0, out0[-4000:])
    assert "peer abort: host 1 (peer_death)" in out0
    evs = _events(base, 0)
    pa = [e for e in evs if e["kind"] == "peer_abort"]
    assert len(pa) == 1
    assert pa[0]["host"] == 1 and pa[0]["cause"] == "peer_death"
    assert pa[0]["observed_by"] == 0
    # bounded reaction: well inside the run, nowhere near the 240s kill
    assert wall < 180, wall


def test_kill_during_save_never_half_commits(tmp_path, corpus):
    """Acceptance (ISSUE 12, two-phase commit proof): kill_during_save on
    ONE of two hosts leaves NO half-committed checkpoint — the survivor's
    commit aborts (its tracker never flips), resume on both hosts falls
    back to the SAME older valid checkpoint, and the post-resume loss
    curve is bitwise-identical to an uninterrupted oracle."""
    # oracle: coordination adds no math/data — a plain single-process
    # uninterrupted run is the curve both hosts must reproduce
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["MEGATRON_TPU_FORCE_PLATFORM"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    env.pop(resilience.FAULT_ENV, None)
    oracle = subprocess.run(
        _host_cmd(corpus, str(tmp_path / "oracle"),
                  str(tmp_path / "oracle_tele"), None, 8, 2),
        env=env, capture_output=True, text=True, cwd=REPO, timeout=300)
    assert oracle.returncode == 0, oracle.stderr[-3000:]
    ref = _losses_by_iteration(oracle.stdout)
    assert set(ref) == set(range(1, 9))

    base = str(tmp_path / "cluster")
    os.makedirs(base)
    coord_dir = os.path.join(base, "coord")
    results = _run_two_hosts(
        corpus, base, coord_dir,
        fault_by_host={1: "kill_during_save:4"},
        train_iters=8, save_interval=2, peer_death_timeout="5")
    rc1, out1 = results[1]
    assert rc1 == -signal.SIGKILL, (rc1, out1[-2000:])
    rc0, out0 = results[0]
    # two designed no-half-commit verdicts race on the survivor: the
    # sideband's peer-death exit (76) vs the commit barrier's own
    # dead-peer CommitAborted (loud error exit) — both watch the same
    # heartbeat staleness, whichever polls first wins. Either way the
    # tracker never flipped.
    assert rc0 in (resilience.PEER_ABORT_EXIT_CODE, 1), (rc0, out0[-4000:])
    assert ("peer abort: host 1" in out0
            or "commit ABORTED" in out0), out0[-4000:]
    evs0 = _events(base, 0)
    assert [e for e in evs0 if e["kind"] in ("peer_abort", "commit_abort")]
    # NO half-commit anywhere: iteration 4 is not valid on either host
    for h in range(2):
        save = os.path.join(base, f"save{h}")
        assert checkpointing.list_valid_checkpoints(save) == [2], h
        assert checkpointing.read_tracker(save) == 2, h

    # resume: both hosts agree on the SAME older checkpoint and complete
    resumed = _run_two_hosts(corpus, base, os.path.join(base, "coord2"),
                             train_iters=8, save_interval=2,
                             peer_death_timeout="10")
    for h, (rc, out) in enumerate(resumed):
        assert rc == 0, f"host {h}: rc={rc}\n{out[-4000:]}"
        assert "loaded checkpoint at iteration 2" in out, (h, out[-3000:])
        # bitwise-identical post-resume loss curve vs the oracle
        losses = _losses_by_iteration(out)
        assert set(losses) == set(range(3, 9)), (h, sorted(losses))
        for it in range(3, 9):
            assert losses[it] == ref[it], (h, it, losses[it], ref[it])
        assert checkpointing.read_tracker(
            os.path.join(base, f"save{h}")) == 8


def _losses_by_iteration(stdout):
    import re

    out = {}
    for m in re.finditer(r"iteration (\d+)/\d+ \|.*?lm loss: ([0-9.einf-]+)",
                         stdout):
        out[int(m.group(1))] = m.group(2)
    return out


def test_save_interval_auto_in_process(tmp_path):
    """--save_interval auto end-to-end: with a grace window too small for
    any budget the cadence clamps to the floor deterministically, saves
    land every `floor` steps, and the retune is journaled."""
    from megatron_tpu.config import (
        ModelConfig, OptimizerConfig, RunConfig, TrainingConfig,
    )
    from megatron_tpu.telemetry.journal import read_events
    from megatron_tpu.training.pretrain import TrainLoop

    model = ModelConfig(
        num_layers=2, hidden_size=32, num_attention_heads=4, num_kv_heads=4,
        ffn_hidden_size=64, vocab_size=64, seq_length=16,
        params_dtype="float32").validate()
    rng = np.random.default_rng(0)
    proto = {"tokens": rng.integers(0, 64, (8, 16)).astype(np.int64),
             "labels": rng.integers(0, 64, (8, 16)).astype(np.int64),
             "loss_mask": np.ones((8, 16), np.float32)}

    def factory(consumed, gbs):
        def gen():
            while True:
                yield proto
        return gen()

    tele = tmp_path / "tele"
    cfg = RunConfig(
        model=model,
        optimizer=OptimizerConfig(lr=1e-3, lr_decay_style="constant"),
        training=TrainingConfig(
            micro_batch_size=1, global_batch_size=8, train_iters=11,
            log_interval=1 << 30, seed=0, telemetry_dir=str(tele),
            save=str(tmp_path / "ckpt"),
            save_interval_auto=True, save_interval_floor=4,
            # grace smaller than any step: budget 0 => floor cadence
            preempt_save_timeout=1e-6))
    loop = TrainLoop(cfg, log=lambda m: None)
    loop.train(factory)
    evs, _ = read_events(os.path.join(str(tele), "events.jsonl"))
    retunes = [e for e in evs if e["kind"] == "cadence_retune"]
    assert retunes and retunes[0]["to_interval"] == 4
    assert retunes[0]["floor"] == 4
    commits = sorted(e["iteration"] for e in evs
                     if e["kind"] == "checkpoint_commit")
    # every floor-th step, plus the end-of-run save
    assert commits == [4, 8, 11], commits
    # mutual exclusion with a fixed interval is validated loudly
    with pytest.raises(ValueError, match="mutually exclusive"):
        TrainingConfig(save_interval=5, save_interval_auto=True).validate()


def test_save_interval_auto_refused_on_coordinated_runs(tmp_path,
                                                        monkeypatch):
    """Per-host-measured cadences cannot agree on exact future save
    iterations; the combination must be a loud startup error, never a
    desynchronized two-phase commit."""
    from megatron_tpu.config import (
        ModelConfig, OptimizerConfig, RunConfig, TrainingConfig,
    )
    from megatron_tpu.training.pretrain import TrainLoop

    monkeypatch.setenv(coordination.COORD_HOST_ENV, "0")
    monkeypatch.setenv(coordination.COORD_NUM_HOSTS_ENV, "2")
    model = ModelConfig(
        num_layers=2, hidden_size=32, num_attention_heads=4, num_kv_heads=4,
        ffn_hidden_size=64, vocab_size=64, seq_length=16,
        params_dtype="float32").validate()
    cfg = RunConfig(
        model=model,
        optimizer=OptimizerConfig(lr=1e-3, lr_decay_style="constant"),
        training=TrainingConfig(
            micro_batch_size=1, global_batch_size=8, train_iters=2,
            save=str(tmp_path / "ckpt"), save_interval_auto=True,
            coordination_dir=str(tmp_path / "coord")))
    with pytest.raises(ValueError, match="not supported on coordinated"):
        TrainLoop(cfg, log=lambda m: None)

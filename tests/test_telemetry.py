"""Unified telemetry subsystem tests (ISSUE 4).

Covers the tentpole's acceptance surface:
  * journal write / rotation / crash-replay (torn final line tolerated);
  * Prometheus text exposition (counters/gauges/histograms, labels,
    escaping, get-or-create registration);
  * goodput accounting — including the REAL train-loop path: a subprocess
    pretrain run under the `slow_save` fault whose journal must show the
    checkpoint stall attributed to non-productive time;
  * recompile tracking: the serving engine's zero-recompiles-after-warmup
    invariant as a runtime counter over a real jitted decode step;
  * the flight recorder firing deterministically on a stalled heartbeat
    (short deadline, bundle contents checked);
  * GET /metrics on a running serving HTTP server returning Prometheus
    text with slot/queue/latency metrics;
  * tools/telemetry_report.py summarizing a journal.
"""

import json
import os
import re
import subprocess
import sys
import threading
import time
import urllib.request
from http.server import ThreadingHTTPServer

import numpy as np
import pytest

from megatron_tpu import telemetry
from megatron_tpu.telemetry import (
    EventJournal, FlightRecorder, GoodputTracker, MetricsRegistry,
    read_events, recompile_tracker,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# metrics registry + Prometheus exposition


def test_prometheus_exposition_format():
    r = MetricsRegistry()
    c = r.counter("http_requests_total", "requests served",
                  label_names=("status",))
    c.inc(status="200")
    c.inc(2, status="500")
    g = r.gauge("slots_active", "live slots")
    g.set(3)
    h = r.histogram("tick_seconds", "tick time", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.5, 5.0):
        h.observe(v)
    text = r.render()
    # HELP/TYPE headers precede each family, one family per metric name
    assert "# HELP http_requests_total requests served" in text
    assert "# TYPE http_requests_total counter" in text
    assert '# TYPE tick_seconds histogram' in text
    assert 'http_requests_total{status="200"} 1' in text
    assert 'http_requests_total{status="500"} 2' in text
    assert "slots_active 3" in text
    # cumulative le buckets + +Inf + sum/count
    assert 'tick_seconds_bucket{le="0.01"} 1' in text
    assert 'tick_seconds_bucket{le="0.1"} 2' in text
    assert 'tick_seconds_bucket{le="1"} 3' in text
    assert 'tick_seconds_bucket{le="+Inf"} 4' in text
    assert "tick_seconds_count 4" in text
    assert re.search(r"tick_seconds_sum 5\.55\d*", text)


def test_prometheus_label_escaping():
    r = MetricsRegistry()
    c = r.counter("errors_total", "errors", label_names=("message",))
    c.inc(message='bad "quote"\nand\\slash')
    text = r.render()
    assert r'message="bad \"quote\"\nand\\slash"' in text


def test_registry_get_or_create_and_conflicts():
    r = MetricsRegistry()
    a = r.counter("x_total", "x")
    b = r.counter("x_total", "x")
    assert a is b  # two subsystems sharing a name share the collector
    with pytest.raises(ValueError):
        r.gauge("x_total", "x")  # same name, different type = a bug
    with pytest.raises(ValueError):
        r.counter("x_total", "x", label_names=("k",))  # schema change too
    with pytest.raises(ValueError):
        a.inc(-1)  # counters are monotonic
    with pytest.raises(ValueError):
        a.inc(1, nope="v")  # undeclared label


def test_labeled_registry_view():
    """The CP x DP lane facade: constant labels stamped onto every
    collector a lane registers, so N lanes share one host registry
    while the exposition keeps per-lane series."""
    from megatron_tpu.telemetry.metrics import LabeledRegistryView

    r = MetricsRegistry()
    lane0 = LabeledRegistryView(r, lane="0")
    lane1 = LabeledRegistryView(r, lane="1")
    c0 = lane0.counter("engine_steps_total", "steps")
    c1 = lane1.counter("engine_steps_total", "steps")
    c0.inc(3)
    c1.inc(5)
    assert c0.value() == 3.0 and c1.value() == 5.0
    # per-call labels merge with the pinned one
    g0 = lane0.gauge("engine_free", "free", label_names=("shard",))
    g0.set(7, shard="1")
    assert g0.value(shard="1") == 7.0
    text = r.render()
    assert 'engine_steps_total{lane="0"} 3' in text
    assert 'engine_steps_total{lane="1"} 5' in text
    assert 'engine_free{lane="0",shard="1"} 7' in text or \
        'engine_free{shard="1",lane="0"} 7' in text
    # passing the pinned label per-call is a collision, not a silent
    # override
    with pytest.raises(ValueError, match="pinned"):
        c0.inc(lane="9")
    with pytest.raises(ValueError):
        LabeledRegistryView(r)  # a view without labels is pointless
    # histograms proxy too (the latency series the router percentiles)
    h = lane1.histogram("engine_tick_seconds", "tick")
    h.observe(0.5)
    assert 'engine_tick_seconds_count{lane="1"} 1' in r.render()


# ---------------------------------------------------------------------------
# event journal


def test_journal_write_and_replay(tmp_path):
    j = EventJournal(str(tmp_path / "events.jsonl"))
    j.emit("step", iteration=1, loss=2.5)
    j.emit("checkpoint_begin", iteration=1, async_save=True)
    j.close()
    evs, torn = read_events(str(tmp_path / "events.jsonl"))
    assert torn is None
    assert [e["kind"] for e in evs] == ["step", "checkpoint_begin"]
    assert evs[0]["loss"] == 2.5 and evs[0]["ts"] > 0
    # numpy scalars must serialize (journal fields come from jax/numpy)
    j2 = EventJournal(str(tmp_path / "events.jsonl"))
    j2.emit("step", loss=np.float32(1.5), n=np.int64(3))
    j2.close()
    evs, _ = read_events(str(tmp_path / "events.jsonl"))
    assert evs[-1]["loss"] == 1.5 and evs[-1]["n"] == 3


def test_journal_rotation_bounds_disk(tmp_path):
    path = str(tmp_path / "events.jsonl")
    j = EventJournal(path, max_bytes=500, keep_segments=2)
    for i in range(60):
        j.emit("step", iteration=i)
    j.close()
    segs = j.segments()
    assert len(segs) <= 3  # live + keep_segments
    assert all(os.path.getsize(s) <= 600 for s in segs)
    # replay across segments is oldest-first and contiguous at the tail
    its = [e["iteration"] for e in j.events()]
    assert its == sorted(its)
    assert its[-1] == 59
    assert j.tail(3) == j.events()[-3:]


def test_journal_crash_replay_tolerates_torn_line(tmp_path):
    path = str(tmp_path / "events.jsonl")
    j = EventJournal(path)
    j.emit("step", iteration=1)
    j.emit("step", iteration=2)
    j.close()
    with open(path, "a") as f:
        f.write('{"ts": 3, "kind": "step", "iterat')  # SIGKILL mid-write
    evs, torn = read_events(path)
    assert [e["iteration"] for e in evs] == [1, 2]
    assert torn is not None and torn.startswith('{"ts": 3')


# ---------------------------------------------------------------------------
# goodput accounting


def test_goodput_tracker_split_and_report():
    now = [100.0]
    gp = GoodputTracker(clock=lambda: now[0])
    gp.attribute("productive", 6.0)
    gp.attribute("checkpoint_stall", 2.0)
    with gp.track("eval"):
        now[0] += 1.0
    now[0] = 110.0
    rep = gp.report()
    assert rep["wall_s"] == 10.0
    assert rep["goodput"] == pytest.approx(0.6)
    assert rep["checkpoint_stall_s"] == 2.0
    assert rep["eval_s"] == 1.0
    # the unattributed remainder lands in `other`; the split sums to wall
    assert rep["other_s"] == pytest.approx(1.0)
    total = sum(rep[f"{c}_s"] for c in telemetry.CATEGORIES)
    assert total == pytest.approx(rep["wall_s"])
    with pytest.raises(ValueError):
        gp.attribute("napping", 1.0)


def test_recompile_tracker_counts_backend_compiles():
    import jax
    import jax.numpy as jnp

    t = recompile_tracker()
    f = jax.jit(lambda x: x * 3 + 1)
    f(jnp.zeros(7)).block_until_ready()
    snap = t.snapshot()
    f(jnp.ones(7)).block_until_ready()     # cache hit: no new compile
    assert t.delta(snap)["compiles"] == 0
    f(jnp.ones(13)).block_until_ready()    # new shape: recompile
    d = t.delta(snap)
    assert d["compiles"] >= 1
    assert d["compile_seconds"] > 0


# ---------------------------------------------------------------------------
# flight recorder


def test_flight_recorder_fires_deterministically_on_stall(tmp_path):
    """Short deadline + stalled heartbeat => exactly one bundle, with
    all-thread stacks and the journal tail (the ISSUE acceptance test)."""
    j = EventJournal(str(tmp_path / "events.jsonl"))
    for i in range(5):
        j.emit("step", iteration=i)
    logs = []
    fr = FlightRecorder(out_dir=str(tmp_path / "bundles"), deadline_s=0.25,
                        journal=j, tail_events=3, poll_s=0.05,
                        log=logs.append)
    with fr:
        fr.heartbeat("iteration 5")
        deadline = time.monotonic() + 10.0
        while not fr.bundles and time.monotonic() < deadline:
            time.sleep(0.05)  # heartbeat stalls; watchdog must fire
        # one bundle per stall, not one per poll tick
        time.sleep(0.4)
    assert len(fr.bundles) == 1, logs
    bundle = fr.bundles[0]
    meta = json.load(open(os.path.join(bundle, "meta.json")))
    assert meta["deadline_s"] == 0.25
    assert meta["heartbeat_age_s"] >= 0.25
    assert meta["last_note"] == "iteration 5"
    stacks = open(os.path.join(bundle, "stacks.txt")).read()
    assert "--- thread MainThread" in stacks
    assert "flight-recorder" in stacks  # every thread, watchdog included
    evs, _ = read_events(os.path.join(bundle, "events.jsonl"))
    assert [e["iteration"] for e in evs] == [2, 3, 4]  # last N only


def test_flight_recorder_heartbeat_keeps_it_quiet(tmp_path):
    fr = FlightRecorder(out_dir=str(tmp_path), deadline_s=0.3, poll_s=0.05,
                        log=lambda m: None)
    with fr:
        for _ in range(12):
            fr.heartbeat()
            time.sleep(0.05)  # 0.6s total, never 0.3s without a beat
    assert fr.bundles == []


def test_flight_recorder_not_live_before_first_heartbeat(tmp_path):
    """The window between arming and the first heartbeat holds the
    initial multi-minute XLA compile — it must never be judged against a
    steady-state step deadline (abort=True would crash-loop there)."""
    fr = FlightRecorder(out_dir=str(tmp_path), deadline_s=0.15, poll_s=0.03,
                        log=lambda m: None)
    with fr:
        time.sleep(0.6)  # way past the deadline, zero heartbeats
        assert fr.bundles == []
        fr.heartbeat("first step")  # live now; a stall past here fires
        deadline = time.monotonic() + 10.0
        while not fr.bundles and time.monotonic() < deadline:
            time.sleep(0.03)
    assert len(fr.bundles) == 1


def test_flight_recorder_refires_after_recovery(tmp_path):
    """A fresh heartbeat after a dumped stall re-arms the watchdog."""
    fr = FlightRecorder(out_dir=str(tmp_path), deadline_s=0.2, poll_s=0.04,
                        log=lambda m: None)
    with fr:
        fr.heartbeat("first step")  # the watchdog goes live here
        deadline = time.monotonic() + 10.0
        while len(fr.bundles) < 1 and time.monotonic() < deadline:
            time.sleep(0.04)
        fr.heartbeat("recovered")  # re-arm
        while len(fr.bundles) < 2 and time.monotonic() < deadline:
            time.sleep(0.04)
    assert len(fr.bundles) == 2


# ---------------------------------------------------------------------------
# serving engine: metrics + the zero-recompiles-after-warmup invariant


def _tiny_cfg():
    from megatron_tpu.models import presets

    return presets.tiny(vocab_size=64, seq_length=64)


def test_engine_metrics_and_zero_recompiles_after_warmup():
    """Two waves of heterogeneous traffic through a REAL jitted decode
    step: the decode jit cache must hold exactly the warmup entry, the
    runtime counter must stay 0, and the latency/occupancy collectors
    must have observed the traffic."""
    import jax

    from megatron_tpu.inference.engine import InferenceEngine, Request
    from megatron_tpu.models.params import init_params

    cfg = _tiny_cfg()
    # COMMITTED params, like every checkpoint-loaded serving deployment
    # (load_params_only restores with explicit shardings): with any
    # committed argument, an uncommitted host-uploaded carry/cache once
    # split the decode step into two compiled signatures — this counter
    # is the regression gate for that (engine._commit)
    params = jax.device_put(
        init_params(cfg, jax.random.PRNGKey(0)),
        jax.sharding.SingleDeviceSharding(jax.devices()[0]))
    reg = MetricsRegistry()
    eng = InferenceEngine(cfg, params, num_slots=2, max_seq_len=48,
                          metrics=reg)
    rng = np.random.default_rng(0)

    def wave(n, temp):
        reqs = [eng.submit(Request(
            prompt=rng.integers(1, 64, 5).astype(np.int32),
            max_new_tokens=4, temperature=temp, top_k=3 if temp else 0,
            seed=i)) for i in range(n)]
        eng.run_until_idle()
        for r in reqs:
            assert r.error is None, r.error

    wave(3, 0.0)          # warmup + greedy traffic
    wave(3, 1.0)          # heterogeneous sampling knobs: SAME compiled step
    assert eng.stats["decode_recompiles"] == 0
    assert eng._decode_step._cache_size() == 1  # warmup entry only
    assert eng.stats["admitted"] == 6 and eng.stats["retired"] == 6

    text = reg.render()
    assert "engine_slots_total 2" in text
    assert "engine_requests_admitted_total 6" in text
    assert "engine_decode_recompiles_total 0" in text
    assert reg.get("engine_ttft_seconds").count() == 6
    assert reg.get("engine_decode_tick_seconds").count() == eng.stats["ticks"]
    assert reg.get("engine_time_per_output_token_seconds").count() == 6
    # idle engine: occupancy gauges back to zero
    assert "engine_slots_active 0" in text
    assert "engine_queue_depth 0" in text


def test_engine_tick_heartbeats_flight_recorder():
    """The engine's step loop feeds the watchdog (fake model: the wiring
    is scheduler-side, no compiles needed)."""
    from test_serving_engine import _fake_steps, make_engine

    from megatron_tpu.inference.engine import Request

    fr = FlightRecorder(out_dir="unused", deadline_s=60.0, log=lambda m: None)
    eng = _fake_steps(make_engine(metrics=MetricsRegistry(),
                                  flight_recorder=fr))
    eng.submit(Request(prompt=np.array([1, 2], np.int32), max_new_tokens=3))
    eng.run_until_idle()
    with fr._lock:
        assert fr._beat_count >= eng.stats["ticks"] > 0


def test_server_metrics_endpoint():
    """Acceptance: GET /metrics on a running serving engine returns
    Prometheus text with slot/queue/latency metrics."""
    import jax

    from megatron_tpu.inference.server import GenerationService, make_handler
    from megatron_tpu.models.params import init_params
    from megatron_tpu.tokenizer.tokenizer import NullTokenizer

    cfg = _tiny_cfg()
    params = init_params(cfg, jax.random.PRNGKey(1))
    reg = MetricsRegistry()
    service = GenerationService(cfg, params, NullTokenizer(63),
                                engine_slots=2, engine_max_seq_len=48,
                                metrics=reg)
    server = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(service))
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        body = json.dumps({"prompts": ["3 7 11"], "tokens_to_generate": 4,
                           "top_k": 1}).encode()
        req = urllib.request.Request(f"http://127.0.0.1:{port}/api",
                                     data=body, method="PUT")
        with urllib.request.urlopen(req, timeout=120) as resp:
            assert json.loads(resp.read())["text"]

        with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                                    timeout=30) as resp:
            assert resp.headers["Content-Type"].startswith("text/plain")
            text = resp.read().decode()
        for family in ("engine_slots_total 2", "engine_slots_active",
                       "engine_queue_depth", "engine_ttft_seconds_bucket",
                       "engine_time_per_output_token_seconds_count",
                       'server_requests_total{status="200"} 1',
                       "server_request_seconds_count"):
            assert family in text, f"{family!r} missing from /metrics"

        with urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz",
                                    timeout=30) as resp:
            assert json.loads(resp.read()) == {"ok": True, "engine": True}
    finally:
        server.shutdown()
        service.shutdown()


# ---------------------------------------------------------------------------
# train loop: goodput under the slow_save fault (REAL subprocess run)


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    from tools import preprocess_data

    tmp = tmp_path_factory.mktemp("corpus")
    rng = np.random.default_rng(0)
    jsonl = tmp / "docs.jsonl"
    with open(jsonl, "w") as f:
        for _ in range(80):
            n = int(rng.integers(20, 60))
            f.write(json.dumps({"text": " ".join(
                str(int(x)) for x in rng.integers(0, 97, n))}) + "\n")
    prefix = str(tmp / "corpus")
    preprocess_data.main(["--input", str(jsonl), "--output_prefix", prefix,
                          "--tokenizer_type", "null", "--vocab_size", "97",
                          "--append_eod"])
    return prefix


@pytest.mark.slow  # 27s subprocess run measured cacheless (PR 4
# re-budget); the in-process goodput/journal units above stay tier-1
def test_train_goodput_attributes_slow_save_stall(tmp_path, corpus):
    """Acceptance: a faulted (slow_save) training run's journal shows the
    checkpoint stall attributed to non-productive time. --no_async_save
    keeps the injected sleep inside the train-loop stall span (async
    saves overlap it with compute by design)."""
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", MEGATRON_TPU_FORCE_PLATFORM="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=1",
               MEGATRON_TPU_FAULT="slow_save:400")
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    tele = str(tmp_path / "tele")
    r = subprocess.run([
        sys.executable, os.path.join(REPO, "pretrain_gpt.py"),
        "--num_layers", "2", "--hidden_size", "32",
        "--num_attention_heads", "4", "--vocab_size", "128",
        "--seq_length", "32", "--use_rms_norm", "--glu_activation", "swiglu",
        "--fp32", "--micro_batch_size", "2", "--global_batch_size", "2",
        "--train_iters", "4", "--log_interval", "1",
        "--lr", "1e-3", "--lr_decay_style", "constant",
        "--data_path", corpus, "--split", "95,5,0", "--eval_interval", "100",
        "--save", str(tmp_path / "ckpt"), "--save_interval", "2",
        "--no_async_save", "--telemetry_dir", tele],
        env=env, capture_output=True, text=True, cwd=REPO, timeout=420)
    assert r.returncode == 0, r.stderr[-3000:]

    evs, torn = read_events(os.path.join(tele, "events.jsonl"))
    assert torn is None
    kinds = [e["kind"] for e in evs]
    assert kinds[0] == "run_start" and kinds[-1] == "run_end"
    # the injected sleep is visible as a fault event AND in the stall
    assert [e for e in evs if e["kind"] == "fault_injection"
            and e["fault"] == "slow_save"]
    stalls = [e for e in evs if e["kind"] == "checkpoint_stall"]
    assert stalls and max(e["seconds"] for e in stalls) >= 0.4
    steps = [e for e in evs if e["kind"] == "step"]
    assert len(steps) == 4
    assert all(np.isfinite(e["loss"]) for e in steps)
    final = [e for e in evs if e["kind"] == "goodput"][-1]
    assert final["checkpoint_stall_s"] >= 0.4  # stall is NON-productive
    assert final["productive_s"] > 0
    assert final["goodput"] < 1.0
    assert [e for e in evs if e["kind"] == "checkpoint_commit"]

    # the report tool reads the same journal and surfaces the stall
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import telemetry_report
    finally:
        sys.path.pop(0)
    summary = telemetry_report.summarize(telemetry_report.load_journal(tele))
    assert summary["steps"] == 4
    assert summary["faults"] == ["slow_save", "slow_save"]
    assert summary["goodput_pct"] < 100.0
    assert summary["stall_top"][0]["kind"] == "checkpoint_stall"
    assert summary["stall_top"][0]["seconds"] >= 0.4
    assert summary["step_ms"]["p50"] > 0
    text = telemetry_report.render(summary)
    assert "goodput:" in text and "checkpoint_stall" in text


# ---------------------------------------------------------------------------
# perfetto timeline + --format json (ISSUE 13)
# ---------------------------------------------------------------------------


def _import_telemetry_report():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import telemetry_report
    finally:
        sys.path.pop(0)
    return telemetry_report


def validate_trace_events(trace):
    """Strict structural check against the Chrome trace-event JSON
     schema (the subset the converter emits): ``traceEvents`` list where
    every event has a phase, pid and microsecond timestamp; complete
    events carry a duration, metadata events carry args.name. Shared
    with test_coordination's multi-host round-trip."""
    assert isinstance(trace, dict)
    assert isinstance(trace["traceEvents"], list)
    assert trace.get("displayTimeUnit") in ("ms", "ns")
    for ev in trace["traceEvents"]:
        assert ev["ph"] in ("X", "i", "M"), ev
        assert isinstance(ev["pid"], int), ev
        assert isinstance(ev["name"], str) and ev["name"], ev
        assert isinstance(ev["ts"], (int, float)), ev
        if ev["ph"] == "X":
            assert isinstance(ev["dur"], (int, float)) and ev["dur"] >= 0
            assert isinstance(ev["tid"], int)
            assert ev["ts"] >= 0
        elif ev["ph"] == "i":
            assert ev["s"] in ("g", "p", "t")
            assert isinstance(ev["tid"], int)
        elif ev["ph"] == "M":
            assert ev["name"] in ("process_name", "thread_name")
            assert isinstance(ev["args"]["name"], str)
    return True


def test_perfetto_converter_lanes_and_schema(tmp_path):
    """Journal -> trace events: steps/data-waits/checkpoints/serve
    requests/profile windows become complete spans drawn back from
    their completion timestamps, incidents become instants, and the
    whole object validates against the trace-event schema."""
    from megatron_tpu.telemetry.perfetto import journals_to_trace_events

    t0 = 1000.0
    events = [
        {"ts": t0, "kind": "run_start", "iteration": 0, "host": 3},
        {"ts": t0 + 1.0, "kind": "step", "iteration": 1, "step_ms": 100.0,
         "data_wait_ms": 20.0, "loss": 2.5},
        {"ts": t0 + 1.5, "kind": "checkpoint_begin", "iteration": 1},
        {"ts": t0 + 2.0, "kind": "checkpoint_commit", "iteration": 1,
         "seconds": 0.4},
        {"ts": t0 + 2.1, "kind": "checkpoint_stall", "iteration": 1,
         "seconds": 0.1},
        {"ts": t0 + 2.5, "kind": "eval", "seconds": 0.2},
        {"ts": t0 + 3.0, "kind": "serve_request", "status": "ok",
         "wall_s": 0.8, "ttft_s": 0.1},
        {"ts": t0 + 3.2, "kind": "profile_begin", "iteration": 2,
         "until": 4, "dir": "/t", "source": "SIGUSR1"},
        {"ts": t0 + 3.9, "kind": "profile_end", "iteration": 4},
        {"ts": t0 + 4.0, "kind": "preemption", "iteration": 4,
         "notice_host": 3},
        {"ts": t0 + 4.1, "kind": "profile_begin", "iteration": 5,
         "until": 7, "dir": "/t", "source": "--profile"},
        {"ts": t0 + 4.2, "kind": "profile_aborted", "reason": "hang",
         "flushed": True},
        {"ts": t0 + 4.3, "kind": "profile_begin", "iteration": 8,
         "until": 9, "dir": "/t", "source": "--profile"},
    ]
    trace = journals_to_trace_events([("h3/events.jsonl", events)])
    assert validate_trace_events(trace)
    evs = trace["traceEvents"]
    # pid = the coordination host id off run_start
    assert all(e["pid"] == 3 for e in evs)
    procs = [e for e in evs if e["ph"] == "M"
             and e["name"] == "process_name"]
    assert len(procs) == 1 and "host 3" in procs[0]["args"]["name"]

    def lane(name):
        [m] = [e for e in evs if e["ph"] == "M"
               and e["name"] == "thread_name"
               and e["args"]["name"] == name]
        return m["tid"]

    step = next(e for e in evs if e["ph"] == "X"
                and e["name"] == "step 1")
    assert step["dur"] == pytest.approx(100e3)       # µs
    assert step["ts"] == pytest.approx((1.0 - 0.1) * 1e6)  # drawn back
    assert step["tid"] == lane("train steps")
    wait = next(e for e in evs if e["name"] == "data_wait")
    assert wait["dur"] == pytest.approx(20e3)
    # the wait lane precedes the step span it fed
    assert wait["ts"] + wait["dur"] == pytest.approx(step["ts"])
    ckpt = next(e for e in evs if e["name"] == "checkpoint 1")
    # begin->commit pairing wins over the commit's own `seconds`
    assert ckpt["dur"] == pytest.approx(0.5e6)
    req = next(e for e in evs if e["name"] == "req ok")
    assert req["dur"] == pytest.approx(0.8e6)
    prof = next(e for e in evs if e["name"] == "profile window")
    assert prof["dur"] == pytest.approx(0.7e6, rel=1e-3)
    # an abort CLOSES the open window (drawn up to the abort) so later
    # begin/end pairs aren't mis-paired across it; the last begin with
    # no close at all renders as an unclosed instant
    aborted = next(e for e in evs
                   if e["name"] == "profile window (aborted)")
    assert aborted["ph"] == "X"
    assert aborted["dur"] == pytest.approx(0.1e6, rel=1e-3)
    names_i = {e["name"] for e in evs if e["ph"] == "i"}
    assert {"run_start", "preemption", "profile_aborted",
            "profile window (unclosed)"} <= names_i


def test_perfetto_multi_journal_pids(tmp_path):
    from megatron_tpu.telemetry.perfetto import journals_to_trace_events

    j0 = [{"ts": 1.0, "kind": "run_start", "host": 0},
          {"ts": 2.0, "kind": "step", "iteration": 1, "step_ms": 5.0}]
    j1 = [{"ts": 1.0, "kind": "run_start", "host": 1},
          {"ts": 2.5, "kind": "peer_abort", "host": 0, "cause": "hang"}]
    trace = journals_to_trace_events([("h0", j0), ("h1", j1)])
    validate_trace_events(trace)
    pids = {e["pid"] for e in trace["traceEvents"]}
    assert pids == {0, 1}
    # journals without host attribution fall back to their index,
    # colliding pids are reassigned
    trace2 = journals_to_trace_events([("a", j0), ("b", j0)])
    validate_trace_events(trace2)
    assert len({e["pid"] for e in trace2["traceEvents"]}) == 2


def test_telemetry_report_format_json_and_perfetto_cli(tmp_path, capsys):
    """--format json emits per-section dicts (CI consumes goodput/
    serving numbers without scraping tables); --perfetto writes the
    timeline file alongside."""
    telemetry_report = _import_telemetry_report()
    journal = tmp_path / "events.jsonl"
    events = [
        {"ts": 1.0, "kind": "run_start", "iteration": 0},
        {"ts": 2.0, "kind": "step", "iteration": 1, "step_ms": 10.0,
         "loss": 1.5, "tokens_per_s": 100.0, "data_wait_ms": 1.0},
        {"ts": 3.0, "kind": "goodput", "wall_s": 2.0, "productive_s": 1.5},
        {"ts": 4.0, "kind": "serve_request", "status": "ok",
         "wall_s": 0.5, "ttft_s": 0.1},
        {"ts": 5.0, "kind": "preemption", "iteration": 1,
         "notice_host": 0},
    ]
    with open(journal, "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
    out_trace = tmp_path / "trace.json"
    rc = telemetry_report.main([str(journal), "--format", "json",
                                "--perfetto", str(out_trace)])
    assert rc == 0
    sections = json.loads(capsys.readouterr().out)
    assert sections["run"]["steps"] == 1
    assert sections["goodput"]["goodput_pct"] == 75.0
    assert sections["steps"]["step_ms"]["p50"] == 10.0
    assert sections["serving"]["requests"]["total"] == 1
    assert sections["resilience"]["preemptions"] == 1
    trace = json.loads(out_trace.read_text())
    assert validate_trace_events(trace)
    assert any(e["name"] == "step 1" for e in trace["traceEvents"])
    # legacy --json still prints the flat summary
    rc = telemetry_report.main([str(journal), "--json"])
    assert rc == 0
    flat = json.loads(capsys.readouterr().out)
    assert flat["steps"] == 1 and "goodput_pct" in flat


# ---------------------------------------------------------------------------
# CLI flags


def test_telemetry_flags_parse_into_config():
    from megatron_tpu.arguments import args_to_run_config, parse_args

    args = parse_args([
        "--num_layers", "2", "--hidden_size", "64",
        "--num_attention_heads", "4", "--telemetry_dir", "/tmp/tele",
        "--journal_max_mb", "8", "--metrics_port", "0",
        "--flight_recorder", "--flight_recorder_deadline_s", "120",
        "--flight_recorder_abort"])
    t = args_to_run_config(args).training
    assert t.telemetry_dir == "/tmp/tele"
    assert t.journal_max_mb == 8.0
    assert t.metrics_port == 0
    assert t.flight_recorder and t.flight_recorder_abort
    assert t.flight_recorder_deadline_s == 120.0
    # defaults: everything off
    args = parse_args(["--num_layers", "2", "--hidden_size", "64",
                       "--num_attention_heads", "4"])
    t = args_to_run_config(args).training
    assert t.telemetry_dir is None and t.metrics_port is None
    assert not t.flight_recorder


def test_resilience_flags_parse_into_config():
    """ISSUE 11 knobs: preemption deadline, hang watchdog, SDC replay
    check, batch fingerprinting."""
    from megatron_tpu.arguments import args_to_run_config, parse_args

    args = parse_args([
        "--num_layers", "2", "--hidden_size", "64",
        "--num_attention_heads", "4",
        "--preempt_save_timeout", "45", "--step_timeout_s", "30",
        "--replay_check_interval", "500", "--log_data_fingerprint"])
    t = args_to_run_config(args).training
    assert t.preempt_save_timeout == 45.0
    assert t.step_timeout_s == 30.0
    assert t.replay_check_interval == 500
    assert t.log_data_fingerprint
    # defaults: deadline on, sentinels off
    args = parse_args(["--num_layers", "2", "--hidden_size", "64",
                       "--num_attention_heads", "4"])
    t = args_to_run_config(args).training
    assert t.preempt_save_timeout == 600.0
    assert t.step_timeout_s == 0.0 and t.replay_check_interval == 0
    assert not t.log_data_fingerprint
    # negatives refuse loudly
    import pytest as _pytest

    from megatron_tpu.config import TrainingConfig

    for bad in ({"step_timeout_s": -1.0}, {"replay_check_interval": -2},
                {"preempt_save_timeout": -0.5}):
        with _pytest.raises(ValueError):
            TrainingConfig(**bad).validate()


def test_telemetry_report_counts_resilience_events(tmp_path):
    """tools/telemetry_report.py surfaces preemption/hang/SDC/elastic
    event counts (ISSUE 11 satellite)."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import telemetry_report
    finally:
        sys.path.pop(0)

    journal = tmp_path / "events.jsonl"
    events = [
        {"ts": 1.0, "kind": "run_start", "iteration": 0},
        {"ts": 2.0, "kind": "step", "iteration": 1, "step_ms": 10.0,
         "loss": 1.5},
        {"ts": 3.0, "kind": "preemption", "iteration": 1,
         "signal": "SIGTERM", "notice_to_commit_ms": 80.0},
        {"ts": 4.0, "kind": "run_end", "received_signal": "SIGTERM"},
        {"ts": 5.0, "kind": "run_start", "iteration": 1},
        {"ts": 6.0, "kind": "elastic_resume", "from_dp": 4, "to_dp": 2},
        {"ts": 7.0, "kind": "hang_detected", "iteration": 3,
         "heartbeat_age_s": 12.0},
        {"ts": 8.0, "kind": "sdc_detected", "iteration": 5,
         "leaves": ["params['embed']"]},
        {"ts": 9.0, "kind": "preemption_timeout", "iteration": 7},
    ]
    with open(journal, "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
    summary = telemetry_report.summarize(
        telemetry_report.load_journal(str(journal)))
    assert summary["preemptions"] == 1
    assert summary["preemption_timeouts"] == 1
    assert summary["hangs"] == 1
    assert summary["sdc_detected"] == 1
    assert summary["elastic_resumes"] == 1
    text = telemetry_report.render(summary)
    assert "1 preemptions" in text
    assert "1 hangs detected" in text
    assert "1 SDC detected" in text
    assert "1 elastic resumes" in text
    assert "1 preempt-save timeouts" in text
